"""Fused lane-packed local-search engine (Pallas TPU kernel).

The generic local-search path (algorithms/_local_search.py on top of
ops.compile.local_cost_tables) spends each MGM/DSA cycle in XLA
gather/segment ops over ``[F, D, D]`` cost tensors — measured 25-50x
slower per cycle than the packed MaxSum engine on the same 10k-var graph
(round-2 verdict).  This module is the same TPU-first re-design for the
local-search family: the whole cycle — local cost tables, masked argmin,
gain computation, and (for MGM) the neighborhood gain arbitration — runs
in ONE pallas kernel, with multiple cycles statically unrolled per kernel
launch.

Covers BOTH packed layouts: all-binary graphs (per-other-value cost
slabs, one Clos permutation) and mixed-arity 1/2/3 graphs (the packed
graph's cost_rows/cost1/cost3 arrays with arity-masked assembly, a
second permutation for the ternary sibling — VERDICT r4 item 1).

Layout (shared with ops.pallas_maxsum.PackedMaxSumGraph — an all-binary
constraints hypergraph IS an all-binary factor graph, with var-var
neighbor pairs as factor mates):

* assignment ``x``: one ``[1, Vp]`` lane row (padded variable columns);
* local tables ``[D, Vp]``: domain on sublanes, variables on lanes;
* the only graph-structured exchanges are Clos-routed lane permutations
  (ops.clos_routing) of SINGLE lane rows: each edge slot pulls its
  factor's other endpoint — once per cycle for values, and for MGM once
  more for gains.  The tie-break indices never travel: the topology is
  static, so each slot's neighbor index is a compile-time constant
  (``mate_idx``).

Cycle semantics are identical to the generic solvers (the reference's
mgm.py value+gain rounds / dsa.py variants A/B/C):

* MGM: move iff own gain is the strict neighborhood max, lexic
  (variable-index) tie-break — _local_search.neighborhood_winner.
* DSA: stochastic move on improvement (+ lateral moves per variant),
  coin flips supplied per cycle as a ``[n_cycles, Vp]`` uniform input so
  the fused path consumes the exact PRNG stream of the generic path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pydcop_tpu.ops.compile import PAD_COST
from pydcop_tpu.ops.pallas_maxsum import (
    PackedMaxSumGraph,
    _LANES,
    _compiler_params,
    _contrib_for_values,
    _hub_op,
    _hub_operands,
    _hub_spread,
    _hub_sum,
    _mixed_operands,
    _parse_mixed_refs,
    _resolve_interpret,
    try_pack_for_pallas,
)
from pydcop_tpu.ops.pallas_permute import _permute_in_kernel, _plan_consts

#: hard-constraint threshold (same sentinel as _local_search.HARD_THRESHOLD;
#: duplicated to keep this module import-light inside kernels)
_HARD = 10000.0
_BIG_IDX = 1e9


@dataclass
class PackedLocalSearch:
    """Packed layout + the extra per-column arrays local search needs."""

    pg: PackedMaxSumGraph
    idx_row: jnp.ndarray    # [1, Vp] f32 — original var index (BIG on pads)
    colmask: jnp.ndarray    # [1, Vp] f32 — 1 on real variable columns
    sreal: jnp.ndarray      # [1, N]  f32 — 1 on real edge slots
    # ALL-BINARY layout: cost_rows split into D separate [D, N] slabs
    # (slab j = costs given the other endpoint holds value j).  Passing
    # each slab as its own kernel operand keeps every read in Mosaic's
    # canonical vector layout; row-slicing one [D*D, N] array gives
    # slices sublane-offset layouts that tpu.concatenate cannot
    # reconcile with the zero-fill pieces of the bucket reduce (verified
    # on hardware).  Empty on MIXED packings — those read the packed
    # graph's cost arrays through the where-select assembly of
    # pallas_maxsum._mixed_contrib, which hardware-compiles fine.
    cost_slabs: Tuple[jnp.ndarray, ...] = ()
    # [1, N] — original variable index of each slot's factor mate (the
    # neighbor on the other end), BIG on dummy slots.  The graph topology
    # is static, so MGM's tie-break index exchange needs NO runtime
    # permute — only the gains travel.
    mate_idx: jnp.ndarray = None
    # [1, N] — 1 exactly where mate_idx is a real neighbor (= sreal for
    # all-binary packings; excludes unary slots on mixed packings).  Gains
    # routed onto masked slots are zeroed before the neighborhood max.
    gmask1: jnp.ndarray = None
    # mixed+ternary/quaternary packings only: the SECOND sibling's
    # index per slot (routed by pg.plan2), BIG off arity ≥ 3 slots; its
    # gain mask is am3 + am4.  mate3 likewise for the THIRD sibling
    # (plan3, quaternary slots, mask am4).
    mate2_idx: Optional[jnp.ndarray] = None
    mate3_idx: Optional[jnp.ndarray] = None

    @property
    def n_vars(self) -> int:
        return self.pg.n_vars

    @property
    def D(self) -> int:
        return self.pg.D


def pack_local_search(tensors) -> Optional[PackedLocalSearch]:
    """Compile the packed local-search layout, or None when the graph is
    not packable (arity > 4, hub overflow, VMEM) — callers fall back to
    the generic engine."""
    return pack_from_pg(try_pack_for_pallas(tensors))


def move_extras(pg: PackedMaxSumGraph) -> dict:
    """Host-side static arrays the packed MOVE rules need, as numpy
    (shared by :func:`pack_from_pg` and the sharded packer
    parallel/packed_mesh, which stacks one set per shard):

    ``idx_row``/``colmask`` [1, Vp], ``sreal``/``gmask1``/``mate``
    [1, N], plus ``mate2``/``mate3`` (or None) for the ternary /
    quaternary siblings of mixed packings."""
    Vp, N = pg.Vp, pg.N
    var_order = np.asarray(pg.var_order)
    idx_np = np.full((1, Vp), _BIG_IDX, dtype=np.float32)
    idx_np[0, var_order] = np.arange(pg.n_vars, dtype=np.float32)
    colmask = np.zeros((1, Vp), dtype=np.float32)
    colmask[0, var_order] = 1.0
    # real-slot mask: row 0 of vmask is 1 exactly on real slots (every
    # variable's value 0 is valid)
    sreal = np.asarray(pg.vmask)[0:1, :].astype(np.float32)
    if pg.mixed:
        am4 = (
            np.asarray(pg.arity_mask4)
            if pg.arity_mask4 is not None else 0.0
        )
        gmask1 = np.clip(
            np.asarray(pg.arity_mask2) + np.asarray(pg.arity_mask3)
            + am4,
            0.0, 1.0,
        ).astype(np.float32)
    else:
        gmask1 = sreal
    # static neighbor index per slot: expand own indices to slots on the
    # host, route them through the plan's numpy reference once.  Uses the
    # per-COLUMN variable map (col_var) rather than idx_np so a hub's
    # member sub-columns advertise the hub's index to their neighbors.
    col_idx = np.full((1, Vp), _BIG_IDX, dtype=np.float32)
    cv = pg.col_var
    col_idx[0, cv >= 0] = cv[cv >= 0].astype(np.float32)
    own_idx_slots = np.full((1, N), _BIG_IDX, dtype=np.float32)
    for cls, nvp, voff, soff in pg.buckets:
        for k in range(cls):
            own_idx_slots[0, soff + k * nvp: soff + (k + 1) * nvp] = \
                col_idx[0, voff: voff + nvp]
    mate = pg.plan.apply_numpy(own_idx_slots)
    mate = np.where(gmask1 > 0, mate, _BIG_IDX).astype(np.float32)
    mate2 = mate3 = None
    if pg.mixed and pg.plan2 is not None:
        am3 = np.asarray(pg.arity_mask3)
        am4 = (
            np.asarray(pg.arity_mask4)
            if pg.arity_mask4 is not None else np.zeros_like(am3)
        )
        m2 = pg.plan2.apply_numpy(own_idx_slots)
        mate2 = np.where(am3 + am4 > 0, m2, _BIG_IDX).astype(np.float32)
        if pg.plan3 is not None:
            m3 = pg.plan3.apply_numpy(own_idx_slots)
            mate3 = np.where(am4 > 0, m3, _BIG_IDX).astype(np.float32)
    return {
        "idx_row": idx_np, "colmask": colmask, "sreal": sreal,
        "gmask1": gmask1, "mate": mate, "mate2": mate2, "mate3": mate3,
    }


def pack_from_pg(pg: Optional[PackedMaxSumGraph]
                 ) -> Optional[PackedLocalSearch]:
    """Build the local-search extras on top of an existing packed graph
    (lets solvers that already hold a PackedMaxSumGraph for the tables
    kernel upgrade lazily, without re-packing).

    Handles both layouts: all-binary packings get the per-other-value
    cost slabs; mixed-arity (1/2/3/4) packings reuse the packed graph's
    own cost arrays (cost_rows/cost1/cost3/cost4 + arity masks) and
    carry second/third mate-index arrays for the ternary/quaternary
    siblings."""
    if pg is None or pg.D < 2:
        return None
    ex = move_extras(pg)
    D = pg.D
    sreal_j = jnp.asarray(ex["sreal"])
    if pg.mixed:
        # mixed kernels slice pg.cost_rows/cost1/cost3 in-kernel (the
        # layout packed_local_tables already proves on hardware)
        slabs = ()
        gmask1_j = jnp.asarray(ex["gmask1"])
    else:
        cost_np = np.asarray(pg.cost_rows)
        slabs = tuple(
            jnp.asarray(cost_np[j * D: (j + 1) * D, :]) for j in range(D)
        )
        # same mask: alias the device buffer instead of re-uploading a
        # second [1, N] copy (tens of MB at stretch scale)
        gmask1_j = sreal_j
    return PackedLocalSearch(
        pg=pg,
        idx_row=jnp.asarray(ex["idx_row"]),
        colmask=jnp.asarray(ex["colmask"]),
        sreal=sreal_j,
        cost_slabs=slabs,
        mate_idx=jnp.asarray(ex["mate"]),
        gmask1=gmask1_j,
        mate2_idx=(
            jnp.asarray(ex["mate2"]) if ex["mate2"] is not None else None
        ),
        mate3_idx=(
            jnp.asarray(ex["mate3"]) if ex["mate3"] is not None else None
        ),
    )


def pack_x(pls: PackedLocalSearch, x: jnp.ndarray) -> jnp.ndarray:
    """[V] int32 value indices → [1, Vp] f32 padded row (0 on pads)."""
    Vp = pls.pg.Vp
    return (
        jnp.zeros((1, Vp), jnp.float32)
        .at[0, pls.pg.var_order]
        .set(x.astype(jnp.float32))
    )


def unpack_x(pls: PackedLocalSearch, x_row: jnp.ndarray) -> jnp.ndarray:
    """[1, Vp] f32 → [V] int32 original order."""
    return x_row[0, pls.pg.var_order].astype(jnp.int32)


# ---------------------------------------------------------------------------
# in-kernel building blocks (traced; shapes are compile-time constants)
# ---------------------------------------------------------------------------


def _bucket_expand(pg: PackedMaxSumGraph, arr, R: int):
    """[R, Vp] per-variable rows → [R, N] per-slot rows (lane-aligned
    repeats of each degree-class block, as in pallas_maxsum._cycle_body)."""
    parts = []
    for cls, nvp, voff, soff in pg.buckets:
        blk = arr[:, voff: voff + nvp]
        parts.extend([blk] * cls)
    out = jnp.concatenate(parts, axis=1) if parts else arr
    if out.shape[1] < pg.N:
        out = jnp.concatenate(
            [out, jnp.zeros((R, pg.N - out.shape[1]), out.dtype)], axis=1
        )
    return out


def _bucket_reduce(pg: PackedMaxSumGraph, arr, R: int, op, fill=0.0):
    """[R, N] per-slot rows → [R, Vp] per-variable rows, combining each
    variable's slots with ``op``.  ``fill`` is the value given to
    gap/degree-0 columns (the op's identity: 0 for sum/max-of-gains,
    _BIG_IDX for index minima)."""
    parts = []
    voff_expect = 0
    for cls, nvp, voff, soff in pg.buckets:
        while voff_expect < voff:
            parts.append(jnp.full((R, _LANES), fill, dtype=arr.dtype))
            voff_expect += _LANES
        acc = arr[:, soff: soff + nvp]
        for k in range(1, cls):
            acc = op(acc, arr[:, soff + k * nvp: soff + (k + 1) * nvp])
        parts.append(acc)
        voff_expect += nvp
    while voff_expect < pg.Vp:
        parts.append(jnp.full((R, _LANES), fill, dtype=arr.dtype))
        voff_expect += _LANES
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


def _permute1(pg: PackedMaxSumGraph, row, consts):
    """Permute one [1, N] lane row (single-sublane plane — verified
    supported by Mosaic on v5e; halves the permutation pipeline's VMEM
    footprint vs a multi-row plane)."""
    return _permute_in_kernel(row, pg.plan, 1, consts)


def _local_tables_body(pg: PackedMaxSumGraph, x_row, slabs, unary, mask_p,
                       consts, hub=None, mixed=None, cost=None):
    """tables[d, v] = unary + Σ_slots cost(v=d | other endpoints at x);
    PAD_COST at invalid (d, v) slots.  One values permute (two on
    ternary graphs).  All-binary layout: ``slabs`` are the D
    per-other-value cost planes [D, N] (see PackedLocalSearch).  Mixed
    layout: ``cost`` is the full [D*D, N] binary array and ``mixed``
    the parsed 8-tuple of pallas_maxsum._parse_mixed_refs — per-slot
    rows are assembled by pallas_maxsum._mixed_contrib, exactly as the
    packed_local_tables kernel does."""
    D = pg.D
    # hub members carry the hub's value for their slots
    xs = _bucket_expand(pg, _hub_spread(pg, x_row, 1, hub), 1)
    xo = _permute1(pg, xs, consts)
    contrib = _contrib_for_values(pg, xs, xo, mixed, cost=cost,
                                  slabs=slabs)
    tables = _hub_sum(
        pg, unary + _bucket_reduce(pg, contrib, D, jnp.add), D, hub
    )
    return jnp.where(mask_p > 0, tables, PAD_COST)


def _iota_rows(D: int, Vp: int):
    # int32 iota then cast: Mosaic's tpu.iota only produces integers
    return jax.lax.broadcasted_iota(jnp.int32, (D, Vp), 0).astype(
        jnp.float32
    )


def _cur_best_gain(pg: PackedMaxSumGraph, tables, x_row, prefer_change):
    """(cur [1,Vp], best_idx [1,Vp], gain [1,Vp]) from masked tables.
    ``prefer_change`` nudges the argmin away from the current value on
    exact ties (DSA B/C lateral moves) — same eps as gains_and_best."""
    D, Vp = tables.shape
    iota = _iota_rows(D, Vp)
    onehot = jnp.where(iota == x_row, 1.0, 0.0)
    cur = jnp.sum(tables * onehot, axis=0, keepdims=True)
    pick = tables + onehot * 1e-6 if prefer_change else tables
    best_cost = pick[0:1, :]
    best_idx = jnp.zeros((1, Vp), jnp.float32)
    for d in range(1, D):
        row = pick[d: d + 1, :]
        better = row < best_cost
        best_idx = jnp.where(better, float(d), best_idx)
        best_cost = jnp.minimum(best_cost, row)
    gain = jnp.maximum(cur - best_cost, 0.0)
    return cur, best_idx, gain


def _routed_gains(pg: PackedMaxSumGraph, gain, consts, gmask1, hub=None,
                  consts2=None, gmask2=None, consts3=None, gmask3=None):
    """Expand per-column gains to slots and Clos-route each slot's
    sibling gains: (gn, gn2, gn3) [1, N] rows (gn2/gn3 None without a
    second/third permutation).  ``gmask*`` zero the slots whose permute
    routes no real neighbor (dummies, and unary slots on mixed layouts,
    which route identity)."""
    # hub member slots must send the hub's gain to their neighbors
    gs = _bucket_expand(pg, _hub_spread(pg, gain, 1, hub), 1)
    gn = _permute1(pg, gs, consts) * gmask1
    gn2 = gn3 = None
    if consts2 is not None:
        gn2 = _permute_in_kernel(gs, pg.plan2, 1, consts2) * gmask2
    if consts3 is not None:
        gn3 = _permute_in_kernel(gs, pg.plan3, 1, consts3) * gmask3
    return gn, gn2, gn3


def _neigh_max_partial(pg: PackedMaxSumGraph, gn, gn2=None, gn3=None,
                       hub=None):
    """[1, Vp] per-column max of the routed neighbor gains over the
    LOCAL slots — the full neighborhood max on one chip; a per-shard
    partial (combine with ``pmax`` over the mesh axis) when the slots
    are sharded."""
    gboth = gn if gn2 is None else jnp.maximum(gn, gn2)
    if gn3 is not None:
        gboth = jnp.maximum(gboth, gn3)
    # hub combine: a hub's neighborhood max/tie-break spans ALL its
    # sub-columns' slots
    return _hub_op(pg, _bucket_reduce(pg, gboth, 1, jnp.maximum), 1, hub,
                   jnp.maximum)


def _tiebreak_idx_partial(pg: PackedMaxSumGraph, nm_exp, gn, mate_idx,
                          gn2=None, mate2=None, gn3=None, mate3=None,
                          hub=None):
    """[1, Vp] min neighbor index achieving the neighborhood max, over
    the LOCAL slots (sharded callers ``pmin`` the partials).  ``nm_exp``
    is the GLOBAL neighborhood max expanded to slots."""
    # masked slots are safe here: their gn is 0 and their mate is BIG
    idx_cand = jnp.where(gn >= nm_exp - 1e-9, mate_idx, _BIG_IDX)
    if gn2 is not None:
        idx_cand = jnp.minimum(
            idx_cand, jnp.where(gn2 >= nm_exp - 1e-9, mate2, _BIG_IDX)
        )
    if gn3 is not None:
        idx_cand = jnp.minimum(
            idx_cand, jnp.where(gn3 >= nm_exp - 1e-9, mate3, _BIG_IDX)
        )
    # fill=_BIG_IDX: degree-0 variables have no neighbor at max, so the
    # lexic tie-break must let them through (generic: idx_at_max = V)
    return _hub_op(
        pg,
        _bucket_reduce(pg, idx_cand, 1, jnp.minimum, fill=_BIG_IDX),
        1, hub, jnp.minimum,
    )


def _mgm_decision(gain, idx_row, neigh_max, idx_at_max):
    """neighborhood_winner's final predicate: move iff own gain is the
    strict neighborhood max, lexic (variable-index) tie-break."""
    return (gain > 0) & (
        (gain > neigh_max + 1e-9)
        | ((jnp.abs(gain - neigh_max) <= 1e-9) & (idx_row < idx_at_max))
    )


def _mgm_move(pls: PackedLocalSearch, gain, idx_row, mate_idx, gmask1,
              consts, hub=None, mate2=None, gmask2=None, consts2=None,
              mate3=None, gmask3=None, consts3=None):
    """MGM neighborhood arbitration (neighborhood_winner semantics):
    True [1, Vp] where own gain is the strict neighborhood max, lexic
    tie-break by original variable index.  One gains permute (a second
    on ternary graphs for the other sibling); the tie-break indices are
    the STATIC mate arrays — topology doesn't change at runtime, so only
    gains travel.  Composed from the partial-arbitration helpers above
    so the sharded engine (parallel/mesh.py) runs the SAME op DAG with a
    pmax/pmin pair between the partials."""
    pg = pls.pg
    gn, gn2, gn3 = _routed_gains(
        pg, gain, consts, gmask1, hub=hub,
        consts2=consts2 if mate2 is not None else None, gmask2=gmask2,
        consts3=consts3 if mate3 is not None else None, gmask3=gmask3,
    )
    neigh_max = jnp.maximum(
        _neigh_max_partial(pg, gn, gn2, gn3, hub=hub), 0.0
    )
    nm_exp = _bucket_expand(pg, neigh_max, 1)
    idx_at_max = _tiebreak_idx_partial(
        pg, nm_exp, gn, mate_idx, gn2, mate2, gn3, mate3, hub=hub,
    )
    return _mgm_decision(gain, idx_row, neigh_max, idx_at_max)


# ---------------------------------------------------------------------------
# fused multi-cycle kernels
# ---------------------------------------------------------------------------


def packed_mgm_cycles(
    pls: PackedLocalSearch,
    x_row: jnp.ndarray,
    n_cycles: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """``n_cycles`` fused MGM cycles in ONE pallas kernel.  x_row is the
    [1, Vp] packed assignment; returns the updated [1, Vp] row.

    Cycles are statically unrolled (same VMEM rationale as
    pallas_maxsum.packed_cycles) — keep n_cycles ≤ ~16.
    """
    if not 1 <= n_cycles <= 64:
        raise ValueError(f"n_cycles must be in [1, 64], got {n_cycles}")
    interpret = _resolve_interpret(interpret)
    pg = pls.pg
    Vp = pg.Vp
    mixed = pg.mixed
    has_m2 = pls.mate2_idx is not None
    has_m3 = pls.mate3_idx is not None

    hub_ops = _hub_operands(pg)
    cost_ops = ((pg.cost_rows,) + _mixed_operands(pg)) if mixed \
        else pls.cost_slabs

    def kern(x_ref, unary_ref, maskp_ref, idx_ref, mate_ref, colm_ref,
             g1_ref, c_r1, c_g1, c_ss, c_g2, c_r2, *rest):
        if has_m2:
            mate2, rest = rest[0][:], rest[1:]
        else:
            mate2 = None
        if has_m3:
            mate3, rest = rest[0][:], rest[1:]
        else:
            mate3 = None
        if hub_ops:
            hub = (rest[0][:], rest[1][:], rest[2][:])
            rest = rest[3:]
        else:
            hub = None
        if mixed:
            cost = rest[0][:]
            mixed_refs, rest = _parse_mixed_refs(pg, rest[1:])
            slabs = None
            consts2 = mixed_refs[2]
            gmask2 = mixed_refs[4]  # am3: gain mask of the 2nd sibling
            consts3 = mixed_refs[6]
            gmask3 = mixed_refs[7]  # am4: gain mask of the 3rd sibling
            if gmask3 is not None:
                # quaternary slots route a second sibling too (masks
                # are disjoint, so plain add is already 0/1)
                gmask2 = gmask2 + gmask3
        else:
            cost = mixed_refs = consts2 = gmask2 = None
            consts3 = gmask3 = None
            slabs = [ref[:] for ref in rest[:-1]]
            rest = rest[-1:]
        (x_out,) = rest
        unary = unary_ref[:]
        mask_p = maskp_ref[:]
        idx_row = idx_ref[:]
        mate_idx = mate_ref[:]
        colm = colm_ref[:]
        g1 = g1_ref[:]
        consts = (c_r1[:], c_g1[:], c_ss[:], c_g2[:], c_r2[:])
        x = x_ref[:]
        for _ in range(n_cycles):
            tables = _local_tables_body(pg, x, slabs, unary, mask_p,
                                        consts, hub=hub,
                                        mixed=mixed_refs, cost=cost)
            _cur, best_idx, gain = _cur_best_gain(pg, tables, x, False)
            move = _mgm_move(pls, gain, idx_row, mate_idx, g1, consts,
                             hub=hub, mate2=mate2, gmask2=gmask2,
                             consts2=consts2, mate3=mate3,
                             gmask3=gmask3, consts3=consts3)
            x = jnp.where(move & (colm > 0), best_idx, x)
        x_out[:] = x

    operands = [x_row, pg.unary_p, pg.mask_p, pls.idx_row, pls.mate_idx,
                pls.colmask, pls.gmask1, *_plan_consts(pg.plan)]
    if has_m2:
        operands.append(pls.mate2_idx)
    if has_m3:
        operands.append(pls.mate3_idx)
    operands.extend(hub_ops)
    operands.extend(cost_ops)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((1, Vp), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(operands),
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(*operands)


def packed_dsa_cycles(
    pls: PackedLocalSearch,
    x_row: jnp.ndarray,
    uniforms: jnp.ndarray,
    probability: float,
    variant: str = "B",
    probability_hard: Optional[float] = None,
    awake_uniforms: Optional[jnp.ndarray] = None,
    activation: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """``n_cycles`` fused DSA-family cycles (variants A/B/C) in ONE
    pallas kernel.  ``uniforms`` is [n_cycles, Vp] — one move coin per
    variable per cycle, pre-drawn so the fused path replays the generic
    path's PRNG stream exactly.  Returns the updated [1, Vp] row.

    Two optional rule extensions cover the rest of the stochastic
    family:

    * mixeddsa: ``probability_hard`` — variables in hard conflict
      (current local cost ≥ the hard threshold) move with this
      probability instead of ``probability`` (MixedDsaSolver.cycle);
    * adsa: ``awake_uniforms`` [n_cycles, Vp] + ``activation`` — a
      variable only acts when its wake coin clears the activation
      probability (ADsaSolver.cycle's timer emulation).
    """
    n_cycles = int(uniforms.shape[0])
    if not 1 <= n_cycles <= 64:
        raise ValueError(f"n_cycles must be in [1, 64], got {n_cycles}")
    if variant not in ("A", "B", "C"):
        raise ValueError(f"unknown DSA variant {variant!r}")
    if (awake_uniforms is None) != (activation is None):
        raise ValueError(
            "awake_uniforms and activation must be passed together"
        )
    interpret = _resolve_interpret(interpret)
    pg = pls.pg
    D, Vp = pg.D, pg.Vp
    prefer_change = variant in ("B", "C")
    adsa_mode = awake_uniforms is not None
    mixed = pg.mixed

    hub_ops = _hub_operands(pg)
    cost_ops = ((pg.cost_rows,) + _mixed_operands(pg)) if mixed \
        else pls.cost_slabs

    def kern(x_ref, u_ref, *rest):
        if adsa_mode:
            au_ref, rest = rest[0], rest[1:]
        (unary_ref, maskp_ref, colm_ref,
         c_r1, c_g1, c_ss, c_g2, c_r2) = rest[:8]
        rest = rest[8:]
        if hub_ops:
            hub = (rest[0][:], rest[1][:], rest[2][:])
            rest = rest[3:]
        else:
            hub = None
        if mixed:
            cost = rest[0][:]
            mixed_refs, rest = _parse_mixed_refs(pg, rest[1:])
            slabs = None
        else:
            cost = mixed_refs = None
            slabs = [ref[:] for ref in rest[:-1]]
            rest = rest[-1:]
        (x_out,) = rest
        unary = unary_ref[:]
        mask_p = maskp_ref[:]
        colm = colm_ref[:]
        consts = (c_r1[:], c_g1[:], c_ss[:], c_g2[:], c_r2[:])
        x = x_ref[:]
        for c in range(n_cycles):
            tables = _local_tables_body(pg, x, slabs, unary, mask_p,
                                        consts, hub=hub,
                                        mixed=mixed_refs, cost=cost)
            cur, best_idx, gain = _cur_best_gain(
                pg, tables, x, prefer_change
            )
            conflict = cur >= _HARD
            improving = gain > 1e-9
            if variant == "A":
                want = improving
            else:
                lateral = (gain <= 1e-9) & (best_idx != x)
                if variant == "B":
                    want = improving | (lateral & conflict)
                else:  # C
                    want = improving | lateral
            u = u_ref[c: c + 1, :]
            if probability_hard is None:
                activate = u < probability
            else:
                p = jnp.where(conflict, probability_hard, probability)
                activate = u < p
            move = want & activate & (colm > 0)
            if adsa_mode:
                move = move & (au_ref[c: c + 1, :] < activation)
            x = jnp.where(move, best_idx, x)
        x_out[:] = x

    operands = [x_row, uniforms]
    if adsa_mode:
        operands.append(awake_uniforms)
    operands.extend([pg.unary_p, pg.mask_p, pls.colmask,
                     *_plan_consts(pg.plan), *hub_ops, *cost_ops])
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((1, Vp), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(operands),
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(*operands)


def uniforms_for_keys(
    pls: PackedLocalSearch, keys: jnp.ndarray
) -> jnp.ndarray:
    """[n, Vp] uniforms matching DsaSolver.cycle's per-cycle
    ``jax.random.uniform(key, (V,))`` draw, scattered to padded columns
    (pads get 1.0 = never activate)."""
    V, Vp = pls.pg.n_vars, pls.pg.Vp

    def one(k):
        u = jax.random.uniform(k, (V,))
        return jnp.ones((Vp,), jnp.float32).at[pls.pg.var_order].set(u)

    return jax.vmap(one)(keys)


def uniforms_for_split_keys(pls: PackedLocalSearch, keys: jnp.ndarray):
    """(wake [n, Vp], move [n, Vp]) uniforms matching ADsaSolver.cycle's
    ``k_wake, k_move = jax.random.split(key)`` draws exactly — the fused
    adsa path consumes the generic path's PRNG stream."""
    V, Vp = pls.pg.n_vars, pls.pg.Vp

    def one(k):
        k_wake, k_move = jax.random.split(k)
        pad = jnp.ones((Vp,), jnp.float32)
        w = pad.at[pls.pg.var_order].set(jax.random.uniform(k_wake, (V,)))
        m = pad.at[pls.pg.var_order].set(jax.random.uniform(k_move, (V,)))
        return w, m

    return jax.vmap(one)(keys)
