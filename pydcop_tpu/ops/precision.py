"""Mixed-precision storage tiers for the tensor engines (ISSUE 19).

Every engine compiles and computes in float32 by default.  This module
adds two cheaper STORAGE tiers with f32 accumulation at every combine
point (the PGMax memory discipline, arXiv:2202.04110), selected by a
``precision`` knob threaded through the solvers, the sharded mesh, the
batch engine, checkpoints and the CLI:

* ``"f32"`` — the default.  :func:`apply_precision` returns the SAME
  tensors object and every kernel's cast guard is a python no-op, so
  the emitted jaxpr — and therefore the numerics — are bit-identical
  to a build without this module (pinned by tests).
* ``"bf16"`` — cost tables, maxsum messages/beliefs and the sharded
  boundary slabs are STORED in bfloat16; every reduction (min over
  table axes, segment sums, damping blends, psum'd partial beliefs)
  upcasts to f32 first.  bfloat16 shares float32's exponent range, so
  PAD_COST (1e30) survives the cast; entries at the hard-violation
  threshold are rounded UP onto the bf16 grid so ``>= QUANT_THRESHOLD``
  feasibility checks never lose a violation to round-to-nearest.  On
  the sharded engines the ppermute/psum payload is the bf16 slab —
  half the bytes per element, enforced by the audit registry's
  per-tier budgets (jaxpr-walked, not estimated).
* ``"int8"`` — cost tables are affine-quantized PER FACTOR: codes in
  ``[QUANT_MIN, QUANT_MAX]`` with an f32 scale/offset pair riding
  alongside the slab (``FactorBucket.qscale/qoffset``), dequantized on
  gather.  Entries at or above ``QUANT_THRESHOLD`` (hard violations,
  PAD) are pinned to the reserved ``QUANT_SATURATION`` code and
  dequantize back to PAD_COST — infeasibility survives quantization
  whatever the finite entries' dynamic range.  Round-trip error of
  finite entries is <= qscale/2 (property-tested).  Messages still use
  the bf16 tier (quantizing accumulating state would compound error).

The exactness contract per tier is :data:`EXACTNESS` — the same
three-level discipline as PR 5's overlap modes: engines declare which
tiers they support in a ``PRECISION_TIERS`` map next to their cycle
code, and refuse the rest with a typed :class:`PrecisionError` instead
of silently computing something else.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax.numpy as jnp

from pydcop_tpu.ops.compile import (
    PAD_COST,
    QUANT_MAX,
    QUANT_MIN,
    QUANT_SATURATION,
    QUANT_THRESHOLD,
)

#: the supported storage tiers, cheapest last
PRECISIONS = ("f32", "bf16", "int8")

#: exactness contract of each tier (mirrors PR 5's overlap-mode map):
#: ``exact`` — bit-identical to the pre-knob engines; ``statistical`` —
#: converges to final costs within the declared gate but individual
#: message trajectories differ; ``quantized`` — costs are exact only up
#: to the per-factor quantization step (argmin-preserving on integer
#: tables whose range fits the code space).
EXACTNESS = {"f32": "exact", "bf16": "statistical", "int8": "quantized"}

#: the declared statistical gate of the bf16 tier: a bf16 run's final
#: cost must land within RTOL of the f32 run's final cost (ATOL floors
#: the comparison for near-zero optima).  The equivalence tests and
#: the bench's precision leg both check THIS pair — one declared gate,
#: not per-caller tolerances.
BF16_COST_RTOL = 0.05
BF16_COST_ATOL = 1.0


class PrecisionError(ValueError):
    """Unknown precision tier, or a tier an engine/path does not
    support.  The message always names the supported fallback."""


def resolve_precision(precision) -> str:
    """Normalize/validate a precision knob value (None → ``"f32"``)."""
    if precision in (None, ""):
        return "f32"
    p = str(precision).lower()
    if p not in PRECISIONS:
        raise PrecisionError(
            f"unknown precision {precision!r}: expected one of "
            f"{'/'.join(PRECISIONS)}"
        )
    return p


def message_dtype(precision: str):
    """Storage dtype of maxsum messages / boundary slabs at a tier.
    int8 keeps bf16 messages: quantizing accumulating state would
    compound error cycle over cycle."""
    return jnp.bfloat16 if precision in ("bf16", "int8") else jnp.float32


def payload_itemsize(precision: str) -> int:
    """Bytes per element of the cross-device collective payload."""
    return 2 if precision in ("bf16", "int8") else 4


def precision_of(tensors) -> str:
    """The storage tier a compiled graph is staged at (bucket dtype)."""
    for b in tensors.buckets:
        if b.tensors.dtype == jnp.int8:
            return "int8"
        if b.tensors.dtype == jnp.bfloat16:
            return "bf16"
    return "f32"


# ---------------------------------------------------------------------------
# bf16: guarded cast
# ---------------------------------------------------------------------------


def cast_bf16_preserving_hard(t: np.ndarray) -> np.ndarray:
    """f32 → bf16 cast that never rounds an entry DOWN across the
    hard-violation threshold.

    round-to-nearest can map 10000.0 to 9984.0 (bf16 has 8 mantissa
    bits), which would make a violated hard constraint pass a
    ``>= QUANT_THRESHOLD`` feasibility check.  Entries that cross are
    bumped one bf16 ulp up instead.
    """
    import ml_dtypes

    t = np.asarray(t, dtype=np.float32)
    bt = t.astype(ml_dtypes.bfloat16)
    low = (t >= QUANT_THRESHOLD) & (bt.astype(np.float32) < QUANT_THRESHOLD)
    if low.any():
        bits = bt.view(np.uint16)
        bits = np.where(low, bits + np.uint16(1), bits)
        bt = bits.view(ml_dtypes.bfloat16)
    return bt


# ---------------------------------------------------------------------------
# int8: per-factor affine quantization
# ---------------------------------------------------------------------------


def quantize_table(table: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                               np.ndarray]:
    """Affine-quantize a stacked [F, D, ..., D] f32 cost table per factor.

    Returns ``(codes int8, scale f32 [F], offset f32 [F])`` with
    ``entry ~= code * scale + offset`` for finite entries (error
    <= scale/2) and every entry >= QUANT_THRESHOLD pinned to the
    QUANT_SATURATION code (dequantizes to PAD_COST).
    """
    t = np.asarray(table, dtype=np.float32)
    F = t.shape[0]
    flat = t.reshape(F, -1)
    finite = flat < QUANT_THRESHOLD
    any_finite = finite.any(axis=1)
    lo = np.where(any_finite,
                  np.where(finite, flat, np.inf).min(axis=1), 0.0)
    hi = np.where(any_finite,
                  np.where(finite, flat, -np.inf).max(axis=1), 0.0)
    scale = (hi - lo) / float(QUANT_MAX - QUANT_MIN)
    scale = np.where(scale <= 0.0, 1.0, scale).astype(np.float32)
    offset = (lo - QUANT_MIN * scale).astype(np.float32)
    codes = np.clip(
        np.rint((flat - offset[:, None]) / scale[:, None]),
        QUANT_MIN, QUANT_MAX,
    ).astype(np.int8)
    codes = np.where(finite, codes, np.int8(QUANT_SATURATION))
    return codes.reshape(t.shape), scale, offset


def quantize_row(row: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """Quantize one factor's [D, ..., D] table (warm in-place edits):
    returns (codes, scale scalar-array, offset scalar-array)."""
    codes, scale, offset = quantize_table(np.asarray(row)[None])
    return codes[0], scale[0], offset[0]


def dequantize_table(codes, scale, offset) -> jnp.ndarray:
    """Full-table dequantization (twin of the gather-side
    ops.compile._dequant): [F, D..D] codes + per-factor scale/offset
    → f32 table with saturated codes pinned to PAD_COST."""
    codes = jnp.asarray(codes)
    shape = (codes.shape[0],) + (1,) * (codes.ndim - 1)
    return jnp.where(
        codes == QUANT_SATURATION,
        jnp.float32(PAD_COST),
        codes.astype(jnp.float32) * jnp.reshape(scale, shape)
        + jnp.reshape(offset, shape),
    )


# ---------------------------------------------------------------------------
# staging: re-store a compiled graph at a tier
# ---------------------------------------------------------------------------


def apply_precision(tensors, precision):
    """Return ``tensors`` staged at ``precision``.

    ``"f32"`` returns the SAME object (the bit-identity pin: no copy,
    no cast, no new jaxpr).  ``"bf16"`` re-stores every dense bucket
    table in bfloat16 (guarded cast, see
    :func:`cast_bf16_preserving_hard`).  ``"int8"`` quantizes every
    dense bucket per factor and rides qscale/qoffset on the bucket.
    Structured (table-free) parameter buckets stay f32 at every tier —
    they are already O(k·D) bytes, far below any table.
    """
    p = resolve_precision(precision)
    if p == "f32":
        return tensors
    staged = precision_of(tensors)
    if staged != "f32":
        if staged == p:
            return tensors
        raise PrecisionError(
            f"tensors already staged at {staged!r}; recompile at f32 "
            f"before re-staging to {p!r}"
        )
    buckets = []
    for b in tensors.buckets:
        if b.n_factors == 0:
            buckets.append(b)
        elif p == "bf16":
            buckets.append(dataclasses.replace(
                b,
                tensors=jnp.asarray(
                    cast_bf16_preserving_hard(np.asarray(b.tensors))
                ),
            ))
        else:
            codes, scale, offset = quantize_table(np.asarray(b.tensors))
            buckets.append(dataclasses.replace(
                b,
                tensors=jnp.asarray(codes),
                qscale=jnp.asarray(scale),
                qoffset=jnp.asarray(offset),
            ))
    return dataclasses.replace(tensors, buckets=buckets)


def require_tier(engine: str, precision: str, supported, fallback: str):
    """Typed refusal helper: engines call this against their declared
    ``PRECISION_TIERS`` map so an unsupported tier fails loudly with
    the supported fallback named."""
    p = resolve_precision(precision)
    if p not in supported:
        raise PrecisionError(
            f"{engine} does not support precision={p!r} (supported: "
            f"{'/'.join(sorted(supported))}); {fallback}"
        )
    return p
