"""Fused lane-packed MaxSum engine (Pallas TPU kernel).

The generic engine (pydcop_tpu.ops.maxsum_kernels) lays messages out as
``[E, D]`` — with the domain axis (D is 3-10 for every reference problem
family) in the 128-lane minor dimension, >90% of HBM traffic is padding,
and the XLA segment/gather ops scalarize.  This engine is the TPU-first
re-design for the all-binary case (graph coloring, Ising — every headline
benchmark):

* messages ``[D, N]``: edge slots ride the lane axis fully packed, the
  domain axis rides sublanes;
* **var-grouped slot layout**: each variable's incoming edges occupy slots
  ``slot_off + k*nv + v`` of its degree-class bucket, so the variable-side
  belief sum and message expansion are aligned slice adds / broadcasts —
  no segment_sum, no gather;
* the single irreducible graph-structured exchange — routing each edge
  slot's outgoing message to its factor's other endpoint (``mate``) — is a
  static lane permutation executed via the Clos-routed stage plan
  (pydcop_tpu.ops.clos_routing / pallas_permute): within-vreg gathers +
  tile transposes + per-lane selects, all Mosaic vector ops;
* one cycle = ONE pallas kernel, everything VMEM-resident.

Cycle math is identical to maxsum_kernels.maxsum_cycle (itself the
reference's factor_costs_for_var / costs_for_factor,
pydcop/algorithms/maxsum.py:345,556): given state (q, r):

    r' = vmask ⊙ (damping*r + (1-damping) * min_j(cost[i,j] + q[mate][j]))
    b  = unary + Σ_incoming r'
    q' = vmask ⊙ (b[var(slot)] - r' - masked_mean)

Falls back (returns None from :func:`pack_for_pallas`) for non-binary or
mixed-arity graphs, or when the working set would exceed VMEM.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pydcop_tpu.ops.clos_routing import PermutationPlan, plan_permutation
from pydcop_tpu.ops.compile import FactorGraphTensors, PAD_COST
from pydcop_tpu.ops.pallas_permute import _permute_in_kernel, _plan_consts

_LANES = 128
_TILE = _LANES * _LANES  # elements routed per (b, l) plane
_VMEM_BUDGET = 13 * 2**20  # leave headroom under ~16MB


_MAX_BUCKETS = 24
# _cycle_body / packed_local_tables unroll a python loop of `cls` slice-adds
# per degree bucket; a scale-free hub with degree in the thousands would blow
# trace/compile time and kernel size, so above this slot class we fall back
# to the generic engine (same spirit as the A>8 guard).  Known limitation:
# one hub knocks the whole graph off the packed engine — splitting hub slots
# across multiple padded columns would keep the rest packed (future work).
_MAX_SLOT_CLASS = 96


def _degree_classes(deg: np.ndarray) -> np.ndarray:
    """Map each variable's degree to its slot-class (the padded per-variable
    slot count).  Exact degrees when few are distinct; otherwise quantile
    boundaries so bucket count stays bounded (scale-free graphs)."""
    nz = np.unique(deg[deg > 0])
    if len(nz) <= _MAX_BUCKETS:
        return deg.copy()
    qs = np.quantile(nz, np.linspace(0, 1, _MAX_BUCKETS + 1)[1:])
    bounds = np.unique(np.ceil(qs).astype(np.int64))
    cls = np.zeros_like(deg)
    pos = np.searchsorted(bounds, deg[deg > 0])
    cls[deg > 0] = bounds[np.minimum(pos, len(bounds) - 1)]
    return cls


@dataclass
class PackedMaxSumGraph:
    """Compiled lane-packed layout of an all-binary factor graph."""

    D: int
    n_vars: int  # real variables
    Vp: int  # padded variable columns
    N: int  # padded edge slots (= plan.n)
    plan: PermutationPlan  # mate routing
    buckets: Tuple[Tuple[int, int, int, int], ...]  # (cls, nvp, voff, soff)
    # cost tables, OTHER-value-major: row j*D+i = cost(d_oth=j, d_tgt=i),
    # so kernels slice cost[j*D:(j+1)*D] as the contiguous d_oth=j slab
    cost_rows: jnp.ndarray  # [D*D, N]
    unary_p: jnp.ndarray  # [D, Vp]
    mask_p: jnp.ndarray  # [D, Vp] 1=valid value (0 on dummy vars)
    vmask: jnp.ndarray  # [D, N] mask_p spread to slots (0 on dummy slots)
    inv_dcount: jnp.ndarray  # [1, N] 1/|valid values| per slot (0 dummy)
    var_order: jnp.ndarray  # [n_vars] padded column of each original var

    @property
    def vmem_bytes(self) -> int:
        return _vmem_estimate(self.D, self.N, self.Vp)


def _vmem_estimate(D: int, N: int, Vp: int) -> int:
    """Rough VMEM working-set bound of the cycle kernel: cost tables, q/r
    in+out, ~2 permute-stage temporaries, belief-side arrays, the 5 Clos
    plan index arrays (~5N int32), plus the A-way select stage of the
    permutation which materializes up to A candidate [D, TILE] planes
    (A*_TILE == N, so that term is one extra D*N)."""
    return 4 * (D * D * N + 7 * D * N + 3 * D * Vp + 5 * N)


def try_pack_for_pallas(t: FactorGraphTensors) -> Optional[PackedMaxSumGraph]:
    """Fail-safe engine selection: any packing bug degrades to the generic
    engine (with a logged warning) instead of taking the solve down.  Solvers
    must use this, never :func:`pack_for_pallas` directly — a broken packed
    engine on TPU would otherwise crash every solve on the target hardware."""
    try:
        return pack_for_pallas(t)
    except Exception:  # noqa: BLE001 — deliberate blanket fallback
        import logging

        # ERROR, not WARNING: the CLI default log level is ERROR, and a
        # silent drop to the generic engine is a large perf cliff the user
        # must be able to see without benchmarking
        logging.getLogger(__name__).error(
            "pack_for_pallas failed; falling back to the generic engine",
            exc_info=True,
        )
        return None


def pack_for_pallas(t: FactorGraphTensors) -> Optional[PackedMaxSumGraph]:
    """Compile the packed layout, or None when not applicable."""
    if len(t.buckets) != 1 or t.buckets[0].arity != 2:
        return None
    b = t.buckets[0]
    F, V, D = b.n_factors, t.n_vars, t.max_domain_size
    if F == 0 or D > 8:
        return None

    vi = np.asarray(b.var_idx)  # [F, 2]
    edge_var = np.concatenate([vi[:, 0], vi[:, 1]])  # edge id e=p*F+f
    deg = np.bincount(edge_var, minlength=V)

    # group variables by slot class (≈ exact degree, quantized when many)
    cls_of = _degree_classes(deg)
    if cls_of.max(initial=0) > _MAX_SLOT_CLASS:
        return None  # hub degree would unroll too far; generic engine
    buckets: List[Tuple[int, int, int, int]] = []
    var_pcol = np.empty(V, dtype=np.int64)  # original var -> padded column
    order_parts: List[np.ndarray] = []
    voff = 0
    for cls in sorted(set(cls_of.tolist())):
        vs = np.flatnonzero(cls_of == cls)
        nvp = max(_LANES, int(np.ceil(len(vs) / _LANES)) * _LANES)
        var_pcol[vs] = voff + np.arange(len(vs))
        order_parts.append(vs)
        if cls > 0:
            buckets.append((cls, nvp, voff, -1))  # slot offsets assigned below
        voff += nvp
    Vp = voff

    soff = 0
    with_slots = []
    for cls, nvp, bvoff, _ in buckets:
        with_slots.append((cls, nvp, bvoff, soff))
        soff += cls * nvp
    n_slots = soff
    A = max(1, int(np.ceil(n_slots / _TILE)))
    if A > 8:
        return None  # permutation select stage degrades; use generic engine
    N = A * _TILE

    # slot assignment: edge e is the k-th incoming edge of its variable
    order = np.argsort(edge_var, kind="stable")
    k_of = np.empty(2 * F, dtype=np.int64)
    start = np.concatenate([[0], np.cumsum(deg)[:-1]])
    k_of[order] = np.arange(2 * F) - start[edge_var[order]]
    slot_of_edge = np.empty(2 * F, dtype=np.int64)
    for cls, nvp, bvoff, bsoff in with_slots:
        sel = np.flatnonzero((cls_of[edge_var] == cls))
        col = var_pcol[edge_var[sel]] - bvoff
        slot_of_edge[sel] = bsoff + k_of[sel] * nvp + col

    # mate permutation: slot of edge (f,p) pulls from slot of edge (f,1-p)
    perm = np.arange(N, dtype=np.int64)  # dummies: identity
    mate_edge = np.concatenate([np.arange(F, 2 * F), np.arange(F)])
    perm[slot_of_edge] = slot_of_edge[mate_edge]
    plan = plan_permutation(perm, A, _LANES, _LANES)

    # cost rows, OTHER-value-major: row j*D+i = cost(d_other=j, d_tgt=i) so
    # the kernel's min over j works on contiguous [D, N] slabs
    tens = np.asarray(b.tensors)  # [F, D, D]
    cost_rows = np.zeros((D * D, N), dtype=np.float32)
    e = np.arange(2 * F)
    f_of, p_of = e % F, e // F
    for i in range(D):
        for j in range(D):
            vals = np.where(p_of == 0, tens[f_of, i, j], tens[f_of, j, i])
            cost_rows[j * D + i, slot_of_edge] = vals

    mask_np = np.zeros((D, Vp), dtype=np.float32)
    unary_np = np.zeros((D, Vp), dtype=np.float32)
    mask_np[:, var_pcol] = np.asarray(t.domain_mask).T
    unary_np[:, var_pcol] = np.asarray(t.unary_costs).T * mask_np[:, var_pcol]

    vmask_np = np.zeros((D, N), dtype=np.float32)
    vmask_np[:, slot_of_edge] = mask_np[:, var_pcol[edge_var]]
    dcount = vmask_np.sum(axis=0, keepdims=True)
    inv_dcount = np.where(dcount > 0, 1.0 / np.maximum(dcount, 1.0), 0.0)

    pg = PackedMaxSumGraph(
        D=D, n_vars=V, Vp=Vp, N=N, plan=plan,
        buckets=tuple(with_slots),
        cost_rows=jnp.asarray(cost_rows),
        unary_p=jnp.asarray(unary_np),
        mask_p=jnp.asarray(mask_np),
        vmask=jnp.asarray(vmask_np),
        inv_dcount=jnp.asarray(inv_dcount.astype(np.float32)),
        var_order=jnp.asarray(var_pcol.astype(np.int32)),
    )
    if pg.vmem_bytes > _VMEM_BUDGET:
        return None
    return pg


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    """Default to interpret mode when the actual devices are not TPUs, so
    solvers whose engine selection chose the packed path (e.g. in tests that
    monkeypatch the backend) still execute correctly on CPU."""
    if interpret is not None:
        return interpret
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover - device init failure
        return True


def packed_init_state(pg: PackedMaxSumGraph
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    z = jnp.zeros((pg.D, pg.N), dtype=jnp.float32)
    return z, z


def _cycle_body(pg: PackedMaxSumGraph, damping: float, q, r, cost, unary,
                vmask, invd, plan_consts):
    """Traced cycle math shared by the pallas kernel and interpret mode."""
    D, N = pg.D, pg.N
    qm = _permute_in_kernel(q, pg.plan, D, plan_consts)
    # factor→var: r'[i] = min_j cost[j*D+i] + qm[j] — full-sublane [D, N]
    # slabs (cost is other-value-major, see pack_for_pallas)
    r_new = cost[0: D, :] + qm[0: 1, :]
    for j in range(1, D):
        r_new = jnp.minimum(
            r_new, cost[j * D: (j + 1) * D, :] + qm[j: j + 1, :]
        )
    r_new = r_new * vmask
    if damping:
        r_new = damping * r + (1.0 - damping) * r_new
    # var side: beliefs per padded column
    bparts = []
    voff_expect = 0
    for cls, nvp, voff, soff in pg.buckets:
        while voff_expect < voff:  # zero-degree bucket gap
            bparts.append(jnp.zeros((D, _LANES), dtype=r_new.dtype))
            voff_expect += _LANES
        acc = r_new[:, soff: soff + nvp]
        for k in range(1, cls):
            acc = acc + r_new[:, soff + k * nvp: soff + (k + 1) * nvp]
        bparts.append(acc)
        voff_expect += nvp
    while voff_expect < pg.Vp:
        bparts.append(jnp.zeros((D, _LANES), dtype=r_new.dtype))
        voff_expect += _LANES
    beliefs = unary + (
        bparts[0] if len(bparts) == 1 else jnp.concatenate(bparts, axis=1)
    )
    # outgoing q' = beliefs(var) - r', normalized to zero masked mean.
    # expansion = lane-aligned repeats of each bucket's belief block (plain
    # VMEM copies; broadcast+reshape would force a Mosaic relayout)
    qparts = []
    for cls, nvp, voff, soff in pg.buckets:
        bb = beliefs[:, voff: voff + nvp]
        qparts.extend([bb] * cls)
    expanded = jnp.concatenate(qparts, axis=1) if qparts else beliefs
    if expanded.shape[1] < N:
        expanded = jnp.concatenate(
            [expanded,
             jnp.zeros((D, N - expanded.shape[1]), dtype=expanded.dtype)],
            axis=1,
        )
    q_new = expanded - r_new
    mean = (q_new * vmask).sum(axis=0, keepdims=True) * invd
    q_new = (q_new - mean) * vmask
    return q_new, r_new, beliefs


def packed_cycle(
    pg: PackedMaxSumGraph,
    q: jnp.ndarray,
    r: jnp.ndarray,
    damping: float = 0.0,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One fused MaxSum cycle.  Returns (q', r', beliefs [D,Vp], values [V])
    with values in ORIGINAL variable order."""
    return packed_cycles(pg, q, r, 1, damping=damping, interpret=interpret)


def packed_cycles(
    pg: PackedMaxSumGraph,
    q: jnp.ndarray,
    r: jnp.ndarray,
    n_cycles: int,
    damping: float = 0.0,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``n_cycles`` fused MaxSum cycles in ONE pallas kernel.

    Amortizes per-kernel launch/dispatch cost: cycles are statically
    UNROLLED inside the kernel (a fori_loop carry would double-buffer
    (q, r) and blow the ~16MB VMEM scoped-allocation limit at benchmark
    sizes), so kernel size grows linearly with ``n_cycles`` — keep it
    small (≤ ~16); measured sweet spot ~5 on the 10k-var bench.  Returns
    (q', r', beliefs, values) after the last cycle — intermediate
    beliefs are not materialized, so use :func:`packed_cycle` when
    per-cycle values are needed.
    """
    if not 1 <= n_cycles <= 64:
        raise ValueError(
            f"packed_cycles unrolls in-kernel: n_cycles must be in "
            f"[1, 64], got {n_cycles}"
        )
    interpret = _resolve_interpret(interpret)
    D, N, Vp = pg.D, pg.N, pg.Vp

    def kern(q_ref, r_ref, cost_ref, unary_ref, vmask_ref,
             invd_ref, c_r1, c_g1, c_ss, c_g2, c_r2, q_out, r_out, b_out):
        cost = cost_ref[:]
        unary = unary_ref[:]
        vmask = vmask_ref[:]
        invd = invd_ref[:]
        consts = (c_r1[:], c_g1[:], c_ss[:], c_g2[:], c_r2[:])

        # static unroll: a fori_loop carry would double-buffer (q, r) and
        # push the kernel over the ~16MB VMEM scoped-allocation limit at
        # benchmark sizes; unrolled cycles let Mosaic reuse buffers
        qn, rn = q_ref[:], r_ref[:]
        bel = None
        for _ in range(n_cycles):
            qn, rn, bel = _cycle_body(
                pg, damping, qn, rn, cost, unary, vmask, invd, consts
            )
        q_out[:] = qn
        r_out[:] = rn
        b_out[:] = bel

    q_new, r_new, beliefs = pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((D, N), jnp.float32),
            jax.ShapeDtypeStruct((D, N), jnp.float32),
            jax.ShapeDtypeStruct((D, Vp), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 11,
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 3),
        interpret=interpret,
    )(q, r, pg.cost_rows, pg.unary_p, pg.vmask, pg.inv_dcount,
      *_plan_consts(pg.plan))
    values = packed_values(pg, beliefs)
    return q_new, r_new, beliefs, values


def packed_values(pg: PackedMaxSumGraph, beliefs: jnp.ndarray) -> jnp.ndarray:
    """Masked argmin per padded column, mapped to original variable order."""
    big = jnp.where(pg.mask_p > 0, beliefs, PAD_COST)
    pvalues = jnp.argmin(big, axis=0).astype(jnp.int32)
    return pvalues[pg.var_order]


def packed_local_tables(pg: PackedMaxSumGraph, x: jnp.ndarray,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Local cost tables for the local-search family, lane-packed.

    Same result as ops.compile.local_cost_tables on the source tensors
    (out[v, d] = unary[v, d] + Σ_{factors containing v} cost(v=d | others
    at x), PAD_COST at invalid slots), computed in one pallas kernel:
    expand current values to slots, Clos-route each slot its factor's
    other-endpoint value, select the matching cost row per slot, and
    bucket-sum slots per variable — no XLA gather/segment ops.

    x: [V] int32 value indices (original variable order) → [V, D] float32.
    """
    interpret = _resolve_interpret(interpret)
    D, N, Vp = pg.D, pg.N, pg.Vp
    # current value per padded column, as f32 broadcast over all D rows —
    # keeps every in-kernel op on the same [D, *] shapes as _cycle_body
    # (Mosaic rejects some 1-sublane-row layouts)
    x_p = jnp.zeros((D, Vp), jnp.float32).at[:, pg.var_order].set(
        x.astype(jnp.float32)[None, :]
    )

    def kern(xp_ref, cost_ref, unary_ref, c_r1, c_g1, c_ss, c_g2, c_r2,
             t_out):
        xp = xp_ref[:]
        cost = cost_ref[:]
        # expand values to slots (aligned repeats, as in _cycle_body)
        parts = []
        for cls, nvp, voff, soff in pg.buckets:
            parts.extend([xp[:, voff: voff + nvp]] * cls)
        xs = jnp.concatenate(parts, axis=1) if parts else xp
        if xs.shape[1] < N:
            xs = jnp.concatenate(
                [xs, jnp.zeros((D, N - xs.shape[1]), xs.dtype)], axis=1
            )
        xo = _permute_in_kernel(
            xs, pg.plan, D, (c_r1[:], c_g1[:], c_ss[:], c_g2[:], c_r2[:])
        )
        # per-slot cost row for the other endpoint's current value
        contrib = cost[0: D, :]
        for j in range(1, D):
            contrib = jnp.where(
                xo == float(j), cost[j * D: (j + 1) * D, :], contrib
            )
        # bucket-sum slots per variable (as in _cycle_body's beliefs)
        bparts = []
        voff_expect = 0
        for cls, nvp, voff, soff in pg.buckets:
            while voff_expect < voff:
                bparts.append(jnp.zeros((D, _LANES), dtype=contrib.dtype))
                voff_expect += _LANES
            acc = contrib[:, soff: soff + nvp]
            for k in range(1, cls):
                acc = acc + contrib[:, soff + k * nvp: soff + (k + 1) * nvp]
            bparts.append(acc)
            voff_expect += nvp
        while voff_expect < Vp:
            bparts.append(jnp.zeros((D, _LANES), dtype=contrib.dtype))
            voff_expect += _LANES
        t_out[:] = unary_ref[:] + (
            bparts[0] if len(bparts) == 1 else jnp.concatenate(bparts, axis=1)
        )

    tables_p = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((D, Vp), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 8,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x_p, pg.cost_rows, pg.unary_p, *_plan_consts(pg.plan))
    tables = tables_p[:, pg.var_order].T  # [V, D] original order
    mask = pg.mask_p[:, pg.var_order].T
    return jnp.where(mask > 0, tables, PAD_COST)
