"""Fused lane-packed MaxSum engine (Pallas TPU kernel).

The generic engine (pydcop_tpu.ops.maxsum_kernels) lays messages out as
``[E, D]`` — with the domain axis (D is 3-10 for every reference problem
family) in the 128-lane minor dimension, >90% of HBM traffic is padding,
and the XLA segment/gather ops scalarize.  This engine is the TPU-first
re-design for the all-binary case (graph coloring, Ising — every headline
benchmark):

* messages ``[D, N]``: edge slots ride the lane axis fully packed, the
  domain axis rides sublanes;
* **var-grouped slot layout**: each variable's incoming edges occupy slots
  ``slot_off + k*nv + v`` of its degree-class bucket, so the variable-side
  belief sum and message expansion are aligned slice adds / broadcasts —
  no segment_sum, no gather;
* the single irreducible graph-structured exchange — routing each edge
  slot's outgoing message to its factor's other endpoint (``mate``) — is a
  static lane permutation executed via the Clos-routed stage plan
  (pydcop_tpu.ops.clos_routing / pallas_permute): within-vreg gathers +
  tile transposes + per-lane selects, all Mosaic vector ops;
* one cycle = ONE pallas kernel, everything VMEM-resident.

Cycle math is identical to maxsum_kernels.maxsum_cycle (itself the
reference's factor_costs_for_var / costs_for_factor,
pydcop/algorithms/maxsum.py:345,556): given state (q, r):

    r' = vmask ⊙ (damping*r + (1-damping) * min_j(cost[i,j] + q[mate][j]))
    b  = unary + Σ_incoming r'
    q' = vmask ⊙ (b[var(slot)] - r' - masked_mean)

Falls back (returns None from :func:`pack_for_pallas`) for non-binary or
mixed-arity graphs, or when the working set would exceed VMEM.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pydcop_tpu.ops.clos_routing import PermutationPlan, plan_permutation
from pydcop_tpu.ops.compile import FactorGraphTensors, PAD_COST
from pydcop_tpu.ops.pallas_permute import _permute_in_kernel, _plan_consts

_LANES = 128
_TILE = _LANES * _LANES  # elements routed per (b, l) plane
# Working-set budget for the ESTIMATE in _vmem_estimate.  v5e has 128MB
# of physical VMEM; the default 16MB scoped-allocation limit is raised
# per-kernel via CompilerParams(vmem_limit_bytes=_VMEM_LIMIT) below, so
# the budget guards against genuinely oversized graphs, not the
# compiler's conservative default.  The estimate runs ~40% under the
# measured scoped allocation (16.3MB actual at 11.7MB estimated), so
# 40MB estimated ≈ 56MB actual — comfortable headroom under _VMEM_LIMIT.
_VMEM_BUDGET = 40 * 2**20
#: sublane stride of quaternary (j, k, m) cost blocks — a full 8-row
#: tile per block so in-kernel slices are sublane-aligned (D ≤ 5)
_Q4_STRIDE = 8
_VMEM_LIMIT = 100 * 2**20


def _compiler_params():
    # CompilerParams was TPUCompilerParams on older jax (version shim,
    # same gate/stub policy as parallel/compat.py)
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(vmem_limit_bytes=_VMEM_LIMIT)


_MAX_BUCKETS = 24
# _cycle_body / packed_local_tables unroll a python loop of `cls` slice-adds
# per degree bucket; a scale-free hub with degree in the thousands would blow
# trace/compile time and kernel size, so above this slot class a variable is
# SPLIT into several sub-columns of ≤ this many slots each (hub splitting,
# see pack_for_pallas).  The sub-columns live as ordinary columns in the
# degree-class buckets — dense lanes, no padding blowup — kept contiguous
# within one 128-lane bin so the cross-column combine is a handful of
# within-vreg lane gathers (suffix doubling + head spread, _hub_sum/_hub_op).
_MAX_SLOT_CLASS = 96


def _class_bounds(deg: np.ndarray) -> np.ndarray:
    """Slot-class boundaries for a population of (sub-)column degrees.
    Exact degrees when few are distinct; otherwise boundaries are chosen
    by a small DP MINIMIZING total padded slots
    Σ_class cls · ceil(n_class/128)·128 — the quantity that decides
    whether the graph fits the A≤8 permutation budget.  (Per-quantile
    boundaries fragmented power-law degree tails into many near-empty
    128-column bins: a 3-variable class-96 bucket pays 12,288 padded
    slots.)"""
    nz, cnt = np.unique(deg[deg > 0], return_counts=True)
    if len(nz) <= _MAX_BUCKETS:
        return nz.astype(np.int64)
    csum = np.concatenate([[0], np.cumsum(cnt)])
    k, B = len(nz), _MAX_BUCKETS
    INF = np.inf
    dp = np.full((B + 1, k + 1), INF)
    dp[0, 0] = 0.0
    choice = np.zeros((B + 1, k + 1), dtype=np.int64)
    for bnum in range(1, B + 1):
        for j in range(1, k + 1):
            # group = distinct degrees (i..j]; every member pads to nz[j-1]
            # slots, columns pad to whole 128-lane bins
            n = csum[j] - csum[:j]
            cost = dp[bnum - 1, :j] + nz[j - 1] * (
                np.ceil(n / _LANES) * _LANES
            )
            i = int(np.argmin(cost))
            dp[bnum, j] = cost[i]
            choice[bnum, j] = i
    bnum = int(np.argmin(dp[:, k]))
    bounds = []
    j = k
    while j > 0:
        bounds.append(nz[j - 1])
        j = int(choice[bnum, j])
        bnum -= 1
    return np.array(sorted(bounds), dtype=np.int64)


def _apply_bounds(deg: np.ndarray, bounds: np.ndarray) -> np.ndarray:
    cls = np.zeros_like(deg)
    if len(bounds) == 0:
        return cls
    pos = np.searchsorted(bounds, deg[deg > 0])
    cls[deg > 0] = bounds[np.minimum(pos, len(bounds) - 1)]
    return cls


@dataclass
class PackedMaxSumGraph:
    """Compiled lane-packed layout of an all-binary factor graph."""

    D: int
    n_vars: int  # real variables
    Vp: int  # padded variable columns
    N: int  # padded edge slots (= plan.n)
    plan: PermutationPlan  # mate routing
    buckets: Tuple[Tuple[int, int, int, int], ...]  # (cls, nvp, voff, soff)
    # cost tables, OTHER-value-major: row j*D+i = cost(d_oth=j, d_tgt=i),
    # so kernels slice cost[j*D:(j+1)*D] as the contiguous d_oth=j slab
    cost_rows: jnp.ndarray  # [D*D, N]
    unary_p: jnp.ndarray  # [D, Vp]
    mask_p: jnp.ndarray  # [D, Vp] 1=valid value (0 on dummy vars)
    vmask: jnp.ndarray  # [D, N] mask_p spread to slots (0 on dummy slots)
    inv_dcount: jnp.ndarray  # [1, N] 1/|valid values| per slot (0 dummy)
    var_order: jnp.ndarray  # [n_vars] padded column of each original var
    # original variable id per padded column (-1 = dummy); hub members map
    # to their hub variable.  Host-side numpy (used by pack_from_pg).
    col_var: np.ndarray = None
    # slot of each edge endpoint (edge e = p*F + f for factor f, side p);
    # host-side numpy — lets packings built on top (mgm2 pairing) map
    # factor-indexed data onto slots
    slot_of_edge: np.ndarray = None
    # -- mixed arity (pack_mixed_for_pallas) ------------------------------
    # Each bucket's slots are grouped by arity: k in [0, c1) unary
    # factors, [c1, c1+c2) binary, [c1+c2, c1+c2+c3) ternary,
    # [c1+c2+c3, cls) quaternary; plan routes the first sibling, plan2
    # the second, plan3 the third (identity elsewhere).
    mixed: bool = False
    buckets_arity: Tuple[Tuple[int, ...], ...] = ()  # (c1, c2, c3, c4)
    plan2: Optional[PermutationPlan] = None
    cost1_rows: Optional[jnp.ndarray] = None  # [D, N]
    cost3_rows: Optional[jnp.ndarray] = None  # [D*D*D, N] row (j*D+k)*D+i
    arity_mask2: Optional[jnp.ndarray] = None  # [1, N] 1 on binary slots
    arity_mask3: Optional[jnp.ndarray] = None  # [1, N] 1 on ternary slots
    # -- arity 4 (round 5): present only when the graph has quaternary
    # factors; cost row ((j*D+k)*D+l)*D+i for siblings (j, k, l) routed
    # by (plan, plan2, plan3).  The D^4-row slab would be ~41MB at full
    # width even for a tiny graph (N ≥ one 16384-lane tile), so it is
    # stored NARROW — only the 4-ary section lanes, which are 128-
    # aligned ranges (q4_sections) gathered/spread in-kernel with the
    # same static lane slicing as the bucket reduce.
    plan3: Optional[PermutationPlan] = None
    cost4_rows: Optional[jnp.ndarray] = None  # [D^3*8, M4] (narrow,
    #                                            8-row-aligned blocks)
    arity_mask4: Optional[jnp.ndarray] = None  # [1, N] 1 on 4-ary slots
    q4_sections: Tuple[Tuple[int, int], ...] = ()  # (start, width) lanes
    # -- hub splitting (variables with degree > _MAX_SLOT_CLASS) ----------
    # A hub's slots are split across m contiguous sub-columns inside a
    # normal degree-class bucket; its full belief/table is recovered with
    # hub_nsteps suffix-doubling lane gathers + one head-spread gather.
    # The head sub-column is the hub's var_order column (mask/unary there).
    hub_nsteps: int = 0
    hub_steps_idx: Optional[jnp.ndarray] = None   # [nsteps*rows, 128] i32
    hub_steps_mask: Optional[jnp.ndarray] = None  # [nsteps, Vp] f32
    hub_head_idx: Optional[jnp.ndarray] = None    # [rows, 128] i32

    @property
    def vmem_bytes(self) -> int:
        return _vmem_estimate(self.D, self.N, self.Vp, self.hub_nsteps)


def _vmem_estimate(D: int, N: int, Vp: int, hub_nsteps: int = 0) -> int:
    """Rough VMEM working-set bound of the cycle kernel: cost tables, q/r
    in+out, ~2 permute-stage temporaries, belief-side arrays, the 5 Clos
    plan index arrays (~5N int32), plus the A-way select stage of the
    permutation which materializes up to A candidate [D, TILE] planes
    (A*_TILE == N, so that term is one extra D*N).  Hub combines add the
    step/head index+mask constants and one [D, Vp] gather temporary."""
    hub = (2 * hub_nsteps + 1) * Vp + (D * Vp if hub_nsteps else 0)
    return 4 * (D * D * N + 7 * D * N + 3 * D * Vp + 5 * N + hub)


@dataclass
class ForcedLayout:
    """A cross-shard-uniform column layout for :func:`pack_for_pallas`.

    The sharded packed engine (parallel/packed_mesh.py) runs ONE
    shard_map trace over every device, so each shard's packing must have
    IDENTICAL static structure — same class blocks (hence same buckets,
    Vp, N, A) AND the same variable→column assignment, so per-shard
    partial beliefs align column-wise and the cross-shard combine is a
    bare ``psum`` on ``[D, Vp]`` (no scatter/gather through the global
    variable axis — measured to dominate the cycle otherwise).

    Built from the per-variable MAXIMUM shard degree: every variable's
    class holds its slots on every shard (shards where it has fewer
    edges leave padding slots empty).
    """

    nvp: Tuple[Tuple[int, int], ...]  # ordered (class, columns) blocks
    var_pcol: "np.ndarray"            # [V] fixed column per variable


def try_pack_for_pallas(t: FactorGraphTensors) -> Optional[PackedMaxSumGraph]:
    """Fail-safe engine selection: any packing bug degrades to the generic
    engine (with a logged warning) instead of taking the solve down.  Solvers
    must use this, never :func:`pack_for_pallas` directly — a broken packed
    engine on TPU would otherwise crash every solve on the target hardware.

    All-binary graphs take the binary packer (hub splitting, DP classes);
    mixed arity-1/2/3/4 graphs the mixed packer."""
    try:
        pg = pack_for_pallas(t)
        if pg is None:
            pg = pack_mixed_for_pallas(t)
        return pg
    except Exception:  # noqa: BLE001 — deliberate blanket fallback
        import logging

        # ERROR, not WARNING: the CLI default log level is ERROR, and a
        # silent drop to the generic engine is a large perf cliff the user
        # must be able to see without benchmarking
        logging.getLogger(__name__).error(
            "pack_for_pallas failed; falling back to the generic engine",
            exc_info=True,
        )
        return None


def pack_for_pallas(
    t: FactorGraphTensors, layout: Optional[ForcedLayout] = None,
) -> Optional[PackedMaxSumGraph]:
    """Compile the packed layout, or None when not applicable.

    ``layout`` forces a cross-shard-uniform column layout (see
    :class:`ForcedLayout`): class blocks and the variable→column map
    come from the layout instead of this graph's own DP, so every shard
    of a partitioned graph packs with identical static structure AND
    aligned columns.  Hub splitting is disabled under a forced layout;
    each variable's degree must fit its forced class (the caller builds
    the layout from max-over-shard degrees, so this holds by
    construction)."""
    if len(t.buckets) != 1 or t.buckets[0].arity != 2:
        return None
    b = t.buckets[0]
    F, V, D = b.n_factors, t.n_vars, t.max_domain_size
    if F == 0 or D > 8:
        return None

    vi = np.asarray(b.var_idx)  # [F, 2]
    edge_var = np.concatenate([vi[:, 0], vi[:, 1]])  # edge id e=p*F+f
    deg = np.bincount(edge_var, minlength=V)

    # hub splitting: a variable with degree above the slot-class ceiling is
    # split into m sub-columns of cls_h ≤ _MAX_SLOT_CLASS slots each (cls_h
    # rounded up to a multiple of 8 to bound the distinct-bucket count).
    # Sub-columns must stay inside one 128-lane bin for the gather-based
    # combine, so per-hub degree is capped at _MAX_SLOT_CLASS * _LANES.
    S = _MAX_SLOT_CLASS
    hub_of = deg > S
    if layout is not None and bool(hub_of.any()):
        return None  # forced layouts carry no per-shard hub structure
    if int(deg.max(initial=0)) > S * _LANES:
        return None  # a single hub beyond ~12k neighbors: generic engine
    hub_vars = np.flatnonzero(hub_of)
    # balanced split: hub v becomes m = ceil(deg/S) sub-columns of
    # sub_deg = ceil(deg/m) ≤ S slots each.  Sub-degrees join the class
    # DP alongside ordinary degrees so sub-columns share buckets with
    # the regular population (a fixed per-hub class would fragment the
    # tail into near-empty 128-column bins).
    hub_m = np.zeros(V, dtype=np.int64)
    sub_deg = np.zeros(V, dtype=np.int64)
    for v in hub_vars:
        hub_m[v] = int(np.ceil(deg[v] / S))
        sub_deg[v] = int(np.ceil(deg[v] / hub_m[v]))

    buckets: List[Tuple[int, int, int, int]] = []
    group_heads: List[Tuple[int, int]] = []  # (head column, m)
    max_m = 1
    if layout is not None:
        # fixed column assignment: blocks and var→column from the layout
        hub_cls = np.zeros(V, dtype=np.int64)  # no hubs under layouts
        var_pcol = np.asarray(layout.var_pcol, dtype=np.int64)
        voff = 0
        col_class = np.zeros(0, dtype=np.int64)
        for cls, nvp in layout.nvp:
            if cls > 0:
                buckets.append((int(cls), int(nvp), voff, -1))
            col_class = np.concatenate(
                [col_class, np.full(nvp, cls, dtype=np.int64)]
            )
            voff += int(nvp)
        Vp = voff
        if np.any(deg > col_class[var_pcol]):
            return None  # a degree outgrew its forced class
        col_var = np.full(Vp, -1, dtype=np.int64)
        col_var[var_pcol] = np.arange(V)
    else:
        pop = np.concatenate(
            [deg[~hub_of]]
            + [np.full(hub_m[v], sub_deg[v]) for v in hub_vars]
        )
        bounds = _class_bounds(pop)
        cls_of = _apply_bounds(np.where(hub_of, 0, deg), bounds)
        hub_cls = _apply_bounds(sub_deg, bounds)
        classes = sorted(
            set(cls_of[~hub_of].tolist())
            | set(hub_cls[hub_vars].tolist())
        )

        # column layout per class bucket: hub groups first (first-fit
        # descending into 128-lane bins, so no group straddles a bin),
        # then single variables fill the gaps
        var_pcol = np.full(V, -1, dtype=np.int64)  # var -> (head) column
        col_var_parts: List[np.ndarray] = []
        voff = 0
        for cls in classes:
            gvars = [v for v in hub_vars if hub_cls[v] == cls]
            svars = np.flatnonzero((cls_of == cls) & ~hub_of).tolist()
            if not gvars and not svars:
                continue
            bins: List[List[int]] = []  # per 128-lane bin: vars/columns
            for v in sorted(gvars, key=lambda u: -hub_m[u]):
                m = int(hub_m[v])
                max_m = max(max_m, m)
                for bi, cols in enumerate(bins):
                    if len(cols) + m <= _LANES:
                        break
                else:
                    bins.append([])
                    bi = len(bins) - 1
                cols = bins[bi]
                head = voff + bi * _LANES + len(cols)
                var_pcol[v] = head
                group_heads.append((head, m))
                cols.extend([v] * m)
            for v in svars:
                for bi, cols in enumerate(bins):
                    if len(cols) < _LANES:
                        break
                else:
                    bins.append([])
                    bi = len(bins) - 1
                cols = bins[bi]
                var_pcol[v] = voff + bi * _LANES + len(cols)
                cols.append(v)
            nvp = max(_LANES, len(bins) * _LANES)
            colv = np.full(nvp, -1, dtype=np.int64)
            for bi, cols in enumerate(bins):
                colv[bi * _LANES: bi * _LANES + len(cols)] = cols
            col_var_parts.append(colv)
            if cls > 0:
                buckets.append((cls, nvp, voff, -1))  # slot offsets below
            voff += nvp
        Vp = voff
        col_var = np.concatenate(col_var_parts)

    soff = 0
    with_slots = []
    for cls, nvp, bvoff, _ in buckets:
        with_slots.append((cls, nvp, bvoff, soff))
        soff += cls * nvp
    n_slots = soff
    A = max(1, int(np.ceil(n_slots / _TILE)))
    if A > 8:
        return None  # permutation select stage degrades; use generic engine
    N = A * _TILE

    # per-column bucket lookups for vectorized slot assignment
    col_soff = np.zeros(Vp, dtype=np.int64)
    col_nvp = np.ones(Vp, dtype=np.int64)
    col_voff = np.zeros(Vp, dtype=np.int64)
    for cls, nvp, bvoff, bsoff in with_slots:
        col_soff[bvoff: bvoff + nvp] = bsoff
        col_nvp[bvoff: bvoff + nvp] = nvp
        col_voff[bvoff: bvoff + nvp] = bvoff

    # slot assignment: edge e is the k-th incoming edge of its variable;
    # hub edges spill into sub-column k // cls_h at rank k % cls_h
    order = np.argsort(edge_var, kind="stable")
    k_of = np.empty(2 * F, dtype=np.int64)
    start = np.concatenate([[0], np.cumsum(deg)[:-1]])
    k_of[order] = np.arange(2 * F) - start[edge_var[order]]
    split = np.where(hub_cls > 0, hub_cls, 1 << 30)[edge_var]
    sub_j = k_of // split
    k_loc = k_of - sub_j * split
    cole = var_pcol[edge_var] + sub_j
    slot_of_edge = col_soff[cole] + k_loc * col_nvp[cole] + (
        cole - col_voff[cole])

    # mate permutation: slot of edge (f,p) pulls from slot of edge (f,1-p)
    perm = np.arange(N, dtype=np.int64)  # dummies: identity
    mate_edge = np.concatenate([np.arange(F, 2 * F), np.arange(F)])
    perm[slot_of_edge] = slot_of_edge[mate_edge]
    plan = plan_permutation(perm, A, _LANES, _LANES)

    # cost rows, OTHER-value-major: row j*D+i = cost(d_other=j, d_tgt=i) so
    # the kernel's min over j works on contiguous [D, N] slabs
    tens = np.asarray(b.tensors)  # [F, D, D]
    cost_rows = np.zeros((D * D, N), dtype=np.float32)
    e = np.arange(2 * F)
    f_of, p_of = e % F, e // F
    for i in range(D):
        for j in range(D):
            vals = np.where(p_of == 0, tens[f_of, i, j], tens[f_of, j, i])
            cost_rows[j * D + i, slot_of_edge] = vals

    mask_np = np.zeros((D, Vp), dtype=np.float32)
    unary_np = np.zeros((D, Vp), dtype=np.float32)
    mask_np[:, var_pcol] = np.asarray(t.domain_mask).T
    unary_np[:, var_pcol] = np.asarray(t.unary_costs).T * mask_np[:, var_pcol]

    vmask_np = np.zeros((D, N), dtype=np.float32)
    vmask_np[:, slot_of_edge] = mask_np[:, var_pcol[edge_var]]
    dcount = vmask_np.sum(axis=0, keepdims=True)
    inv_dcount = np.where(dcount > 0, 1.0 / np.maximum(dcount, 1.0), 0.0)

    nsteps, steps_idx, steps_mask, head_idx = _hub_constants(
        group_heads, Vp, max_m
    )

    pg = PackedMaxSumGraph(
        D=D, n_vars=V, Vp=Vp, N=N, plan=plan,
        buckets=tuple(with_slots),
        cost_rows=jnp.asarray(cost_rows),
        unary_p=jnp.asarray(unary_np),
        mask_p=jnp.asarray(mask_np),
        vmask=jnp.asarray(vmask_np),
        inv_dcount=jnp.asarray(inv_dcount.astype(np.float32)),
        var_order=jnp.asarray(var_pcol.astype(np.int32)),
        col_var=col_var,
        slot_of_edge=slot_of_edge,
        hub_nsteps=nsteps,
        hub_steps_idx=steps_idx,
        hub_steps_mask=steps_mask,
        hub_head_idx=head_idx,
    )
    if pg.vmem_bytes > _VMEM_BUDGET:
        return None
    return pg


def packed_swap_factor(pg: PackedMaxSumGraph, k: int,
                       table) -> PackedMaxSumGraph:
    """Hot-swap ONE binary factor's cost table at the packed layout's
    fixed shape (ISSUE 8 / the in-place rewrite maxsum_dynamic's
    layout comment planned for): writes the two slot COLUMNS of
    ``cost_rows`` that belong to factor ``k`` (bucket row order) —
    no re-routing, no re-packing, O(D²) instead of O(F·D²) host work.

    ``table`` is the factor's full padded sign-adjusted [D, D] tensor
    in the bucket slot's axis order.  Returns a layout sharing every
    static structure (plan, masks, slots) with ``pg`` — only
    ``cost_rows`` is replaced, so consumers that stage ``cost_rows``
    as a runtime argument (parallel/packed_mesh stacked packs,
    parallel/mesh ``_run_args``) pay zero retraces; the single-chip
    solver still flushes its compiled chunks (the pg rides them as a
    closure constant there).
    """
    import dataclasses as _dc

    if pg.mixed or pg.slot_of_edge is None:
        raise NotImplementedError(
            "packed_swap_factor supports the all-binary packed layout; "
            "mixed-arity packs are rebuilt by the repack path"
        )
    D = pg.D
    t = np.asarray(table, dtype=np.float32)
    if t.shape != (D, D):
        raise ValueError(
            f"swap table shape {t.shape} != ({D}, {D}) — the factor's "
            f"scope must be unchanged"
        )
    F = pg.slot_of_edge.shape[0] // 2
    if not (0 <= k < F):
        raise ValueError(f"factor index {k} out of range [0, {F})")
    # cost_rows is OTHER-value-major (row j*D+i = cost(d_oth=j,
    # d_tgt=i)): the p=0 slot sees the table as [tgt, oth] → column is
    # t.T flattened; the p=1 slot sees [oth, tgt] → t flattened
    s0 = int(pg.slot_of_edge[k])
    s1 = int(pg.slot_of_edge[F + k])
    col0 = jnp.asarray(np.ascontiguousarray(t.T).reshape(-1))
    col1 = jnp.asarray(t.reshape(-1))
    cost_rows = pg.cost_rows.at[:, s0].set(col0).at[:, s1].set(col1)
    return _dc.replace(pg, cost_rows=cost_rows)


#: distinct-class cap ABOVE which merging is not attempted: the greedy
#: pair scan is O(C^2) per merge, so a pathologically heterogeneous
#: graph (up to 14^3 distinct quantized triples) must fall to the
#: generic engine instantly instead of grinding through minutes of
#: host-side merging inside "fail-safe" engine selection
_MERGE_CLASS_CAP = 128


def _merge_mixed_classes(keys: np.ndarray, hub_m: np.ndarray,
                         max_classes: int, slot_budget: int):
    """Agglomerative merging of mixed class triples.

    The ladder quantization of (c1, c2, c3) triples can fragment a
    power-law graph into dozens of classes whose 128-column padding
    blows the Clos A ≤ 8 slot budget (measured: 174k padded slots for
    76k real on the ternary scale-free bench).  Greedily merge the pair
    of classes with the smallest padded-slot delta (the merged class is
    the componentwise max) until the class count fits, then keep
    merging while it SAVES slots.

    Column counts use the SAME first-fit-descending bin packing as the
    layout (hub groups cannot straddle a 128-lane bin), so the greedy
    deltas and the budget check see the real costs.

    Returns {original triple -> representative triple}, or None when
    the result cannot fit the slot budget (or the class population is
    too fragmented to even try).
    """
    # per class: [n_single_columns, list of hub group sizes]
    cnt: dict = {}
    for kt, m in zip(map(tuple, keys.tolist()), hub_m.tolist()):
        e = cnt.setdefault(kt, [0, []])
        if m > 0:
            e[1].append(int(m))
        else:
            e[0] += 1
    if len(cnt) > _MERGE_CLASS_CAP:
        return None

    def pad_cols(singles, groups):
        # first-fit descending of groups into 128-lane bins, singles
        # fill the gaps — mirrors the layout loop exactly
        space: list = []
        for m in sorted(groups, reverse=True):
            for bi, free in enumerate(space):
                if free >= m:
                    space[bi] -= m
                    break
            else:
                space.append(_LANES - m)
        left = singles
        for bi, free in enumerate(space):
            take = min(left, free)
            space[bi] -= take
            left -= take
        bins = len(space) + int(np.ceil(left / _LANES))
        return max(1, bins) * _LANES

    def class_slots(k, e):
        return sum(k) * pad_cols(e[0], e[1])

    def slots():
        return sum(class_slots(k, e) for k, e in cnt.items())

    rep = {k: k for k in cnt}

    def best_merge():
        items = list(cnt.items())
        best = None
        for i in range(len(items)):
            for j in range(i + 1, len(items)):
                (u, eu), (w, ew) = items[i], items[j]
                m = tuple(max(a, b) for a, b in zip(u, w))
                merged = [eu[0] + ew[0], eu[1] + ew[1]]
                delta = (class_slots(m, merged)
                         - class_slots(u, eu) - class_slots(w, ew))
                if best is None or delta < best[0]:
                    best = (delta, u, w, m)
        return best

    def apply(u, w, m):
        eu, ew = cnt.pop(u), cnt.pop(w)
        e = cnt.setdefault(m, [0, []])
        e[0] += eu[0] + ew[0]
        e[1].extend(eu[1] + ew[1])
        for k, r in rep.items():
            if r == u or r == w:
                rep[k] = m

    while len(cnt) > max_classes and len(cnt) > 1:
        _d, u, w, m = best_merge()
        apply(u, w, m)  # forced: the class count must fit
    while len(cnt) > 1:
        d, u, w, m = best_merge()
        if d >= 0:
            break  # no merge saves slots anymore
        apply(u, w, m)
    if slots() > slot_budget:
        return None
    return rep


def _hub_constants(group_heads, Vp: int, max_m: int):
    """Hub combine constants: suffix-doubling partner gathers confined
    to each group's lane range, plus the head-spread gather.  Identity
    (and mask 0) everywhere else, so non-hub columns pass through.
    Returns (nsteps, steps_idx, steps_mask, head_idx) — all None when
    there are no hub groups."""
    if not group_heads:
        return 0, None, None, None
    rows = Vp // _LANES
    nsteps = max(1, int(np.ceil(np.log2(max_m))))
    lane_id = np.tile(np.arange(_LANES, dtype=np.int32), (rows, 1))
    head_np = lane_id.copy()
    sidx_np = np.tile(lane_id, (nsteps, 1))
    smask_np = np.zeros((nsteps, Vp), dtype=np.float32)
    for head, m in group_heads:
        r0, l0 = head // _LANES, head % _LANES
        head_np[r0, l0: l0 + m] = l0
        for s in range(nsteps):
            step = 1 << s
            for lane in range(l0, l0 + m):
                if lane + step < l0 + m:
                    sidx_np[s * rows + r0, lane] = lane + step
                    smask_np[s, r0 * _LANES + lane] = 1.0
    return (nsteps, jnp.asarray(sidx_np), jnp.asarray(smask_np),
            jnp.asarray(head_np))


#: slot-count quantization ladder for mixed class triples — short so the
#: class-triple product space stays small
_QUANT_LADDER = np.array(
    (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96), dtype=np.int64)


def _quantize_up(counts: np.ndarray) -> np.ndarray:
    return _QUANT_LADDER[np.minimum(
        np.searchsorted(_QUANT_LADDER, counts), len(_QUANT_LADDER) - 1)]


@dataclass
class MixedLayout:
    """Column/slot layout of a mixed-arity packing, independent of any
    particular edge set — built by :func:`_mixed_layout` from the
    per-variable class triples.  parallel/packed_mesh builds ONE from
    per-variable MAX per-shard degrees and forces it on every shard's
    :func:`pack_mixed_for_pallas` call so the packed statics (D, Vp, N,
    buckets, plan shapes) are shard-invariant (SPMD single trace)."""

    keys: np.ndarray                     # [V, 4] post-merge tuples
    hub_of: np.ndarray                   # [V] bool
    hub_m: np.ndarray                    # [V] sub-columns per hub
    var_pcol: np.ndarray                 # [V] head column
    col_var: np.ndarray                  # [Vp] var per column (-1 dummy)
    with_slots: List[Tuple[int, int, int, int]]
    buckets_arity: List[Tuple[int, ...]]      # (c1, c2, c3, c4)
    group_heads: List[Tuple[int, int]]
    max_m: int
    Vp: int
    N: int
    col_soff: np.ndarray
    col_nvp: np.ndarray
    col_voff: np.ndarray
    col_base: dict


def _mixed_layout(keys: np.ndarray, hub_of: np.ndarray,
                  hub_m: np.ndarray) -> Optional[MixedLayout]:
    """Column layout per class triple: hub groups first (first-fit
    descending into 128-lane bins so no group straddles a bin), then
    singles fill the gaps — same scheme as the binary packer.  Pure
    function of (keys, hub_of, hub_m); returns None when the slot count
    exceeds the Clos A ≤ 8 budget."""
    V = keys.shape[0]
    key_of = [tuple(row) for row in keys.tolist()]
    classes = sorted(set(key_of))
    hub_vars = np.flatnonzero(hub_of)

    buckets: List[Tuple[int, int, int, int]] = []
    buckets_arity: List[Tuple[int, ...]] = []  # (c1, c2, c3, c4)
    var_pcol = np.full(V, -1, dtype=np.int64)
    col_var_parts: List[np.ndarray] = []
    group_heads: List[Tuple[int, int]] = []
    max_m = 1
    voff = 0
    for key in classes:
        gvars = [v for v in hub_vars if key_of[v] == key]
        svars = [v for v in np.flatnonzero(~hub_of)
                 if key_of[v] == key]
        bins: List[List[int]] = []
        for v in sorted(gvars, key=lambda u: -hub_m[u]):
            m = int(hub_m[v])
            max_m = max(max_m, m)
            for bi, cols in enumerate(bins):
                if len(cols) + m <= _LANES:
                    break
            else:
                bins.append([])
                bi = len(bins) - 1
            cols = bins[bi]
            head = voff + bi * _LANES + len(cols)
            var_pcol[v] = head
            group_heads.append((head, m))
            cols.extend([v] * m)
        for v in svars:
            for bi, cols in enumerate(bins):
                if len(cols) < _LANES:
                    break
            else:
                bins.append([])
                bi = len(bins) - 1
            cols = bins[bi]
            var_pcol[v] = voff + bi * _LANES + len(cols)
            cols.append(v)
        nvp = max(_LANES, len(bins) * _LANES)
        colv = np.full(nvp, -1, dtype=np.int64)
        for bi, cols in enumerate(bins):
            colv[bi * _LANES: bi * _LANES + len(cols)] = cols
        col_var_parts.append(colv)
        cls = sum(key)
        if cls > 0:
            buckets.append([cls, nvp, voff, -1])
            buckets_arity.append(key)
        voff += nvp
    Vp = voff
    col_var = np.concatenate(col_var_parts)

    soff = 0
    with_slots = []
    for cls, nvp, bvoff, _ in buckets:
        with_slots.append((cls, nvp, bvoff, soff))
        soff += cls * nvp
    n_slots = soff
    A = max(1, int(np.ceil(n_slots / _TILE)))
    if A > 8:
        return None
    N = A * _TILE

    col_soff = np.zeros(Vp, dtype=np.int64)
    col_nvp = np.ones(Vp, dtype=np.int64)
    col_voff = np.zeros(Vp, dtype=np.int64)
    col_base = {a: np.zeros(Vp, dtype=np.int64) for a in (1, 2, 3, 4)}
    for (cls, nvp, bvoff, bsoff), key in zip(with_slots, buckets_arity):
        sl = slice(bvoff, bvoff + nvp)
        col_soff[sl] = bsoff
        col_nvp[sl] = nvp
        col_voff[sl] = bvoff
        col_base[1][sl] = 0
        col_base[2][sl] = key[0]
        col_base[3][sl] = key[0] + key[1]
        col_base[4][sl] = key[0] + key[1] + key[2]
    return MixedLayout(
        keys=keys, hub_of=hub_of, hub_m=hub_m, var_pcol=var_pcol,
        col_var=col_var, with_slots=with_slots,
        buckets_arity=buckets_arity, group_heads=group_heads,
        max_m=max_m, Vp=Vp, N=N, col_soff=col_soff, col_nvp=col_nvp,
        col_voff=col_voff, col_base=col_base,
    )


def pack_mixed_for_pallas(t: FactorGraphTensors,
                          layout: Optional[MixedLayout] = None,
                          ) -> Optional[PackedMaxSumGraph]:
    """Compile a MIXED-arity (1/2/3/4) graph into the lane-packed
    layout (ROADMAP §2a / VERDICT r4 item 7 — SECP model factors, n-ary
    rule tables).  Column classes are exact per-arity slot-count tuples
    (c1, c2, c3, c4); each bucket's slots are grouped by arity so the
    kernel applies the right update on aligned lane ranges; the third
    endpoint of ternary factors rides a SECOND Clos permutation, the
    fourth endpoint of quaternary factors a THIRD.

    Hubs (total degree > _MAX_SLOT_CLASS — VERDICT r4 item 4): a hub is
    split into m = ceil(deg/96) sub-columns, each holding the quantized
    per-arity shares ceil(deg_a/m); the group lives contiguously inside
    one 128-lane bin and is combined with the same suffix-doubling
    gathers as the binary packer (the hub machinery is arity-agnostic —
    it operates on columns).

    ``layout`` forces a pre-built :class:`MixedLayout` (the sharded
    packer builds one from max-per-shard degrees so every shard's pack
    shares the statics); when forced, sections the layout reserves for
    an arity this subgraph lacks still get their plan/cost arrays
    (identity routing, zero rows) so the traced structure stays
    invariant across shards.

    Arity-4 factors (SECP models with 3 lights — VERDICT r4's last
    capability gap) ride a THIRD Clos permutation; their D^3-block cost
    slabs are stored NARROW (quaternary section lanes only, 8-row-
    aligned blocks) because a full-width D^4-row array would be ~41MB
    even for a tiny graph.

    Returns None out of scope: arity > 4, D > 5 (the ternary/quaternary
    slab arrays grow as D^3/D^4), a hub beyond _MAX_SLOT_CLASS*128
    total edges, too many distinct classes, edges that don't fit a
    forced layout, or VMEM.
    """
    by_arity = {b.arity: b for b in t.buckets if b.n_factors > 0}
    if layout is None:
        if not by_arity:
            return None
    if any(a not in (1, 2, 3, 4) for a in by_arity):
        return None
    V, D = t.n_vars, t.max_domain_size
    has4 = 4 in by_arity or (
        layout is not None and bool((layout.keys[:, 3] > 0).any())
    )
    # quaternary presence forces the ternary structures too (zero rows
    # when no ternary factors exist): plan2 routes the second sibling
    # for BOTH arities, and keeping cost3/am3 alongside keeps the
    # operand contract (_mixed_operands) a simple chain of presences
    has3 = has4 or 3 in by_arity or (
        layout is not None and bool((layout.keys[:, 2] > 0).any())
    )
    if has3 and D > 5:
        return None
    if D > 8:
        return None

    # per-arity endpoint lists and per-var degrees
    ends = {
        a: np.asarray(b.var_idx).T.ravel()  # e = p*F + f ordering
        for a, b in by_arity.items()
    }
    deg_a = {
        a: np.bincount(e, minlength=V) for a, e in ends.items()
    }
    zero = np.zeros(V, dtype=np.int64)
    S = _MAX_SLOT_CLASS
    if layout is None:
        deg = sum(deg_a.values())
        if int(deg.max(initial=0)) > S * _LANES:
            return None  # a hub beyond ~12k edges: generic engine
        hub_of = deg > S
        hub_vars = np.flatnonzero(hub_of)
        hub_m = np.zeros(V, dtype=np.int64)
        for v in hub_vars:
            hub_m[v] = int(np.ceil(deg[v] / S))

        # class triples, each component quantized up a short ladder so
        # the product space stays small (a variable pads each arity
        # section to its quantized count with zero-masked dummy slots).
        # Vectorized: a per-variable python loop here would be O(V^2)
        # with the zeros default, and this path also runs as the
        # FALLBACK for large binary graphs that the binary packer
        # rejects.  A hub's key is the quantized triple of its per-arity
        # sub-column shares.
        share = np.maximum(hub_m, 1)
        keys = np.stack([
            _quantize_up(-(-deg_a.get(a, zero) // share))  # ceil(deg/m)
            for a in (1, 2, 3, 4)
        ], axis=1)  # [V, 4]
        # merge fragmented classes until both the class count and the
        # Clos A ≤ 8 slot budget fit (power-law degree tails with
        # ternary presence fork a fresh 128-column block per triple
        # otherwise)
        rep = _merge_mixed_classes(keys, hub_m, 2 * _MAX_BUCKETS,
                                   8 * _TILE)
        if rep is None:
            return None
        keys = np.array([rep[tuple(k)] for k in keys.tolist()],
                        dtype=np.int64)
        layout = _mixed_layout(keys, hub_of, hub_m)
        if layout is None:
            return None
    else:
        # defensive: this subgraph's per-arity degrees must fit the
        # forced per-arity shares
        share = np.maximum(layout.hub_m, 1)
        for a in (1, 2, 3, 4):
            if (-(-deg_a.get(a, zero) // share)
                    > layout.keys[:, a - 1]).any():
                return None

    keys = layout.keys
    hub_m = layout.hub_m
    var_pcol = layout.var_pcol
    col_var = layout.col_var
    with_slots = layout.with_slots
    buckets_arity = layout.buckets_arity
    group_heads = layout.group_heads
    max_m = layout.max_m
    Vp, N = layout.Vp, layout.N
    col_soff, col_nvp = layout.col_soff, layout.col_nvp
    col_voff, col_base = layout.col_voff, layout.col_base

    # slot per edge endpoint, per arity: rank within (var, arity).
    # Hub edges spill into sub-column rank // share at local rank
    # rank % share (share = the quantized per-arity sub-class; ≥ deg_a
    # for non-hubs, so their sub_j is always 0)
    slot_of = {}
    for a, e in ends.items():
        order = np.argsort(e, kind="stable")
        rank = np.empty(len(e), dtype=np.int64)
        start = np.concatenate([[0], np.cumsum(deg_a[a])[:-1]])
        rank[order] = np.arange(len(e)) - start[e[order]]
        split = np.maximum(keys[:, a - 1], 1)[e]
        sub_j = rank // split
        k_loc = rank - sub_j * split
        col = var_pcol[e] + sub_j
        k = col_base[a][col] + k_loc
        slot_of[a] = col_soff[col] + k * col_nvp[col] + (
            col - col_voff[col])

    # routing permutations: plan = first sibling, plan2 = second,
    # plan3 = third (quaternary factors only)
    A = N // _TILE
    perm1 = np.arange(N, dtype=np.int64)
    perm2 = np.arange(N, dtype=np.int64)
    perm3 = np.arange(N, dtype=np.int64)
    if 2 in by_arity:
        F2 = by_arity[2].n_factors
        s2 = slot_of[2]
        perm1[s2[:F2]] = s2[F2:]
        perm1[s2[F2:]] = s2[:F2]
    if 3 in by_arity:
        F3 = by_arity[3].n_factors
        s3 = slot_of[3]
        for p in range(3):
            mine = s3[p * F3: (p + 1) * F3]
            sib1 = ((p + 1) % 3)
            sib2 = ((p + 2) % 3)
            perm1[mine] = s3[sib1 * F3: (sib1 + 1) * F3]
            perm2[mine] = s3[sib2 * F3: (sib2 + 1) * F3]
    if 4 in by_arity:
        F4 = by_arity[4].n_factors
        s4 = slot_of[4]
        for p in range(4):
            mine = s4[p * F4: (p + 1) * F4]
            for step, perm in enumerate((perm1, perm2, perm3), start=1):
                sib = (p + step) % 4
                perm[mine] = s4[sib * F4: (sib + 1) * F4]
    plan = plan_permutation(perm1, A, _LANES, _LANES)
    # has3 (not `3 in by_arity`): a forced layout with ternary sections
    # keeps plan2 (identity here) even when THIS subgraph has no ternary
    # factors, so the traced structure is shard-invariant
    plan2 = plan_permutation(perm2, A, _LANES, _LANES) if has3 else None
    plan3 = plan_permutation(perm3, A, _LANES, _LANES) if has4 else None

    # cost arrays per arity
    cost1 = np.zeros((D, N), dtype=np.float32)
    if 1 in by_arity:
        T1 = np.asarray(by_arity[1].tensors)  # [F1, D]
        cost1[:, slot_of[1]] = T1.T
    cost_rows = np.zeros((D * D, N), dtype=np.float32)
    if 2 in by_arity:
        b2 = by_arity[2]
        F2 = b2.n_factors
        T2 = np.asarray(b2.tensors)
        e2 = np.arange(2 * F2)
        f_of, p_of = e2 % F2, e2 // F2
        for i in range(D):
            for j in range(D):
                vals = np.where(
                    p_of == 0, T2[f_of, i, j], T2[f_of, j, i])
                cost_rows[j * D + i, slot_of[2]] = vals
    cost3 = np.zeros((D * D * D, N), dtype=np.float32) if has3 else None
    if 3 in by_arity:
        b3 = by_arity[3]
        F3 = b3.n_factors
        T3 = np.asarray(b3.tensors)  # [F3, D, D, D]
        for p in range(3):
            mine = slot_of[3][p * F3: (p + 1) * F3]
            # move the target axis first, then sib1 ((p+1)%3), sib2
            axes = (0, 1 + p, 1 + (p + 1) % 3, 1 + (p + 2) % 3)
            Tp = np.transpose(T3, axes)  # [F3, i, j, k]
            for i in range(D):
                for j in range(D):
                    for k in range(D):
                        cost3[(j * D + k) * D + i, mine] = Tp[:, i, j, k]
    cost4 = None
    q4_sections: List[Tuple[int, int]] = []
    if has4:
        # 128-aligned lane ranges of the quaternary sections, and the
        # narrow (section-concatenated) column of each full-width slot
        narrow_of = np.full(N, -1, dtype=np.int64)
        pos = 0
        for (cls, nvp, _bv, soff), key in zip(with_slots, buckets_arity):
            c123 = key[0] + key[1] + key[2]
            if cls > c123:
                st, w = soff + c123 * nvp, (cls - c123) * nvp
                q4_sections.append((int(st), int(w)))
                narrow_of[st: st + w] = pos + np.arange(w)
                pos += w
        # each (j, k, m) block is padded to a full 8-row sublane tile
        # so every in-kernel slice starts at sublane offset 0 — Mosaic
        # rejects concatenating pieces with mismatched non-concat-dim
        # offsets (measured on v5e via _spread_q4), and D ≤ 5 here
        cost4 = np.zeros((D ** 3 * _Q4_STRIDE, max(pos, _LANES)),
                         dtype=np.float32)
    if 4 in by_arity:
        b4 = by_arity[4]
        F4 = b4.n_factors
        T4 = np.asarray(b4.tensors)  # [F4, D, D, D, D]
        for p in range(4):
            mine = narrow_of[slot_of[4][p * F4: (p + 1) * F4]]
            axes = (0, 1 + p, 1 + (p + 1) % 4, 1 + (p + 2) % 4,
                    1 + (p + 3) % 4)
            Tp = np.transpose(T4, axes)  # [F4, i, j, k, l]
            for i in range(D):
                for j in range(D):
                    for k in range(D):
                        for m in range(D):
                            row = ((j * D + k) * D + m) * _Q4_STRIDE + i
                            cost4[row, mine] = Tp[:, i, j, k, m]

    mask_np = np.zeros((D, Vp), dtype=np.float32)
    unary_np = np.zeros((D, Vp), dtype=np.float32)
    mask_np[:, var_pcol] = np.asarray(t.domain_mask).T
    unary_np[:, var_pcol] = np.asarray(t.unary_costs).T * mask_np[:, var_pcol]
    vmask_np = np.zeros((D, N), dtype=np.float32)
    for a, e in ends.items():
        vmask_np[:, slot_of[a]] = mask_np[:, var_pcol[e]]
    dcount = vmask_np.sum(axis=0, keepdims=True)
    inv_dcount = np.where(dcount > 0, 1.0 / np.maximum(dcount, 1.0), 0.0)

    # slot_of_edge for the BINARY bucket only (mgm2 pairing contract)
    soe = slot_of.get(2)

    am2 = np.zeros((1, N), dtype=np.float32)
    am3 = np.zeros((1, N), dtype=np.float32)
    am4 = np.zeros((1, N), dtype=np.float32) if has4 else None
    if 2 in slot_of:
        am2[0, slot_of[2]] = 1.0
    if 3 in slot_of:
        am3[0, slot_of[3]] = 1.0
    if 4 in slot_of:
        am4[0, slot_of[4]] = 1.0

    nsteps, steps_idx, steps_mask, head_idx = _hub_constants(
        group_heads, Vp, max_m
    )
    if cost4 is not None and not q4_sections:
        # every quaternary bucket must have contributed a lane range:
        # _gather_q4 concatenates q4_sections and IndexErrors on an
        # empty list deep inside the kernel trace — fail at pack time
        # with the actual invariant instead (ADVICE r5)
        raise AssertionError(
            "pack_mixed_for_pallas: cost4_rows is set but no "
            "q4_sections were collected — a quaternary bucket packed "
            "without its section lane range (packer invariant broken)"
        )
    pg = PackedMaxSumGraph(
        D=D, n_vars=V, Vp=Vp, N=N, plan=plan,
        buckets=tuple(with_slots),
        cost_rows=jnp.asarray(cost_rows),
        unary_p=jnp.asarray(unary_np),
        mask_p=jnp.asarray(mask_np),
        vmask=jnp.asarray(vmask_np),
        inv_dcount=jnp.asarray(inv_dcount.astype(np.float32)),
        var_order=jnp.asarray(var_pcol.astype(np.int32)),
        col_var=col_var,
        slot_of_edge=soe,
        mixed=True,
        buckets_arity=tuple(buckets_arity),
        plan2=plan2,
        cost1_rows=jnp.asarray(cost1),
        cost3_rows=jnp.asarray(cost3) if cost3 is not None else None,
        arity_mask2=jnp.asarray(am2),
        arity_mask3=jnp.asarray(am3),
        plan3=plan3,
        cost4_rows=jnp.asarray(cost4) if cost4 is not None else None,
        arity_mask4=jnp.asarray(am4) if am4 is not None else None,
        q4_sections=tuple(q4_sections),
        hub_nsteps=nsteps,
        hub_steps_idx=steps_idx,
        hub_steps_mask=steps_mask,
        hub_head_idx=head_idx,
    )
    # extra working set over the binary estimate: the ternary slab
    # array (D^3 rows), the unary rows, the two arity masks, plan2's 5
    # index arrays, and ~2 [D, N] temporaries of the second permutation
    # (same again, one power of D bigger, for the quaternary slabs)
    extra = D * N + 2 * N
    if cost3 is not None:
        extra += D * D * D * N + 5 * N + 2 * D * N
    if cost4 is not None:
        M4 = cost4.shape[1]
        extra += D ** 3 * _Q4_STRIDE * M4 + 6 * N + 2 * D * N + 3 * D * M4
    if 4 * extra + pg.vmem_bytes > _VMEM_BUDGET:
        return None
    return pg


# ---------------------------------------------------------------------------
# hub cross-column combine (traced; no-ops when the graph has no hubs)
# ---------------------------------------------------------------------------


def _hub_operands(pg: PackedMaxSumGraph) -> Tuple[jnp.ndarray, ...]:
    """Extra kernel operands for hub graphs (empty tuple otherwise)."""
    if pg.hub_nsteps == 0:
        return ()
    return (pg.hub_steps_idx, pg.hub_steps_mask, pg.hub_head_idx)


def _mixed_operands(pg: PackedMaxSumGraph) -> Tuple[jnp.ndarray, ...]:
    """Extra kernel operands for mixed-arity graphs: the unary cost
    rows, then (arity ≥ 3 only) the ternary slab array and the second
    permutation's 5 index arrays, then (arity-4 only) the quaternary
    slab array, the third permutation's 5 index arrays and the 4-ary
    mask.  THE operand-order contract — every kernel parses it back
    with :func:`_parse_mixed_refs`."""
    if not pg.mixed:
        return ()
    ops = [pg.cost1_rows, pg.arity_mask2, pg.arity_mask3]
    if pg.cost3_rows is not None:
        ops.append(pg.cost3_rows)
        ops.extend(_plan_consts(pg.plan2))
    if pg.cost4_rows is not None:
        ops.append(pg.cost4_rows)
        ops.extend(_plan_consts(pg.plan3))
        ops.append(pg.arity_mask4)
    return tuple(ops)


def _parse_mixed_refs(pg: PackedMaxSumGraph, rest):
    """(mixed_ops, remaining rest) from kernel ref list — inverse of
    :func:`_mixed_operands`.  The bundle appends quaternary entries
    AFTER the original 5, so positional reads of [0..4] stay valid."""
    if not pg.mixed:
        return None, rest
    cost1, am2, am3 = rest[0][:], rest[1][:], rest[2][:]
    rest = rest[3:]
    cost3 = consts2 = None
    if pg.cost3_rows is not None:
        cost3 = rest[0][:]
        consts2 = tuple(r[:] for r in rest[1: 6])
        rest = rest[6:]
    cost4 = consts3 = am4 = None
    if pg.cost4_rows is not None:
        cost4 = rest[0][:]
        consts3 = tuple(r[:] for r in rest[1: 6])
        am4 = rest[6][:]
        rest = rest[7:]
    return (cost1, cost3, consts2, am2, am3, cost4, consts3, am4), rest


def _hub_gather(arr, idx, R: int, rows: int):
    """Within-vreg lane gather of [R, rows*128] by per-bin indices
    idx [rows, 128] (same Mosaic-supported pattern as the Clos stages)."""
    vi = arr.reshape(R * rows, _LANES)
    ii = jnp.broadcast_to(
        idx.reshape(1, rows, _LANES), (R, rows, _LANES)
    ).reshape(R * rows, _LANES)
    return jnp.take_along_axis(vi, ii, axis=1).reshape(R, rows * _LANES)


def _hub_sum(pg: PackedMaxSumGraph, arr, R: int, hub):
    """Replace every hub group's columns with the full-group SUM (suffix
    doubling with masked adds, then spread from the group head); identity
    on all other columns.  ``hub`` is the traced operand triple or None."""
    if hub is None:
        return arr
    steps_idx, steps_mask, head_idx = hub
    rows = pg.Vp // _LANES
    for s in range(pg.hub_nsteps):
        got = _hub_gather(arr, steps_idx[s * rows: (s + 1) * rows], R, rows)
        arr = arr + got * steps_mask[s: s + 1, :]
    return _hub_gather(arr, head_idx, R, rows)


def _hub_op(pg: PackedMaxSumGraph, arr, R: int, hub, op):
    """Full-group combine under an idempotent ``op`` (max/min): clamped
    partners gather their own lane, so op(a, a) = a needs no mask."""
    if hub is None:
        return arr
    steps_idx, _, head_idx = hub
    rows = pg.Vp // _LANES
    for s in range(pg.hub_nsteps):
        got = _hub_gather(arr, steps_idx[s * rows: (s + 1) * rows], R, rows)
        arr = op(arr, got)
    return _hub_gather(arr, head_idx, R, rows)


def _hub_spread(pg: PackedMaxSumGraph, arr, R: int, hub):
    """Copy each hub group's head-column value to all its member columns
    (identity elsewhere) — used to give member slots the hub's value."""
    if hub is None:
        return arr
    return _hub_gather(arr, hub[2], R, pg.Vp // _LANES)


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    """Default to interpret mode when the actual devices are not TPUs, so
    solvers whose engine selection chose the packed path (e.g. in tests that
    monkeypatch the backend) still execute correctly on CPU."""
    if interpret is not None:
        return interpret
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover - device init failure
        return True


def packed_init_state(pg: PackedMaxSumGraph
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    z = jnp.zeros((pg.D, pg.N), dtype=jnp.float32)
    return z, z


def _gather_q4(pg: PackedMaxSumGraph, arr):
    """[R, N] → [R, M4]: concatenate the (128-aligned) quaternary
    section lane ranges — static slicing, same pattern as the bucket
    reduce."""
    parts = [arr[:, st: st + w] for st, w in pg.q4_sections]
    out = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    M4 = pg.cost4_rows.shape[1]
    if out.shape[1] < M4:  # packer pads M4 up to one lane tile
        out = jnp.concatenate(
            [out, jnp.zeros((arr.shape[0], M4 - out.shape[1]),
                            out.dtype)], axis=1)
    return out


def _spread_q4(pg: PackedMaxSumGraph, narrow, R: int):
    """[R, M4] → [R, N]: place each quaternary section's block back at
    its full-width lane range, zeros elsewhere."""
    parts = []
    at = 0
    pos = 0
    for st, w in pg.q4_sections:
        if at < st:
            parts.append(jnp.zeros((R, st - at), narrow.dtype))
        parts.append(narrow[:, pos: pos + w])
        pos += w
        at = st + w
    if at < pg.N:
        parts.append(jnp.zeros((R, pg.N - at), narrow.dtype))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


def _mixed_contrib(pg: PackedMaxSumGraph, xo1, xo2, cost, cost1, cost3,
                   am2, am3, xo3=None, cost4=None, am4=None):
    """Per-slot cost row given the sibling endpoints' current values
    (mixed-arity local tables): binary select by xo1, ternary by
    (xo1, xo2), quaternary by (xo1, xo2, xo3) — assembled FULL-width
    with the static arity masks — per-range lane slicing trips Mosaic
    layout inference (a broadcast of a lane-sliced row is rejected)."""
    D = pg.D
    cb = cost[0: D, :]
    for j in range(1, D):
        cb = jnp.where(xo1 == float(j), cost[j * D: (j + 1) * D, :], cb)
    out = jnp.where(am2 > 0, cb, cost1)
    if cost3 is not None:
        ct = cost3[0: D, :]
        for j in range(D):
            for k in range(D):
                if j == 0 and k == 0:
                    continue
                row = (j * D + k) * D
                ct = jnp.where(
                    (xo1 == float(j)) & (xo2 == float(k)),
                    cost3[row: row + D, :], ct,
                )
        out = jnp.where(am3 > 0, ct, out)
    if cost4 is not None:
        # narrow compute on the quaternary section lanes only (the
        # full-width D^4 slab would be tens of MB — see cost4_rows)
        n1 = _gather_q4(pg, xo1)
        n2 = _gather_q4(pg, xo2)
        n3 = _gather_q4(pg, xo3)
        cq = cost4[0: D, :]
        for j in range(D):
            for k in range(D):
                for m in range(D):
                    if j == 0 and k == 0 and m == 0:
                        continue
                    row = ((j * D + k) * D + m) * _Q4_STRIDE
                    cq = jnp.where(
                        (n1 == float(j)) & (n2 == float(k))
                        & (n3 == float(m)),
                        cost4[row: row + D, :], cq,
                    )
        out = jnp.where(am4 > 0, _spread_q4(pg, cq, D), out)
    return out


def _contrib_for_values(pg: PackedMaxSumGraph, xs, xo, mixed, cost=None,
                        slabs=None):
    """Per-slot cost row given each slot's sibling endpoints' current
    values — the table/exclusive-cost building block shared by the
    local-tables, MGM/DSA and MGM-2 kernels.  ``xs`` are the expanded
    own values (needed for the second permute), ``xo`` the first-sibling
    values already routed by ``pg.plan``.  Mixed layouts (``mixed`` =
    the parsed 8-tuple of :func:`_parse_mixed_refs` + ``cost``
    [D*D, N]) run the arity-masked assembly with a second permute for
    ternary slots and a third for quaternary; all-binary layouts select
    from the D ``slabs``."""
    if mixed is not None:
        cost1, cost3, consts2, am2, am3, cost4, consts3, am4 = mixed
        R = xs.shape[0]
        xo2 = (
            _permute_in_kernel(xs, pg.plan2, R, consts2)
            if consts2 is not None else xo
        )
        xo3 = (
            _permute_in_kernel(xs, pg.plan3, R, consts3)
            if consts3 is not None else xo
        )
        return _mixed_contrib(pg, xo, xo2, cost, cost1, cost3, am2, am3,
                              xo3=xo3, cost4=cost4, am4=am4)
    contrib = slabs[0]
    for j in range(1, pg.D):
        contrib = jnp.where(xo == float(j), slabs[j], contrib)
    return contrib


def _mixed_r_new(pg: PackedMaxSumGraph, qm1, qm2, cost, cost1, cost3,
                 am2, am3, qm3=None, cost4=None, am4=None):
    """factor→var messages for the mixed-arity layout: unary slots take
    their constant cost rows, binary slots the D-slab min over the
    routed sibling, ternary slots the D²-slab min over BOTH routed
    siblings, quaternary slots the D³-slab min over all THREE — all
    computed FULL-width and combined with the static arity masks (see
    :func:`_mixed_contrib` for the layout rationale)."""
    D = pg.D
    rb = cost[0: D, :] + qm1[0: 1, :]
    for j in range(1, D):
        rb = jnp.minimum(
            rb, cost[j * D: (j + 1) * D, :] + qm1[j: j + 1, :]
        )
    out = jnp.where(am2 > 0, rb, cost1)
    if cost3 is not None:
        rt = None
        for j in range(D):
            for k in range(D):
                row = (j * D + k) * D
                cand = (cost3[row: row + D, :]
                        + qm1[j: j + 1, :] + qm2[k: k + 1, :])
                rt = cand if rt is None else jnp.minimum(rt, cand)
        out = jnp.where(am3 > 0, rt, out)
    if cost4 is not None:
        # narrow compute on the quaternary section lanes only
        n1 = _gather_q4(pg, qm1)
        n2 = _gather_q4(pg, qm2)
        n3 = _gather_q4(pg, qm3)
        rq = None
        for j in range(D):
            for k in range(D):
                # hoist the (j, k) part of the sibling sum out of the
                # inner loop: D² adds instead of D³
                qjk = n1[j: j + 1, :] + n2[k: k + 1, :]
                for m in range(D):
                    row = ((j * D + k) * D + m) * _Q4_STRIDE
                    cand = (cost4[row: row + D, :]
                            + qjk + n3[m: m + 1, :])
                    rq = cand if rq is None else jnp.minimum(rq, cand)
        out = jnp.where(am4 > 0, _spread_q4(pg, rq, D), out)
    return out


def _cycle_body(pg: PackedMaxSumGraph, damping: float, q, r, cost, unary,
                vmask, invd, plan_consts, hub=None, mixed_ops=None):
    """Traced cycle math shared by the pallas kernel and interpret mode."""
    D, N = pg.D, pg.N
    qm = _permute_in_kernel(q, pg.plan, D, plan_consts)
    if mixed_ops is not None:
        (cost1, cost3, consts2, am2, am3, cost4, consts3, am4) = mixed_ops
        qm2 = (
            _permute_in_kernel(q, pg.plan2, D, consts2)
            if consts2 is not None else qm
        )
        qm3 = (
            _permute_in_kernel(q, pg.plan3, D, consts3)
            if consts3 is not None else qm
        )
        r_new = _mixed_r_new(pg, qm, qm2, cost, cost1, cost3, am2, am3,
                             qm3=qm3, cost4=cost4, am4=am4)
    else:
        # factor→var: r'[i] = min_j cost[j*D+i] + qm[j] — full-sublane
        # [D, N] slabs (cost is other-value-major, see pack_for_pallas)
        r_new = cost[0: D, :] + qm[0: 1, :]
        for j in range(1, D):
            r_new = jnp.minimum(
                r_new, cost[j * D: (j + 1) * D, :] + qm[j: j + 1, :]
            )
    r_new = r_new * vmask
    if damping:
        r_new = damping * r + (1.0 - damping) * r_new
    # var side: beliefs per padded column
    bparts = []
    voff_expect = 0
    for cls, nvp, voff, soff in pg.buckets:
        while voff_expect < voff:  # zero-degree bucket gap
            bparts.append(jnp.zeros((D, _LANES), dtype=r_new.dtype))
            voff_expect += _LANES
        acc = r_new[:, soff: soff + nvp]
        for k in range(1, cls):
            acc = acc + r_new[:, soff + k * nvp: soff + (k + 1) * nvp]
        bparts.append(acc)
        voff_expect += nvp
    while voff_expect < pg.Vp:
        bparts.append(jnp.zeros((D, _LANES), dtype=r_new.dtype))
        voff_expect += _LANES
    beliefs = unary + (
        bparts[0] if len(bparts) == 1 else jnp.concatenate(bparts, axis=1)
    )
    # hub groups: sum the per-sub-column partial beliefs (head's unary
    # counted once — member columns carry zero unary) and give every
    # member the combined belief for the expansion below
    beliefs = _hub_sum(pg, beliefs, D, hub)
    # outgoing q' = beliefs(var) - r', normalized to zero masked mean.
    # expansion = lane-aligned repeats of each bucket's belief block (plain
    # VMEM copies; broadcast+reshape would force a Mosaic relayout)
    qparts = []
    for cls, nvp, voff, soff in pg.buckets:
        bb = beliefs[:, voff: voff + nvp]
        qparts.extend([bb] * cls)
    expanded = jnp.concatenate(qparts, axis=1) if qparts else beliefs
    if expanded.shape[1] < N:
        expanded = jnp.concatenate(
            [expanded,
             jnp.zeros((D, N - expanded.shape[1]), dtype=expanded.dtype)],
            axis=1,
        )
    q_new = expanded - r_new
    mean = (q_new * vmask).sum(axis=0, keepdims=True) * invd
    q_new = (q_new - mean) * vmask
    return q_new, r_new, beliefs


def packed_cycle(
    pg: PackedMaxSumGraph,
    q: jnp.ndarray,
    r: jnp.ndarray,
    damping: float = 0.0,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One fused MaxSum cycle.  Returns (q', r', beliefs [D,Vp], values [V])
    with values in ORIGINAL variable order."""
    return packed_cycles(pg, q, r, 1, damping=damping, interpret=interpret)


def packed_cycles(
    pg: PackedMaxSumGraph,
    q: jnp.ndarray,
    r: jnp.ndarray,
    n_cycles: int,
    damping: float = 0.0,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``n_cycles`` fused MaxSum cycles in ONE pallas kernel.

    Amortizes per-kernel launch/dispatch cost: cycles are statically
    UNROLLED inside the kernel (a fori_loop carry would double-buffer
    (q, r) and blow the ~16MB VMEM scoped-allocation limit at benchmark
    sizes), so kernel size grows linearly with ``n_cycles`` — keep it
    small (≤ ~16); measured sweet spot ~5 on the 10k-var bench.  Returns
    (q', r', beliefs, values) after the last cycle — intermediate
    beliefs are not materialized, so use :func:`packed_cycle` when
    per-cycle values are needed.
    """
    if not 1 <= n_cycles <= 64:
        raise ValueError(
            f"packed_cycles unrolls in-kernel: n_cycles must be in "
            f"[1, 64], got {n_cycles}"
        )
    interpret = _resolve_interpret(interpret)
    D, N, Vp = pg.D, pg.N, pg.Vp

    hub_ops = _hub_operands(pg)
    mixed_ops_in = _mixed_operands(pg)

    def kern(q_ref, r_ref, cost_ref, unary_ref, vmask_ref,
             invd_ref, c_r1, c_g1, c_ss, c_g2, c_r2, *rest):
        if hub_ops:
            hub = (rest[0][:], rest[1][:], rest[2][:])
            rest = rest[3:]
        else:
            hub = None
        mixed, rest = _parse_mixed_refs(pg, rest)
        q_out, r_out, b_out = rest
        cost = cost_ref[:]
        unary = unary_ref[:]
        vmask = vmask_ref[:]
        invd = invd_ref[:]
        consts = (c_r1[:], c_g1[:], c_ss[:], c_g2[:], c_r2[:])

        # static unroll: a fori_loop carry would double-buffer (q, r) and
        # push the kernel over the ~16MB VMEM scoped-allocation limit at
        # benchmark sizes; unrolled cycles let Mosaic reuse buffers
        qn, rn = q_ref[:], r_ref[:]
        bel = None
        for _ in range(n_cycles):
            qn, rn, bel = _cycle_body(
                pg, damping, qn, rn, cost, unary, vmask, invd, consts,
                hub=hub, mixed_ops=mixed,
            )
        q_out[:] = qn
        r_out[:] = rn
        b_out[:] = bel

    q_new, r_new, beliefs = pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((D, N), jnp.float32),
            jax.ShapeDtypeStruct((D, N), jnp.float32),
            jax.ShapeDtypeStruct((D, Vp), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * (
            11 + len(hub_ops) + len(mixed_ops_in)),
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 3),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(q, r, pg.cost_rows, pg.unary_p, pg.vmask, pg.inv_dcount,
      *_plan_consts(pg.plan), *hub_ops, *mixed_ops_in)
    values = packed_values(pg, beliefs)
    return q_new, r_new, beliefs, values


def packed_values(pg: PackedMaxSumGraph, beliefs: jnp.ndarray) -> jnp.ndarray:
    """Masked argmin per padded column, mapped to original variable order."""
    big = jnp.where(pg.mask_p > 0, beliefs, PAD_COST)
    pvalues = jnp.argmin(big, axis=0).astype(jnp.int32)
    return pvalues[pg.var_order]


def packed_local_tables(pg: PackedMaxSumGraph, x: jnp.ndarray,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Local cost tables for the local-search family, lane-packed.

    Same result as ops.compile.local_cost_tables on the source tensors
    (out[v, d] = unary[v, d] + Σ_{factors containing v} cost(v=d | others
    at x), PAD_COST at invalid slots), computed in one pallas kernel:
    expand current values to slots, Clos-route each slot its factor's
    other-endpoint value, select the matching cost row per slot, and
    bucket-sum slots per variable — no XLA gather/segment ops.

    x: [V] int32 value indices (original variable order) → [V, D] float32.
    """
    interpret = _resolve_interpret(interpret)
    D, N, Vp = pg.D, pg.N, pg.Vp
    # current value per padded column, as f32 broadcast over all D rows —
    # keeps every in-kernel op on the same [D, *] shapes as _cycle_body
    # (Mosaic rejects some 1-sublane-row layouts)
    x_p = jnp.zeros((D, Vp), jnp.float32).at[:, pg.var_order].set(
        x.astype(jnp.float32)[None, :]
    )

    hub_ops = _hub_operands(pg)
    mixed_ops_in = _mixed_operands(pg)

    def kern(xp_ref, cost_ref, unary_ref, c_r1, c_g1, c_ss, c_g2, c_r2,
             *rest):
        if hub_ops:
            hub = (rest[0][:], rest[1][:], rest[2][:])
            rest = rest[3:]
        else:
            hub = None
        mixed, rest = _parse_mixed_refs(pg, rest)
        (t_out,) = rest
        # hub members carry the hub's current value for their slots
        xp = _hub_spread(pg, xp_ref[:], D, hub)
        cost = cost_ref[:]
        # expand values to slots (aligned repeats, as in _cycle_body)
        parts = []
        for cls, nvp, voff, soff in pg.buckets:
            parts.extend([xp[:, voff: voff + nvp]] * cls)
        xs = jnp.concatenate(parts, axis=1) if parts else xp
        if xs.shape[1] < N:
            xs = jnp.concatenate(
                [xs, jnp.zeros((D, N - xs.shape[1]), xs.dtype)], axis=1
            )
        consts1 = (c_r1[:], c_g1[:], c_ss[:], c_g2[:], c_r2[:])
        xo = _permute_in_kernel(xs, pg.plan, D, consts1)
        contrib = _contrib_for_values(
            pg, xs, xo, mixed, cost=cost,
            slabs=None if mixed is not None
            else [cost[j * D: (j + 1) * D, :] for j in range(D)],
        )
        # bucket-sum slots per variable (as in _cycle_body's beliefs)
        bparts = []
        voff_expect = 0
        for cls, nvp, voff, soff in pg.buckets:
            while voff_expect < voff:
                bparts.append(jnp.zeros((D, _LANES), dtype=contrib.dtype))
                voff_expect += _LANES
            acc = contrib[:, soff: soff + nvp]
            for k in range(1, cls):
                acc = acc + contrib[:, soff + k * nvp: soff + (k + 1) * nvp]
            bparts.append(acc)
            voff_expect += nvp
        while voff_expect < Vp:
            bparts.append(jnp.zeros((D, _LANES), dtype=contrib.dtype))
            voff_expect += _LANES
        tables = unary_ref[:] + (
            bparts[0] if len(bparts) == 1 else jnp.concatenate(bparts, axis=1)
        )
        t_out[:] = _hub_sum(pg, tables, D, hub)

    tables_p = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((D, Vp), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * (
            8 + len(hub_ops) + len(mixed_ops_in)),
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(x_p, pg.cost_rows, pg.unary_p, *_plan_consts(pg.plan), *hub_ops,
      *mixed_ops_in)
    tables = tables_p[:, pg.var_order].T  # [V, D] original order
    mask = pg.mask_p[:, pg.var_order].T
    return jnp.where(mask > 0, tables, PAD_COST)
