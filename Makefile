# Test/check targets (reference twin: pyDcop Makefile:1-21)

.PHONY: test unit api cli doctest all-tests bench bench-probe faults \
	bench-batch batch-smoke bench-harness bench-sharded bench-serve \
	serve-smoke chaos-smoke bench-churn churn-smoke bench-dpop \
	dpop-smoke bench-auto portfolio-smoke bench-fleet fleet-smoke \
	bench-twin twin-smoke bench-r06 analyze bench-search search-smoke \
	bench-r08 bench-pfleet pfleet-smoke bench-structured \
	structured-smoke bench-r09 bench-memo memo-smoke bench-r10 \
	precision-smoke bench-precision bench-r11

test: all-tests

unit:
	python -m pytest tests/unit -q

api:
	python -m pytest tests/api -q

cli:
	python -m pytest tests/cli -q

doctest:
	python -m pytest --doctest-modules pydcop_tpu -q

all-tests:
	python -m pytest tests/ -q
	python -m pytest --doctest-modules pydcop_tpu -q

bench:
	python bench.py

# the static-analysis guard tier (ISSUE 13): audit every registered
# engine×mode cycle program against its DECLARED ProgramBudget
# (collectives per cycle, payload bytes, host callbacks, dtype tier,
# embedded constants, donation — docs/analysis.rst), then lint the
# tree for tracer-hostile calls in cycle/chunk code and lock-
# discipline races in the serving tier.  Exits nonzero on ANY
# finding; fast enough to run next to the smokes (seconds, no
# solves — the registry audits SHAPE on tiny instances).  Runtime
# recorded in BENCHREF.md "Program auditor".
analyze:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m pydcop_tpu analyze program
	JAX_PLATFORMS=cpu python -m pydcop_tpu analyze lint

# calibration probe + sharded local-search micro-bench only: a
# minutes-long spot check of the lane-packed move-rule rate with its
# drift anchor (docs/performance.rst "Drift-normalized benchmarking")
bench-probe:
	python bench.py --only probe

# batched multi-instance throughput only: instances/sec at B in
# {1, 8, 32} on the graph-coloring family with compile-cache counters
# (docs/performance.rst "Batched solving")
bench-batch:
	python bench.py --only batch

# sharded benches only: the 8-device CPU-mesh compact-vs-dense maxsum
# pair on the partitioned ring-lattice instance (+ packed canary) —
# docs/performance.rst "Boundary-compacted sharding"
bench-sharded:
	python bench.py --only sharded

# sharded exact DPOP (ISSUE 9): the separator-tiled sweep on the
# 8-device CPU mesh against an instance whose largest joint util table
# exceeds the simulated per-device budget — bitmatch flag, bytes
# shipped and pruning counters in the JSON (docs/performance.rst
# "Sharded exact inference", BENCHREF.md "Sharded exact DPOP")
bench-dpop:
	python bench.py --only dpop-sharded

# anytime exact search (ISSUE 15): optimality-gap-vs-time curve +
# node throughput on two high-width instances that full DPOP refuses
# under budget (typed UtilTableTooLarge pinned in the leg), drift-
# normalized (docs/performance.rst "Frontier-batched exact search",
# BENCHREF.md "Anytime exact search")
bench-search:
	python bench.py --only search

# the anytime exact search end-to-end through the CLI: the kill-9
# checkpoint/resume scenario (SIGKILL a checkpointing
# `solve --anytime-exact` mid-search, `--resume` lands on the exact
# frontier state and still proves the clean optimum); slow-marked, so
# it does NOT run in tier-1 — run it next to dpop-smoke whenever
# touching pydcop_tpu/search/.  The fast (not-slow) search CLI tests
# ride tier-1 via tests/cli.
search-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_search_cli.py tests/unit/test_search.py \
		-q

# the r07 legs + the anytime exact-search and process-fleet legs in
# one run with a machine-readable BENCH_r08.json snapshot
bench-r08:
	python bench.py --only r08 --snapshot BENCH_r08.json

# table-free structured constraints (ISSUE 17): dense-vs-structured
# byte ratios at table-fitting arity with evaluation/frontier parity
# pinned, plus the 100-arity end-to-end headline no table path can
# represent (docs/performance.rst "Table-free constraints",
# BENCHREF.md "Table-free constraints")
bench-structured:
	python bench.py --only structured

# the 100-arity window end-to-end through the CLI in seconds:
# `generate routing_structured` emits the parameter form (KBs, not a
# 4^100 table), maxsum runs table-free message kernels, the frontier
# engine returns a FEASIBLE anytime answer — run it whenever touching
# pydcop_tpu/dcop/structured.py or ops/structured_kernels.py
structured-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_structured_cli.py tests/unit/test_structured.py \
		-q

# the r08 legs + the table-free structured-constraints leg in one run
# with a machine-readable BENCH_r09.json snapshot (ISSUE 17 satellite)
bench-r09:
	python bench.py --only r09 --snapshot BENCH_r09.json

# cross-request solution cache smoke (ISSUE 18): serve a seeded
# duplicate trace twice through the real CLI — the second pass
# rehydrates the persisted cache and must hit; the slow leg SIGKILLs
# the service mid-trace and asserts `--resume` rehydrates the CRC'd
# entries with bit-identical answers.  Run it whenever touching
# pydcop_tpu/serve/memo.py or dcop/canonical.py
memo-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_memo_cli.py -q

# solution-cache bench only: hit taxonomy on a duplicate/variant/novel
# trace, warm-vs-cold p50/p99 (drift-normalized), the k-edit variant
# speedup pin and the per-algo never-worse booleans (docs/serving.rst
# "Solution cache and warm-start serving", BENCHREF.md "Solution
# cache")
bench-memo:
	python bench.py --only memo

# the r09 legs + the solution-cache leg in one run with a
# machine-readable BENCH_r10.json snapshot (ISSUE 18 satellite)
bench-r10:
	python bench.py --only r10 --snapshot BENCH_r10.json

# mixed-precision tier smoke (ISSUE 19): quantization round-trip /
# saturation properties, f32 bit-identity pins, bf16 statistical
# equivalence, typed tier refusals, checkpoint tier guard and the
# audited wire-byte cut of the bf16 sharded cells.  Run it whenever
# touching ops/precision.py, ops/compile.py or parallel/mesh.py
precision-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/unit/test_precision.py -q

# mixed-precision bench only: per-tier throughput + final cost for
# maxsum/mgm, the declared bf16 gate and the jaxpr-walked collective
# payload cut of the bf16 wire cells vs their f32 twins
# (docs/performance.rst "Mixed precision tiers")
bench-precision:
	python bench.py --only precision

# the r10 legs + the mixed-precision leg in one run with a
# machine-readable BENCH_r11.json snapshot (ISSUE 19 satellite)
bench-r11:
	python bench.py --only r11 --snapshot BENCH_r11.json

# fast sharded-DPOP smoke: the tiled-vs-single-device parity matrix,
# pruning property and mini-bucket bound-sandwich tests on the CPU
# backend — run it whenever touching the exact-inference engines
dpop-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/unit/test_dpop_shard.py tests/unit/test_dpop_mesh.py \
		-q -m 'not slow'

# harness sync-overhead spot check: blocking vs pipelined chunk
# dispatch on a convergence-bound solve (docs/performance.rst
# "Pipelined convergence")
bench-harness:
	python bench.py --only harness

# 2-bucket / 6-instance in-process sweep smoke on the CPU backend —
# the same scenario the tier-1 CLI test pins, runnable standalone
batch-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_batch_cli.py -q -m 'not slow'

# continuous-batching serve throughput: seeded Poisson arrivals over a
# mixed-shape family — the streaming service vs the naive
# sequential-per-job baseline, with p50/p99 latency and the arrival
# trace in the JSON (docs/serving.rst, BENCHREF.md "Serve throughput")
bench-serve:
	python bench.py --only serve

# short Poisson burst through the in-process solve service on the CPU
# backend: every job must complete with the standalone solve's exact
# cost (the tier-1 serve CLI scenario, runnable standalone); the
# long service soak/crash tests are slow-marked — see also
# chaos-smoke below for the fault-injected twin
serve-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_serve_cli.py -q -m 'not slow'

# replicated solve fleet (ISSUE 11): the PR 6 Poisson trace replayed
# against 1/2/4 replicas behind the signature router — jobs/s + p99
# scaling, bit-match vs standalone solves, and the kill_replica chaos
# pin with its recovery-time objective (docs/serving.rst "Fleet
# deployment and failover", BENCHREF.md "Fleet serve")
bench-fleet:
	python bench.py --only fleet

# process fleet (ISSUE 16): the fleet trace against 1/2/4 replica
# CHILD PROCESSES behind the CRC-framed socket journal — jobs/s + p99
# scaling, bit-match, the kill_process RTO, and the cold-join
# zero-compile pin (docs/serving.rst "Process fleet")
bench-pfleet:
	python bench.py --only pfleet

# the process-fleet chaos scenario end-to-end through the CLI: serve
# --processes with a fault-plan kill_process — a REAL kill -9 of a
# whole replica child mid-trace; every job completes bit-identically
# on the survivor with a finite RTO and the watchdog relaunches the
# slot.  Slow-marked, so it does NOT run in tier-1 — run it next to
# fleet-smoke whenever touching serve/procfleet.py, serve/wire.py or
# serve/artifacts.py.  The subprocess acceptance pins DO ride tier-1
# via tests/unit/test_procfleet.py.
pfleet-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_fleet_cli.py -q -m slow -k process

# the fleet failover scenario end-to-end through the CLI: start a
# 2-replica fleet, kill one replica mid-trace (fault-plan
# kill_replica — the thread-hosted kill -9), assert every job
# completes on the peer bit-identically with a finite RTO;
# slow-marked, so it does NOT run in tier-1 — run it next to
# serve-smoke/chaos-smoke whenever touching the fleet layer.  The
# fast (not-slow) fleet CLI tests ride tier-1 via tests/cli.
fleet-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_fleet_cli.py -q -m slow

# the seeded serve fault plan driven end-to-end through a real service
# process: raise_in_step / nan_lane / torn_journal_write / stall_tick,
# each exercising the supervised-scheduler + poison-quarantine
# machinery (docs/serving.rst "Failure model and overload behavior");
# slow-marked, so it does NOT run in tier-1 — run it next to
# serve-smoke whenever touching the serving layer
chaos-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_serve_cli.py -q -m slow -k chaos

# fault-tolerance suite only (docs/resilience.rst); tier-1 subset —
# the multi-process crash tests beyond ~30s are marked slow
faults:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/unit/test_faults.py tests/api/test_api_process_faults.py \
		-q -m 'not slow'

# warm-repair churn recovery: the seeded 50-mutation stream against a
# live 100k-var instance — warm in-place mutation (repair retraces MUST
# be 0) vs the cold repack + recompile baseline, time-to-recover-cost
# per mutation (docs/resilience.rst "Warm repair and agent churn",
# BENCHREF.md "Churn recovery")
bench-churn:
	python bench.py --only churn

# learned-portfolio held-out regret leg (ISSUE 10): train the cost
# model on seeded training families, then on a HELD-OUT suite compare
# `solve --auto` against every fixed single-config baseline in the
# grid — total drift-normalized time-to-target, mean top-1 regret vs
# the per-instance oracle and the predicted-vs-actual gap audit in
# the JSON (docs/portfolio.rst, BENCHREF.md "Portfolio auto-selection")
bench-auto:
	python bench.py --only auto

# tiny grid -> dataset sweep -> train -> `solve --auto` end to end on
# the CPU backend in under a minute: the portfolio CLI smoke (tier-1
# subset; run it whenever touching pydcop_tpu/portfolio/)
portfolio-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_portfolio_cli.py -q -m 'not slow'

# city-scale digital twin (ISSUE 12): the combined sustained scenario
# — Poisson deadline-tier traffic through the fleet + warm-repair
# churn + the combined chaos plan + --auto — scored by SLO attainment,
# ladder ON vs OFF on the same seeds; the headline is gold-tier
# attainment holding >= 99% under chaos with the ladder while the
# ladder-off arm measurably misses it, with bit-identity to standalone
# solves pinned (docs/scenarios.rst, BENCHREF.md "City twin")
bench-twin:
	python bench.py --only twin

# the serve/churn/dpop-sharded/auto/fleet/twin legs in one run with a
# machine-readable BENCH_r06.json snapshot — the consolidated perf
# record resuming past r05 (ROADMAP re-anchor note)
bench-r06:
	python bench.py --only r06 --snapshot BENCH_r06.json

# elastic device-fault tier (ISSUE 14): degraded-throughput curve
# 8→6→4 devices on the partitioned 2000-var instance, SDC detection
# latency with zero false positives on the clean legs, sentinel
# overhead vs sentinel-off (BENCHREF.md "Elastic mesh")
bench-elastic:
	python bench.py --only elastic

# the r06 legs + the elastic leg in one run with a machine-readable
# BENCH_r07.json snapshot (ISSUE 14 satellite)
bench-r07:
	python bench.py --only r07 --snapshot BENCH_r07.json

# the elastic device-fault tier end-to-end through the CLI: 8-device
# CPU mesh, two kill_device faults mid-solve through
# `solve --fault-plan`, the solve completes on 6 devices and the
# final assignment bit-matches the clean elastic run (exact-restore
# path); slow-marked, so it does NOT run in tier-1 — run it next to
# faults/chaos-smoke whenever touching parallel/elastic or the
# sentinels.  The fast (not-slow) elastic CLI tests ride tier-1 via
# tests/cli.
elastic-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_elastic_cli.py -q -m slow

# the small twin end-to-end through the CLI: 2 replicas, 3 tiers, 10
# mutations, 1 injected kill — finite RTO, zero gold deadline misses,
# ladder engaged-and-released; slow-marked, so it does NOT run in
# tier-1 — run it next to fleet-smoke/chaos-smoke whenever touching
# the scenario tier.  The fast (not-slow) twin CLI tests ride tier-1
# via tests/cli.
twin-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_twin_cli.py -q -m slow

# the seeded churn fault plan driven end-to-end through `run
# --warm-repair`: edit_factor / remove_agent_burst / add_agent_burst at
# phase boundaries, kill-9 mid-churn + --resume included; slow-marked,
# so it does NOT run in tier-1 — run it next to faults/chaos-smoke
# whenever touching the repair layer
churn-smoke:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/cli/test_churn_cli.py -q
