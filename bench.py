#!/usr/bin/env python
"""Benchmark driver.  Prints ONE JSON line:

  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": R,
   "extra": {...secondary metrics...}}

Primary metric (BASELINE.md): MaxSum message-passing iterations/sec on a
10k-variable / 30k-edge random graph-coloring instance, on the default
JAX backend (the TPU under the driver).

Secondary metrics in "extra" (all BASELINE.md / VERDICT round-2 asks):
  * dpop_tables_per_sec_10000var — DPOP UTIL+VALUE batched sweep on a
    10k-node random tree, D=10 (second primary in BASELINE.md).
  * mgm_cycles_per_sec_10000var / dsa_cycles_per_sec_10000var — local
    search family on the same 10k coloring instance.
  * sharded_maxsum_iters_per_sec_8dev — ShardedMaxSum on a virtual
    8-device CPU mesh (subprocess), regression canary for the mesh path.
  * stretch_* — North star: MaxSum convergence on 100k-var/300k-edge
    coloring; wall-clock to a stable assignment (target < 10 s).

vs_baseline for the primary compares against a freshly-measured
reference-equivalent python implementation of the same factor-update
math (pydcop/algorithms/maxsum.py:345-423 enumerates the neighbor-domain
cross product in python), measured on a factor subsample here and
extrapolated.  See BENCHREF.md for the honest end-to-end reference CLI
baseline (VERDICT item 10).
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time

import numpy as np


# --------------------------------------------------------------------------
# reference-equivalent python baselines (measured, not hardcoded)
# --------------------------------------------------------------------------

def python_reference_cycle_time(tensors, sample: int = 200) -> float:
    """Seconds per full MaxSum cycle for a python-loop implementation of
    the factor update (reference-equivalent math)."""
    b = max(tensors.buckets, key=lambda b: b.n_factors)
    t_np = np.asarray(b.tensors)
    n = min(sample, b.n_factors)
    D = tensors.max_domain_size
    q = np.zeros((b.arity, D), dtype=np.float32)
    t0 = time.perf_counter()
    for f in range(n):
        cost = t_np[f]
        for p in range(b.arity):
            others = [o for o in range(b.arity) if o != p]
            for d in range(D):
                best = float("inf")
                for combo in itertools.product(range(D), repeat=len(others)):
                    idx = [0] * b.arity
                    idx[p] = d
                    for o, c in zip(others, combo):
                        idx[o] = c
                    val = cost[tuple(idx)] + sum(
                        q[o, c] for o, c in zip(others, combo)
                    )
                    if val < best:
                        best = val
    per_factor = (time.perf_counter() - t0) / n
    total_factors = sum(bb.n_factors for bb in tensors.buckets)
    return per_factor * total_factors


def python_reference_dpop_time(D: int, n_nodes: int, n_children: int = 1,
                               sample: int = 100) -> float:
    """Seconds for the reference-equivalent UTIL join+project over
    n_nodes tree nodes.

    Mirrors the reference's control flow (relations.py:1622-1706): join
    enumerates EVERY assignment of the joined dims as a dict, reads both
    operands via per-assignment keyword calls, and writes element-wise;
    projection then optimizes one variable out per remaining assignment.
    (The actual reference cannot run here — its join uses
    ndarray.itemset, removed in NumPy 2.0 — so this faithful
    re-implementation of its per-assignment loop is the stand-in; see
    BENCHREF.md for measured end-to-end reference baselines.)
    """
    import itertools as it

    rng = np.random.default_rng(0)
    cost = {(o, p): float(v) for (o, p), v in np.ndenumerate(
        rng.uniform(0, 10, (D, D)))}
    unary = {o: float(v) for o, v in enumerate(rng.uniform(0, 1, D))}
    child_msgs = [
        {o: float(v) for o, v in enumerate(rng.uniform(0, 10, D))}
        for _ in range(n_children)
    ]
    t0 = time.perf_counter()
    for _ in range(sample):
        # join: full cross product of the union dims, dict-keyed reads
        joined = {}
        for asst in it.product(range(D), range(D)):
            assignment = {"own": asst[0], "par": asst[1]}
            v = unary[assignment["own"]] + \
                cost[(assignment["own"], assignment["par"])]
            for m in child_msgs:
                v += m[assignment["own"]]
            joined[asst] = v
        # projection: min over own per remaining assignment
        msg = {}
        for par in range(D):
            best = float("inf")
            for own in range(D):
                val = joined[(own, par)]
                if val < best:
                    best = val
            msg[par] = best
    per_node = (time.perf_counter() - t0) / sample
    return per_node * n_nodes


# --------------------------------------------------------------------------
# drift calibration probe (round-5 verdict item 1)
# --------------------------------------------------------------------------

#: probe kernel geometry — FIXED across rounds (the whole point: a
#: constant-shape, constant-cost kernel whose only variable is the
#: host/tunnel/device state).  Changing these invalidates normalized
#: comparisons against earlier rounds.
PROBE_DIM = 1024
PROBE_CHAIN = 400


def make_drift_probe(repeat: int = 3, dim: int = PROBE_DIM,
                     chain: int = PROBE_CHAIN):
    """Calibration probe for tunnel/host drift normalization.

    The shared chip's effective throughput drifts on a minutes-to-hours
    scale (round 5's 28.4% primary drop could not be separated from
    environment).  This builds ONE jitted fixed-shape kernel — a chain
    of ``PROBE_CHAIN`` [PROBE_DIM]² f32 matmuls with a tanh squash to
    keep values bounded — whose device cost is constant by
    construction, and returns a ``probe()`` closure measuring it in
    matmuls/sec with the same ``measure_rate`` discipline as the
    primary.  Timed INSIDE every burst, right next to the primary
    measurement, it sees the same tunnel state: the ratio
    ``primary / probe_rate`` (``primary_normalized``) cancels the
    environment term, so a normalized round-over-round drop is code,
    not drift.  ``dim``/``chain`` exist for the unit tests; recorded
    rounds must keep the defaults."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    a = jnp.asarray(
        rng.uniform(-1, 1, (dim, dim)).astype(np.float32)
    )

    @jax.jit
    def chain_fn(x):
        def body(c, _):
            return jnp.tanh(c @ a), ()

        c, _ = jax.lax.scan(body, x, None, length=chain)
        return c

    jax.block_until_ready(chain_fn(a))  # warmup / compile

    def probe():
        return measure_rate(
            lambda: jax.block_until_ready(chain_fn(a)), chain, repeat
        )

    return probe


def drift_verdict(value: float, extra: dict, here: str):
    """One-line verdict on the PREVIOUS round's primary drop, recorded
    into extra (the round-5 ask: was the 28.4% drop drift or real?).

    Before the probe existed the only retroactive evidence is this
    run's RAW primary against the last two rounds': a recovery back to
    the round-before-last level with no intervening kernel change means
    the dropped round sat in a slow environment window; staying at the
    dropped level is consistent with a real regression (or a persistent
    window — which ``primary_normalized``, recorded from this round on,
    disambiguates next time)."""
    import glob
    import re

    rounds = {}
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                v, _extra = _primary_from_record(json.load(f))
            if v:
                rounds[int(m.group(1))] = float(v)
        except (OSError, ValueError):
            continue
    if len(rounds) < 2 or not value:
        return
    r_last, r_before = sorted(rounds)[-1], sorted(rounds)[-2]
    v_last, v_before = rounds[r_last], rounds[r_before]
    drop = 1.0 - v_last / v_before if v_before else 0.0
    if drop <= 0.10:
        return
    if value >= v_before * 0.9:
        verdict = (
            f"drift: this run's raw primary ({value:.0f}) recovered to "
            f"round {r_before}'s level ({v_before:.0f}) with no "
            f"intervening kernel change, so round {r_last}'s "
            f"{100 * drop:.1f}% drop was environment"
        )
    elif value <= v_last * 1.1:
        verdict = (
            f"real-or-persistent: this run's raw primary ({value:.0f}) "
            f"stays at round {r_last}'s dropped level ({v_last:.0f}); "
            f"compare primary_normalized from this round on to "
            f"separate code from a persistent slow window"
        )
    else:
        verdict = (
            f"partial recovery ({value:.0f} between {v_last:.0f} and "
            f"{v_before:.0f}): inconclusive on raw — trust "
            f"primary_normalized from this round on"
        )
    extra["prior_round_drop"] = {
        "rounds": [r_before, r_last],
        "raw": [v_before, v_last],
        "drop_pct": round(100 * drop, 1),
        "verdict": verdict,
    }


# --------------------------------------------------------------------------
# watchdog: guarantee the one-JSON-line contract even if the device wedges
# --------------------------------------------------------------------------

def _arm_watchdog(seconds: float, metric: str):
    import threading

    def fire():
        print(
            json.dumps({
                "metric": metric, "value": 0.0, "unit": "iters/s",
                "vs_baseline": 0.0,
                "error": f"watchdog: no result within {seconds}s "
                "(device init or run wedged)",
            }),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


# --------------------------------------------------------------------------
# benchmark parts
# --------------------------------------------------------------------------

class BenchAbort(RuntimeError):
    """Raised when a requested bench configuration cannot run; main()
    turns it into the contractual one-JSON-line error output."""


def robust_best(times, floor: float = 0.02):
    """Representative per-call time from repeated measurements.

    The tunneled device occasionally returns from block_until_ready
    before the work is actually done, yielding a physically impossible
    near-zero sample (observed once: a 2000-cycle run "finishing" in
    29us; observed r4: a glitch burst hitting 2 of 3 samples, which
    poisons a median-only guard).  Two defenses:

    * an ABSOLUTE floor: every timed call here wraps a jit dispatch that
      costs ~70ms on the tunneled host, so any sample below ``floor``
      seconds is a glitch regardless of what the median says;
    * the median ratio test for glitches above the floor.

    With NO sample above the floor — a direct-attached (non-tunneled)
    device where sub-20ms calls are legitimate, or a full glitch burst —
    the median of all samples is the answer: representative in the
    former case, and bounded damage in the latter."""
    ts = sorted(t for t in times if t >= floor)
    if not ts:
        allts = sorted(times)
        return allts[len(allts) // 2]
    med = ts[len(ts) // 2]
    sane = [t for t in ts if t > med / 50]
    return min(sane) if sane else med


def measure_rate(run_fn, n_units: float, repeat: int,
                 floor: float = 0.02, retries: int = 2) -> float:
    """units/sec from repeated timed calls of ``run_fn`` (which must
    block until the device work is done), with glitch-burst retries.

    robust_best's sub-floor fallback bounds the damage of a FULL glitch
    burst (every block_until_ready returning early) but still yields a
    physically impossible rate — the r5 full run recorded 37M iters/s
    for a measurement that sanely reads ~5k.  When no sample clears the
    floor, the whole burst is re-measured up to ``retries`` times; only
    if EVERY burst stays sub-floor does the median of the last burst
    stand — the representative answer on a direct-attached device where
    sub-floor calls are legitimate, and bounded damage in the (now
    retries-deep) tunnel-glitch case."""
    best = None
    for _ in range(retries + 1):
        times = []
        for _r in range(repeat):
            t0 = time.perf_counter()
            run_fn()
            times.append(time.perf_counter() - t0)
        best = robust_best(times, floor)
        if best >= floor:
            return n_units / best
    return n_units / best


def build_stretch_tensors(args, V=None, E=None):
    """The stretch coloring instance (single source for the --stretch
    compat mode and the convergence bench — same rng(1) data).  V/E
    default to the 100k/300k primary stretch; stretch2 passes 1M/3M."""
    from pydcop_tpu.ops.compile import compile_binary_from_arrays

    C = args.colors
    V = V if V is not None else args.stretch_vars
    E = E if E is not None else args.stretch_edges
    rng = np.random.default_rng(1)
    edge_i = rng.integers(0, V, E)
    edge_j = (edge_i + 1 + rng.integers(0, V - 1, E)) % V
    mats = rng.uniform(0, 1, (E, C, C)).astype(np.float32)
    mats += np.eye(C, dtype=np.float32) * 10  # coloring penalty
    return compile_binary_from_arrays(
        edge_i, edge_j, mats, V,
        unary=rng.uniform(0, 0.01, (V, C)).astype(np.float32),
    )


def bench_maxsum(args):
    """Primary metric + the tensors for the local-search benches."""
    import jax

    from pydcop_tpu.ops import compile_factor_graph
    from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
    from pydcop_tpu.ops.pallas_maxsum import (
        packed_cycles, packed_init_state, try_pack_for_pallas,
    )
    from pydcop_tpu.generators import generate_graph_coloring

    dcop = generate_graph_coloring(
        n_variables=args.vars, n_colors=args.colors, n_edges=args.edges,
        soft=True, n_agents=1, seed=1,
    )
    tensors = compile_factor_graph(dcop)

    packed = None
    if args.engine == "packed":
        packed = try_pack_for_pallas(tensors)
        if packed is None:
            raise BenchAbort("--engine packed: graph not packable")
    elif args.engine == "auto" and jax.default_backend() == "tpu":
        packed = try_pack_for_pallas(tensors)

    # 5 cycles fused per pallas kernel amortizes per-kernel launch inside
    # the scan (measured +28% over one kernel per cycle at bench sizes)
    chunk = 5 if packed is not None and args.cycles % 5 == 0 else 1

    @jax.jit
    def run_n(q, r):
        def body(carry, _):
            q, r = carry
            if packed is not None:
                q2, r2, _, _ = packed_cycles(
                    packed, q, r, chunk, damping=0.5
                )
            else:
                q2, r2, _, _ = maxsum_cycle(tensors, q, r, damping=0.5)
            return (q2, r2), ()

        (q, r), _ = jax.lax.scan(
            body, (q, r), None, length=args.cycles // chunk
        )
        return q, r

    q0, r0 = (
        packed_init_state(packed) if packed is not None
        else init_messages(tensors)
    )
    q, r = run_n(q0, r0)  # warmup / compile
    jax.block_until_ready((q, r))
    # the tunnel's throughput drifts on a MINUTES timescale (measured
    # r5: 15.0k vs 21.4k for identical code an hour apart), so every
    # repeat in one burst sees the same tunnel state.  The SAME closure
    # times the first burst here and a second one main() runs at the
    # END of the full bench — two bursts ~30 min apart straddle the
    # drift and the max is the honest engine rate.  Keeping it pins
    # run_n's executable + q0/r0 (~3MB packed at the 10k default) until
    # the run ends — noise next to stretch2's ~430MB working set.
    def remeasure():
        return measure_rate(
            lambda: jax.block_until_ready(run_n(q0, r0)),
            args.cycles // chunk * chunk, args.repeat)

    iters_per_sec = remeasure()

    ref_cycle_s = python_reference_cycle_time(tensors)
    vs = iters_per_sec * ref_cycle_s if ref_cycle_s > 0 else 0.0
    return iters_per_sec, vs, dcop, tensors, remeasure


def bench_dpop(args):
    """DPOP UTIL+VALUE cost-tables/sec on a 10k-node random tree, D=10
    (BASELINE.md second primary metric), batched sweep engine."""
    import jax

    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.graph import pseudotree
    from pydcop_tpu.ops.dpop_sweep import compile_sweep, make_throughput_fn

    N, D = args.dpop_vars, args.dpop_domain
    rng = np.random.default_rng(2)
    dcop = DCOP("dpop_bench", objective="min")
    dom = Domain("d", "vals", list(range(D)))
    vs = [Variable(f"v{i}", dom) for i in range(N)]
    for v in vs:
        dcop.add_variable(v)
    parents = [int(rng.integers(0, i)) for i in range(1, N)]
    mats = rng.uniform(0, 10, (N - 1, D, D)).astype(np.float32)
    for i, p in enumerate(parents):
        dcop.add_constraint(
            NAryMatrixRelation([vs[p], vs[i + 1]], mats[i], name=f"c{i}")
        )
    dcop.add_agents([AgentDef("a0")])

    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    if plan is None:
        raise RuntimeError("dpop bench instance not sweepable")
    # several chained sweeps per program: the tunneled bench host pays
    # ~70ms dispatch per jit call, which would otherwise dominate the
    # ~25ms sweep (see make_throughput_fn)
    reps = 10
    fn, dev_args = make_throughput_fn(plan, reps)
    out = fn(*dev_args)  # warmup / compile
    jax.block_until_ready(out)
    times = []
    for _ in range(args.repeat):
        t0 = time.perf_counter()
        out = fn(*dev_args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    tables_per_sec = reps * plan.n_nodes / robust_best(times)

    mean_children = (N - 1) / max(1, len(set(parents)))
    ref_s = python_reference_dpop_time(D, N, n_children=round(mean_children))
    vs = tables_per_sec * (ref_s / N) if ref_s > 0 else 0.0

    # whole-sweep pallas kernel: UTIL+VALUE in ONE launch (the level
    # scan above is dispatch-latency-bound).  Measured with the same
    # rep-chaining discipline; failure must not lose the level-scan
    # numbers.
    whole_tps = None
    try:
        from pydcop_tpu.ops.pallas_dpop import (
            make_whole_sweep_fn, pack_sweep,
        )

        ps = pack_sweep(plan)
        if ps is not None and jax.default_backend() == "tpu":
            # the whole sweep runs in ~0.6ms — at reps=10 the ~70ms
            # tunnel dispatch would hide ~10x of the device rate
            wreps = 200
            wfn, wargs = make_whole_sweep_fn(ps, wreps)
            out = wfn(*wargs)
            jax.block_until_ready(out)
            wtimes = []
            for _ in range(args.repeat):
                t0 = time.perf_counter()
                out = wfn(*wargs)
                jax.block_until_ready(out)
                wtimes.append(time.perf_counter() - t0)
            whole_tps = wreps * plan.n_nodes / robust_best(wtimes)
    except Exception:
        pass

    # batched throughput: B same-topology instances (different cost
    # tables — the dynamic-DCOP / sweep workload) in ONE dispatch.  The
    # single sweep is latency-bound (L sequential levels of tiny
    # kernels, docs/performance.rst); batching recovers device
    # throughput.  A failure here must not lose the already-measured
    # primary metric.
    batched_tps = batched_vs = None
    try:
        from pydcop_tpu.ops.dpop_sweep import make_batched_sweep_fn

        B = 100
        rng_b = np.random.default_rng(7)
        local_b = jax.device_put(
            np.asarray(plan.local)[None]
            + rng_b.uniform(0, 1e-3, (B, 1, 1, 1)).astype(np.float32)
        )
        bfn, bargs = make_batched_sweep_fn(plan, batch=B)
        out = bfn(local_b, *bargs)
        jax.block_until_ready(out)
        btimes = []
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            out = bfn(local_b, *bargs)
            jax.block_until_ready(out)
            btimes.append(time.perf_counter() - t0)
        batched_tps = B * plan.n_nodes / robust_best(btimes)
        batched_vs = batched_tps * (ref_s / N) if ref_s > 0 else 0.0
    except Exception:
        pass
    return tables_per_sec, vs, plan, batched_tps, batched_vs, whole_tps


def bench_local_search(dcop, algo: str, cycles: int = 2000, repeat: int = 3):
    """MGM / DSA cycles per second on the 10k coloring instance.

    2000 cycles per timed dispatch for the same reason as the primary
    metric (--cycles help): the tunneled device costs ~100ms per jit
    dispatch, which at 200 cycles/call would hide ~10x of the real
    fused-kernel rate."""
    from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module

    mod = load_algorithm_module(algo)
    algo_def = AlgorithmDef.build_with_default_params(algo)
    solver = mod.build_solver(dcop, algo_def=algo_def)
    solver.run(cycles=cycles, chunk=cycles)  # warmup incl. compile
    return measure_rate(
        lambda: solver.run(cycles=cycles, chunk=cycles), cycles, repeat)


def build_scalefree_dcop(args):
    """Barabási–Albert coloring instance with the top hub boosted past
    degree 500 (the BA tail at 10k vars tops out ~300).  Exercises hub
    splitting in the packed engines (VERDICT r3 item 2): one such hub
    used to knock the whole graph onto the 8-25x slower generic path."""
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.generators import generate_graph_coloring

    rng = np.random.default_rng(11)
    dcop = generate_graph_coloring(
        n_variables=args.vars, n_colors=args.colors,
        graph_type="scalefree", m_edge=3, soft=True, n_agents=1, seed=1,
    )
    deg: dict = {}
    neighbors = set()
    for c in dcop.constraints.values():
        names = [v.name for v in c.dimensions]
        for n_ in names:
            deg[n_] = deg.get(n_, 0) + 1
    hub = max(deg, key=deg.get)
    for c in dcop.constraints.values():
        names = [v.name for v in c.dimensions]
        if hub in names:
            neighbors.update(names)
    hubv = dcop.variables[hub]
    C = args.colors
    k = 0
    for vn, var in dcop.variables.items():
        if deg[hub] + k >= 520:
            break
        if vn == hub or vn in neighbors:
            continue
        mat = rng.uniform(0, 1, (C, C)).astype(np.float32) \
            + np.eye(C, dtype=np.float32) * 10
        dcop.add_constraint(
            NAryMatrixRelation([hubv, var], mat, name=f"hub_extra_{k}")
        )
        k += 1
    return dcop, deg[hub] + k


def bench_scalefree(args):
    """Packed-engine rates on the scale-free instance: MaxSum iters/s
    (fused pallas, hub splitting) + MGM cycles/s.  Returns extras dict."""
    import jax

    from pydcop_tpu.ops import compile_factor_graph
    from pydcop_tpu.ops.pallas_maxsum import (
        packed_cycles, packed_init_state, try_pack_for_pallas,
    )

    dcop, hub_deg = build_scalefree_dcop(args)
    out = {"scalefree_hub_degree": hub_deg}
    if jax.default_backend() == "tpu":
        tensors = compile_factor_graph(dcop)
        packed = try_pack_for_pallas(tensors)
        if packed is None or packed.hub_nsteps == 0:
            out["scalefree_error"] = "instance did not pack with hub split"
            return out

        chunk = 5

        @jax.jit
        def run_n(q, r):
            def body(carry, _):
                q, r = carry
                q2, r2, _, _ = packed_cycles(packed, q, r, chunk,
                                             damping=0.5)
                return (q2, r2), ()

            (q, r), _ = jax.lax.scan(
                body, (q, r), None, length=args.cycles // chunk
            )
            return q, r

        q0, r0 = packed_init_state(packed)
        q, r = run_n(q0, r0)
        jax.block_until_ready((q, r))
        rate = measure_rate(
            lambda: jax.block_until_ready(run_n(q0, r0)),
            args.cycles // chunk * chunk, args.repeat)
        out[f"maxsum_iters_per_sec_scalefree_{args.vars}var"] = round(
            rate, 1)
    try:
        out[f"mgm_cycles_per_sec_scalefree_{args.vars}var"] = round(
            bench_local_search(dcop, "mgm", repeat=args.repeat), 1)
    except Exception as e:  # never lose the maxsum number
        out["scalefree_mgm_error"] = repr(e)

    # scale-free WITH ternary factors (ROADMAP item 3 / VERDICT r5
    # item 4): hub splitting now composes with the mixed packer, so
    # this previously-generic family rides a packed engine too
    try:
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        rng = np.random.default_rng(13)
        tern = 0
        names = list(dcop.variables)
        for tern in range(args.vars // 10):
            i, j, k = rng.choice(len(names), 3, replace=False)
            sc = [dcop.variables[names[i]], dcop.variables[names[j]],
                  dcop.variables[names[k]]]
            dcop.add_constraint(NAryMatrixRelation(
                sc, rng.integers(0, 10, [len(v.domain) for v in sc])
                .astype(np.float32), name=f"tern_{tern}"))
        t3 = compile_factor_graph(dcop)
        p3 = try_pack_for_pallas(t3)
        out["scalefree_ternary_packed"] = bool(
            p3 is not None and p3.mixed and p3.hub_nsteps > 0)
        if p3 is not None and jax.default_backend() == "tpu":
            chunk = 5

            @jax.jit
            def run3(q, r):
                def body(carry, _):
                    q, r = carry
                    q2, r2, _, _ = packed_cycles(p3, q, r, chunk,
                                                 damping=0.5)
                    return (q2, r2), ()

                (q, r), _ = jax.lax.scan(
                    body, (q, r), None, length=args.cycles // chunk)
                return q, r

            q0, r0 = packed_init_state(p3)
            q, r = run3(q0, r0)
            jax.block_until_ready((q, r))
            out["maxsum_iters_per_sec_scalefree_ternary"] = round(
                measure_rate(
                    lambda: jax.block_until_ready(run3(q0, r0)),
                    args.cycles // chunk * chunk, args.repeat), 1)
    except Exception as e:
        out["scalefree_ternary_error"] = repr(e)
    return out


def bench_mixed_arity(args):
    """Packed-engine rate on a mixed-arity SECP instance (VERDICT r4
    item 7): 3900 vars, arity-1 light costs + arity-2/3 model and rule
    factors — the family that previously fell entirely to the generic
    engine.  Also confirms a PEAV meeting-scheduling instance (unary
    preference factors + binary equality/overlap factors) rides the
    mixed packer."""
    import jax

    from pydcop_tpu.generators.meetingscheduling import (
        generate_meetings_peav,
    )
    from pydcop_tpu.generators.secp import generate_secp
    from pydcop_tpu.ops import compile_factor_graph
    from pydcop_tpu.ops.pallas_maxsum import (
        packed_cycles, packed_init_state, try_pack_for_pallas,
    )

    out = {}
    dcop = generate_secp(n_lights=3000, n_models=900, n_rules=300,
                         max_model_size=2, seed=1)
    tensors = compile_factor_graph(dcop)
    packed = try_pack_for_pallas(tensors)
    out["secp_mixed_packed"] = bool(packed is not None and packed.mixed)
    if packed is None or jax.default_backend() != "tpu":
        return out

    chunk = 5

    @jax.jit
    def run_n(q, r):
        def body(carry, _):
            q, r = carry
            q2, r2, _, _ = packed_cycles(packed, q, r, chunk, damping=0.5)
            return (q2, r2), ()

        (q, r), _ = jax.lax.scan(
            body, (q, r), None, length=args.cycles // chunk)
        return q, r

    q0, r0 = packed_init_state(packed)
    q, r = run_n(q0, r0)
    jax.block_until_ready((q, r))
    out["maxsum_iters_per_sec_secp_mixed_arity"] = round(
        measure_rate(
            lambda: jax.block_until_ready(run_n(q0, r0)),
            args.cycles // chunk * chunk, args.repeat), 1)

    # fused mixed-arity MOVE kernels (VERDICT r5 item 1): the local
    # search family on the same SECP instance rides the packed engines
    # (previously a 10-20x generic-engine cliff)
    for algo in ("mgm", "dsa", "mgm2"):
        try:
            out[f"{algo}_cycles_per_sec_secp_mixed"] = round(
                bench_local_search(dcop, algo, repeat=args.repeat), 1)
        except Exception as e:  # keep the other rates
            out[f"secp_mixed_{algo}_error"] = repr(e)

    # the SHARDED path on the same mixed instance (1-device mesh):
    # ROADMAP item 7's first half — mixed-arity graphs ride the
    # lane-packed per-shard kernels under a shared MixedLayout
    # (~15.4k iters/s when this landed vs sub-1k for the generic
    # sharded engine)
    try:
        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        shp = ShardedMaxSum(tensors, build_mesh(1), damping=0.5)
        if shp.packs is not None and shp.packs.mixed:
            shp.run(cycles=args.cycles)  # warmup / compile
            out["sharded_packed_secp_iters_per_sec_tpu"] = round(
                measure_rate(
                    lambda: shp.run(cycles=args.cycles),
                    args.cycles, args.repeat), 1)
    except Exception as e:  # never lose the single-chip rates
        out["sharded_packed_secp_error"] = repr(e)

    # arity-4 SECP (3-light models — round 5, the last packed-path
    # capability gap): the quaternary packing with its third Clos
    # permutation and narrow 8-row-aligned D^3-block slabs
    try:
        dcop4 = generate_secp(n_lights=3000, n_models=900, n_rules=300,
                              max_model_size=3, seed=1)
        t4 = compile_factor_graph(dcop4)
        p4 = try_pack_for_pallas(t4)
        out["secp4_packed"] = bool(
            p4 is not None and p4.cost4_rows is not None)
        if p4 is not None and jax.default_backend() == "tpu":
            @jax.jit
            def run4(q, r):
                def body(carry, _):
                    q, r = carry
                    q2, r2, _, _ = packed_cycles(p4, q, r, chunk,
                                                 damping=0.5)
                    return (q2, r2), ()

                (q, r), _ = jax.lax.scan(
                    body, (q, r), None, length=args.cycles // chunk)
                return q, r

            q40, r40 = packed_init_state(p4)
            jax.block_until_ready(run4(q40, r40))
            out["maxsum_iters_per_sec_secp4_arity4"] = round(
                measure_rate(
                    lambda: jax.block_until_ready(run4(q40, r40)),
                    args.cycles // chunk * chunk, args.repeat), 1)
            out["mgm_cycles_per_sec_secp4"] = round(
                bench_local_search(dcop4, "mgm", repeat=args.repeat), 1)
    except Exception as e:
        out["secp4_error"] = repr(e)

    # PEAV meeting scheduling: unary preference factors + binary
    # equality/overlap factors → the mixed packer (slots_count 7 keeps
    # the value domain within the engine's D <= 8)
    peav, _ = generate_meetings_peav(
        slots_count=7, events_count=40, resources_count=30,
        max_resources_event=3, seed=1)
    ppacked = try_pack_for_pallas(compile_factor_graph(peav))
    out["peav_packed"] = bool(ppacked is not None and ppacked.mixed)
    return out


def bench_convergence_stretch(args, V=None, E=None, prefix="stretch",
                              max_cycles=None, check_messages=True,
                              plateau_patience=5):
    """North star: wall-clock to MaxSum convergence on a large coloring
    instance (100k vars / 300k edges; ``stretch2`` = 1M / 3M).

    Three convergence criteria, checked in-device per chunk:
      * ``assignment`` — strict: no variable flipped for STABLE_CYCLES
        consecutive cycles (tracked in-scan);
      * ``messages`` — the reference's own test (approx_match within
        STABILITY_COEFF=0.1 for SAME_COUNT=4 cycles,
        pydcop/algorithms/maxsum.py:98-100,620): every r-message stable;
      * ``cost`` — anytime plateau: best cost not improved by >0.1%
        for 5 consecutive chunks.
    On frustrated random instances plain BP oscillates (strict stability
    never fires — measured); the plateau criterion captures what the
    anytime solver delivers, the message criterion is reference parity.

    The factor update runs in edge-slab form with the big arrays passed
    as jit ARGUMENTS: the [F, D, D] broadcast-min compiles for >10
    minutes at 1M vars (closure constants make it worse) while the
    edge-slab form compiles in seconds at every size (ops/maxsum_kernels
    EdgeSlabs rationale; a [D, E] column-major variant was measured
    equally compile-pathological through this toolchain's fused
    transpose+scatter path, so the row layout stays).
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from pydcop_tpu.ops.maxsum_kernels import (
        EdgeSlabs, maxsum_cycle_edge_slabs,
    )

    V = V if V is not None else args.stretch_vars
    E = E if E is not None else args.stretch_edges
    max_cycles = max_cycles or args.stretch_max_cycles
    tensors = build_stretch_tensors(args, V, E)
    eslabs = EdgeSlabs(tensors, sort_edges=True)
    D = tensors.max_domain_size
    big_args = (tuple(eslabs.slabs), eslabs.mate, eslabs.edge_var,
                tensors.unary_costs, tensors.domain_mask)

    chunk = 10
    damping = 0.9  # measured best for convergence on the 100k instance
    STABILITY_COEFF = 0.1  # reference maxsum.py:98

    from pydcop_tpu.ops.maxsum_kernels import edge_slab_total_cost

    def rebuild(slab_arrs, mate, ev, un, dm):
        t2 = dataclasses.replace(
            tensors, unary_costs=un, domain_mask=dm)
        sl = EdgeSlabs.from_arrays(slab_arrs, mate, ev, D,
                                   sorted_edges=True)
        return t2, sl

    def make_run_chunk(damping_):
        @jax.jit
        def run_chunk(q, r, prev_vals, msg_stable_in, stable_cyc_in,
                      best_c_in, best_v_in, *big):
            t2, sl = rebuild(*big)

            def body(carry, _):
                (q, r, msg_stable, vals_prev, stable_cyc,
                 best_c, best_v) = carry
                q2, r2, _, values = maxsum_cycle_edge_slabs(
                    t2, sl, q, r, damping=damping_)
                if check_messages:
                    # reference approx_match (maxsum.py:620-639)
                    from pydcop_tpu.algorithms.maxsum import (
                        messages_stable,
                    )

                    all_stable = jnp.all(
                        messages_stable(r, r2, STABILITY_COEFF))
                    msg_stable = jnp.where(all_stable, msg_stable + 1, 0)
                # assignment stability: cycles since ANY variable
                # flipped — the signal an anytime-algorithm user
                # actually watches (VERDICT r3 item 5)
                flipped = jnp.any(values != vals_prev)
                stable_cyc = jnp.where(flipped, 0, stable_cyc + 1)
                # best-assignment tracking IN-scan (VERDICT r4 item 2):
                # the anytime solver DELIVERS the cheapest assignment it
                # visited, not the last one BP oscillated onto (~+11%
                # work: an O(E) cost eval vs the O(E·D²) cycle)
                c = edge_slab_total_cost(
                    sl, t2.unary_costs, t2.domain_mask, values)
                better = c < best_c
                best_c = jnp.where(better, c, best_c)
                best_v = jnp.where(better, values, best_v)
                return (q2, r2, msg_stable, values, stable_cyc,
                        best_c, best_v), ()

            (q, r, msg_stable, vals, stable_cyc, best_c, best_v), _ = \
                jax.lax.scan(
                    body,
                    (q, r, msg_stable_in, prev_vals, stable_cyc_in,
                     best_c_in, best_v_in),
                    None, length=chunk,
                )
            # all convergence signals are tracked IN-scan (stable_cyc
            # carries across chunk boundaries); no extra probe cycle
            return (q, r, vals, msg_stable, stable_cyc, best_c, best_v,
                    edge_slab_total_cost(
                        sl, t2.unary_costs, t2.domain_mask, vals))

        return run_chunk

    run_chunk = make_run_chunk(damping)

    @jax.jit
    def final_diag(q, r, *big):
        """One extra cycle for the END-of-run diagnostics: fraction of
        messages still failing the reference approx_match test."""
        t2, sl = rebuild(*big)
        _, r_next, _, _ = maxsum_cycle_edge_slabs(
            t2, sl, q, r, damping=damping)
        from pydcop_tpu.algorithms.maxsum import messages_stable

        return jnp.sum(~messages_stable(r, r_next, STABILITY_COEFF))

    def init_messages(_t):
        z = jnp.zeros((2 * E, D), dtype=jnp.float32)
        return z, z

    q, r = init_messages(tensors)
    zero_vals = jnp.zeros(V, dtype=jnp.int32)
    zero_stab = jnp.zeros((), dtype=jnp.int32)
    inf_cost = jnp.asarray(np.float32(np.inf))
    out = run_chunk(q, r, zero_vals, zero_stab, zero_stab, inf_cost,
                    zero_vals, *big_args)
    jax.block_until_ready(out)  # warmup / compile
    #: once the cost plateaus, damping is FROZEN near 1 for up to this
    #: many chunks, giving the argmin assignment a window to hold still
    #: (VERDICT r4 item 2's annealing schedule).  Measured negative
    #: result at 100k vars: even at damping 0.98-0.995 the ~22% of
    #: messages that keep failing approx_match flip SOME variable every
    #: cycle, so the strict global-stillness criterion never fires on
    #: this frustrated instance — the honest deliverable is the
    #: best-cost assignment tracked in-scan (stretch_delivered_cost,
    #: measured 5.7% below the plateau-exit cost).
    FREEZE_DAMPING = 0.98
    FREEZE_CHUNKS = 6
    # pre-warm the frozen-damping runner too: its compile must not land
    # inside the timed window when the plateau fires
    frozen_chunk = make_run_chunk(FREEZE_DAMPING)
    out = frozen_chunk(q, r, zero_vals, zero_stab, zero_stab, inf_cost,
                       zero_vals, *big_args)
    jax.block_until_ready(out)

    q, r = init_messages(tensors)
    t0 = time.perf_counter()
    prev_vals = zero_vals
    msg_stable = zero_stab
    stable_cyc = zero_stab
    best_c, best_v = inf_cost, zero_vals
    converged = None
    cycles_run = 0
    best_cost = float("inf")
    plateau = 0
    final_cost = None
    max_stable = 0
    freeze_used = False
    plateau_wall = None  # wall at plateau detection — the number
    # comparable to rounds BEFORE the freeze/delivery window existed
    #: assignment-stability bar: no variable flipped for this many
    #: consecutive cycles (strictest criterion; checked in-scan)
    STABLE_CYCLES = 20
    freeze_left = 0
    chunks_total = max_cycles // chunk
    for it in range(chunks_total):
        (q, r, prev_vals, msg_stable, stable_cyc, best_c, best_v,
         cost) = run_chunk(
            q, r, prev_vals, msg_stable, stable_cyc, best_c, best_v,
            *big_args)
        cycles_run += chunk
        final_cost = float(cost)
        max_stable = max(max_stable, int(stable_cyc))
        if int(stable_cyc) >= STABLE_CYCLES:
            converged = "assignment"
            break
        if int(msg_stable) >= 4:  # reference SAME_COUNT, maxsum.py:100
            converged = "messages"
            break
        if freeze_left > 0:
            freeze_left -= 1
            if freeze_left == 0:
                converged = "cost_plateau"  # froze but never held still
                break
            continue
        if final_cost >= best_cost * (1 - 1e-3):
            plateau += 1
            if plateau >= plateau_patience:
                plateau_wall = time.perf_counter() - t0
                if chunks_total - it <= FREEZE_CHUNKS:
                    # not enough budget left for the freeze window —
                    # report the plateau as before
                    converged = "cost_plateau"
                    break
                # anneal: swap in the pre-warmed frozen-damping runner
                # and give the assignment a window to stop flipping
                freeze_used = True
                freeze_left = FREEZE_CHUNKS
                run_chunk = frozen_chunk
        else:
            plateau = 0
        best_cost = min(best_cost, final_cost)
    wall = time.perf_counter() - t0
    unstable = (
        final_diag(q, r, *big_args) if converged != "messages" else None
    )
    delivered_cost = float(best_c)
    out = {
        f"{prefix}_vars": V,
        f"{prefix}_edges": E,
        f"{prefix}_wall_s": round(wall, 3),
        # time to the plateau itself (the pre-round-5 wall definition;
        # the freeze/delivery window that follows adds up to 60 cycles
        # in exchange for the delivered-cost improvement)
        f"{prefix}_plateau_wall_s": round(
            plateau_wall if plateau_wall is not None else wall, 3),
        f"{prefix}_converged": converged is not None,
        f"{prefix}_criterion": converged,
        f"{prefix}_cycles": cycles_run,
        f"{prefix}_assignment_stable_cycles": max_stable,
        f"{prefix}_freeze_phase_used": freeze_used,
        f"{prefix}_final_cost": (
            round(final_cost, 1) if final_cost is not None else None
        ),
        # the anytime DELIVERABLE: cheapest assignment visited in-scan
        f"{prefix}_delivered_cost": round(delivered_cost, 1),
        f"{prefix}_delivered_beats_final": bool(
            final_cost is None or delivered_cost <= final_cost + 1e-6
        ),
    }
    if converged != "messages" and unstable is not None:
        # documented negative result (VERDICT r2 item 10): on this
        # frustrated random instance a fraction of messages keeps
        # oscillating under ANY damping (measured: ~74% at 0.5, ~20% at
        # 0.9, ~4% plateau at 0.98 — the approx_match criterion is
        # scale-invariant, so damping cannot force it), hence the
        # reference's own message criterion never fires and the honest
        # convergence signal is the cost plateau.  See
        # docs/performance.rst.
        out[f"{prefix}_msg_unstable_frac"] = round(
            float(unstable) / (tensors.n_edges * tensors.max_domain_size),
            4,
        )
    return out


def bench_sharded_local_tpu(args, extra, dcop=None):
    """Sharded LOCAL-SEARCH micro-bench on the real chip (1-device
    mesh): the lane-packed move rule (this round's tentpole — packed
    tables + column-space coins + routed-gain pmax/pmin arbitration)
    must carry the single-chip engineering, where the round-5 replicated
    generic move rule capped MGM at ~520 cycles/s.  Chunk sizes sized so
    one timed call clears the ~70ms tunnel dispatch floor at the TARGET
    rates (≥5k cycles/s)."""
    import jax

    if jax.default_backend() != "tpu":
        extra["sharded_local_note"] = (
            "sharded local-search micro-bench needs the TPU backend; "
            "CPU-mesh validation lives in the sharded canary"
        )
        return
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_constraint_graph
    from pydcop_tpu.parallel.mesh import ShardedLocalSearch, build_mesh

    if dcop is None:
        dcop = generate_graph_coloring(
            n_variables=args.vars, n_colors=args.colors,
            n_edges=args.edges, soft=True, n_agents=1, seed=1,
        )
    _ct = compile_constraint_graph(dcop)
    for rule, n_cyc in (("mgm", 1000), ("dsa", 2000)):
        sls = ShardedLocalSearch(_ct, build_mesh(1), rule=rule)
        if sls.packs is None:
            extra[f"sharded_packed_{rule}_error"] = (
                "instance did not shard-pack"
            )
            continue
        sls.run(cycles=n_cyc)  # warmup / compile
        extra[f"sharded_packed_{rule}_cycles_per_sec_tpu"] = round(
            measure_rate(
                lambda: sls.run(cycles=n_cyc), n_cyc, args.repeat), 1)


def bench_batch(args, probe=None):
    """Batched multi-instance throughput (the batch/ subsystem):
    instances/sec completing a fixed-cycle MGM solve on the
    graph-coloring family at B ∈ {1, 8, 32} — one compile + one
    vmapped dispatch chain per shape bucket vs one chain per instance.
    Drift-normalized against the calibration probe like the primary
    (``batch_throughput_b*_normalized``); the engine's compile-cache
    hit/miss counts ride along so a round where the cache stopped
    working is visible in the JSON, not just slower."""
    from pydcop_tpu.batch import BatchEngine, BatchItem
    from pydcop_tpu.batch.cache import CompileCache
    from pydcop_tpu.generators import generate_graph_coloring

    V, E, C = args.batch_vars, args.batch_vars * 3, args.colors
    cycles = 50
    sizes = (1, 8, 32)
    # seeds vary per instance: same family/shape signature, different
    # cost tables + PRNG streams — the sweep-traffic profile
    dcops = [
        generate_graph_coloring(
            n_variables=V, n_colors=C, n_edges=E, soft=True,
            n_agents=1, seed=100 + i,
        )
        for i in range(max(sizes))
    ]
    out = {}
    engine = BatchEngine(cache=CompileCache())
    for b in sizes:
        items = [
            BatchItem(dcops[i], "mgm", seed=i, label=f"gc{i}")
            for i in range(b)
        ]
        engine.solve(items, cycles=cycles)  # warmup incl. compile
        rate = measure_rate(
            lambda: engine.solve(items, cycles=cycles), b, args.repeat
        )
        out[f"batch_throughput_b{b}"] = round(rate, 2)
        if probe is not None:
            pr = probe()
            if pr:
                out[f"batch_throughput_b{b}_normalized"] = round(
                    rate / pr, 6
                )
    b1, bmax = out.get("batch_throughput_b1"), out.get(
        f"batch_throughput_b{max(sizes)}"
    )
    if b1 and bmax:
        out["batch_speedup_b32_vs_b1"] = round(bmax / b1, 2)
    out["batch_compile_cache"] = engine.cache.stats()
    out["batch_counters"] = {
        k: v for k, v in engine.counters.as_dict().items()
        if k in ("buckets_formed", "compile_hits", "compile_misses")
    }
    return out


def bench_harness(args, probe=None):
    """Harness sync overhead (round 8): one convergence-bound MGM solve
    (open-ended, prime chunks, two-stable-chunks rule) timed end to end
    on the pre-pipeline BLOCKING path (host-compare convergence,
    per-shape chunk runners) vs the PIPELINED path (device-side
    convergence scalar, fixed-shape masked runner, one-deep dispatch
    pipeline) — docs/performance.rst "Pipelined convergence".
    Drift-normalized like the primary; both runs' HarnessCounters ride
    along so a regression in the sync budget (host_sync_count per
    chunk) is visible in the JSON, not just slower."""
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.generators import generate_graph_coloring

    V = args.harness_vars
    dcop = generate_graph_coloring(
        n_variables=V, n_colors=args.colors, n_edges=V * 3, soft=True,
        n_agents=1, seed=7,
    )
    mod = load_algorithm_module("mgm")
    out = {}
    rates = {}
    for name, force_host, pipeline in (
        ("pipelined", False, True),
        ("blocking", True, False),
    ):
        solver = mod.build_solver(dcop, seed=1)
        solver._force_host_convergence = force_host

        def run(s=solver, p=pipeline):
            return s.run(max_cycles=400, pipeline=p)

        res = run()  # warmup incl. compile
        times = []
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            res = run()
            times.append(time.perf_counter() - t0)
        rate = res.cycle / robust_best(times)
        rates[name] = rate
        out[f"harness_{name}_cycles_per_sec"] = round(rate, 1)
        h = res.harness or {}
        out[f"harness_{name}_sync_per_chunk"] = round(
            h.get("host_sync_count", 0)
            / max(1, h.get("chunks_dispatched", 1)), 3,
        )
        out[f"harness_{name}_stop_cycle"] = res.cycle
        if probe is not None:
            pr = probe()
            if pr:
                out[f"harness_{name}_cycles_per_sec_normalized"] = round(
                    rate / pr, 6
                )
    if rates.get("blocking"):
        # > 1.0 means the pipelined path is strictly faster on the
        # convergence-bound run — the acceptance headline
        out["harness_sync_overhead"] = round(
            rates["pipelined"] / rates["blocking"], 3
        )
    return out


def bench_serve(args, probe=None):
    """Continuous-batching serve throughput (the serve/ subsystem):
    seeded Poisson arrivals over a mixed-shape graph-coloring family,
    solved run-to-convergence, three ways on the SAME arrival trace:

    * ``serve_*`` — the streaming service: warm compile-cache pools
      (prewarmed before arrivals open), arrivals folded into running
      buckets at chunk boundaries, freed lanes reused;
    * ``serve_seq_*`` — the NAIVE sequential-per-job baseline: each
      arrival handled the way every pre-serve entry point handles a
      job, with a fresh solver paying its own instance compilation and
      jit trace+XLA compile (that cold cost IS the point of the warm
      pools — BENCHREF.md);
    * ``serve_seqwarm_*`` — an idealized clairvoyant baseline with
      every per-job solver pre-compiled before the trace starts
      (unrealizable for streaming traffic, reported for honesty: on a
      single-core CPU host batching is roughly compute-neutral and the
      service's win over THIS baseline comes only from queueing/
      dispatch effects; on parallel backends the vmapped lanes win
      outright).

    Reports solves/s and p50/p99 latency for all three, the seeded
    arrival trace (recorded so a round is reproducible), the
    compile-cache hit counts, and a bit-match flag (per-job serve
    results must equal the standalone solves — the determinism
    contract, cheap to re-assert here).  Drift-normalized like the
    primary."""
    from pydcop_tpu.batch.cache import CompileCache
    from pydcop_tpu.batch.engine import BatchItem, adapter_for
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.serve import SolveService

    n_jobs = args.serve_jobs
    rate = args.serve_rate
    max_cycles = 200
    sizes = (args.serve_vars, args.serve_vars // 2)
    dcops = []
    for i in range(n_jobs):
        V = sizes[i % len(sizes)]
        dcops.append(generate_graph_coloring(
            n_variables=V, n_colors=args.colors, n_edges=V * 3,
            soft=True, n_agents=1, seed=300 + i,
        ))
    rng = np.random.default_rng(args.serve_seed)
    inter = rng.exponential(1.0 / rate, n_jobs)
    inter[0] = 0.0
    offsets = np.cumsum(inter)
    trace = [round(float(o), 6) for o in offsets]
    adapter = adapter_for("mgm")

    def replay_sequential(run_job):
        """FIFO worker on the arrival trace: each job's latency
        includes its queue wait behind earlier jobs."""
        t0 = time.perf_counter()
        lat, results = [], []
        for i in range(n_jobs):
            now = time.perf_counter() - t0
            if now < offsets[i]:
                time.sleep(offsets[i] - now)
            results.append(run_job(i))
            lat.append((time.perf_counter() - t0) - offsets[i])
        return lat, results, time.perf_counter() - t0

    # -- naive sequential-per-job: fresh solver per arrival (cold)
    seq_lat, seq_results, seq_wall = replay_sequential(
        lambda i: adapter.build_spec(
            BatchItem(dcops[i], "mgm", seed=i)
        ).solver.run(max_cycles=max_cycles)
    )

    # -- idealized warm sequential: per-job solvers pre-compiled ahead
    warm_specs = [
        adapter.build_spec(BatchItem(d, "mgm", seed=i))
        for i, d in enumerate(dcops)
    ]
    for spec in warm_specs:
        spec.solver.run(max_cycles=7)
    warm_lat, _warm_results, warm_wall = replay_sequential(
        lambda i: warm_specs[i].solver.run(max_cycles=max_cycles)
    )

    # -- the continuous-batching service: runners prewarmed before
    # arrivals open; per-job instance compilation happens on the
    # service's own prep pipeline, inside the measurement
    cache = CompileCache()
    service = SolveService(
        lanes=args.serve_lanes, cache=cache, max_cycles=max_cycles,
    )
    service.prewarm([(d, "mgm") for d in dcops], block=True)
    service.start()
    t0 = time.perf_counter()
    jids = []
    for i, d in enumerate(dcops):
        now = time.perf_counter() - t0
        if now < offsets[i]:
            time.sleep(offsets[i] - now)
        jids.append((service.submit(d, "mgm", seed=i),
                     time.perf_counter() - t0))
    serve_lat, serve_results = [], []
    for i, (jid, submitted) in enumerate(jids):
        res = service.result(jid, timeout=300)
        serve_results.append(res)
        # latency vs the SCHEDULED arrival, like the baselines
        serve_lat.append((submitted + res.time) - offsets[i])
    serve_wall = max(
        s + r.time for (_j, s), r in zip(jids, serve_results)
    )
    service.stop(drain=False)

    bitmatch = all(
        r.cost == s.cost and r.cycle == s.cycle
        and r.assignment == s.assignment
        for r, s in zip(serve_results, seq_results)
    )

    def pcts(lat, prefix):
        return {
            f"{prefix}_p50_ms": round(
                float(np.percentile(lat, 50)) * 1e3, 1),
            f"{prefix}_p99_ms": round(
                float(np.percentile(lat, 99)) * 1e3, 1),
        }

    out = {
        "serve_throughput_solves_per_sec": round(n_jobs / serve_wall, 2),
        "serve_seq_solves_per_sec": round(n_jobs / seq_wall, 2),
        "serve_seqwarm_solves_per_sec": round(n_jobs / warm_wall, 2),
        "serve_speedup": round(seq_wall / serve_wall, 2),
        "serve_speedup_vs_warm": round(warm_wall / serve_wall, 2),
        **pcts(serve_lat, "serve"),
        **pcts(seq_lat, "serve_seq"),
        **pcts(warm_lat, "serve_seqwarm"),
        "serve_bitmatch": bitmatch,
        "serve_jobs": n_jobs,
        "serve_rate_jobs_per_sec": rate,
        "serve_arrival_seed": args.serve_seed,
        "serve_arrival_trace": trace,
        "serve_compile_cache": cache.stats(),
        "serve_counters": {
            k: v for k, v in service.counters.as_dict().items()
            if k in ("jobs_admitted", "lanes_reused",
                     "midflight_admissions", "buckets_opened",
                     "buckets_merged", "prewarmed_runners")
        },
    }
    out["serve_p99_ratio"] = round(
        out["serve_seq_p99_ms"] / max(out["serve_p99_ms"], 1e-9), 2)
    # > 1.0 on BOTH means continuous batching is strictly better than
    # the sequential-per-job baseline on throughput AND tail latency —
    # the acceptance headline
    out["serve_strictly_better"] = (
        out["serve_speedup"] > 1.0 and out["serve_p99_ratio"] > 1.0
    )

    # -- overload: a saturating burst against a bounded pending queue.
    # Admission control must shed (structured rejections, counted) —
    # and the jobs it DOES admit must keep their tail latency: the pin
    # is admitted-p99 within 2x the unloaded serve p99 above.
    from pydcop_tpu.serve import ServeError

    overload = SolveService(
        lanes=args.serve_lanes, cache=cache, max_cycles=max_cycles,
        max_pending=max(2, args.serve_lanes),
    )
    overload.start()
    ov_jids, ov_rejected = [], 0
    for i, d in enumerate(dcops):  # no pacing: everything at once
        try:
            # hand the pre-built specs over (the warm baseline built
            # them anyway): the admitted-latency record then measures
            # what the bounded queue actually controls — queue wait +
            # solve — not instance-compilation noise
            ov_jids.append(overload.submit(
                d, "mgm", seed=i, spec=warm_specs[i],
            ))
        except ServeError:
            ov_rejected += 1
    ov_lat = []
    for jid in ov_jids:
        r = overload.result(jid, timeout=300)
        if r.status == "FINISHED":
            ov_lat.append(r.time)
    overload.stop(drain=False)
    # jobs_shed already counts the submit-time rejections, alongside
    # any queued job displaced by a higher-priority arrival
    out["serve_overload_max_pending"] = max(2, args.serve_lanes)
    out["serve_overload_shed"] = overload.counters.counts["jobs_shed"]
    out["serve_overload_rejected_submits"] = ov_rejected
    out["serve_overload_admitted"] = len(ov_jids)
    if ov_lat:
        out.update(pcts(ov_lat, "serve_overload"))
        out["serve_overload_p99_within_2x"] = (
            out["serve_overload_p99_ms"] <= 2.0 * out["serve_p99_ms"]
        )
    if probe is not None:
        pr = probe()
        if pr:
            out["serve_throughput_normalized"] = round(
                out["serve_throughput_solves_per_sec"] / pr, 6)
    return out


def bench_fleet(args, probe=None):
    """Replicated solve fleet (ISSUE 11): the PR 6 Poisson trace —
    same seeded arrival process, same mixed-shape graph-coloring
    family as the serve leg — replayed against 1, 2 and 4 thread-
    hosted SolveService replicas behind the signature router, then a
    2-replica run with ``kill_replica`` injected mid-trace.

    Reported:

    * ``fleet_<n>_jobs_per_sec`` + p50/p99 latency per replica count
      (latency vs the SCHEDULED arrival, like the serve leg) and the
      ``fleet_scaling_<n>x`` ratios — the jobs/s + tail-latency
      scaling curve of the horizontal tier.  On a single-CPU host the
      replicas share one core so near-flat scaling is expected; the
      curve's job is to pin the coordination overhead (routing,
      journal streaming, supervision) stays small, and on parallel
      backends the same harness measures real scale-out;
    * ``fleet_bitmatch`` — every job of every leg must equal its
      standalone solve exactly (the determinism contract survives
      replication);
    * the chaos pin: ``fleet_kill_*`` — with a replica killed
      mid-trace, every in-flight job completes on a peer
      (``fleet_kill_reseated``), results stay bit-identical to the
      unfailed run, and ``fleet_rto_s`` is the finite recovery-time
      objective (kill detection -> last orphaned job completed
      elsewhere).  Checkpoint re-seats are counted so the journal
      actually being USED is visible, not assumed.
    """
    import shutil
    import tempfile

    from pydcop_tpu.batch.engine import BatchItem, adapter_for
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.runtime.faults import Fault, FaultPlan
    from pydcop_tpu.serve import SolveFleet

    n_jobs = args.serve_jobs
    rate = args.serve_rate
    max_cycles = 200
    sizes = (args.serve_vars, args.serve_vars // 2)
    dcops = []
    for i in range(n_jobs):
        V = sizes[i % len(sizes)]
        dcops.append(generate_graph_coloring(
            n_variables=V, n_colors=args.colors, n_edges=V * 3,
            soft=True, n_agents=1, seed=300 + i,
        ))
    rng = np.random.default_rng(args.serve_seed)
    inter = rng.exponential(1.0 / rate, n_jobs)
    inter[0] = 0.0
    offsets = np.cumsum(inter)
    adapter = adapter_for("dsa")

    # the unfailed anchor: every fleet result must bit-match the
    # standalone solve of its (instance, seed)
    baseline = [
        adapter.build_spec(BatchItem(d, "dsa", seed=i)).solver.run(
            max_cycles=max_cycles
        )
        for i, d in enumerate(dcops)
    ]

    def replay(fleet):
        """Submit the trace, wait for every result; returns
        (latencies vs scheduled arrival, results, wall)."""
        t0 = time.perf_counter()
        jids = []
        for i, d in enumerate(dcops):
            now = time.perf_counter() - t0
            if now < offsets[i]:
                time.sleep(offsets[i] - now)
            jids.append((fleet.submit(d, "dsa", seed=i),
                         time.perf_counter() - t0))
        lat, results = [], []
        for i, (jid, submitted) in enumerate(jids):
            res = fleet.result(jid, timeout=300)
            results.append(res)
            lat.append((submitted + res.time) - offsets[i])
        wall = max(
            s + r.time for (_j, s), r in zip(jids, results)
        )
        return lat, results, wall

    def pcts(lat, prefix):
        return {
            f"{prefix}_p50_ms": round(
                float(np.percentile(lat, 50)) * 1e3, 1),
            f"{prefix}_p99_ms": round(
                float(np.percentile(lat, 99)) * 1e3, 1),
        }

    out = {
        "fleet_jobs": n_jobs,
        "fleet_rate_jobs_per_sec": rate,
        "fleet_arrival_seed": args.serve_seed,
    }
    bitmatch = True
    for n in (1, 2, 4):
        fleet = SolveFleet(replicas=n, lanes=args.serve_lanes,
                           max_cycles=max_cycles)
        fleet.prewarm([(d, "dsa") for d in dcops], block=True)
        fleet.start()
        lat, results, wall = replay(fleet)
        fleet.stop(drain=False)
        bitmatch = bitmatch and all(
            r.cost == b.cost and r.cycle == b.cycle
            and r.assignment == b.assignment
            for r, b in zip(results, baseline)
        )
        out[f"fleet_{n}_jobs_per_sec"] = round(n_jobs / wall, 2)
        out.update(pcts(lat, f"fleet_{n}"))
    for n in (2, 4):
        out[f"fleet_scaling_{n}x"] = round(
            out[f"fleet_{n}_jobs_per_sec"] / out["fleet_1_jobs_per_sec"],
            2,
        )
    out["fleet_bitmatch"] = bitmatch

    # -- the chaos pin: kill one of two replicas mid-trace; every
    # in-flight job must complete on the peer, bit-identical, with a
    # finite recovery-time objective.  Tick-driven (the unit tests'
    # idiom) so the kill DETERMINISTICALLY lands while the doomed
    # replica holds checkpointed in-flight work — a wall-clock-timed
    # kill on a fast host can fire into an already-drained fleet and
    # measure nothing.
    jd = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        plan = FaultPlan(faults=[Fault(
            kind="kill_replica", replica=0, cycle=4,
        )])
        fleet = SolveFleet(replicas=2, lanes=args.serve_lanes,
                           max_cycles=max_cycles, journal_dir=jd,
                           checkpoint_every=1, fault_plan=plan)
        fleet.prewarm([(d, "dsa") for d in dcops], block=True)
        jids = [fleet.submit(d, "dsa", seed=i)
                for i, d in enumerate(dcops)]
        for _ in range(2000):
            if not fleet.tick():
                break
        results = [fleet.result(j, timeout=10) for j in jids]
        m = fleet.metrics()
        out["fleet_kill_all_completed"] = all(
            r.status == "FINISHED" for r in results
        )
        out["fleet_kill_bitmatch"] = all(
            r.cost == b.cost and r.cycle == b.cycle
            and r.assignment == b.assignment
            for r, b in zip(results, baseline)
        )
        out["fleet_kill_reseated"] = m["fleet"]["jobs_reseated"]
        out["fleet_kill_checkpoint_reseats"] = (
            m["fleet"]["reseat_checkpoint_hits"]
        )
        out["fleet_kill_replicas_down"] = m["fleet"]["replicas_down"]
        rtos = [r["rto_s"] for r in m["recoveries"]
                if r.get("rto_s") is not None]
        out["fleet_rto_s"] = round(max(rtos), 4) if rtos else None
    finally:
        shutil.rmtree(jd, ignore_errors=True)
    if probe is not None:
        pr = probe()
        if pr:
            out["fleet_throughput_normalized"] = round(
                out["fleet_1_jobs_per_sec"] / pr, 6)
    return out


def bench_pfleet(args, probe=None):
    """Process fleet (ISSUE 16): the fleet leg's Poisson trace
    replayed against 1, 2 and 4 replica CHILD PROCESSES behind the
    socket journal — real failure domains instead of threads.

    Reported:

    * ``pfleet_<n>_jobs_per_sec`` + p50/p99 latency per process count
      and the ``pfleet_scaling_<n>x`` ratios.  Unlike the thread
      fleet, each replica owns a whole interpreter (no shared GIL), so
      on multi-core hosts this curve measures REAL scale-out plus the
      socket/serialization overhead of crossing the process boundary;
    * ``pfleet_bitmatch`` — every job equals its standalone solve
      (determinism survives the YAML file-trip and the JSON wire);
    * ``pfleet_kill_*`` / ``pfleet_rto_s`` — a real ``kill -9``
      lands on a whole replica while it holds in-flight jobs: every
      job still completes bit-identically, the orphans re-seat, the
      RTO is finite, the watchdog relaunches the slot;
    * ``pfleet_cold_join_compiles`` — a replica cold-joined after the
      chaos run prewarms purely from the shared artifact store: the
      pin is ZERO XLA compiles (``misses == 0``) before its first job.

    Legs after the first bring their replicas up from the previous
    leg's exported artifacts (copied into the fresh journal dir), so
    the curve measures serving, not recompiles.
    """
    import shutil
    import signal
    import tempfile

    from pydcop_tpu.batch.engine import BatchItem, adapter_for
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.serve import ProcessFleet
    from pydcop_tpu.serve.procfleet import ARTIFACT_SUBDIR

    n_jobs = args.serve_jobs
    rate = args.serve_rate
    max_cycles = 200
    sizes = (args.serve_vars, args.serve_vars // 2)
    root = tempfile.mkdtemp(prefix="bench_pfleet_")
    paths, dcops = [], []
    try:
        for i in range(n_jobs):
            V = sizes[i % len(sizes)]
            d = generate_graph_coloring(
                n_variables=V, n_colors=args.colors, n_edges=V * 3,
                soft=True, n_agents=1, seed=300 + i,
            )
            p = os.path.join(root, f"job{i:03d}.yaml")
            with open(p, "w") as f:
                f.write(dcop_yaml(d))
            paths.append(p)
            # jobs cross the process boundary by YAML path: the
            # baseline must solve the same FILE-TRIPPED instance the
            # replicas load
            dcops.append(load_dcop_from_file([p]))
        adapter = adapter_for("dsa")
        baseline = [
            adapter.build_spec(BatchItem(d, "dsa", seed=i)).solver.run(
                max_cycles=max_cycles
            )
            for i, d in enumerate(dcops)
        ]
        # the service POOLS prewarm targets by (algo, params, shape
        # family): both generated sizes are binary graph-coloring at
        # the same D, so they share ONE pooled runner.  The readiness
        # polls below must expect the pooled count, not the target
        # count.
        expected_runners = len({
            adapter.build_spec(BatchItem(dcops[i], "dsa",
                                         seed=i)).dims.family_key
            for i in (0, 1)
        })
        rng = np.random.default_rng(args.serve_seed)
        inter = rng.exponential(1.0 / rate, n_jobs)
        inter[0] = 0.0
        offsets = np.cumsum(inter)

        def submit_trace(fleet, tick=False):
            t0 = time.perf_counter()
            jids = []
            for i, d in enumerate(dcops):
                now = time.perf_counter() - t0
                while not tick and now < offsets[i]:
                    time.sleep(min(0.005, offsets[i] - now))
                    now = time.perf_counter() - t0
                while tick and now < offsets[i]:
                    fleet.tick()
                    now = time.perf_counter() - t0
                jids.append((
                    fleet.submit(d, "dsa", seed=i,
                                 source_file=paths[i]),
                    time.perf_counter() - t0,
                ))
            return jids

        def prewarm_all(fleet):
            """Warm EVERY replica for both job shapes before the
            trace clock starts (the thread leg's block=True twin)."""
            targets = [(paths[0], "dsa", {}), (paths[1], "dsa", {})]
            names = list(fleet.router.routable())
            for name in names:
                fleet.handle(name).service.prewarm(targets)
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                fleet.tick()
                if all(
                    fleet.handle(n).service.cache.stats()
                    .get("entries", 0) >= expected_runners
                    for n in names
                ):
                    return True
                time.sleep(0.02)
            return False

        def drain(fleet):
            for _ in range(60000):
                if not fleet.tick():
                    return
                time.sleep(0.005)

        def seed_artifacts(jd):
            src = os.path.join(art_src, ARTIFACT_SUBDIR) \
                if art_src else None
            if src and os.path.isdir(src):
                shutil.copytree(
                    src, os.path.join(jd, ARTIFACT_SUBDIR),
                    dirs_exist_ok=True,
                )

        def pcts(lat, prefix):
            return {
                f"{prefix}_p50_ms": round(
                    float(np.percentile(lat, 50)) * 1e3, 1),
                f"{prefix}_p99_ms": round(
                    float(np.percentile(lat, 99)) * 1e3, 1),
            }

        out = {
            "pfleet_jobs": n_jobs,
            "pfleet_rate_jobs_per_sec": rate,
            "pfleet_arrival_seed": args.serve_seed,
        }
        bitmatch = True
        art_src = None
        for n in (1, 2, 4):
            jd = os.path.join(root, f"fleet{n}")
            os.makedirs(jd, exist_ok=True)
            seed_artifacts(jd)
            fleet = ProcessFleet(replicas=n, lanes=args.serve_lanes,
                                 max_cycles=max_cycles,
                                 journal_dir=jd)
            try:
                if not fleet.wait_ready(timeout=300):
                    raise RuntimeError("replicas never ready")
                prewarm_all(fleet)
                t0 = time.perf_counter()
                jids = submit_trace(fleet, tick=True)
                drain(fleet)
                results = [fleet.result(j, timeout=300)
                           for j, _s in jids]
                wall = time.perf_counter() - t0
                lat = [
                    (s + r.time) - offsets[i]
                    for i, ((_j, s), r) in enumerate(zip(jids, results))
                ]
            finally:
                fleet.stop(drain=False)
            bitmatch = bitmatch and all(
                r.cost == b.cost and r.cycle == b.cycle
                and r.assignment == b.assignment
                for r, b in zip(results, baseline)
            )
            out[f"pfleet_{n}_jobs_per_sec"] = round(n_jobs / wall, 2)
            out.update(pcts(lat, f"pfleet_{n}"))
            art_src = jd
        for n in (2, 4):
            out[f"pfleet_scaling_{n}x"] = round(
                out[f"pfleet_{n}_jobs_per_sec"]
                / out["pfleet_1_jobs_per_sec"], 2,
            )
        out["pfleet_bitmatch"] = bitmatch

        # -- chaos: a REAL ``kill -9`` of replica 0 with the trace in
        # flight; survivors re-seat and finish bit-identically.  The
        # plan-driven ``kill_process`` path is pinned by the chaos
        # tests; here the SIGKILL is delivered directly once the
        # victim holds in-flight jobs, so the re-seat count and the
        # RTO measurement are never vacuous (a planned tick number
        # can fire during the prewarm ticks, before any submission).
        jd = os.path.join(root, "fleet_kill")
        os.makedirs(jd, exist_ok=True)
        seed_artifacts(jd)
        fleet = ProcessFleet(replicas=2, lanes=args.serve_lanes,
                             max_cycles=max_cycles, journal_dir=jd,
                             checkpoint_every=1, backoff_base=0.1)
        try:
            if not fleet.wait_ready(timeout=300):
                raise RuntimeError("replicas never ready")
            prewarm_all(fleet)
            jids = [
                fleet.submit(d, "dsa", seed=i, source_file=paths[i])
                for i, d in enumerate(dcops)
            ]
            victim = fleet.handle(0)
            t_kill = time.monotonic()
            while time.monotonic() - t_kill < 10.0:
                fleet.tick()
                if victim.service.tick() \
                        and time.monotonic() - t_kill >= 0.5:
                    break  # the victim is mid-solve: kill it now
                time.sleep(0.005)
            os.kill(victim.proc.pid, signal.SIGKILL)
            drain(fleet)
            results = [fleet.result(j, timeout=300) for j in jids]
            m = fleet.metrics()
            out["pfleet_kill_all_completed"] = all(
                r.status == "FINISHED" for r in results
            )
            out["pfleet_kill_bitmatch"] = all(
                r.cost == b.cost and r.cycle == b.cycle
                and r.assignment == b.assignment
                for r, b in zip(results, baseline)
            )
            out["pfleet_kill_reseated"] = m["fleet"]["jobs_reseated"]
            out["pfleet_kill_replicas_down"] = (
                m["fleet"]["replicas_down"]
            )
            out["pfleet_kill_relaunched"] = (
                m["fleet"]["replicas_relaunched"]
            )
            rtos = [r["rto_s"] for r in m["recoveries"]
                    if r.get("rto_s") is not None]
            out["pfleet_rto_s"] = round(max(rtos), 4) if rtos else None

            # -- cold join: a replica added AFTER the chaos run warms
            # purely from the shared artifact store — zero XLA compiles
            name = fleet.add_replica()
            fleet.wait_ready(timeout=300)
            hc = fleet.handle(name)
            hc.service.prewarm([(paths[0], "dsa", {}),
                                (paths[1], "dsa", {})])
            deadline = time.monotonic() + 300
            stats = {}
            while time.monotonic() < deadline:
                fleet.tick()
                stats = hc.service.cache.stats()
                if stats.get("entries", 0) >= expected_runners:
                    break
                time.sleep(0.02)
            out["pfleet_cold_join_runners"] = stats.get("entries", 0)
            out["pfleet_cold_join_compiles"] = stats.get("misses", -1)
            out["pfleet_cold_join_artifact_hits"] = stats.get(
                "artifact_hits", 0
            )
        finally:
            fleet.stop(drain=False)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    if probe is not None:
        pr = probe()
        if pr:
            out["pfleet_throughput_normalized"] = round(
                out["pfleet_1_jobs_per_sec"] / pr, 6)
    return out


def bench_churn(args, probe=None):
    """Warm-repair churn recovery (ISSUE 8): a seeded sustained
    mutation stream against a LIVE instance — time-to-recover-cost per
    mutation and the repair retrace count (MUST be 0), warm vs cold.

    Two sub-legs:

    * ``maxsum`` at ``--churn-vars`` (default 100k) variables: the
      kernel-level warm layout (ops/headroom operand pytree riding the
      jitted chunk as an ARGUMENT).  A mutation is one ``.at[].set``
      write on the factor slab; time-to-recover-cost is the wall time
      of a fixed 3-chunk (30-cycle) re-convergence window after the
      mutation — the same window for warm and cold, so the comparison
      isolates exactly the mutation overhead (zero for warm, repack +
      XLA recompile for cold).  The COLD baseline replays the same
      stream through a fresh jit closure per mutation (tables baked as
      constants — exactly what the cold engines do), state carried,
      recompile included; it runs a capped number of mutations
      (compile-bound) and reports the per-mutation mean.
    * ``mgm`` solver-level at 2000 vars through
      algorithms/warm.build_warm_solver + apply_mutations — the
      local-search engine of the acceptance criterion, with
      ``trace_count()`` pinned at its post-warmup value.

    ``churn_speedup`` (cold mean / warm mean) is a same-process ratio,
    so tunnel/host drift cancels (BENCHREF.md "Churn recovery"); the
    absolute recover times are additionally probe-normalized like every
    other leg.
    """
    import jax
    import jax.numpy as jnp

    from pydcop_tpu.ops.compile import compile_binary_from_arrays
    from pydcop_tpu.ops.headroom import (
        make_operands, operand_view, reserve_headroom,
    )
    from pydcop_tpu.ops.maxsum_kernels import maxsum_cycle
    from pydcop_tpu.ops.compile import total_cost

    V = args.churn_vars
    D = 4
    n_mut = args.churn_mutations
    rng = np.random.default_rng(77)
    # ring lattice: every var constrained to its 2 successors
    ei = np.concatenate([np.arange(V), np.arange(V)])
    ej = np.concatenate([(np.arange(V) + 1) % V, (np.arange(V) + 2) % V])
    mats = rng.uniform(0.0, 5.0, (ei.size, D, D)).astype(np.float32)
    base = compile_binary_from_arrays(ei, ej, mats, V)
    cap, layout = reserve_headroom(
        None, graph="factor", headroom=0.1, tensors=base,
    )
    ops0 = make_operands(cap)
    chunk = 10
    traces = {"n": 0}

    @jax.jit
    def run_chunk(q, r, ops):
        traces["n"] += 1
        view = operand_view(cap, ops)

        def body(carry, _):
            q, r = carry
            q2, r2, _, vals = maxsum_cycle(view, q, r, damping=0.7)
            return (q2, r2), vals

        (q, r), vals = jax.lax.scan(body, (q, r), None, length=chunk)
        return q, r, vals[-1], total_cost(view, vals[-1])

    E = int(cap.n_edges)
    z = jnp.zeros((E, cap.max_domain_size), dtype=jnp.float32)

    def solve_to_target(q, r, ops, target, max_chunks=60):
        cost = None
        for _ in range(max_chunks):
            q, r, vals, cost = run_chunk(q, r, ops)
            if target is not None and float(cost) <= target:
                break
        return q, r, float(cost)

    # converge the base instance (includes the ONE compile)
    t0 = time.perf_counter()
    q, r, base_cost = solve_to_target(z, z, ops0, None)
    out = {"churn_vars": V, "churn_mutations": n_mut,
           "churn_base_solve_s": round(time.perf_counter() - t0, 3)}
    traces_after_warmup = traces["n"]

    mut_rows = rng.integers(0, ei.size, size=n_mut)
    mut_tabs = rng.uniform(0.0, 5.0, (n_mut, D, D)).astype(np.float32)

    # -- warm stream: in-place slab writes, shared compiled chunk ------
    ops = ops0
    recover = []
    for m in range(n_mut):
        t1 = time.perf_counter()
        tl = list(ops["tensors"])
        tl[0] = tl[0].at[int(mut_rows[m])].set(jnp.asarray(mut_tabs[m]))
        ops = dict(ops, tensors=tuple(tl))
        q, r, cost = solve_to_target(q, r, ops, target=None,
                                     max_chunks=3)
        recover.append(time.perf_counter() - t1)
    out["churn_warm_recover_s_mean"] = round(
        float(np.mean(recover)), 5)
    out["churn_warm_recover_s_p99"] = round(
        float(np.percentile(recover, 99)), 5)
    out["churn_warm_retraces"] = traces["n"] - traces_after_warmup
    out["churn_warm_cost_final"] = round(cost, 2)

    # -- cold baseline: fresh jit closure per mutation (tables baked
    # as constants, the cold engines' shape), state carried -----------
    n_cold = min(args.churn_cold_mutations, n_mut)
    mats_cold = mats.copy()
    cold_q = jnp.zeros((2 * ei.size, D), dtype=jnp.float32)
    cold_r = cold_q
    cold = []
    for m in range(n_cold):
        t1 = time.perf_counter()
        mats_cold[int(mut_rows[m]) % ei.size] = mut_tabs[m]
        t_cold = compile_binary_from_arrays(ei, ej, mats_cold, V)

        @jax.jit
        def run_cold(q, r, _t=t_cold):
            def body(carry, _):
                q, r = carry
                q2, r2, _, vals = maxsum_cycle(_t, q, r, damping=0.7)
                return (q2, r2), vals

            (q, r), vals = jax.lax.scan(
                body, (q, r), None, length=chunk)
            return q, r, total_cost(_t, vals[-1])

        for _ in range(3):
            cold_q, cold_r, c = run_cold(cold_q, cold_r)
        jax.block_until_ready(c)
        cold.append(time.perf_counter() - t1)
    out["churn_cold_mutations"] = n_cold
    out["churn_cold_recover_s_mean"] = round(float(np.mean(cold)), 5)
    if out["churn_warm_recover_s_mean"] > 0:
        out["churn_speedup"] = round(
            out["churn_cold_recover_s_mean"]
            / out["churn_warm_recover_s_mean"], 2)
        out["churn_warm_5x_better"] = (
            out.get("churn_speedup", 0.0) >= 5.0)

    # -- local-search sub-leg: warm MGM solver, retraces pinned --------
    from pydcop_tpu.algorithms.warm import build_warm_solver
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.runtime.repair import perturbed_constraint

    dcop = generate_graph_coloring(
        n_variables=2000, n_colors=3, n_edges=6000, soft=True,
        n_agents=1, seed=5,
    )
    solver = build_warm_solver(dcop, algo="mgm", seed=5, headroom=0.1)
    solver.run(chunk=16)
    t_base = solver.trace_count()
    names = sorted(dcop.constraints)
    rng2 = np.random.default_rng(99)
    t1 = time.perf_counter()
    for m in range(min(n_mut, 50)):
        name = names[int(rng2.integers(len(names)))]
        new_c = perturbed_constraint(dcop.constraints[name], seed=m)
        solver.change_factor_function(new_c)
        solver.run(resume=True, cycles=16, chunk=16)
    out["churn_mgm_stream_s"] = round(time.perf_counter() - t1, 3)
    out["churn_mgm_retraces"] = solver.trace_count() - t_base
    if probe is not None:
        pr = probe()
        if pr:
            out["churn_warm_recover_normalized"] = round(
                out["churn_warm_recover_s_mean"] * pr, 6)
    return out


def bench_memo(args, probe=None):
    """Cross-request solution cache (ISSUE 18): the hit taxonomy on a
    seeded duplicate/variant/novel request trace, warm-vs-cold request
    latency (p50/p99, drift-normalized), the k-edit variant speedup
    pin (``memo_variant_3x_better``), the per-warm-algo never-worse
    booleans, and the fleet mid-trace-kill bit-match
    (docs/serving.rst "Solution cache and warm-start serving").

    The cold reference runs the SERVICE cycle budget (``max_cycles``
    2000, the deployment default) — the comparison is "what would this
    request have cost without the cache", not a truncated solve.  The
    first variant serve pays a one-time YAML parse + warm-kernel
    compile; like every other leg, one warmup request of each kind
    runs before the timed trace so the steady-state rates are
    compile-free.  ``churn_speedup``-style same-process ratios cancel
    host drift; the absolute latencies are probe-normalized on top.
    """
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.runtime.repair import perturbed_constraint
    from pydcop_tpu.runtime.run import solve_result
    from pydcop_tpu.serve.memo import MemoCache, MemoConfig

    V = args.memo_vars
    algo = "mgm"
    cold_cycles = 2000          # the serve-tier default budget
    out = {"memo_vars": V, "memo_algo": algo,
           "memo_cold_cycles": cold_cycles}

    def inst(seed, n=V):
        return generate_graph_coloring(
            n_variables=n, n_colors=3, n_edges=2 * n - 2, soft=True,
            seed=seed)

    def edit(d, edit_seed, which=2):
        name = sorted(d.constraints)[which % len(d.constraints)]
        d.constraints[name] = perturbed_constraint(
            d.constraints[name], seed=edit_seed)
        return d

    def cold(d, cycles=cold_cycles):
        return solve_result(d, algo, seed=1, cycles=cycles)

    # -- warmup: pay the compiles + the one-time YAML parse OUTSIDE
    # the timed trace (seed 900 never reappears below) ----------------
    wcache = MemoCache(MemoConfig())
    w = inst(900)
    wcache.memoize(wcache.probe(w, algo, seed=1), w, cold(w))
    wv = edit(inst(900), 901)
    wcache.serve_variant(wcache.probe(wv, algo, seed=1), wv)

    # -- seeded trace: 4 novel bases, 8 exact duplicates, 4 one-edit
    # variants — the "millions of users" shape in miniature -----------
    bases = list(range(4))
    trace = ([("novel", s, None) for s in bases]
             + [("dup", s, None) for s in bases]
             + [("variant", s, 100 + i) for i, s in enumerate(bases)]
             + [("dup", s, None) for s in bases])
    cache = MemoCache(MemoConfig())
    lat = {"exact": [], "variant": [], "miss": []}
    cold_variant = []
    never_worse_trace = []
    for kind, s, es in trace:
        d = inst(s) if es is None else edit(inst(s), es)
        t0 = time.perf_counter()
        p = cache.probe(d, algo, seed=1)
        if p.kind == "exact":
            res = cache.result_from_entry(p.entry, p)
        elif p.kind == "variant":
            res = cache.serve_variant(p, d)
            if res is None:        # never-worse fallback: solve cold
                res = cold(d)
                cache.memoize(p, d, res)
        else:
            res = cold(d)
            cache.memoize(p, d, res)
        lat[p.kind].append(time.perf_counter() - t0)
        if kind == "variant":
            # the cold reference for the SAME variant request,
            # measured in the same process right after the warm serve
            t1 = time.perf_counter()
            rc = cold(d)
            cold_variant.append(time.perf_counter() - t1)
            if res.cost is not None and rc.cost is not None:
                never_worse_trace.append(res.cost <= rc.cost + 1e-6)

    st = cache.stats()
    n_req = len(trace)
    out["memo_trace_requests"] = n_req
    out["memo_hits_exact"] = st["hits_exact"]
    out["memo_hits_variant"] = st["hits_variant"]
    out["memo_misses"] = st["misses"]
    out["memo_cold_fallbacks"] = st["variant_cold_fallbacks"]
    out["memo_hit_rate"] = round(
        (st["hits_exact"] + st["hits_variant"]) / n_req, 4)
    for k in ("exact", "variant", "miss"):
        if lat[k]:
            out[f"memo_{k}_p50_ms"] = round(
                float(np.percentile(lat[k], 50)) * 1000, 3)
            out[f"memo_{k}_p99_ms"] = round(
                float(np.percentile(lat[k], 99)) * 1000, 3)
    if lat["variant"] and cold_variant:
        warm_mean = float(np.mean(lat["variant"]))
        cold_mean = float(np.mean(cold_variant))
        out["memo_variant_speedup"] = round(cold_mean / warm_mean, 2)
        out["memo_variant_3x_better"] = (
            out["memo_variant_speedup"] >= 3.0)
    out["memo_never_worse_trace"] = (
        bool(never_worse_trace) and all(never_worse_trace))
    if probe is not None:
        pr = probe()
        if pr:
            for k in ("exact", "variant", "miss"):
                if lat[k]:
                    out[f"memo_{k}_normalized"] = round(
                        float(np.mean(lat[k])) * pr, 6)

    # -- never-worse guarantee, pinned per warm-capable algo (small
    # instances: the booleans are the product, not the rates) ---------
    for a in ("mgm", "dsa", "adsa", "maxsum"):
        c = MemoCache(MemoConfig())
        d = inst(11, n=60)
        p = c.probe(d, a, seed=1)
        c.memoize(p, d, solve_result(d, a, seed=1, cycles=300))
        v = edit(inst(11, n=60), 33)
        pv = c.probe(v, a, seed=1)
        okflag = True
        if pv.kind == "variant":
            r = c.serve_variant(pv, v)
            if r is not None:      # served: must not regress cold
                rc = solve_result(v, a, seed=1, cycles=300)
                okflag = (r.cost is not None and rc.cost is not None
                          and r.cost <= rc.cost + 1e-6)
            # r is None = cold fallback: the guarantee held by refusal
        out[f"memo_never_worse_{a}"] = bool(okflag)

    # -- fleet mid-trace kill: entries shared through the journal tap
    # survive a replica kill — duplicates of EVERY base (including
    # those solved on the dead replica) still exact-hit bit-identically
    # on the survivor ------------------------------------------------
    from pydcop_tpu.serve.fleet import SolveFleet

    t0 = time.perf_counter()
    fl = SolveFleet(replicas=2, lanes=2, max_cycles=cold_cycles,
                    memo=MemoConfig())

    def drain(jid, max_ticks=3000):
        for _ in range(max_ticks):
            fl.tick()
            try:
                return fl.result(jid, timeout=0.01)
            except TimeoutError:
                continue
        return fl.result(jid, timeout=1)

    try:
        first = {s: drain(fl.submit(inst(s), algo, seed=1))
                 for s in bases}
        fl.handle(0).kill()            # mid-trace replica kill
        bitmatch, kill_hits = True, 0
        for s in bases:
            r = drain(fl.submit(inst(s), algo, seed=1))
            if (r.memo or {}).get("hit") == "exact":
                kill_hits += 1
            if (r.assignment != first[s].assignment
                    or r.cost != first[s].cost):
                bitmatch = False
    finally:
        fl.stop(drain=False)
    out["memo_fleet_kill_exact_hits"] = kill_hits
    out["memo_fleet_kill_bitmatch"] = bool(bitmatch)
    out["memo_fleet_wall_s"] = round(time.perf_counter() - t0, 3)
    return out


def bench_precision(args, probe=None):
    """Mixed-precision storage tiers (ISSUE 19): per-tier harness
    throughput + final cost for maxsum and mgm on one soft
    graph-coloring instance, the bf16 runs checked against the ONE
    declared statistical gate (``ops.precision.BF16_COST_RTOL/ATOL``
    — the same pair the equivalence tests assert), and the
    collective-payload byte cut of the bf16 sharded wire cells vs
    their f32 twins, read off the audit registry's jaxpr walk — the
    same ``max_collective_payload_bytes`` the per-tier budgets
    enforce, NOT an itemsize estimate (docs/performance.rst "Mixed
    precision tiers").

    Throughput ratios are same-process (host drift cancels like
    ``churn_speedup``); one warmup run per (algo, tier) pays the
    compile outside the timed window.  The gate here is the ONE-SIDED
    form of the declared pair, over 3-seed mean final costs: loopy
    max-sum at bench scale is chaotic enough that bf16's rounding acts
    as beneficial noise and lands BELOW f32 by more than RTOL — for a
    minimization tier that is a pass, not a failure, so the check is
    ``mean(bf16) <= mean(f32) + max(ATOL, RTOL*|mean(f32)|)`` (the
    small-instance equivalence tests keep the two-sided form).  The
    int8 rows ride along for the table-byte story (4 B -> 1 B per
    entry is structural — ``precision_int8_table_bytes_cut_x`` is
    exact, not measured); the float-valued coloring tables here are
    deliberately OUTSIDE the int8 losslessness rule, so its costs are
    reported but not gated — ``solve --auto`` would mask int8 on this
    instance.
    """
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.precision import BF16_COST_ATOL, BF16_COST_RTOL
    from pydcop_tpu.runtime.run import solve_result

    V = 200
    cycles = 200
    # headline slot reserved FIRST: single-leg promotion scans extra
    # in insertion order, and the per-tier throughput keys would match
    # the generic "_cycles_per" pattern before the real headline
    out = {"precision_payload_cut_x": 0.0,
           "precision_vars": V, "precision_cycles": cycles}
    d = generate_graph_coloring(
        n_variables=V, n_colors=3, n_edges=2 * V - 2, soft=True, seed=7)

    gates_ok = True
    seeds = (1, 2, 3)
    for algo in ("maxsum", "mgm"):
        costs = {}
        for tier in ("f32", "bf16", "int8"):
            params = {} if tier == "f32" else {"precision": tier}
            solve_result(d, algo, cycles=50, seed=1, chunk=50,
                         algo_params=params)      # warmup: compile
            t0 = time.perf_counter()
            r = solve_result(d, algo, cycles=cycles, seed=1, chunk=50,
                             algo_params=params)
            dt = time.perf_counter() - t0
            out[f"precision_{algo}_{tier}_cycles_per_s"] = round(
                cycles / dt, 1)
            out[f"precision_{algo}_{tier}_cost"] = round(
                float(r.cost), 3)
            if tier in ("f32", "bf16"):
                # 3-seed mean for the gate (compile already warm; the
                # extra seeds reuse the staged kernels)
                cs = [float(r.cost)] + [
                    float(solve_result(
                        d, algo, cycles=cycles, seed=s, chunk=50,
                        algo_params=params).cost)
                    for s in seeds[1:]
                ]
                costs[tier] = sum(cs) / len(cs)
                out[f"precision_{algo}_{tier}_mean_cost"] = round(
                    costs[tier], 3)
        gate = max(BF16_COST_ATOL, BF16_COST_RTOL * abs(costs["f32"]))
        ok = bool(costs["bf16"] <= costs["f32"] + gate)
        out[f"precision_{algo}_bf16_within_gate"] = ok
        gates_ok = gates_ok and ok
    out["precision_bf16_within_gate"] = gates_ok

    # audited wire-byte cut: walk the SAME registry cells the per-tier
    # budgets gate (compact sharded maxsum + packed local search).  A
    # real mesh needs >1 device or the comm plan degenerates to width
    # 1 and the walk sees no collectives at all, so this runs on the
    # virtual 8-device CPU mesh in a subprocess (same pattern as the
    # sharded legs — XLA device count is fixed at process start).
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    audit_src = (
        "import json\n"
        "from pydcop_tpu.analysis import registry\n"
        "res = {}\n"
        "for label, f32_cell, bf16_cell in (\n"
        "    ('maxsum', 'sharded/maxsum/generic/exact',\n"
        "     'sharded/maxsum/generic/exact-bf16'),\n"
        "    ('mgm', 'sharded/mgm/packed/exact',\n"
        "     'sharded/mgm/packed/exact-bf16'),\n"
        "):\n"
        "    a = registry.audit_cell(f32_cell)\n"
        "    b = registry.audit_cell(bf16_cell)\n"
        "    res[label] = {\n"
        "        'f32': int(a.scorecard['max_collective_payload_bytes']),\n"
        "        'bf16': int(b.scorecard['max_collective_payload_bytes']),\n"
        "        'clean': not a.findings and not b.findings,\n"
        "    }\n"
        "print(json.dumps(res))\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", audit_src],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    if r.returncode != 0 or not r.stdout.strip():
        raise RuntimeError(
            "precision audit subprocess failed "
            f"(rc={r.returncode}): " + r.stderr.strip()[-400:]
        )
    audits = json.loads(r.stdout.strip().splitlines()[-1])
    ratios = []
    audits_clean = True
    for label, row in audits.items():
        out[f"precision_{label}_payload_bytes_f32"] = row["f32"]
        out[f"precision_{label}_payload_bytes_bf16"] = row["bf16"]
        audits_clean = audits_clean and bool(row["clean"])
        ratios.append(row["f32"] / max(row["bf16"], 1))
    out["precision_audits_clean"] = audits_clean
    out["precision_payload_cut_x"] = round(min(ratios), 2)
    out["precision_int8_table_bytes_cut_x"] = 4.0
    return out


def bench_auto(args, probe=None):
    """Learned-portfolio auto-selection (ISSUE 10): train the cost
    model on a seeded sweep of TRAINING families, then score a
    held-out suite (families excluded from training) three ways on the
    same per-cell measurements:

    * per-instance **oracle** — the best config's drift-normalized
      time-to-target (the lower bound no selector can beat);
    * every **fixed single config** of the grid, summed over the
      suite (a config that misses the target or is feasibility-masked
      on an instance is charged the dataset harness's miss penalty);
    * **auto** — the model's per-instance argmin.

    The acceptance headline is ``auto_speedup_vs_best_fixed`` (> 1 =
    auto's total beats EVERY fixed config) with the mean top-1 regret
    vs the oracle and the model's ranking report riding along; a real
    ``solve --auto`` runs per held-out instance too so the
    predicted-vs-actual gap audit (metrics['portfolio']) lands in the
    JSON (BENCHREF.md "Portfolio auto-selection")."""
    import tempfile

    import numpy as np

    from pydcop_tpu.portfolio.dataset import (
        InstanceSpec,
        PortfolioDataset,
        SweepSpec,
        make_probe,
        run_cell,
        run_sweep,
        split_holdout,
        training_matrix,
    )
    from pydcop_tpu.portfolio.features import featurize_detail
    from pydcop_tpu.portfolio.model import evaluate, train_model
    from pydcop_tpu.portfolio.select import (
        DEFAULT_GRID,
        feasible_grid,
        select_config,
        solve_auto,
    )

    grid = DEFAULT_GRID
    cycles, cell_timeout = 120, 25.0
    # training families span the structural axes the held-out suite
    # probes — ring-lattice-like (ising torus, grid coloring),
    # scale-free (gc scalefree ~ iot's preferential attachment) and
    # width-diverse random graphs — WITHOUT ever containing a
    # held-out family instance
    train_instances = [
        InstanceSpec(f, s, sd)
        for f, sizes in (("graphcoloring", (8, 14, 20)),
                         ("ising", (4, 5, 6)),
                         ("secp", (6, 9)),
                         ("meetingscheduling", (4, 6)))
        for s in sizes for sd in (0, 1)
    ] + [
        InstanceSpec("graphcoloring", s, sd,
                     params=(("graph_type", "scalefree"),
                             ("m_edge", 2), ("n_edges", None)))
        for s in (10, 18) for sd in (0, 1)
    ] + [
        InstanceSpec("graphcoloring", s * s, sd,
                     params=(("graph_type", "grid"), ("n_edges", None)))
        for s in (3, 4) for sd in (0, 1)
    ]
    # held out: two UNSEEN FAMILIES (smallworld, iot) plus an UNSEEN
    # SIZE of a training family (ising 7x7, width ~14 — its winner
    # flips away from the narrow-width choice, so a selector that
    # cannot generalize loses here) — the "families or sizes excluded
    # from training" suite of the acceptance criterion
    held_instances = [
        InstanceSpec("smallworld", s, 5) for s in (10, 16, 24)
    ] + [InstanceSpec("iot", s, 5) for s in (12, 20)] + [
        InstanceSpec("ising", 7, 7),
    ]

    out = {}
    tmp = tempfile.mkdtemp(prefix="bench_auto_")
    pf_probe = make_probe(repeat=max(2, args.repeat))
    sweep = run_sweep(
        SweepSpec(train_instances, grid, cycles=cycles,
                  timeout_s=cell_timeout),
        tmp, probe=pf_probe,
    )
    out["auto_train_cells"] = sweep["cells_run"]
    out["auto_train_sweep_s"] = sweep["wall_s"]
    ds = PortfolioDataset(tmp)
    X, y, gids, _keys = training_matrix(ds.rows())
    (trX, trY, tr_gids), _ = split_holdout(X, y, gids, [])
    model, hist = train_model(
        trX, trY, hidden=(64, 64), epochs=600, lr=2e-3, seed=0,
        group_ids=tr_gids,
        meta={"probe_rate": float(np.median([
            float(r.get("probe_rate") or 0) for r in ds.rows()
        ]))},
    )
    out["auto_train_rows"] = int(trX.shape[0])
    out["auto_train_loss"] = round(hist["final_loss"], 5)
    out["auto_train_rank_pairs"] = hist["rank_pairs"]

    # ---- held-out suite: measure every feasible config per instance
    held_rows = []
    selections = {}
    gaps = []
    for inst in held_instances:
        dcop = inst.build()
        features, info = featurize_detail(dcop)
        feasible, _masked = feasible_grid(grid, info)
        for cfg in feasible:
            rate = pf_probe()
            cell = run_cell(dcop, cfg, cycles, cell_timeout, inst.seed)
            held_rows.append({
                "key": f"{inst.key()}::{cfg.key()}",
                "instance": inst.key(),
                "config": cfg.as_dict(),
                "features": [float(v) for v in features],
                "probe_rate": rate,
                **cell,
            })
        sel = select_config(dcop, grid=grid, model=model,
                            features=features, info=info)
        selections[inst.key()] = sel.config.key()
        # the real front door, for the honesty audit in the JSON
        res = solve_auto(dcop, model=model, grid=grid, cycles=cycles,
                         timeout=cell_timeout, seed=inst.seed)
        pf = res.portfolio or {}
        if pf.get("gap_ratio") is not None:
            gaps.append(pf["gap_ratio"])

    hX, hy, hgids, hkeys = training_matrix(held_rows)
    # label per (instance, config key) in normalized-time units
    label = {}
    for k, gid, yy in zip(hkeys, hgids, hy):
        cfg_key = k.split("::", 1)[1]
        label[(gid, cfg_key)] = float(np.expm1(yy))
    insts = sorted(set(hgids))
    miss_charge = {
        gid: max(v for (g, _c), v in label.items() if g == gid)
        for gid in insts
    }
    fixed_totals = {}
    for cfg in grid:
        fixed_totals[cfg.key()] = round(sum(
            label.get((gid, cfg.key()), miss_charge[gid])
            for gid in insts
        ), 4)
    oracle_per = {
        gid: min(v for (g, _c), v in label.items() if g == gid)
        for gid in insts
    }
    auto_per = {
        gid: label.get((gid, selections[gid]), miss_charge[gid])
        for gid in insts
    }
    auto_total = round(sum(auto_per.values()), 4)
    oracle_total = round(sum(oracle_per.values()), 4)
    best_fixed = min(fixed_totals.values())
    out["auto_total_norm_time"] = auto_total
    out["auto_oracle_total_norm_time"] = oracle_total
    out["auto_best_fixed_total_norm_time"] = round(best_fixed, 4)
    out["auto_fixed_config_totals"] = fixed_totals
    out["auto_beats_all_fixed"] = bool(auto_total < best_fixed)
    out["auto_speedup_vs_best_fixed"] = round(
        best_fixed / auto_total, 4) if auto_total else 0.0
    out["auto_mean_top1_regret_ratio"] = round(float(np.mean([
        auto_per[g] / oracle_per[g] if oracle_per[g] > 0 else 1.0
        for g in insts
    ])), 4)
    out["auto_selections"] = selections
    # ranking report on the held-out groups (not just MSE)
    groups = []
    for gid in insts:
        idx = [i for i, g in enumerate(hgids) if g == gid]
        groups.append((hX[idx], hy[idx]))
    out["auto_holdout_eval"] = evaluate(model, groups)
    if gaps:
        out["auto_gap_ratio_mean"] = round(float(np.mean(gaps)), 4)
        out["auto_gap_ratio_worst"] = round(float(np.max(gaps)), 4)
    return out


def bench_twin(args, probe=None):
    """City-scale digital twin (ISSUE 12): the combined sustained
    scenario — seeded Poisson multi-tenant traffic with gold/silver/
    bronze deadline tiers through a replicated SolveFleet, concurrent
    warm-repair churn against a live tracking problem, the combined
    chaos plan (kill_replica + stall_tick + nan_lane +
    torn_journal_write + edit_factor), and --auto portfolio selection
    — scored by SLO attainment, twice on the SAME seeds:

    * ladder ON — the guardrail ladder (shed bronze → clamp silver
      chunks → reroute gold to the emptiest healthy replica) guards
      the gold floor; acceptance: gold attainment >= 0.99 under the
      chaos plan;
    * ladder OFF — identical scenario, ladder never escalates; the
      pin is that gold attainment measurably misses the floor, i.e.
      the ladder (not slack capacity) is what holds gold.

    Saturation is real compute contention: bronze jobs are large
    slow-converging coloring instances that dilute every tick while
    they run; shedding them is what buys gold its latency back.
    Bit-identity: every FINISHED job of the chaos run must equal its
    standalone solve exactly (mgm traffic — chunk-independent streams
    — so deadline-shrunk chunks cannot perturb results), the serve
    determinism contract surviving the full combined scenario
    (BENCHREF.md "City twin")."""
    import dataclasses as _dc

    from pydcop_tpu.generators import (
        generate_graph_coloring,
        generate_routing,
        generate_tracking,
        tracking_scenario,
    )
    from pydcop_tpu.scenario import (
        TierSpec,
        TwinJob,
        TwinRunner,
        default_chaos_plan,
        standalone_results,
    )

    seed = args.twin_seed
    n_jobs = args.twin_jobs
    max_cycles = 300
    tiers = (
        TierSpec("gold", priority=2, deadline_s=args.twin_gold_deadline,
                 floor=0.99, share=0.25),
        TierSpec("silver", priority=1,
                 deadline_s=args.twin_silver_deadline, floor=0.90,
                 share=0.25),
        TierSpec("bronze", priority=0,
                 deadline_s=args.twin_bronze_deadline, floor=0.50,
                 share=0.50),
    )
    rng = np.random.default_rng(seed)
    inter = rng.exponential(args.twin_interarrival, n_jobs)
    inter[0] = 0.0
    ticks = np.cumsum(inter).astype(int)
    # deterministic tier pattern (per 12: silver 4, gold 3, bronze 5):
    # a bronze-light prefix so the first gold flies nearly clean in
    # both arms (pre-engagement traffic is identical by construction),
    # ONE early bronze so the following silvers miss their tight
    # budget and engage the ladder before the backlog builds, and gold
    # spread through the trace so late gold rides the regime the
    # ladder (or its absence) created — the A/B's discriminating
    # samples
    pattern = ("silver", "silver", "gold", "bronze",
               "silver", "bronze", "silver", "bronze",
               "gold", "bronze", "gold", "bronze")
    jobs = []
    for i in range(n_jobs):
        tier = {t.name: t for t in tiers}[pattern[i % len(pattern)]]
        if tier.name == "gold":
            # small, fast — the protected tier; alternate the two new
            # hard-axis families
            if i % 2:
                dcop, fam = generate_routing(12, seed=1000 + i), "routing"
            else:
                dcop, fam = (
                    generate_tracking(16, n_targets=2, seed=1000 + i),
                    "tracking",
                )
        elif tier.name == "silver":
            V = 150
            dcop, fam = generate_graph_coloring(
                n_variables=V, n_colors=args.colors, n_edges=V * 3,
                soft=True, n_agents=1, seed=2000 + i,
            ), "coloring"
        else:
            V = args.twin_bronze_vars
            dcop, fam = generate_graph_coloring(
                n_variables=V, n_colors=args.colors, n_edges=V * 3,
                soft=True, n_agents=1, seed=3000 + i,
            ), "coloring"
        # bronze runs dsa at p=1.0: every improving variable flips
        # every cycle, so the walk never holds two stable chunks and
        # runs to the cycle cap — long-lived background load that
        # genuinely ACCUMULATES in the OFF arm while the ladder arm
        # sheds it.  Bronze never rides a deadline clamp (60 s
        # budget), so its chunk stream — and with it bit-identity — is
        # untouched.
        algo, params = (
            ("dsa", {"probability": 1.0})
            if tier.name == "bronze" else ("mgm", {})
        )
        jobs.append(TwinJob(
            index=i, dcop=dcop, family=fam, tier=tier.name,
            tenant=tier.name, seed=i, arrival_tick=int(ticks[i]),
            algo=algo, algo_params=params,
            label=f"{fam}:{tier.name}:{i}",
        ))

    # --auto arm: the portfolio selector (heuristic fallback without a
    # trained model) picks the GOLD tier's configs — the protected
    # traffic chooses its engine; silver/bronze stay the designed
    # background load the A/B depends on.  Batch-eligible picks
    # override the algo, every choice is recorded.
    auto_configs = []
    try:
        from pydcop_tpu.batch.engine import SUPPORTED_ALGOS
        from pydcop_tpu.portfolio.select import select_config

        for job in jobs:
            if job.tier != "gold":
                continue
            sel = select_config(job.dcop)
            job.config = sel.config.as_dict()
            auto_configs.append(
                {"label": job.label, "config": sel.config.key()}
            )
            if sel.config.algo in SUPPORTED_ALGOS:
                job.algo = sel.config.algo
                job.algo_params = dict(sel.config.algo_params())
    except Exception as e:
        auto_configs = [{"error": repr(e)}]

    side = max(4, int(round(args.twin_live_vars ** 0.5)))

    def one_run(ladder):
        run_jobs = [
            _dc.replace(j, jid=None, submitted_at=None, scored=False)
            for j in jobs
        ]
        live = generate_tracking(side * side, n_targets=3,
                                 seed=seed + 1)
        scen = tracking_scenario(live, args.twin_mutations)
        plan = default_chaos_plan(
            seed=seed, kill_tick=args.twin_kill_tick,
            stall_tick_at=4, nan_tick=18, churn_edit_ticks=(10, 18),
        )
        twin = TwinRunner(
            run_jobs, tiers, replicas=args.twin_replicas,
            lanes=args.twin_lanes,
            max_buckets=args.twin_max_buckets or None,
            max_cycles=max_cycles,
            fault_plan=plan, live_dcop=live, live_scenario=scen,
            ladder=ladder, ladder_min_samples=3, ladder_window=8,
            # ticks are the hysteresis clock and they are FAST: a
            # short hold would release mid-pressure and let bronze
            # leak back in (measured in the r06 shakedown)
            ladder_hold=30,
        )
        t0 = time.perf_counter()
        card = twin.run()
        return twin, card, time.perf_counter() - t0

    # throwaway warmup: absorb one-time process costs (imports, jit
    # warmup, allocator growth) so the FIRST measured arm is not the
    # one paying them — without this the ON arm (run first) reads
    # ~0.5 s slower on its early jobs than the identical OFF prefix
    warm_jobs = [
        _dc.replace(j, jid=None, submitted_at=None, scored=False)
        for j in jobs[:4]
    ]
    TwinRunner(
        warm_jobs, tiers, replicas=args.twin_replicas,
        lanes=args.twin_lanes, max_cycles=40,
    ).run(max_ticks=400)

    twin_on, card_on, wall_on = one_run(True)
    twin_off, card_off, wall_off = one_run(False)

    # the unfaulted anchor: FINISHED chaos-run jobs must be
    # bit-identical to standalone solves of the same (instance, algo,
    # seed)
    base = standalone_results(jobs, max_cycles=max_cycles)
    checked = mismatched = 0
    for label, res in twin_on.results.items():
        if res.status != "FINISHED":
            continue
        b = base[label]
        checked += 1
        if not (res.cost == b.cost and res.assignment == b.assignment):
            mismatched += 1

    def att(card, tier):
        return card["tiers"][tier]["attainment"]

    g_on, g_off = att(card_on, "gold"), att(card_off, "gold")
    out = {
        "twin_jobs": n_jobs,
        "twin_seed": seed,
        "twin_replicas": args.twin_replicas,
        "twin_live_vars": side * side,
        "twin_mutations": args.twin_mutations,
        "twin_wall_s_ladder_on": round(wall_on, 2),
        "twin_wall_s_ladder_off": round(wall_off, 2),
        "twin_gold_attainment_ladder_on": g_on,
        "twin_gold_attainment_ladder_off": g_off,
        "twin_gold_holds_floor": bool(
            g_on is not None and g_on >= 0.99
        ),
        "twin_ladder_effective": bool(
            g_on is not None and g_on >= 0.99
            and (g_off is None or g_off < 0.99)
        ),
        "twin_silver_attainment_ladder_on": att(card_on, "silver"),
        "twin_bronze_shed_ladder_on": card_on["tiers"]["bronze"]["shed"],
        "twin_shed_rate_ladder_on": card_on["shed_rate"],
        "twin_shed_rate_ladder_off": card_off["shed_rate"],
        "twin_gold_p99_ms_ladder_on": card_on["tiers"]["gold"].get(
            "p99_ms"),
        "twin_gold_p99_ms_ladder_off": card_off["tiers"]["gold"].get(
            "p99_ms"),
        "twin_rto_s": card_on["rto_max_s"],
        "twin_recover_s_mean": card_on["recover_s_mean"],
        "twin_churn_retraces": (
            card_on.get("churn", {}).get("repair_retraces")
        ),
        "twin_ladder": card_on["ladder"],
        "twin_slo_counters": card_on["slo"],
        "twin_fleet": card_on["fleet"],
        "twin_bitmatch_checked": checked,
        "twin_bitmatch": mismatched == 0 and checked > 0,
        "twin_auto_configs": auto_configs,
    }
    if probe is not None:
        pr = probe()
        if pr and out["twin_gold_p99_ms_ladder_on"]:
            out["twin_gold_p99_normalized"] = round(
                out["twin_gold_p99_ms_ladder_on"] / 1e3 * pr, 4)
    return out


def bench_dpop_sharded_subprocess(args):
    """Sharded exact DPOP on a virtual 8-device CPU mesh, in a
    subprocess so the forced-CPU platform doesn't poison this process's
    TPU backend (same pattern as the maxsum sharded leg)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    cmd = [sys.executable, os.path.abspath(__file__), "--only",
           "dpop-sharded-inner",
           "--dpop-sharded-clique", str(args.dpop_sharded_clique),
           "--dpop-sharded-branches", str(args.dpop_sharded_branches),
           "--repeat", str(args.repeat), "--watchdog", "0"]
    out = subprocess.run(
        cmd,
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    lines = out.stdout.strip().splitlines()
    if not lines:
        raise RuntimeError(
            f"dpop-sharded subprocess produced no output "
            f"(rc={out.returncode}): " + out.stderr.strip()[-400:]
        )
    return json.loads(lines[-1])


def build_dpop_sharded_dcop(args):
    """The high-width exact-inference instance (BENCHREF.md "Sharded
    exact DPOP"): ``branches`` disjoint cliques of ``clique`` variables
    at domain 4 — every clique node's separator is its full ancestor
    set, so the deepest joint util table holds ``4^clique`` entries
    (~4 MiB at the default clique=9) and ALONE exceeds the simulated
    per-device budget, while the 8-way separator tiles fit.  Integer
    costs: exactly representable, so sharded-vs-single must match bit
    for bit."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    K, R, D = args.dpop_sharded_clique, args.dpop_sharded_branches, 4
    rng = np.random.default_rng(3)
    dcop = DCOP("dpop_sharded_bench", objective="min")
    dom = Domain("d", "vals", list(range(D)))
    k = 0
    for r in range(R):
        vs = [Variable(f"b{r}v{i:02d}", dom) for i in range(K)]
        for v in vs:
            dcop.add_variable(v)
        for i in range(K):
            for j in range(i + 1, K):
                m = rng.integers(0, 10, (D, D)).astype(float)
                dcop.add_constraint(
                    NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{k}")
                )
                k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def bench_dpop_sharded_inner(args):
    """Runs inside the CPU-mesh subprocess: the sharded exact sweep on
    an instance whose LARGEST JOINT UTIL TABLE alone exceeds the
    simulated per-device budget (the acceptance scenario of ISSUE 9),
    vs the single-device per-level sweep (bitmatch + wall pair), with
    bytes-shipped and pruning counters from the plan.  Drift-
    normalized: the calibration probe runs adjacent to the walls and
    the headline is additionally reported per unit of probe rate."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pydcop_tpu.algorithms.dpop import DpopSolver
    from pydcop_tpu.graph import pseudotree
    from pydcop_tpu.ops.dpop_shard import (
        estimate_sweep_bytes, plan_tiled_sweep,
    )
    from pydcop_tpu.ops.dpop_sweep import (
        compile_sweep_perlevel, run_sweep_perlevel,
    )
    from pydcop_tpu.parallel.dpop_mesh import ShardedSepDpop

    dcop = build_dpop_sharded_dcop(args)
    tree = pseudotree.build_computation_graph(dcop)
    est = estimate_sweep_bytes(tree)
    largest_table_bytes = est["max_node_entries"] * 4

    # pre-plan unbudgeted to learn the true per-device need, then pin
    # the simulated budget BETWEEN it and the largest single table:
    # the budget admits the 8-way tiles but NOT one whole table —
    # i.e. no single device could even hold the widest joint table
    probe_plan = plan_tiled_sweep(tree, dcop, "min", n_shards=8)
    per_dev = probe_plan.bytes_per_device
    assert per_dev < largest_table_bytes, (per_dev, largest_table_bytes)
    budget_bytes = (per_dev + largest_table_bytes) // 2

    try:
        probe = make_drift_probe(repeat=max(2, args.repeat))
    except Exception:
        probe = None

    # routing check once through the solver front door (engine="auto"
    # + budget -> sharded), then engine-level timing so the jitted
    # per-level steps are reused across repeats like every other leg
    solver = DpopSolver(dcop)
    solver.budget_bytes = budget_bytes
    sh_res = solver.run()
    assert solver.last_engine == "sharded", solver.last_engine

    plan = plan_tiled_sweep(tree, dcop, "min", n_shards=8,
                            budget_bytes=budget_bytes)
    engine = ShardedSepDpop(plan)
    sh_assign = engine.run()  # warmup / compile
    times = []
    for _ in range(max(2, args.repeat)):
        t0 = time.perf_counter()
        sh_assign = engine.run()
        times.append(time.perf_counter() - t0)
    sh_wall = robust_best(times)

    base = compile_sweep_perlevel(tree, dcop, "min")
    if base is not None:
        sg_assign, _ = run_sweep_perlevel(base)  # warmup / compile
        stimes = []
        for _ in range(max(2, args.repeat)):
            t0 = time.perf_counter()
            sg_assign, _ = run_sweep_perlevel(base)
            stimes.append(time.perf_counter() - t0)
        sg_wall = robust_best(stimes)
        bitmatch = bool(np.array_equal(sh_assign, sg_assign))
    else:  # clique too wide even for the per-level single-device tier
        sg_res = DpopSolver(dcop, tree)._run_pernode()
        sg_wall = sg_res.time
        bitmatch = bool(sh_res.assignment == sg_res.assignment)

    dpop_m = sh_res.metrics()["dpop"]
    shard_m = sh_res.metrics()["shard"]
    out = {
        "metric": (f"dpop_sharded_sweep_wall_s_8dev_"
                   f"k{args.dpop_sharded_clique}x"
                   f"{args.dpop_sharded_branches}"),
        "value": round(sh_wall, 4), "unit": "s",
        "n_devices": len(jax.devices()),
        "dpop_sharded_single_device_wall_s": round(sg_wall, 4),
        "dpop_sharded_bitmatch": bitmatch,
        "dpop_sharded_budget_bytes": budget_bytes,
        "dpop_sharded_largest_table_bytes": largest_table_bytes,
        "dpop_sharded_table_over_budget": bool(
            largest_table_bytes > budget_bytes
        ),
        "dpop_sharded_est_single_bytes": est["bytes"],
        "dpop_sharded_bytes_per_device": dpop_m["bytes_per_device"],
        "dpop_sharded_wire_bytes_pruned": dpop_m["wire_bytes_pruned"],
        "dpop_sharded_wire_bytes_dense": dpop_m["wire_bytes_dense"],
        "dpop_sharded_pruned_fraction": dpop_m["pruned_fraction"],
        "dpop_sharded_shard_comm": shard_m,
        "dpop_sharded_cost": sh_res.cost,
    }
    if probe is not None:
        pr = probe()
        out["dpop_sharded_probe_rate"] = round(pr, 1)
        if pr:
            # wall x probe-rate is dimensionless: cancels host drift
            out["dpop_sharded_wall_probe_normalized"] = round(
                sh_wall * pr, 2
            )
    print(json.dumps(out), flush=True)
    return out


def bench_search_subprocess(args):
    """Anytime exact search on the CPU backend, in a subprocess for
    the same platform-isolation reason as the other forced-CPU legs."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__), "--only",
           "search-inner", "--repeat", str(args.repeat),
           "--watchdog", "0"]
    out = subprocess.run(
        cmd,
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    lines = out.stdout.strip().splitlines()
    if not lines:
        raise RuntimeError(
            f"search subprocess produced no output "
            f"(rc={out.returncode}): " + out.stderr.strip()[-400:]
        )
    return json.loads(lines[-1])


def build_search_dcop(K, R, D, seed):
    """High-width anytime-search instance: ``R`` cliques of ``K``
    variables at domain ``D`` — induced width K-1, so the widest util
    table holds ``D^K`` entries and full DPOP refuses under any
    budget below it, while the frontier engine needs only its [B, n]
    slab.  Integer costs: exactly representable."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dcop = DCOP("search_bench", objective="min")
    dom = Domain("d", "vals", list(range(D)))
    k = 0
    for r in range(R):
        vs = [Variable(f"b{r}v{i:02d}", dom) for i in range(K)]
        for v in vs:
            dcop.add_variable(v)
        for i in range(K):
            for j in range(i + 1, K):
                m = rng.integers(0, 10, (D, D)).astype(float)
                dcop.add_constraint(
                    NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{k}")
                )
                k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def bench_search_inner(args):
    """Runs inside the CPU subprocess: the optimality-gap-vs-time
    curve of `solve --anytime-exact` on TWO high-width instances that
    full DPOP refuses under budget (typed UtilTableTooLarge — pinned
    here), with node throughput and the proof wall in the JSON;
    drift-normalized via the calibration probe (BENCHREF.md "Anytime
    exact search")."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pydcop_tpu.graph import pseudotree
    from pydcop_tpu.ops.dpop_shard import (
        UtilTableTooLarge, plan_tiled_sweep,
    )
    from pydcop_tpu.search.solver import FrontierSearchSolver

    try:
        probe = make_drift_probe(repeat=max(2, args.repeat))
    except Exception:
        probe = None

    out = {}
    # two instances, two bound tiers: the DPOP-exact heuristic
    # (near-instant proof) and a weak i_bound=2 mini-bucket heuristic
    # (a real anytime trajectory with a visibly closing gap)
    legs = (
        ("k10x4", dict(K=10, R=1, D=4, seed=3), 0, 8),
        ("k11x3_ib2", dict(K=11, R=2, D=3, seed=7), 2, 8),
    )
    for label, spec, i_bound, steps in legs:
        dcop = build_search_dcop(**spec)
        tree = pseudotree.build_computation_graph(dcop)
        # pin the typed refusal: even the 8-way tiled sweep busts a
        # budget set below one tile — the regime this engine opens
        probe_plan = plan_tiled_sweep(tree, dcop, "min", n_shards=8)
        budget = probe_plan.bytes_per_device // 2
        refused = False
        try:
            plan_tiled_sweep(tree, dcop, "min", n_shards=8,
                             budget_bytes=budget)
        except UtilTableTooLarge:
            refused = True
        out[f"search_dpop_refusal_typed_{label}"] = refused

        solver = FrontierSearchSolver(
            dcop, tree=tree, frontier_width=256, steps=steps,
            i_bound=i_bound,
        )
        t0 = time.perf_counter()
        res = solver.run(collect_cycles=True)
        wall = time.perf_counter() - t0
        s = res.metrics()["search"]
        out[f"search_proved_optimal_{label}"] = s["optimal"]
        out[f"search_time_to_proof_s_{label}"] = round(wall, 4)
        out[f"search_nodes_per_s_{label}"] = s["nodes_per_s"]
        out[f"search_nodes_{label}"] = s["nodes"]
        out[f"search_chunks_{label}"] = s["chunks"]
        out[f"search_bound_source_{label}"] = s["bound_source"]
        out[f"search_cost_{label}"] = res.cost
        # host-loop bitmatch: the proof must land on the legacy NCBB
        # host loop's optimum (integer costs — exactly representable)
        from pydcop_tpu.algorithms.ncbb import NcbbSolver

        host = NcbbSolver(dcop).run()
        out[f"search_host_bitmatch_{label}"] = bool(
            res.cost == host.cost
        )
        # the gap trajectory, downsampled to <= 64 points (long weak-
        # bound searches emit thousands of chunks; the curve's shape
        # is the record, not every sample)
        hist = res.history or []
        stride = max(1, len(hist) // 64)
        keep = hist[::stride]
        if hist and keep[-1] is not hist[-1]:
            keep.append(hist[-1])
        out[f"search_gap_curve_{label}"] = [
            [round(h["time"], 4), h["lower_bound"],
             h["upper_bound"] if h["cost"] is not None else None]
            for h in keep
        ]
    if probe is not None:
        pr = probe()
        out["search_probe_rate"] = round(pr, 1)
        if pr:
            # wall x probe-rate is dimensionless: cancels host drift
            out["search_proof_probe_normalized_k10x4"] = round(
                out["search_time_to_proof_s_k10x4"] * pr, 2
            )
    headline = {
        "metric": "search_time_to_proof_s_k10x4",
        "value": out["search_time_to_proof_s_k10x4"], "unit": "s",
        "vs_baseline": 0.0,
        "extra": out,
    }
    print(json.dumps(headline), flush=True)
    return headline


def bench_structured_subprocess(args):
    """Table-free structured kernels on the CPU backend, in a
    subprocess for the same platform-isolation reason as the other
    forced-CPU legs."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__), "--only",
           "structured-inner", "--repeat", str(args.repeat),
           "--watchdog", "0"]
    out = subprocess.run(
        cmd,
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    lines = out.stdout.strip().splitlines()
    if not lines:
        raise RuntimeError(
            f"structured subprocess produced no output "
            f"(rc={out.returncode}): " + out.stderr.strip()[-400:]
        )
    return json.loads(lines[-1])


def _densified_twin(dcop):
    """Same instance with every structured constraint materialized as
    its dense table (guarded — only valid at table-fitting arity)."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.structured import StructuredConstraint

    out = DCOP(
        dcop.name + "_dense",
        objective=dcop.objective,
        domains=dict(dcop.domains),
        variables=dict(dcop.variables),
        agents=dict(dcop.agents),
    )
    for c in dcop.constraints.values():
        out.add_constraint(
            c.densified() if isinstance(c, StructuredConstraint) else c
        )
    return out


def bench_structured_inner(args):
    """Table-free constraints (ISSUE 17): the routing-window family
    through the structured kernels vs the densified table path at a
    table-fitting arity (10 at D=4: a 4 MB dense table), parity
    pinned on maxsum AND the frontier engine; then the headline
    100-arity instance NO table path can even represent (a 4^100
    table), solved end-to-end with device bytes linear in arity
    (BENCHREF.md "Table-free constraints")."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pydcop_tpu.algorithms import AlgorithmDef
    from pydcop_tpu.algorithms.base import tensor_const_bytes
    from pydcop_tpu.algorithms.maxsum import MaxSumSolver
    from pydcop_tpu.algorithms.maxsum import algo_params as ms_params
    from pydcop_tpu.dcop.structured import StructuredConstraint
    from pydcop_tpu.generators import generate_routing_structured
    from pydcop_tpu.ops.compile import compile_factor_graph
    from pydcop_tpu.search.solver import FrontierSearchSolver

    algo = AlgorithmDef.build_with_default_params(
        "maxsum", {}, parameters_definitions=ms_params)
    out = {}

    # -- parity tier: arity 10, the dense twin still materializable ----
    K_FIT = 10
    d = generate_routing_structured(
        K_FIT, n_slots=4, window=K_FIT, p_soft=0.0, seed=0)
    dd = _densified_twin(d)
    ts, td = compile_factor_graph(d), compile_factor_graph(dd)
    b_s, b_d = tensor_const_bytes(ts), tensor_const_bytes(td)
    param_bytes = sum(sb.param_bytes() for sb in ts.sbuckets)
    table_bytes = sum(
        int(c.dense_entries()) * 4
        for c in d.constraints.values()
        if isinstance(c, StructuredConstraint)
    )
    out["structured_const_bytes_k10"] = int(b_s)
    out["structured_dense_const_bytes_k10"] = int(b_d)
    out["structured_bytes_ratio_k10"] = round(b_d / max(b_s, 1), 1)
    # per-cycle factor-side traffic: the dense message update re-reads
    # the whole D^k table, the structured kernel only its parameters
    out["structured_msg_bytes_per_cycle_k10"] = int(param_bytes)
    out["structured_dense_msg_bytes_per_cycle_k10"] = int(table_bytes)
    out["structured_wire_ratio_k10"] = round(
        table_bytes / max(param_bytes, 1), 1)

    # evaluation parity: the two compilations must agree EXACTLY on
    # the cost of every assignment (trajectory equality is not a
    # sound pin here — lowering changes the factor-graph topology)
    from pydcop_tpu.ops.compile import total_cost

    rng = np.random.default_rng(4)
    n_vars = len(d.variables)
    eval_gap = 0.0
    for _ in range(50):
        x = rng.integers(0, 4, n_vars)
        a, b = float(total_cost(ts, x)), float(total_cost(td, x))
        # relative: hard-violation sums sit at 1e9+ where the f32 ulp
        # is ~64 and summation order differs between the two paths
        eval_gap = max(eval_gap, abs(a - b) / max(1.0, abs(a)))
    out["structured_eval_rel_gap_k10"] = float(eval_gap)
    out["structured_eval_parity_k10"] = bool(eval_gap <= 1e-6)
    t0 = time.perf_counter()
    rs = MaxSumSolver(d, ts, algo, seed=0).run(cycles=20)
    out["structured_maxsum_wall_s_k10"] = round(
        time.perf_counter() - t0, 3)

    fs = FrontierSearchSolver(d, frontier_width=128, i_bound=2).run()
    fd = FrontierSearchSolver(dd, frontier_width=128, i_bound=2).run()
    out["structured_frontier_cost_k10"] = round(fs.cost, 6)
    out["structured_frontier_parity_k10"] = bool(
        fs.search["optimal"] and fd.search["optimal"]
        and abs(fs.cost - fd.cost) <= 1e-3)

    # -- headline tier: arity 100, table path impossible ---------------
    K = 100
    d100 = generate_routing_structured(
        K, n_slots=4, window=K, p_soft=0.0, seed=0)
    t100 = compile_factor_graph(d100)
    out["structured_const_bytes_k100"] = int(tensor_const_bytes(t100))
    out["structured_dense_bytes_k100"] = max(
        c.dense_bytes()
        for c in d100.constraints.values()
        if isinstance(c, StructuredConstraint)
    )  # ~6.4e60: the point of the exercise

    t0 = time.perf_counter()
    ms = MaxSumSolver(d100, t100, algo, seed=0).run(cycles=10)
    out["structured_maxsum_wall_s_k100"] = round(
        time.perf_counter() - t0, 3)
    out["structured_maxsum_assigned_k100"] = len(ms.assignment) == K

    sol = FrontierSearchSolver(d100, frontier_width=256, i_bound=2)
    out["structured_plan_bytes_k100"] = int(sol.plan.table_bytes)
    t0 = time.perf_counter()
    res = sol.run(cycles=5)
    out["structured_frontier_wall_s_k100"] = round(
        time.perf_counter() - t0, 3)
    out["structured_frontier_feasible_k100"] = res.violation == 0
    out["structured_frontier_cost_k100"] = round(res.cost, 6)

    headline = {
        "metric": "structured_wire_ratio_k10",
        "value": out["structured_wire_ratio_k10"],
        "unit": "x (dense table bytes / structured param bytes "
                "per message cycle)",
        "vs_baseline": 0.0,
        "extra": out,
    }
    print(json.dumps(headline), flush=True)
    return headline


def bench_sharded_subprocess(args):
    """ShardedMaxSum on a virtual 8-device CPU mesh, in a subprocess so
    the forced-CPU platform doesn't poison this process's TPU backend."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    cmd = [sys.executable, os.path.abspath(__file__), "--only",
           "sharded-inner", "--vars", str(args.sharded_vars), "--edges",
           str(args.sharded_vars * 3), "--watchdog", "0"]
    if getattr(args, "stretch2_sharded", False):
        cmd += ["--stretch2-sharded",
                "--stretch2-vars", str(args.stretch2_vars),
                "--stretch2-edges", str(args.stretch2_edges)]
    out = subprocess.run(
        cmd,
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    lines = out.stdout.strip().splitlines()
    if not lines:
        raise RuntimeError(
            f"sharded subprocess produced no output (rc={out.returncode}): "
            + out.stderr.strip()[-400:]
        )
    return json.loads(lines[-1])


def build_partitioned_tensors(args, V=None, E_per_var=3):
    """The PARTITIONED sharded-bench instance (ISSUE 5, BENCHREF.md
    "Sharded metrics"): a ring lattice — variable i constrained to
    i+1..i+E_per_var — whose BFS-region partition cuts only the arc
    seams, the locality profile the boundary-compacted collectives are
    built for (a random instance is an expander: ~everything boundary,
    where the auto-policy correctly keeps the dense psum and there is
    nothing to measure)."""
    from pydcop_tpu.ops.compile import compile_binary_from_arrays

    C = args.colors
    V = V if V is not None else args.vars
    rng = np.random.default_rng(1)
    idx = np.arange(V)
    edge_i = np.concatenate([idx] * E_per_var)
    edge_j = np.concatenate([(idx + k) % V
                             for k in range(1, E_per_var + 1)])
    mats = rng.uniform(0, 1, (E_per_var * V, C, C)).astype(np.float32)
    mats += np.eye(C, dtype=np.float32) * 10  # coloring penalty
    return compile_binary_from_arrays(
        edge_i, edge_j, mats, V,
        unary=rng.uniform(0, 0.01, (V, C)).astype(np.float32),
    )


def bench_elastic_subprocess(args):
    """Elastic device-fault tier (ISSUE 14) on a virtual 8-device CPU
    mesh, in a subprocess so the forced-CPU platform doesn't poison
    this process's TPU backend."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    cmd = [sys.executable, os.path.abspath(__file__), "--only",
           "elastic-inner", "--sharded-vars",
           str(args.sharded_vars), "--watchdog", "0"]
    out = subprocess.run(
        cmd,
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    lines = out.stdout.strip().splitlines()
    if not lines:
        raise RuntimeError(
            f"elastic subprocess produced no output "
            f"(rc={out.returncode}): " + out.stderr.strip()[-400:]
        )
    return json.loads(lines[-1])


def bench_elastic_inner(args):
    """Runs inside the CPU-mesh subprocess (BENCHREF.md "Elastic
    mesh"): the degraded-throughput curve 8→6→4 devices on the
    partitioned sharded instance, SDC detection latency with zero
    false positives on the clean legs, and the sentinel overhead
    (interleaved on/off bursts, repeat-best — the same
    drift-discipline as the sharded canary)."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pydcop_tpu.parallel.elastic import ElasticRunner
    from pydcop_tpu.runtime.faults import Fault, FaultPlan

    tensors = build_partitioned_tensors(args, V=args.sharded_vars)
    devices = jax.devices()
    chunk, timed_cycles = 20, 60

    def rate(n_dev, sentinel, fault_plan=None, scrub_every=0):
        r = ElasticRunner(
            tensors, engine="maxsum", devices=devices[:n_dev],
            chunk=chunk, sentinel=sentinel, fault_plan=fault_plan,
            scrub_every=scrub_every,
        )
        r.solve(chunk, seed=0)  # build + warmup chunk
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            res = r.solve(timed_cycles, seed=0)
            dt = time.perf_counter() - t0
            best = max(best, timed_cycles / dt)
        return best, res

    extra = {}
    # 1) degraded-throughput curve: sustained rate at each mesh size
    #    the elastic shrink lands on
    for n in (8, 6, 4):
        extra[f"elastic_iters_per_s_{n}dev"], _ = rate(n, True)
    # 2) the shrink machinery end-to-end: 8→6→4 in ONE faulted solve
    plan = FaultPlan(faults=[
        Fault(kind="kill_device", device=7, cycle=chunk + 1),
        Fault(kind="kill_device", device=6, cycle=2 * chunk + 1),
        Fault(kind="shrink_mesh", devices=4, cycle=3 * chunk + 1),
    ], seed=9)
    runner = ElasticRunner(tensors, engine="maxsum", devices=devices,
                           chunk=chunk, sentinel=True,
                           fault_plan=plan)
    res = runner.solve(5 * chunk, seed=0)
    extra["elastic_shrink_run_devices_final"] = res.n_devices
    extra["elastic_shrink_run_shrinks"] = \
        res.counters.counts["elastic_shrinks"]
    # 3) SDC detection latency (chunks) + zero false positives on the
    #    clean legs above (operand checksums are constants, so clean
    #    trips are impossible by construction — assert anyway)
    plan = FaultPlan(faults=[
        Fault(kind="corrupt_slab", operand="bucket0",
              cycle=chunk + 1),
    ], seed=3)
    _, res_clean = rate(8, True, scrub_every=2)
    sdc = ElasticRunner(tensors, engine="maxsum", devices=devices,
                        chunk=chunk, sentinel=True, fault_plan=plan)
    res_sdc = sdc.solve(4 * chunk, seed=0)
    assert res_sdc.counters.counts["sdc_detected"] == 1
    extra["elastic_sdc_detection_latency_chunks"] = \
        res_sdc.counters.counts["detection_latency_chunks"]
    extra["elastic_false_positives"] = (
        res_clean.counters.counts["sentinel_trips"]
        + res_clean.counters.counts["scrub_mismatches"]
    )
    # 4) sentinel overhead: interleaved on/off bursts, repeat-best
    on = off = 0.0
    for _ in range(3):
        b_off, _ = rate(8, False)
        b_on, _ = rate(8, True)
        off, on = max(off, b_off), max(on, b_on)
    overhead = max(0.0, (off - on) / off * 100.0) if off else 0.0
    extra["elastic_sentinel_overhead_pct"] = overhead
    extra["elastic_iters_per_s_8dev_sentinel_off"] = off
    out = {
        "metric": "elastic_sharded_iters_per_s",
        "value": extra["elastic_iters_per_s_8dev"],
        "unit": "iters/s (8-dev CPU mesh, sentinel on)",
        "vs_baseline": 0.0,
        "extra": extra,
    }
    print(json.dumps(out), flush=True)
    return out


def bench_sharded_inner(args):
    """Runs inside the CPU-mesh subprocess."""
    # sitecustomize clobbers JAX_PLATFORMS; jax.config (pre-backend-init)
    # is the only override that sticks (same pattern as __graft_entry__)
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pydcop_tpu.ops.compile import total_cost
    from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

    tensors = build_partitioned_tensors(args)
    cycles = 20

    def rate(solver):
        solver.run(cycles=cycles)  # warmup / compile
        # repeat-best like the primary: this is the regression canary
        # for the mesh path, and a single sample on a shared CPU host
        # is noise
        times = []
        for _ in range(max(3, args.repeat)):
            t0 = time.perf_counter()
            solver.run(cycles=cycles)
            times.append(time.perf_counter() - t0)
        return round(cycles / robust_best(times), 2)

    # the compact-vs-dense PAIR (ISSUE 5): the headline tracks the
    # auto-policy engine (compact on this partitioned instance); the
    # dense rate is the overhead baseline it must beat
    compact = ShardedMaxSum(tensors, build_mesh(8), damping=0.5)
    dense = ShardedMaxSum(tensors, build_mesh(8), damping=0.5,
                          overlap="off")
    out = {
        "metric": f"sharded_maxsum_iters_per_sec_8dev_{args.vars}var",
        "value": rate(compact), "unit": "iters/s",
        "n_devices": len(jax.devices()),
        "sharded_maxsum_dense_iters_per_sec": rate(dense),
        "shard_comm": compact.comm_stats(),
    }
    out["sharded_compact_speedup"] = round(
        out["value"] / out["sharded_maxsum_dense_iters_per_sec"], 3
    )
    vc, _, _ = compact.run(cycles=cycles)
    vd, _, _ = dense.run(cycles=cycles)
    out["sharded_compact_bitmatch"] = bool((vc == vd).all())
    # VERDICT r4 item 3: the lane-packed per-shard engine must pack this
    # all-binary instance AND bit-match the generic sharded run.  On the
    # virtual CPU mesh the pallas kernels execute in interpret mode
    # (emulated — not a rate to track), so the canary validates the
    # packed path and keeps timing the platform-native engine above.
    try:
        packed = ShardedMaxSum(tensors, build_mesh(8), damping=0.5,
                               use_packed=True)
        out["sharded_packed_path"] = packed.packs is not None
        if packed.packs is None:
            out["sharded_packed_error"] = (
                "build_shard_packs declined the canary instance"
            )
        else:
            vp, _, _ = packed.run(cycles=cycles)
            out["sharded_packed_bitmatch"] = bool((vp == vd).all())
    except Exception as e:  # never lose the canary rate
        out["sharded_packed_error"] = repr(e)
    if getattr(args, "stretch2_sharded", False):
        # the 1M-var / 3M-edge stretch2 instance over the 8-device mesh
        # (VERDICT r4 item 4's sharded leg): a few cycles on the virtual
        # CPU mesh demonstrating the sharded path EXECUTES the instance
        # and descends in cost (full convergence on CPU would take
        # minutes; the single-chip TPU run is the convergence record)
        s2 = build_stretch_tensors(args, args.stretch2_vars,
                                   args.stretch2_edges)
        sh2 = ShardedMaxSum(s2, build_mesh(8), damping=0.9)
        # the stretch instance is an expander (random offsets): record
        # which path the auto-policy chose (expected: dense fallback)
        out["stretch2_shard_comm_mode"] = sh2.comm_stats()["mode"]
        import jax.numpy as jnp

        v1, _, _ = sh2.run(cycles=1)
        c1 = float(total_cost(s2, jnp.asarray(v1)))
        sh2.run(cycles=5)  # warm the cycles=5 scan shape before timing
        t0 = time.perf_counter()
        v5, _, _ = sh2.run(cycles=5)
        dt = time.perf_counter() - t0
        c5 = float(total_cost(s2, jnp.asarray(v5)))
        out["stretch2_sharded_vars"] = args.stretch2_vars
        out["stretch2_sharded_iters_per_sec_8dev"] = round(5 / dt, 3)
        out["stretch2_sharded_cost_c1"] = round(c1, 1)
        out["stretch2_sharded_cost_c5"] = round(c5, 1)
        out["stretch2_sharded_cost_decreased"] = bool(c5 < c1)
    print(json.dumps(out), flush=True)


# --------------------------------------------------------------------------
# round-over-round regression guard
# --------------------------------------------------------------------------

#: headline metrics guarded against silent round-over-round drops.  A
#: >10% drop on any of these emits a "regressions" extra so a real cost
#: of a code change is distinguishable from unmeasured drift (VERDICT r3
#: weak #1: the primary fell 23% and nothing flagged it).
GUARDED_HEADLINES = (
    "primary",  # the top-level "value"
    "dpop_tables_per_sec_10000var",
    "dpop_tables_per_sec_batched100",
    "mgm_cycles_per_sec_10000var",
    "dsa_cycles_per_sec_10000var",
    "sharded_maxsum_iters_per_sec_8dev_2000var",
    "sharded_packed_maxsum_iters_per_sec_tpu",
    "batch_throughput_b32",
)


def _primary_from_record(rec: dict):
    """(primary value, extras) from a driver BENCH_r*.json record.

    The driver usually archives the full parsed JSON line; when parsing
    failed on its side (round 5) only the output TAIL survives — the
    steady-state burst recorded in extra is recovered from it so the
    drift verdict and the regression guard keep their history."""
    import re

    parsed = rec.get("parsed") or {}
    if parsed.get("value"):
        return float(parsed["value"]), parsed.get("extra") or {}
    tail = rec.get("tail") or ""
    m = (re.search(r'"primary_burst2": ([0-9.]+)', tail)
         or re.search(r'"primary_burst1": ([0-9.]+)', tail))
    if m:
        return float(m.group(1)), {}
    return None, {}


def load_previous_bench(here: str):
    """(round, primary value, extras) from the newest BENCH_r*.json the
    driver left in the repo root, or None."""
    import glob
    import re

    best = None
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rnd = int(m.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, path)
    if best is None:
        return None
    try:
        with open(best[1], encoding="utf-8") as f:
            rec = json.load(f)
        value, extras = _primary_from_record(rec)
        return best[0], value, extras
    except (OSError, ValueError):
        return None


def regression_check(value: float, extra: dict, here: str,
                     threshold: float = 0.10):
    """Compare this run's headline metrics with the previous round's and
    record any >threshold drop under extra["regressions"]."""
    prev = load_previous_bench(here)
    if prev is None:
        return
    rnd, prev_value, prev_extra = prev
    regressions = {}
    for name in GUARDED_HEADLINES:
        basis = None
        if name == "primary":
            cur, old = value, prev_value
            # prefer the drift-normalized primary when BOTH rounds
            # carry it (round-5 verdict item 1): a raw drop that the
            # normalized value doesn't show is environment, not code —
            # and must not be flagged
            cur_n = (extra or {}).get("primary_normalized")
            old_n = (prev_extra or {}).get("primary_normalized")
            if cur_n and old_n:
                cur, old = cur_n, old_n
                basis = "primary_normalized"
        else:
            cur, old = extra.get(name), prev_extra.get(name)
        if cur is None or old is None or not old:
            continue
        drop = 1.0 - float(cur) / float(old)
        if drop > threshold:
            regressions[name] = {
                "prev": old, "cur": cur, "drop_pct": round(100 * drop, 1),
                "prev_round": rnd,
            }
            if basis:
                regressions[name]["basis"] = basis
            if (name == "primary"
                    and extra.get("primary_policy")
                    and not prev_extra.get("primary_policy")):
                # the baseline round still reported max-of-2-bursts;
                # this round reports the steady-state second burst — an
                # apparent drop up to the burst spread is the policy
                # change, not a code regression
                regressions[name]["note"] = (
                    "primary policy changed max-of-2-bursts -> "
                    "second-burst steady state; compare against "
                    "prev round's primary_burst2 if recorded"
                )
    if regressions:
        extra["regressions"] = regressions


# --------------------------------------------------------------------------

def _maybe_snapshot(args, out):
    """Write the run's JSON as a BENCH_r<N>.json snapshot record
    (ISSUE 12 satellite: the machine-readable perf record resumes past
    r05).  Shape mirrors the earlier driver-captured snapshots:
    ``{"n": <round>, "cmd": ..., "rc": 0, "parsed": <the JSON>}``."""
    import re

    if not getattr(args, "snapshot", None):
        return
    m = re.search(r"r(\d+)", os.path.basename(args.snapshot))
    rec = {
        "n": int(m.group(1)) if m else 0,
        "cmd": "python " + " ".join(sys.argv),
        "rc": 0,
        "parsed": out,
    }
    with open(args.snapshot, "w", encoding="utf-8") as f:
        json.dump(rec, f)
        f.write("\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vars", type=int, default=10_000)
    # warm-repair churn leg (ISSUE 8; BENCHREF.md "Churn recovery")
    ap.add_argument("--churn-vars", type=int, default=100_000,
                    help="live-instance size of the churn leg")
    ap.add_argument("--churn-mutations", type=int, default=50,
                    help="seeded mutation-stream length (warm path)")
    ap.add_argument("--churn-cold-mutations", type=int, default=8,
                    help="cold-baseline mutations (each pays a full "
                    "repack + XLA recompile, so the baseline is capped "
                    "and reported as a per-mutation mean)")
    # solution-cache leg (ISSUE 18; BENCHREF.md "Solution cache")
    ap.add_argument("--memo-vars", type=int, default=800,
                    help="instance size of the solution-cache trace "
                    "(big enough that a cold solve visibly costs, "
                    "small enough that the 16-request trace stays "
                    "in minutes)")
    ap.add_argument("--edges", type=int, default=30_000)
    ap.add_argument("--colors", type=int, default=3)
    ap.add_argument(
        "--cycles", type=int, default=None,
        help="cycles per timed jit call; default 2000 for the primary "
        "10k bench (the tunneled TPU costs ~70ms dispatch per call, "
        "which at 50 cycles/call hid 8x of the real device rate) and "
        "50 for the 100k stretch instance (per-cycle cost is large "
        "enough there that dispatch is noise)",
    )
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--dpop-vars", type=int, default=10_000)
    ap.add_argument("--dpop-domain", type=int, default=10)
    ap.add_argument("--stretch-vars", type=int, default=100_000)
    ap.add_argument("--stretch-edges", type=int, default=300_000)
    ap.add_argument("--stretch-max-cycles", type=int, default=400)
    ap.add_argument("--stretch2-vars", type=int, default=1_000_000)
    ap.add_argument("--stretch2-edges", type=int, default=3_000_000)
    ap.add_argument(
        "--stretch2-sharded", action="store_true",
        help="include the 1M-var stretch2 instance in the 8-device "
        "sharded canary (a few cycles on the virtual CPU mesh)",
    )
    ap.add_argument("--sharded-vars", type=int, default=2_000)
    ap.add_argument(
        "--dpop-sharded-clique", type=int, default=9,
        help="clique size of the sharded exact-DPOP leg: the deepest "
        "joint util table holds 4^clique entries (~4MiB at 9) and "
        "alone exceeds the simulated per-device budget",
    )
    ap.add_argument(
        "--dpop-sharded-branches", type=int, default=2,
        help="disjoint cliques in the sharded exact-DPOP leg",
    )
    ap.add_argument(
        "--harness-vars", type=int, default=2000,
        help="variables in the harness sync-overhead bench's "
        "convergence-bound MGM instance (edges = 3x)",
    )
    ap.add_argument(
        "--batch-vars", type=int, default=500,
        help="variables per instance in the batched-throughput bench "
        "(edges = 3x); small enough that B=32 stacks comfortably, big "
        "enough that per-instance device work is real",
    )
    ap.add_argument(
        "--serve-jobs", type=int, default=24,
        help="jobs in the serve-throughput bench's Poisson burst",
    )
    ap.add_argument(
        "--serve-vars", type=int, default=120,
        help="variables of the LARGER shape in the serve bench's "
        "mixed-shape family (the smaller is half; edges = 3x)",
    )
    ap.add_argument(
        "--serve-rate", type=float, default=20.0,
        help="Poisson arrival rate of the serve bench, jobs/sec",
    )
    ap.add_argument(
        "--serve-seed", type=int, default=11,
        help="seed of the serve bench's arrival process (the trace is "
        "recorded in the JSON)",
    )
    ap.add_argument(
        "--serve-lanes", type=int, default=8,
        help="lanes per service bucket in the serve bench",
    )
    ap.add_argument(
        "--twin-jobs", type=int, default=24,
        help="tenant jobs in the twin scenario's traffic stream",
    )
    ap.add_argument(
        "--twin-seed", type=int, default=17,
        help="seeds the twin's traffic, tiers, chaos and churn",
    )
    ap.add_argument(
        "--twin-replicas", type=int, default=2,
        help="fleet replicas under the twin scenario",
    )
    ap.add_argument(
        "--twin-live-vars", type=int, default=400,
        help="live tracking problem size (rounded to a square grid); "
        "scale up toward stretch2 with this flag — per-step mutation "
        "batches grow with the grid, so the tier deadlines need "
        "retuning past ~2.5k (BENCHREF.md 'City twin')",
    )
    ap.add_argument(
        "--twin-mutations", type=int, default=8,
        help="target-walk churn mutations against the live problem",
    )
    ap.add_argument(
        "--twin-kill-tick", type=int, default=14,
        help="supervisor tick of the injected kill_replica (mid-trace "
        "— clear of the bronze-light prefix the early gold rides)",
    )
    ap.add_argument("--twin-gold-deadline", type=float, default=2.3)
    ap.add_argument("--twin-silver-deadline", type=float, default=0.8)
    ap.add_argument("--twin-bronze-deadline", type=float, default=60.0)
    ap.add_argument(
        "--twin-interarrival", type=float, default=1.5,
        help="mean Poisson inter-arrival of twin traffic, in ticks",
    )
    ap.add_argument(
        "--twin-lanes", type=int, default=2,
        help="lanes per twin service bucket (small: lanes are the "
        "contended resource the ladder reallocates)",
    )
    ap.add_argument(
        "--twin-max-buckets", type=int, default=0,
        help="per-replica open-bucket bound under the twin (0 = "
        "unbounded: saturation is compute contention, every active "
        "bucket dilutes every tick)",
    )
    ap.add_argument(
        "--twin-bronze-vars", type=int, default=20_000,
        help="bronze-tier coloring instance size (the compute-"
        "contention driver the ladder sheds; bronze runs dsa p=1.0 "
        "to the cycle cap, so unshed bronze accumulates)",
    )
    ap.add_argument(
        "--snapshot", default=None,
        help="also write the run's JSON as a BENCH_r<N>.json snapshot "
        "record ({n, cmd, rc, parsed}) to this path",
    )
    ap.add_argument(
        "--stretch", action="store_true",
        help="compat: run ONLY the 100k stretch instance as primary",
    )
    ap.add_argument(
        "--engine", choices=["auto", "generic", "packed"], default="auto",
        help="force a maxsum engine (auto = packed on TPU when applicable)",
    )
    ap.add_argument(
        "--only",
        choices=["all", "maxsum", "dpop", "convergence", "convergence2",
                 "local", "scalefree", "mixed", "sharded",
                 "sharded-inner", "dpop-sharded", "dpop-sharded-inner",
                 "probe", "batch", "harness", "serve", "fleet",
                 "pfleet", "churn",
                 "auto", "twin", "elastic", "elastic-inner", "search",
                 "search-inner", "structured", "structured-inner",
                 "memo", "precision",
                 "r06", "r07", "r08", "r09", "r10", "r11"],
        default="all",
    )
    # watchdog covers the FULL run: the wholesweep DPOP kernel compile
    # (~140s), the stretch2 instance (~60s convergence + warmup) and the
    # sharded stretch2 leg grew the all-parts wall to ~30min (measured
    # end-to-end r4); the watchdog is a hang detector, not a budget
    ap.add_argument("--watchdog", type=float, default=2700.0)
    args = ap.parse_args()
    if args.cycles is None:
        args.cycles = 50 if args.stretch else 2000

    if args.only == "r11":
        # consolidated r11 record (ISSUE 19 satellite): the r10 legs
        # plus the mixed-precision leg, EACH in a fresh subprocess
        # (same isolation rationale as r06 below)
        legs = ("serve", "churn", "dpop-sharded", "auto", "fleet",
                "pfleet", "twin", "elastic", "search", "structured",
                "memo", "precision")
        fwd = []
        skip_next = False
        for a in sys.argv[1:]:
            if skip_next:
                skip_next = False
                continue
            if a in ("--only", "--snapshot"):
                skip_next = True
                continue
            if a.startswith(("--only=", "--snapshot=")):
                continue
            fwd.append(a)
        extra = {}
        for leg in legs:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--only", leg] + fwd
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=3000,
                )
                parsed = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                extra.update(parsed.get("extra", {}))
            except Exception as e:
                extra[f"{leg}_error"] = repr(e)[:500]
        out = {
            "metric": "r11_consolidated",
            "value": extra.get("precision_payload_cut_x", 0.0),
            "unit": "x (f32 / bf16 max collective payload bytes)",
            "vs_baseline": 0.0,
            "extra": extra,
        }
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "r10":
        # consolidated r10 record (ISSUE 18 satellite): the r09 legs
        # plus the solution-cache leg, EACH in a fresh subprocess
        # (same isolation rationale as r06 below)
        legs = ("serve", "churn", "dpop-sharded", "auto", "fleet",
                "pfleet", "twin", "elastic", "search", "structured",
                "memo")
        fwd = []
        skip_next = False
        for a in sys.argv[1:]:
            if skip_next:
                skip_next = False
                continue
            if a in ("--only", "--snapshot"):
                skip_next = True
                continue
            if a.startswith(("--only=", "--snapshot=")):
                continue
            fwd.append(a)
        extra = {}
        for leg in legs:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--only", leg] + fwd
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=3000,
                )
                parsed = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                extra.update(parsed.get("extra", {}))
            except Exception as e:
                extra[f"{leg}_error"] = repr(e)[:500]
        out = {
            "metric": "r10_consolidated",
            "value": extra.get("memo_variant_speedup", 0.0),
            "unit": "x (cold solve / warm variant serve)",
            "vs_baseline": 0.0,
            "extra": extra,
        }
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "r09":
        # consolidated r09 record (ISSUE 17 satellite): the r08 legs
        # plus the table-free structured-constraints leg, EACH in a
        # fresh subprocess (same isolation rationale as r06 below)
        legs = ("serve", "churn", "dpop-sharded", "auto", "fleet",
                "pfleet", "twin", "elastic", "search", "structured")
        fwd = []
        skip_next = False
        for a in sys.argv[1:]:
            if skip_next:
                skip_next = False
                continue
            if a in ("--only", "--snapshot"):
                skip_next = True
                continue
            if a.startswith(("--only=", "--snapshot=")):
                continue
            fwd.append(a)
        extra = {}
        for leg in legs:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--only", leg] + fwd
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=3000,
                )
                parsed = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                extra.update(parsed.get("extra", {}))
            except Exception as e:
                extra[f"{leg}_error"] = repr(e)[:500]
        out = {
            "metric": "r09_consolidated",
            "value": extra.get("structured_wire_ratio_k10", 0.0),
            "unit": "x (dense/structured bytes per message cycle)",
            "vs_baseline": 0.0,
            "extra": extra,
        }
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "r08":
        # consolidated r08 record (ISSUE 15 satellite; the process-
        # fleet leg joined in ISSUE 16): the r07 legs plus the anytime
        # exact-search and process-fleet legs, EACH in a fresh
        # subprocess (same isolation rationale as r06 below)
        legs = ("serve", "churn", "dpop-sharded", "auto", "fleet",
                "pfleet", "twin", "elastic", "search")
        fwd = []
        skip_next = False
        for a in sys.argv[1:]:
            if skip_next:
                skip_next = False
                continue
            if a in ("--only", "--snapshot"):
                skip_next = True
                continue
            if a.startswith(("--only=", "--snapshot=")):
                continue
            fwd.append(a)
        extra = {}
        for leg in legs:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--only", leg] + fwd
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=3000,
                )
                parsed = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                extra.update(parsed.get("extra", {}))
            except Exception as e:
                extra[f"{leg}_error"] = repr(e)[:500]
        out = {
            "metric": "r08_consolidated",
            "value": extra.get("search_time_to_proof_s_k10x4", 0.0),
            "unit": "anytime exact proof wall (s, k10x4)",
            "vs_baseline": 0.0,
            "extra": extra,
        }
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "r07":
        # consolidated r07 record (ISSUE 14 satellite): the r06 legs
        # plus the elastic device-fault leg, EACH in a fresh
        # subprocess (same isolation rationale as r06 below)
        legs = ("serve", "churn", "dpop-sharded", "auto", "fleet",
                "twin", "elastic")
        fwd = []
        skip_next = False
        for a in sys.argv[1:]:
            if skip_next:
                skip_next = False
                continue
            if a in ("--only", "--snapshot"):
                skip_next = True
                continue
            if a.startswith(("--only=", "--snapshot=")):
                continue
            fwd.append(a)
        extra = {}
        for leg in legs:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--only", leg] + fwd
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=3000,
                )
                parsed = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                extra.update(parsed.get("extra", {}))
            except Exception as e:
                extra[f"{leg}_error"] = repr(e)[:500]
        out = {
            "metric": "r07_consolidated",
            "value": extra.get("elastic_iters_per_s_8dev", 0.0),
            "unit": "elastic 8-dev iters/s (sentinel on)",
            "vs_baseline": 0.0,
            "extra": extra,
        }
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "r06":
        # consolidated r06 record (ISSUE 12 satellite): the serve /
        # churn / dpop-sharded / auto / fleet / twin legs, EACH in a
        # fresh subprocess — a single process would distort the
        # wall-sensitive legs (e.g. the auto sweep turns on the
        # persistent XLA cache, which makes the churn leg's cold
        # baseline artificially warm; the twin's deadline attainment
        # inherits whatever allocator/cache state earlier legs left).
        # Subprocess-per-leg preserves each leg's standalone
        # semantics, which is how every historical number was
        # measured.
        legs = ("serve", "churn", "dpop-sharded", "auto", "fleet",
                "twin")
        fwd = []
        skip_next = False
        for a in sys.argv[1:]:
            if skip_next:
                skip_next = False
                continue
            if a in ("--only", "--snapshot"):
                skip_next = True
                continue
            if a.startswith(("--only=", "--snapshot=")):
                continue
            fwd.append(a)
        extra = {}
        for leg in legs:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--only", leg] + fwd
            try:
                r = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=3000,
                )
                parsed = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                extra.update(parsed.get("extra", {}))
            except Exception as e:
                extra[f"{leg}_error"] = repr(e)[:500]
        out = {
            "metric": "r06_consolidated",
            "value": extra.get("twin_gold_attainment_ladder_on", 0.0),
            "unit": "gold attainment (ladder on)",
            "vs_baseline": 0.0,
            "extra": extra,
        }
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "sharded-inner":
        bench_sharded_inner(args)
        return

    if args.only == "elastic-inner":
        bench_elastic_inner(args)
        return

    if args.only == "dpop-sharded-inner":
        bench_dpop_sharded_inner(args)
        return

    if args.only == "structured-inner":
        bench_structured_inner(args)
        return

    if args.only == "search-inner":
        bench_search_inner(args)
        return

    if args.stretch:
        # the watchdog (and output) must name the instance actually run
        metric = (f"maxsum_iters_per_sec_{args.stretch_vars}var_"
                  f"{args.stretch_edges}edge")
    else:
        metric = f"maxsum_iters_per_sec_{args.vars}var_{args.edges}edge"
    watchdog = _arm_watchdog(args.watchdog, metric) if args.watchdog else None

    if args.stretch:
        # compat mode: the 100k instance timed as plain iters/s, with the
        # same engine selection as the primary bench (--engine honored)
        import jax
        from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
        from pydcop_tpu.ops.pallas_maxsum import (
            packed_cycle, packed_init_state, try_pack_for_pallas,
        )

        tensors = build_stretch_tensors(args)
        packed = None
        if args.engine == "packed":
            packed = try_pack_for_pallas(tensors)
            if packed is None:
                if watchdog:
                    watchdog.cancel()
                print(json.dumps({
                    "metric": metric, "value": 0.0, "unit": "iters/s",
                    "vs_baseline": 0.0,
                    "error": "--engine packed: graph not packable",
                }), flush=True)
                raise SystemExit(1)
        elif args.engine == "auto" and jax.default_backend() == "tpu":
            packed = try_pack_for_pallas(tensors)

        @jax.jit
        def run_n(q, r):
            def body(carry, _):
                q, r = carry
                if packed is not None:
                    q2, r2, _, _ = packed_cycle(packed, q, r, damping=0.5)
                else:
                    q2, r2, _, _ = maxsum_cycle(tensors, q, r, damping=0.5)
                return (q2, r2), ()
            (q, r), _ = jax.lax.scan(body, (q, r), None, length=args.cycles)
            return q, r

        q0, r0 = (
            packed_init_state(packed) if packed is not None
            else init_messages(tensors)
        )
        q, r = run_n(q0, r0)
        jax.block_until_ready((q, r))
        times = []
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            q, r = run_n(q0, r0)
            jax.block_until_ready((q, r))
            times.append(time.perf_counter() - t0)
        val = args.cycles / robust_best(times)
        ref = python_reference_cycle_time(tensors)
        if watchdog:
            watchdog.cancel()
        print(json.dumps({
            "metric": metric,
            "value": round(val, 2), "unit": "iters/s",
            "vs_baseline": round(val * ref, 2) if ref > 0 else 0.0,
        }), flush=True)
        return

    extra = {}
    value = vs = 0.0
    dcop = None

    # drift calibration (round-5 verdict item 1): compile the probe
    # once up front; each burst then times it ADJACENT to the primary
    # measurement so both see the same tunnel state
    probe = None
    if args.only in ("all", "maxsum", "probe", "batch", "harness",
                     "serve", "fleet", "pfleet", "churn", "twin",
                     "memo"):
        try:
            probe = make_drift_probe(repeat=args.repeat)
        except Exception as e:
            extra["probe_error"] = repr(e)

    if args.only == "probe":
        # `make bench-probe`: the sharded local-search micro-bench +
        # the calibration probe only (a minutes-long spot check of the
        # tentpole rate with its drift anchor, vs the ~30min full run).
        # The probe runs ADJACENT to the rates so the normalized values
        # are comparable across runs regardless of tunnel state.
        try:
            bench_sharded_local_tpu(args, extra)
        except Exception as e:
            extra["sharded_local_error"] = repr(e)
        if probe is not None:
            pr = round(probe(), 1)
            extra["probe_rate_burst1"] = pr
            for rule in ("mgm", "dsa"):
                k = f"sharded_packed_{rule}_cycles_per_sec_tpu"
                if extra.get(k) and pr:
                    extra[f"{k}_normalized"] = round(extra[k] / pr, 4)

    remeasure_primary = None
    if args.only in ("all", "maxsum"):
        try:
            (value, vs, dcop, _tensors,
             remeasure_primary) = bench_maxsum(args)
            if probe is not None:
                # burst-1 probe: timed right after the burst-1 primary
                extra["probe_rate_burst1"] = round(probe(), 1)
        except BenchAbort as e:
            if watchdog:
                watchdog.cancel()
            print(json.dumps({
                "metric": metric, "value": 0.0, "unit": "iters/s",
                "vs_baseline": 0.0, "error": str(e),
            }), flush=True)
            raise SystemExit(1)
        # the SHARDED path on the real chip (1-device mesh): the
        # lane-packed per-shard engine (VERDICT r4 item 3) must carry
        # the single-chip engineering — 11.7k vs 1.1k generic at 10k
        # vars when this landed; ~14.1k after the rotated single-launch
        # cycle (ROADMAP item 7)
        try:
            import jax as _jax

            if _jax.default_backend() == "tpu":
                from pydcop_tpu.parallel.mesh import (
                    ShardedMaxSum, build_mesh,
                )

                shp = ShardedMaxSum(_tensors, build_mesh(1), damping=0.5)
                if shp.packs is not None:
                    shp.run(cycles=args.cycles)  # warmup / compile
                    extra["sharded_packed_maxsum_iters_per_sec_tpu"] = \
                        round(measure_rate(
                            lambda: shp.run(cycles=args.cycles),
                            args.cycles, args.repeat), 1)
                # sharded LOCAL SEARCH on the chip: the lane-packed
                # move rule (this round's tentpole) — see
                # bench_sharded_local_tpu
                bench_sharded_local_tpu(args, extra, dcop=dcop)
        except Exception as e:  # never lose the primary
            extra["sharded_packed_tpu_error"] = repr(e)

    if args.only in ("all", "dpop"):
        try:
            tps, dvs, _plan, btps, bdvs, wtps = bench_dpop(args)
            extra["dpop_tables_per_sec_%dvar" % args.dpop_vars] = round(tps, 1)
            extra["dpop_vs_python_reference"] = round(dvs, 1)
            if btps is not None:
                extra["dpop_tables_per_sec_batched100"] = round(btps, 1)
                extra["dpop_batched_vs_python_reference"] = round(bdvs, 1)
            if wtps is not None:
                extra["dpop_tables_per_sec_wholesweep"] = round(wtps, 1)
                extra["dpop_wholesweep_vs_python_reference"] = round(
                    wtps * (dvs / tps) if tps else 0.0, 1)
        except Exception as e:  # never lose the primary metric
            extra["dpop_error"] = repr(e)

    if args.only in ("all", "local"):
        try:
            if dcop is None:
                from pydcop_tpu.generators import generate_graph_coloring
                dcop = generate_graph_coloring(
                    n_variables=args.vars, n_colors=args.colors,
                    n_edges=args.edges, soft=True, n_agents=1, seed=1,
                )
            extra["mgm_cycles_per_sec_%dvar" % args.vars] = round(
                bench_local_search(dcop, "mgm"), 1)
            extra["dsa_cycles_per_sec_%dvar" % args.vars] = round(
                bench_local_search(dcop, "dsa"), 1)
            extra["mgm2_cycles_per_sec_%dvar" % args.vars] = round(
                bench_local_search(dcop, "mgm2"), 1)
        except Exception as e:
            extra["local_error"] = repr(e)

    if args.only in ("all", "scalefree"):
        try:
            extra.update(bench_scalefree(args))
        except Exception as e:
            extra["scalefree_error"] = repr(e)

    if args.only in ("all", "mixed"):
        try:
            extra.update(bench_mixed_arity(args))
        except Exception as e:
            extra["mixed_error"] = repr(e)

    if args.only in ("all", "batch"):
        try:
            extra.update(bench_batch(args, probe=probe))
        except Exception as e:
            extra["batch_error"] = repr(e)

    if args.only in ("all", "harness"):
        try:
            extra.update(bench_harness(args, probe=probe))
        except Exception as e:
            extra["harness_error"] = repr(e)

    if args.only in ("all", "serve"):
        try:
            extra.update(bench_serve(args, probe=probe))
        except Exception as e:
            extra["serve_error"] = repr(e)

    if args.only in ("all", "fleet"):
        try:
            extra.update(bench_fleet(args, probe=probe))
        except Exception as e:
            extra["fleet_error"] = repr(e)

    if args.only in ("all", "pfleet"):
        # process fleet (ISSUE 16): jobs/s + p99 across 1/2/4 replica
        # child processes, RTO under a real kill -9, and the cold-join
        # zero-compile pin (BENCHREF.md "Process fleet")
        try:
            extra.update(bench_pfleet(args, probe=probe))
        except Exception as e:
            extra["pfleet_error"] = repr(e)

    if args.only in ("all", "churn"):
        try:
            extra.update(bench_churn(args, probe=probe))
        except Exception as e:
            extra["churn_error"] = repr(e)

    if args.only in ("all", "memo"):
        # cross-request solution cache (ISSUE 18): hit taxonomy,
        # warm-vs-cold latency, the variant-speedup pin and the fleet
        # mid-trace-kill bit-match (BENCHREF.md "Solution cache")
        try:
            extra.update(bench_memo(args, probe=probe))
        except Exception as e:
            extra["memo_error"] = repr(e)

    if args.only in ("all", "precision"):
        # mixed-precision tiers (ISSUE 19): per-tier throughput/cost,
        # the declared bf16 statistical gate and the jaxpr-walked
        # collective payload-byte cut (BENCHREF.md "Mixed precision")
        try:
            extra.update(bench_precision(args, probe=probe))
        except Exception as e:
            extra["precision_error"] = repr(e)

    if args.only in ("all", "twin"):
        # city-scale digital twin (ISSUE 12): the combined sustained
        # scenario (traffic tiers + churn + chaos + --auto) scored by
        # SLO attainment, ladder ON vs OFF on the same seeds
        # (BENCHREF.md "City twin")
        try:
            extra.update(bench_twin(args, probe=probe))
        except Exception as e:
            extra["twin_error"] = repr(e)

    if args.only in ("all", "search"):
        # anytime exact search (ISSUE 15): gap-vs-time curve + node
        # throughput on two high-width instances that full DPOP
        # refuses under budget (BENCHREF.md "Anytime exact search")
        se = None
        try:
            se = bench_search_subprocess(args)
            extra.update(se.get("extra", {}))
        except Exception as e:
            extra["search_error"] = repr(e)
        if args.only == "search":
            out = se if se is not None else {
                "metric": "search_error", "value": 0.0, "unit": "",
                "vs_baseline": 0.0, "extra": extra,
            }
            if watchdog:
                watchdog.cancel()
            _maybe_snapshot(args, out)
            print(json.dumps(out), flush=True)
            return

    if args.only in ("all", "structured"):
        # table-free structured constraints (ISSUE 17): dense-vs-
        # structured byte ratios at table-fitting arity with parity
        # pinned, plus the 100-arity end-to-end headline (BENCHREF.md
        # "Table-free constraints")
        st = None
        try:
            st = bench_structured_subprocess(args)
            extra.update(st.get("extra", {}))
        except Exception as e:
            extra["structured_error"] = repr(e)
        if args.only == "structured":
            out = st if st is not None else {
                "metric": "structured_error", "value": 0.0, "unit": "",
                "vs_baseline": 0.0, "extra": extra,
            }
            if watchdog:
                watchdog.cancel()
            _maybe_snapshot(args, out)
            print(json.dumps(out), flush=True)
            return

    if args.only in ("all", "elastic"):
        # elastic device-fault tier (ISSUE 14): degraded-throughput
        # curve 8→6→4 devices, SDC detection latency with zero false
        # positives, sentinel overhead (BENCHREF.md "Elastic mesh")
        el = None
        try:
            el = bench_elastic_subprocess(args)
            extra.update(el.get("extra", {}))
        except Exception as e:
            extra["elastic_error"] = repr(e)
        if args.only == "elastic":
            out = el if el is not None else {
                "metric": "elastic_error", "value": 0.0, "unit": "",
                "vs_baseline": 0.0, "extra": extra,
            }
            if watchdog:
                watchdog.cancel()
            _maybe_snapshot(args, out)
            print(json.dumps(out), flush=True)
            return

    def run_with_transient_retry(fn, err_key):
        # the tunneled remote-compile service occasionally drops a
        # response mid-read; one retry keeps such a transient from
        # costing the recorded stretch number.  Deterministic failures
        # (OOM, shape errors) are not retried — rerunning a multi-minute
        # bench to hit the same error would just double time-to-failure.
        for attempt in (1, 2):
            try:
                extra.update(fn())
                extra.pop(err_key, None)
                break
            except Exception as e:
                extra[err_key] = repr(e)
                transient = any(
                    marker in repr(e)
                    for marker in ("remote_compile", "read body",
                                   "Connection", "Socket closed")
                )
                if not transient:
                    break

    if args.only in ("all", "convergence"):
        run_with_transient_retry(
            lambda: bench_convergence_stretch(args), "stretch_error")

    if args.only in ("all", "convergence2"):
        # stretch2 (VERDICT r4 item 4): 1M vars / 3M edges on ONE chip —
        # ~430MB of message+cost state in HBM, a scale the reference's
        # thread runtime cannot represent at all (BENCHREF.md: 311s wall
        # at 500 vars).  Budget: convergence in < 60s.
        # check_messages=False: the reference message criterion is
        # measured unfirable on these frustrated instances (22% of
        # messages oscillate under any damping — see the 100k run's
        # stretch_msg_unstable_frac, computed here too by final_diag)
        # and its in-scan evaluation costs ~15% of the wall at 3M edges;
        # plateau patience 3 chunks = 30 no-improvement cycles.
        run_with_transient_retry(
            lambda: bench_convergence_stretch(
                args, V=args.stretch2_vars, E=args.stretch2_edges,
                prefix="stretch2", max_cycles=args.stretch_max_cycles,
                check_messages=False, plateau_patience=3,
            ),
            "stretch2_error",
        )

    if args.only in ("all", "sharded"):
        try:
            if args.only == "all":
                # the full run always pushes the 1M stretch2 instance
                # through the 8-device mesh (VERDICT r4 item 4's sharded
                # leg); a bare --only sharded honors the opt-in flag so
                # the quick canary stays quick
                args.stretch2_sharded = True
            sh = bench_sharded_subprocess(args)
            extra[sh["metric"]] = sh["value"]
            extra.update({k: v for k, v in sh.items()
                          if k.startswith(("stretch2_sharded_",
                                           "stretch2_shard_",
                                           "sharded_packed_",
                                           "sharded_compact_",
                                           "sharded_maxsum_dense_",
                                           "shard_comm"))})
        except Exception as e:
            extra["sharded_error"] = repr(e)

    if args.only in ("all", "auto"):
        # learned-portfolio auto-selection (ISSUE 10): train on seeded
        # families, pick per-instance on a HELD-OUT suite; headline is
        # auto's total drift-normalized time-to-target vs the best
        # fixed single config (BENCHREF.md "Portfolio auto-selection")
        try:
            extra.update(bench_auto(args, probe=probe))
        except Exception as e:
            extra["auto_error"] = repr(e)

    if args.only in ("all", "dpop-sharded"):
        # sharded exact DPOP (ISSUE 9): util tables tiled over the
        # 8-device CPU mesh; the headline is the sweep wall on an
        # instance whose largest joint table exceeds the simulated
        # per-device budget, with the bitmatch flag and bytes-shipped
        # scorecard riding along (BENCHREF.md "Sharded exact DPOP")
        try:
            sh = bench_dpop_sharded_subprocess(args)
            extra[sh["metric"]] = sh["value"]
            extra.update({k: v for k, v in sh.items()
                          if k.startswith("dpop_sharded_")})
        except Exception as e:
            extra["dpop_sharded_error"] = repr(e)

    if args.only in ("dpop", "local", "convergence", "convergence2",
                     "scalefree", "mixed", "sharded", "dpop-sharded",
                     "probe", "batch", "harness", "serve", "churn",
                     "auto", "twin", "memo", "precision") \
            and not value:
        # single-part run: promote the part's headline measurement (not
        # config constants like stretch_vars) to the primary slot
        headline = ("_per_sec", "_wall_s", "_cycles_per", "probe_rate",
                    "batch_throughput", "serve_throughput",
                    "churn_speedup", "auto_speedup",
                    "memo_variant_speedup",
                    "twin_gold_attainment_ladder_on")
        if args.only == "twin":
            headline = ("twin_gold_attainment_ladder_on",) + headline
        if args.only == "memo":
            headline = ("memo_variant_speedup",) + headline
        if args.only == "precision":
            headline = ("precision_payload_cut_x",) + headline
        k = next(
            (k for k in extra if any(h in k for h in headline)),
            next((k for k in extra if not k.endswith("_error")), None),
        )
        out = {"metric": k or "error", "value": extra.get(k, 0.0),
               "unit": "", "vs_baseline": 0.0, "extra": extra}
        if watchdog:
            watchdog.cancel()
        _maybe_snapshot(args, out)
        print(json.dumps(out), flush=True)
        return

    if args.only == "all" and remeasure_primary is not None:
        # second primary burst ~30 min of wall after the first: the
        # tunnel's throughput drifts on a minutes timescale, so one
        # burst under-reads whenever it lands in a trough (r5 measured
        # 15.0k vs 21.4k for identical code).  POLICY (changed from
        # max-of-2, ADVICE r5): the primary is the SECOND burst — by
        # then dispatch caches and the tunnel are warm, so it is the
        # steady-state number and comparable round over round, where a
        # max-of-2 is order-statistic-biased upward and makes honest
        # regressions look like drift.  Both bursts stay recorded;
        # regression_check baselines written before this round carry a
        # max-of-2 primary, so a one-round apparent drop up to the
        # burst spread is the policy change, not a code regression
        # (flagged via extra["primary_policy"]).
        extra["primary_burst1"] = round(value, 2)
        extra["primary_policy"] = "burst2_steady_state"
        try:
            second = remeasure_primary()
            extra["primary_burst2"] = round(second, 2)
            if probe is not None:
                # burst-2 probe: same tunnel state as the burst that
                # defines the primary
                extra["probe_rate_burst2"] = round(probe(), 1)
            if second and value:
                vs = vs * (second / value)
            value = second
        except Exception as e:
            extra["primary_remeasure_error"] = repr(e)

    if value and args.only in ("all", "maxsum"):
        # the drift-normalized primary: engine rate per unit of probe
        # rate, measured in the SAME burst — dimensionless, so it
        # cancels tunnel/host drift round over round.  regression_check
        # prefers it over the raw primary when both rounds carry it.
        pr = (extra.get("probe_rate_burst2")
              or extra.get("probe_rate_burst1"))
        if pr:
            extra["primary_normalized"] = round(value / pr, 4)

    if args.only == "all":
        here = os.path.dirname(os.path.abspath(__file__)) or "."
        drift_verdict(value, extra, here)
        regression_check(value, extra, here)

    if watchdog:
        watchdog.cancel()
    out = {
        "metric": metric,
        "value": round(value, 2),
        "unit": "iters/s",
        "vs_baseline": round(vs, 2),
        "extra": extra,
    }
    _maybe_snapshot(args, out)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
