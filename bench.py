#!/usr/bin/env python
"""Benchmark: MaxSum message-passing iterations/sec on a 10k-variable random
graph (the BASELINE.md primary metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": R}

vs_baseline compares against a freshly-measured reference-equivalent
python implementation of the same factor-update math (the reference's
factor_costs_for_var enumerates the cross product of neighbor domains in
python per factor per cycle — pydcop/algorithms/maxsum.py:345-423); its
per-cycle time is measured on a factor subsample here and extrapolated to
the full graph.  Runs on the default JAX backend (the TPU under the
driver).
"""
from __future__ import annotations

import argparse
import itertools
import json
import time

import numpy as np


def python_reference_cycle_time(tensors, sample: int = 200) -> float:
    """Seconds per full message-passing cycle for a python-loop
    implementation of the factor update (reference-equivalent math)."""
    b = max(tensors.buckets, key=lambda b: b.n_factors)
    t_np = np.asarray(b.tensors)
    n = min(sample, b.n_factors)
    D = tensors.max_domain_size
    q = np.zeros((b.arity, D), dtype=np.float32)
    t0 = time.perf_counter()
    for f in range(n):
        cost = t_np[f]
        for p in range(b.arity):
            others = [o for o in range(b.arity) if o != p]
            for d in range(D):
                best = float("inf")
                for combo in itertools.product(range(D), repeat=len(others)):
                    idx = [0] * b.arity
                    idx[p] = d
                    for o, c in zip(others, combo):
                        idx[o] = c
                    val = cost[tuple(idx)] + sum(
                        q[o, c] for o, c in zip(others, combo)
                    )
                    if val < best:
                        best = val
    per_factor = (time.perf_counter() - t0) / n
    total_factors = sum(bb.n_factors for bb in tensors.buckets)
    return per_factor * total_factors


def _arm_watchdog(seconds: float, metric: str):
    """Guarantee the one-JSON-line contract even if device init wedges
    (the tunneled TPU is single-tenant; a stale claim can block forever).
    Returns the Timer so the success path can cancel it."""
    import os
    import threading

    def fire():
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": 0.0,
                    "unit": "iters/s",
                    "vs_baseline": 0.0,
                    "error": f"watchdog: no result within {seconds}s "
                    "(device init or run wedged)",
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vars", type=int, default=10_000)
    ap.add_argument("--edges", type=int, default=30_000)
    ap.add_argument("--colors", type=int, default=3)
    ap.add_argument("--cycles", type=int, default=50)
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument(
        "--stretch", action="store_true",
        help="100k-var / 300k-edge instance via the direct array compiler",
    )
    ap.add_argument(
        "--engine", choices=["auto", "generic", "packed"], default="auto",
        help="force an engine (auto = packed on TPU when applicable)",
    )
    ap.add_argument("--watchdog", type=float, default=900.0)
    args = ap.parse_args()
    if args.stretch:
        args.vars, args.edges = 100_000, 300_000
    metric = f"maxsum_iters_per_sec_{args.vars}var_{args.edges}edge"
    watchdog = None
    if args.watchdog:
        watchdog = _arm_watchdog(args.watchdog, metric)

    import jax
    import jax.numpy as jnp

    from pydcop_tpu.ops import compile_factor_graph
    from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
    from pydcop_tpu.ops.pallas_maxsum import (
        packed_cycle, packed_init_state, try_pack_for_pallas,
    )

    if args.stretch:
        from pydcop_tpu.ops.compile import compile_binary_from_arrays

        rng = np.random.default_rng(1)
        edge_i = rng.integers(0, args.vars, args.edges)
        edge_j = (edge_i + 1 + rng.integers(
            0, args.vars - 1, args.edges)) % args.vars
        mats = rng.uniform(0, 1, (args.edges, args.colors, args.colors))
        mats += np.eye(args.colors) * 10  # coloring penalty
        tensors = compile_binary_from_arrays(
            edge_i, edge_j, mats.astype(np.float32), args.vars,
            unary=rng.uniform(0, 0.01, (args.vars, args.colors)).astype(
                np.float32
            ),
        )
    else:
        from pydcop_tpu.generators import generate_graph_coloring

        dcop = generate_graph_coloring(
            n_variables=args.vars,
            n_colors=args.colors,
            n_edges=args.edges,
            soft=True,
            n_agents=1,
            seed=1,
        )
        tensors = compile_factor_graph(dcop)

    # engine: lane-packed pallas kernel on TPU (binary graphs), else generic
    packed = None
    if args.engine == "packed":
        packed = try_pack_for_pallas(tensors)
        if packed is None:
            if watchdog is not None:
                watchdog.cancel()
            print(json.dumps({
                "metric": metric, "value": 0.0, "unit": "iters/s",
                "vs_baseline": 0.0,
                "error": "--engine packed: graph not packable",
            }), flush=True)
            raise SystemExit(1)
    elif args.engine == "auto" and jax.default_backend() == "tpu":
        packed = try_pack_for_pallas(tensors)

    @jax.jit
    def run_n(q, r):
        def body(carry, _):
            q, r = carry
            if packed is not None:
                q2, r2, _, _ = packed_cycle(packed, q, r, damping=0.5)
            else:
                q2, r2, _, _ = maxsum_cycle(tensors, q, r, damping=0.5)
            return (q2, r2), ()

        (q, r), _ = jax.lax.scan(body, (q, r), None, length=args.cycles)
        return q, r

    q0, r0 = (
        packed_init_state(packed) if packed is not None
        else init_messages(tensors)
    )
    # warmup / compile
    q, r = run_n(q0, r0)
    jax.block_until_ready((q, r))

    times = []
    for _ in range(args.repeat):
        t0 = time.perf_counter()
        q, r = run_n(q0, r0)
        jax.block_until_ready((q, r))
        times.append(time.perf_counter() - t0)
    best = min(times)
    iters_per_sec = args.cycles / best

    ref_cycle_s = python_reference_cycle_time(tensors)
    ref_iters_per_sec = 1.0 / ref_cycle_s if ref_cycle_s > 0 else 0.0
    vs_baseline = (
        iters_per_sec / ref_iters_per_sec if ref_iters_per_sec else 0.0
    )

    if watchdog is not None:
        watchdog.cancel()
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(iters_per_sec, 2),
                "unit": "iters/s",
                "vs_baseline": round(vs_baseline, 2),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
