"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh (the standard JAX testing pattern).
Must run before jax is imported anywhere.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# persistent compilation cache cuts repeat test-run time drastically
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
