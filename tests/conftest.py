"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh (the standard JAX testing pattern).

Note: in this environment a sitecustomize imports jax at interpreter start
with JAX_PLATFORMS=axon (the single tunneled TPU chip), so env-var changes
here are too late — the platform must be overridden through jax.config.
Tests must never touch the TPU: it is single-tenant and a concurrent holder
blocks every other process.
"""
import os

# XLA flags are read at first backend initialization, which has not happened
# yet at conftest time — set before any jax.devices() call.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# persistent compilation cache cuts repeat test-run time drastically
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
