"""Assignment/cost-level parity against the ACTUAL reference runtime.

Each case runs the real /root/reference pyDCOP (thread-mode actors, via
tests/parity/ref_runner.py in a subprocess with py3.12 shims) and our
tensor runtime on the same instance, then compares solution quality.

Reference DPOP is excluded: under the shimmed 3.12 runtime it returns an
empty assignment (its computation threads die silently — reproduced on
the unmodified reference via its own orchestrator); our DPOP is instead
cross-checked against brute force in tests/api/test_api_complete.py,
which is the stronger oracle for an exact algorithm.
"""
import json
import os
import subprocess
import sys

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime import solve_result

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
RUNNER = os.path.join(os.path.dirname(__file__), "ref_runner.py")


def run_reference(instance, algo, timeout=6):
    out = subprocess.run(
        [sys.executable, RUNNER, os.path.join(INSTANCES, instance), algo,
         str(timeout)],
        capture_output=True, text=True, timeout=180,
    )
    assert out.returncode == 0, out.stderr[-1200:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_ours(instance, algo, cycles=40, seed=0):
    dcop = load_dcop_from_file(os.path.join(INSTANCES, instance))
    return solve_result(dcop, algo, cycles=cycles, seed=seed)


def best_of_seeds(instance, algo, n_seeds=8, cycles=40):
    """Local search is start-dependent on BOTH sides (random initial
    values); quality parity means our solver reaches the reference's
    cost from some start."""
    return min(
        (run_ours(instance, algo, cycles=cycles, seed=s) for s in
         range(n_seeds)),
        key=lambda r: r.cost,
    )


@pytest.mark.parametrize("algo", ["maxsum", "dsa", "mgm"])
def test_tuto_cost_parity(algo):
    """graph_coloring_tuto: our solver must reach at least the
    reference's solution quality (both sides are stochastic local
    search / BP, so the claim is directional, not exact-equality)."""
    ref = run_reference("graph_coloring_tuto.yaml", algo)
    assert ref["cost"] is not None and ref["cost"] <= 19, ref
    ours = best_of_seeds("graph_coloring_tuto.yaml", algo)
    assert ours.cost <= ref["cost"] + 1e-6
    assert ours.cost == pytest.approx(12)  # we find the optimum
    assert ours.violation == 0


def test_tuto_maxsum_assignment_parity():
    ref = run_reference("graph_coloring_tuto.yaml", "maxsum")
    ours = run_ours("graph_coloring_tuto.yaml", "maxsum")
    assert ours.assignment == ref["assignment"]  # all-G, unique optimum


def test_intention_mgm_cost_parity():
    """coloring_intention: intentional constraints + variable costs.
    Both sides start randomly and may land on either local optimum
    (-0.1 or 0.1); ours must match or beat the reference's run AND
    reach the true optimum from some start."""
    ref = run_reference("coloring_intention.yaml", "mgm")
    ours = best_of_seeds("coloring_intention.yaml", "mgm")
    assert ours.cost <= ref["cost"] + 1e-6
    assert ours.cost == pytest.approx(-0.1)
