"""Assignment/cost-level parity against the ACTUAL reference runtime.

Each case runs the real /root/reference pyDCOP (thread-mode actors, via
tests/parity/ref_runner.py in a subprocess with py3.12 shims) and our
tensor runtime on the same instance, then compares solution quality.

Reference DPOP dies under the shimmed py3.12 runtime (its computation
threads exit silently and its join() needs the NumPy-1 ndarray.itemset),
so DPOP cases re-run the reference under the image's python3.11 +
NumPy 1.24 interpreter instead (VERDICT r3 item 8), borrowing the
pure-python deps from the 3.12 site-packages via REF_EXTRA_PATH — see
ref_runner.py.  Brute-force cross-checks remain in
tests/api/test_api_complete.py.
"""
import json
import os
import shutil
import subprocess
import sys
import sysconfig

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime import solve_result

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
RUNNER = os.path.join(os.path.dirname(__file__), "ref_runner.py")

#: interpreter for the DPOP oracle: NumPy 1.x (ndarray.itemset) and a
#: pre-3.12 threading runtime the 3.7-era reference survives on
PY311 = shutil.which("python3.11")

#: the oracle itself: these are parity tests against the REAL pyDCOP
#: checkout — without it there is nothing to compare against, so the
#: module skips instead of failing on an absent interpreter path
pytestmark = pytest.mark.skipif(
    not os.path.isdir("/root/reference"),
    reason="reference pyDCOP checkout not present at /root/reference",
)


def run_reference(instance, algo, timeout=6, interpreter=None):
    env = dict(os.environ)
    cmd_py = interpreter or sys.executable
    if interpreter is not None:
        env["REF_EXTRA_PATH"] = sysconfig.get_paths()["purelib"]
    out = subprocess.run(
        [cmd_py, RUNNER, os.path.join(INSTANCES, instance), algo,
         str(timeout)],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 0, out.stderr[-1200:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_ours(instance, algo, cycles=40, seed=0):
    dcop = load_dcop_from_file(os.path.join(INSTANCES, instance))
    return solve_result(dcop, algo, cycles=cycles, seed=seed)


def best_of_seeds(instance, algo, n_seeds=8, cycles=40):
    """Local search is start-dependent on BOTH sides (random initial
    values); quality parity means our solver reaches the reference's
    cost from some start."""
    return min(
        (run_ours(instance, algo, cycles=cycles, seed=s) for s in
         range(n_seeds)),
        key=lambda r: r.cost,
    )


@pytest.mark.parametrize("algo", ["maxsum", "dsa", "mgm"])
def test_tuto_cost_parity(algo):
    """graph_coloring_tuto: our solver must reach at least the
    reference's solution quality (both sides are stochastic local
    search / BP, so the claim is directional, not exact-equality)."""
    ref = run_reference("graph_coloring_tuto.yaml", algo)
    assert ref["cost"] is not None and ref["cost"] <= 19, ref
    ours = best_of_seeds("graph_coloring_tuto.yaml", algo)
    assert ours.cost <= ref["cost"] + 1e-6
    assert ours.cost == pytest.approx(12)  # we find the optimum
    assert ours.violation == 0


def test_tuto_maxsum_assignment_parity():
    ref = run_reference("graph_coloring_tuto.yaml", "maxsum")
    ours = run_ours("graph_coloring_tuto.yaml", "maxsum")
    assert ours.assignment == ref["assignment"]  # all-G, unique optimum


@pytest.mark.skipif(PY311 is None, reason="python3.11 not in image")
@pytest.mark.parametrize("instance", [
    "graph_coloring_tuto.yaml",
    "coloring_intention.yaml",
])
def test_dpop_exact_parity(instance):
    """The REAL reference DPOP (under python3.11 + NumPy 1.24) and our
    sweep engine are both exact: costs must agree exactly on
    pseudo-tree instances — the end-to-end oracle the py3.12 shims
    could not provide (VERDICT r3 item 8)."""
    ref = run_reference(instance, "dpop", interpreter=PY311)
    assert ref["assignment"], "reference DPOP returned empty assignment"
    ours = run_ours(instance, "dpop")
    assert ours.cost == pytest.approx(ref["cost"], abs=1e-4)
    # re-evaluate the reference's assignment under OUR cost model: no
    # hard violations, and exactly our optimum (ties may differ in the
    # chosen assignment, never in its cost)
    dcop = load_dcop_from_file(os.path.join(INSTANCES, instance))
    v_ref, c_ref = dcop.solution_cost(ref["assignment"], 10000)
    assert v_ref == 0
    assert c_ref == pytest.approx(ours.cost, abs=1e-4)


@pytest.mark.parametrize("algo", ["mgm", "dsa"])
def test_secp_nary_cost_parity(algo):
    """secp_small: a REAL n-ary instance (unary light costs, binary +
    ternary + quaternary model/rule factors, D=5) through the ACTUAL
    reference runtime — the family the round-5 quaternary packing
    covers.  Directional quality parity: our solver must reach the
    reference's cost from some start (the packed kernels bit-match our
    generic engine in tests/unit, so this oracle covers them too).

    The shimmed reference thread runtime occasionally fails to complete
    an assignment on this instance under heavy host load (its actor
    threads starve within the timeout) — that is a reference-runtime
    limitation, not a parity signal, so the oracle run retries once and
    skips if the reference still can't answer."""
    ref = None
    for _attempt in range(2):
        try:
            ref = run_reference("secp_small.yaml", algo, timeout=8)
            if ref["cost"] is not None and ref["violation"] == 0:
                break
        except subprocess.TimeoutExpired:
            ref = None  # starved threads never joined; retry/skip
        except AssertionError as e:
            # starvation surfaces as the runner's 'incomplete
            # assignment' ValueError (nonzero rc, stderr in the assert
            # message); any OTHER runner crash is a real regression in
            # the oracle and must fail loudly, not skip
            if "incomplete assignment" not in str(e):
                raise
            ref = None
    if ref is None or ref["cost"] is None or ref["violation"] != 0:
        pytest.skip("reference runtime did not complete an assignment "
                    "on secp_small (thread starvation under load)")
    ours = best_of_seeds("secp_small.yaml", algo)
    assert ours.violation == 0
    assert ours.cost <= ref["cost"] + 1e-6


@pytest.mark.parametrize("algo", ["mgm2", "gdba"])
def test_tuto_pair_and_breakout_cost_parity(algo):
    """Round-5 verdict item 4 (partial): the pair-coordination (mgm2)
    and breakout (gdba) families get reference-oracle cases too.  Both
    sides are start-dependent local search, so the claim is directional
    — our solver must reach the reference's cost from some start — and
    on this instance our best-of-seeds lands on the known optimum 12."""
    ref = run_reference("graph_coloring_tuto.yaml", algo)
    assert ref["cost"] is not None, ref
    ours = best_of_seeds("graph_coloring_tuto.yaml", algo)
    assert ours.cost <= ref["cost"] + 1e-6
    assert ours.cost == pytest.approx(12)
    assert ours.violation == 0


@pytest.mark.parametrize("algo", ["mgm2", "gdba"])
def test_csp_pair_and_breakout_solve_parity(algo):
    """Hard-constraint coloring (breakout's home turf): both the
    reference run and our best-of-seeds must reach a zero-violation
    zero-cost assignment on the satisfiable 3-cycle."""
    ref = run_reference("coloring_csp.yaml", algo, timeout=8)
    ours = best_of_seeds("coloring_csp.yaml", algo, cycles=60)
    assert ours.violation == 0
    assert ours.cost == pytest.approx(0)
    if ref["cost"] is not None and ref["violation"] == 0:
        assert ours.cost <= ref["cost"] + 1e-6


def test_intention_mgm_cost_parity():
    """coloring_intention: intentional constraints + variable costs.
    Both sides start randomly and may land on either local optimum
    (-0.1 or 0.1); ours must match or beat the reference's run AND
    reach the true optimum from some start."""
    ref = run_reference("coloring_intention.yaml", "mgm")
    ours = best_of_seeds("coloring_intention.yaml", "mgm")
    assert ours.cost <= ref["cost"] + 1e-6
    assert ours.cost == pytest.approx(-0.1)
