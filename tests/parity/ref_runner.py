"""Run the ACTUAL /root/reference pyDCOP on an instance and print its
result as one JSON line — the parity oracle for
tests/parity/test_reference_parity.py.

Usage: python ref_runner.py <instance.yaml> <algo> <timeout_s>

Python-3.12 shims only (collections ABC aliases + a no-op
websocket_server module injected into sys.modules); no reference file
is modified or copied.
"""
import json
import os
import sys
import types

# Older-interpreter mode (the DPOP parity oracle, VERDICT r3 item 8):
# the reference's join() uses ndarray.itemset (removed in NumPy 2) and
# its computation threads die under py3.12, so test_reference_parity
# re-runs DPOP cases under the image's python3.11 + NumPy 1.24.  That
# interpreter lacks the pure-python deps (networkx/yaml); REF_EXTRA_PATH
# names the py3.12 site-packages to borrow them from — APPENDED so the
# 3.11 interpreter's own numpy stays first (the 3.12 numpy is a 2.x
# C-extension build that cannot load), and yaml falls back to its pure
# loader when its 3.12 _yaml extension fails to import.
_extra = os.environ.get("REF_EXTRA_PATH")
if _extra:
    sys.path.append(_extra)

# --- py3.12 compat for the 3.7-era reference
import collections
import collections.abc
for _n in ("Iterable", "Mapping", "Sequence", "Callable", "Hashable",
           "MutableMapping", "Set", "MutableSet", "MutableSequence"):
    if not hasattr(collections, _n):
        setattr(collections, _n, getattr(collections.abc, _n))

# --- websocket-server is not in the image; the UI is unused here
_ws = types.ModuleType("websocket_server")
_wsi = types.ModuleType("websocket_server.websocket_server")


class _WS:  # noqa: D401 - minimal surface pydcop.infrastructure.ui needs
    def __init__(self, *a, **k): pass

    def __getattr__(self, name):
        return lambda *a, **k: None


_wsi.WebsocketServer = _WS
_ws.WebsocketServer = _WS
_ws.websocket_server = _wsi
sys.modules["websocket_server"] = _ws
sys.modules["websocket_server.websocket_server"] = _wsi

sys.path.insert(0, "/root/reference")

import logging
logging.disable(logging.CRITICAL)


def main():
    instance, algo, timeout = sys.argv[1], sys.argv[2], float(sys.argv[3])
    from pydcop.dcop.yamldcop import load_dcop_from_file
    from pydcop.algorithms import AlgorithmDef, load_algorithm_module
    from pydcop.infrastructure.run import solve

    dcop = load_dcop_from_file([instance])
    mod = load_algorithm_module(algo)
    algo_def = AlgorithmDef.build_with_default_param(
        algo, {}, parameters_definitions=mod.algo_params,
        mode=dcop.objective,
    )
    # oneagent for dpop: the reference's dpop.computation_memory raises
    # NotImplementedError (dpop.py:81), which the adhoc distribution
    # calls; oneagent needs no memory callback
    dist = "oneagent" if algo == "dpop" else "adhoc"
    assignment = solve(dcop, algo_def, dist, timeout=timeout)
    violation, cost = (None, None)
    if assignment:
        # reference solution_cost returns (hard_violations, soft_cost)
        violation, cost = dcop.solution_cost(assignment, 10000)
    def _py(o):
        # reference assignments can carry numpy scalars (e.g. int64
        # domain values on SECP instances); JSON needs plain python
        return o.item() if hasattr(o, "item") else str(o)

    print(json.dumps({"assignment": assignment, "cost": cost,
                      "violation": violation}, default=_py), flush=True)


if __name__ == "__main__":
    main()
