"""CLI surface of the replicated solve fleet: ``pydcop_tpu serve
--replicas N``.

The fast test is the fleet twin of the serve smoke: a seeded Poisson
burst through a 2-replica fleet, every job completing with the
standalone solve's exact cost/cycle/assignment and the output JSON
carrying the ``fleet`` section (router state, per-replica counters).

The ``make fleet-smoke`` scenario is ``slow``-marked: a 2-replica
fleet with ``kill_replica`` injected mid-trace (the thread-hosted
kill -9: the replica's scheduler halts without draining and only its
journal survives) — every job must still complete bit-identically,
the orphans re-seated on the peer, with a finite recovery-time
objective recorded.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
CSP = os.path.join(INSTANCES, "coloring_csp.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


class TestFleetSmoke:
    def test_two_replica_fleet_serves_bit_identical(self):
        """A seeded Poisson burst through --replicas 2: every job
        FINISHED with exactly the standalone solve's cost, cycle and
        assignment, the fleet section reports the routing scorecard,
        and each per-job result names the replica that served it."""
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.run import solve_result

        proc = run_cli(
            "serve", "-a", "mgm", "--jobs", "6", "--replicas", "2",
            "--arrival", "poisson", "--rate", "50",
            "--arrival-seed", "7", "--lanes", "2",
            "--max-cycles", "2000", "--prewarm", TUTO, CSP,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert len(out["results"]) == 6
        dcops = {f: load_dcop_from_file([f]) for f in (TUTO, CSP)}
        for jid, m in out["results"].items():
            assert m["status"] == "FINISHED", (jid, m)
            fn, seed = m["label"].rsplit(":", 1)
            seq = solve_result(dcops[fn], "mgm", seed=int(seed))
            assert m["cost"] == seq.cost, (jid, m)
            assert m["cycle"] == seq.cycle, (jid, m)
            assert m["assignment"] == seq.assignment, (jid, m)
            assert m["serve"]["replica"].startswith("replica-")
        fleet = out["fleet"]
        assert fleet["fleet"]["jobs_routed"] == 6
        assert set(fleet["replicas"]) == {"replica-0", "replica-1"}
        assert all(r["up"] for r in fleet["replicas"].values())

    def test_processes_requires_journal_dir(self):
        proc = run_cli(
            "serve", "-a", "dsa", "--replicas", "2", "--processes",
            TUTO,
        )
        assert proc.returncode == 1
        assert "journal-dir" in json.loads(proc.stdout)["error"]

    def test_resume_rejected_with_replicas(self):
        proc = run_cli(
            "serve", "-a", "mgm", "--replicas", "2", "--resume",
            "--journal-dir", "/tmp/x", TUTO,
        )
        assert proc.returncode == 1
        assert "fleet" in json.loads(proc.stdout)["error"]


@pytest.mark.slow
class TestProcessFleetKillSmoke:
    """`make pfleet-smoke`: the ISSUE 16 chaos pin through the CLI —
    a REAL ``kill -9`` of a whole replica child process mid-trace.
    Every job must still complete bit-identically on the survivor and
    the watchdog must relaunch the slot."""

    def test_kill_process_midtrace_all_complete_bit_identical(
        self, tmp_path
    ):
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.run import solve_result

        plan = tmp_path / "plan.yaml"
        plan.write_text(
            "seed: 7\n"
            "faults:\n"
            "  - kind: kill_process\n"
            "    replica: 0\n"
            "    cycle: 3\n"
        )
        journal = str(tmp_path / "pfleet")
        proc = run_cli(
            "serve", "-a", "dsa", "--jobs", "8", "--replicas", "2",
            "--processes", "--lanes", "2", "--max-cycles", "2000",
            "--journal-dir", journal, "--fault-plan", str(plan),
            "--prewarm", TUTO, CSP,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert len(out["results"]) == 8
        dcops = {f: load_dcop_from_file([f]) for f in (TUTO, CSP)}
        for jid, m in out["results"].items():
            assert m["status"] == "FINISHED", (jid, m)
            fn, seed = m["label"].rsplit(":", 1)
            seq = solve_result(dcops[fn], "dsa", seed=int(seed))
            assert m["cost"] == seq.cost, (jid, m)
            assert m["cycle"] == seq.cycle, (jid, m)
            assert m["assignment"] == seq.assignment, (jid, m)
        fleet = out["fleet"]["fleet"]
        assert fleet["replicas_down"] >= 1
        assert fleet["faults_injected"] >= 1
        recov = out["fleet"]["recoveries"]
        assert recov and recov[0]["rto_s"] is not None
        # the journal socket framed + fsynced the whole handoff
        fj = os.path.join(journal, "fleet.jsonl")
        with open(fj, encoding="utf-8") as f:
            kinds = [json.loads(line)["kind"] for line in f
                     if line.strip()]
        assert kinds.count("done") == 8


@pytest.mark.slow
class TestFleetKillSmoke:
    """`make fleet-smoke`: the chaos-pin scenario through the CLI."""

    def test_kill_replica_midtrace_all_complete_bit_identical(
        self, tmp_path
    ):
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.run import solve_result

        plan = tmp_path / "plan.yaml"
        plan.write_text(
            "seed: 7\n"
            "faults:\n"
            "  - kind: kill_replica\n"
            "    replica: 0\n"
            "    cycle: 3\n"   # ~0.15s in: the un-prewarmed burst is
                               # still compiling/solving on replica-0
        )
        journal = str(tmp_path / "fleet")
        proc = run_cli(
            "serve", "-a", "dsa", "--jobs", "16", "--replicas", "2",
            "--lanes", "1", "--max-cycles", "2000",
            "--journal-dir", journal, "--fault-plan", str(plan),
            TUTO, CSP,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert len(out["results"]) == 16
        dcops = {f: load_dcop_from_file([f]) for f in (TUTO, CSP)}
        for jid, m in out["results"].items():
            assert m["status"] == "FINISHED", (jid, m)
            fn, seed = m["label"].rsplit(":", 1)
            seq = solve_result(dcops[fn], "dsa", seed=int(seed))
            assert m["cost"] == seq.cost, (jid, m)
            assert m["cycle"] == seq.cycle, (jid, m)
            assert m["assignment"] == seq.assignment, (jid, m)
            # the dead replica served nothing to completion
            assert m["serve"]["replica"] == "replica-1", (jid, m)
        fleet = out["fleet"]["fleet"]
        assert fleet["replicas_down"] == 1
        assert fleet["faults_injected"] == 1
        assert fleet["jobs_reseated"] >= 1
        recov = out["fleet"]["recoveries"]
        assert recov and recov[0]["rto_s"] is not None
        assert recov[0]["rto_s"] > 0
        # the fleet journal streamed the whole handoff
        fj = os.path.join(journal, "fleet.jsonl")
        with open(fj, encoding="utf-8") as f:
            kinds = [json.loads(line)["kind"] for line in f
                     if line.strip()]
        assert kinds.count("done") == 16
        assert "reseat" in kinds
