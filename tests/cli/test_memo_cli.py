"""CLI surface of the cross-request solution cache: ``pydcop_tpu
serve --memo`` (the `make memo-smoke` scenario).

The fast test serves a seeded duplicate trace twice through real CLI
processes: pass 2 starts cold in a fresh process, rehydrates the
persisted cache via ``--resume`` and must answer with a positive
exact-hit rate and bit-identical results.  The kill -9 test is
``slow``-marked: a SIGKILLed service loses nothing — the restarted
process rehydrates the CRC'd entries and serves duplicates from them.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


def _memo_stats(out):
    return out["serve"]["memo"]


class TestMemoSmoke:
    def test_duplicate_trace_twice_second_pass_hits(self, tmp_path):
        """`make memo-smoke` leg 1: the same seeded duplicate trace
        served twice; the second pass (a FRESH process rehydrating the
        persisted cache) answers duplicates from the cache with a
        positive hit rate and bit-identical results."""
        journal = str(tmp_path / "journal")
        args = ("serve", "-a", "mgm", "--jobs", "4",
                "--seed-period", "2", "--lanes", "2",
                "--memo", "--journal-dir", journal, TUTO)
        p1 = run_cli(*args)
        assert p1.returncode == 0, p1.stderr[-2000:]
        out1 = json.loads(p1.stdout)
        memo1 = _memo_stats(out1)
        assert memo1["inserts"] >= 1
        # the persisted entries are on disk beside the journal
        memo_dir = os.path.join(journal, "memo")
        assert [f for f in os.listdir(memo_dir) if f.endswith(".npz")]

        p2 = run_cli(*args[:-1], "--resume", TUTO)
        assert p2.returncode == 0, p2.stderr[-2000:]
        out2 = json.loads(p2.stdout)
        memo2 = _memo_stats(out2)
        assert memo2["rehydrated"] >= 1
        assert memo2["hits_exact"] >= 1  # second-pass hit rate > 0
        # every cache-served job is bit-identical to its pass-1 twin
        by_label1 = {m["label"]: m for m in out1["results"].values()
                     if isinstance(m, dict) and m.get("label")}
        for m in out2["results"].values():
            if not isinstance(m, dict) or not m.get("memo"):
                continue
            if m["memo"].get("hit") != "exact":
                continue
            twin = by_label1[m["label"]]
            assert m["assignment"] == twin["assignment"]
            assert m["cost"] == twin["cost"]

    def test_memo_provenance_in_per_job_metrics(self, tmp_path):
        """Every job served with --memo carries a hit/miss provenance
        stamp in its metrics."""
        p = run_cli("serve", "-a", "mgm", "--jobs", "2",
                    "--seed-period", "1", "--lanes", "2",
                    "--memo", TUTO)
        assert p.returncode == 0, p.stderr[-2000:]
        out = json.loads(p.stdout)
        kinds = [m["memo"]["hit"] for m in out["results"].values()
                 if isinstance(m, dict)]
        assert len(kinds) == 2
        assert all(k in ("exact", "variant", "miss") for k in kinds)


@pytest.mark.slow
class TestMemoCrashRehydrate:
    def test_kill9_midtrace_then_resume_rehydrates_cache(
            self, tmp_path):
        """`make memo-smoke` leg 2: SIGKILL the serving process
        mid-trace AFTER at least one entry persisted; the restarted
        process rehydrates the cache from the CRC'd npz files and
        serves duplicates from it — no correctness lost."""
        journal = str(tmp_path / "journal")
        memo_dir = os.path.join(journal, "memo")
        proc = subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu", "serve", "-a", "mgm",
             "--jobs", "12", "--seed-period", "2",
             "--arrival", "poisson", "--rate", "10",
             "--arrival-seed", "3", "--lanes", "2",
             "--memo", "--journal-dir", journal, TUTO],
            env=ENV, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        # wait until a memo entry lands on disk, then kill -9
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.isdir(memo_dir) and any(
                    f.endswith(".npz") for f in os.listdir(memo_dir)):
                break
            if proc.poll() is not None:
                break  # trace finished before we could kill: still fine
            time.sleep(0.05)
        else:
            proc.kill()
            raise AssertionError("no memo entry was ever persisted")
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

        p2 = run_cli(
            "serve", "-a", "mgm", "--jobs", "4", "--seed-period", "2",
            "--lanes", "2", "--memo", "--journal-dir", journal,
            "--resume", TUTO,
        )
        assert p2.returncode == 0, p2.stderr[-2000:]
        out = json.loads(p2.stdout)
        memo = _memo_stats(out)
        assert memo["rehydrated"] >= 1
        assert memo["corrupt_skipped"] == 0
        assert memo["hits_exact"] >= 1
        for jid, m in out["results"].items():
            if isinstance(m, dict) and m.get("status"):
                assert m["status"] == "FINISHED", (jid, m)
