"""CLI subprocess tests (reference twin: tests/dcop_cli/ — spawn the real
CLI against YAML instances and assert on the JSON output)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
CSP = os.path.join(INSTANCES, "coloring_csp.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,  # drop the axon sitecustomize, add the repo
}


def run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV, cwd=REPO,
    )


def json_out(proc):
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout)


class TestSolve:
    def test_solve_maxsum(self):
        out = json_out(
            run_cli("--timeout", "20", "solve", "--algo", "maxsum", TUTO)
        )
        assert out["assignment"] == {
            "v1": "G", "v2": "G", "v3": "G", "v4": "G"
        }
        assert out["cost"] == 12
        assert out["status"] == "FINISHED"

    def test_solve_dpop(self):
        out = json_out(run_cli("solve", "--algo", "dpop", TUTO))
        assert out["cost"] == 12

    def test_solve_with_params(self):
        out = json_out(
            run_cli(
                "--timeout", "20", "solve", "--algo", "dsa",
                "--algo_params", "variant:C",
                "--algo_params", "probability:0.8",
                "--cycles", "40", CSP,
            )
        )
        assert out["cost"] == 0

    def test_solve_unknown_algo_fails(self):
        proc = run_cli("solve", "--algo", "nope", TUTO)
        assert proc.returncode != 0

    def test_output_file(self, tmp_path):
        out_file = str(tmp_path / "res.json")
        run_cli("--output", out_file, "solve", "--algo", "dpop", TUTO)
        with open(out_file) as f:
            assert json.load(f)["cost"] == 12


class TestSolveMatrix:
    """End-to-end CLI solves across FOUR problem families (round-5
    verdict item 8: the CLI/parity tests previously exercised only
    coloring + one SECP): graph coloring, SECP smart lighting, an Ising
    grid and PEAV-style meeting scheduling, each from a committed YAML
    in tests/instances/."""

    CASES = [
        # (instance, algo, extra args, max cost or None)
        ("graph_coloring_tuto.yaml", "mgm", (), 19),
        ("secp_small.yaml", "dsa", ("--cycles", "40"), None),
        ("ising_grid.yaml", "dsa", ("--cycles", "40"), 0),
        ("ising_grid.yaml", "maxsum", ("--cycles", "40"), 0),
        ("meeting_scheduling.yaml", "dpop", (), 0),
        ("meeting_scheduling.yaml", "mgm", ("--cycles", "40"), None),
    ]

    @pytest.mark.parametrize(
        "instance,algo,extra,max_cost", CASES,
        ids=[f"{i.split('.')[0]}-{a}" for i, a, _e, _m in CASES],
    )
    def test_family_solves(self, instance, algo, extra, max_cost):
        out = json_out(run_cli(
            "--timeout", "60", "solve", "--algo", algo, "--seed", "1",
            *extra, os.path.join(INSTANCES, instance),
        ))
        assert out["status"] == "FINISHED"
        assert out["violation"] == 0
        assert out["assignment"]
        if max_cost is not None:
            assert out["cost"] <= max_cost


class TestGraphDistribute:
    def test_graph_metrics(self):
        out = json_out(
            run_cli("graph", "--graph", "factor_graph", TUTO)
        )
        assert out["nodes_count"] == 8
        assert out["edges_count"] == 8

    def test_distribute(self):
        out = json_out(
            run_cli("distribute", "--distribution", "adhoc",
                    "--algo", "maxsum", TUTO)
        )
        hosted = [c for comps in out["distribution"].values()
                  for c in comps]
        assert len(hosted) == 8


class TestGenerate:
    def test_generate_graphcoloring(self, tmp_path):
        out_file = str(tmp_path / "gen.yaml")
        proc = run_cli(
            "--output", out_file, "generate", "graphcoloring",
            "--variables_count", "6", "--colors_count", "3",
            "--edges_count", "8", "--soft",
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(out_file)
        assert len(dcop.variables) == 6
        assert len(dcop.constraints) == 8

    def test_generate_ising(self, tmp_path):
        out_file = str(tmp_path / "ising.yaml")
        run_cli("--output", out_file, "generate", "ising",
                "--row_count", "3")
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(out_file)
        assert len(dcop.variables) == 9
        # 9 unary fields + 18 toroidal couplings (2 per cell)
        assert len(dcop.constraints) == 27
        assert "cu_v_0_0" in dcop.constraints
        assert len(dcop.agents) == 9

    def test_generate_ising_options(self, tmp_path):
        """Reference option surface: --intentional --no_agents
        --fg_dist --var_dist (ising.py:155-240)."""
        out_file = str(tmp_path / "ising.yaml")
        run_cli("--output", out_file, "generate", "ising",
                "--row_count", "3", "--intentional",
                "--fg_dist", "--var_dist")
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(out_file)
        assert len(dcop.constraints) == 27
        # intentional form survives the YAML round-trip as expressions
        cu = dcop.constraints["cu_v_0_0"]
        assert cu(v_0_0=0) == -cu(v_0_0=1)
        # both distributions written next to the DCOP
        import yaml as _yaml

        fg = _yaml.safe_load(
            open(str(tmp_path / "ising_fgdist.yaml"), encoding="utf-8"))
        var = _yaml.safe_load(
            open(str(tmp_path / "ising_vardist.yaml"), encoding="utf-8"))
        assert var["distribution"]["a_0_0"] == ["v_0_0"]
        fg00 = fg["distribution"]["a_0_0"]
        assert "v_0_0" in fg00 and "cu_v_0_0" in fg00
        assert sum(c.startswith("cb_") for c in fg00) == 2
        # every computation is mapped exactly once in the fg dist
        mapped = [c for comps in fg["distribution"].values()
                  for c in comps]
        assert len(mapped) == len(set(mapped)) == 27 + 9
        # the generated distribution solves with maxsum
        out = json_out(run_cli(
            "solve", "--algo", "maxsum", "--distribution",
            str(tmp_path / "ising_fgdist.yaml"), out_file))
        assert out["status"] in ("FINISHED", "TIMEOUT")

    def test_generate_ising_no_agents(self, tmp_path):
        out_file = str(tmp_path / "ising.yaml")
        run_cli("--output", out_file, "generate", "ising",
                "--row_count", "3", "--no_agents")
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(out_file)
        assert len(dcop.agents) == 0

    @pytest.mark.parametrize(
        "gen_args",
        [
            ("secp", "--lights", "4", "--models", "2", "--rules", "1"),
            ("meetingscheduling", "--agents_count", "3",
             "--meetings_count", "2"),
            ("iot", "-n", "5"),
            ("smallworld", "-V", "8"),
        ],
    )
    def test_generate_others(self, tmp_path, gen_args):
        out_file = str(tmp_path / "gen.yaml")
        proc = run_cli("--output", out_file, "generate", *gen_args)
        assert proc.returncode == 0, proc.stderr[-800:]
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(out_file)
        assert dcop.variables

    def test_generate_agents_and_scenario(self, tmp_path):
        agents_file = str(tmp_path / "agents.yaml")
        proc = run_cli("--output", agents_file, "generate", "agents",
                       "--count", "5")
        assert proc.returncode == 0, proc.stderr[-800:]
        scen_file = str(tmp_path / "scenario.yaml")
        proc = run_cli(
            "--output", scen_file, "generate", "scenario",
            "--agents_count", "5", "--evts_count", "2",
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        from pydcop_tpu.dcop import load_scenario_from_file

        scenario = load_scenario_from_file(scen_file)
        assert len(scenario) >= 2


class TestEndToEndGenerateSolve:
    def test_generate_then_solve(self, tmp_path):
        gen_file = str(tmp_path / "p.yaml")
        run_cli(
            "--output", gen_file, "generate", "graphcoloring",
            "--variables_count", "8", "--edges_count", "10", "--soft",
        )
        out = json_out(
            run_cli("--timeout", "30", "solve", "--algo", "mgm",
                    "--cycles", "15", gen_file)
        )
        assert out["status"] == "FINISHED"
        assert len(out["assignment"]) == 8


class TestRunScenario:
    def test_dynamic_run_with_repair(self, tmp_path):
        scen = tmp_path / "scen.yaml"
        scen.write_text(
            """
events:
  - id: d1
    delay: 1
  - id: e1
    actions:
      - type: remove_agent
        agent: a2
"""
        )
        out = json_out(
            run_cli(
                "--timeout", "40", "run", "--algo", "maxsum",
                "--distribution", "adhoc", "--scenario", str(scen),
                "--ktarget", "2", TUTO,
            )
        )
        assert out["status"] in ("FINISHED", "TIMEOUT")
        # a2 must be gone from the distribution; all computations re-hosted
        assert "a2" not in out["distribution"]
        hosted = [c for comps in out["distribution"].values()
                  for c in comps]
        assert sorted(hosted) == sorted(
            ["v1", "v2", "v3", "v4", "c_1_2", "c_1_3", "c_2_3", "c_2_4"]
        )


    def test_replica_dist_yaml_roundtrip_into_run(self, tmp_path):
        """`replica_dist` saves a replica-distribution YAML; `run
        --replica_dist` consumes it for repair (reference
        replication/yamlformat.py + commands/replica_dist.py:219-233)."""
        rep_file = tmp_path / "replicas.yaml"
        proc = run_cli(
            "--output", str(rep_file), "replica_dist", "--algo", "maxsum",
            "--distribution", "adhoc", "-k", "2", TUTO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        from pydcop_tpu.replication.yamlformat import (
            load_replica_dist_from_file,
        )

        replicas = load_replica_dist_from_file(str(rep_file))
        # every computation of the factor graph has 2 replicas
        mapping = replicas.mapping()
        assert sorted(mapping) == sorted(
            ["v1", "v2", "v3", "v4", "c_1_2", "c_1_3", "c_2_3", "c_2_4"]
        )
        assert all(len(hosts) == 2 for hosts in mapping.values())

        scen = tmp_path / "scen.yaml"
        scen.write_text(
            """
events:
  - id: d1
    delay: 1
  - id: e1
    actions:
      - type: remove_agent
        agent: a2
"""
        )
        out = json_out(
            run_cli(
                "--timeout", "40", "run", "--algo", "maxsum",
                "--distribution", "adhoc", "--scenario", str(scen),
                "--replica_dist", str(rep_file), TUTO,
            )
        )
        assert out["status"] in ("FINISHED", "TIMEOUT")
        assert "a2" not in out["distribution"]
        # repair respected the saved replica placement: every computation
        # that lived on a2 moved to one of its saved replica holders
        assert out["replicas"] == {
            c: hosts for c, hosts in mapping.items()
        }


class TestBatchConsolidate:
    def test_batch_and_consolidate(self, tmp_path):
        batch_def = tmp_path / "batch.yaml"
        batch_def.write_text(
            f"""
sets:
  s1:
    path: ["{TUTO}"]
    iterations: 1
batches:
  sweep:
    command: solve
    command_options:
      algo: [dpop, syncbb]
    global_options:
      timeout: 20
"""
        )
        out_dir = str(tmp_path / "out")
        proc = run_cli("batch", str(batch_def), "--output_dir", out_dir,
                       timeout=240)
        assert proc.returncode == 0, proc.stderr[-800:]
        import glob

        results = glob.glob(os.path.join(out_dir, "*.json"))
        assert len(results) == 2
        csv_file = str(tmp_path / "all.csv")
        proc = run_cli(
            "consolidate", os.path.join(out_dir, "*.json"),
            "--csv_file", csv_file,
        )
        assert proc.returncode == 0
        with open(csv_file) as f:
            lines = f.read().strip().splitlines()
        assert len(lines) == 3  # header + 2 rows
        assert "cost" in lines[0]


class TestBatchResume:
    def _batch_def(self, tmp_path, n_algos=3):
        algos = ["dpop", "syncbb", "ncbb"][:n_algos]
        batch_def = tmp_path / "resume.yaml"
        batch_def.write_text(
            f"""
sets:
  s1:
    path: ["{TUTO}"]
    iterations: 1
batches:
  sweep:
    command: solve
    command_options:
      algo: {algos}
    global_options:
      timeout: 30
"""
        )
        return batch_def

    def _progress_lines(self, out_dir):
        path = os.path.join(out_dir, "progress_resume")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return [ln for ln in f.read().splitlines()
                    if ln.startswith("JID: ")]

    def test_kill_then_resume_runs_each_job_exactly_once(self, tmp_path):
        """Reference progress-file protocol (batch.py:56-142): kill -9
        mid-batch, rerun, no job lost or duplicated."""
        import time

        batch_def = self._batch_def(tmp_path)
        out_dir = str(tmp_path / "out")
        proc = subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu", "batch", str(batch_def),
             "--output_dir", out_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=ENV, cwd=REPO,
        )
        # wait until exactly one job is registered, then kill -9
        deadline = time.time() + 120
        try:
            while time.time() < deadline:
                lines = self._progress_lines(out_dir)
                if lines and len(lines) >= 1:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError("no job registered before deadline")
        finally:
            proc.kill()
            proc.wait(timeout=30)

        done_before = self._progress_lines(out_dir)
        assert done_before, "progress file must survive the kill"

        # resume: remaining jobs run, registered ones are skipped
        proc2 = run_cli("batch", str(batch_def), "--output_dir", out_dir,
                        timeout=240)
        assert proc2.returncode == 0, proc2.stderr[-800:]
        assert f"{len(done_before)} already done" in proc2.stdout
        assert "3 jobs total" in proc2.stdout

        # all three outputs exist, none was re-run (skip count matches)
        import glob as _glob

        results = _glob.glob(os.path.join(out_dir, "*.json"))
        assert len(results) == 3
        assert f"skipped {len(done_before)}" in proc2.stdout

        # completion renames progress_ -> done_<stem>_<date>
        assert self._progress_lines(out_dir) is None
        done_files = _glob.glob(os.path.join(out_dir, "done_resume_*"))
        assert len(done_files) == 1

    def test_simulate_estimates_without_running(self, tmp_path):
        batch_def = self._batch_def(tmp_path, n_algos=2)
        out_dir = str(tmp_path / "sim")
        proc = run_cli("batch", str(batch_def), "--output_dir", out_dir,
                       "--simulate")
        assert proc.returncode == 0
        assert "2 jobs total" in proc.stdout
        # no progress file is created in simulate mode
        assert self._progress_lines(out_dir) is None


class TestUiPort:
    def test_solve_uiport_serves_state_and_ws(self, tmp_path):
        """--uiport (previously accepted-for-compat) serves the HTTP
        /state endpoint and the reference's websocket protocol while
        solving."""
        import socket
        import time
        import urllib.request

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        proc = subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu", "--timeout", "60",
             "solve", "--algo", "dsa", "--cycles", "2000",
             "--uiport", str(port), TUTO],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=ENV, cwd=REPO,
        )
        try:
            state = None
            deadline = time.time() + 50
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/state", timeout=2
                    ) as resp:
                        state = json.loads(resp.read())
                    break
                except OSError:
                    time.sleep(0.3)
            assert state is not None, "UI server never came up"
        finally:
            proc.kill()
            proc.wait(timeout=30)
