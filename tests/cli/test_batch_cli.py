"""CLI surface of the batched solve engine: ``solve --batch`` and
``pydcop_tpu batch --engine in-process`` (the `make batch-smoke`
scenario: a 2-bucket, 6-instance in-process sweep on the CPU backend,
small enough for the tier-1 time budget)."""
import json
import os
import subprocess
import sys

import yaml

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
CSP = os.path.join(INSTANCES, "coloring_csp.yaml")
INTENTION = os.path.join(INSTANCES, "coloring_intention.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


class TestSolveBatch:
    def test_solve_batch_two_files(self):
        proc = run_cli(
            "solve", "--batch", "-a", "mgm", "--cycles", "20",
            TUTO, CSP,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert out["results"][TUTO]["cost"] == 12
        assert out["results"][CSP]["cost"] == 0
        assert out["batch"]["buckets_formed"] >= 1
        assert out["batch"]["cache"]["misses"] >= 1

    def test_solve_batch_rejects_distribution(self):
        proc = run_cli(
            "solve", "--batch", "-a", "mgm", "-d", "oneagent", TUTO, CSP
        )
        assert proc.returncode != 0
        assert "batch" in json.loads(proc.stdout)["error"]


class TestInProcessBatchCommand:
    """The `make batch-smoke` sweep: 6 solve jobs over two shape
    families (2-color tuto + 3-color csp/intention), routed through the
    BatchEngine with the JID resume protocol intact."""

    def _definition(self):
        return {
            "sets": {
                "smoke": {
                    "path": [TUTO, CSP, INTENTION],
                    "iterations": 1,
                },
            },
            "batches": {
                "sweep": {
                    "command": "solve",
                    "command_options": {
                        "algo": ["mgm", "dsa"],
                        "cycles": 15,
                    },
                },
            },
        }

    def test_in_process_sweep_two_buckets(self, tmp_path):
        bdef = tmp_path / "smoke.yaml"
        bdef.write_text(yaml.safe_dump(self._definition()))
        out_dir = tmp_path / "out"
        proc = run_cli(
            "batch", "--engine", "in-process",
            "--output_dir", str(out_dir), str(bdef),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "in-process engine solved 6 jobs" in proc.stdout
        outputs = sorted(p for p in os.listdir(out_dir)
                         if p.endswith(".json"))
        assert len(outputs) == 6
        for p in outputs:
            with open(out_dir / p) as f:
                m = json.load(f)
            assert m["status"] == "FINISHED"
            assert m["batch_engine"] == "in-process"
            assert m["cycle"] == 15
        # sweep completed → progress file became the done_ record with
        # one JID per job (resume-protocol parity with subprocess mode)
        done = [p for p in os.listdir(out_dir) if p.startswith("done_")]
        assert len(done) == 1
        with open(out_dir / done[0]) as f:
            jids = [ln for ln in f if ln.startswith("JID: ")]
        assert len(jids) == 6

    def test_in_process_resume_skips_done_jobs(self, tmp_path):
        bdef = tmp_path / "smoke.yaml"
        bdef.write_text(yaml.safe_dump(self._definition()))
        out_dir = tmp_path / "out"
        run_cli("batch", "--engine", "in-process",
                "--output_dir", str(out_dir), str(bdef))
        # re-run after completion: outputs are trusted, nothing re-runs
        proc = run_cli(
            "batch", "--engine", "in-process",
            "--output_dir", str(out_dir), str(bdef),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "ran 0, skipped 6" in proc.stdout

    def test_in_process_matches_subprocess_output(self, tmp_path):
        """Same job, both engines → same metrics JSON (modulo wall
        time and the engine tag)."""
        bdef = tmp_path / "one.yaml"
        bdef.write_text(yaml.safe_dump({
            "sets": {"s": {"path": [CSP], "iterations": 1}},
            "batches": {"b": {
                "command": "solve",
                "command_options": {"algo": ["dsa"], "cycles": 15},
            }},
        }))
        outs = {}
        for engine in ("in-process", "subprocess"):
            out_dir = tmp_path / engine
            proc = run_cli("batch", "--engine", engine,
                           "--output_dir", str(out_dir), str(bdef))
            assert proc.returncode == 0, proc.stderr[-2000:]
            (job,) = [p for p in os.listdir(out_dir)
                      if p.endswith(".json")]
            with open(out_dir / job) as f:
                outs[engine] = json.load(f)
        for key in ("assignment", "cost", "violation", "cycle",
                    "msg_count", "msg_size", "status"):
            assert outs["in-process"][key] == outs["subprocess"][key], key
