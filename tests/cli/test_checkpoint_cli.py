"""CLI surface of ``pydcop_tpu checkpoint scrub`` (ISSUE 14
satellite): offline CRC/schema verification of a journal/checkpoint
tree, exit 1 on corruption, ``--fix`` quarantining exactly the files
``resume()`` would have skipped."""
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


def _make_tree(root):
    from pydcop_tpu.runtime.checkpoint import write_state_npz
    from pydcop_tpu.runtime.faults import corrupt_checkpoint

    sub = os.path.join(root, "replica-0")
    os.makedirs(sub)
    write_state_npz(os.path.join(root, "ck_00000001.npz"),
                    {"a": np.arange(8)}, {"kind": "solver"})
    write_state_npz(os.path.join(sub, "ck_00000002.npz"),
                    {"a": np.arange(8)}, {"kind": "solver"})
    corrupt_checkpoint(os.path.join(sub, "ck_00000002.npz"), seed=1)
    with open(os.path.join(root, "journal.jsonl"), "w") as f:
        f.write('{"kind": "job"}\n{"kind": "done"}\ntorn-tail')
    with open(os.path.join(sub, "bad.jsonl"), "w") as f:
        f.write('{"kind": "job"}\nGARBAGE\n{"kind": "done"}\n')


class TestCheckpointScrub:
    def test_clean_tree_exits_zero(self, tmp_path):
        from pydcop_tpu.runtime.checkpoint import write_state_npz

        write_state_npz(str(tmp_path / "ck_00000001.npz"),
                        {"a": np.arange(4)}, {"kind": "solver"})
        proc = run_cli("checkpoint", "scrub", str(tmp_path))
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "OK"
        assert out["checked"] == 1
        assert out["corrupt"] == []

    def test_corruption_found_exits_one(self, tmp_path):
        _make_tree(str(tmp_path))
        proc = run_cli("checkpoint", "scrub", str(tmp_path))
        assert proc.returncode == 1
        out = json.loads(proc.stdout)
        assert out["status"] == "CORRUPT"
        assert out["checked"] == 4
        bad = {c["file"] for c in out["corrupt"]}
        assert bad == {os.path.join("replica-0", "ck_00000002.npz"),
                       os.path.join("replica-0", "bad.jsonl")}
        # the torn TAIL is tolerated (counted), not corruption
        assert out["torn_tails_tolerated"] == 1

    def test_fix_quarantines_and_exits_zero(self, tmp_path):
        _make_tree(str(tmp_path))
        proc = run_cli("checkpoint", "scrub", str(tmp_path), "--fix")
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert len(out["quarantined"]) == 2
        sub = tmp_path / "replica-0"
        assert (sub / "ck_00000002.npz.quarantined").exists()
        assert not (sub / "ck_00000002.npz").exists()
        # the scrubbed tree is clean now
        proc = run_cli("checkpoint", "scrub", str(tmp_path))
        assert proc.returncode == 0
        # and resume-side walkers see only the good snapshot
        from pydcop_tpu.runtime.checkpoint import CheckpointManager

        got = CheckpointManager(str(sub)).latest_valid_state()
        assert got is None  # the only snapshot there was quarantined

    def test_missing_directory_errors(self, tmp_path):
        proc = run_cli("checkpoint", "scrub",
                       str(tmp_path / "nope"))
        assert proc.returncode == 1
