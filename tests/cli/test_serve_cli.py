"""CLI surface of the continuous-batching solve service: ``pydcop_tpu
serve`` (the `make serve-smoke` scenario: a short Poisson burst through
the in-process service on the CPU backend, every job completing with
the standalone solve's exact cost).

The kill-9 crash/resume integration test is ``slow``-marked: it runs a
real service subprocess, SIGKILLs it mid-stream and verifies the
restarted service resumes the in-flight jobs via the JID protocol.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
CSP = os.path.join(INSTANCES, "coloring_csp.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


class TestServeSmoke:
    def test_poisson_burst_all_jobs_complete_with_correct_costs(self):
        """`make serve-smoke`: a seeded Poisson burst of 6 jobs over
        two instance shapes; every job must FINISH with exactly the
        cost AND stop cycle of the standalone solve of its
        (file, seed) — the bit-identity contract, asserted end to end
        through the CLI."""
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.run import solve_result

        proc = run_cli(
            "serve", "-a", "mgm", "--jobs", "6",
            "--arrival", "poisson", "--rate", "50",
            "--arrival-seed", "7", "--lanes", "2",
            "--max-cycles", "2000", "--prewarm", TUTO, CSP,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert len(out["results"]) == 6
        dcops = {f: load_dcop_from_file([f]) for f in (TUTO, CSP)}
        for jid, m in out["results"].items():
            assert m["status"] == "FINISHED", (jid, m)
            fn, seed = m["label"].rsplit(":", 1)
            seq = solve_result(dcops[fn], "mgm", seed=int(seed))
            assert m["cost"] == seq.cost, (jid, m)
            assert m["cycle"] == seq.cycle, (jid, m)
            assert m["assignment"] == seq.assignment, (jid, m)
        serve = out["serve"]["serve"]
        assert serve["jobs_completed"] == 6
        assert serve["prewarmed_runners"] >= 1
        # the seeded trace is recorded and reproducible in length
        assert len(out["arrival"]["trace"]) == 6
        assert out["arrival"]["seed"] == 7

    def test_arrival_trace_is_reproducible(self):
        """Two runs with the same arrival seed record the same trace."""
        traces = []
        for _ in range(2):
            proc = run_cli(
                "serve", "-a", "mgm", "--jobs", "3",
                "--arrival", "poisson", "--rate", "100",
                "--arrival-seed", "13", "--lanes", "2", TUTO,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            traces.append(json.loads(proc.stdout)["arrival"]["trace"])
        assert traces[0] == traces[1]

    def test_resume_requires_journal(self):
        proc = run_cli("serve", "-a", "mgm", "--resume", TUTO)
        assert proc.returncode == 1
        assert "journal" in json.loads(proc.stdout)["error"]


@pytest.mark.slow
class TestServeChaosSmoke:
    """`make chaos-smoke`: the seeded serve fault plan driven through
    a real service process — the poison job must end terminal ERROR,
    every healthy job must match its standalone solve exactly, and the
    quarantine counters must show the machinery actually fired."""

    def test_fault_plan_quarantines_poison_completes_healthy(
        self, tmp_path
    ):
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.run import solve_result

        plan = tmp_path / "plan.yaml"
        plan.write_text(
            "seed: 7\n"
            "faults:\n"
            "  - kind: raise_in_step\n"
            "    jid: job-000002\n"   # the second submitted job
            "    cycle: 2\n"
            "  - kind: stall_tick\n"
            "    duration: 0.05\n"
            "    cycle: 1\n"
        )
        proc = run_cli(
            "serve", "-a", "mgm", "--jobs", "4", "--lanes", "2",
            "--max-cycles", "2000", "--fault-plan", str(plan), TUTO,
        )
        # the poison job ends ERROR, so the CLI exits nonzero — but
        # with a full JSON report, not a crash
        assert proc.returncode == 1, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert len(out["results"]) == 4
        dcop = load_dcop_from_file([TUTO])
        for jid, m in out["results"].items():
            if jid == "job-000002":
                assert m["status"] == "ERROR", (jid, m)
                continue
            assert m["status"] == "FINISHED", (jid, m)
            _fn, seed = m["label"].rsplit(":", 1)
            seq = solve_result(dcop, "mgm", seed=int(seed))
            assert m["cost"] == seq.cost, (jid, m)
            assert m["cycle"] == seq.cycle, (jid, m)
            assert m["assignment"] == seq.assignment, (jid, m)
        serve = out["serve"]["serve"]
        assert serve["faults_injected"] >= 2
        assert serve["ticks_stalled"] == 1
        assert serve["buckets_failed"] >= 1
        assert serve["jobs_quarantined"] == 1

    def test_overload_rejections_recorded(self):
        """Admission control through the CLI: a saturating burst with
        a tiny pending bound sheds with structured rejections in the
        output JSON."""
        proc = run_cli(
            "serve", "-a", "mgm", "--jobs", "8", "--lanes", "1",
            "--max-pending", "1", TUTO,
        )
        out = json.loads(proc.stdout)
        shed = out["serve"]["serve"]["jobs_shed"]
        assert shed == len(out["rejected"])
        for rej in out["rejected"]:
            assert "overloaded" in rej["error"]
            assert rej["retry_after"] > 0
        # every ADMITTED job still finished correctly
        for jid, m in out["results"].items():
            assert m["status"] == "FINISHED", (jid, m)


@pytest.mark.slow
class TestServeCrashResume:
    def test_kill9_midstream_then_resume_completes_all(self, tmp_path):
        """Acceptance pin: kill the service mid-stream (SIGKILL, no
        cleanup); a restarted service with --resume completes every
        journaled job, the previously in-flight ones restored from
        their last chunk-boundary checkpoints."""
        journal = str(tmp_path / "journal")
        # a big enough burst that jobs are still in flight when the
        # kill lands; checkpoints are written every chunk boundary
        proc = subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu", "serve", "-a", "dsa",
             "--jobs", "8", "--arrival", "poisson", "--rate", "20",
             "--arrival-seed", "3", "--lanes", "2",
             "--max-cycles", "2000", "--journal-dir", journal,
             TUTO, CSP],
            env=ENV, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        # wait for the journal to show submissions, then kill -9
        jobs_file = os.path.join(journal, "jobs.jsonl")
        deadline = time.time() + 60
        while time.time() < deadline:
            if os.path.exists(jobs_file) and os.path.getsize(jobs_file):
                break
            time.sleep(0.05)
        else:
            proc.kill()
            raise AssertionError("service never journaled a job")
        time.sleep(0.3)  # let some jobs get in flight / checkpoint
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

        with open(jobs_file, encoding="utf-8") as f:
            journaled = [json.loads(line)["jid"] for line in f if
                         line.strip()]
        assert journaled

        # restart with --resume and no new jobs
        proc2 = run_cli(
            "serve", "-a", "dsa", "--jobs", "0",
            "--journal-dir", journal, "--resume", TUTO,
        )
        assert proc2.returncode == 0, proc2.stderr[-2000:]
        out = json.loads(proc2.stdout)
        # every journaled job either completed before the kill (its
        # JID: line survived) or was resumed and completed now
        progress = os.path.join(journal, "progress_serve")
        with open(progress, encoding="utf-8") as f:
            done = {line[5:].strip() for line in f
                    if line.startswith("JID: ")}
        assert set(journaled) <= done
        for jid, m in out["results"].items():
            assert m["status"] == "FINISHED", (jid, m)
