"""CLI surface of the table-free structured constraints (ISSUE 17):
``generate routing_structured`` emits a 100-arity window as a few KB
of parameters, and ``solve`` runs it end-to-end — maxsum (table-free
message kernels) and the frontier engine (feasible anytime answer) —
where the dense path's 4^100 table is physically impossible.  This is
the ``make structured-smoke`` pipeline."""
import json
import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


def _generate(path, n=100):
    r = run_cli(
        "-o", str(path), "generate", "routing_structured",
        "-V", str(n), "--window", str(n), "--p_soft", "0",
    )
    assert r.returncode == 0, r.stderr[-800:]
    return path


class TestStructuredCli:
    def test_generate_emits_parameter_form(self, tmp_path):
        y = _generate(tmp_path / "rs.yaml", n=100)
        text = y.read_text()
        # 100-arity as parameters, not a table: the whole file is KBs
        assert "type: structured" in text
        assert os.path.getsize(y) < 100_000

    def test_maxsum_solves_hundred_arity(self, tmp_path):
        y = _generate(tmp_path / "rs.yaml", n=100)
        r = run_cli("solve", "--algo", "maxsum", "--cycles", "5",
                    str(y))
        assert r.returncode == 0, r.stderr[-800:]
        out = json.loads(r.stdout)
        assert len(out["assignment"]) == 100

    def test_frontier_finds_feasible_hundred_arity(self, tmp_path):
        y = _generate(tmp_path / "rs.yaml", n=100)
        r = run_cli("solve", "--algo", "syncbb", "--anytime-exact",
                    "--i-bound", "2", "--cycles", "5", str(y))
        assert r.returncode == 0, r.stderr[-800:]
        out = json.loads(r.stdout)
        # exact caps + barred slots: the beam-seeded incumbent is a
        # real feasible leaf, not the all-zero default
        assert out["violation"] == 0
        assert 0.0 < out["cost"] < 1000.0
