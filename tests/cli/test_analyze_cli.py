"""`pydcop_tpu analyze {program,lint}` front door (ISSUE 13).

Fast CLI surface: the lint half end-to-end on fixture files (findings
as JSON, nonzero exit), the registry listing, and one single-cell
program audit.  The full 8-device program sweep rides `make analyze`
and the slow-marked sweep test in tests/unit/test_analysis.py.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..", "..")


def _run(*args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=timeout,
    )


class TestAnalyzeLintCli:
    def test_violating_file_exits_nonzero_with_findings(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time\n"
            "def cycle_fn(x):\n"
            "    t = time.time()\n"
            "    return x\n"
        )
        out = _run("analyze", "lint", str(bad))
        assert out.returncode == 1, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert not payload["ok"]
        assert payload["findings"][0]["rule"] == "time-in-jit"
        assert payload["findings"][0]["line"] == 3

    def test_clean_file_exits_zero(self, tmp_path):
        good = tmp_path / "good.py"
        good.write_text("def helper(x):\n    return x + 1\n")
        out = _run("analyze", "lint", str(good))
        assert out.returncode == 0, out.stdout + out.stderr
        assert json.loads(out.stdout)["ok"]

    def test_shipped_tree_lints_clean_via_cli(self):
        out = _run("analyze", "lint")
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["ok"] and payload["findings"] == []

    def test_rule_filter(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time, numpy as np\n"
            "def cycle_fn(x):\n"
            "    t = time.time()\n"
            "    u = np.random.uniform()\n"
            "    return x\n"
        )
        out = _run("analyze", "lint", str(bad),
                   "--rule", "global-rng-in-jit")
        payload = json.loads(out.stdout)
        assert [f["rule"] for f in payload["findings"]] == [
            "global-rng-in-jit"
        ]


class TestAnalyzeProgramCli:
    def test_list_cells(self):
        out = _run("analyze", "program", "--list")
        assert out.returncode == 0, out.stdout + out.stderr
        cells = json.loads(out.stdout)["cells"]
        assert len(cells) >= 20
        assert "single/mgm" in cells

    def test_single_cell_audit_exits_zero(self):
        out = _run("analyze", "program", "--cell", "single/mgm")
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["ok"] and payload["audited"] == 1
        sc = payload["scorecard"]["single/mgm"]
        assert sc["host_callbacks"] == 0
        assert sc["collectives"]["psum"] == 0
