"""CLI surface of the elastic device-fault tier (ISSUE 14):
``solve --fault-plan`` with device kinds routes through
parallel/elastic.

The fast tests pin the routing + the integrity scorecard in the JSON
output.  ``make elastic-smoke`` is the slow-marked acceptance
scenario: an 8-device CPU mesh loses two devices mid-solve through
``kill_device`` faults, the solve completes on 6 devices, and the
final assignment bit-matches a clean elastic run (the exact-restore
path — MGM's integer-sum tables are partition-exact)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
    # the CLI subprocess does not inherit the test conftest's virtual
    # mesh — force the same 8-device CPU mesh explicitly
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def run_cli(*args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


@pytest.fixture(scope="module")
def dcop_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("elastic") / "gc.yaml")
    proc = run_cli(
        "--output", path, "generate", "graphcoloring",
        "--variables_count", "16", "--colors_count", "3",
        "--edges_count", "24", "--soft",
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    return path


def _plan(tmp_path, text):
    p = tmp_path / "plan.yaml"
    p.write_text(text)
    return str(p)


class TestElasticCli:
    def test_corrupt_slab_detected_through_cli(self, dcop_file,
                                               tmp_path):
        plan = _plan(tmp_path, (
            "seed: 3\n"
            "faults:\n"
            "  - kind: corrupt_slab\n"
            "    operand: bucket0\n"
            "    cycle: 4\n"
        ))
        proc = run_cli(
            "solve", "-a", "mgm", "--cycles", "16",
            "--fault-plan", plan, "--elastic-chunk", "4",
            dcop_file,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        integ = out["integrity"]
        assert integ["sentinel_trips"] == 1
        assert integ["sdc_detected"] == 1
        assert integ["snapshot_restores"] == 1

    def test_elastic_flag_clean_run(self, dcop_file):
        proc = run_cli(
            "solve", "-a", "maxsum", "--cycles", "12", "--elastic",
            "--elastic-chunk", "4", "--scrub-every", "2", dcop_file,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        integ = out["integrity"]
        # zero false positives on the clean legs
        assert integ["sentinel_trips"] == 0
        assert integ["scrub_mismatches"] == 0
        assert integ["scrub_runs"] >= 1

    def test_bad_plan_is_rejected(self, dcop_file, tmp_path):
        plan = _plan(tmp_path, (
            "seed: 1\n"
            "faults:\n"
            "  - kind: corrupt_slab\n"
            "    operand: bucket0\n"
            "    rank: 2\n"   # corrupt_slab never reads 'rank'
        ))
        proc = run_cli(
            "solve", "-a", "mgm", "--fault-plan", plan, dcop_file,
        )
        assert proc.returncode == 1
        out = json.loads(proc.stdout)
        assert out["status"] == "ERROR"
        assert "never consumes" in out["error"]


@pytest.mark.slow
class TestElasticSmoke:
    """``make elastic-smoke``: kill two devices mid-solve on the
    8-device CPU mesh; the solve finishes on 6 devices and
    bit-matches the clean elastic run."""

    def test_kill_device_mid_solve_bitmatch(self, dcop_file,
                                            tmp_path):
        clean = run_cli(
            "solve", "-a", "mgm", "--cycles", "24", "--elastic",
            "--elastic-chunk", "6", "--seed", "5", dcop_file,
        )
        assert clean.returncode == 0, clean.stderr[-2000:]
        ref = json.loads(clean.stdout)

        plan = _plan(tmp_path, (
            "seed: 7\n"
            "faults:\n"
            "  - kind: kill_device\n"
            "    device: 3\n"
            "    cycle: 8\n"
            "  - kind: kill_device\n"
            "    device: 0\n"
            "    cycle: 14\n"
        ))
        proc = run_cli(
            "solve", "-a", "mgm", "--cycles", "24",
            "--fault-plan", plan, "--elastic-chunk", "6",
            "--seed", "5", dcop_file,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        integ = out["integrity"]
        assert integ["devices_lost"] == 2
        assert integ["elastic_shrinks"] == 2
        # the exact-restore path: bit-identical to the unfailed run
        assert out["assignment"] == ref["assignment"]
        assert out["cost"] == ref["cost"]
