"""CLI surface of the city-scale digital twin: ``pydcop_tpu twin``.

The fast test is a tiny clean twin (no chaos, no churn): the JSON
scorecard parses, every tier is accounted, nothing is shed, the
ladder never needed to engage.

``make twin-smoke`` is the slow-marked acceptance scenario (ISSUE 12
satellite): 2 replicas, 3 tiers, 10 live mutations, 1 injected
kill_replica — asserting a finite RTO, ZERO gold deadline misses,
zero churn retraces, and the guardrail ladder engaged AND released
(the bronze tier's unmeetable deadline forces the engagement; the
post-shed drain clears it).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=420):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


class TestTwinCli:
    def test_small_clean_twin(self):
        proc = run_cli(
            "twin", "--jobs", "6", "--replicas", "2", "--lanes", "2",
            "--no-chaos", "--no-churn", "--seed", "3",
            "--max-cycles", "80",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert out["jobs"] == 6
        assert out["shed_rate"] == 0.0
        assert set(out["tiers"]) == {"gold", "silver", "bronze"}
        assert sum(t["scored"] for t in out["tiers"].values()) == 6
        assert out["ladder"]["enabled"]
        assert out["fleet"]["replicas_down"] == 0
        assert out["slo"]["jobs_scored"] == 6

    def test_no_ladder_flag(self):
        proc = run_cli(
            "twin", "--jobs", "4", "--replicas", "1", "--lanes", "2",
            "--no-chaos", "--no-churn", "--no-ladder", "--seed", "3",
            "--max-cycles", "60",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert not out["ladder"]["enabled"]
        assert out["slo"]["ladder_escalations"] == 0


@pytest.mark.slow
class TestTwinSmoke:
    def test_twin_smoke_full_scenario(self):
        """The ISSUE 12 smoke: 2 replicas, 3 tiers, 10 mutations, 1
        kill — finite RTO, zero gold deadline misses, ladder
        engaged-and-released, zero churn retraces."""
        proc = run_cli(
            "twin", "--jobs", "12", "--replicas", "2", "--lanes", "2",
            "--mutations", "10", "--live-vars", "100",
            "--seed", "1", "--max-cycles", "120",
            "--kill-tick", "6",
            # bronze's unmeetable budget forces the engagement the
            # smoke asserts; gold stays generous so the pin is strict
            "--gold-deadline", "60", "--silver-deadline", "60",
            "--bronze-deadline", "0.0001",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        # zero gold deadline misses
        gold = out["tiers"]["gold"]
        if gold["scored"]:
            assert gold["misses"] == 0, gold
            assert gold["attainment"] == 1.0
        # the injected kill recovered with a finite RTO (or had no
        # orphans to re-seat — then nothing was in flight, which the
        # reseats counter distinguishes)
        assert out["fleet"]["replicas_down"] == 1
        if out["fleet"]["jobs_reseated"]:
            assert out["rto_max_s"] is not None
            assert out["rto_max_s"] > 0
        # ladder engaged AND released
        assert out["ladder"]["engaged"], out["slo"]
        assert out["ladder"]["released"], out["ladder"]
        assert out["ladder"]["final_rung"] == 0
        # churn ran warm: 10 mutations' events, zero retraces
        assert out["churn"]["mutations_applied"] > 0
        assert out["churn"]["repair_retraces"] == 0
        assert len(out["recover_s"]) > 0
