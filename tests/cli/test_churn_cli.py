"""`pydcop_tpu run --warm-repair` end to end: the seeded churn
FaultPlan replayed through the CLI (the `make churn-smoke` scenario).

The kill-9 mid-churn + `--resume` integration test is ``slow``-marked:
it SIGKILLs a real run between phases and verifies the restarted run
resumes from the rotating checkpoint (schema v3 carries the warm
layout) and still finishes the churn stream.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}

DCOP_YAML = textwrap.dedent("""
    name: churn
    objective: min
    domains:
      d: {values: [0, 1, 2]}
    variables:
      v1: {domain: d}
      v2: {domain: d}
      v3: {domain: d}
      v4: {domain: d}
    constraints:
      c12: {type: intention, function: "0 if v1 == v2 else 5"}
      c23: {type: intention, function: "0 if v2 != v3 else 3"}
      c34: {type: intention, function: "abs(v3 - v4)"}
    agents: [a1, a2, a3, a4, a5, a6, a7, a8]
""")

PLAN_YAML = textwrap.dedent("""
    seed: 11
    faults:
      - kind: edit_factor
        cycle: 10
      - kind: remove_agent_burst
        cycle: 30
        count: 2
      - kind: add_agent_burst
        cycle: 50
        count: 1
      - kind: edit_factor
        cycle: 70
        constraint: c23
""")


def write_inputs(tmp_path, delays):
    (tmp_path / "prob.yaml").write_text(DCOP_YAML)
    (tmp_path / "plan.yaml").write_text(PLAN_YAML)
    events = "".join(
        f"  - id: d{i}\n    delay: {d}\n" for i, d in enumerate(delays)
    )
    (tmp_path / "scen.yaml").write_text("events:\n" + events)


def cli(*args):
    return [sys.executable, "-m", "pydcop_tpu", *args]


def test_warm_churn_plan_zero_retraces(tmp_path):
    """The seeded churn plan through `run --warm-repair`: every fault
    fires, zero repair retraces, clean exit."""
    write_inputs(tmp_path, delays=[0.4, 0.4, 0.4])
    out = subprocess.run(
        cli("--timeout", "120", "run", "--algo", "maxsum",
            "--warm-repair", "--headroom", "0.3",
            "-s", "scen.yaml", "--fault-plan", "plan.yaml",
            "--ktarget", "2", "prob.yaml"),
        capture_output=True, text=True, timeout=300, env=ENV,
        cwd=tmp_path,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    m = json.loads(out.stdout)
    assert m["status"] in ("FINISHED", "TIMEOUT")
    assert m["repair"]["repair_retraces"] == 0, m["repair"]
    assert m["repair"]["mutations_applied"] >= 2
    assert m["resilience"]["faults_injected"] == 4
    kinds = [e.get("fault") for e in m["events"] if "fault" in e]
    assert kinds.count("edit_factor") == 2
    assert "remove_agent_burst" in kinds and "add_agent_burst" in kinds


def test_structural_scenario_via_cli(tmp_path):
    """Warm-only structural events (grow + shrink the live problem)
    through the CLI."""
    (tmp_path / "prob.yaml").write_text(DCOP_YAML)
    (tmp_path / "scen.yaml").write_text(textwrap.dedent("""
        events:
          - id: d0
            delay: 0.3
          - id: grow
            actions:
              - type: add_variable
                variable: z9
                domain: d
              - type: add_constraint
                constraint: cz
                expression: "0 if z9 == v4 else 7"
                scope: [z9, v4]
          - id: d1
            delay: 0.3
    """))
    out = subprocess.run(
        cli("--timeout", "120", "run", "--algo", "mgm",
            "--warm-repair", "-s", "scen.yaml", "prob.yaml"),
        capture_output=True, text=True, timeout=300, env=ENV,
        cwd=tmp_path,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    m = json.loads(out.stdout)
    assert m["assignment"]["z9"] == m["assignment"]["v4"]
    assert m["repair"]["headroom_claimed"] == 2
    assert m["repair"]["repair_retraces"] == 0


def test_solve_headroom_flag(tmp_path):
    """`solve --headroom` builds the warm engine and surfaces the
    repair scorecard in the metrics JSON."""
    (tmp_path / "prob.yaml").write_text(DCOP_YAML)
    out = subprocess.run(
        cli("--timeout", "90", "solve", "-a", "mgm",
            "--headroom", "0.25", "--cycles", "30", "prob.yaml"),
        capture_output=True, text=True, timeout=240, env=ENV,
        cwd=tmp_path,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    m = json.loads(out.stdout)
    assert m["status"] == "FINISHED"
    assert "repair" in m, sorted(m)
    assert m["repair"]["repair_retraces"] == 0


@pytest.mark.slow
def test_kill9_mid_churn_then_resume(tmp_path):
    """Acceptance pin for `make churn-smoke`: SIGKILL the churn run
    between phases (no shutdown path at all), then rerun with
    `--resume` — the restarted run warm-starts from the newest v3
    snapshot and completes the stream."""
    write_inputs(tmp_path, delays=[1.0] * 8)
    ckpt = str(tmp_path / "ckpt")
    args = cli(
        "--timeout", "120", "run", "--algo", "maxsum",
        "--warm-repair", "-s", "scen.yaml", "--fault-plan", "plan.yaml",
        "--checkpoint", ckpt, "--checkpoint-every", "10",
        "--ktarget", "2", "prob.yaml",
    )
    proc = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=ENV, cwd=tmp_path,
    )
    # let it converge a few phases and write snapshots, then kill -9
    deadline = time.time() + 60
    while time.time() < deadline:
        time.sleep(0.5)
        if os.path.isdir(ckpt) and any(
                n.startswith("ck_") for n in os.listdir(ckpt)):
            break
    assert proc.poll() is None, (
        "run finished before the kill; lengthen the scenario\n"
        + proc.communicate()[1][-1000:]
    )
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    assert any(n.startswith("ck_") for n in os.listdir(ckpt)), \
        "no snapshot was written before the kill"

    out = subprocess.run(
        args + ["--resume"],
        capture_output=True, text=True, timeout=300, env=ENV,
        cwd=tmp_path,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    m = json.loads(out.stdout)
    assert m["status"] in ("FINISHED", "TIMEOUT")
    assert m["resilience"]["resumes"] == 1, m["resilience"]
    assert m["repair"]["repair_retraces"] == 0, m["repair"]
    assert m["resilience"]["faults_injected"] >= 1
