"""CLI surface of the anytime exact search (ISSUE 15):
``solve --anytime-exact`` / ``--frontier-width`` and the
``engine:frontier`` algo param, plus the slow-marked kill-9 smoke
(``make search-smoke``): SIGKILL a checkpointing search mid-run, then
``--resume`` onto the exact frontier state and finish with the clean
run's proven optimum."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


def _write_clique(path, K=9, D=4, seed=5):
    """High-width instance (induced width K-1) with integer costs —
    the regime where full DPOP refuses under budget and the frontier
    engine proves the optimum."""
    rng = np.random.default_rng(seed)
    lines = ["name: clique", "objective: min", "domains:",
             f"  d: {{values: [{', '.join(str(i) for i in range(D))}]}}",
             "variables:"]
    for i in range(K):
        lines.append(f"  v{i:02d}: {{domain: d}}")
    lines.append("constraints:")
    k = 0
    for i in range(K):
        for j in range(i + 1, K):
            m = rng.integers(0, 10, (D, D))
            by_cost = {}
            for a in range(D):
                for b in range(D):
                    if m[a, b]:
                        by_cost.setdefault(int(m[a, b]), []).append(
                            f"{a} {b}"
                        )
            vals = ", ".join(
                f"{cost}: \"{' | '.join(combos)}\""
                for cost, combos in sorted(by_cost.items())
            )
            lines.append(
                f"  c{k}: {{type: extensional, "
                f"variables: [v{i:02d}, v{j:02d}], "
                f"default: 0, values: {{{vals}}}}}"
            )
            k += 1
    lines += ["agents: [a0]"]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


class TestAnytimeExactCli:
    def test_help_covers_the_flags(self):
        proc = run_cli("solve", "--help")
        assert proc.returncode == 0
        assert "--anytime-exact" in proc.stdout
        assert "--frontier-width" in proc.stdout
        assert "optimality" in proc.stdout.lower()

    def test_anytime_exact_proves_and_reports(self, tmp_path):
        yaml = _write_clique(str(tmp_path / "clique.yaml"), K=8, D=3)
        proc = run_cli("solve", "--anytime-exact",
                       "--frontier-width", "64", yaml)
        assert proc.returncode == 0, proc.stderr[-2000:]
        m = json.loads(proc.stdout)
        assert m["status"] == "FINISHED"
        s = m["search"]
        assert s["optimal"] is True and s["gap"] == 0.0
        assert s["lower_bound"] <= m["cost"] <= s["upper_bound"]
        assert s["engine"] == "frontier"
        assert m["config"]["engine"] == "frontier"
        assert s["lost_rows"] == 0

    def test_engine_param_spelling(self, tmp_path):
        yaml = _write_clique(str(tmp_path / "c.yaml"), K=7, D=3)
        proc = run_cli("solve", "-a", "ncbb", "-p", "engine:frontier",
                       yaml)
        assert proc.returncode == 0, proc.stderr[-2000:]
        m = json.loads(proc.stdout)
        assert m["search"]["optimal"] is True

    def test_flag_combos_rejected(self, tmp_path):
        yaml = _write_clique(str(tmp_path / "c.yaml"), K=6, D=3)
        proc = run_cli("solve", "--anytime-exact", "--auto", yaml)
        assert proc.returncode == 1
        assert "anytime-exact" in json.loads(proc.stdout)["error"]
        proc = run_cli("solve", "--anytime-exact", "-a", "maxsum",
                       yaml)
        assert proc.returncode == 1
        proc = run_cli("solve", "-a", "mgm", "--frontier-width", "8",
                       yaml)
        assert proc.returncode == 1


@pytest.mark.slow
class TestKill9Smoke:
    def test_kill9_then_resume_finishes_exact(self, tmp_path):
        """The ``make search-smoke`` scenario: a checkpointing
        anytime-exact solve is SIGKILLed mid-search; rerunning with
        ``--resume`` restores the frontier slab + incumbent from the
        newest CRC-valid snapshot and still proves the clean
        optimum."""
        yaml = _write_clique(str(tmp_path / "clique.yaml"), K=9, D=4)
        ck = str(tmp_path / "ck")

        clean = run_cli("solve", "--anytime-exact",
                        "--frontier-width", "64", yaml)
        assert clean.returncode == 0, clean.stderr[-2000:]
        want = json.loads(clean.stdout)["cost"]

        # tiny chunks + per-chunk snapshots so the kill lands mid-run
        proc = subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu", "solve",
             "--anytime-exact", "--frontier-width", "16",
             "-p", "search_chunk:1", "--cycles", "100000",
             "--checkpoint", ck, "--checkpoint-every", "1", yaml],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=ENV,
            cwd=REPO,
        )
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.isdir(ck) and any(
                f.endswith(".npz") for f in os.listdir(ck)
            ):
                break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        assert proc.poll() is None, (
            "solve finished before a snapshot landed; shrink the "
            "chunk further"
        )
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        # the slab/ring shapes must match the snapshot (same
        # frontier_width); steps-per-chunk is runner-side only, so
        # the resumed run can take bigger strides to the proof
        resumed = run_cli("solve", "--anytime-exact",
                          "--frontier-width", "16",
                          "-p", "search_chunk:16",
                          "--cycles", "100000",
                          "--checkpoint", ck, "--checkpoint-every",
                          "200", "--resume", yaml, timeout=600)
        assert resumed.returncode == 0, resumed.stderr[-2000:]
        m = json.loads(resumed.stdout)
        assert m["search"]["optimal"] is True
        assert m["cost"] == want
