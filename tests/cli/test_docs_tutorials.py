"""The docs' tutorial commands run verbatim (VERDICT r2 item 5:
"tutorial commands run verbatim" is the acceptance criterion for the
docs tree)."""
import json
import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}

GETTING_STARTED_YAML = """
name: graph coloring
objective: min

domains:
  colors:
    values: [R, G]

variables:
  v1:
    domain: colors
  v2:
    domain: colors
  v3:
    domain: colors

constraints:
    pref_1:
      type: extensional
      variables: v1
      values:
        -0.1: R
        0.1: G

    pref_2:
      type: extensional
      variables: v2
      values:
        -0.1: G
        0.1: R

    pref_3:
      type: extensional
      variables: v3
      values:
        -0.1: G
        0.1: R

    diff_1_2:
      type: intention
      function: 10 if v1 == v2 else 0

    diff_2_3:
      type: intention
      function: 10 if v3 == v2 else 0

agents: [a1, a2, a3, a4, a5]
"""


def run(args, cwd, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV, cwd=cwd,
    )


def test_getting_started_flow(tmp_path):
    """docs/tutorials/getting_started.rst, command for command."""
    (tmp_path / "graph_coloring.yaml").write_text(GETTING_STARTED_YAML)

    # solve with DPOP: the documented optimal result
    proc = run(["solve", "--algo", "dpop", "graph_coloring.yaml"],
               cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    out = json.loads(proc.stdout)
    assert out["assignment"] == {"v1": "R", "v2": "G", "v3": "R"}
    assert abs(out["cost"] - (-0.1)) < 1e-6
    assert out["status"] == "FINISHED"

    # bounded local search
    proc = run(["--timeout", "3", "solve", "--algo", "mgm",
                "graph_coloring.yaml"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert json.loads(proc.stdout)["status"] in ("FINISHED", "TIMEOUT")

    proc = run(["solve", "--algo", "dsa", "--cycles", "50",
                "graph_coloring.yaml"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]

    # algo params, reference spelling
    proc = run(["solve", "--algo", "maxsum",
                "--algo_params", "damping:0.7",
                "--algo_params", "stop_cycle:30",
                "graph_coloring.yaml"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]

    # generate a bigger instance, then solve it
    proc = run(["generate", "graphcoloring", "--variables_count", "50",
                "--colors_count", "3", "--graph", "random", "-p", "0.1",
                "--soft"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    (tmp_path / "graph_coloring_50.yaml").write_text(proc.stdout)
    proc = run(["--timeout", "10", "solve", "--algo", "dsa",
                "graph_coloring_50.yaml"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]


def test_analysing_results_flow(tmp_path):
    """docs/tutorials/analysing_results.rst — including the reference
    docs' singular --run_metric spelling (argparse prefix match) and the
    getting-started doc's exact generate line."""
    proc = run(["generate", "graphcoloring", "--variables_count", "50",
                "--colors_count", "3", "--graph", "random", "-p", "0.1",
                "--soft"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    (tmp_path / "graph_coloring_50.yaml").write_text(proc.stdout)

    proc = run(["solve", "--algo", "mgm",
                "--algo_params", "stop_cycle:20",
                "--collect_on", "cycle_change",
                "--run_metric", "./metrics_cycle.csv",
                "graph_coloring_50.yaml"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    csv = (tmp_path / "metrics_cycle.csv").read_text().strip().splitlines()
    assert len(csv) == 21  # header + 20 cycles
    assert "cost" in csv[0]

    # mgm cost trace is monotonically non-increasing (doc claim)
    costs = [float(line.split(",")[2]) for line in csv[1:]]
    assert all(b <= a + 1e-6 for a, b in zip(costs, costs[1:]))

    proc = run(["solve", "--algo", "dsa", "--cycles", "10",
                "--end_metrics", "./end_metrics.csv",
                "graph_coloring_50.yaml"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert (tmp_path / "end_metrics.csv").exists()


def test_dynamic_dcops_flow(tmp_path):
    """docs/tutorials/dynamic_dcops.rst, command for command."""
    (tmp_path / "graph_coloring.yaml").write_text(GETTING_STARTED_YAML)
    (tmp_path / "scenario.yaml").write_text(
        """
events:
  - delay: 2
  - id: e1
    actions:
      - type: remove_agent
        agent: a2
  - delay: 2
"""
    )
    proc = run(["--timeout", "60", "run", "--algo", "maxsum",
                "--distribution", "adhoc", "--scenario", "scenario.yaml",
                "--replication_method", "dist_ucs_hostingcosts",
                "--ktarget", "2", "graph_coloring.yaml"],
               cwd=tmp_path, timeout=240)
    assert proc.returncode == 0, proc.stderr[-800:]
    out = json.loads(proc.stdout)
    assert out["status"] in ("FINISHED", "TIMEOUT")
    assert "a2" not in out["distribution"]


def test_dynamic_dcops_warm_repair_flow(tmp_path):
    """docs/tutorials/dynamic_dcops.rst, "Warm repair" section —
    command and structural scenario verbatim."""
    (tmp_path / "graph_coloring.yaml").write_text(GETTING_STARTED_YAML)
    (tmp_path / "scenario.yaml").write_text(
        """
events:
  - delay: 1
  - id: grow
    actions:
      - type: add_variable
        variable: v9
        domain: colors
      - type: add_constraint
        constraint: c9
        expression: "0 if v9 != v1 else 10"
        scope: [v9, v1]
  - delay: 1
  - id: shrink
    actions:
      - type: remove_variable
        variable: v9
"""
    )
    proc = run(["--timeout", "60", "run", "--algo", "maxsum",
                "--warm-repair", "--headroom", "0.25",
                "--distribution", "adhoc",
                "--scenario", "scenario.yaml", "--ktarget", "2",
                "graph_coloring.yaml"],
               cwd=tmp_path, timeout=240)
    assert proc.returncode == 0, proc.stderr[-800:]
    out = json.loads(proc.stdout)
    assert out["status"] in ("FINISHED", "TIMEOUT")
    assert "v9" not in out["assignment"]  # grown, then shrunk away
    assert out["repair"]["mutations_applied"] >= 4
    assert out["repair"]["repair_retraces"] == 0


def test_batch_and_consolidate_flow(tmp_path):
    """docs/tutorials/analysing_results.rst batch/consolidate section."""
    (tmp_path / "graph_coloring.yaml").write_text(GETTING_STARTED_YAML)
    (tmp_path / "my_sweep.yaml").write_text(
        """
sets:
  s1:
    path: ["graph_coloring.yaml"]
batches:
  sweep:
    command: solve
    command_options:
      algo: [dpop]
    global_options:
      timeout: 30
"""
    )
    proc = run(["batch", "my_sweep.yaml", "--output_dir", "results/"],
               cwd=tmp_path, timeout=240)
    assert proc.returncode == 0, proc.stderr[-800:]
    proc = run(["consolidate", "results/*.json", "--csv_file", "all.csv"],
               cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert (tmp_path / "all.csv").exists()
