"""Orchestrator + agent CLI commands: multi-process control plane."""
import json
import os
import subprocess
import sys
import time

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def test_orchestrator_and_agent():
    port = 19371
    orch = subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu", "--timeout", "30",
         "orchestrator", "--algo", "dpop", "--port", str(port),
         "--expected_agents", "2", TUTO],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=ENV, cwd=REPO,
    )
    try:
        time.sleep(1.0)
        agent = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu", "--timeout", "40",
             "agent", "--names", "a1", "a2",
             "--orchestrator", f"127.0.0.1:{port}"],
            capture_output=True, text=True, timeout=60, env=ENV, cwd=REPO,
        )
        assert agent.returncode == 0, agent.stderr[-800:]
        agent_metrics = json.loads(agent.stdout)
        assert agent_metrics["cost"] == 12
        out, err = orch.communicate(timeout=60)
        assert orch.returncode == 0, err[-800:]
        orch_metrics = json.loads(out)
        assert orch_metrics["cost"] == 12
        assert orch_metrics["status"] == "FINISHED"
    finally:
        if orch.poll() is None:
            orch.kill()
