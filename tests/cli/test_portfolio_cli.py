"""CLI surface of the learned portfolio (the `make portfolio-smoke`
scenario): tiny grid -> dataset sweep -> train -> ``solve --auto``
end to end on the CPU backend, in under a minute — plus the --auto
flag validation and the pinned no-model heuristic fallback.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def run_cli(*args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=REPO,
    )


class TestSolveAutoFallback:
    def test_auto_without_model_uses_heuristics(self):
        proc = run_cli("solve", "--auto", "--portfolio-grid", "tiny",
                       "--cycles", "20", TUTO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        pf = out["portfolio"]
        assert pf["fallback"] is True and pf["model"] is None
        # the tuto instance is tiny: the PR 9 byte-estimate heuristic
        # picks exact DPOP, whose optimum on this instance is cost 12
        assert pf["config"]["algo"] == "dpop"
        assert out["cost"] == 12
        # the canonical executed-config section rides along
        assert out["config"]["algo"] == "dpop"

    def test_auto_rejects_explicit_algo(self):
        proc = run_cli("solve", "--auto", "-a", "mgm", TUTO)
        assert proc.returncode != 0
        assert "mutually exclusive" in json.loads(proc.stdout)["error"]

    def test_algo_or_auto_required(self):
        proc = run_cli("solve", TUTO)
        assert proc.returncode != 0
        assert "--auto" in json.loads(proc.stdout)["error"]

    def test_auto_rejects_batch(self):
        proc = run_cli("solve", "--auto", "--batch", TUTO)
        assert proc.returncode != 0
        assert "--auto" in json.loads(proc.stdout)["error"]


class TestPortfolioSmoke:
    """dataset -> train -> select -> solve --auto, all through the
    CLI, on a tiny grid and tiny instances (the `make portfolio-smoke`
    budget: under a minute on the CPU backend)."""

    @pytest.fixture(scope="class")
    def workdir(self, tmp_path_factory):
        return tmp_path_factory.mktemp("portfolio")

    def test_end_to_end(self, workdir):
        ds = str(workdir / "ds")
        model = str(workdir / "model.npz")

        proc = run_cli(
            "portfolio", "dataset", "--out", ds,
            "--families", "graphcoloring,ising",
            "--sizes", "6", "--seeds", "0,1", "--grid", "tiny",
            "--cycles", "25", "--cell-timeout", "20",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED" and out["cells_run"] > 0
        assert out["cells_error"] == 0
        assert os.path.exists(os.path.join(ds, "rows.jsonl"))
        assert os.path.exists(os.path.join(ds, "dataset.npz"))

        # resumable by cell key: a second sweep runs nothing
        proc = run_cli(
            "portfolio", "dataset", "--out", ds,
            "--families", "graphcoloring,ising",
            "--sizes", "6", "--seeds", "0,1", "--grid", "tiny",
            "--cycles", "25", "--cell-timeout", "20",
        )
        out = json.loads(proc.stdout)
        assert out["cells_run"] == 0 and out["cells_skipped"] > 0

        proc = run_cli(
            "portfolio", "train", "--data", ds, "--model", model,
            "--holdout", "ising", "--epochs", "80",
            "--hidden", "16,16",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        assert os.path.exists(model)
        ev = out["holdout_eval"]
        for k in ("rank_correlation", "top1_regret",
                  "top1_regret_ratio", "top1_hits"):
            assert k in ev

        proc = run_cli(
            "portfolio", "select", "--model", model, "--grid", "tiny",
            TUTO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        sel = json.loads(proc.stdout)["selections"][TUTO]
        assert sel["fallback"] is False and sel["scores"]

        proc = run_cli(
            "solve", "--auto", "--portfolio-model", model,
            "--portfolio-grid", "tiny", "--cycles", "25", TUTO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["status"] == "FINISHED"
        pf = out["portfolio"]
        assert pf["fallback"] is False
        assert pf["model"].endswith("model.npz")
        assert pf["predicted_time_to_target_s"] is not None
        assert "gap_s" in pf and pf["actual_solve_s"] > 0
