"""Semantics of MixedDSA (hard/soft move probabilities, reference
pydcop/algorithms/mixeddsa.py:119-154) and DBA (breakout weights,
pydcop/algorithms/dba.py).
"""
import jax.numpy as jnp
import jax.random
import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.dba import DbaSolver
from pydcop_tpu.algorithms.dba import algo_params as dba_params
from pydcop_tpu.algorithms.mixeddsa import MixedDsaSolver
from pydcop_tpu.algorithms.mixeddsa import algo_params as mix_params
from pydcop_tpu.dcop import load_dcop
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.compile import compile_constraint_graph
from pydcop_tpu.runtime import solve_result

import textwrap

MIXED_YAML = textwrap.dedent("""
    name: mixed
    objective: min
    domains:
      d: {values: [0, 1, 2]}
    variables:
      a: {domain: d}
      b: {domain: d}
      c: {domain: d}
    constraints:
      hard_ab:
        type: intention
        function: "10000 if a == b else 0"
      soft_bc:
        type: intention
        function: "abs(b - c)"
    agents: [a1, a2, a3, a4, a5]
""")


def mixed_solver(**params):
    dcop = load_dcop(MIXED_YAML)
    algo = AlgorithmDef.build_with_default_params(
        "mixeddsa", params, parameters_definitions=mix_params
    )
    return MixedDsaSolver(dcop, compile_constraint_graph(dcop), algo)


class TestMixedDsa:
    def test_solves_mixed_problem(self):
        dcop = load_dcop(MIXED_YAML)
        res = solve_result(dcop, "mixeddsa", cycles=60, seed=1)
        assert res.status == "FINISHED"
        assert res.violation == 0  # the hard constraint is satisfied
        assert res.assignment["a"] != res.assignment["b"]

    def test_hard_conflict_uses_proba_hard(self):
        """proba_hard=1, proba_soft=0: variables in hard conflict always
        move (when improving), others never do."""
        solver = mixed_solver(proba_hard=1.0, proba_soft=0.0)
        # a == b -> hard conflict for a and b; c only has soft costs
        x0 = jnp.asarray([1, 1, 0], dtype=jnp.int32)
        moved_hard, moved_soft = 0, 0
        for k in range(25):
            (x1,) = solver.cycle((x0,), jax.random.PRNGKey(k))
            x1 = np.asarray(x1)
            if x1[0] != 1 or x1[1] != 1:
                moved_hard += 1
            if x1[2] != 0:
                moved_soft += 1
        assert moved_hard == 25  # always resolves the hard conflict
        assert moved_soft == 0  # soft-only variable frozen at proba 0

    def test_proba_soft_controls_soft_moves(self):
        solver = mixed_solver(proba_hard=0.0, proba_soft=1.0)
        # no hard conflict: a=0, b=1; c=0 has soft gain (b=1 -> c=1)
        x0 = jnp.asarray([0, 1, 0], dtype=jnp.int32)
        (x1,) = solver.cycle((x0,), jax.random.PRNGKey(3))
        assert np.asarray(x1)[2] == 1  # c follows b

    def test_variants_accepted(self):
        dcop = load_dcop(MIXED_YAML)
        for variant in ("A", "B", "C"):
            res = solve_result(
                dcop, "mixeddsa", cycles=40,
                algo_params={"variant": variant}, seed=2,
            )
            assert res.status == "FINISHED"


def dba_solver(m, **params):
    dcop = DCOP("dba", objective="min")
    d = Domain("d", "vals", [0, 1])
    a, b = Variable("a", d), Variable("b", d)
    dcop.add_variable(a)
    dcop.add_variable(b)
    dcop.add_constraint(
        NAryMatrixRelation([a, b], np.asarray(m, dtype=float), name="c")
    )
    dcop.add_agents([AgentDef("ag")])
    algo = AlgorithmDef.build_with_default_params(
        "dba", params, parameters_definitions=dba_params
    )
    return DbaSolver(dcop, compile_constraint_graph(dcop), algo)


class TestDba:
    def test_weights_grow_only_when_stuck_and_violated(self):
        # (0,0) is a strict local min with nonzero cost -> breakout bumps
        solver = dba_solver([[1.0, 2.0], [2.0, 3.0]])
        state = solver.initial_state()
        x = jnp.asarray([0, 0], dtype=jnp.int32)
        state = (x,) + tuple(state[1:])
        state2 = solver.cycle(state, jax.random.PRNGKey(0))
        w_after = [np.asarray(w) for w in state2[1]]
        assert sum(float(w.sum()) for w in w_after) > sum(
            float(np.asarray(w).sum()) for w in solver.initial_state()[1]
        )

    def test_breakout_reweighting_escapes_tie(self):
        """The canonical breakout move: b is torn between violating c1
        (at b=0) or c2 (at b=1) with equal weights — a tie, so it is
        stuck; the violated constraint's weight grows until the balance
        tips and b moves."""
        dcop = DCOP("tie", objective="min")
        d1 = Domain("d1", "v", [0])
        d2 = Domain("d2", "v", [0, 1])
        a, b, c = Variable("a", d1), Variable("b", d2), Variable("c", d1)
        for v in (a, b, c):
            dcop.add_variable(v)
        dcop.add_constraint(NAryMatrixRelation(
            [a, b], np.array([[1.0, 0.0]]), name="c1"))  # violated iff b=0
        dcop.add_constraint(NAryMatrixRelation(
            [b, c], np.array([[0.0], [1.0]]), name="c2"))  # viol. iff b=1
        dcop.add_agents([AgentDef("ag")])
        algo = AlgorithmDef.build_with_default_params(
            "dba", {}, parameters_definitions=dba_params
        )
        solver = DbaSolver(dcop, compile_constraint_graph(dcop), algo)
        state = solver.initial_state()
        state = (jnp.asarray([0, 0, 0], dtype=jnp.int32),) + \
            tuple(state[1:])
        key = jax.random.PRNGKey(5)
        bs = []
        for _ in range(4):
            key, sub = jax.random.split(key)
            state = solver.cycle(state, sub)
            bs.append(int(np.asarray(state[0])[1]))
        # cycle 1: tie -> stuck, c1's weight bumps; cycle 2: b moves
        assert bs[0] == 0 and 1 in bs, bs
        w = np.asarray(state[1])
        assert w.max() > 1.0  # a weight actually grew

    def test_csp_solved(self):
        # classic CSP use: 3-coloring a triangle (dba is a CSP algorithm)
        yaml_str = textwrap.dedent("""
            name: tri
            objective: min
            domains:
              colors: {values: [R, G, B]}
            variables:
              v1: {domain: colors}
              v2: {domain: colors}
              v3: {domain: colors}
            constraints:
              c12: {type: intention, function: "10000 if v1 == v2 else 0"}
              c13: {type: intention, function: "10000 if v1 == v3 else 0"}
              c23: {type: intention, function: "10000 if v2 == v3 else 0"}
            agents: [a1, a2, a3, a4, a5, a6]
        """)
        dcop = load_dcop(yaml_str)
        res = solve_result(dcop, "dba", cycles=50, seed=3)
        assert res.violation == 0
        vals = set(res.assignment.values())
        assert len(vals) == 3  # proper coloring
