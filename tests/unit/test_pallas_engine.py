"""Tests for the lane-packed pallas MaxSum engine and its Clos routing.

The pallas kernels themselves run in interpret mode here (CPU test mesh);
the routing planner and layout compiler are pure host code and are tested
exactly.  On-TPU equivalence of the compiled kernels vs the generic engine
is additionally exercised by bench runs (the kernels share _cycle_body with
interpret mode, so the math under test is the same trace).
"""
import numpy as np
import pytest

from pydcop_tpu.ops.clos_routing import edge_color, plan_permutation
from pydcop_tpu.ops.compile import compile_binary_from_arrays
from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
from pydcop_tpu.ops.pallas_maxsum import (
    pack_for_pallas,
    packed_cycle,
    packed_init_state,
    packed_values,
    try_pack_for_pallas,
)


class TestClosRouting:
    def test_edge_color_is_proper(self):
        rng = np.random.default_rng(3)
        n, deg = 16, 8
        # deg-regular bipartite multigraph: deg random perfect matchings
        src = np.concatenate([np.arange(n)] * deg)
        dst = np.concatenate([rng.permutation(n) for _ in range(deg)])
        colors = edge_color(src, dst, n, n, deg)
        for v in range(n):
            assert sorted(colors[src == v]) == list(range(deg))
            assert sorted(colors[dst == v]) == list(range(deg))

    @pytest.mark.parametrize("A,B,L", [(1, 2, 2), (2, 4, 4), (3, 8, 8),
                                       (5, 16, 16), (2, 128, 128)])
    def test_plan_applies_any_permutation(self, A, B, L):
        rng = np.random.default_rng(A * 100 + B)
        N = A * B * L
        for _ in range(3):
            perm = rng.permutation(N)
            plan = plan_permutation(perm, A, B, L)
            x = rng.uniform(0, 1, (4, N)).astype(np.float32)
            assert np.array_equal(plan.apply_numpy(x), x[:, perm])

    def test_plan_identity(self):
        plan = plan_permutation(np.arange(2 * 8 * 8), 2, 8, 8)
        x = np.arange(2 * 2 * 8 * 8, dtype=np.float32).reshape(2, -1)
        assert np.array_equal(plan.apply_numpy(x), x)


def _random_binary_instance(V=60, F=150, D=3, seed=0):
    rng = np.random.default_rng(seed)
    ei = rng.integers(0, V, F)
    ej = (ei + 1 + rng.integers(0, V - 1, F)) % V
    mats = rng.uniform(0, 5, (F, D, D)).astype(np.float32)
    un = rng.uniform(0, 1, (V, D)).astype(np.float32)
    return compile_binary_from_arrays(ei, ej, mats, V, unary=un)


class TestPackedEngine:
    def test_layout_invariants(self):
        t = _random_binary_instance()
        pg = pack_for_pallas(t)
        assert pg is not None
        assert pg.N == pg.plan.n
        # every real variable has a distinct padded column
        cols = np.asarray(pg.var_order)
        assert len(set(cols.tolist())) == t.n_vars
        # mask/unary agree with the source tensors at those columns
        assert np.allclose(
            np.asarray(pg.mask_p)[:, cols], np.asarray(t.domain_mask).T
        )

    def test_pack_rejects_non_binary(self):
        rng = np.random.default_rng(0)
        from pydcop_tpu.dcop import DCOP, Domain, NAryMatrixRelation, Variable

        d = Domain("d", "d", [0, 1])
        vs = [Variable(f"v{i}", d) for i in range(3)]
        c = NAryMatrixRelation(vs, rng.uniform(0, 1, (2, 2, 2)), name="c")
        dcop = DCOP("t")
        for v in vs:
            dcop.add_variable(v)
        dcop.add_constraint(c)
        from pydcop_tpu.ops.compile import compile_factor_graph

        assert pack_for_pallas(compile_factor_graph(dcop)) is None

    def test_cycle_matches_generic_engine(self):
        t = _random_binary_instance()
        pg = pack_for_pallas(t)
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(4):
            q, r, bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        belp_orig = np.asarray(belp)[:, np.asarray(pg.var_order)].T
        assert np.allclose(np.asarray(bel), belp_orig, atol=1e-4)
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))

    def test_local_tables_match_generic(self):
        from pydcop_tpu.ops.compile import local_cost_tables
        from pydcop_tpu.ops.pallas_maxsum import packed_local_tables

        t = _random_binary_instance(V=50, F=120, D=3, seed=5)
        pg = pack_for_pallas(t)
        rng = np.random.default_rng(2)
        x = np.asarray(rng.integers(0, 3, 50), dtype=np.int32)
        import jax.numpy as jnp

        ref = np.asarray(local_cost_tables(t, jnp.asarray(x)))
        got = np.asarray(packed_local_tables(pg, jnp.asarray(x),
                                             interpret=True))
        assert np.allclose(ref, got, atol=1e-4)

    def test_vmem_bytes_property(self):
        t = _random_binary_instance()
        pg = pack_for_pallas(t)
        assert isinstance(pg.vmem_bytes, int) and pg.vmem_bytes > 0

    def test_star_hub_packs_and_matches_generic(self):
        # a star graph: center degree above _MAX_SLOT_CLASS is split into
        # sub-columns (hub splitting) and must bit-match the generic engine
        from pydcop_tpu.ops.pallas_maxsum import _MAX_SLOT_CLASS

        rng = np.random.default_rng(7)
        F = _MAX_SLOT_CLASS + 50
        ei = np.zeros(F, dtype=np.int64)
        ej = np.arange(1, F + 1)
        mats = rng.uniform(0, 1, (F, 3, 3)).astype(np.float32)
        un = rng.uniform(0, 1, (F + 1, 3)).astype(np.float32)
        t = compile_binary_from_arrays(ei, ej, mats, F + 1, unary=un)
        pg = pack_for_pallas(t)
        assert pg is not None and pg.hub_nsteps > 0
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(4):
            q, r, bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        belp_orig = np.asarray(belp)[:, np.asarray(pg.var_order)].T
        assert np.allclose(np.asarray(bel), belp_orig, atol=1e-4)
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))

    def test_pack_rejects_bin_overflow_hub(self):
        # a hub beyond _MAX_SLOT_CLASS * 128 sub-column slots cannot keep
        # its group inside one 128-lane bin — must fall back
        from pydcop_tpu.ops.pallas_maxsum import _LANES, _MAX_SLOT_CLASS

        F = _MAX_SLOT_CLASS * _LANES + 1
        ei = np.zeros(F, dtype=np.int64)
        ej = np.arange(1, F + 1)
        mats = np.ones((F, 2, 2), dtype=np.float32)
        t = compile_binary_from_arrays(ei, ej, mats, F + 1)
        assert pack_for_pallas(t) is None

    def test_packed_values_respects_domain_mask(self):
        # variables with smaller domains must never select padded values
        rng = np.random.default_rng(1)
        V, F, D = 40, 80, 4
        ei = rng.integers(0, V, F)
        ej = (ei + 1 + rng.integers(0, V - 1, F)) % V
        mats = rng.uniform(0, 5, (F, D, D)).astype(np.float32)
        t = compile_binary_from_arrays(ei, ej, mats, V)
        # shrink every other variable to domain size 2
        import jax.numpy as jnp

        mask = np.array(t.domain_mask, copy=True)
        mask[::2, 2:] = 0.0
        t.domain_mask = jnp.asarray(mask)
        pg = pack_for_pallas(t)
        qp, rp = packed_init_state(pg)
        for _ in range(3):
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.3, interpret=True
            )
        vals = np.asarray(valsp)
        assert (vals[::2] < 2).all()
        assert (vals < D).all()


class TestEngineSelection:
    """The round-1 regression class: the TPU branch of engine selection was
    never executed in CI and shipped broken.  These tests drive the exact
    branch solvers take on TPU hardware (backend monkeypatched to "tpu";
    the packed kernels auto-run in interpret mode off-TPU)."""

    def _coloring_dcop(self):
        from pydcop_tpu.generators import generate_graph_coloring

        return generate_graph_coloring(
            n_variables=25, n_colors=3, n_edges=60, soft=True,
            n_agents=1, seed=3,
        )

    def test_maxsum_tpu_branch_solves(self, monkeypatch):
        import jax

        from pydcop_tpu.algorithms.maxsum import build_solver

        dcop = self._coloring_dcop()
        generic = build_solver(dcop)
        assert generic.packed is None  # CPU backend → generic engine
        ref = generic.run(cycles=10)

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        solver = build_solver(dcop)
        assert solver.packed is not None  # TPU branch picked the engine
        got = solver.run(cycles=10)
        assert got.status == "FINISHED"
        # engines sum beliefs in different fp orders, so near-tied argmins
        # may flip; cost equivalence is the robust invariant
        assert got.cost == pytest.approx(ref.cost, rel=1e-3)

    def test_local_search_tpu_branch_solves(self, monkeypatch):
        import jax

        from pydcop_tpu.algorithms.mgm import build_solver

        dcop = self._coloring_dcop()
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        solver = build_solver(dcop)
        got = solver.run(cycles=8)
        assert got.status == "FINISHED"
        assert got.cost is not None

    def test_packing_error_falls_back_to_generic(self, monkeypatch):
        import pydcop_tpu.ops.pallas_maxsum as pm
        from pydcop_tpu.algorithms.maxsum import MaxSumSolver
        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms.maxsum import algo_params
        from pydcop_tpu.ops.compile import compile_factor_graph

        def boom(t):
            raise AttributeError("simulated packing regression")

        monkeypatch.setattr(pm, "pack_for_pallas", boom)
        assert try_pack_for_pallas(None) is None

        dcop = self._coloring_dcop()
        algo = AlgorithmDef.build_with_default_params(
            "maxsum", parameters_definitions=algo_params
        )
        solver = MaxSumSolver(
            dcop, compile_factor_graph(dcop), algo, use_packed=True
        )
        assert solver.packed is None  # degraded, not crashed
        res = solver.run(cycles=5)
        assert res.status == "FINISHED"


class TestFusedCycles:
    def test_fused_matches_per_cycle(self):
        from pydcop_tpu.ops.pallas_maxsum import packed_cycles

        t = _random_binary_instance()
        pg = pack_for_pallas(t)
        q1, r1 = packed_init_state(pg)
        for _ in range(6):
            q1, r1, bel1, vals1 = packed_cycle(
                pg, q1, r1, damping=0.5, interpret=True
            )
        q2, r2 = packed_init_state(pg)
        q2, r2, bel2, vals2 = packed_cycles(
            pg, q2, r2, 6, damping=0.5, interpret=True
        )
        assert np.allclose(np.asarray(q1), np.asarray(q2), atol=1e-4)
        assert np.allclose(np.asarray(r1), np.asarray(r2), atol=1e-4)
        assert np.array_equal(np.asarray(vals1), np.asarray(vals2))

    def test_fused_single_cycle(self):
        from pydcop_tpu.ops.pallas_maxsum import packed_cycles

        t = _random_binary_instance()
        pg = pack_for_pallas(t)
        q0, r0 = packed_init_state(pg)
        q1, r1, _, v1 = packed_cycle(pg, q0, r0, damping=0.0,
                                     interpret=True)
        q2, r2, _, v2 = packed_cycles(pg, q0, r0, 1, damping=0.0,
                                      interpret=True)
        assert np.allclose(np.asarray(q1), np.asarray(q2), atol=1e-5)
        assert np.array_equal(np.asarray(v1), np.asarray(v2))
