"""Fused packed local-search engine ≡ generic engine (exact cross-checks).

Costs in these instances are integers, so float sums are exact and the
packed kernels must reproduce the generic path bit-for-bit — including
argmin tie-breaks and MGM's lexic neighborhood arbitration.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms._local_search import random_valid_values
from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.ops.compile import compile_constraint_graph, total_cost
from pydcop_tpu.ops.pallas_local_search import (
    pack_local_search,
    pack_x,
    packed_dsa_cycles,
    packed_mgm_cycles,
    uniforms_for_keys,
    unpack_x,
)


def _instance(n_vars=40, n_edges=90, seed=3):
    dcop = generate_graph_coloring(
        n_variables=n_vars, n_colors=3, n_edges=n_edges, soft=True,
        n_agents=1, seed=seed,
    )
    return dcop, compile_constraint_graph(dcop)


@pytest.fixture(scope="module")
def packed_instance():
    dcop, tensors = _instance()
    pls = pack_local_search(tensors)
    assert pls is not None
    return dcop, tensors, pls


def test_pack_roundtrip(packed_instance):
    _, tensors, pls = packed_instance
    x = random_valid_values(tensors, jax.random.PRNGKey(0))
    x_row = pack_x(pls, x)
    np.testing.assert_array_equal(np.asarray(unpack_x(pls, x_row)),
                                  np.asarray(x))


def test_mgm_fused_matches_generic(packed_instance):
    from pydcop_tpu.algorithms.mgm import MgmSolver

    dcop, tensors, pls = packed_instance
    algo_def = AlgorithmDef.build_with_default_params("mgm")
    solver = MgmSolver(dcop, tensors, algo_def, seed=0)
    assert solver.packed is None  # CPU: generic per-cycle path

    x = random_valid_values(tensors, jax.random.PRNGKey(17))
    state = (x,)
    n = 12
    for i in range(n):
        state = solver.cycle(state, jax.random.PRNGKey(i))
    expected = np.asarray(state[0])

    x_row = packed_mgm_cycles(pls, pack_x(pls, x), n)
    got = np.asarray(unpack_x(pls, x_row))
    np.testing.assert_array_equal(got, expected)


def test_mgm_fused_is_monotone(packed_instance):
    dcop, tensors, pls = packed_instance
    x = random_valid_values(tensors, jax.random.PRNGKey(5))
    x_row = pack_x(pls, x)
    prev_cost = float(total_cost(tensors, unpack_x(pls, x_row)))
    for _ in range(6):
        x_row = packed_mgm_cycles(pls, x_row, 2)
        cost = float(total_cost(tensors, unpack_x(pls, x_row)))
        assert cost <= prev_cost + 1e-6  # MGM never increases total cost
        prev_cost = cost


@pytest.mark.parametrize("variant", ["A", "B", "C"])
def test_dsa_fused_matches_generic(packed_instance, variant):
    from pydcop_tpu.algorithms.dsa import DsaSolver

    dcop, tensors, pls = packed_instance
    algo_def = AlgorithmDef.build_with_default_params(
        "dsa", {"variant": variant, "probability": 0.7}
    )
    solver = DsaSolver(dcop, tensors, algo_def, seed=0)

    x = random_valid_values(tensors, jax.random.PRNGKey(23))
    keys = jax.random.split(jax.random.PRNGKey(99), 10)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    expected = np.asarray(state[0])

    uniforms = uniforms_for_keys(pls, keys)
    x_row = packed_dsa_cycles(
        pls, pack_x(pls, x), uniforms, probability=0.7, variant=variant
    )
    got = np.asarray(unpack_x(pls, x_row))
    np.testing.assert_array_equal(got, expected)


def test_mgm_lexic_tiebreak_smallest_index_wins():
    """Two variables in conflict with equal gains: only the smaller
    index may move in one MGM cycle (reference mgm.py lexic break_mode)."""
    from pydcop_tpu.dcop import DCOP, Domain, Variable, constraint_from_str

    d = Domain("c", "c", ["R", "G"])
    dcop = DCOP("tie", objective="min")
    va = Variable("a", d)
    vb = Variable("b", d)
    dcop.add_constraint(constraint_from_str(
        "conf", "10 if a == b else 0", [va, vb]))
    tensors = compile_constraint_graph(dcop)
    pls = pack_local_search(tensors)
    assert pls is not None

    x = jnp.array([0, 0], dtype=jnp.int32)  # both "R": conflict, tied gain
    x_row = packed_mgm_cycles(pls, pack_x(pls, x), 1)
    got = np.asarray(unpack_x(pls, x_row))
    # only variable 0 ("a") moves in the first cycle
    assert got[0] != 0 and got[1] == 0


def test_degree_zero_variable_moves_on_unary_gain():
    """An isolated variable has no neighbors — MGM must let it move on
    its own unary gain (generic: empty neighborhood)."""
    from pydcop_tpu.dcop import DCOP, Domain, Variable, constraint_from_str
    from pydcop_tpu.dcop.objects import VariableWithCostDict

    d = Domain("c", "c", [0, 1])
    dcop = DCOP("iso", objective="min")
    va = Variable("a", d)
    vb = Variable("b", d)
    # a-b constrained; z isolated with a unary cost preferring value 1
    vz = VariableWithCostDict("z", d, {0: 10.0, 1: 0.0})
    dcop.add_variable(vz)
    dcop.add_constraint(constraint_from_str(
        "conf", "5 if a == b else 0", [va, vb]))
    tensors = compile_constraint_graph(dcop)
    pls = pack_local_search(tensors)
    assert pls is not None
    iz = tensors.var_index("z")

    x = jnp.zeros(3, dtype=jnp.int32)
    x_row = packed_mgm_cycles(pls, pack_x(pls, x), 1)
    got = np.asarray(unpack_x(pls, x_row))
    assert got[iz] == 1  # moved to the cheap value


def test_fused_chunks_equal_single_calls(packed_instance):
    """packed_mgm_cycles(n) ≡ n sequential packed_mgm_cycles(1)."""
    _, tensors, pls = packed_instance
    x = random_valid_values(tensors, jax.random.PRNGKey(7))
    a = pack_x(pls, x)
    b = a
    a = packed_mgm_cycles(pls, a, 6)
    for _ in range(6):
        b = packed_mgm_cycles(pls, b, 1)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("algo", ["mgm", "dsa"])
def test_solver_fused_path_matches_generic(algo):
    """MgmSolver/DsaSolver with the packed engine (fused chunk runner)
    produce the same run as the generic engine — same seed, same PRNG
    stream, integer costs."""
    from pydcop_tpu.algorithms import load_algorithm_module

    dcop, _ = _instance(n_vars=30, n_edges=70, seed=11)
    mod = load_algorithm_module(algo)
    algo_def = AlgorithmDef.build_with_default_params(algo)

    tensors_a = compile_constraint_graph(dcop)
    generic = mod.__dict__[
        "MgmSolver" if algo == "mgm" else "DsaSolver"
    ](dcop, tensors_a, algo_def, seed=4, use_packed=False)
    assert generic.packed_ls is None
    res_g = generic.run(cycles=20, chunk=20)

    tensors_b = compile_constraint_graph(dcop)
    fused = mod.__dict__[
        "MgmSolver" if algo == "mgm" else "DsaSolver"
    ](dcop, tensors_b, algo_def, seed=4, use_packed=True)
    assert fused.packed_ls is not None
    res_f = fused.run(cycles=20, chunk=20)

    assert res_f.assignment == res_g.assignment
    assert res_f.cost == res_g.cost


@pytest.mark.parametrize("variant", ["A", "B", "C"])
def test_mixeddsa_fused_matches_generic(packed_instance, variant):
    """packed_dsa_cycles with probability_hard ≡ MixedDsaSolver.cycle."""
    from pydcop_tpu.algorithms.mixeddsa import MixedDsaSolver

    dcop, tensors, pls = packed_instance
    algo_def = AlgorithmDef.build_with_default_params(
        "mixeddsa",
        {"variant": variant, "proba_hard": 0.9, "proba_soft": 0.4},
    )
    solver = MixedDsaSolver(dcop, tensors, algo_def, seed=0)

    x = random_valid_values(tensors, jax.random.PRNGKey(31))
    keys = jax.random.split(jax.random.PRNGKey(77), 10)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    expected = np.asarray(state[0])

    uniforms = uniforms_for_keys(pls, keys)
    x_row = packed_dsa_cycles(
        pls, pack_x(pls, x), uniforms, probability=0.4, variant=variant,
        probability_hard=0.9,
    )
    np.testing.assert_array_equal(
        np.asarray(unpack_x(pls, x_row)), expected)


@pytest.mark.parametrize("variant", ["A", "B", "C"])
def test_adsa_fused_matches_generic(packed_instance, variant):
    """packed_dsa_cycles with the wake mask ≡ ADsaSolver.cycle (same
    split-key PRNG stream), for every variant."""
    from pydcop_tpu.algorithms.adsa import ADsaSolver
    from pydcop_tpu.ops.pallas_local_search import uniforms_for_split_keys

    dcop, tensors, pls = packed_instance
    algo_def = AlgorithmDef.build_with_default_params(
        "adsa", {"activation": 0.6, "probability": 0.7,
                 "variant": variant})
    solver = ADsaSolver(dcop, tensors, algo_def, seed=0)

    x = random_valid_values(tensors, jax.random.PRNGKey(41))
    keys = jax.random.split(jax.random.PRNGKey(55), 10)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    expected = np.asarray(state[0])

    wake_u, move_u = uniforms_for_split_keys(pls, keys)
    x_row = packed_dsa_cycles(
        pls, pack_x(pls, x), move_u, probability=0.7, variant=variant,
        awake_uniforms=wake_u, activation=0.6,
    )
    np.testing.assert_array_equal(
        np.asarray(unpack_x(pls, x_row)), expected)


@pytest.mark.parametrize("algo", ["mixeddsa", "adsa"])
def test_solver_fused_path_mixed_adsa(algo):
    """Full-solver equivalence through the fused chunk runners."""
    from pydcop_tpu.algorithms.adsa import ADsaSolver
    from pydcop_tpu.algorithms.mixeddsa import MixedDsaSolver

    cls = MixedDsaSolver if algo == "mixeddsa" else ADsaSolver
    dcop, _ = _instance(n_vars=30, n_edges=70, seed=19)
    algo_def = AlgorithmDef.build_with_default_params(algo)

    generic = cls(dcop, compile_constraint_graph(dcop), algo_def, seed=4,
                  use_packed=False)
    assert generic.packed_ls is None
    res_g = generic.run(cycles=20, chunk=20)

    fused = cls(dcop, compile_constraint_graph(dcop), algo_def, seed=4,
                use_packed=True)
    assert fused.packed_ls is not None
    res_f = fused.run(cycles=20, chunk=20)

    assert res_f.assignment == res_g.assignment
    assert res_f.cost == res_g.cost


# ---------------------------------------------------------------------------
# mixed-arity (1/2/3) fused MOVE kernels (VERDICT r5 item 1)
# ---------------------------------------------------------------------------


import os
import sys

if os.path.dirname(__file__) not in sys.path:
    sys.path.insert(0, os.path.dirname(__file__))


def _mixed_instance(seed=5, **kw):
    from test_mixed_arity_packing import _mixed_dcop

    dcop = _mixed_dcop(seed=seed, **kw)
    return dcop, compile_constraint_graph(dcop)


@pytest.fixture(scope="module")
def packed_mixed():
    dcop, tensors = _mixed_instance()
    pls = pack_local_search(tensors)
    assert pls is not None and pls.pg.mixed
    return dcop, tensors, pls


def test_mixed_mgm_fused_matches_generic(packed_mixed):
    from pydcop_tpu.algorithms.mgm import MgmSolver

    dcop, tensors, pls = packed_mixed
    solver = MgmSolver(dcop, tensors,
                       AlgorithmDef.build_with_default_params("mgm"),
                       seed=0)
    x = random_valid_values(tensors, jax.random.PRNGKey(17))
    state = (x,)
    n = 10
    for i in range(n):
        state = solver.cycle(state, jax.random.PRNGKey(i))
    expected = np.asarray(state[0])
    got = np.asarray(unpack_x(pls, packed_mgm_cycles(
        pls, pack_x(pls, x), n)))
    np.testing.assert_array_equal(got, expected)


def test_mixed_mgm_fused_is_monotone(packed_mixed):
    _, tensors, pls = packed_mixed
    x = random_valid_values(tensors, jax.random.PRNGKey(3))
    x_row = pack_x(pls, x)
    prev = float(total_cost(tensors, unpack_x(pls, x_row)))
    for _ in range(5):
        x_row = packed_mgm_cycles(pls, x_row, 2)
        cost = float(total_cost(tensors, unpack_x(pls, x_row)))
        assert cost <= prev + 1e-5
        prev = cost


@pytest.mark.parametrize("variant", ["A", "B", "C"])
def test_mixed_dsa_fused_matches_generic(packed_mixed, variant):
    from pydcop_tpu.algorithms.dsa import DsaSolver

    dcop, tensors, pls = packed_mixed
    algo_def = AlgorithmDef.build_with_default_params(
        "dsa", {"variant": variant, "probability": 0.7})
    solver = DsaSolver(dcop, tensors, algo_def, seed=0)
    x = random_valid_values(tensors, jax.random.PRNGKey(23))
    keys = jax.random.split(jax.random.PRNGKey(99), 8)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    expected = np.asarray(state[0])
    uniforms = uniforms_for_keys(pls, keys)
    got = np.asarray(unpack_x(pls, packed_dsa_cycles(
        pls, pack_x(pls, x), uniforms, probability=0.7,
        variant=variant)))
    np.testing.assert_array_equal(got, expected)


def test_mixed_adsa_and_mixeddsa_fused(packed_mixed):
    """The whole stochastic family rides the mixed fused kernel: adsa's
    wake masks and mixeddsa's per-conflict probabilities."""
    from pydcop_tpu.algorithms.adsa import ADsaSolver
    from pydcop_tpu.algorithms.mixeddsa import MixedDsaSolver
    from pydcop_tpu.ops.pallas_local_search import uniforms_for_split_keys

    dcop, tensors, pls = packed_mixed
    x = random_valid_values(tensors, jax.random.PRNGKey(31))
    keys = jax.random.split(jax.random.PRNGKey(77), 6)

    solver = MixedDsaSolver(
        dcop, tensors, AlgorithmDef.build_with_default_params(
            "mixeddsa", {"proba_hard": 0.9, "proba_soft": 0.4}),
        seed=0)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    got = np.asarray(unpack_x(pls, packed_dsa_cycles(
        pls, pack_x(pls, x), uniforms_for_keys(pls, keys),
        probability=0.4, variant="A", probability_hard=0.9)))
    np.testing.assert_array_equal(got, np.asarray(state[0]))

    solver = ADsaSolver(
        dcop, tensors, AlgorithmDef.build_with_default_params(
            "adsa", {"activation": 0.6, "probability": 0.7,
                     "variant": "B"}),
        seed=0)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    wake_u, move_u = uniforms_for_split_keys(pls, keys)
    got = np.asarray(unpack_x(pls, packed_dsa_cycles(
        pls, pack_x(pls, x), move_u, probability=0.7, variant="B",
        awake_uniforms=wake_u, activation=0.6)))
    np.testing.assert_array_equal(got, np.asarray(state[0]))


def test_mixed_ternary_only_mgm():
    """All-ternary graph: both sibling permutations carry gains."""
    from pydcop_tpu.algorithms.mgm import MgmSolver

    dcop, tensors = _mixed_instance(seed=3, n2=0, n1=0, n3=30)
    pls = pack_local_search(tensors)
    assert pls is not None and pls.mate2_idx is not None
    solver = MgmSolver(dcop, tensors,
                       AlgorithmDef.build_with_default_params("mgm"),
                       seed=0)
    x = random_valid_values(tensors, jax.random.PRNGKey(11))
    state = (x,)
    for i in range(8):
        state = solver.cycle(state, jax.random.PRNGKey(i))
    got = np.asarray(unpack_x(pls, packed_mgm_cycles(
        pls, pack_x(pls, x), 8)))
    np.testing.assert_array_equal(got, np.asarray(state[0]))


@pytest.mark.parametrize("algo", ["mgm", "dsa"])
def test_mixed_solver_fused_path_matches_generic(algo):
    """Solver-level: the fused chunk runner on a mixed instance equals
    the generic engine run (same seed → same PRNG stream)."""
    from pydcop_tpu.algorithms import load_algorithm_module

    dcop, _ = _mixed_instance(seed=11, V=30, n2=40, n3=15, n1=6)
    mod = load_algorithm_module(algo)
    algo_def = AlgorithmDef.build_with_default_params(algo)
    cls = mod.MgmSolver if algo == "mgm" else mod.DsaSolver

    generic = cls(dcop, compile_constraint_graph(dcop), algo_def, seed=4,
                  use_packed=False)
    res_g = generic.run(cycles=16, chunk=16)

    fused = cls(dcop, compile_constraint_graph(dcop), algo_def, seed=4,
                use_packed=True)
    assert fused.packed_ls is not None and fused.packed_ls.pg.mixed
    res_f = fused.run(cycles=16, chunk=16)

    assert res_f.assignment == res_g.assignment
    assert res_f.cost == res_g.cost


@pytest.mark.parametrize("favor", ["unilateral", "no", "coordinated"])
def test_mixed_mgm2_fused_matches_generic(favor):
    """The 5-round MGM-2 kernel on a mixed graph ≡ the generic solver:
    pairing stays on binary edges, tables and the gain/go arbitration
    cover unary+ternary too (both sibling permutations)."""
    from pydcop_tpu.algorithms.mgm2 import Mgm2Solver
    from pydcop_tpu.ops.pallas_mgm2 import (
        pack_mgm2_from_pls,
        packed_mgm2_cycles,
        uniforms_for_mgm2,
    )

    dcop, tensors = _mixed_instance(seed=7, V=30, n2=40, n3=15, n1=6)
    pls = pack_local_search(tensors)
    pm = pack_mgm2_from_pls(pls)
    assert pm is not None and pls.pg.mixed
    solver = Mgm2Solver(
        dcop, tensors,
        AlgorithmDef.build_with_default_params("mgm2", {"favor": favor}),
        seed=0, use_packed=False)
    x = random_valid_values(tensors, jax.random.PRNGKey(13))
    keys = jax.random.split(jax.random.PRNGKey(42), 6)
    state = (x,)
    for k in keys:
        state = solver.cycle(state, k)
    uo, up, uf = uniforms_for_mgm2(pm, keys)
    got = np.asarray(unpack_x(pls, packed_mgm2_cycles(
        pm, pack_x(pls, x), uo, up, uf, solver.threshold, favor)))
    np.testing.assert_array_equal(got, np.asarray(state[0]))


def test_mixed_mgm2_solver_fused_path():
    """Solver-level equivalence on a mixed instance through the fused
    chunk runner."""
    from pydcop_tpu.algorithms.mgm2 import Mgm2Solver

    dcop, _ = _mixed_instance(seed=9, V=24, n2=30, n3=10, n1=4)
    algo_def = AlgorithmDef.build_with_default_params("mgm2")

    generic = Mgm2Solver(dcop, compile_constraint_graph(dcop), algo_def,
                         seed=2, use_packed=False)
    res_g = generic.run(cycles=12, chunk=12)

    fused = Mgm2Solver(dcop, compile_constraint_graph(dcop), algo_def,
                       seed=2, use_packed=True)
    assert fused.packed_mgm2 is not None
    res_f = fused.run(cycles=12, chunk=12)

    assert res_f.assignment == res_g.assignment
    assert res_f.cost == res_g.cost
