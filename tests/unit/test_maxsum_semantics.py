"""MaxSum parameter semantics (reference pydcop/algorithms/maxsum.py):
damping, noise tie-breaking, normalization, stop_cycle accounting.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.maxsum import MaxSumSolver, algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.compile import compile_factor_graph
from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle


def ring_dcop(n=4, d=3, seed=0):
    rng = np.random.default_rng(seed)
    dcop = DCOP("ring", objective="min")
    dom = Domain("d", "vals", list(range(d)))
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        m = rng.uniform(0, 5, (d, d))
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[(i + 1) % n]], m, name=f"c{i}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


def solver_with(dcop, **params):
    algo = AlgorithmDef.build_with_default_params(
        "maxsum", params, parameters_definitions=algo_params
    )
    return MaxSumSolver(dcop, compile_factor_graph(dcop), algo,
                        use_packed=False)


def test_damping_zero_is_respected():
    """damping=0 is a VALID value (the reference default) and must not
    be silently replaced by the 0.5 framework default."""
    s = solver_with(ring_dcop(), damping=0.0)
    assert s.damping == 0.0
    s2 = solver_with(ring_dcop())
    assert s2.damping == 0.5


def test_damping_slows_message_movement():
    """Damped messages move less per cycle: ||r1 - r0|| shrinks as
    damping grows (r0 = 0, so damping scales the first step by 1-d)."""
    dcop = ring_dcop(seed=3)
    tensors = compile_factor_graph(dcop)
    q0, r0 = init_messages(tensors)
    norms = {}
    for d in (0.0, 0.5, 0.9):
        _, r1, _, _ = maxsum_cycle(tensors, q0, r0, damping=d)
        norms[d] = float(jnp.abs(r1).sum())
    assert norms[0.0] > norms[0.5] > norms[0.9]
    assert norms[0.5] == pytest.approx(norms[0.0] * 0.5, rel=1e-4)
    assert norms[0.9] == pytest.approx(norms[0.0] * 0.1, rel=1e-3)


def test_var_to_factor_messages_are_mean_normalized():
    """The reference normalizes var→factor messages by their average
    (costs_for_factor, maxsum.py:602) to stop drift; q messages must
    stay zero-mean over valid domain slots."""
    dcop = ring_dcop(seed=4)
    tensors = compile_factor_graph(dcop)
    q, r = init_messages(tensors)
    for _ in range(5):
        q, r, _, _ = maxsum_cycle(tensors, q, r, damping=0.0)
    means = np.asarray(q).mean(axis=1)  # all domain slots valid here
    assert np.abs(means).max() < 1e-4


def test_noise_deterministic_per_seed():
    d1 = solver_with(ring_dcop(), noise=0.01)
    d2 = solver_with(ring_dcop(), noise=0.01)
    assert np.allclose(
        np.asarray(d1.tensors.unary_costs),
        np.asarray(d2.tensors.unary_costs),
    )
    r1 = d1.run(cycles=15)
    r2 = d2.run(cycles=15)
    assert r1.assignment == r2.assignment
    assert r1.cost == pytest.approx(r2.cost)


def test_noise_breaks_symmetric_ties():
    """On a perfectly symmetric coloring instance BP beliefs are
    identical across values; without noise every variable argmins to
    index 0 (all-same = worst for coloring), with noise the symmetry
    breaks (reference injects VariableNoisyCostFunc, maxsum.py:449-454)."""
    dcop = DCOP("sym", objective="min")
    dom = Domain("c", "colors", ["R", "G", "B"])
    vs = [Variable(f"v{i}", dom) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    eq = np.ones((3, 3)) * 0 + np.eye(3) * 10  # penalize equality
    for i in range(3):
        for j in range(i + 1, 3):
            dcop.add_constraint(
                NAryMatrixRelation([vs[i], vs[j]], eq, name=f"c{i}{j}")
            )
    dcop.add_agents([AgentDef("a0")])

    res_noise = solver_with(dcop, noise=0.01).run(cycles=30)
    assert res_noise.cost < 30  # not all-same
    res_flat = solver_with(dcop, noise=0.0).run(cycles=30)
    # without noise the fully symmetric instance cannot do better than
    # picking identical values (documented reference behavior)
    assert res_flat.cost >= 30


def test_stop_cycle_and_message_accounting():
    dcop = ring_dcop()
    s = solver_with(dcop)
    res = s.run(cycles=7)
    assert res.cycle == 7
    tensors = s.tensors
    assert res.msg_count == 2 * tensors.n_edges * 7
    assert res.msg_size == pytest.approx(
        2 * tensors.n_edges * 7 * tensors.max_domain_size
    )


def test_maxsum_max_mode():
    dcop = ring_dcop(n=3, seed=6)
    dcop.objective = "max"  # maximize the same tables
    algo = AlgorithmDef.build_with_default_params(
        "maxsum", {}, mode="max", parameters_definitions=algo_params
    )
    tensors = compile_factor_graph(dcop)
    s = MaxSumSolver(dcop, tensors, algo, use_packed=False)
    res = s.run(cycles=25)
    # brute force the true max
    import itertools

    names = sorted(dcop.variables)
    best = -1e18
    for combo in itertools.product(range(3), repeat=3):
        _, c = dcop.solution_cost(dict(zip(names, combo)), 10000)
        best = max(best, c)
    assert res.cost >= 0.8 * best  # BP near-optimal on a tiny ring

def test_stability_param_drives_convergence():
    """The `stability` algo param is the message-stability convergence
    coefficient (reference approx_match, maxsum.py:98-100) — a loose
    coefficient converges in no more chunks than a strict one (VERDICT
    r2: the param must not be a silent no-op)."""
    import numpy as np

    from pydcop_tpu.algorithms import AlgorithmDef
    from pydcop_tpu.algorithms.maxsum import MaxSumSolver
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_factor_graph

    dcop = generate_graph_coloring(
        n_variables=20, n_colors=3, n_edges=40, soft=True, n_agents=1,
        seed=6,
    )
    tensors = compile_factor_graph(dcop)

    def cycles_until_stop(stability):
        algo_def = AlgorithmDef.build_with_default_params(
            "maxsum", {"stability": stability})
        s = MaxSumSolver(dcop, tensors, algo_def, seed=0)
        res = s.run(max_cycles=400, chunk=8)
        return res.cycle

    strict = cycles_until_stop(1e-9)
    loose = cycles_until_stop(1e6)  # any same-sign change accepted
    assert loose <= strict
    # the loose criterion converges well before the cycle cap
    assert loose < 400


class TestEdgeSlabs:
    """Edge-slab factor side (big-graph stretch path) ≡ the [F, D, D]
    broadcast-min cycle, in both edge orders and with ragged domains."""

    def _instance(self, D=4, seed=0):
        import numpy as np
        import jax.numpy as jnp
        from pydcop_tpu.ops.compile import compile_binary_from_arrays

        rng = np.random.default_rng(seed)
        V, E = 60, 150
        ei = rng.integers(0, V, E)
        ej = (ei + 1 + rng.integers(0, V - 1, E)) % V
        mats = rng.uniform(0, 5, (E, D, D)).astype(np.float32)
        un = rng.uniform(0, 1, (V, D)).astype(np.float32)
        t = compile_binary_from_arrays(ei, ej, mats, V, unary=un)
        mask = np.array(t.domain_mask, copy=True)
        mask[::3, D - 1:] = 0.0  # ragged domains
        t.domain_mask = jnp.asarray(mask)
        return t

    def test_matches_generic_cycle(self):
        import numpy as np
        from pydcop_tpu.ops.maxsum_kernels import (
            EdgeSlabs,
            init_messages,
            maxsum_cycle,
            maxsum_cycle_edge_slabs,
        )

        t = self._instance()
        for sort in (False, True):
            slabs = EdgeSlabs(t, sort_edges=sort)
            q1, r1 = init_messages(t)
            q2, r2 = init_messages(t)
            for _ in range(5):
                q1, r1, b1, v1 = maxsum_cycle(t, q1, r1, damping=0.5)
                q2, r2, b2, v2 = maxsum_cycle_edge_slabs(
                    t, slabs, q2, r2, damping=0.5
                )
            assert np.allclose(np.asarray(b1), np.asarray(b2), atol=1e-4)
            assert np.array_equal(np.asarray(v1), np.asarray(v2))

    def test_solver_eslab_engine_matches_generic(self):
        """MaxSumSolver's megascale edge-slab tier (forced on a small
        instance) must reproduce the generic engine's run exactly."""
        import numpy as np
        from pydcop_tpu.algorithms.maxsum import build_solver
        from pydcop_tpu.generators import generate_graph_coloring
        from pydcop_tpu.ops.maxsum_kernels import EdgeSlabs

        dcop = generate_graph_coloring(
            n_variables=40, n_colors=3, n_edges=90, soft=True,
            n_agents=1, seed=2,
        )
        ref = build_solver(dcop).run(cycles=12, chunk=12)
        s = build_solver(dcop)
        assert s.eslabs is None  # below the megascale threshold
        s.eslabs = EdgeSlabs(s.tensors)  # force the tier
        got = s.run(cycles=12, chunk=12)
        assert got.assignment == ref.assignment
        assert got.cost == ref.cost
        # metrics collection path too
        got2 = build_solver(dcop)
        got2.eslabs = EdgeSlabs(got2.tensors)
        r2 = got2.run(cycles=6, chunk=6, collect_cycles=True)
        assert r2.history is not None and len(r2.history) == 6
