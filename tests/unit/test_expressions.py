"""Unit tests for the safe expression evaluator."""
import pytest

from pydcop_tpu.utils.expressions import (
    ExpressionFunction,
    ExpressionFunctionError,
)
from pydcop_tpu.utils.serialization import from_repr, simple_repr


def test_simple_expression():
    f = ExpressionFunction("a + b * 2")
    assert f.variable_names == {"a", "b"}
    assert f(a=1, b=3) == 7


def test_conditional():
    f = ExpressionFunction("1 if v1 == v2 else 0")
    assert f(v1="R", v2="R") == 1
    assert f(v1="R", v2="G") == 0


def test_math_helpers():
    f = ExpressionFunction("abs(x) + round(y)")
    assert f(x=-2, y=1.4) == 3
    g = ExpressionFunction("sqrt(x)")
    assert g(x=9) == 3


def test_partial():
    f = ExpressionFunction("a + b + c")
    g = f.partial(b=10)
    assert g.variable_names == {"a", "c"}
    assert g(a=1, c=2) == 13


def test_partial_unknown_var():
    with pytest.raises(ExpressionFunctionError):
        ExpressionFunction("a + b").partial(z=1)


def test_missing_variable():
    with pytest.raises(ExpressionFunctionError):
        ExpressionFunction("a + b")(a=1)


def test_multiline_return():
    f = ExpressionFunction(
        """
total = a + b
return total * 2
"""
    )
    assert f(a=1, b=2) == 6


@pytest.mark.parametrize(
    "expr",
    [
        "__import__('os').system('true')",
        "open('/etc/passwd')",
        "(lambda: 1)()",
        "x.__class__",
    ],
)
def test_unsafe_rejected(expr):
    with pytest.raises(Exception):
        f = ExpressionFunction(expr)
        f(x=1)


def test_serialization():
    f = ExpressionFunction("a + b")
    f2 = from_repr(simple_repr(f))
    assert f2(a=1, b=2) == 3
    g = f.partial(b=5)
    g2 = from_repr(simple_repr(g))
    assert g2(a=1) == 6
