"""Batched level-synchronous DPOP sweep engine vs the per-node path.

The sweep engine (ops/dpop_sweep.py) must produce exactly the same
optimal cost as the per-node hybrid path and brute force on any instance
it accepts — and must refuse (None) instances whose padded form blows up.
"""
import itertools

import numpy as np
import pytest

from pydcop_tpu.algorithms.dpop import DpopSolver
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.graph import pseudotree
from pydcop_tpu.ops.dpop_sweep import compile_sweep, run_sweep


def random_dcop(n_vars, n_edges, dom_sizes=(2, 3), seed=0, objective="min",
                tree_only=False):
    rng = np.random.default_rng(seed)
    dcop = DCOP("rand", objective=objective)
    doms = {
        d: Domain(f"d{d}", "vals", list(range(d))) for d in dom_sizes
    }
    vs = []
    for i in range(n_vars):
        d = doms[dom_sizes[i % len(dom_sizes)]]
        v = Variable(f"v{i}", d)
        vs.append(v)
        dcop.add_variable(v)
    edges = set()
    for i in range(1, n_vars):
        j = int(rng.integers(0, i))  # random tree backbone
        edges.add((j, i))
    if not tree_only:
        for _ in range(n_edges):
            i, j = rng.integers(0, n_vars, 2)
            if i != j:
                edges.add((min(i, j), max(i, j)))
    for k, (i, j) in enumerate(sorted(edges)):
        m = rng.integers(0, 10, (len(vs[i].domain), len(vs[j].domain)))
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[i], vs[j]], m.astype(float), name=f"c{k}"
            )
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


def brute_force_cost(dcop):
    names = sorted(dcop.variables)
    domains = [list(dcop.variables[n].domain) for n in names]
    best = float("inf") if dcop.objective == "min" else -float("inf")
    for combo in itertools.product(*domains):
        _, cost = dcop.solution_cost(dict(zip(names, combo)), 10000000)
        best = min(best, cost) if dcop.objective == "min" else max(best, cost)
    return best


@pytest.mark.parametrize("seed", range(5))
def test_sweep_matches_brute_force(seed):
    dcop = random_dcop(8, 4, seed=seed)
    solver = DpopSolver(dcop)
    res = solver.run()
    assert solver.last_engine == "sweep"
    assert res.cost == pytest.approx(brute_force_cost(dcop))


@pytest.mark.parametrize("seed", range(3))
def test_sweep_matches_pernode_engine(seed):
    dcop = random_dcop(20, 8, seed=seed)
    tree = pseudotree.build_computation_graph(dcop)
    s1 = DpopSolver(dcop, tree)
    r1 = s1._run_pernode()
    s2 = DpopSolver(dcop, tree)
    plan = compile_sweep(tree, dcop, dcop.objective)
    assert plan is not None
    r2 = s2._run_sweep(plan)
    assert r2.cost == pytest.approx(r1.cost)
    assert r2.msg_count == r1.msg_count
    assert r2.msg_size == pytest.approx(r1.msg_size)


def test_sweep_max_mode():
    dcop = random_dcop(7, 3, seed=11, objective="max")
    solver = DpopSolver(dcop)
    res = solver.run()
    assert solver.last_engine == "sweep"
    assert res.cost == pytest.approx(brute_force_cost(dcop))


def test_sweep_pure_tree_width_one():
    dcop = random_dcop(30, 0, seed=3, tree_only=True)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assert plan is not None
    assert plan.W == 1  # tree: every separator is just the parent
    solver = DpopSolver(dcop, tree)
    res = solver._run_sweep(plan)
    # 30 vars is beyond brute force; the per-node engine is the oracle
    ref = DpopSolver(dcop, tree)._run_pernode()
    assert res.cost == pytest.approx(ref.cost)


def test_sweep_forest_and_isolated():
    # two disconnected components + an isolated variable
    dcop = DCOP("forest", objective="min")
    d = Domain("d", "vals", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(5)]
    for v in vs:
        dcop.add_variable(v)
    m = np.array([[0, 5, 5], [5, 0, 5], [5, 5, 1.0]])
    dcop.add_constraint(NAryMatrixRelation([vs[0], vs[1]], m, name="c0"))
    dcop.add_constraint(NAryMatrixRelation([vs[2], vs[3]], m, name="c1"))
    # v4 isolated: no constraints at all
    dcop.add_agents([AgentDef("a0")])
    solver = DpopSolver(dcop)
    res = solver.run()
    assert res.cost == pytest.approx(brute_force_cost(dcop))
    assert set(res.assignment) == {f"v{i}" for i in range(5)}


def test_sweep_ternary_constraint():
    dcop = DCOP("tern", objective="min")
    d = Domain("d", "vals", [0, 1])
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    rng = np.random.default_rng(5)
    t = rng.integers(0, 9, (2, 2, 2)).astype(float)
    dcop.add_constraint(
        NAryMatrixRelation([vs[0], vs[1], vs[2]], t, name="c3")
    )
    m = rng.integers(0, 9, (2, 2)).astype(float)
    dcop.add_constraint(NAryMatrixRelation([vs[2], vs[3]], m, name="c2"))
    dcop.add_agents([AgentDef("a0")])
    solver = DpopSolver(dcop)
    res = solver.run()
    assert solver.last_engine == "sweep"
    assert res.cost == pytest.approx(brute_force_cost(dcop))


def test_sweep_refuses_width_blowup(monkeypatch):
    import pydcop_tpu.ops.dpop_sweep as ds

    monkeypatch.setattr(ds, "MAX_TABLE_ENTRIES_PER_NODE", 4)
    dcop = random_dcop(10, 10, seed=1)
    tree = pseudotree.build_computation_graph(dcop)
    assert compile_sweep(tree, dcop, "min") is None
    # solver still solves exactly via the per-node fallback
    solver = DpopSolver(dcop, tree)
    res = solver.run()
    assert solver.last_engine == "pernode"
    assert res.cost == pytest.approx(brute_force_cost(dcop))


def test_run_sweep_direct_assignment_indices():
    dcop = random_dcop(6, 2, seed=7)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assign_idx, n = run_sweep(plan)
    assert n == 6
    assignment = {
        name: tree.computation(name).variable.domain[int(assign_idx[g])]
        for g, name in enumerate(plan.gid_to_name)
    }
    _, cost = dcop.solution_cost(assignment, 10000000)
    assert cost == pytest.approx(brute_force_cost(dcop))


class TestPerLevelTier:
    """The per-level engine (each level padded to its own separator
    width) must agree with the per-node oracle and engage exactly when
    the global plan refuses but per-level budgets fit."""

    def test_perlevel_matches_pernode(self):
        from pydcop_tpu.ops.dpop_sweep import (
            compile_sweep_perlevel,
            run_sweep_perlevel,
        )

        for seed in range(4):
            dcop = random_dcop(15, 6, seed=seed)
            tree = pseudotree.build_computation_graph(dcop)
            plan = compile_sweep_perlevel(tree, dcop, dcop.objective)
            assert plan is not None
            assign_idx, n = run_sweep_perlevel(plan)
            assignment = {
                name: tree.computation(name).variable.domain[
                    int(assign_idx[g])
                ]
                for g, name in enumerate(plan.gid_to_name)
            }
            _, cost = dcop.solution_cost(assignment, 10000000)
            ref = DpopSolver(dcop, tree)._run_pernode()
            assert cost == pytest.approx(ref.cost), seed

    def test_engages_when_global_refuses(self, monkeypatch):
        """A single wide hub blows the global-width padding; the
        per-level tier isolates the cost to the hub's level."""
        import pydcop_tpu.ops.dpop_sweep as ds

        # mostly width-1 chain with one dense clique near the root:
        # depresses the global budget without making any level huge
        rng = np.random.default_rng(6)
        dcop = DCOP("hub", objective="min")
        d = Domain("d", "vals", list(range(4)))
        vs = [Variable(f"v{i:02d}", d) for i in range(24)]
        for v in vs:
            dcop.add_variable(v)
        k = 0
        # clique over v0..v3 -> separator width ~3 at the clique's level
        for i in range(4):
            for j in range(i + 1, 4):
                m = rng.integers(0, 9, (4, 4)).astype(float)
                dcop.add_constraint(
                    NAryMatrixRelation([vs[i], vs[j]], m, name=f"q{k}")
                )
                k += 1
        # long chains hanging off v3
        for i in range(4, 24):
            p = vs[i - 1] if i > 4 else vs[3]
            m = rng.integers(0, 9, (4, 4)).astype(float)
            dcop.add_constraint(
                NAryMatrixRelation([p, vs[i]], m, name=f"c{i}")
            )
        dcop.add_agents([AgentDef("a0")])
        tree = pseudotree.build_computation_graph(dcop)

        global_plan = ds.compile_sweep(tree, dcop, "min")
        assert global_plan is not None
        # shrink the total-entry budget to just below the global plan's
        # need: global refuses, per-level (much smaller) fits
        perlevel_plan = ds.compile_sweep_perlevel(tree, dcop, "min")
        assert perlevel_plan is not None
        assert perlevel_plan.total_entries < global_plan.total_entries
        monkeypatch.setattr(
            ds, "MAX_PLAN_ENTRIES", global_plan.total_entries - 1
        )
        assert ds.compile_sweep(tree, dcop, "min") is None
        assert ds.compile_sweep_perlevel(tree, dcop, "min") is not None

        solver = DpopSolver(dcop, tree)
        res = solver.run()
        assert solver.last_engine == "sweep_perlevel"
        ref = DpopSolver(dcop, tree)._run_pernode()
        assert res.cost == pytest.approx(ref.cost)

    def test_perlevel_mixed_domains_and_max_mode(self):
        from pydcop_tpu.ops.dpop_sweep import (
            compile_sweep_perlevel,
            run_sweep_perlevel,
        )

        dcop = random_dcop(10, 4, seed=9, objective="max")
        tree = pseudotree.build_computation_graph(dcop)
        plan = compile_sweep_perlevel(tree, dcop, "max")
        assert plan is not None
        assign_idx, _ = run_sweep_perlevel(plan)
        assignment = {
            name: tree.computation(name).variable.domain[
                int(assign_idx[g])
            ]
            for g, name in enumerate(plan.gid_to_name)
        }
        _, cost = dcop.solution_cost(assignment, 10000000)
        assert cost == pytest.approx(brute_force_cost(dcop))


def test_batched_sweep_matches_single():
    """make_batched_sweep_fn with B stacked cost tables reproduces each
    single sweep (vmapped semantics; same-topology batch)."""
    import jax.numpy as jnp

    from pydcop_tpu.ops.dpop_sweep import (
        make_batched_sweep_fn,
        make_sweep_fn,
    )

    dcop = random_dcop(40, 0, dom_sizes=(3,), seed=5, tree_only=True)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assert plan is not None

    B = 4
    # per-instance perturbation so the B solutions genuinely differ
    rng = np.random.default_rng(5)
    pert = rng.uniform(0, 5, (B,) + plan.local.shape).astype(np.float32)
    local_b = jnp.asarray(plan.local[None] + pert)

    bfn, bargs = make_batched_sweep_fn(plan)
    got = np.asarray(bfn(local_b, *bargs))

    sfn, sargs = make_sweep_fn(plan)
    for b in range(B):
        single = np.asarray(sfn(local_b[b], *sargs[1:]))
        np.testing.assert_array_equal(got[b], single)
