"""Unit tests for the problem model layer (dcop/objects, relations, dcop)."""
import os

import numpy as np
import pytest

from pydcop_tpu.dcop import (
    DCOP,
    AgentDef,
    AsNAryFunctionRelation,
    BinaryVariable,
    Domain,
    ExternalVariable,
    NAryFunctionRelation,
    NAryMatrixRelation,
    UnaryFunctionRelation,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    assignment_cost,
    constraint_from_str,
    create_agents,
    create_variables,
    dcop_yaml,
    find_arg_optimal,
    find_optimum,
    join,
    load_dcop,
    load_dcop_from_file,
    projection,
)
from pydcop_tpu.utils import ExpressionFunction, from_repr, simple_repr

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def d3():
    return Domain("d3", "test", [0, 1, 2])


class TestDomain:
    def test_basic(self, d3):
        assert len(d3) == 3
        assert d3.index(1) == 1
        assert d3[2] == 2
        assert 0 in d3 and 5 not in d3
        assert list(d3) == [0, 1, 2]

    def test_to_domain_value(self):
        d = Domain("c", "color", ["R", "G"])
        assert d.to_domain_value("G") == "G"
        di = Domain("n", "int", [1, 2, 3])
        assert di.to_domain_value("2") == 2

    def test_serialization(self, d3):
        r = simple_repr(d3)
        assert from_repr(r) == d3


class TestVariables:
    def test_variable(self, d3):
        v = Variable("v1", d3, initial_value=1)
        assert v.initial_value == 1
        assert v.cost_for_val(2) == 0
        assert not v.has_cost

    def test_bad_initial_value(self, d3):
        with pytest.raises(ValueError):
            Variable("v1", d3, initial_value=7)

    def test_cost_dict(self, d3):
        v = VariableWithCostDict("v1", d3, {0: 1.5, 2: -1.0})
        assert v.cost_for_val(0) == 1.5
        assert v.cost_for_val(1) == 0
        np.testing.assert_allclose(v.cost_vector(), [1.5, 0, -1.0])

    def test_cost_func(self, d3):
        v = VariableWithCostFunc("v1", d3, ExpressionFunction("v1 * 2"))
        assert v.cost_for_val(2) == 4
        assert v.has_cost

    def test_cost_func_wrong_var(self, d3):
        with pytest.raises(ValueError):
            VariableWithCostFunc("v1", d3, ExpressionFunction("other * 2"))

    def test_noisy_cost_deterministic(self, d3):
        v1 = VariableNoisyCostFunc("v1", d3, ExpressionFunction("v1 * 2"),
                                   noise_level=0.1)
        v2 = VariableNoisyCostFunc("v1", d3, ExpressionFunction("v1 * 2"),
                                   noise_level=0.1)
        assert v1.cost_for_val(1) == v2.cost_for_val(1)
        assert 2 <= v1.cost_for_val(1) <= 2.1

    def test_binary(self):
        b = BinaryVariable("b1")
        assert list(b.domain) == [0, 1]

    def test_external(self, d3):
        seen = []
        ev = ExternalVariable("e1", d3, 0)
        ev.subscribe(seen.append)
        ev.value = 2
        assert ev.value == 2 and seen == [2]
        with pytest.raises(ValueError):
            ev.value = 9

    def test_create_variables(self, d3):
        vs = create_variables("x_", ["a", "b"], d3)
        assert set(vs) == {"x_a", "x_b"}
        vs2 = create_variables("m", (["1", "2"], ["a"]), d3)
        assert vs2[("1", "a")].name == "m1_a"


class TestAgentDef:
    def test_costs_routes(self):
        a = AgentDef("a1", capacity=50, default_hosting_cost=2,
                     hosting_costs={"c1": 7}, default_route=3,
                     routes={"a2": 1})
        assert a.hosting_cost("c1") == 7
        assert a.hosting_cost("cX") == 2
        assert a.route("a1") == 0
        assert a.route("a2") == 1
        assert a.route("a9") == 3

    def test_extra_attrs(self):
        a = AgentDef("a1", preference="high")
        assert a.preference == "high"
        with pytest.raises(AttributeError):
            _ = a.nope

    def test_create_agents(self):
        agts = create_agents("a", range(3), capacity=10)
        assert set(agts) == {"a0", "a1", "a2"}
        assert agts["a1"].capacity == 10

    def test_serialization(self):
        a = AgentDef("a1", capacity=11, hosting_costs={"c": 3}, routes={"a2": 5})
        a2 = from_repr(simple_repr(a))
        assert a2 == a


class TestRelations:
    def test_matrix_relation(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        m = np.arange(9).reshape(3, 3)
        r = NAryMatrixRelation([x, y], m, "r")
        assert r(x=1, y=2) == 5
        assert r.get_value_for_assignment({"x": 2, "y": 0}) == 6
        assert r.get_value_for_assignment([2, 0]) == 6
        assert r.arity == 2 and r.shape == (3, 3)

    def test_slice(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryMatrixRelation([x, y], np.arange(9).reshape(3, 3), "r")
        s = r.slice({"x": 1})
        assert s.arity == 1
        assert s(y=0) == 3

    def test_set_value(self, d3):
        x = Variable("x", d3)
        r = NAryMatrixRelation([x], name="r")
        r2 = r.set_value_for_assignment({"x": 1}, 5)
        assert r(x=1) == 0 and r2(x=1) == 5

    def test_function_relation(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryFunctionRelation(lambda a, b: a * 10 + b, [x, y], "r")
        assert r(2, 1) == 21
        t = r.to_tensor()
        assert t.shape == (3, 3) and t[2, 1] == 21

    def test_decorator(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)

        @AsNAryFunctionRelation(x, y)
        def my_rel(x, y):
            return x + y

        assert my_rel.name == "my_rel"
        assert my_rel(1, 2) == 3

    def test_unary(self, d3):
        x = Variable("x", d3)
        r = UnaryFunctionRelation("r", x, lambda v: v * 3)
        assert r(2) == 6
        vals, opt = find_arg_optimal(x, r, "min")
        assert vals == [0] and opt == 0

    def test_constraint_from_str(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        c = constraint_from_str("c", "1 if x == y else 0", [x, y])
        assert c(1, 1) == 1 and c(0, 1) == 0
        assert set(c.scope_names) == {"x", "y"}

    def test_find_optimum(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryMatrixRelation([x, y], np.arange(9).reshape(3, 3) - 4, "r")
        assert find_optimum(r, "min") == -4
        assert find_optimum(r, "max") == 4

    def test_join(self, d3):
        x, y, z = Variable("x", d3), Variable("y", d3), Variable("z", d3)
        r1 = NAryMatrixRelation([x, y], np.arange(9).reshape(3, 3), "r1")
        r2 = NAryMatrixRelation([y, z], 10 * np.arange(9).reshape(3, 3), "r2")
        j = join(r1, r2)
        assert [v.name for v in j.dimensions] == ["x", "y", "z"]
        for xa in range(3):
            for ya in range(3):
                for za in range(3):
                    assert j(x=xa, y=ya, z=za) == r1(xa, ya) + r2(ya, za)

    def test_join_same_dims(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r1 = NAryMatrixRelation([x, y], np.ones((3, 3)), "r1")
        r2 = NAryMatrixRelation([y, x], np.arange(9).reshape(3, 3), "r2")
        j = join(r1, r2)
        assert j.arity == 2
        assert j(x=0, y=2) == 1 + r2(2, 0)

    def test_projection(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryMatrixRelation([x, y], [[5, 1, 7], [2, 8, 0], [9, 9, 9]], "r")
        p = projection(r, y, "min")
        assert p(x=0) == 1 and p(x=1) == 0 and p(x=2) == 9
        pm = projection(r, x, "max")
        assert pm(y=0) == 9

    def test_assignment_cost(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        c1 = constraint_from_str("c1", "x + y", [x, y])
        assert assignment_cost({"x": 1, "y": 2}, [c1]) == 3

    def test_matrix_serialization(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryMatrixRelation([x, y], np.arange(9).reshape(3, 3), "r")
        r2 = from_repr(simple_repr(r))
        assert r2 == r


class TestDCOP:
    def test_container(self, d3):
        dcop = DCOP("t")
        x, y = Variable("x", d3), Variable("y", d3)
        dcop.add_constraint(constraint_from_str("c", "x + y", [x, y]))
        assert set(dcop.variables) == {"x", "y"}
        assert dcop.domains["d3"] == d3
        assert len(dcop.constraints_for_variable("x")) == 1

    def test_solution_cost_with_violation(self, d3):
        dcop = DCOP("t")
        x, y = Variable("x", d3), Variable("y", d3)
        dcop.add_constraint(
            constraint_from_str("c", "10000 if x == y else x + y", [x, y])
        )
        assert dcop.solution_cost({"x": 1, "y": 1}, 10000) == (1, 0)
        assert dcop.solution_cost({"x": 1, "y": 2}, 10000) == (0, 3)

    def test_variable_costs_in_solution_cost(self, d3):
        dcop = DCOP("t")
        x = VariableWithCostDict("x", d3, {0: 0.5, 1: 0, 2: 0})
        y = Variable("y", d3)
        dcop.add_variable(x)
        dcop.add_constraint(constraint_from_str("c", "x + y", [x, y]))
        violations, cost = dcop.solution_cost({"x": 0, "y": 1}, 10000)
        assert violations == 0 and cost == 1.5

    def test_merge(self, d3):
        a, b = DCOP("a"), DCOP("b")
        x, y = Variable("x", d3), Variable("y", d3)
        a.add_constraint(constraint_from_str("c1", "x * 2", [x]))
        b.add_constraint(constraint_from_str("c2", "y * 3", [y]))
        m = a + b
        assert set(m.constraints) == {"c1", "c2"}
        assert set(m.variables) == {"x", "y"}


class TestYaml:
    def test_load_tuto(self):
        dcop = load_dcop_from_file(
            os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
        )
        assert dcop.objective == "min"
        assert set(dcop.variables) == {"v1", "v2", "v3", "v4"}
        assert len(dcop.constraints) == 4
        assert len(dcop.agents) == 5
        assert dcop.agents["a1"].capacity == 100
        # known optimum
        assert dcop.solution_cost(
            {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}, 10000
        ) == (0, 12)
        c = dcop.constraints["c_2_3"]
        assert c(**{"v2": "G", "v3": "R"}) == 3  # from 'G R | G G' grouping

    def test_load_intention(self):
        dcop = load_dcop_from_file(
            os.path.join(INSTANCES, "coloring_intention.yaml")
        )
        assert dcop.variables["v1"].has_cost
        assert dcop.variables["v1"].cost_for_val("R") == pytest.approx(-0.1)
        assert dcop.dist_hints is not None
        assert dcop.dist_hints.must_host("a1") == ["v1"]
        violations, cost = dcop.solution_cost(
            {"v1": "R", "v2": "G", "v3": "R"}, 10000
        )
        assert violations == 0
        assert cost == pytest.approx(-0.1 - 0.1 + 0.1)

    def test_load_range_domain(self):
        dcop = load_dcop(
            """
name: r
domains:
  ten:
    values: [0 .. 9]
variables:
  v1: {domain: ten}
constraints:
  c1: {type: intention, function: v1 * 2}
agents: [a1]
"""
        )
        assert len(dcop.domains["ten"]) == 10
        assert dcop.constraints["c1"](5) == 10

    def test_load_agents_routes_hosting(self):
        dcop = load_dcop(
            """
name: r
domains: {d: {values: [0, 1]}}
variables: {v1: {domain: d}}
constraints: {c1: {type: intention, function: v1}}
agents:
  a1: {capacity: 10}
  a2: {capacity: 20}
routes:
  default: 5
  a1: {a2: 2}
hosting_costs:
  default: 7
  a1:
    default: 3
    computations: {v1: 1}
"""
        )
        a1, a2 = dcop.agents["a1"], dcop.agents["a2"]
        assert a1.route("a2") == 2 and a2.route("a1") == 2
        assert a1.route("aX") == 5
        assert a1.hosting_cost("v1") == 1
        assert a1.hosting_cost("other") == 3
        assert a2.hosting_cost("v1") == 7

    def test_roundtrip(self):
        dcop = load_dcop_from_file(
            os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
        )
        dumped = dcop_yaml(dcop)
        dcop2 = load_dcop(dumped)
        assert set(dcop2.variables) == set(dcop.variables)
        assert set(dcop2.constraints) == set(dcop.constraints)
        for a in ("G", "R"):
            asst = {v: a for v in dcop.variables}
            assert dcop2.solution_cost(asst, 10000) == dcop.solution_cost(
                asst, 10000
            )

    def test_external_variables(self):
        dcop = load_dcop(
            """
name: r
domains: {d: {values: [0, 1]}}
variables: {v1: {domain: d}}
external_variables:
  e1: {domain: d, initial_value: 1}
constraints:
  c1: {type: intention, function: v1 + e1}
agents: [a1]
"""
        )
        assert dcop.external_variables["e1"].value == 1
        # external variable value is injected into solution_cost
        assert dcop.solution_cost({"v1": 1}, 10000) == (0, 2)


@pytest.mark.skipif(
    not os.path.isdir("/root/reference/tests/instances"),
    reason="reference instances not mounted",
)
class TestReferenceInstanceParity:
    """Load every instance file shipped with the reference (format parity)."""

    def test_load_all_reference_instances(self):
        import glob

        files = glob.glob("/root/reference/tests/instances/*.y*ml")
        assert files
        for fn in files:
            dcop = load_dcop_from_file(fn)
            assert dcop.variables or dcop.external_variables, fn

    def test_reference_tuto_optimum(self):
        dcop = load_dcop_from_file(
            "/root/reference/tests/instances/graph_coloring_tuto.yaml"
        )
        assert dcop.solution_cost(
            {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}, 10000
        ) == (0, 12)
