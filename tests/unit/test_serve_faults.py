"""Fault isolation + overload control of the solve service (ISSUE 7).

Contracts pinned here:

* **chaos matrix** (acceptance pin): for each serve fault kind
  (``raise_in_step``, ``nan_lane``, ``torn_journal_write``,
  ``stall_tick``) injected via a seeded FaultPlan, the service
  completes every healthy job bit-identically to a fault-free run, the
  poison job ends in a terminal ``ERROR`` (never a hang), and the
  matching quarantine/shed/restart counters are nonzero;
* **quarantine**: a bucket whose step throws is bisected into isolated
  suspect groups; a transient fault (no target jid) is absorbed with
  every job still completing correctly;
* **supervisor**: a tick-loop failure is relaunched with backoff
  (``scheduler_restarts``); a dead scheduler fails pending ``result()``
  / ``wait_all()`` calls with :class:`ServiceStopped` — never a hang;
* **admission control**: bounded pending queue (priority-aware
  shedding with a structured, retry-after-carrying rejection),
  per-tenant quotas, deadline-infeasibility rejection at submit;
* **journal hygiene**: done-job compaction (atomic rewrite, on resume
  and at a size threshold) and torn-line tolerance (truncated final
  ``jobs.jsonl`` line, half-written ``JID:`` line — skipped + counted,
  not a crash);
* **lossy streams**: slow-consumer event drops are counted with one
  ``serve.stream.lossy`` notice per job.

Like test_serve_service.py, tests drive :meth:`SolveService.tick`
synchronously where determinism matters; supervisor tests run the real
scheduler thread.
"""
import json
import os
import queue

import pytest

from pydcop_tpu.batch.cache import CompileCache
from pydcop_tpu.batch.engine import BatchItem, adapter_for
from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.faults import (
    Fault,
    FaultPlan,
    ServeFaultInjector,
)
from pydcop_tpu.runtime.stats import ServeCounters
from pydcop_tpu.serve import (
    DeadlineInfeasible,
    ServeJob,
    ServiceOverloaded,
    ServiceStopped,
    SolveService,
)

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")

LIMIT = 63  # multiple of the harness chunk (7), see test_serve_service


def _load():
    return load_dcop_from_file([TUTO])


def _standalone(dcop, algo, seed, params=None):
    spec = adapter_for(algo).build_spec(
        BatchItem(dcop, algo, algo_params=params, seed=seed)
    )
    return spec.solver.run(max_cycles=LIMIT)


def _drain(svc, max_ticks=300):
    for _ in range(max_ticks):
        if not svc.tick() and all(
            j.done.is_set() for j in svc._jobs.values()
        ):
            return
    raise AssertionError("service did not drain")


def _svc(**kw):
    """A deterministic sync-driven service: zero quarantine backoff so
    tick-driven tests never wait on wall-clock gates."""
    kw.setdefault("lanes", 2)
    kw.setdefault("cache", CompileCache())
    kw.setdefault("max_cycles", LIMIT)
    kw.setdefault("backoff_base", 0.0)
    return SolveService(**kw)


# ---------------------------------------------------------------------------
# the chaos matrix (acceptance pin)
# ---------------------------------------------------------------------------

#: per-kind scenario: the algorithm, the fault spec (jid-targeted =
#: persistent poison), and the counter that must be nonzero afterwards
MATRIX = {
    "raise_in_step": dict(
        algo="mgm",
        fault=dict(kind="raise_in_step", jid="job-000002", cycle=2),
        counter="jobs_quarantined",
        poison="job-000002",
    ),
    "nan_lane": dict(
        algo="maxsum",  # float state: the device-side finiteness check
        fault=dict(kind="nan_lane", jid="job-000002", cycle=2),
        counter="lanes_nan",
        poison="job-000002",
    ),
    "torn_journal_write": dict(
        algo="mgm",
        fault=dict(kind="torn_journal_write", jid="job-000002"),
        counter="faults_injected",
        poison=None,  # journal damage, not a poison job
    ),
    "stall_tick": dict(
        algo="mgm",
        fault=dict(kind="stall_tick", duration=0.02, cycle=1),
        counter="ticks_stalled",
        poison=None,
    ),
}


class TestChaosMatrix:
    @pytest.mark.parametrize("kind", sorted(MATRIX))
    def test_injected_fault_is_contained(self, kind, tmp_path):
        cfg = MATRIX[kind]
        plan = FaultPlan(faults=[Fault(**cfg["fault"])], seed=7)
        needs_journal = kind == "torn_journal_write"
        jd = str(tmp_path / "journal") if needs_journal else None
        svc = _svc(fault_plan=plan, journal_dir=jd)
        dcop = _load()
        a = svc.submit(dcop, cfg["algo"], seed=0,
                       source_file=TUTO if needs_journal else None)
        b = svc.submit(dcop, cfg["algo"], seed=1,
                       source_file=TUTO if needs_journal else None)
        assert (a, b) == ("job-000001", "job-000002")
        _drain(svc)  # bounded: a hang fails here, never blocks CI

        poison = cfg["poison"]
        for jid, seed in ((a, 0), (b, 1)):
            res = svc.result(jid, timeout=1)
            if jid == poison:
                # the poison job ends terminal, isolated to itself
                assert res.status == "ERROR", (kind, res.status)
                continue
            # every healthy job is bit-identical to a fault-free run
            seq = _standalone(dcop, cfg["algo"], seed)
            assert res.status == seq.status, (kind, jid)
            assert res.assignment == seq.assignment, (kind, jid)
            assert res.cycle == seq.cycle, (kind, jid)
            assert res.cost == seq.cost, (kind, jid)
        assert svc.counters.counts[cfg["counter"]] > 0, kind
        assert svc.counters.counts["faults_injected"] > 0, kind

    def test_torn_write_is_skipped_and_counted_on_resume(self, tmp_path):
        """The torn_journal_write leg's second half: the journal the
        fault damaged must resume cleanly — the torn record skipped and
        counted, the rest of the session intact."""
        cfg = MATRIX["torn_journal_write"]
        plan = FaultPlan(faults=[Fault(**cfg["fault"])], seed=7)
        jd = str(tmp_path / "journal")
        svc = _svc(fault_plan=plan, journal_dir=jd,
                   journal_compact_bytes=1 << 30)  # keep records
        dcop = _load()
        svc.submit(dcop, "mgm", seed=0, source_file=TUTO)
        svc.submit(dcop, "mgm", seed=1, source_file=TUTO)
        svc.tick()  # both journaled (B's record torn), work started
        del svc  # crash

        svc2 = _svc(journal_dir=jd)
        n = svc2.resume()
        # A's complete record resumes; B's torn record is skipped
        assert n == 1
        assert svc2.counters.counts["torn_journal_lines"] >= 1
        _drain(svc2)
        res = svc2.result("job-000001", timeout=1)
        seq = _standalone(dcop, "mgm", 0)
        assert res.assignment == seq.assignment


# ---------------------------------------------------------------------------
# quarantine mechanics
# ---------------------------------------------------------------------------

class TestQuarantine:
    def test_transient_step_failure_absorbed(self):
        """A raise_in_step WITHOUT a jid is a one-shot glitch: the
        bucket is bisected, every job re-runs in isolation and
        completes bit-identically — nothing ends in ERROR."""
        plan = FaultPlan(
            faults=[Fault(kind="raise_in_step", cycle=2)], seed=3
        )
        svc = _svc(fault_plan=plan)
        dcop = _load()
        jids = [svc.submit(dcop, "mgm", seed=s) for s in range(2)]
        _drain(svc)
        assert svc.counters.counts["buckets_failed"] >= 1
        for jid, seed in zip(jids, range(2)):
            res = svc.result(jid, timeout=1)
            seq = _standalone(dcop, "mgm", seed)
            assert res.status == "FINISHED"
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle

    def test_bisect_isolates_suspect_groups(self):
        """After a bucket failure the requeued jobs carry isolation
        tags, so suspects re-run in their own buckets instead of
        re-contaminating shared ones."""
        plan = FaultPlan(
            faults=[Fault(kind="raise_in_step", cycle=2)], seed=3
        )
        svc = _svc(fault_plan=plan)
        dcop = _load()
        jids = [svc.submit(dcop, "mgm", seed=s) for s in range(2)]
        svc.tick()  # admit into ONE shared bucket
        assert svc.counters.counts["buckets_opened"] == 1
        svc.tick()  # the step throws: bisect
        keys = {svc._jobs[j].isolate_key for j in jids}
        assert None not in keys
        assert len(keys) == 2  # two distinct isolation groups
        _drain(svc)
        # each group opened its own bucket afterwards
        assert svc.counters.counts["buckets_opened"] >= 3

    def test_poison_ladder_retries_then_escalates(self):
        """The cornered singleton consumes its retry budget with
        backoff, then the sequential-fallback escalation ends it in a
        terminal ERROR (the injected poison is persistent)."""
        plan = FaultPlan(
            faults=[Fault(kind="raise_in_step", jid="job-000001")],
            seed=3,
        )
        svc = _svc(lanes=1, fault_plan=plan, max_job_retries=2)
        jid = svc.submit(_load(), "mgm", seed=0)
        _drain(svc)
        res = svc.result(jid, timeout=1)
        assert res.status == "ERROR"
        assert svc.counters.counts["jobs_retried"] == 2
        assert svc.counters.counts["jobs_quarantined"] == 1
        assert svc.counters.counts["buckets_failed"] >= 3

    def test_engine_freezes_nonfinite_lane(self):
        """The batch engine twin of the lane check: a NaN-poisoned
        instance is frozen ERROR at the chunk boundary (and released
        through the on_lane_release hook) while its bucket-mate solves
        to the standalone result."""
        import numpy as np

        import pydcop_tpu.batch.engine as eng_mod
        from pydcop_tpu.batch.engine import BatchEngine

        dcop = _load()
        engine = BatchEngine(cache=CompileCache())
        released = []

        # poison instance 1's initial maxsum messages so its float
        # state is non-finite from the first chunk; instance 0 healthy
        orig = eng_mod._adapter_for

        def fake_adapter(algo):
            a = orig(algo)
            real_init = a.initial_state
            calls = {"n": 0}

            def init(spec, target):
                st = real_init(spec, target)
                calls["n"] += 1
                if calls["n"] == 2:  # the second instance of the bucket
                    q, r, v = st
                    st = (np.full_like(q, np.nan), r, v)
                return st

            a.initial_state = init
            return a

        eng_mod._adapter_for = fake_adapter
        try:
            results = engine.solve(
                [BatchItem(dcop, "maxsum", seed=0),
                 BatchItem(dcop, "maxsum", seed=1)],
                max_cycles=LIMIT,
                on_lane_release=lambda i, c, s: released.append(i),
            )
        finally:
            eng_mod._adapter_for = orig
        assert engine.counters.counts["lanes_nonfinite"] == 1
        assert results[1].status == "ERROR"
        assert 1 in released  # the poisoned lane was released too
        seq = _standalone(dcop, "maxsum", 0)
        assert results[0].status == seq.status
        assert results[0].assignment == seq.assignment
        assert results[0].cycle == seq.cycle


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_transient_tick_failure_restarts_with_backoff(self):
        svc = _svc()
        calls = {"n": 0}
        orig = svc.tick

        def flaky():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("transient scheduler glitch")
            return orig()

        svc.tick = flaky
        svc.start()
        try:
            jid = svc.submit(_load(), "mgm", seed=0)
            res = svc.result(jid, timeout=60)
        finally:
            svc.stop(drain=False)
        assert res.status == "FINISHED"
        assert svc.counters.counts["scheduler_restarts"] == 2

    def test_dead_scheduler_raises_service_stopped(self):
        svc = _svc(max_scheduler_restarts=1)

        def always_raise():
            raise RuntimeError("scheduler is toast")

        svc.tick = always_raise
        jid = svc.submit(_load(), "mgm", seed=0)
        svc.start()
        try:
            with pytest.raises(ServiceStopped):
                svc.result(jid, timeout=30)
            # the job was failed terminally, not left hanging — so
            # wait_all returns instead of blocking forever
            assert svc._jobs[jid].done.is_set()
            assert svc.wait_all(timeout=10) is True
            with pytest.raises(ServiceStopped):
                svc.submit(_load(), "mgm", seed=1)
        finally:
            svc.stop(drain=False)
        assert svc.counters.counts["scheduler_restarts"] == 1

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    )
    def test_silently_dead_thread_detected(self):
        """A thread that dies without the supervisor recording a
        failure (SystemExit kills it outright) is still detected by
        result()'s liveness polling — never a hang."""
        svc = _svc()

        def die():
            raise SystemExit

        svc.tick = die
        jid = svc.submit(_load(), "mgm", seed=0)
        svc.start()
        with pytest.raises(ServiceStopped):
            svc.result(jid, timeout=30)

    def test_result_after_abandoning_stop_raises(self):
        svc = _svc()
        svc.tick = lambda: False  # a scheduler that never does work
        jid = svc.submit(_load(), "mgm", seed=0)
        svc.start()
        svc.stop(drain=False)
        with pytest.raises(ServiceStopped):
            svc.result(jid, timeout=5)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

class TestAdmissionControl:
    def test_max_pending_rejects_with_retry_after(self):
        svc = _svc(lanes=1, max_pending=1)
        dcop = _load()
        svc.submit(dcop, "mgm", seed=0)
        with pytest.raises(ServiceOverloaded) as ei:
            svc.submit(dcop, "mgm", seed=1)
        assert ei.value.retry_after > 0
        d = ei.value.to_dict()
        assert d["error"] == "overloaded"
        assert "queue" in d["reason"]
        assert svc.counters.counts["jobs_shed"] == 1
        _drain(svc)  # the accepted job is unaffected

    def test_higher_priority_arrival_sheds_lowest_pending(self):
        svc = _svc(lanes=1, max_pending=1)
        dcop = _load()
        lo = svc.submit(dcop, "mgm", seed=0, priority=0)
        hi = svc.submit(dcop, "mgm", seed=1, priority=5)
        # the low-priority job was displaced: already terminal, ERROR
        res_lo = svc.result(lo, timeout=1)
        assert res_lo.status == "ERROR"
        assert svc.counters.counts["jobs_shed"] == 1
        _drain(svc)
        res_hi = svc.result(hi, timeout=1)
        seq = _standalone(dcop, "mgm", 1)
        assert res_hi.status == "FINISHED"
        assert res_hi.assignment == seq.assignment

    def test_tenant_quota_rejections(self):
        svc = _svc(tenant_quota=1)
        dcop = _load()
        a = svc.submit(dcop, "mgm", seed=0, tenant="t1")
        with pytest.raises(ServiceOverloaded) as ei:
            svc.submit(dcop, "mgm", seed=1, tenant="t1")
        assert ei.value.tenant == "t1"
        assert svc.counters.counts["quota_rejections"] == 1
        # another tenant is unaffected
        b = svc.submit(dcop, "mgm", seed=2, tenant="t2")
        _drain(svc)
        assert svc.result(a, timeout=1).status == "FINISHED"
        assert svc.result(b, timeout=1).status == "FINISHED"
        # quota releases with completion
        c = svc.submit(dcop, "mgm", seed=3, tenant="t1")
        _drain(svc)
        assert svc.result(c, timeout=1).status == "FINISHED"

    def test_infeasible_deadline_rejected_at_submit(self):
        svc = _svc()
        dcop = _load()
        for bad in (0, -1.5):
            with pytest.raises(DeadlineInfeasible):
                svc.submit(dcop, "mgm", seed=0, deadline_s=bad)
        assert svc.counters.counts["jobs_shed"] == 2
        assert not svc._jobs  # nothing was queued

    def test_resumed_jobs_bypass_admission_control(self, tmp_path):
        """Jobs re-queued by resume() were admitted before the crash:
        the bounded queue must not reject them."""
        jd = str(tmp_path / "journal")
        svc1 = _svc(journal_dir=jd, checkpoint_every=1)
        dcop = _load()
        for s in range(3):
            svc1.submit(dcop, "dsa", seed=s, source_file=TUTO)
        svc1.tick()
        del svc1  # crash mid-flight

        svc2 = _svc(journal_dir=jd, max_pending=1)
        assert svc2.resume() == 3  # > max_pending, still accepted
        _drain(svc2)
        for jid in list(svc2._jobs):
            assert svc2.result(jid, timeout=1).status == "FINISHED"


# ---------------------------------------------------------------------------
# journal hygiene
# ---------------------------------------------------------------------------

class TestJournalCompaction:
    def test_compaction_drops_done_records(self, tmp_path):
        jd = str(tmp_path / "journal")
        svc = _svc(journal_dir=jd, journal_compact_bytes=1 << 30)
        dcop = _load()
        for s in range(3):
            svc.submit(dcop, "mgm", seed=s, source_file=TUTO)
        _drain(svc)
        path = os.path.join(jd, "jobs.jsonl")
        assert len(open(path).read().splitlines()) == 3
        kept = svc.compact_journal()
        assert kept == 0
        assert open(path).read() == ""
        assert open(os.path.join(jd, "progress_serve")).read() == ""
        assert svc.counters.counts["journal_compactions"] == 1
        # a fresh service sees a clean, resumable-from journal
        svc2 = _svc(journal_dir=jd)
        assert svc2.resume() == 0

    def test_compaction_keeps_inflight_records(self, tmp_path):
        jd = str(tmp_path / "journal")
        svc = _svc(journal_dir=jd, journal_compact_bytes=1 << 30)
        dcop = _load()
        a = svc.submit(dcop, "mgm", seed=0, source_file=TUTO)
        _drain(svc)
        assert svc.result(a, timeout=1).status == "FINISHED"
        b = svc.submit(dcop, "dsa", seed=1, source_file=TUTO)
        svc.tick()  # b in flight, not done
        kept = svc.compact_journal()
        assert kept == 1
        recs = [json.loads(ln) for ln in open(
            os.path.join(jd, "jobs.jsonl")
        ).read().splitlines()]
        assert [r["jid"] for r in recs] == [b]
        # the in-flight record still resumes after a crash
        del svc
        svc2 = _svc(journal_dir=jd)
        assert svc2.resume() == 1
        _drain(svc2)
        assert svc2.result(b, timeout=1).status == "FINISHED"

    def test_size_threshold_triggers_compaction(self, tmp_path):
        jd = str(tmp_path / "journal")
        # 1-byte threshold: every completion compacts
        svc = _svc(journal_dir=jd, journal_compact_bytes=1)
        svc.submit(_load(), "mgm", seed=0, source_file=TUTO)
        _drain(svc)
        assert svc.counters.counts["journal_compactions"] >= 1
        assert open(os.path.join(jd, "jobs.jsonl")).read() == ""


def _write_journal(jd, records, torn_fragment=None, progress=()):
    os.makedirs(os.path.join(jd, "ckpt"), exist_ok=True)
    with open(os.path.join(jd, "jobs.jsonl"), "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
        if torn_fragment is not None:
            f.write(torn_fragment)  # no newline: a torn append
    with open(os.path.join(jd, "progress_serve"), "w") as f:
        for line in progress:
            f.write(line)


def _rec(jid, seed=0, algo="mgm"):
    return {"jid": jid, "file": TUTO, "algo": algo, "seed": seed}


class TestTornJournal:
    def test_truncated_final_jobs_line_resumes_cleanly(self, tmp_path):
        jd = str(tmp_path / "journal")
        _write_journal(
            jd, [_rec("job-000001")],
            torn_fragment='{"jid": "job-0000',
        )
        svc = _svc(journal_dir=jd)
        assert svc.resume() == 1  # the complete record
        assert svc.counters.counts["torn_journal_lines"] == 1
        _drain(svc)
        res = svc.result("job-000001", timeout=1)
        seq = _standalone(_load(), "mgm", 0)
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle

    def test_glued_torn_fragment_skipped(self, tmp_path):
        """A fragment the next append glued onto parses as neither
        record: skipped + counted, the neighbors resume."""
        jd = str(tmp_path / "journal")
        os.makedirs(os.path.join(jd, "ckpt"), exist_ok=True)
        with open(os.path.join(jd, "jobs.jsonl"), "w") as f:
            f.write(json.dumps(_rec("job-000001")) + "\n")
            f.write('{"jid": "job-0000' + json.dumps(_rec(
                "job-000002", seed=1)) + "\n")
            f.write(json.dumps(_rec("job-000003", seed=2)) + "\n")
        svc = _svc(journal_dir=jd)
        assert svc.resume() == 2  # 1 and 3; the glued line is torn
        assert svc.counters.counts["torn_journal_lines"] == 1

    def test_half_written_jid_line_skipped_and_counted(self, tmp_path):
        jd = str(tmp_path / "journal")
        _write_journal(
            jd,
            [_rec("job-000001"), _rec("job-000002", seed=1)],
            progress=["JID: job-000001\n", "JID: job-0000"],  # torn
        )
        svc = _svc(journal_dir=jd)
        assert svc.counters.counts["torn_journal_lines"] == 1
        # job 1's completion is trusted; the torn line is not, so job
        # 2 re-runs (idempotent) instead of being wrongly skipped
        assert svc.resume() == 1
        _drain(svc)
        assert svc.result("job-000002", timeout=1).status == "FINISHED"

    def test_corrupt_checkpoint_still_restarts_from_zero(self, tmp_path):
        """The pre-existing corrupt-checkpoint path coexists with torn
        tolerance: CRC rejection restarts the job from cycle 0."""
        jd = str(tmp_path / "journal")
        svc1 = _svc(lanes=1, journal_dir=jd, checkpoint_every=1)
        a = svc1.submit(_load(), "mgm", seed=0, source_file=TUTO)
        svc1.tick()
        ck = svc1._ckpt_path(a)
        assert os.path.exists(ck)
        with open(ck, "r+b") as f:
            f.seek(30)
            f.write(b"\xde\xad\xbe\xef")
        del svc1
        svc2 = _svc(journal_dir=jd)
        assert svc2.resume() == 1
        _drain(svc2)
        res = svc2.result(a, timeout=1)
        seq = _standalone(_load(), "mgm", 0)
        assert res.assignment == seq.assignment
        assert svc2.counters.counts["jobs_resumed"] == 0


# ---------------------------------------------------------------------------
# lossy streams, injector semantics, plan parsing
# ---------------------------------------------------------------------------

class TestLossyStream:
    def test_drops_counted_with_one_notice_per_job(self):
        from pydcop_tpu.runtime.events import event_bus

        counters = ServeCounters()
        job = ServeJob(
            jid="j1", dcop=None, algo="mgm", algo_params={}, seed=0,
            tenant="t", priority=0, deadline_s=None, deadline_at=None,
            label=None, source_file=None, stream=True,
            submitted_at=0.0, seq=1, counters=counters,
        )
        job.events = queue.Queue(maxsize=1)
        seen = []
        cb = lambda t, e: seen.append((t, e))  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("serve.stream.lossy", cb)
        try:
            job.emit("job.progress", {"cycle": 1})  # fills the queue
            job.emit("job.progress", {"cycle": 2})  # dropped + notice
            job.emit("job.progress", {"cycle": 3})  # dropped, silent
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        assert counters.counts["events_dropped"] == 2
        assert len(seen) == 1
        assert seen[0][1] == {"jid": "j1"}


class TestInjectorSemantics:
    def test_one_shot_vs_persistent(self):
        plan = FaultPlan(faults=[
            Fault(kind="raise_in_step", cycle=1),  # transient
            Fault(kind="nan_lane", jid="jA", cycle=1),  # poison
        ])
        inj = ServeFaultInjector(plan)
        # not due before its tick threshold
        assert inj.due("raise_in_step", 0, jids={"jX"}) is None
        assert inj.due("raise_in_step", 1, jids={"jX"}) is not None
        assert inj.due("raise_in_step", 2, jids={"jX"}) is None  # spent
        # the targeted fault only fires in its jid's scope, forever
        assert inj.due("nan_lane", 1, jid="jB") is None
        assert inj.due("nan_lane", 1) is None  # no scope, no fire
        for _ in range(3):
            assert inj.due("nan_lane", 1, jid="jA") is not None
        assert inj.poisoned("jA")
        assert not inj.poisoned("jB")

    def test_plan_yaml_roundtrip(self, tmp_path):
        p = tmp_path / "plan.yaml"
        p.write_text(
            "seed: 7\n"
            "faults:\n"
            "  - kind: raise_in_step\n"
            "    jid: job-000002\n"
            "    cycle: 2\n"
            "  - kind: nan_lane\n"
            "    jid: job-000003\n"
            "  - kind: torn_journal_write\n"
            "  - kind: stall_tick\n"
            "    duration: 0.5\n"
        )
        plan = FaultPlan.from_yaml(str(p))
        assert len(plan.serve_faults()) == 4
        assert plan.serve_faults()[0].jid == "job-000002"
        # jid survives the env-channel JSON roundtrip
        again = FaultPlan.from_json(plan.to_json())
        assert again.serve_faults()[0].jid == "job-000002"

    def test_stall_tick_requires_duration(self):
        with pytest.raises(ValueError):
            Fault(kind="stall_tick")
