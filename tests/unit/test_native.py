"""Native C++ partitioner: build, correctness, cut quality."""
import numpy as np
import pytest

from pydcop_tpu import native
from pydcop_tpu.parallel.partition import partition_factors, partition_stats


def grid_edges(side):
    eu, ev = [], []
    for r in range(side):
        for c in range(side):
            i = r * side + c
            if c + 1 < side:
                eu.append(i)
                ev.append(i + 1)
            if r + 1 < side:
                eu.append(i)
                ev.append(i + side)
    return np.array(eu, dtype=np.int32), np.array(ev, dtype=np.int32)


@pytest.mark.skipif(not native.native_available(),
                    reason="g++ unavailable")
class TestNativePartitioner:
    def test_partitions_all_vertices(self):
        eu, ev = grid_edges(8)
        part = native.partition_vertices(eu, ev, 64, 4)
        assert part is not None
        assert part.shape == (64,)
        assert set(np.unique(part)) <= {0, 1, 2, 3}
        # roughly balanced
        counts = np.bincount(part, minlength=4)
        assert counts.max() <= 2 * counts.min() + 16

    def test_grid_cut_quality(self):
        """BFS-grown regions on a grid must beat random assignment by a
        wide margin."""
        eu, ev = grid_edges(16)
        part = native.partition_vertices(eu, ev, 256, 4)
        cut = int(np.sum(part[eu] != part[ev]))
        rng = np.random.default_rng(0)
        rand = rng.integers(0, 4, 256)
        rand_cut = int(np.sum(rand[eu] != rand[ev]))
        assert cut < rand_cut / 2

    def test_disconnected_leftovers_assigned(self):
        # two components + isolated vertices
        eu = np.array([0, 1, 5, 6], dtype=np.int32)
        ev = np.array([1, 2, 6, 7], dtype=np.int32)
        part = native.partition_vertices(eu, ev, 10, 2)
        assert part is not None
        assert (part >= 0).all()


class TestFactorPartitionIntegration:
    def test_native_factor_partition_balanced(self):
        rng = np.random.default_rng(1)
        # ring of 120 vars → 120 binary factors
        var_idx = np.stack(
            [np.arange(120), (np.arange(120) + 1) % 120], axis=1
        ).astype(np.int32)
        assigns = partition_factors([var_idx], 120, 4)
        counts = np.bincount(assigns[0], minlength=4)
        assert counts.max() <= 31  # ceil(120/4) + rebalance slack
        stats = partition_stats([var_idx], assigns, 4)
        # a ring partitioned into contiguous arcs cuts few variables
        assert stats["cut_fraction"] < 0.2

    def test_fallback_used_when_disabled(self):
        var_idx = np.stack(
            [np.arange(40), (np.arange(40) + 1) % 40], axis=1
        ).astype(np.int32)
        assigns = partition_factors([var_idx], 40, 4, use_native=False)
        assert assigns[0].shape == (40,)
