"""Whole-sweep DPOP pallas kernel (VERDICT r3 item 3) vs the level-scan
engine: identical assignments on random trees, forests, ragged domains,
and max-mode.  Kernels run in interpret mode here; the traced math is
identical on TPU."""
import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.graph import pseudotree
from pydcop_tpu.ops.dpop_sweep import compile_sweep, run_sweep
from pydcop_tpu.ops.pallas_dpop import pack_sweep, whole_sweep_values


def _tree_dcop(N=60, D=4, seed=0, objective="min", ragged=False,
               forest=False):
    rng = np.random.default_rng(seed)
    dcop = DCOP("t", objective=objective)
    doms = [Domain("d", "vals", list(range(D)))]
    if ragged:
        doms.append(Domain("d2", "vals", list(range(max(2, D - 2)))))
    vs = []
    for i in range(N):
        dom = doms[i % len(doms)]
        v = Variable(f"v{i}", dom)
        vs.append(v)
        dcop.add_variable(v)
    for i in range(1, N):
        if forest and i % 17 == 0:
            continue  # no parent: this node roots a new tree
        p = int(rng.integers(max(0, i - 8), i))
        Dp, Di = len(vs[p].domain), len(vs[i].domain)
        mat = rng.uniform(0, 10, (Dp, Di)).astype(np.float32)
        dcop.add_constraint(
            NAryMatrixRelation([vs[p], vs[i]], mat, name=f"c{i}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_matches_level_scan_random_tree(seed):
    dcop = _tree_dcop(seed=seed)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assert plan is not None and plan.W == 1
    ref, _ = run_sweep(plan)
    ps = pack_sweep(plan)
    assert ps is not None
    got = np.asarray(whole_sweep_values(ps, interpret=True))
    assert np.array_equal(ref, got)


def test_matches_on_forest():
    dcop = _tree_dcop(N=70, seed=3, forest=True)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    if plan is None:
        pytest.skip("forest not sweepable by level engine")
    ref, _ = run_sweep(plan)
    ps = pack_sweep(plan)
    assert ps is not None
    got = np.asarray(whole_sweep_values(ps, interpret=True))
    assert np.array_equal(ref, got)


def test_matches_ragged_domains():
    dcop = _tree_dcop(N=50, D=5, seed=4, ragged=True)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    ref, _ = run_sweep(plan)
    ps = pack_sweep(plan)
    assert ps is not None
    got = np.asarray(whole_sweep_values(ps, interpret=True))
    assert np.array_equal(ref, got)
    # ragged nodes never pick out-of-domain values
    for gid, name in enumerate(plan.gid_to_name):
        dom = len(dcop.variables[name].domain)
        assert got[gid] < dom


def test_matches_max_mode():
    dcop = _tree_dcop(N=40, seed=5, objective="max")
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "max")
    ref, _ = run_sweep(plan)
    ps = pack_sweep(plan)
    assert ps is not None
    got = np.asarray(whole_sweep_values(ps, interpret=True))
    assert np.array_equal(ref, got)


def test_costs_match_brute_force():
    # the kernel's assignment must reach the exact optimum
    import itertools

    dcop = _tree_dcop(N=9, D=3, seed=6)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    ps = pack_sweep(plan)
    got = np.asarray(whole_sweep_values(ps, interpret=True))
    assign = {
        name: dcop.variables[name].domain.values[got[g]]
        for g, name in enumerate(plan.gid_to_name)
    }
    _, cost = dcop.solution_cost(assign, 1e9)
    best = min(
        dcop.solution_cost(
            {v.name: v.domain.values[c[k]]
             for k, v in enumerate(dcop.variables.values())}, 1e9
        )[1]
        for c in itertools.product(
            *[range(len(v.domain)) for v in dcop.variables.values()]
        )
    )
    assert cost == pytest.approx(best, abs=1e-3)


def test_refuses_wide_separators():
    # a triangle makes a pseudo-parent link -> W=2 plan -> pack refuses
    dcop = _tree_dcop(N=20, seed=7)
    vs = list(dcop.variables.values())
    mat = np.ones((len(vs[0].domain), len(vs[5].domain)), np.float32)
    dcop.add_constraint(NAryMatrixRelation([vs[0], vs[5]], mat, name="x"))
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    if plan is None or plan.W == 1:
        pytest.skip("instance did not produce a wide separator")
    assert pack_sweep(plan) is None


class TestSweepCache:
    """Persistent executable cache mechanics (ops/sweep_cache) — the
    serialize round-trip itself needs real hardware (driven in the
    bench/TPU flow); these cover key stability, disable, and corrupt
    file handling."""

    def _ps(self, N=40, seed=5):
        dcop = _tree_dcop(N=N, D=3, seed=seed)
        tree = pseudotree.build_computation_graph(dcop)
        plan = compile_sweep(tree, dcop, "min")
        ps = pack_sweep(plan)
        assert ps is not None
        return ps

    def test_key_stable_and_shape_sensitive(self):
        from pydcop_tpu.ops.sweep_cache import sweep_cache_key

        ps = self._ps()
        assert sweep_cache_key(ps) == sweep_cache_key(ps)
        ps2 = self._ps(N=80, seed=7)
        assert sweep_cache_key(ps) != sweep_cache_key(ps2)

    def test_disabled_by_empty_env(self, monkeypatch):
        from pydcop_tpu.ops import sweep_cache

        monkeypatch.setenv("PYDCOP_TPU_CACHE_DIR", "")
        assert sweep_cache.cache_dir() is None
        assert sweep_cache.load_sweep_executable(self._ps()) is None
        # save must be a silent no-op
        sweep_cache.save_sweep_executable(self._ps(), object())

    def test_corrupt_cache_file_recompiles(self, tmp_path, monkeypatch):
        from pydcop_tpu.ops import sweep_cache

        monkeypatch.setenv("PYDCOP_TPU_CACHE_DIR", str(tmp_path))
        ps = self._ps()
        path = tmp_path / f"sweep-{sweep_cache.sweep_cache_key(ps)}.bin"
        path.write_bytes(b"\x08\x00\x00\x00\x00\x00\x00\x00garbage")
        assert sweep_cache.load_sweep_executable(ps) is None
        assert not path.exists()  # corrupt entry evicted
