"""Sharded-kernel correctness on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import jax

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.ops import compile_factor_graph
from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
from pydcop_tpu.parallel import ShardedMaxSum, build_mesh, \
    shard_factor_graph
from pydcop_tpu.parallel.partition import partition_factors, partition_stats

import os

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def tuto_tensors():
    dcop = load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )
    return dcop, compile_factor_graph(dcop)


def test_mesh_has_8_devices():
    mesh = build_mesh()
    assert mesh.devices.size == 8


def test_shard_factor_graph_layout(tuto_tensors):
    _, tensors = tuto_tensors
    st = shard_factor_graph(tensors, 4)
    assert st.n_shards == 4
    # every real factor appears exactly once across shards
    total_real = sum(
        int((np.asarray(sb.var_idx) < tensors.n_vars).all(axis=1).sum())
        for sb in st.buckets
    )
    assert total_real == tensors.n_factors
    assert st.edge_var.shape[0] == st.edges_per_shard * 4


def test_sharded_matches_unsharded(tuto_tensors):
    """Sharded psum cycle ≡ single-device cycle, bit-for-bit semantics."""
    dcop, tensors = tuto_tensors
    # unsharded: run 8 cycles (no noise here: raw tensors)
    q, r = init_messages(tensors)
    for _ in range(8):
        q, r, beliefs, values = maxsum_cycle(tensors, q, r, damping=0.5)
    expected = tensors.assignment_from_indices(np.asarray(values))

    sharded = ShardedMaxSum(tensors, build_mesh(8), damping=0.5)
    values_sh, _, _ = sharded.run(cycles=8)
    got = tensors.assignment_from_indices(values_sh)
    assert got == expected
    assert got == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}


def test_sharded_on_subset_mesh(tuto_tensors):
    _, tensors = tuto_tensors
    sharded = ShardedMaxSum(tensors, build_mesh(2), damping=0.5)
    values_sh, _, _ = sharded.run(cycles=8)
    got = tensors.assignment_from_indices(values_sh)
    assert got == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}


class TestShardedLocalSearch:
    def test_sharded_mgm_matches_unsharded(self):
        """Sharded MGM ≡ single-device MGM from the same start (MGM is
        deterministic given x0)."""
        import jax
        import jax.numpy as jnp

        from pydcop_tpu.algorithms._local_search import (
            gains_and_best,
            neighborhood_winner,
            random_valid_values,
        )
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.ops import compile_constraint_graph
        from pydcop_tpu.parallel import ShardedLocalSearch

        dcop = load_dcop_from_file(
            os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
        )
        tensors = compile_constraint_graph(dcop)
        seed = 3
        # unsharded rollout
        x = random_valid_values(tensors, jax.random.PRNGKey(seed + 17))
        for _ in range(10):
            cur, best_val, gain, _ = gains_and_best(tensors, x)
            move = neighborhood_winner(tensors, gain)
            x = jnp.where(move, best_val, x).astype(jnp.int32)
        expected = np.asarray(x)

        sharded = ShardedLocalSearch(tensors, build_mesh(4), rule="mgm")
        got = sharded.run(cycles=10, seed=seed)
        np.testing.assert_array_equal(got, expected)

    def test_sharded_dsa_solves_csp(self):
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.ops import compile_constraint_graph
        from pydcop_tpu.parallel import ShardedLocalSearch

        dcop = load_dcop_from_file(
            os.path.join(INSTANCES, "coloring_csp.yaml")
        )
        tensors = compile_constraint_graph(dcop)
        sharded = ShardedLocalSearch(tensors, build_mesh(2), rule="dsa")
        values = sharded.run(cycles=60, seed=1)
        assignment = tensors.assignment_from_indices(values)
        assert dcop.solution_cost(assignment, 10000) == (0, 0)

    def test_sharded_adsa_matches_single_device_rule(self):
        """Sharded adsa ≡ a single-device rollout of ADsaSolver.cycle's
        activation-mask semantics fed the SAME per-cycle keys (VERDICT
        r3 item 9: the last non-host-sequential family member without a
        multi-device twin)."""
        import jax
        import jax.numpy as jnp

        from pydcop_tpu.algorithms._local_search import (
            HARD_THRESHOLD,
            gains_and_best,
            random_valid_values,
        )
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.ops import compile_constraint_graph
        from pydcop_tpu.parallel import ShardedLocalSearch

        dcop = load_dcop_from_file(
            os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
        )
        tensors = compile_constraint_graph(dcop)
        seed, cycles, activation, prob = 5, 12, 0.6, 0.7
        # single-device rollout with the sharded runner's key schedule
        x = random_valid_values(tensors, jax.random.PRNGKey(seed + 17))
        for key in jax.random.split(jax.random.PRNGKey(seed), cycles):
            k_wake, k_move = jax.random.split(key)
            awake = (
                jax.random.uniform(k_wake, (tensors.n_vars,)) < activation
            )
            cur, best_val, gain, _ = gains_and_best(
                tensors, x, prefer_change=True
            )
            activate = (
                jax.random.uniform(k_move, (tensors.n_vars,)) < prob
            )
            want = (gain > 1e-9) | (
                (gain <= 1e-9) & (best_val != x)
                & (cur >= HARD_THRESHOLD)
            )
            x = jnp.where(want & activate & awake, best_val, x).astype(
                jnp.int32)
        expected = np.asarray(x)

        sharded = ShardedLocalSearch(
            tensors, build_mesh(4), rule="adsa", probability=prob,
            algo_params={"activation": activation, "variant": "B"},
        )
        got = sharded.run(cycles=cycles, seed=seed)
        np.testing.assert_array_equal(got, expected)


def test_partition_locality():
    rng = np.random.default_rng(0)
    var_idx = rng.integers(0, 100, size=(200, 2)).astype(np.int32)
    assigns = partition_factors([var_idx], 100, 4)
    stats = partition_stats([var_idx], assigns, 4)
    assert 0 <= stats["cut_fraction"] <= 1
    # locality ordering beats random assignment on average
    rand_assign = [rng.integers(0, 4, size=200).astype(np.int32)]
    rand_stats = partition_stats([var_idx], rand_assign, 4)
    assert stats["cut_fraction"] <= rand_stats["cut_fraction"] + 0.05


class TestShardedAMaxSum:
    """amaxsum's activation masks in the sharded engine (ADVICE r2:
    the placement-driven path used to silently run synchronous maxsum)."""

    def test_activation_one_equals_maxsum(self, tuto_tensors):
        dcop, tensors = tuto_tensors
        sync = ShardedMaxSum(tensors, build_mesh(4), damping=0.5)
        v_sync, q_s, _ = sync.run(cycles=8)
        full = ShardedMaxSum(tensors, build_mesh(4), damping=0.5,
                             activation=1.0)
        v_full, q_f, _ = full.run(cycles=8)
        assert full.activation is None  # >= 1 disables masking
        np.testing.assert_array_equal(v_sync, v_full)
        np.testing.assert_allclose(np.asarray(q_s), np.asarray(q_f))

    def test_activation_masks_message_updates(self, tuto_tensors):
        """With activation<1 some edges must keep their previous messages
        (state differs from the synchronous run), and the solver still
        reaches the known optimum on the tutorial instance."""
        dcop, tensors = tuto_tensors
        sync = ShardedMaxSum(tensors, build_mesh(4), damping=0.5)
        _, q_sync, _ = sync.run(cycles=6)
        a = ShardedMaxSum(tensors, build_mesh(4), damping=0.5,
                          activation=0.5)
        v_a, q_a, r_a = a.run(cycles=6)
        assert not np.allclose(np.asarray(q_sync), np.asarray(q_a))
        # anytime semantics still converge on the 4-var tutorial graph
        v_a, _, _ = a.run(cycles=30, q=q_a, r=r_a)
        got = tensors.assignment_from_indices(v_a)
        assert got == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}

    def test_resumed_runs_advance_activation_stream(self, tuto_tensors):
        """Chunked runs must not replay the same activation pattern
        (epoch folding)."""
        _, tensors = tuto_tensors
        a = ShardedMaxSum(tensors, build_mesh(2), damping=0.5,
                          activation=0.5)
        _, q1, r1 = a.run(cycles=3)
        _, q2, r2 = a.run(cycles=3, q=q1, r=r1)
        b = ShardedMaxSum(tensors, build_mesh(2), damping=0.5,
                          activation=0.5)
        _, qb, rb = b.run(cycles=3)
        # same seed+cycles from scratch reproduces chunk 1...
        np.testing.assert_allclose(np.asarray(q1), np.asarray(qb))
        # ...but chunk 2 continues the stream instead of replaying it
        assert not np.allclose(np.asarray(q1), np.asarray(q2))


class TestShardedBreakout:
    """dba/gdba sharded twins ≡ single-device solvers (deterministic
    given x0: MGM-style arbitration, integer costs)."""

    def _dcop(self, seed=13):
        from pydcop_tpu.generators import generate_graph_coloring

        return generate_graph_coloring(
            n_variables=24, n_colors=3, n_edges=50, soft=True,
            n_agents=1, seed=seed,
        )

    def test_sharded_dba_matches_single_device(self):
        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms.dba import DbaSolver
        from pydcop_tpu.ops.compile import compile_constraint_graph
        from pydcop_tpu.parallel.mesh import ShardedLocalSearch

        dcop = self._dcop()
        tensors = compile_constraint_graph(dcop)
        solver = DbaSolver(
            dcop, tensors, AlgorithmDef.build_with_default_params("dba"),
            seed=0,
        )
        state = solver.initial_state()
        for i in range(12):
            state = solver.cycle(state, jax.random.PRNGKey(i))
        expected = np.asarray(state[0])

        sharded = ShardedLocalSearch(tensors, build_mesh(4), rule="dba")
        got = sharded.run(cycles=12, seed=0)
        np.testing.assert_array_equal(got, expected)

    @pytest.mark.parametrize("params", [
        {"modifier": "A", "violation": "NZ", "increase_mode": "E"},
        {"modifier": "M", "violation": "NM", "increase_mode": "R"},
    ])
    def test_sharded_gdba_matches_single_device(self, params):
        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms.gdba import GdbaSolver
        from pydcop_tpu.ops.compile import compile_constraint_graph
        from pydcop_tpu.parallel.mesh import ShardedLocalSearch

        dcop = self._dcop(seed=29)
        tensors = compile_constraint_graph(dcop)
        solver = GdbaSolver(
            dcop, tensors,
            AlgorithmDef.build_with_default_params("gdba", params), seed=0,
        )
        state = solver.initial_state()
        for i in range(10):
            state = solver.cycle(state, jax.random.PRNGKey(i))
        expected = np.asarray(state[0])

        sharded = ShardedLocalSearch(
            tensors, build_mesh(4), rule="gdba", algo_params=params,
        )
        got = sharded.run(cycles=10, seed=0)
        np.testing.assert_array_equal(got, expected)
