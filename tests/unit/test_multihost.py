"""Multi-process mesh execution (reference process mode reborn —
pydcop/infrastructure/run.py:225-287).

Two JAX processes × 4 virtual CPU devices form one global 8-device mesh
via jax.distributed (Gloo); both run the same sharded MaxSum and must
agree with each other AND with the single-process 8-device mesh result.
"""
import contextlib
import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

#: jaxlib's refusal marker when the CPU backend was built without
#: multi-process (Gloo) collective support — an environment property,
#: not a code path under test
_NO_MULTIPROC = "Multiprocess computations aren't implemented"


def assert_rank_ok(p, stderr):
    """Rank exit check with a guarded environment skip: a rank that
    died specifically because this host's jaxlib cannot form a
    multi-process CPU mesh skips the test (with the reason) instead of
    failing; ANY other failure still fails loudly."""
    if p.returncode != 0 and _NO_MULTIPROC in (stderr or ""):
        pytest.skip(
            "environment: this jaxlib's CPU backend lacks multi-process "
            "(Gloo) collectives — XlaRuntimeError 'Multiprocess "
            "computations aren't implemented on the CPU backend'; the "
            "2-process mesh tests need a Gloo-enabled jaxlib or a real "
            "multi-host platform"
        )
    assert p.returncode == 0, stderr[-1500:]


def free_port():
    """OS-assigned free port for the jax.distributed coordinator — fixed
    ports collide across parallel/reentrant test runs."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@contextlib.contextmanager
def reaped(procs):
    """Kill stragglers on any failure: an asserting rank must not leave
    its peer blocked in jax.distributed.initialize holding the port."""
    try:
        yield procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)


def spawn_worker(process_id, port, num_processes=2, extra_args=()):
    env = {
        **os.environ,
        "PYTHONPATH": REPO,  # drop axon sitecustomize so cpu sticks
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    return subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu.parallel.multihost",
         "--coordinator", f"127.0.0.1:{port}",
         "--num-processes", str(num_processes),
         "--process-id", str(process_id),
         "--local-devices", "4", "--platform", "cpu",
         "--vars", "60", "--edges", "120", *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO,
    )


def test_two_process_mesh_agrees_with_single_process():
    port = free_port()
    outs = []
    with reaped([spawn_worker(0, port), spawn_worker(1, port)]) as procs:
        for p in procs:
            stdout, stderr = p.communicate(timeout=240)
            assert_rank_ok(p, stderr)
            outs.append(json.loads(stdout.strip().splitlines()[-1]))

    # both processes computed over the GLOBAL 8-device mesh
    assert all(o["n_global_devices"] == 8 for o in outs), outs
    assert outs[0]["values_checksum"] == outs[1]["values_checksum"]
    assert outs[0]["n_values"] == 60

    # and the multi-process result matches the single-process 8-mesh
    import numpy as np

    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_factor_graph
    from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

    dcop = generate_graph_coloring(
        n_variables=60, n_colors=3, n_edges=120, soft=True, n_agents=1,
        seed=1,
    )
    tensors = compile_factor_graph(dcop)
    sharded = ShardedMaxSum(tensors, build_mesh(8), damping=0.5)
    values, _, _ = sharded.run(cycles=15)
    assert int(np.asarray(values).sum()) == outs[0]["values_checksum"]


def test_two_process_mesh_packed_engine():
    """The LANE-PACKED per-shard engine on a REAL 2-process mesh: the
    stacked operands (cost rows, plan consts, mixed extras) are
    device_put with explicit NamedShardings and the rotated-launch scan
    state spans the global mesh — the exact paths the 'jit ARGUMENTS,
    not closure constants' rules exist for.  Both ranks must agree with
    each other and with the single-process packed 8-device mesh."""

    port = free_port()
    extra = ["--packed", "--cycles", "8"]
    outs = []
    with reaped([spawn_worker(0, port, extra_args=extra),
                 spawn_worker(1, port, extra_args=extra)]) as procs:
        for p in procs:
            stdout, stderr = p.communicate(timeout=240)
            assert_rank_ok(p, stderr)
            outs.append(json.loads(stdout.strip().splitlines()[-1]))

    assert all(o["n_global_devices"] == 8 for o in outs), outs
    # the packed engine actually ran (use_packed=True is a request —
    # the packer can decline and silently fall back to generic)
    assert all(o["packed"] for o in outs), outs
    assert outs[0]["values_checksum"] == outs[1]["values_checksum"]

    import numpy as np

    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_factor_graph
    from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

    dcop = generate_graph_coloring(
        n_variables=60, n_colors=3, n_edges=120, soft=True, n_agents=1,
        seed=1,
    )
    tensors = compile_factor_graph(dcop)
    packed = ShardedMaxSum(tensors, build_mesh(8), damping=0.5,
                           use_packed=True)
    assert packed.packs is not None
    values, _, _ = packed.run(cycles=8)
    assert int(np.asarray(values).sum()) == outs[0]["values_checksum"]


def test_agent_multihost_cli(tmp_path):
    """`pydcop_tpu agent --multihost` — agent processes as compute ranks
    of a global mesh, the TPU-native twin of reference agent processes
    hosting computations (pydcop/commands/agent.py:32-46)."""
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_graph_coloring

    dcop = generate_graph_coloring(
        n_variables=30, n_colors=3, n_edges=60, soft=True, n_agents=1,
        seed=2,
    )
    dcop_f = tmp_path / "prob.yaml"
    dcop_f.write_text(dcop_yaml(dcop))
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }

    port = free_port()

    def worker(pid):
        return subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu", "--timeout", "240",
             "agent", "--multihost",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             "--local-devices", "4", "--platform", "cpu",
             "--dcop", str(dcop_f), "--algo", "maxsum",
             "--cycles", "12"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO,
        )

    outs = []
    with reaped([worker(0), worker(1)]) as procs:
        for p in procs:
            stdout, stderr = p.communicate(timeout=240)
            assert_rank_ok(p, stderr)
            # Gloo may chat on stdout before the metrics JSON
            payload = stdout[stdout.find("{"):]
            outs.append(json.JSONDecoder().raw_decode(payload)[0])
    assert all(o["status"] == "FINISHED" for o in outs)
    assert all(o["n_global_devices"] == 8 for o in outs)
    assert outs[0]["assignment"] == outs[1]["assignment"]
    assert outs[0]["cost"] == outs[1]["cost"]


def test_agent_multihost_rejects_missing_args():
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", "agent", "--multihost"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert out.returncode != 0
    assert "num-processes" in out.stdout or "num-processes" in out.stderr


def test_local_search_packed_engine_plumbing():
    """run_multihost_local_search's ``use_packed``/``info`` plumbing
    (the lane-packed sharded move rule, round 6) — exercised in-process
    over the 8-device virtual mesh, which IS the global mesh of a
    single-process run: the packed request must reach
    ShardedLocalSearch, info must report the engine that actually ran,
    and coin-free MGM must agree with the direct packed solver."""
    import numpy as np

    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_constraint_graph
    from pydcop_tpu.parallel.mesh import ShardedLocalSearch, build_mesh
    from pydcop_tpu.parallel.multihost import run_multihost_local_search

    dcop = generate_graph_coloring(
        n_variables=40, n_colors=3, n_edges=80, soft=True, n_agents=1,
        seed=1,
    )
    info = {}
    values, n_dev, _t = run_multihost_local_search(
        dcop, rule="mgm", cycles=10, seed=0, use_packed=True, info=info)
    assert n_dev == 8
    assert info["packed"] is True
    tensors = compile_constraint_graph(dcop)
    direct = ShardedLocalSearch(tensors, build_mesh(8), rule="mgm",
                                use_packed=True)
    np.testing.assert_array_equal(values, direct.run(cycles=10, seed=0))
    # the generic request is honored too (and reported honestly)
    info_g = {}
    run_multihost_local_search(
        dcop, rule="mgm", cycles=2, seed=0, use_packed=False,
        info=info_g)
    assert info_g["packed"] is False


def test_two_process_mesh_dba():
    """The breakout family rides the multi-process mesh too: 2 real
    processes x 4 virtual devices run sharded DBA (shard-local weight
    state) and must agree with each other and with the single-process
    8-device mesh."""

    def worker(pid, port):
        env = {
            **os.environ,
            "PYTHONPATH": REPO,
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        }
        return subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu.parallel.multihost",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             "--local-devices", "4", "--platform", "cpu",
             "--algo", "dba",
             "--vars", "40", "--edges", "80", "--cycles", "10"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO,
        )

    port = free_port()
    outs = []
    with reaped([worker(0, port), worker(1, port)]) as procs:
        for p in procs:
            stdout, stderr = p.communicate(timeout=240)
            assert_rank_ok(p, stderr)
            outs.append(json.loads(stdout.strip().splitlines()[-1]))

    assert all(o["n_global_devices"] == 8 for o in outs), outs
    assert outs[0]["values_checksum"] == outs[1]["values_checksum"]

    import numpy as np

    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_constraint_graph
    from pydcop_tpu.parallel.mesh import ShardedLocalSearch, build_mesh

    dcop = generate_graph_coloring(
        n_variables=40, n_colors=3, n_edges=80, soft=True, n_agents=1,
        seed=1,
    )
    tensors = compile_constraint_graph(dcop)
    sharded = ShardedLocalSearch(tensors, build_mesh(8), rule="dba")
    values = sharded.run(cycles=10, seed=0)
    assert int(np.asarray(values).sum()) == outs[0]["values_checksum"]
