"""Multi-process mesh execution (reference process mode reborn —
pydcop/infrastructure/run.py:225-287).

Two JAX processes × 4 virtual CPU devices form one global 8-device mesh
via jax.distributed (Gloo); both run the same sharded MaxSum and must
agree with each other AND with the single-process 8-device mesh result.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
PORT = 29517


def spawn_worker(process_id, num_processes=2):
    env = {
        **os.environ,
        "PYTHONPATH": REPO,  # drop axon sitecustomize so cpu sticks
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    return subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu.parallel.multihost",
         "--coordinator", f"127.0.0.1:{PORT}",
         "--num-processes", str(num_processes),
         "--process-id", str(process_id),
         "--local-devices", "4", "--platform", "cpu",
         "--vars", "60", "--edges", "120", "--cycles", "15"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO,
    )


def test_two_process_mesh_agrees_with_single_process():
    procs = [spawn_worker(0), spawn_worker(1)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=240)
        assert p.returncode == 0, stderr[-1500:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))

    # both processes computed over the GLOBAL 8-device mesh
    assert all(o["n_global_devices"] == 8 for o in outs), outs
    assert outs[0]["values_checksum"] == outs[1]["values_checksum"]
    assert outs[0]["n_values"] == 60

    # and the multi-process result matches the single-process 8-mesh
    import numpy as np

    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.ops.compile import compile_factor_graph
    from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

    dcop = generate_graph_coloring(
        n_variables=60, n_colors=3, n_edges=120, soft=True, n_agents=1,
        seed=1,
    )
    tensors = compile_factor_graph(dcop)
    sharded = ShardedMaxSum(tensors, build_mesh(8), damping=0.5)
    values, _, _ = sharded.run(cycles=15)
    assert int(np.asarray(values).sum()) == outs[0]["values_checksum"]
