"""Asynchronous-family semantics: amaxsum and adsa emulate asynchrony
with random activation masks (documented deviation, SURVEY §7.10 /
module docstrings).  These tests pin the mask semantics themselves.
"""
import jax.numpy as jnp
import jax.random
import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.amaxsum import AMaxSumSolver
from pydcop_tpu.algorithms.amaxsum import algo_params as ams_params
from pydcop_tpu.algorithms.adsa import ADsaSolver
from pydcop_tpu.algorithms.adsa import algo_params as adsa_params
from pydcop_tpu.algorithms.maxsum import MaxSumSolver
from pydcop_tpu.algorithms.maxsum import algo_params as ms_params
from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.ops.compile import compile_constraint_graph, \
    compile_factor_graph
from pydcop_tpu.runtime import solve_result


@pytest.fixture(scope="module")
def coloring():
    return generate_graph_coloring(
        n_variables=10, n_colors=3, n_edges=16, soft=True, n_agents=1,
        seed=9,
    )


def amaxsum_solver(dcop, activation, seed=0):
    algo = AlgorithmDef.build_with_default_params(
        "amaxsum", {"activation": activation},
        parameters_definitions=ams_params,
    )
    return AMaxSumSolver(dcop, compile_factor_graph(dcop), algo, seed)


class TestAMaxSum:
    def test_activation_one_equals_sync_maxsum(self, coloring):
        """activation=1.0 -> every edge fires every round = synchronous
        MaxSum exactly (same seed -> same noise -> same trajectory)."""
        a = amaxsum_solver(coloring, 1.0)
        algo = AlgorithmDef.build_with_default_params(
            "maxsum", {}, parameters_definitions=ms_params
        )
        s = MaxSumSolver(coloring, compile_factor_graph(coloring), algo,
                         seed=0, use_packed=False)
        ra = a.run(cycles=20)
        rs = s.run(cycles=20)
        assert ra.assignment == rs.assignment
        assert ra.cost == pytest.approx(rs.cost)

    def test_partial_activation_freezes_inactive_edges(self, coloring):
        solver = amaxsum_solver(coloring, 0.5)
        state = solver.initial_state()
        key = jax.random.PRNGKey(4)
        q0, r0, _ = state
        q1, r1, _ = solver.cycle(state, key)
        # run the same step fully synchronously to see which edges moved
        from pydcop_tpu.ops.maxsum_kernels import maxsum_cycle

        q_sync, r_sync, _, _ = maxsum_cycle(
            solver.tensors, q0, r0, damping=solver.damping
        )
        q1, r1 = np.asarray(q1), np.asarray(r1)
        frozen = np.all(q1 == np.asarray(q0), axis=1) & np.all(
            r1 == np.asarray(r0), axis=1
        )
        updated = np.all(q1 == np.asarray(q_sync), axis=1) & np.all(
            r1 == np.asarray(r_sync), axis=1
        )
        # every edge is either fully frozen or fully updated...
        assert np.all(frozen | updated)
        # ...and with activation=0.5 both kinds occur
        assert frozen.any() and updated.any()

    def test_converges_to_good_solution(self, coloring):
        res = solve_result(coloring, "amaxsum", cycles=40)
        opt = solve_result(coloring, "dpop")
        assert res.cost <= opt.cost * 1.5 + 2.0

    def test_activation_zero_never_moves_messages(self, coloring):
        solver = amaxsum_solver(coloring, 0.0)
        state = solver.initial_state()
        q0, r0, _ = state
        q1, r1, _ = solver.cycle(state, jax.random.PRNGKey(0))
        assert np.array_equal(np.asarray(q1), np.asarray(q0))
        assert np.array_equal(np.asarray(r1), np.asarray(r0))


def adsa_solver(dcop, activation, seed=0):
    algo = AlgorithmDef.build_with_default_params(
        "adsa", {"activation": activation},
        parameters_definitions=adsa_params,
    )
    return ADsaSolver(dcop, compile_constraint_graph(dcop), algo, seed)


class TestADsa:
    def test_sleeping_variables_keep_values(self, coloring):
        """With low activation most variables must keep their value each
        round (only awake AND probability-activated ones move)."""
        solver = adsa_solver(coloring, 0.1)
        state = solver.initial_state()
        (x0,) = state
        moved = 0
        key = jax.random.PRNGKey(2)
        for _ in range(10):
            key, sub = jax.random.split(key)
            state = solver.cycle(state, sub)
        (x1,) = state
        moved = int(np.sum(np.asarray(x1) != np.asarray(x0)))
        # 10 vars, 10 rounds, wake prob 0.1 x move prob 0.7: far fewer
        # moves than a synchronous DSA would make
        assert moved <= 8

    def test_activation_zero_is_frozen(self, coloring):
        solver = adsa_solver(coloring, 0.0)
        state = solver.initial_state()
        (x0,) = state
        for k in range(5):
            state = solver.cycle(state, jax.random.PRNGKey(k))
        assert np.array_equal(np.asarray(state[0]), np.asarray(x0))

    def test_still_solves(self, coloring):
        res = solve_result(coloring, "adsa", cycles=60)
        assert res.status == "FINISHED"
        assert res.violation == 0

    def test_period_param_accepted_for_parity(self, coloring):
        # the reference's wall-clock period maps onto metrics only;
        # accepting it must not change the math
        r1 = solve_result(
            coloring, "adsa", cycles=30, algo_params={"period": 0.1},
            seed=3,
        )
        r2 = solve_result(
            coloring, "adsa", cycles=30, algo_params={"period": 5.0},
            seed=3,
        )
        assert r1.assignment == r2.assignment
