"""Replicated solve fleet (pydcop_tpu.serve.fleet / router).

Contracts pinned here:

* **signature routing**: jobs place by compile-cache routing key —
  warm replicas win, load spills past one bucket's worth of queue,
  down/stalled/partitioned replicas are skipped;
* **failover re-seating** (acceptance pin): with ``kill_replica``
  injected mid-trace, every in-flight job of the dead replica
  completes on a peer with results bit-identical to an unfailed
  standalone solve, the RTO lands finite, and the re-seat admissions
  pay ZERO new cache misses (the peer prewarms the exact re-seat
  signature first — the PR 10 prewarm-hook fix);
* **stall != death**: a stale heartbeat routes traffic around a
  replica and heals when it resumes — its jobs are never re-seated;
* **journal handoff edges**: a kill between a lane's checkpoint and
  its ``JID:`` completion line re-runs the job exactly once (never
  double-completes), stale ``JID:`` lines left by a mid-compaction
  crash are harmless, and glued/unterminated lines in the streamed
  fleet journal are skipped and counted;
* **provenance**: every result's ``metrics()["serve"]`` names the
  replica/JID that served it (and survives re-seats), and the
  ServeCounters summary carries the replica label;
* **fleet admission control**: the aggregate pending bound and the
  fleet-wide tenant quota reject with structured, retry-after-carrying
  errors.

Tests drive :meth:`SolveFleet.tick` synchronously (no threads), so
kill timing — "the fault lands while the doomed replica holds
checkpointed in-flight work" — is deterministic.
"""
import json
import os

import pytest

from pydcop_tpu.batch.cache import CompileCache
from pydcop_tpu.batch.engine import BatchItem, adapter_for
from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.faults import Fault, FaultPlan
from pydcop_tpu.serve import (
    FleetJournal,
    FleetRouter,
    ServiceOverloaded,
    SolveFleet,
    SolveService,
    job_routing_key,
)

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")

#: cycle ceiling: a multiple of the harness chunk (7), like the
#: single-service tests
LIMIT = 63


def _load():
    return load_dcop_from_file([TUTO])


def _standalone(dcop, algo, seed, params=None):
    spec = adapter_for(algo).build_spec(
        BatchItem(dcop, algo, algo_params=params, seed=seed)
    )
    return spec.solver.run(max_cycles=LIMIT)


def _drain(fleet, max_ticks=400):
    for _ in range(max_ticks):
        if not fleet.tick():
            return
    raise AssertionError("fleet did not drain")


class TestRouter:
    def test_warm_replica_wins_placement(self):
        r = FleetRouter()
        r.add_replica("a")
        r.add_replica("b")
        r.note_warm("b", ("k",))
        name, warm = r.place(("k",))
        assert name == "b" and warm

    def test_cold_key_goes_least_loaded_and_sticks(self):
        r = FleetRouter()
        r.add_replica("a")
        r.add_replica("b")
        r.job_placed("a")  # a carries existing load
        name, warm = r.place(("k",))
        assert name == "b" and not warm
        # the family now sticks to b (co-located bucketing)
        name2, warm2 = r.place(("k",))
        assert name2 == "b" and warm2

    def test_spill_past_one_bucket_of_queue(self):
        r = FleetRouter(spill_load=2)
        r.add_replica("a")
        r.add_replica("b")
        placements = [r.place(("k",))[0] for _ in range(4)]
        # a takes the first two (warm affinity), then spills to b
        assert placements[:2] == ["a", "a"]
        assert "b" in placements[2:]

    def test_down_stalled_partitioned_skipped(self):
        r = FleetRouter()
        for n in ("a", "b", "c", "d"):
            r.add_replica(n)
        r.mark_down("a")
        r.set_stalled("b", True)
        r.set_partitioned("c", True)
        assert r.routable() == ["d"]
        assert r.place(("k",))[0] == "d"
        r.set_stalled("b", False)
        assert set(r.routable()) == {"b", "d"}
        r.mark_down("d")
        r.mark_down("b")
        assert r.place(("k",)) is None

    def test_exclude_bars_the_dead_replica(self):
        r = FleetRouter()
        r.add_replica("a")
        r.add_replica("b")
        r.note_warm("a", ("k",))
        assert r.place(("k",), exclude="a")[0] == "b"

    def test_routing_key_matches_cache_key_prefix(self):
        """The routing key is the leading fields of the runner cache
        key the job's bucket will resolve to — same algo/params-key and
        the spec's family_key, with NO tensor compilation needed."""
        from pydcop_tpu.batch.engine import _params_key

        dcop = _load()
        key = job_routing_key(dcop, "mgm", {})
        spec = adapter_for("mgm").build_spec(
            BatchItem(dcop, "mgm", seed=0)
        )
        assert key == (
            ("mgm", _params_key({})) + spec.dims.family_key
        )


class TestFleetEndToEnd:
    def test_jobs_complete_bit_identical_with_provenance(self):
        """Two replicas, four jobs: every result equals its standalone
        solve exactly, and metrics()['serve'] names the replica + JID
        that served it (satellite: auditable failover paths)."""
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT)
        jids = [fleet.submit(dcop, "mgm", seed=s) for s in range(4)]
        _drain(fleet)
        for s, jid in enumerate(jids):
            res = fleet.result(jid, timeout=1)
            seq = _standalone(dcop, "mgm", s)
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle
            assert res.cost == seq.cost
            serve = res.metrics()["serve"]
            assert serve["jid"] == jid
            assert serve["replica"] in ("replica-0", "replica-1")
            assert serve["reseats"] == 0
        m = fleet.metrics()
        assert m["fleet"]["jobs_routed"] == 4
        # the replica label rides each replica's counters summary too
        assert (
            m["replicas"]["replica-0"]["serve"]["replica"]
            == "replica-0"
        )

    def test_standalone_service_metrics_carry_replica_field(self):
        """The ServeCounters summary always has the replica field —
        None for a standalone service, the name for a fleet replica."""
        dcop = _load()
        svc = SolveService(lanes=1, cache=CompileCache(),
                           max_cycles=LIMIT)
        jid = svc.submit(dcop, "mgm", seed=0)
        for _ in range(80):
            if not svc.tick():
                break
        res = svc.result(jid, timeout=1)
        assert svc.metrics()["serve"]["replica"] is None
        assert res.metrics()["serve"]["replica"] is None
        assert res.metrics()["serve"]["jid"] == jid

    def test_same_family_co_locates(self):
        """Same-signature traffic lands on the replica that is already
        warm for it (the routing tentpole) — all four jobs on one
        replica, three of the four placements warm."""
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=4, max_cycles=LIMIT)
        for s in range(4):
            fleet.submit(dcop, "mgm", seed=s)
        _drain(fleet)
        m = fleet.metrics()
        assert m["fleet"]["jobs_routed_warm"] == 3
        loads = [
            r["serve"]["jobs_admitted"]
            for r in m["replicas"].values()
        ]
        assert sorted(loads) == [0, 4]

    def test_prewarm_distributes_families(self):
        """Fleet prewarm assigns each routing-key group to a replica
        round-robin; arrivals then route onto their warm replica."""
        from pydcop_tpu.generators import generate_graph_coloring

        col = _load()  # binary constraints
        tri = generate_graph_coloring(
            n_variables=8, n_colors=3, n_edges=16, soft=True,
            n_agents=1, seed=4,
        )
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT)
        spread = fleet.prewarm(
            [(col, "mgm"), (tri, "dsa")], block=True
        )
        assert sum(spread.values()) == 2  # two families prewarmed
        a = fleet.submit(col, "mgm", seed=0)
        b = fleet.submit(tri, "dsa", seed=0)
        _drain(fleet)
        assert fleet.metrics()["fleet"]["jobs_routed_warm"] == 2
        ra, rb = fleet.result(a, timeout=1), fleet.result(b, timeout=1)
        # the two families ended on the two different replicas
        assert (
            ra.metrics()["serve"]["replica"]
            != rb.metrics()["serve"]["replica"]
        )


class TestFailover:
    def _run_kill(self, tmp_path, algo="dsa", jobs=4, kill_tick=3):
        dcop = _load()
        jd = str(tmp_path / "fleet")
        plan = FaultPlan(faults=[Fault(
            kind="kill_replica", replica=0, cycle=kill_tick,
        )])
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT,
                           journal_dir=jd, checkpoint_every=1,
                           fault_plan=plan)
        jids = [fleet.submit(dcop, algo, seed=s) for s in range(jobs)]
        _drain(fleet)
        return dcop, fleet, jids

    def test_kill_replica_reseats_bit_identical(self, tmp_path):
        """Acceptance pin: kill one of two replicas while its lanes
        hold checkpointed mid-flight jobs; every job completes on the
        peer, bit-identical to an unfailed standalone run, with a
        finite recovery-time objective and checkpoint re-seats
        actually used (not cold restarts)."""
        dcop, fleet, jids = self._run_kill(tmp_path)
        m = fleet.metrics()
        assert m["fleet"]["replicas_down"] == 1
        assert m["fleet"]["faults_injected"] == 1
        assert m["fleet"]["jobs_reseated"] >= 1
        assert m["fleet"]["reseat_checkpoint_hits"] >= 1
        assert m["fleet"]["recoveries_completed"] == 1
        [rec] = m["recoveries"]
        assert rec["rto_s"] is not None and rec["rto_s"] > 0
        assert rec["pending"] == []
        reseated = 0
        for s, jid in enumerate(jids):
            res = fleet.result(jid, timeout=1)
            seq = _standalone(dcop, "dsa", s)
            assert res.status == "FINISHED"
            assert res.assignment == seq.assignment, (jid, s)
            assert res.cycle == seq.cycle, (jid, s)
            assert res.cost == seq.cost, (jid, s)
            serve = res.metrics()["serve"]
            # everything ends on the survivor: jobs that load-spilled
            # there before the kill show reseats 0, the orphans 1
            assert serve["replica"] == "replica-1"
            reseated += serve["reseats"]
        assert reseated == m["fleet"]["jobs_reseated"]

    def test_reseat_admission_pays_zero_new_cache_misses(
        self, tmp_path
    ):
        """Satellite pin: the peer prewarms the exact re-seat
        signature BEFORE the orphaned jobs are re-submitted, so every
        compile miss on the peer happened at prewarm time — the
        failover admission path itself is all cache hits.  Two jobs:
        both co-locate on replica-0 (no load spill), so the peer's
        cache is UNTOUCHED until the re-seat."""
        _dcop, fleet, _jids = self._run_kill(tmp_path, jobs=2)
        peer = fleet.handle(1).service.cache.stats()
        assert peer["misses"] >= 1
        assert peer["misses"] == peer["prewarmed"]
        assert peer["hits"] >= 1

    def test_fleet_journal_streams_the_handoff(self, tmp_path):
        """The fleet journal records placement, replica lifecycle,
        re-seat and completion for every job — and exactly ONE done
        record per jid (no double-complete)."""
        _dcop, fleet, jids = self._run_kill(tmp_path)
        records, torn = fleet.journal.load()
        assert torn == 0
        kinds = [r["kind"] for r in records]
        assert kinds.count("job") == len(jids)
        assert "reseat" in kinds
        downs = [r for r in records if r["kind"] == "replica"
                 and r["event"] == "down"]
        assert [d["name"] for d in downs] == ["replica-0"]
        for jid in jids:
            dones = [r for r in records
                     if r["kind"] == "done" and r["jid"] == jid]
            assert len(dones) == 1, jid
            assert dones[0]["replica"] == "replica-1"

    def test_kill_between_checkpoint_and_jid_line_reruns_once(
        self, tmp_path
    ):
        """Satellite pin: a kill landing AFTER a lane checkpointed but
        BEFORE its JID: completion line means the job must re-run (from
        the checkpoint) and complete exactly once — re-seated, not
        double-completed, and not dropped."""
        dcop = _load()
        jd = str(tmp_path / "fleet")
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT,
                           journal_dir=jd, checkpoint_every=1)
        jid = fleet.submit(dcop, "dsa", seed=0)
        fleet.tick()
        fleet.tick()  # checkpointed at two chunk boundaries, not done
        h0 = fleet.handle(0)
        assert os.path.exists(h0.checkpoint_path(jid))
        assert jid not in h0.done_jids()  # no JID: line yet
        assert not fleet._jobs[jid].done.is_set()
        h0.kill()
        _drain(fleet)
        res = fleet.result(jid, timeout=1)
        seq = _standalone(dcop, "dsa", 0)
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle
        m = fleet.metrics()
        assert m["fleet"]["jobs_reseated"] == 1
        assert m["fleet"]["reseat_checkpoint_hits"] == 1
        records, _ = fleet.journal.load()
        dones = [r for r in records if r["kind"] == "done"]
        assert len(dones) == 1 and dones[0]["jid"] == jid

    def test_job_done_on_disk_is_never_rerun(self, tmp_path):
        """The other side of the same edge: a job whose JID: line
        reached the dead replica's disk is DONE — the re-seat pass
        must skip it even though the replica died."""
        dcop = _load()
        jd = str(tmp_path / "fleet")
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT,
                           journal_dir=jd, checkpoint_every=1)
        a = fleet.submit(dcop, "mgm", seed=0)
        _drain(fleet)  # a completes on replica-0, JID line on disk
        h0 = fleet.handle(0)
        assert a in h0.done_jids()
        b = fleet.submit(dcop, "mgm", seed=1)
        fleet.tick()  # b mid-flight on the warm replica-0
        h0.kill()
        _drain(fleet)
        m = fleet.metrics()
        assert m["fleet"]["jobs_reseated"] == 1  # only b
        assert fleet.result(b, timeout=1).status == "FINISHED"
        records, _ = fleet.journal.load()
        assert len([r for r in records if r["kind"] == "done"
                    and r["jid"] == a]) == 1

    def test_mid_compaction_kill_leaves_harmless_stale_lines(
        self, tmp_path
    ):
        """Satellite pin: a replica killed between compaction's two
        atomic renames leaves jobs.jsonl compacted but progress_serve
        still holding JID: lines for records no longer journaled —
        stale-but-harmless by design.  The fleet re-seat (and a
        single-service resume) must re-run exactly the truly
        unfinished jobs and ignore the stale completions."""
        dcop = _load()
        jd = str(tmp_path / "fleet")
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT,
                           journal_dir=jd, checkpoint_every=1)
        a = fleet.submit(dcop, "mgm", seed=0)
        _drain(fleet)
        assert fleet.result(a, timeout=1).status == "FINISHED"
        h0 = fleet.handle(0)
        # replica-0's journal auto-compacted a away on completion?  No:
        # compaction is size-triggered — force the mid-compaction
        # crash state by compacting jobs.jsonl and RE-APPENDING the
        # stale JID line (rename 1 done, rename 2 lost)
        h0.service.compact_journal()
        with open(os.path.join(h0.journal_dir, "progress_serve"),
                  "a", encoding="utf-8") as f:
            f.write(f"JID: {a}\n")  # the stale completion line
        b = fleet.submit(dcop, "dsa", seed=1)
        fleet.tick()
        fleet.tick()
        h0.kill()
        _drain(fleet)
        res = fleet.result(b, timeout=1)
        seq = _standalone(dcop, "dsa", 1)
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle
        # exactly the one unfinished job re-seated; the stale line
        # neither resurrected a done job nor blocked the live one
        assert fleet.metrics()["fleet"]["jobs_reseated"] == 1

    def test_scheduler_death_reseats_instead_of_erroring(self):
        """A replica whose SCHEDULER dies (tick supervisor exhausted)
        is a replica loss, not a job failure: the service-side ERROR
        completions are ignored by the fleet tap and the supervisor
        re-seats the jobs on a peer, which completes them
        bit-identically."""
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT)
        jid = fleet.submit(dcop, "mgm", seed=0)
        fleet.tick()  # mid-flight on replica-0
        h0 = fleet.handle(0)
        h0.service._scheduler_died(RuntimeError("tick kept throwing"))
        _drain(fleet)
        res = fleet.result(jid, timeout=1)
        seq = _standalone(dcop, "mgm", 0)
        assert res.status == "FINISHED"
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle
        m = fleet.metrics()
        assert m["fleet"]["replicas_down"] == 1
        assert m["fleet"]["jobs_reseated"] == 1

    def test_all_replicas_down_fails_loudly(self, tmp_path):
        """Losing every replica ends the job in a terminal structured
        ERROR (the re-seat finds no routable peer) — a caller blocked
        on result() gets an answer, never a hang."""
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT)
        jid = fleet.submit(dcop, "mgm", seed=0)
        fleet.handle(0).kill()
        fleet.handle(1).kill()
        for _ in range(10):
            fleet.tick()
        res = fleet.result(jid, timeout=1)
        assert res.status == "ERROR"
        assert res.metrics()["serve"]["error"]  # names the cause
        # and NEW submissions are refused loudly
        from pydcop_tpu.serve import ServiceStopped

        with pytest.raises(ServiceStopped):
            fleet.submit(dcop, "mgm", seed=1)


class TestStallAndPartition:
    def test_stale_heartbeat_routes_around_then_heals(self):
        """Stall != death: a stale heartbeat makes the replica
        unroutable (new traffic goes to peers, nothing re-seats); a
        fresh heartbeat heals it."""
        import time as _time

        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT,
                           heartbeat_timeout=1.0)
        # heartbeats only arm in threaded mode; fake it tick-driven
        fleet._started = True
        h0 = fleet.handle(0)
        h1 = fleet.handle(1)
        for h in (h0, h1):
            h.hb_path = str(h.name) + ".hb"
        try:
            for h in (h0, h1):
                with open(h.hb_path, "w"):
                    pass
            old = _time.time() - 60
            os.utime(h0.hb_path, (old, old))  # h0 wedged
            fleet._supervise()
            assert h0.stalled
            assert fleet.router.routable() == ["replica-1"]
            assert fleet.metrics()["fleet"]["replicas_stalled"] == 1
            # nothing was re-seated: a stall is not a death
            assert fleet.metrics()["fleet"]["jobs_reseated"] == 0
            jid = fleet.submit(dcop, "mgm", seed=0)
            with open(h0.hb_path, "a"):
                os.utime(h0.hb_path, None)  # h0 recovers
            fleet._supervise()
            assert not h0.stalled
            assert fleet.metrics()["fleet"]["replicas_healed"] == 1
            _drain(fleet)
            res = fleet.result(jid, timeout=1)
            assert res.metrics()["serve"]["replica"] == "replica-1"
        finally:
            for h in (h0, h1):
                if os.path.exists(h.hb_path):
                    os.unlink(h.hb_path)

    def test_partition_bars_new_placements_until_heal(self):
        """partition_replica: the replica takes no NEW jobs while
        partitioned but its in-flight work keeps running; the
        partition heals after its duration."""
        dcop = _load()
        plan = FaultPlan(faults=[Fault(
            kind="partition_replica", replica=0, cycle=2,
            duration=1e-6,  # heals on the next supervisor pass
        )])
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT,
                           fault_plan=plan)
        a = fleet.submit(dcop, "mgm", seed=0)  # lands on replica-0
        fleet.tick()  # tick 1: a admitted on replica-0
        fleet.tick()  # tick 2: partition fires
        assert fleet.router.routable() == ["replica-1"]
        b = fleet.submit(dcop, "mgm", seed=1)  # must avoid replica-0
        _drain(fleet)
        m = fleet.metrics()
        assert m["fleet"]["replicas_partitioned"] == 1
        assert m["fleet"]["replicas_healed"] == 1
        ra, rb = fleet.result(a, timeout=1), fleet.result(b, timeout=1)
        assert ra.metrics()["serve"]["replica"] == "replica-0"
        assert rb.metrics()["serve"]["replica"] == "replica-1"
        seq = _standalone(dcop, "mgm", 0)
        assert ra.assignment == seq.assignment

    def test_stall_replica_fault_wedges_one_tick(self):
        """stall_replica wires through the injector: the target
        replica's next tick sleeps `duration` (heartbeat stale from
        outside); jobs still complete correctly afterwards."""
        from time import monotonic

        dcop = _load()
        plan = FaultPlan(faults=[Fault(
            kind="stall_replica", replica=0, cycle=2, duration=0.05,
        )])
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT,
                           fault_plan=plan)
        jid = fleet.submit(dcop, "mgm", seed=0)
        t0 = monotonic()
        _drain(fleet)
        assert monotonic() - t0 >= 0.05  # the wedge really happened
        assert fleet.metrics()["fleet"]["faults_injected"] == 1
        seq = _standalone(dcop, "mgm", 0)
        res = fleet.result(jid, timeout=1)
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle


class TestFleetAdmission:
    def test_aggregate_pending_bound(self):
        """max_pending aggregates across routable replicas into ONE
        fleet bound; a submit past it sheds with a structured
        retry-after-carrying overload error."""
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT,
                           max_pending=1)
        fleet.submit(dcop, "mgm", seed=0)
        fleet.submit(dcop, "mgm", seed=1)
        with pytest.raises(ServiceOverloaded) as ei:
            fleet.submit(dcop, "mgm", seed=2)
        assert ei.value.retry_after > 0
        assert fleet.metrics()["fleet"]["jobs_shed"] == 1
        _drain(fleet)

    def test_bound_shrinks_when_a_replica_dies(self):
        """A degraded fleet sheds earlier: with one of two replicas
        down, the aggregate bound halves."""
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=1, max_cycles=LIMIT,
                           max_pending=1)
        fleet.handle(1).kill()
        fleet.tick()  # supervisor notices the death
        fleet.submit(dcop, "mgm", seed=0)
        with pytest.raises(ServiceOverloaded):
            fleet.submit(dcop, "mgm", seed=1)
        _drain(fleet)

    def test_fleet_tenant_quota(self):
        dcop = _load()
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT,
                           tenant_quota=1)
        fleet.submit(dcop, "mgm", seed=0, tenant="t1")
        with pytest.raises(ServiceOverloaded):
            fleet.submit(dcop, "mgm", seed=1, tenant="t1")
        # another tenant is unaffected
        fleet.submit(dcop, "mgm", seed=2, tenant="t2")
        assert fleet.metrics()["fleet"]["quota_rejections"] == 1
        _drain(fleet)


class TestFleetJournalEdges:
    def test_glued_and_unterminated_lines_skipped_and_counted(
        self, tmp_path
    ):
        """Satellite pin: the streamed fleet journal tolerates the
        same damage the per-replica journals do — a glued double-line
        fragment and an append cut short are skipped and counted,
        never fatal."""
        path = str(tmp_path / "fleet.jsonl")
        j = FleetJournal(path)
        j.append({"kind": "job", "jid": "job-000001"})
        j.append({"kind": "done", "jid": "job-000001"})
        with open(path, "a", encoding="utf-8") as f:
            # a torn append glued to the next record: one unparseable
            # merged line
            f.write('{"kind": "job", "ji{"kind": "done", "jid": "x"}\n')
            # and a final append cut short mid-record, no newline
            f.write('{"kind": "job", "jid": "job-0000')
        records, torn = j.load()
        assert [r["kind"] for r in records] == ["job", "done"]
        assert torn == 2

    def test_load_missing_and_empty(self, tmp_path):
        j = FleetJournal(str(tmp_path / "nope.jsonl"))
        assert j.load() == ([], 0)
        open(j.path, "w").close()
        assert j.load() == ([], 0)

    def test_non_record_json_counts_torn(self, tmp_path):
        j = FleetJournal(str(tmp_path / "fleet.jsonl"))
        with open(j.path, "w", encoding="utf-8") as f:
            f.write('[1, 2]\n{"no_kind": true}\n')
        records, torn = j.load()
        assert records == [] and torn == 2


class TestResumePrewarm:
    def test_resume_prewarms_reseat_signatures(self, tmp_path):
        """Satellite pin (single service): resume() warms the exact
        re-seat targets BEFORE re-queueing, so the admission path pays
        zero new cache misses — every miss on the fresh cache happened
        inside resume()'s blocking prewarm."""
        dcop = _load()
        jd = str(tmp_path / "journal")
        svc1 = SolveService(lanes=2, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd,
                            checkpoint_every=1)
        a = svc1.submit(dcop, "dsa", seed=0, source_file=TUTO)
        b = svc1.submit(dcop, "dsa", seed=1, source_file=TUTO)
        svc1.tick()
        svc1.tick()  # checkpointed mid-flight
        assert not svc1._jobs[a].done.is_set()
        del svc1  # crash

        cache = CompileCache()
        svc2 = SolveService(lanes=2, cache=cache, max_cycles=LIMIT,
                            journal_dir=jd, checkpoint_every=1)
        assert svc2.resume() == 2
        misses_at_resume = cache.stats()["misses"]
        assert misses_at_resume >= 1  # the prewarm compiled something
        assert cache.stats()["prewarmed"] == misses_at_resume
        for _ in range(120):
            if not svc2.tick():
                break
        # ZERO new cache misses after resume() returned
        assert cache.stats()["misses"] == misses_at_resume
        for jid, seed in ((a, 0), (b, 1)):
            res = svc2.result(jid, timeout=1)
            seq = _standalone(dcop, "dsa", seed)
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle

    def test_resume_prewarm_optional(self, tmp_path):
        """resume(prewarm=False) keeps the old lazy behavior."""
        dcop = _load()
        jd = str(tmp_path / "journal")
        svc1 = SolveService(lanes=1, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd,
                            checkpoint_every=1)
        svc1.submit(dcop, "mgm", seed=0, source_file=TUTO)
        svc1.tick()
        del svc1
        cache = CompileCache()
        svc2 = SolveService(lanes=1, cache=cache, max_cycles=LIMIT,
                            journal_dir=jd)
        assert svc2.resume(prewarm=False) == 1
        assert cache.stats()["misses"] == 0  # nothing compiled yet


class TestFleetEvents:
    def test_fleet_lifecycle_events_emitted(self, tmp_path):
        from pydcop_tpu.runtime.events import event_bus

        dcop = _load()
        seen = []
        cb = lambda topic, evt: seen.append(topic)  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("fleet.*", cb)
        try:
            plan = FaultPlan(faults=[Fault(
                kind="kill_replica", replica=0, cycle=3,
            )])
            fleet = SolveFleet(replicas=2, lanes=2, max_cycles=LIMIT,
                               journal_dir=str(tmp_path / "f"),
                               checkpoint_every=1, fault_plan=plan)
            jid = fleet.submit(dcop, "dsa", seed=0)
            _drain(fleet)
            fleet.result(jid, timeout=1)
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        for expected in ("fleet.replica.up", "fleet.router.placed",
                         "fleet.fault.injected", "fleet.replica.down",
                         "fleet.job.reseated", "fleet.recovery.done"):
            assert expected in seen, (expected, sorted(set(seen)))

    def test_unknown_fleet_counter_rejected(self):
        from pydcop_tpu.runtime.stats import FleetCounters

        with pytest.raises(KeyError):
            FleetCounters().inc("nope")

    def test_fleet_fault_kinds_validate(self):
        with pytest.raises(ValueError, match="needs a 'replica'"):
            Fault(kind="kill_replica")
        with pytest.raises(ValueError, match="duration"):
            Fault(kind="stall_replica", replica=0)
        f = Fault(kind="partition_replica", replica=1, duration=0.5)
        rt = Fault(**{k: v for k, v in f.to_dict().items()})
        assert rt == f
        plan = FaultPlan(faults=[f])
        assert plan.fleet_faults() == [f]
        assert plan.serve_faults() == []
        # round-trips through the env/json channel like every kind
        assert FaultPlan.from_json(plan.to_json()).fleet_faults() == [f]
