"""Dynamic DCOP scenarios driving maxsum_dynamic's factor hot-swap
(VERDICT item 6: hot-swap through a scenario via `pydcop_tpu run`).

Reference twin: DynamicFactorComputation.change_factor_function
(pydcop/algorithms/maxsum_dynamic.py:188) — here the swap arrives as a
`change_factor` scenario event handled by the VirtualOrchestrator.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.dcop import load_dcop
from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario
from pydcop_tpu.dcop.yamldcop import load_scenario
from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

REPO = os.path.join(os.path.dirname(__file__), "..", "..")

# two variables preferring equality; the swap flips the factor to
# prefer INEQUALITY — the solver must follow
DCOP_YAML = textwrap.dedent("""
    name: swap_test
    objective: min
    domains:
      d: {values: [0, 1]}
    variables:
      v1: {domain: d}
      v2: {domain: d}
      v3: {domain: d}
    constraints:
      prefer:
        type: intention
        function: "0 if v1 == v2 else 10"
      tie:
        type: intention
        function: "0 if v2 == v3 else 1"
      anchor:
        type: intention
        function: "v1 * 2"
    agents: [a1, a2, a3, a4, a5, a6]
""")

SWAPPED_EXPR = "0 if v1 != v2 else 10"


def orch_for(dcop, algo="maxsum_dynamic"):
    algo_def = AlgorithmDef.build_with_default_params(
        algo, {}, mode=dcop.objective
    )
    orch = VirtualOrchestrator(dcop, algo_def)
    orch.deploy_computations()
    return orch


def test_change_factor_scenario_flips_solution():
    dcop = load_dcop(DCOP_YAML)
    scenario = Scenario([
        DcopEvent("d1", delay=0.5),
        DcopEvent("e1", actions=[EventAction(
            "change_factor", constraint="prefer",
            expression=SWAPPED_EXPR,
        )]),
        DcopEvent("d2", delay=0.5),
    ])
    orch = orch_for(dcop)
    res = orch.run(scenario, cycles=15)
    assert res.status == "FINISHED"
    # after the swap, v1 != v2 is optimal (anchor keeps v1 at 0)
    assert res.assignment["v1"] != res.assignment["v2"]
    # the swapped constraint is live in the dcop too
    assert dcop.constraints["prefer"](0, 0) == 10
    assert dcop.constraints["prefer"](0, 1) == 0


def test_change_factor_without_swap_keeps_equality():
    dcop = load_dcop(DCOP_YAML)
    orch = orch_for(dcop)
    res = orch.run(Scenario([DcopEvent("d1", delay=0.5)]), cycles=15)
    assert res.assignment["v1"] == res.assignment["v2"]


def test_change_factor_rejected_for_static_algorithms():
    dcop = load_dcop(DCOP_YAML)
    orch = orch_for(dcop, algo="maxsum")
    scenario = Scenario([
        DcopEvent("e1", actions=[EventAction(
            "change_factor", constraint="prefer",
            expression=SWAPPED_EXPR,
        )]),
    ])
    with pytest.raises(ValueError, match="maxsum_dynamic"):
        orch.run(scenario, cycles=5)


def test_scenario_yaml_roundtrip_change_factor():
    yaml_str = textwrap.dedent(f"""
        events:
          - id: d1
            delay: 0.5
          - id: e1
            actions:
              - type: change_factor
                constraint: prefer
                expression: "{SWAPPED_EXPR}"
    """)
    scenario = load_scenario(yaml_str)
    assert len(scenario) == 2
    ev = scenario.events[1]
    assert ev.actions[0].type == "change_factor"
    assert ev.actions[0].parameters["expression"] == SWAPPED_EXPR


def test_cli_run_with_change_factor_scenario(tmp_path):
    """`pydcop_tpu run -a maxsum_dynamic -s scenario.yaml` end-to-end."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,  # drop axon sitecustomize so cpu sticks
    }
    dcop_f = tmp_path / "prob.yaml"
    dcop_f.write_text(DCOP_YAML)
    scen_f = tmp_path / "scen.yaml"
    scen_f.write_text(textwrap.dedent(f"""
        events:
          - id: d1
            delay: 0.3
          - id: e1
            actions:
              - type: change_factor
                constraint: prefer
                expression: "{SWAPPED_EXPR}"
          - id: d2
            delay: 0.3
    """))
    out = subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", "--timeout", "120", "run",
         "--algo", "maxsum_dynamic", "-s", str(scen_f), str(dcop_f)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    data = json.loads(out.stdout)
    assert data["assignment"]["v1"] != data["assignment"]["v2"]


def test_change_factor_scope_order_preserved():
    """The swapped-in constraint may list the same scope in a different
    order (constraint_from_str sorts by name); the tensor must be
    realigned to the bucket slot's axis order, not written transposed."""
    import numpy as np

    from pydcop_tpu.algorithms.maxsum_dynamic import build_solver
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    dcop = DCOP("t", objective="min")
    d2 = Domain("d2", "v", [0, 1])
    d3 = Domain("d3", "v", [0, 1, 2])
    va, vb = Variable("va", d2), Variable("vb", d3)
    dcop.add_variable(va)
    dcop.add_variable(vb)
    # original order [va, vb]: shape (2, 3)
    m = np.arange(6, dtype=float).reshape(2, 3)
    dcop.add_constraint(NAryMatrixRelation([va, vb], m, name="c"))
    dcop.add_agents([AgentDef("a")])
    solver = build_solver(dcop)
    # swap with REVERSED scope order [vb, va]: shape (3, 2); an asym
    # table makes a transposed write detectable through the solve
    m2 = np.array([[0.0, 9], [9, 9], [9, 0]])  # prefers (0,0) or (2,1)
    solver.change_factor_function(
        NAryMatrixRelation([vb, va], m2, name="c")
    )
    res = solver.run(cycles=20)
    pair = (res.assignment["vb"], res.assignment["va"])
    assert pair in ((0, 0), (2, 1)), res.assignment
    assert res.cost == pytest.approx(0.0)
    # wrong-order scope must be rejected loudly
    other = Variable("vc", d3)
    dcop.add_variable(other)
    with pytest.raises(ValueError, match="scope"):
        solver.change_factor_function(
            NAryMatrixRelation([va, other], np.zeros((2, 3)), name="c")
        )


def test_change_factor_unknown_constraint_fails_loudly():
    dcop = load_dcop(DCOP_YAML)
    orch = orch_for(dcop)
    scenario = Scenario([
        DcopEvent("e1", actions=[EventAction(
            "change_factor", constraint="nope", expression="0",
        )]),
    ])
    with pytest.raises(ValueError, match="unknown constraint"):
        orch.run(scenario, cycles=5)


def test_external_change_scenario():
    """set_external events re-slice factors that read a sensor variable
    (reference: FactorWithReadOnlyVariableComputation)."""
    yaml_str = textwrap.dedent("""
        name: ext_test
        objective: min
        domains:
          d: {values: [0, 1]}
        variables:
          v1: {domain: d}
        external_variables:
          sensor: {domain: d, initial_value: 0}
        constraints:
          follow:
            type: intention
            function: "0 if v1 == sensor else 5"
        agents: [a1, a2]
    """)
    dcop = load_dcop(yaml_str)
    orch = orch_for(dcop)
    scenario = Scenario([
        DcopEvent("d1", delay=0.3),
        DcopEvent("e1", actions=[EventAction(
            "set_external", variable="sensor", value=1,
        )]),
        DcopEvent("d2", delay=0.3),
    ])
    res = orch.run(scenario, cycles=10)
    assert res.assignment["v1"] == 1  # follows the sensor to its new value
