"""Algorithm-level unit tests: drive solver cycles directly with crafted
states (reference twin: tests/unit/test_algorithms_*.py drive handlers with
mocks, e.g. test_algorithms_dpop.py:80-148)."""
import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.dcop import DCOP, Domain, NAryMatrixRelation, Variable


def chain_dcop():
    """v0 - v1 - v2 chain, equality penalized by 10."""
    d = Domain("d", "d", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(3)]
    dcop = DCOP("chain")
    for i in range(2):
        m = np.where(np.eye(3, dtype=bool), 10.0, 0.0)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], m, f"c{i}")
        )
    return dcop


def pair_trap_dcop():
    """Two variables where only a coordinated flip escapes the minimum:
    cost(0,0)=5, cost(1,1)=0, cost(0,1)=cost(1,0)=20."""
    d = Domain("d", "d", [0, 1])
    x, y = Variable("x", d), Variable("y", d)
    dcop = DCOP("trap")
    dcop.add_constraint(
        NAryMatrixRelation([x, y], [[5.0, 20.0], [20.0, 0.0]], "c")
    )
    return dcop


class TestMgmCycle:
    def test_only_max_gain_moves(self):
        from pydcop_tpu.algorithms.mgm import build_solver

        dcop = chain_dcop()
        solver = build_solver(dcop)
        # all equal (0,0,0): v1 gains 20 by moving, v0/v2 gain 10
        x = jnp.array([0, 0, 0], dtype=jnp.int32)
        (x2,) = solver.cycle((x,), jax.random.PRNGKey(0))
        x2 = np.asarray(x2)
        assert x2[1] != 0  # the max-gain variable moved
        assert x2[0] == 0 and x2[2] == 0  # neighbors of the winner held

    def test_stable_at_optimum(self):
        from pydcop_tpu.algorithms.mgm import build_solver

        dcop = chain_dcop()
        solver = build_solver(dcop)
        x = jnp.array([0, 1, 0], dtype=jnp.int32)  # cost 0: no move
        (x2,) = solver.cycle((x,), jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(x2), [0, 1, 0])

    def test_lexic_tie_break(self):
        """Equal gains: the lower-index variable wins."""
        from pydcop_tpu.algorithms.mgm import build_solver

        d = Domain("d", "d", [0, 1])
        a, b = Variable("a", d), Variable("b", d)
        dcop = DCOP("tie")
        dcop.add_constraint(
            NAryMatrixRelation([a, b], [[10.0, 0.0], [0.0, 10.0]], "c")
        )
        solver = build_solver(dcop)
        x = jnp.array([0, 0], dtype=jnp.int32)  # both could gain 10
        (x2,) = solver.cycle((x,), jax.random.PRNGKey(0))
        x2 = np.asarray(x2)
        assert x2[0] == 1 and x2[1] == 0


class TestDsaCycle:
    def test_variant_a_never_moves_laterally(self):
        from pydcop_tpu.algorithms.dsa import build_solver

        d = Domain("d", "d", [0, 1])
        a, b = Variable("a", d), Variable("b", d)
        dcop = DCOP("flat")
        # all assignments cost the same: no strict improvement exists
        dcop.add_constraint(
            NAryMatrixRelation([a, b], [[1.0, 1.0], [1.0, 1.0]], "c")
        )
        algo = AlgorithmDef(
            "dsa", {"probability": 1.0, "variant": "A", "stop_cycle": 0}
        )
        solver = build_solver(dcop, algo_def=algo)
        x = jnp.array([0, 0], dtype=jnp.int32)
        for i in range(5):
            (x,) = solver.cycle((x,), jax.random.PRNGKey(i))
        np.testing.assert_array_equal(np.asarray(x), [0, 0])

    def test_probability_zero_freezes(self):
        from pydcop_tpu.algorithms.dsa import build_solver

        dcop = chain_dcop()
        algo = AlgorithmDef(
            "dsa", {"probability": 0.0, "variant": "B", "stop_cycle": 0}
        )
        solver = build_solver(dcop, algo_def=algo)
        x = jnp.array([0, 0, 0], dtype=jnp.int32)
        (x2,) = solver.cycle((x,), jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(x2), [0, 0, 0])


class TestMgm2Pairs:
    def test_coordinated_escape(self):
        """From (0,0), no unilateral move helps (cost 5 → 20), but the pair
        flip to (1,1) reaches 0 — only MGM-2 can take it."""
        from pydcop_tpu.algorithms.mgm import build_solver as build_mgm
        from pydcop_tpu.algorithms.mgm2 import build_solver as build_mgm2

        dcop = pair_trap_dcop()
        x0 = jnp.array([0, 0], dtype=jnp.int32)

        mgm = build_mgm(dcop)
        (x_mgm,) = mgm.cycle((x0,), jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(x_mgm), [0, 0])  # stuck

        mgm2 = build_mgm2(dcop, algo_def=AlgorithmDef(
            "mgm2", {"threshold": 0.5, "favor": "unilateral",
                     "stop_cycle": 0}))
        # over a few cycles some offer coin flip pairs them up
        x = x0
        for i in range(10):
            (x,) = mgm2.cycle((x,), jax.random.PRNGKey(i))
        np.testing.assert_array_equal(np.asarray(x), [1, 1])


class TestDbaWeights:
    def test_weights_increase_at_quasi_local_minimum(self):
        from pydcop_tpu.algorithms.dba import build_solver

        d = Domain("d", "d", [0, 1])
        a, b = Variable("a", d), Variable("b", d)
        dcop = DCOP("stuck")
        # every assignment violates: weights must grow
        dcop.add_constraint(
            NAryMatrixRelation([a, b], [[1.0, 1.0], [1.0, 1.0]], "c")
        )
        solver = build_solver(dcop)
        x = jnp.array([0, 0], dtype=jnp.int32)
        w = jnp.ones(1, dtype=jnp.float32)
        x2, w2 = solver.cycle((x, w), jax.random.PRNGKey(0))
        assert float(w2[0]) == 2.0


class TestAMaxsumActivation:
    def test_zero_activation_freezes_messages(self):
        from pydcop_tpu.algorithms.amaxsum import build_solver

        dcop = chain_dcop()
        algo = AlgorithmDef(
            "amaxsum",
            {"stop_cycle": 0, "damping": 0.0, "stability": 0.1,
             "noise": 0.0, "activation": 0.0},
        )
        solver = build_solver(dcop, algo_def=algo)
        q, r, v = solver.initial_state()
        q2, r2, _ = solver.cycle((q, r, v), jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(q2), np.asarray(q))
        np.testing.assert_array_equal(np.asarray(r2), np.asarray(r))
