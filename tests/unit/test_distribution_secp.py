"""SECP-specific distribution strategies on a real SECP instance.

The four SECP strategies must behave differently from their generic
twins: actuator variables (hosting_cost == 0) are pinned to their device
agents, cost factors follow them (factor-graph variants), and the ILP
objective is communication-only.
"""
import pytest

from pydcop_tpu.distribution import load_distribution_module
from pydcop_tpu.distribution._costs import distribution_cost
from pydcop_tpu.distribution._secp import secp_comm_cost
from pydcop_tpu.generators import generate_secp
from pydcop_tpu.graph import constraints_hypergraph, factor_graph


def _mem(node):
    return 1.0


def _load(node, target=None):
    return 1.0


@pytest.fixture(scope="module")
def secp():
    return generate_secp(n_lights=4, n_models=2, n_rules=2,
                         light_levels=3, seed=3)


def test_generator_reference_structure(secp):
    # lights l{i} with cost factors c_l{i}, models m{j} with factors
    # c_m{j}, rules — the reference naming scheme
    # (pydcop/commands/generators/secp.py:304-319,201-231)
    assert {"l0", "l1", "l2", "l3", "m0", "m1"} <= set(secp.variables)
    assert {"c_l0", "c_m0", "c_m1", "rule_0"} <= set(secp.constraints)
    a0 = secp.agents["a0"]
    assert a0.hosting_cost("l0") == 0
    assert a0.hosting_cost("c_l0") == 0
    assert a0.hosting_cost("l1") == 100


def test_oilp_secp_fgdp_pins_actuators_and_cost_factors(secp):
    fg = factor_graph.build_computation_graph(secp)
    dist = load_distribution_module("oilp_secp_fgdp").distribute(
        fg, secp.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    assert sorted(dist.computations) == sorted(n.name for n in fg.nodes)
    for i in range(4):
        assert f"l{i}" in dist.computations_hosted(f"a{i}")
        assert f"c_l{i}" in dist.computations_hosted(f"a{i}")
    # every agent hosts at least one computation
    for a in secp.agents:
        assert dist.computations_hosted(a)


def test_oilp_secp_cgdp_pins_actuators(secp):
    cg = constraints_hypergraph.build_computation_graph(secp)
    dist = load_distribution_module("oilp_secp_cgdp").distribute(
        cg, secp.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    assert sorted(dist.computations) == sorted(n.name for n in cg.nodes)
    for i in range(4):
        assert f"l{i}" in dist.computations_hosted(f"a{i}")
    for a in secp.agents:
        assert dist.computations_hosted(a)


def test_oilp_secp_fgdp_differs_from_generic(secp):
    """The SECP ILP must beat (or match) the generic weighted ILP on the
    SECP's own communication-only objective, thanks to actuator pinning
    + comm-only objective."""
    fg = factor_graph.build_computation_graph(secp)
    agents = list(secp.agents.values())
    secp_dist = load_distribution_module("oilp_secp_fgdp").distribute(
        fg, agents, computation_memory=_mem, communication_load=_load,
    )
    generic_dist = load_distribution_module("oilp_cgdp").distribute(
        fg, agents, computation_memory=_mem, communication_load=_load,
    )
    secp_comm = secp_comm_cost(secp_dist, fg, agents, _mem, _load)
    generic_comm = secp_comm_cost(generic_dist, fg, agents, _mem, _load)
    # generic oilp_cgdp weighs hosting costs: with default hosting 100,
    # it is pulled toward agent piling; the SECP model pins actuators
    # first — the placements must differ
    assert secp_dist.mapping() != generic_dist.mapping()
    # and the SECP ILP is optimal for comm among actuator-pinned
    # placements (can't assert global dominance, but must be sane):
    assert secp_comm <= generic_comm + 4.0


def test_gh_secp_fgdp_cohosts_model_pairs(secp):
    fg = factor_graph.build_computation_graph(secp)
    dist = load_distribution_module("gh_secp_fgdp").distribute(
        fg, secp.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    assert sorted(dist.computations) == sorted(n.name for n in fg.nodes)
    # actuators + their cost factors pinned
    for i in range(4):
        assert f"l{i}" in dist.computations_hosted(f"a{i}")
        assert f"c_l{i}" in dist.computations_hosted(f"a{i}")
    # physical model variable and factor are placed as a unit
    for j in range(2):
        assert dist.agent_for(f"m{j}") == dist.agent_for(f"c_m{j}")


def test_gh_secp_fgdp_differs_from_cgdp_variant(secp):
    fg = factor_graph.build_computation_graph(secp)
    agents = list(secp.agents.values())
    fgdp = load_distribution_module("gh_secp_fgdp").distribute(
        fg, agents, computation_memory=_mem, communication_load=_load,
    )
    cgdp = load_distribution_module("gh_secp_cgdp").distribute(
        fg, agents, computation_memory=_mem, communication_load=_load,
    )
    # both host everything...
    assert sorted(fgdp.computations) == sorted(cgdp.computations)
    # ...but the FG variant's model-pair rule gives a different placement
    assert fgdp.mapping() != cgdp.mapping()


def test_secp_ilp_respects_capacity():
    secp = generate_secp(n_lights=3, n_models=1, n_rules=1,
                         light_levels=3, seed=1, capacity=3)
    fg = factor_graph.build_computation_graph(secp)
    dist = load_distribution_module("oilp_secp_fgdp").distribute(
        fg, secp.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    for a in dist.agents:
        assert len(dist.computations_hosted(a)) <= 3


def test_secp_ilp_liveness_with_no_free_comps():
    """ADVICE r2: when nothing is free to host but an agent's pre-mapping
    is empty, the reference ILP's liveness constraints are infeasible —
    we must raise, not return a dead-agent distribution."""
    import pytest as _pytest

    from pydcop_tpu.distribution._secp import secp_ilp
    from pydcop_tpu.distribution.objects import (
        ImpossibleDistributionException,
    )

    class _A:
        def __init__(self, name):
            self.name = name

    agents = [_A("a1"), _A("a2")]
    with _pytest.raises(ImpossibleDistributionException):
        secp_ilp(
            computation_graph=None,
            agents=agents,
            pre_mapping={"a1": ["c1"], "a2": []},
            comps_to_host=[],
            capa={"a1": 10.0, "a2": 10.0},
            computation_memory=None,
            communication_load=None,
        )
