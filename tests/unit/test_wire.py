"""Socket journal wire protocol (pydcop_tpu.serve.wire).

The process fleet's journal discipline, pinned at the frame level
(ISSUE 16 satellite — the edge cases a live fleet only hits under
chaos):

* **torn frame at the kill point**: a ``kill -9`` mid-send leaves a
  partial tail frame — held pending, counted on close, never applied;
* **glued frames**: one recv carrying several frames decodes them all;
* **CRC skip-and-count**: a corrupt payload skips exactly that frame
  (the length prefix preserves resync) and the stream continues;
* **header corruption is fatal for the connection, not the journal**:
  bad magic / absurd length kill the decoder; the sender's replay
  machinery re-delivers on reconnect;
* **replay-from-offset never double-applies**: a completion record
  whose ack was lost with the connection is either dropped at the
  reconnect handshake (the hub's applied high-water mark) or deduped
  by seq — applied exactly once, every interleaving;
* **partition buffering**: frames sent into a partition buffer client-
  side and replay on heal — nothing lost, nothing doubled.
"""
import socket
import threading
import time

import pytest

from pydcop_tpu.serve.wire import (
    MAGIC,
    FrameDecoder,
    JournalClient,
    JournalHub,
    encode_frame,
)


class TestFrameDecoder:
    def test_roundtrip_single_frame(self):
        d = FrameDecoder()
        out = d.feed(encode_frame({"a": 1}))
        assert out == [{"a": 1}]
        assert d.torn == 0

    def test_glued_frames_decode_all(self):
        d = FrameDecoder()
        blob = b"".join(encode_frame({"i": i}) for i in range(5))
        assert d.feed(blob) == [{"i": i} for i in range(5)]

    def test_partial_tail_waits_then_completes(self):
        d = FrameDecoder()
        frame = encode_frame({"x": "y"})
        assert d.feed(frame[:7]) == []
        assert d.feed(frame[7:]) == [{"x": "y"}]
        assert d.torn == 0

    def test_torn_tail_counted_on_close(self):
        """The kill -9 signature: a send cut short mid-frame."""
        d = FrameDecoder()
        frame = encode_frame({"jid": "job-000001", "evt": "complete"})
        d.feed(frame[: len(frame) - 3])
        assert d.close() == 1
        assert d.torn == 1

    def test_crc_mismatch_skips_and_counts_but_resyncs(self):
        d = FrameDecoder()
        bad = bytearray(encode_frame({"n": 1}))
        bad[-1] ^= 0xFF  # corrupt the payload, header intact
        good = encode_frame({"n": 2})
        out = d.feed(bytes(bad) + good)
        assert out == [{"n": 2}]
        assert d.torn == 1
        assert not d.dead

    def test_bad_magic_kills_decoder(self):
        d = FrameDecoder()
        blob = bytearray(encode_frame({"n": 1}))
        assert blob[:2] == MAGIC
        blob[0] ^= 0xFF
        assert d.feed(bytes(blob)) == []
        assert d.dead
        assert d.torn == 1

    def test_absurd_length_kills_decoder(self):
        import struct

        d = FrameDecoder()
        header = struct.Struct("<2sII").pack(MAGIC, 1 << 30, 0)
        d.feed(header)
        assert d.dead

    def test_non_dict_payload_skipped(self):
        import json
        import struct
        import zlib

        payload = json.dumps([1, 2]).encode()
        frame = struct.Struct("<2sII").pack(
            MAGIC, len(payload), zlib.crc32(payload) & 0xFFFFFFFF
        ) + payload
        d = FrameDecoder()
        assert d.feed(frame) == []
        assert d.torn == 1
        assert not d.dead


class _Pump:
    """Background hub pump — the role the fleet supervisor plays."""

    def __init__(self, hub):
        self.hub = hub
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._stop.is_set():
            self.hub.pump(0.01)

    def stop(self):
        self._stop.set()
        self._t.join(timeout=5)


@pytest.fixture
def hub_records():
    records = []

    def on_record(client, body):
        records.append((client, body))

    hub = JournalHub(on_record=on_record)
    pump = _Pump(hub)
    yield hub, records
    pump.stop()
    hub.stop()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


class TestHubClient:
    def test_records_apply_in_order(self, hub_records):
        hub, records = hub_records
        cli = JournalClient(("127.0.0.1", hub.port), "r0")
        assert cli.connect()
        for i in range(4):
            cli.send({"n": i})
        assert _wait(lambda: len(records) == 4)
        assert [b["n"] for _c, b in records] == [0, 1, 2, 3]
        cli.close()

    def test_lost_ack_reconnect_never_double_applies(self, hub_records):
        """THE completion-record pin: the record reaches the hub, the
        connection dies before the client sees the ack, the client
        replays on reconnect — applied exactly once."""
        hub, records = hub_records
        cli = JournalClient(("127.0.0.1", hub.port), "r0")
        assert cli.connect()
        cli.send({"evt": "complete", "jid": "job-000007"})
        assert _wait(lambda: len(records) == 1)
        # the ack is in flight but the client never reads it: the
        # frame is still in its replay buffer when the link dies
        assert len(cli.ep.unacked) == 1
        cli._disconnect()
        assert cli.connect()  # handshake learns hub applied=1
        cli.send({"evt": "after"})
        assert _wait(lambda: len(records) == 2)
        events = [b.get("evt") for _c, b in records]
        assert events == ["complete", "after"]  # never twice
        assert _wait(lambda: hub.stats()["connected"] == ["r0"])

    def test_torn_frame_at_kill_point_counted(self, hub_records):
        """A raw connection killed mid-frame: the hub counts the torn
        tail and applies nothing from it."""
        hub, records = hub_records
        sock = socket.create_connection(("127.0.0.1", hub.port),
                                        timeout=5)
        sock.sendall(encode_frame({"hello": {"client": "torn",
                                             "applied": 0}}))
        frame = encode_frame({"seq": 1,
                              "body": {"evt": "complete",
                                       "jid": "job-000001"}})
        sock.sendall(frame[: len(frame) - 4])
        time.sleep(0.1)
        sock.close()  # the kill point
        assert _wait(lambda: hub.stats()["torn_frames"] >= 1)
        assert records == []

    def test_head_to_client_commands_dedupe(self, hub_records):
        hub, _records = hub_records
        got = []
        cli = JournalClient(("127.0.0.1", hub.port), "r0",
                            on_record=got.append)
        assert cli.connect()
        assert _wait(lambda: hub.connected("r0"))
        hub.send("r0", {"cmd": "submit", "jid": "job-000001"})
        assert _wait(lambda: bool(cli.pump(0.05) or got))
        assert got == [{"cmd": "submit", "jid": "job-000001"}]
        # sever without the hub noticing, reconnect: the hub replays
        # its unacked suffix, the client's seq dedup drops re-sends
        cli._disconnect()
        assert cli.connect()
        hub.send("r0", {"cmd": "stats"})
        deadline = time.monotonic() + 5
        while len(got) < 2 and time.monotonic() < deadline:
            cli.pump(0.05)
        assert got == [{"cmd": "submit", "jid": "job-000001"},
                       {"cmd": "stats"}]
        cli.close()

    def test_partition_buffers_and_replays_on_heal(self, hub_records):
        hub, records = hub_records
        cli = JournalClient(("127.0.0.1", hub.port), "r0",
                            max_retries=1, backoff_base=0.01)
        assert cli.connect()
        cli.send({"n": 0})
        assert _wait(lambda: len(records) == 1)
        hub.partition("r0")
        # sends into the partition buffer client-side (the send may
        # report a live link once before TCP notices the drop)
        for i in range(1, 4):
            cli.send({"n": i})
            cli.pump(0.01)
        assert len(records) == 1
        assert "r0" in hub.stats()["partitioned"]
        hub.heal_partition("r0")
        deadline = time.monotonic() + 5
        while len(records) < 4 and time.monotonic() < deadline:
            cli.pump(0.02)
            time.sleep(0.01)
        assert [b["n"] for _c, b in records] == [0, 1, 2, 3]
        cli.close()

    def test_bounded_retry_reports_failure(self):
        # a port nothing listens on: bounded retries, then False
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        cli = JournalClient(("127.0.0.1", port), "r0",
                            max_retries=2, backoff_base=0.01)
        t0 = time.monotonic()
        assert not cli.connect()
        assert time.monotonic() - t0 < 5
        assert not cli.connected
