"""Device-resident convergence + pipelined chunk dispatch (the solve
harness's hot loop).

Pins the acceptance contract of the pipelined harness:

* converging (open-ended) solves are BIT-IDENTICAL — assignments AND
  reported stop cycles — to the pre-pipeline host-compare harness for
  all five vmap-factored algorithms with ``pipeline=False``, and
  assignment-identical with ≤ one chunk of overshoot when pipelined;
* exactly ONE XLA compile per (solver, collect) pair regardless of
  remainder-chunk sizes (trace-count + cache-count assertions);
* the hot loop contains no host round-trip per cycle: convergence is a
  scalar computed inside the jitted chunk (jaxpr-pinned), and
  ``host_sync_count`` is ≤ 1 per chunk;
* warm restarts (``resume=True``) continue the PRNG stream identically
  on both paths.
"""
from __future__ import annotations

import jax
import numpy as np
import pytest

from pydcop_tpu.algorithms import load_algorithm_module
from pydcop_tpu.algorithms.base import (
    LruCache,
    clamp_chunk_to_deadline,
)
from pydcop_tpu.generators import generate_graph_coloring

ALGOS = ["mgm", "dsa", "adsa", "gdba", "maxsum"]


def _dcop(seed=1, V=16, E=24):
    return generate_graph_coloring(
        n_variables=V, n_colors=3, n_edges=E, soft=True, n_agents=1,
        seed=seed,
    )


def _solver(algo, dcop, seed=0):
    return load_algorithm_module(algo).build_solver(dcop, seed=seed)


@pytest.fixture(scope="module")
def dcop():
    return _dcop()


class TestConvergenceParity:
    """Open-ended solves vs the pre-pipeline harness, per algorithm."""

    @pytest.mark.parametrize("algo", ALGOS)
    def test_device_convergence_bit_identical(self, dcop, algo):
        legacy = _solver(algo, dcop)
        legacy._force_host_convergence = True
        ref = legacy.run(max_cycles=300)
        assert not legacy._device_convergence_ok()

        modern = _solver(algo, dcop)
        assert modern._device_convergence_ok()
        res = modern.run(max_cycles=300, pipeline=False)
        assert res.assignment == ref.assignment, algo
        assert res.cycle == ref.cycle, algo
        assert res.cost == ref.cost, algo
        # the device loop reads ONE scalar per chunk, never bulk state
        h = res.harness
        assert h["host_sync_count"] <= h["chunks_dispatched"]

    @pytest.mark.parametrize("algo", ALGOS)
    def test_pipelined_overshoots_at_most_one_chunk(self, dcop, algo):
        legacy = _solver(algo, dcop)
        legacy._force_host_convergence = True
        ref = legacy.run(max_cycles=300)

        piped = _solver(algo, dcop)
        res = piped.run(max_cycles=300, pipeline=True)
        assert res.assignment == ref.assignment, algo
        assert ref.cycle <= res.cycle <= ref.cycle + 7, algo
        assert res.harness["overshoot_cycles"] == res.cycle - ref.cycle


class TestFixedShapeRunner:
    def test_one_compile_despite_remainder_chunks(self, dcop):
        solver = _solver("dsa", dcop)
        res = solver.run(cycles=23, chunk=7)  # chunks 7, 7, 7, tail 2
        assert res.cycle == 23
        assert solver._masked_trace_counts == {("masked", 7, False): 1}
        assert len(solver._compiled_chunks) == 1
        assert res.harness["chunks_dispatched"] == 4
        assert res.harness["masked_tail_cycles"] == 5
        # fixed-cycle runs never block on convergence reads
        assert res.harness["host_sync_count"] == 0

    def test_masked_tail_bit_identical_to_per_shape_runner(self, dcop):
        ref = _solver("dsa", dcop)
        ref._force_host_convergence = True  # per-(n, collect) runners
        a = ref.run(cycles=23, chunk=7)
        b = _solver("dsa", dcop).run(cycles=23, chunk=7)
        assert a.assignment == b.assignment
        assert a.cost == b.cost
        # the legacy path really did compile a remainder shape
        assert (2, False) in ref._compiled_chunks

    def test_collect_cycles_history_matches(self, dcop):
        ref = _solver("mgm", dcop)
        ref._force_host_convergence = True
        a = ref.run(cycles=10, collect_cycles=True)
        b = _solver("mgm", dcop).run(cycles=10, collect_cycles=True)
        assert [h["cost"] for h in a.history] == [
            h["cost"] for h in b.history
        ]
        assert [h["cycle"] for h in a.history] == [
            h["cycle"] for h in b.history
        ]


class TestWarmRestart:
    @pytest.mark.parametrize("algo", ["dsa", "maxsum"])
    def test_resume_continues_prng_stream_identically(self, dcop, algo):
        legacy = _solver(algo, dcop)
        legacy._force_host_convergence = True
        legacy.run(cycles=10)
        legacy.run(cycles=10, resume=True)

        modern = _solver(algo, dcop)
        modern.run(cycles=10)
        modern.run(cycles=10, resume=True)
        assert np.array_equal(
            np.asarray(legacy._last_key), np.asarray(modern._last_key)
        )
        assert np.array_equal(
            np.asarray(legacy.values_of(legacy._last_state)),
            np.asarray(modern.values_of(modern._last_state)),
        )


class TestNoHostRoundTripPerCycle:
    def test_registry_audits_the_masked_runner(self):
        """The no-host-callback / zero-collective contract of the
        masked chunk runner is now DECLARED
        (SynchronousTensorSolver.program_budget) and audited by the
        analysis registry sweep (ISSUE 13) — the migrated form of the
        jaxpr pin below, which is kept as a legacy cross-check on the
        auditor's walker."""
        from pydcop_tpu.analysis import registry

        for algo in ALGOS:
            prog = registry.build_cell(f"single/{algo}")
            assert prog.budget.max_host_callbacks == 0
            assert all(
                v == 0 for v in prog.budget.collectives.values()
            )
            rep = registry.audit_cell(f"single/{algo}")
            assert rep.ok, (algo,
                            [f.to_dict() for f in rep.findings])
            assert rep.scorecard["host_callbacks"] == 0

    def test_masked_runner_jaxpr_is_one_scan_with_scalar_conv(self, dcop):
        solver = _solver("mgm", dcop)
        runner = solver._masked_chunk_runner(7, collect=False)
        state = solver.initial_state()
        keys = jax.random.split(jax.random.PRNGKey(0), 7)
        jaxpr = jax.make_jaxpr(runner)(state, keys, 5)

        prims = []

        def walk(jx):
            for eqn in jx.eqns:
                prims.append(eqn.primitive.name)
                for p in eqn.params.values():
                    if hasattr(p, "jaxpr"):
                        walk(p.jaxpr)
                    elif isinstance(p, (tuple, list)):
                        for q in p:
                            if hasattr(q, "jaxpr"):
                                walk(q.jaxpr)

        walk(jaxpr.jaxpr)
        # the whole chunk is one scanned program...
        assert "scan" in prims
        # ...with no host-callback escape hatches anywhere inside
        forbidden = {"io_callback", "pure_callback", "outside_call",
                     "host_callback_call"}
        assert not forbidden.intersection(prims)
        # the convergence decision leaves the device as ONE bool scalar
        conv_aval = jaxpr.out_avals[-1]
        assert conv_aval.shape == ()
        assert conv_aval.dtype == np.bool_


class TestCountersAndEvents:
    def test_harness_counters_in_metrics(self, dcop):
        res = _solver("mgm", dcop).run(max_cycles=300)
        m = res.metrics()
        for k in ("host_sync_count", "dispatch_wait_s", "donated_chunks",
                  "masked_tail_cycles", "chunks_dispatched",
                  "compile_cache_evictions"):
            assert k in m["harness"], k

    def test_harness_run_done_event_forwarded(self, dcop):
        from pydcop_tpu.runtime.events import event_bus

        got = []
        cb = lambda topic, evt: got.append((topic, evt))  # noqa: E731
        event_bus.subscribe("harness.*", cb)
        was = event_bus.enabled
        event_bus.enabled = True
        try:
            _solver("mgm", dcop).run(max_cycles=50)
        finally:
            event_bus.enabled = was
            event_bus.unsubscribe(cb)
        assert got, "no harness.* event emitted"
        topic, evt = got[-1]
        assert topic == "harness.run.done"
        assert evt["algo"] == "mgm"
        assert "host_sync_count" in evt


class TestDeadlineClamp:
    def test_no_rate_no_clamp(self):
        assert clamp_chunk_to_deadline(100, None, 5.0) == 100
        assert clamp_chunk_to_deadline(100, 10.0, None) == 100

    def test_clamps_to_projected_budget(self):
        # 10 cycles/sec, 2s left → at most 20 more cycles
        assert clamp_chunk_to_deadline(100, 10.0, 2.0) == 20
        assert clamp_chunk_to_deadline(15, 10.0, 2.0) == 15

    def test_floor_of_one_cycle(self):
        assert clamp_chunk_to_deadline(100, 10.0, 0.01) == 1
        assert clamp_chunk_to_deadline(100, 10.0, -3.0) == 1

    def test_shrunk_chunk_reuses_the_compiled_runner(self, dcop):
        # a deadline-shrunk chunk is just a masked tail — same XLA
        # program, no remainder-shape compile
        solver = _solver("mgm", dcop)
        solver.run(cycles=40, chunk=20, timeout=30.0)
        assert solver._masked_trace_counts == {("masked", 20, False): 1}


class TestCompiledChunkLru:
    def test_eviction_counted(self):
        c = LruCache(capacity=2)
        c["a"], c["b"] = 1, 2
        _ = c["a"]  # refresh a
        c["c"] = 3  # evicts b
        assert len(c) == 2
        assert c.evictions == 1
        assert "b" not in c and "a" in c and "c" in c
        c.clear()
        assert len(c) == 0

    def test_solver_cache_is_bounded(self, dcop):
        solver = _solver("mgm", dcop)
        solver._compiled_chunks.capacity = 2
        solver._force_host_convergence = True  # per-n runners
        for n in (3, 4, 5, 6):
            solver.run(cycles=n, chunk=n)
        assert len(solver._compiled_chunks) <= 2
        assert solver._compiled_chunks.evictions >= 2
        res = solver.run(cycles=3, chunk=3)
        assert res.harness["compile_cache_evictions"] >= 3


class TestBatchEngineFixedShape:
    def test_one_compile_despite_remainder_chunk(self):
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.batch.engine import BatchEngine, BatchItem

        cache = CompileCache()
        engine = BatchEngine(cache=cache)
        items = [BatchItem(_dcop(seed=3), "mgm", seed=0)]
        # max_cycles=10 → chunks 7 + masked tail 3: one runner compile
        res = engine.solve(items, max_cycles=10)
        assert cache.misses == 1
        assert res[0].cycle <= 10
