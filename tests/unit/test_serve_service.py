"""Continuous-batching solve service (pydcop_tpu.serve).

Contracts pinned here:

* **mid-bucket determinism** (acceptance pin): a job admitted into an
  ALREADY-RUNNING bucket produces bit-identical assignment and stop
  cycle to the same instance solved standalone, for every
  batch-eligible algorithm;
* **slot reuse**: a lane freed by a converged job is re-used by the
  next arrival, and the re-seated job is still bit-identical;
* **crash resume**: a service killed mid-stream and restarted resumes
  every in-flight job from its last chunk-boundary checkpoint (same
  PRNG key/age/stability), and the resumed results are STILL
  bit-identical to an uninterrupted standalone solve;
* **deadlines**: an expired deadline preempts the job (TIMEOUT) at a
  chunk boundary without perturbing its bucket-mates' streams;
* **prewarm**: compiling a bucket runner ahead of arrival makes the
  first admission a cache hit — no cold XLA compile on the hot path;
* **merging**: two under-filled same-signature buckets fold together
  and the migrated lanes' results stay bit-identical;
* serve.* lifecycle events and the ServeCounters schema.

Tests drive :meth:`SolveService.tick` synchronously (no scheduler
thread), so admission timing — "submit B after A's bucket has already
stepped" — is deterministic.
"""
import os

import pytest

from pydcop_tpu.batch.cache import CompileCache
from pydcop_tpu.batch.engine import SUPPORTED_ALGOS, BatchItem, adapter_for
from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.serve import SolveService

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")

#: cycle ceiling for the determinism tests: a multiple of the harness
#: chunk (7), small enough that even non-converging algos stay fast
LIMIT = 63


def _load(name=TUTO):
    return load_dcop_from_file([name])


def _standalone(dcop, algo, seed, params=None):
    """The standalone harness run the service must bit-match: the SAME
    solver construction the batch adapters use."""
    spec = adapter_for(algo).build_spec(
        BatchItem(dcop, algo, algo_params=params, seed=seed)
    )
    return spec.solver.run(max_cycles=LIMIT)


def _drain(svc, max_ticks=80):
    for _ in range(max_ticks):
        if not svc.tick():
            return
    raise AssertionError("service did not drain")


class TestMidflightDeterminism:
    """Acceptance pin: mid-bucket admission is bit-identical to a
    standalone solve, for every batch-eligible algorithm."""

    @pytest.mark.parametrize("algo", SUPPORTED_ALGOS)
    def test_job_admitted_midbucket_bit_identical(self, algo):
        dcop = _load()
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=LIMIT)
        a = svc.submit(dcop, algo, seed=0, label="A")
        svc.tick()
        svc.tick()  # A's bucket is now mid-flight (age 14)
        b = svc.submit(dcop, algo, seed=1, label="B")
        _drain(svc)
        assert svc.counters.counts["midflight_admissions"] >= 1
        for jid, seed in ((a, 0), (b, 1)):
            res = svc.result(jid, timeout=1)
            seq = _standalone(dcop, algo, seed)
            assert res.assignment == seq.assignment, (algo, seed)
            assert res.cycle == seq.cycle, (algo, seed)
            assert res.cost == seq.cost, (algo, seed)

    def test_smaller_instance_folds_into_running_bucket(self):
        """A mixed-shape arrival: the smaller instance pads into the
        bigger instance's running bucket (dummy-routed padding) and
        still solves bit-identically."""
        from pydcop_tpu.generators import generate_graph_coloring

        big = generate_graph_coloring(
            n_variables=20, n_colors=3, n_edges=40, soft=True,
            n_agents=1, seed=2,
        )
        small = generate_graph_coloring(
            n_variables=10, n_colors=3, n_edges=20, soft=True,
            n_agents=1, seed=3,
        )
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=LIMIT)
        a = svc.submit(big, "mgm", seed=0)
        svc.tick()
        b = svc.submit(small, "mgm", seed=3)
        _drain(svc)
        # both ran in ONE bucket (the second folded in mid-flight) ...
        assert svc.counters.counts["buckets_opened"] == 1
        assert svc.counters.counts["midflight_admissions"] == 1
        # ... and both match their standalone solves exactly
        for jid, dcop, seed in ((a, big, 0), (b, small, 3)):
            res = svc.result(jid, timeout=1)
            seq = _standalone(dcop, "mgm", seed)
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle


class TestSlotReuse:
    def test_freed_lane_is_reused(self):
        """lanes=1, max_buckets=1: the second job can only run by
        re-using the lane the first job's convergence freed —
        continuous batching's core move — and is still
        bit-identical."""
        dcop = _load()
        svc = SolveService(lanes=1, cache=CompileCache(),
                           max_cycles=LIMIT, max_buckets=1)
        a = svc.submit(dcop, "mgm", seed=0)
        b = svc.submit(dcop, "mgm", seed=1)
        _drain(svc)
        assert svc.counters.counts["lanes_reused"] >= 1
        for jid, seed in ((a, 0), (b, 1)):
            res = svc.result(jid, timeout=1)
            seq = _standalone(dcop, "mgm", seed)
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle

    def test_priority_orders_admission(self):
        """With one lane and one bucket, the higher-priority job is
        admitted first even though it was submitted second."""
        dcop = _load()
        svc = SolveService(lanes=1, cache=CompileCache(),
                           max_cycles=LIMIT, max_buckets=1)
        lo = svc.submit(dcop, "mgm", seed=0, priority=0)
        hi = svc.submit(dcop, "mgm", seed=1, priority=5)
        svc.tick()
        res_hi = None
        for _ in range(80):
            if svc._jobs[hi].done.is_set():
                res_hi = svc.result(hi)
                break
            svc.tick()
        assert res_hi is not None
        # the low-priority job was still waiting when hi finished
        assert not svc._jobs[lo].done.is_set()
        _drain(svc)
        assert svc.result(lo, timeout=1).status == "FINISHED"


class TestDeadlines:
    def test_expired_deadline_preempts_without_perturbing_others(self):
        dcop = _load()
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=LIMIT)
        a = svc.submit(dcop, "mgm", seed=0)  # no deadline
        # deadline so tight it expires at the first chunk boundary
        b = svc.submit(dcop, "mgm", seed=1, deadline_s=1e-4)
        _drain(svc)
        rb = svc.result(b, timeout=1)
        assert rb.status == "TIMEOUT"
        assert rb.cycle < LIMIT
        assert svc.counters.counts["jobs_preempted"] == 1
        # the bucket-mate's stream was untouched
        ra = svc.result(a, timeout=1)
        seq = _standalone(dcop, "mgm", 0)
        assert ra.assignment == seq.assignment
        assert ra.cycle == seq.cycle

    def test_deadline_pressure_shrinks_lane_chunks(self):
        from pydcop_tpu.serve.scheduler import BucketWorker, serve_target

        dcop = _load()
        spec = adapter_for("mgm").build_spec(
            BatchItem(dcop, "mgm", seed=0)
        )

        class _Job:
            jid = "j0"
            seed = 0
            submitted_at = 0.0
            stream = False

            def __init__(self):
                from time import monotonic

                self.dcop = dcop
                # plenty of budget left, but less than a full chunk at
                # the forced rate below
                self.deadline_at = monotonic() + 0.5

        w = BucketWorker("mgm", {}, serve_target([spec.dims]), 1,
                         CompileCache(), limit=2000)
        w.admit(_Job(), spec)
        w.rate = 4.0  # 4 cycles/sec → 0.5s budget → 2-cycle chunks
        w.step()
        assert w.counters.counts["deadline_shrunk_lanes"] >= 1
        assert w.lanes[0].age < w.chunk


class TestPrewarm:
    def test_admission_hits_prewarmed_runner(self):
        dcop = _load()
        cache = CompileCache()
        svc = SolveService(lanes=2, cache=cache, max_cycles=LIMIT)
        svc.prewarm([(dcop, "mgm")], block=True)
        assert cache.stats()["prewarmed"] == 1
        assert svc.counters.counts["prewarmed_runners"] == 1
        misses_before = cache.misses
        jid = svc.submit(dcop, "mgm", seed=0)
        _drain(svc)
        assert svc.result(jid, timeout=1).status == "FINISHED"
        # the hot path never paid a cold compile
        assert cache.misses == misses_before
        assert cache.hits >= 1

    def test_cache_lock_shared_across_threads(self):
        """Two threads racing get_or_compile on the same key build
        exactly once (the serve scheduler + prewarm thread contract)."""
        import threading

        cache = CompileCache()
        built = []

        def builder():
            built.append(1)
            return "runner"

        def race():
            cache.get_or_build(("k",), builder)

        ts = [threading.Thread(target=race) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(built) == 1
        assert cache.hits == 3 and cache.misses == 1


class TestMergeAndEvict:
    def test_underfilled_buckets_merge_bit_identically(self):
        """Force two same-signature buckets, drain one lane of each,
        and verify the service folds them (buckets_merged) with the
        migrated jobs' results unchanged."""
        dcop = _load()
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=LIMIT)
        # four jobs at once: bucket 1 takes two, bucket 2 takes two
        jids = [svc.submit(dcop, "mgm", seed=s) for s in range(4)]
        _drain(svc)
        assert svc.counters.counts["buckets_opened"] == 2
        # mgm converges at the same cycle for all seeds here, so both
        # buckets drained in lockstep; merging may or may not have
        # fired depending on timing — correctness is the bit-identity
        for jid, seed in zip(jids, range(4)):
            res = svc.result(jid, timeout=1)
            seq = _standalone(dcop, "mgm", seed)
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle
        # drained buckets were closed
        assert svc.counters.counts["buckets_closed"] == 2

    def test_worker_migration_preserves_streams(self):
        """Direct scheduler-level pin: migrate a mid-flight lane
        between same-signature workers and finish it — bit-identical
        to the un-migrated run (dsa: the PRNG stream must survive the
        move)."""
        from time import monotonic

        from pydcop_tpu.serve.scheduler import BucketWorker, serve_target

        dcop = _load()
        adapter = adapter_for("dsa")

        class _Job:
            def __init__(self, seed):
                self.jid = f"j{seed}"
                self.seed = seed
                self.dcop = dcop
                self.deadline_at = None
                self.submitted_at = monotonic()
                self.stream = False

        def run_to_end(w, i):
            for _ in range(40):
                fin = w.step()
                for j, lane, status in fin:
                    if j == i:
                        return w.lane_result(j, lane, status)
            raise AssertionError("lane did not finish")

        cache = CompileCache()
        spec = adapter.build_spec(BatchItem(dcop, "dsa", seed=5))
        target = serve_target([spec.dims])
        w1 = BucketWorker("dsa", {}, target, 2, cache, limit=LIMIT)
        i1 = w1.admit(_Job(5), spec)
        w1.step()
        w1.step()
        # migrate mid-flight into a fresh same-signature worker
        w2 = BucketWorker("dsa", {}, target, 2, cache, limit=LIMIT)
        moved = w2.migrate_from(w1)
        assert moved == 1
        assert w1.occupied == 0
        i2 = next(i for i, ln in enumerate(w2.lanes) if ln is not None)
        res = run_to_end(w2, i2)

        seq = _standalone(dcop, "dsa", 5)
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle


class TestCrashResume:
    def test_resume_midflight_bit_identical(self, tmp_path):
        """Kill the service mid-stream (abandon, no drain); a fresh
        service resumes every in-flight job from its last chunk
        boundary and the final results are bit-identical to
        uninterrupted standalone solves."""
        dcop = _load()
        jd = str(tmp_path / "journal")
        svc1 = SolveService(lanes=2, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd,
                            checkpoint_every=1)
        a = svc1.submit(dcop, "dsa", seed=0, source_file=TUTO)
        b = svc1.submit(dcop, "dsa", seed=1, source_file=TUTO)
        svc1.tick()
        svc1.tick()  # two chunk boundaries checkpointed, nobody done
        assert svc1.counters.counts["checkpoints_saved"] >= 2
        assert not svc1._jobs[a].done.is_set()
        del svc1  # crash: no drain, no cleanup

        svc2 = SolveService(lanes=2, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd,
                            checkpoint_every=1)
        assert svc2.resume() == 2
        _drain(svc2)
        assert svc2.counters.counts["jobs_resumed"] == 2
        for jid, seed in ((a, 0), (b, 1)):
            res = svc2.result(jid, timeout=1)
            seq = _standalone(dcop, "dsa", seed)
            assert res.assignment == seq.assignment
            assert res.cycle == seq.cycle

    def test_completed_jobs_not_rerun_on_resume(self, tmp_path):
        dcop = _load()
        jd = str(tmp_path / "journal")
        svc1 = SolveService(lanes=2, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd)
        a = svc1.submit(dcop, "mgm", seed=0, source_file=TUTO)
        _drain(svc1)
        assert svc1.result(a, timeout=1).status == "FINISHED"

        svc2 = SolveService(lanes=2, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd)
        assert svc2.resume() == 0  # the JID: line marks it done

    def test_corrupt_checkpoint_restarts_from_scratch(self, tmp_path):
        dcop = _load()
        jd = str(tmp_path / "journal")
        svc1 = SolveService(lanes=1, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd,
                            checkpoint_every=1)
        a = svc1.submit(dcop, "mgm", seed=0, source_file=TUTO)
        svc1.tick()
        ck = svc1._ckpt_path(a)
        assert os.path.exists(ck)
        with open(ck, "r+b") as f:  # corrupt it
            f.seek(30)
            f.write(b"\xde\xad\xbe\xef")
        del svc1

        svc2 = SolveService(lanes=1, cache=CompileCache(),
                            max_cycles=LIMIT, journal_dir=jd)
        assert svc2.resume() == 1
        _drain(svc2)
        res = svc2.result(a, timeout=1)
        # restarted from cycle 0 — still the exact standalone result
        seq = _standalone(dcop, "mgm", 0)
        assert res.assignment == seq.assignment
        assert res.cycle == seq.cycle
        assert svc2.counters.counts["jobs_resumed"] == 0


class TestServiceThread:
    def test_background_thread_end_to_end(self):
        """The threaded front door: submit from the caller thread,
        block on result(), stream() yields progress then done."""
        dcop = _load()
        with SolveService(lanes=2, cache=CompileCache(),
                          max_cycles=LIMIT) as svc:
            jid = svc.submit(dcop, "mgm", seed=0, stream=True)
            events = list(svc.stream(jid, timeout=30))
            res = svc.result(jid, timeout=30)
        assert res.status == "FINISHED"
        kinds = [e["event"] for e in events]
        assert kinds[0] == "job.submitted"
        assert "job.admitted" in kinds
        assert "job.progress" in kinds
        assert kinds[-1] == "job.done"
        # anytime stream: progress cycles increase chunk by chunk
        cycles = [e["cycle"] for e in events
                  if e["event"] == "job.progress"]
        assert cycles == sorted(cycles) and cycles

    def test_fallback_algo_served(self):
        dcop = _load()
        with SolveService(lanes=2, cache=CompileCache()) as svc:
            jid = svc.submit(dcop, "dpop")
            res = svc.result(jid, timeout=60)
        assert res.status == "FINISHED"
        assert res.cost == 12
        assert svc.counters.counts["jobs_fallback"] == 1


class TestEventsAndCounters:
    def test_serve_events_emitted(self):
        from pydcop_tpu.runtime.events import event_bus

        dcop = _load()
        seen = []
        cb = lambda topic, evt: seen.append((topic, evt))  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("serve.*", cb)
        try:
            svc = SolveService(lanes=2, cache=CompileCache(),
                               max_cycles=LIMIT)
            jid = svc.submit(dcop, "mgm", seed=0)
            _drain(svc)
            svc.result(jid, timeout=1)
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        topics = [t for t, _ in seen]
        for expected in ("serve.job.submitted", "serve.job.admitted",
                         "serve.bucket.opened", "serve.job.done",
                         "serve.bucket.closed"):
            assert expected in topics, topics

    def test_unknown_counter_rejected(self):
        from pydcop_tpu.runtime.stats import ServeCounters

        with pytest.raises(KeyError):
            ServeCounters().inc("nope")

    def test_metrics_shape(self):
        dcop = _load()
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=LIMIT)
        jid = svc.submit(dcop, "mgm", seed=0)
        _drain(svc)
        svc.result(jid, timeout=1)
        m = svc.metrics()
        assert set(m) == {"serve", "cache", "workers", "pending"}
        assert m["serve"]["jobs_completed"] == 1
        assert m["pending"] == 0
