"""Separator-sharded exact DPOP (ISSUE 9): tiled util tables over the
virtual 8-mesh, cross-edge-consistency pruning, mini-bucket fallback.

The contract under test:

* the tiled sweep is BIT-IDENTICAL to the single-device per-level sweep
  on exactly-representable integer costs — pinned over a parity matrix
  of cut shapes (chain, dense hub, adversarial all-back-edge
  separators) × shard counts, pruning on and off;
* pruning never changes the optimum (property test over random
  hard-constraint instances) and actually shrinks the wire;
* the mini-bucket mode reports a correct bound sandwich
  ``lower ≤ exact ≤ upper`` and collapses to exact at a sufficient
  i-bound;
* ``engine="auto"`` routes on the planner's byte estimate:
  over-budget instances go to the sharded sweep, and a typed
  :class:`UtilTableTooLarge` (with suggested shard count / i-bound)
  fires only when every route is exhausted;
* sharded / mini-bucket configurations never collide with
  single-device entries in the persistent sweep-executable cache.
"""
import numpy as np
import pytest

from pydcop_tpu.algorithms.dpop import DpopSolver
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.graph import pseudotree
from pydcop_tpu.ops.dpop_shard import (
    UtilTableTooLarge,
    estimate_sweep_bytes,
    minibucket_solve,
    plan_tiled_sweep,
    prune_preconditions,
    suggest_i_bound,
)
from pydcop_tpu.ops.dpop_sweep import (
    BIG,
    compile_sweep_perlevel,
    run_sweep_perlevel,
)
from pydcop_tpu.parallel import ShardedSepDpop, build_mesh

from tests.unit.test_dpop_sweep import brute_force_cost, random_dcop


# ---------------------------------------------------------------------------
# instance families (integer costs: exactly representable in f32)
# ---------------------------------------------------------------------------


def chain_dcop(n=24, D=3, seed=0):
    """Pure chain: width-1 separators at every level (also exercises
    the Sm < n_shards padding — Sm = D = 3 against an 8-mesh)."""
    return random_dcop(n, 0, dom_sizes=(D,), seed=seed, tree_only=True)


def hub_dcop(seed=0):
    """Dense hub: a clique near the root widens ONE level's separator
    while long chains keep the rest narrow (per-level tilings must
    pick different split widths)."""
    rng = np.random.default_rng(seed)
    dcop = DCOP("hub", objective="min")
    d = Domain("d", "vals", list(range(3)))
    vs = [Variable(f"v{i:02d}", d) for i in range(18)]
    for v in vs:
        dcop.add_variable(v)
    k = 0
    for i in range(5):
        for j in range(i + 1, 5):
            m = rng.integers(0, 9, (3, 3)).astype(float)
            dcop.add_constraint(
                NAryMatrixRelation([vs[i], vs[j]], m, name=f"q{k}")
            )
            k += 1
    for i in range(5, 18):
        p = vs[i - 1] if i > 5 else vs[4]
        m = rng.integers(0, 9, (3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([p, vs[i]], m, name=f"c{i}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


def backedge_dcop(n=8, D=2, seed=0):
    """Adversarial all-back-edge separators: every node constrains ALL
    its ancestors (a clique), so every level's separator is the full
    ancestor set — the worst tiling case."""
    rng = np.random.default_rng(seed)
    dcop = DCOP("backedge", objective="min")
    d = Domain("d", "vals", list(range(D)))
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    k = 0
    for i in range(n):
        for j in range(i + 1, n):
            m = rng.integers(0, 9, (D, D)).astype(float)
            dcop.add_constraint(
                NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{k}")
            )
            k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def hard_dcop(n_vars=20, n_edges=10, seed=0, frac_hard=0.3):
    """Random instance with BIG (hard) entries sprinkled in — the food
    of the cross-edge-consistency pruning — while every pair keeps a
    feasible entry so the optimum stays finite."""
    rng = np.random.default_rng(seed)
    dcop = DCOP("hard", objective="min")
    d = Domain("d", "vals", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    edges = set(
        (int(rng.integers(0, i)), i) for i in range(1, n_vars)
    )
    for _ in range(n_edges):
        i, j = rng.integers(0, n_vars, 2)
        if i != j:
            edges.add((min(i, j), max(i, j)))
    for k, (i, j) in enumerate(sorted(edges)):
        m = rng.integers(0, 10, (3, 3)).astype(float)
        hard = rng.random((3, 3)) < frac_hard
        hard[0, 0] = False  # keep a feasible entry per constraint
        m[hard] = BIG
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{k}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _cost_of(dcop, gid_to_name, assign):
    a = {
        nm: list(dcop.variables[nm].domain)[int(assign[i])]
        for i, nm in enumerate(gid_to_name)
    }
    return dcop.solution_cost(a, 10_000_000)[1]


# ---------------------------------------------------------------------------
# parity matrix: tiled sweep ≡ single-device per-level sweep, bit-exact
# ---------------------------------------------------------------------------


class TestShardedParity:
    @pytest.mark.parametrize("family", ["chain", "hub", "backedge"])
    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_bitmatches_single_device(self, family, n_shards):
        dcop = {
            "chain": chain_dcop, "hub": hub_dcop, "backedge": backedge_dcop,
        }[family]()
        tree = pseudotree.build_computation_graph(dcop)
        base = compile_sweep_perlevel(tree, dcop, "min")
        assert base is not None
        single, _ = run_sweep_perlevel(base)
        for prune in (True, False):
            plan = plan_tiled_sweep(
                tree, dcop, "min", n_shards=n_shards, prune=prune
            )
            got = ShardedSepDpop(plan, build_mesh(n_shards)).run()
            np.testing.assert_array_equal(got, single)

    def test_sharded_is_optimal_small(self):
        dcop = backedge_dcop(n=6, D=2, seed=3)
        tree = pseudotree.build_computation_graph(dcop)
        plan = plan_tiled_sweep(tree, dcop, "min", n_shards=4)
        assign = ShardedSepDpop(plan, build_mesh(4)).run()
        assert _cost_of(dcop, plan.base.gid_to_name, assign) == (
            brute_force_cost(dcop)
        )

    def test_max_mode(self):
        dcop = random_dcop(16, 7, dom_sizes=(2,), seed=11,
                           objective="max")
        tree = pseudotree.build_computation_graph(dcop)
        base = compile_sweep_perlevel(tree, dcop, "max")
        single, _ = run_sweep_perlevel(base)
        plan = plan_tiled_sweep(tree, dcop, "max", n_shards=8)
        got = ShardedSepDpop(plan, build_mesh(8)).run()
        np.testing.assert_array_equal(got, single)

    def test_mixed_domains_padding(self):
        """Ragged domains + Sm not divisible by n_shards exercise both
        padding paths."""
        dcop = random_dcop(30, 12, dom_sizes=(2, 3), seed=9)
        tree = pseudotree.build_computation_graph(dcop)
        base = compile_sweep_perlevel(tree, dcop, "min")
        single, _ = run_sweep_perlevel(base)
        plan = plan_tiled_sweep(tree, dcop, "min", n_shards=8)
        got = ShardedSepDpop(plan, build_mesh(8)).run()
        np.testing.assert_array_equal(got, single)

    def test_tiles_are_genuinely_smaller(self):
        """The per-device byte estimate must shrink with the shard
        count — the whole point of the tiling."""
        dcop = backedge_dcop(n=8, D=2)
        tree = pseudotree.build_computation_graph(dcop)
        p1 = plan_tiled_sweep(tree, dcop, "min", n_shards=1)
        p8 = plan_tiled_sweep(tree, dcop, "min", n_shards=8)
        assert p8.bytes_per_device < p1.bytes_per_device
        # split digits were actually consumed at the wide levels
        assert any(t.split_digits > 0 for t in p8.tilings)


# ---------------------------------------------------------------------------
# cross-edge-consistency pruning
# ---------------------------------------------------------------------------


class TestPruning:
    @pytest.mark.parametrize("seed", range(5))
    def test_pruning_never_changes_the_optimum(self, seed):
        """Property: with hard back-edge entries in play, the pruned
        sweep's solution cost equals both the unpruned sweep's and the
        single-device engine's."""
        dcop = hard_dcop(seed=seed)
        tree = pseudotree.build_computation_graph(dcop)
        base = compile_sweep_perlevel(tree, dcop, "min")
        single, _ = run_sweep_perlevel(base)
        ref_cost = _cost_of(dcop, base.gid_to_name, single)
        for n_shards in (2, 8):
            costs = {}
            for prune in (True, False):
                plan = plan_tiled_sweep(
                    tree, dcop, "min", n_shards=n_shards, prune=prune
                )
                assign = ShardedSepDpop(plan, build_mesh(n_shards)).run()
                costs[prune] = _cost_of(
                    dcop, plan.base.gid_to_name, assign
                )
                # on these (feasible-per-context) instances the pruned
                # sweep is even bit-identical, not just cost-equal
                np.testing.assert_array_equal(assign, single)
            assert costs[True] == costs[False] == ref_cost

    def test_pruning_shrinks_the_wire(self):
        dcop = hard_dcop(seed=1)
        tree = pseudotree.build_computation_graph(dcop)
        plan = plan_tiled_sweep(tree, dcop, "min", n_shards=8)
        assert plan.prune
        assert plan.wire_entries_pruned < plan.wire_entries_dense
        assert 0.0 < plan.pruned_fraction < 1.0

    def test_preconditions_disable_pruning(self):
        """A wrong-signed hard value (a -BIG entry in min mode) makes
        the feasibility classification unsound — the planner must
        fall back to the unpruned wire, not produce wrong answers."""
        dcop = hard_dcop(seed=2)
        # poison one constraint with a wrong-signed big entry (before
        # the tree is built: nodes hold constraint references)
        c = next(iter(dcop.constraints.values()))
        m = np.asarray(c.to_tensor()).copy()
        m[1, 1] = -BIG
        dcop.constraints[c.name] = NAryMatrixRelation(
            list(c.dimensions), m, name=c.name
        )
        tree = pseudotree.build_computation_graph(dcop)
        ok, reason = prune_preconditions(dcop)
        assert not ok and "wrong-signed" in reason
        plan = plan_tiled_sweep(tree, dcop, "min", n_shards=2)
        assert not plan.prune
        assert plan.prune_disabled_reason
        # and the unpruned sharded solve still matches single-device
        base = compile_sweep_perlevel(tree, dcop, "min")
        single, _ = run_sweep_perlevel(base)
        got = ShardedSepDpop(plan, build_mesh(2)).run()
        np.testing.assert_array_equal(got, single)

    def test_prune_noop_without_hard_entries(self):
        """Soft-only instances have nothing to prune: the wire is
        dense and results are (trivially) bit-identical."""
        dcop = random_dcop(20, 8, dom_sizes=(3,), seed=4)
        tree = pseudotree.build_computation_graph(dcop)
        plan = plan_tiled_sweep(tree, dcop, "min", n_shards=4)
        assert plan.prune
        assert plan.wire_entries_pruned == plan.wire_entries_dense


# ---------------------------------------------------------------------------
# mini-bucket fallback: bound sandwich
# ---------------------------------------------------------------------------


class TestMiniBucket:
    @pytest.mark.parametrize("seed", range(4))
    def test_bound_sandwich(self, seed):
        dcop = random_dcop(10, 5, seed=seed)
        tree = pseudotree.build_computation_graph(dcop)
        exact = brute_force_cost(dcop)
        for i_bound in (1, 2):
            aidx, relax, info = minibucket_solve(
                tree, dcop, "min", i_bound
            )
            a = {
                nm: list(dcop.variables[nm].domain)[i]
                for nm, i in aidx.items()
            }
            ub = dcop.solution_cost(a, 10_000_000)[1]
            assert relax <= exact + 1e-4
            assert exact <= ub + 1e-4

    def test_exact_at_sufficient_i_bound(self):
        dcop = random_dcop(9, 4, seed=7)
        tree = pseudotree.build_computation_graph(dcop)
        exact = brute_force_cost(dcop)
        width = tree.induced_width
        aidx, relax, info = minibucket_solve(
            tree, dcop, "min", max(1, width)
        )
        assert info["exact"] and info["bucket_splits"] == 0
        a = {
            nm: list(dcop.variables[nm].domain)[i]
            for nm, i in aidx.items()
        }
        ub = dcop.solution_cost(a, 10_000_000)[1]
        assert relax == pytest.approx(exact)
        assert ub == pytest.approx(exact)

    def test_solver_reports_gap_in_metrics(self):
        from pydcop_tpu.runtime.run import solve_result

        dcop = random_dcop(12, 6, seed=3)
        exact = DpopSolver(dcop).run().cost
        res = solve_result(
            dcop, "dpop",
            algo_params={"engine": "minibucket", "i_bound": 1},
        )
        m = res.metrics()["dpop"]
        assert m["engine"] == "minibucket"
        assert m["i_bound"] == 1
        assert m["lower_bound"] <= exact + 1e-4 <= (
            m["upper_bound"] + 2e-4
        )
        assert m["gap"] == pytest.approx(
            m["upper_bound"] - m["lower_bound"]
        )

    def test_max_mode_bounds_flip(self):
        dcop = random_dcop(8, 3, seed=5, objective="max")
        exact = brute_force_cost(dcop)
        solver = DpopSolver(dcop)
        solver.engine = "minibucket"
        solver.i_bound = 1
        res = solver.run()
        m = res.dpop
        assert m["lower_bound"] <= exact + 1e-4 <= m["upper_bound"] + 2e-4


# ---------------------------------------------------------------------------
# engine routing: planner byte estimates drive auto
# ---------------------------------------------------------------------------


class TestRouting:
    def test_auto_routes_to_sharded_under_budget(self):
        """An instance whose util tables exceed the per-device budget
        solves EXACTLY through the tiled sweep (the acceptance
        scenario), bit-identical to the unbudgeted single-device
        solve."""
        dcop = random_dcop(40, 20, dom_sizes=(3,), seed=5)
        ref = DpopSolver(dcop).run()
        est = estimate_sweep_bytes(
            pseudotree.build_computation_graph(dcop)
        )
        solver = DpopSolver(dcop)
        # budget below the single-device need, above one 8-way tile
        solver.budget_bytes = est["bytes"] // 4
        res = solver.run()
        assert solver.last_engine == "sharded"
        assert res.assignment == ref.assignment
        assert res.cost == ref.cost
        assert res.dpop["engine"] == "sharded"
        assert res.dpop["bytes_per_device"] <= solver.budget_bytes
        assert res.shard["mode"] == "dpop_sep_tiled"
        assert res.shard["collective"] == "psum_wire"
        assert res.shard["bytes_per_cycle_compact"] > 0

    def test_too_large_is_typed_with_suggestions(self):
        dcop = random_dcop(40, 20, dom_sizes=(3,), seed=5)
        solver = DpopSolver(dcop)
        solver.budget_bytes = 64  # absurd: nothing fits
        with pytest.raises(UtilTableTooLarge) as ei:
            solver.run()
        err = ei.value
        assert isinstance(err, MemoryError)  # back-compat catchability
        assert err.estimated_bytes > 64
        assert err.suggested_shards > err.n_shards
        assert err.suggested_i_bound >= 1
        assert "i-bound" in str(err)

    def test_too_large_routes_to_frontier_then_minibucket(self):
        """ISSUE 15 re-ordered this rung: over-budget instances try
        the frontier exact search BEFORE degrading to mini-bucket
        bounds — in the search regime (small n) the ladder now proves
        the optimum where it used to return a sandwich.  The
        mini-bucket tier is still the floor: forcing the engine (or
        an instance outside the search regime) reaches it."""
        dcop = random_dcop(40, 20, dom_sizes=(3,), seed=5)
        solver = DpopSolver(dcop)
        solver.budget_bytes = 64
        solver.i_bound = 2
        res = solver.run()
        assert solver.last_engine == "frontier"
        assert res.status == "FINISHED"
        assert res.search["optimal"]
        assert res.config["engine"] == "frontier"
        # the floor is intact: the forced tier still degrades to the
        # bound sandwich, and it brackets the frontier's proven cost
        forced = DpopSolver(dcop)
        forced.engine = "minibucket"
        forced.i_bound = 2
        mb = forced.run()
        assert forced.last_engine == "minibucket"
        assert (mb.dpop["lower_bound"] - 1e-6 <= res.cost
                <= mb.dpop["upper_bound"] + 1e-6)

    def test_pernode_refusal_is_typed(self, monkeypatch):
        """The per-node path's old bare MemoryError is now the typed
        UtilTableTooLarge carrying suggestions."""
        dcop = random_dcop(10, 10, seed=1)
        tree = pseudotree.build_computation_graph(dcop)
        solver = DpopSolver(dcop, tree)
        monkeypatch.setattr(solver, "max_table_entries", 4)
        with pytest.raises(UtilTableTooLarge) as ei:
            solver._run_pernode()
        assert ei.value.suggested_i_bound >= 1

    def test_estimates_and_suggestions(self):
        dcop = backedge_dcop(n=8, D=2)
        tree = pseudotree.build_computation_graph(dcop)
        est = estimate_sweep_bytes(tree)
        assert est["bytes"] > 0
        assert est["max_node_entries"] == 2 ** 8  # the root clique table
        assert tree.induced_width == 7
        assert suggest_i_bound(2, 4 * 2**10) >= 1
        # larger budget → larger feasible i-bound
        assert suggest_i_bound(2, 2**20) > suggest_i_bound(2, 2**8)


# ---------------------------------------------------------------------------
# observability: events + cache keys
# ---------------------------------------------------------------------------


class TestObservability:
    def test_shard_events_emitted(self):
        from pydcop_tpu.runtime.events import event_bus

        dcop = random_dcop(20, 8, dom_sizes=(3,), seed=2)
        solver = DpopSolver(dcop)
        solver.engine = "sharded"
        got = []

        def cb(t, e):
            got.append((t, e))

        event_bus.subscribe("dpop.*", cb)
        was = event_bus.enabled
        event_bus.enabled = True
        try:
            solver.run()
        finally:
            event_bus.enabled = was
            event_bus.unsubscribe(cb)
        topics = [t for t, _ in got]
        assert "dpop.shard.plan" in topics
        assert "dpop.shard.sweep.done" in topics
        plan_evt = dict(got[topics.index("dpop.shard.plan")][1])
        assert plan_evt["engine"] == "sharded"
        assert plan_evt["wire_bytes_dense"] > 0

    def test_minibucket_events_emitted(self):
        from pydcop_tpu.runtime.events import event_bus

        dcop = random_dcop(10, 4, seed=6)
        solver = DpopSolver(dcop)
        solver.engine = "minibucket"
        solver.i_bound = 1
        got = []

        def cb(t, e):
            got.append((t, e))

        event_bus.subscribe("dpop.*", cb)
        was = event_bus.enabled
        event_bus.enabled = True
        try:
            solver.run()
        finally:
            event_bus.enabled = was
            event_bus.unsubscribe(cb)
        topics = [t for t, _ in got]
        assert "dpop.minibucket.bounds" in topics

    def test_sweep_cache_variant_keys_never_collide(self):
        """Satellite: sharded / i-bounded plans must hash to DIFFERENT
        persistent-cache keys than the single-device entry for the
        same packed tree shape."""
        from types import SimpleNamespace

        from pydcop_tpu.ops.sweep_cache import sweep_cache_key

        ps = SimpleNamespace(
            D=4, n_nodes=100, Vp=128, N=16, L=7, mode="min",
            buckets=((2, 8),),
            plan=SimpleNamespace(A=8, B=16, L=3),
        )
        base = sweep_cache_key(ps)
        assert base == sweep_cache_key(ps)  # stable
        tiled = sweep_cache_key(ps, variant=("tiled", 8, 2 ** 20))
        mb = sweep_cache_key(ps, variant=("minibucket", 4))
        assert len({base, tiled, mb}) == 3
        # tiling/i-bound/budget FIELDS are key material, not just the tag
        assert tiled != sweep_cache_key(ps, variant=("tiled", 4, 2 ** 20))
        assert tiled != sweep_cache_key(ps, variant=("tiled", 8, 2 ** 21))
        assert mb != sweep_cache_key(ps, variant=("minibucket", 6))
        # distinct shapes still get distinct keys under the same variant
        ps2 = SimpleNamespace(
            D=4, n_nodes=101, Vp=128, N=16, L=7, mode="min",
            buckets=((2, 8),),
            plan=SimpleNamespace(A=8, B=16, L=3),
        )
        assert sweep_cache_key(ps2, variant=("tiled", 8, 2 ** 20)) != tiled
