"""Boundary-compacted collectives + comm/compute overlap (ISSUE 5).

The sharded engines' per-cycle collective must carry only the
partition's BOUNDARY columns — interior variables (all incident
factors on one shard) combine locally — and the compact-exact mode
must be BIT-IDENTICAL to the dense whole-space psum for every sharded
engine, on partitioned and adversarial cuts, for the psum slab AND the
edge-colored ppermute neighbor-exchange path.  ``stale`` (the
opt-in staleness-1 halo) is held to statistical equivalence plus a
guarded golden pin, like PR 2's coin-stream break.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.ops.compile import (
    compile_binary_from_arrays,
    compile_constraint_graph,
    compile_factor_graph,
    total_cost,
)
from pydcop_tpu.parallel.boundary import (
    analyze_boundary,
    build_exchange_plan,
    padded_boundary_idx,
)
from pydcop_tpu.parallel.mesh import (
    ShardedLocalSearch,
    ShardedMaxSum,
    build_mesh,
)


def ring_factor_tensors(V=64, C=3, seed=0):
    """Ring-lattice coloring factor graph — the partition-friendly
    instance (contiguous BFS regions cut only the seams)."""
    rng = np.random.default_rng(seed)
    idx = np.arange(V)
    ei = np.concatenate([idx, idx])
    ej = np.concatenate([(idx + 1) % V, (idx + 2) % V])
    mats = rng.uniform(0, 1, (2 * V, C, C)).astype(np.float32)
    mats += np.eye(C, dtype=np.float32) * 5
    return compile_binary_from_arrays(
        ei, ej, mats, V,
        unary=rng.uniform(0, 0.01, (V, C)).astype(np.float32),
    )


def ring_dcop(V=48, C=3, seed=0):
    """Same locality profile as a constraint-graph DCOP (for the
    local-search engines)."""
    rng = np.random.default_rng(seed)
    d = DCOP("ring", "min")
    dom = Domain("colors", "color", list(range(C)))
    vs = [Variable(f"v{i:03d}", dom) for i in range(V)]
    for v in vs:
        d.add_variable(v)
    k = 0
    for i in range(V):
        for off in (1, 2):
            m = rng.uniform(0, 1, (C, C)) + np.eye(C) * 5
            d.add_constraint(NAryMatrixRelation(
                [vs[i], vs[(i + off) % V]], m, name=f"c{k}"))
            k += 1
    d.add_agents([AgentDef(f"a{i}") for i in range(4)])
    return d


def random_instance(n_vars=60, n_edges=120, seed=1):
    return generate_graph_coloring(
        n_variables=n_vars, n_colors=3, n_edges=n_edges, soft=True,
        n_agents=1, seed=seed,
    )


def collect_collectives(jaxpr, out=None):
    """(primitive name, first-operand shape) for every collective in a
    (recursively traversed) jaxpr."""
    out = [] if out is None else out
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("psum", "pmax", "pmin", "ppermute",
                                  "psum2", "all_reduce", "pmax2",
                                  "pmin2"):
            out.append((eqn.primitive.name, eqn.invars[0].aval.shape))
        for v in eqn.params.values():
            for j in jax.tree_util.tree_leaves(
                    v, is_leaf=lambda x: hasattr(x, "eqns")):
                if hasattr(j, "eqns"):
                    collect_collectives(j, out)
                elif hasattr(j, "jaxpr"):
                    collect_collectives(j.jaxpr, out)
    return out


class TestBoundaryAnalysis:
    def test_ring_partition_is_pairwise(self):
        V = 16
        vi = np.stack([np.arange(V), (np.arange(V) + 1) % V],
                      axis=1).astype(np.int32)
        asg = (np.arange(V) // 4).astype(np.int32)
        info = analyze_boundary([vi], [asg], V, 4)
        assert info.n_boundary == 4 and info.pairwise
        # owner covers every variable exactly once
        assert info.owner.shape == (V,)
        assert set(info.owner.tolist()) <= {0, 1, 2, 3}
        idx = padded_boundary_idx(info, quantum=8)
        assert idx.shape[0] % 8 == 0
        assert set(info.boundary_vars.tolist()) <= set(idx.tolist())

    def test_star_cut_is_not_pairwise(self):
        vi = np.stack([np.zeros(8), np.arange(1, 9)],
                      axis=1).astype(np.int32)
        asg = (np.arange(8) // 2).astype(np.int32)
        info = analyze_boundary([vi], [asg], 9, 4)
        assert not info.pairwise
        assert build_exchange_plan(info, [vi], [asg]) is None

    def test_exchange_rounds_are_partial_permutations(self):
        V = 16
        vi = np.stack([np.arange(V), (np.arange(V) + 1) % V],
                      axis=1).astype(np.int32)
        asg = (np.arange(V) // 4).astype(np.int32)
        info = analyze_boundary([vi], [asg], V, 4)
        plan = build_exchange_plan(info, [vi], [asg])
        assert plan is not None
        for perm in plan.rounds:
            srcs = [a for a, _ in perm]
            dsts = [b for _, b in perm]
            assert len(srcs) == len(set(srcs))  # each sends at most once
            assert len(dsts) == len(set(dsts))  # each receives at most once
        # every pair exchanged in both directions exactly once
        directed = [e for perm in plan.rounds for e in perm]
        assert len(directed) == len(set(directed))

    def test_partition_stats_shares_the_analysis(self):
        from pydcop_tpu.parallel.partition import partition_stats

        V = 16
        vi = np.stack([np.arange(V), (np.arange(V) + 1) % V],
                      axis=1).astype(np.int32)
        asg = (np.arange(V) // 4).astype(np.int32)
        stats = partition_stats([vi], [asg], 4)
        info = analyze_boundary([vi], [asg], V, 4)
        assert stats["n_boundary"] == info.n_boundary
        assert stats["cut_fraction"] == pytest.approx(info.cut_fraction)
        assert stats["pairwise_cut"] == info.pairwise


class TestCompactExactMaxSum:
    """compact-exact must be BIT-IDENTICAL to the dense psum —
    assignments and continuation trajectories."""

    @pytest.mark.parametrize("use_packed", [False, True])
    @pytest.mark.parametrize("exchange", [False, True])
    def test_partitioned_bitmatch(self, use_packed, exchange):
        t = ring_factor_tensors()
        mesh = build_mesh(8)
        dense = ShardedMaxSum(t, mesh, damping=0.5,
                              use_packed=use_packed, overlap="off")
        vd, _, _ = dense.run(cycles=8)
        comp = ShardedMaxSum(t, mesh, damping=0.5,
                             use_packed=use_packed, overlap="exact",
                             exchange=exchange)
        assert comp.comm.mode == "exact"
        vc, q, r = comp.run(cycles=8)
        np.testing.assert_array_equal(vc, vd)
        # chunked continuation lands on the same trajectory
        v1, q1, r1 = comp.run(cycles=4)
        v2, _, _ = comp.run(cycles=4, q=q1, r=r1)
        np.testing.assert_array_equal(v2, vd)

    @pytest.mark.parametrize("use_packed", [False, True])
    def test_adversarial_all_boundary_bitmatch(self, use_packed):
        """Forced exact on an adversarial (near-all-boundary) cut is
        still bit-identical; the auto-policy refuses to compact it."""
        t = compile_factor_graph(random_instance())
        rng = np.random.default_rng(3)
        assigns = [rng.integers(0, 8, t.n_factors).astype(np.int32)]
        mesh = build_mesh(8)
        dense = ShardedMaxSum(t, mesh, damping=0.5, assigns=assigns,
                              use_packed=use_packed, overlap="off")
        comp = ShardedMaxSum(t, mesh, damping=0.5, assigns=assigns,
                             use_packed=use_packed, overlap="exact")
        assert comp.comm.info.cut_fraction > 0.5
        vd, _, _ = dense.run(cycles=8)
        vc, _, _ = comp.run(cycles=8)
        np.testing.assert_array_equal(vc, vd)
        auto = ShardedMaxSum(t, mesh, damping=0.5, assigns=assigns,
                             use_packed=use_packed)
        assert auto.comm.mode == "dense"
        va, _, _ = auto.run(cycles=8)
        np.testing.assert_array_equal(va, vd)

    def test_mixed_arity_packed_bitmatch(self):
        from pydcop_tpu.generators.secp import generate_secp

        t = compile_factor_graph(generate_secp(
            n_lights=30, n_models=10, n_rules=6, max_model_size=2,
            seed=3))
        mesh = build_mesh(4)
        dense = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True,
                              overlap="off")
        assert dense.packs is not None and dense.packs.mixed
        comp = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True,
                             overlap="exact")
        vd, _, _ = dense.run(cycles=8)
        vc, _, _ = comp.run(cycles=8)
        np.testing.assert_array_equal(vc, vd)

    def test_activation_bitmatch(self):
        t = ring_factor_tensors()
        mesh = build_mesh(8)
        vd, _, _ = ShardedMaxSum(t, mesh, damping=0.5, activation=0.6,
                                 overlap="off").run(cycles=6, seed=3)
        vc, _, _ = ShardedMaxSum(t, mesh, damping=0.5, activation=0.6,
                                 overlap="exact").run(cycles=6, seed=3)
        np.testing.assert_array_equal(vc, vd)

    def test_exchange_on_non_pairwise_cut_fails_loudly(self):
        t = compile_factor_graph(random_instance())
        rng = np.random.default_rng(3)
        assigns = [rng.integers(0, 8, t.n_factors).astype(np.int32)]
        with pytest.raises(ValueError, match="pairwise"):
            ShardedMaxSum(t, build_mesh(8), damping=0.5,
                          assigns=assigns, overlap="exact",
                          exchange=True)


class TestCompactExactLocalSearch:
    @pytest.mark.parametrize("rule,params", [
        ("mgm", {}),
        ("dsa", {}),
        ("adsa", {"activation": 0.7, "variant": "B"}),
        ("dba", {}),
        ("gdba", {}),
    ])
    @pytest.mark.parametrize("exchange", [False, True])
    def test_generic_bitmatch(self, rule, params, exchange):
        t = compile_constraint_graph(ring_dcop())
        mesh = build_mesh(8)
        vd = ShardedLocalSearch(
            t, mesh, rule=rule, algo_params=params, overlap="off"
        ).run(cycles=8, seed=3)
        comp = ShardedLocalSearch(
            t, mesh, rule=rule, algo_params=params, overlap="exact",
            exchange=exchange,
        )
        assert comp.comm.mode == "exact"
        np.testing.assert_array_equal(comp.run(cycles=8, seed=3), vd)

    @pytest.mark.parametrize("rule", ["mgm", "dsa", "adsa"])
    def test_packed_bitmatch(self, rule):
        t = compile_constraint_graph(ring_dcop())
        mesh = build_mesh(8)
        params = (
            {"activation": 0.7, "variant": "B"} if rule == "adsa" else {}
        )
        dense = ShardedLocalSearch(t, mesh, rule=rule,
                                   algo_params=params, use_packed=True,
                                   overlap="off")
        assert dense.packs is not None
        vd = dense.run(cycles=8, seed=3)
        comp = ShardedLocalSearch(t, mesh, rule=rule,
                                  algo_params=params, use_packed=True,
                                  overlap="exact")
        np.testing.assert_array_equal(comp.run(cycles=8, seed=3), vd)

    def test_generic_mgm_adversarial_forced_exact(self):
        """The compact partial-arbitration (pair-block pmax/pmin)
        mirrors neighborhood_winner exactly even when every variable
        is boundary."""
        t = compile_constraint_graph(random_instance(seed=2))
        mesh = build_mesh(8)
        vd = ShardedLocalSearch(t, mesh, rule="mgm",
                                overlap="off").run(cycles=8, seed=3)
        comp = ShardedLocalSearch(t, mesh, rule="mgm", overlap="exact")
        assert comp.comm.info.cut_fraction > 0.5  # adversarial indeed
        np.testing.assert_array_equal(comp.run(cycles=8, seed=3), vd)


class TestCollectiveBudgetPins:
    """The collective-budget contract, now enforced by the program
    auditor: the sharded cells of the analysis registry declare ONE
    compact-slab collective per cycle and the sweep audits the traced
    program against the declaration (ISSUE 13 — this replaced the
    hand-written jaxpr pins that used to live here).  ONE legacy
    jaxpr pin is kept below as a cross-check on the auditor itself."""

    def test_registry_pins_generic_compact_maxsum(self):
        """The migrated `generic compact has no dense psum` pin: the
        compact cell's declared payload is strictly below dense, and
        the traced program audits clean against it."""
        from pydcop_tpu.analysis import registry

        dense = registry.build_cell("sharded/maxsum/generic/off")
        comp = registry.build_cell("sharded/maxsum/generic/exact")
        assert (comp.budget.max_collective_bytes
                < dense.budget.max_collective_bytes)
        assert comp.budget.collectives["psum"] == 1
        rep = registry.audit_cell("sharded/maxsum/generic/exact")
        assert rep.ok, [f.to_dict() for f in rep.findings]
        assert rep.scorecard["collectives"]["psum"] == 1

    def test_registry_pins_exchange_mode_uses_ppermute(self):
        """The migrated `exchange mode uses ppermute not psum` pin."""
        from pydcop_tpu.analysis import registry

        rep = registry.audit_cell("sharded/maxsum/generic/exchange")
        assert rep.ok, [f.to_dict() for f in rep.findings]
        assert rep.scorecard["collectives"]["psum"] == 0
        assert rep.scorecard["collectives"]["ppermute"] >= 1

    def test_registry_pins_packed_mgm_budget(self):
        """The migrated packed-MGM budget pin: one compact psum plus
        one pmax/pmin arbitration pair per cycle on the psum path."""
        from pydcop_tpu.analysis import registry

        prog = registry.build_cell("sharded/mgm/packed/off")
        assert prog.budget.collectives == {
            "psum": 1, "pmax": 1, "pmin": 1, "ppermute": 0,
        }
        rep = registry.audit_cell("sharded/mgm/packed/off")
        assert rep.ok, [f.to_dict() for f in rep.findings]

    def test_packed_maxsum_compact_operand_is_boundary_slab(self):
        """LEGACY jaxpr pin (kept as a cross-check on the auditor: a
        bug that blinded collect_collectives would break this
        independent walker too)."""
        t = ring_factor_tensors()
        mesh = build_mesh(8)
        comp = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True,
                             overlap="exact", exchange=False)
        comp._build()
        state, _ = comp.init_messages()
        keys = jax.random.split(jax.random.PRNGKey(0), 1)
        cj = jax.make_jaxpr(comp._run_n)(state, keys, *comp._run_args)
        cols = collect_collectives(cj.jaxpr)
        psums = [s for n, s in cols if n == "psum"]
        assert len(psums) == 1
        D, Vp = comp.packs.D, comp.packs.Vp
        Bp = int(comp.comm.bnd.shape[0])
        assert psums[0] == (D, Bp)
        assert Bp < Vp
        assert all(s != (D, Vp) for s in psums)

class TestStaleOverlap:
    """overlap='stale' (staleness-1 boundary halo) is opt-in and held
    to statistical equivalence, like PR 2's coin-stream break."""

    def test_maxsum_stale_reaches_dense_quality(self):
        """Mean solution cost over several instances stays in a band
        of the dense engine's (single trajectories legitimately differ
        — BP oscillates on the frustrated ring, and a 1-cycle boundary
        halo shifts which crest it lands on)."""
        mesh = build_mesh(8)
        costs_s, costs_d = [], []
        for seed in range(4):
            t = ring_factor_tensors(seed=seed)
            vd, _, _ = ShardedMaxSum(t, mesh, damping=0.9,
                                     overlap="off").run(cycles=60)
            vs, _, _ = ShardedMaxSum(t, mesh, damping=0.9,
                                     overlap="stale").run(cycles=60)
            costs_d.append(float(total_cost(t, jnp.asarray(vd))))
            costs_s.append(float(total_cost(t, jnp.asarray(vs))))
        assert np.mean(costs_s) <= np.mean(costs_d) * 1.15 + 1.0, (
            costs_s, costs_d)

    def test_dsa_stale_statistical_equivalence(self):
        t = compile_constraint_graph(ring_dcop())
        mesh = build_mesh(8)
        costs_s, costs_d = [], []
        for s in range(4):
            vs = ShardedLocalSearch(t, mesh, rule="dsa",
                                    overlap="stale").run(cycles=25,
                                                         seed=s)
            vd = ShardedLocalSearch(t, mesh, rule="dsa",
                                    overlap="off").run(cycles=25,
                                                       seed=s)
            costs_s.append(float(total_cost(t, jnp.asarray(vs))))
            costs_d.append(float(total_cost(t, jnp.asarray(vd))))
        assert np.mean(costs_s) <= np.mean(costs_d) * 1.15 + 1.0, (
            costs_s, costs_d)

    def test_stale_golden_stream(self):
        """Guarded golden (minted on the CPU interpret / experimental
        shard_map stack, like the PR 2 coin-stream pins): the stale
        halo schedule is part of the mode's contract — an edit that
        changes WHICH cycle's slab merges where must break this pin,
        not pass silently.  Semantic assertions run everywhere."""
        t = ring_factor_tensors(V=24, seed=7)
        mesh = build_mesh(4)
        vs, _, _ = ShardedMaxSum(t, mesh, damping=0.5,
                                 overlap="stale").run(cycles=6, seed=11)
        vd, _, _ = ShardedMaxSum(t, mesh, damping=0.5,
                                 overlap="off").run(cycles=6, seed=11)
        assert vs.shape == vd.shape
        if (jax.devices()[0].platform == "cpu"
                and not hasattr(jax, "shard_map")):
            np.testing.assert_array_equal(vs, GOLDEN_STALE_24)

    def test_stale_downgrades_to_exact_without_boundary(self):
        """A 1-shard mesh has no boundary: stale has nothing to
        double-buffer and must degrade to the (exact) no-collective
        path, bit-identical to dense."""
        t = ring_factor_tensors()
        mesh = build_mesh(1)
        stale = ShardedMaxSum(t, mesh, damping=0.5, overlap="stale")
        assert stale.comm.collective == "none"
        vd, _, _ = ShardedMaxSum(t, mesh, damping=0.5,
                                 overlap="off").run(cycles=8)
        vs, _, _ = stale.run(cycles=8)
        np.testing.assert_array_equal(vs, vd)


#: minted by test_stale_golden_stream on the stack described there
GOLDEN_STALE_24 = [2, 0, 1, 2, 0, 1, 1, 0, 1, 2, 0, 1, 0, 2, 2, 0, 2,
                   2, 0, 1, 2, 0, 1, 1]


class TestObservability:
    def test_comm_stats_schema(self):
        from pydcop_tpu.runtime.stats import SHARD_COMM_FIELDS

        t = ring_factor_tensors()
        s = ShardedMaxSum(t, build_mesh(8), damping=0.5,
                          overlap="exact")
        stats = s.comm_stats()
        assert set(SHARD_COMM_FIELDS) <= set(stats)
        assert stats["mode"] == "compact-exact"
        assert 0 < stats["boundary_columns"] < stats["total_columns"]
        assert (stats["bytes_per_cycle_compact"]
                < stats["bytes_per_cycle_dense"])

    def test_comm_selected_event_emitted(self):
        from pydcop_tpu.runtime.events import event_bus

        got = []
        event_bus.enabled = True
        event_bus.subscribe("shard.*", lambda t_, e: got.append((t_, e)))
        try:
            t = ring_factor_tensors()
            ShardedMaxSum(t, build_mesh(4), damping=0.5,
                          overlap="exact")
        finally:
            event_bus.enabled = False
            event_bus._subs = [
                (p, cb) for p, cb in event_bus._subs
                if not p.startswith("shard.")
            ]
        assert any(t_ == "shard.comm.selected" for t_, _ in got)
        payload = got[0][1]
        assert payload["mode"] == "compact-exact"
        assert payload["engine"] == "maxsum"

    def test_solve_result_metrics_carry_shard(self):
        from pydcop_tpu.algorithms.base import SolveResult

        res = SolveResult(
            status="FINISHED", assignment={}, cost=0.0, violation=0,
            cycle=1, msg_count=0, msg_size=0.0, time=0.0,
            shard={"mode": "compact-exact"},
        )
        assert res.metrics()["shard"]["mode"] == "compact-exact"


class TestMultihostPlumbing:
    """overlap plumbing mirrors use_packed: the in-process 8-device
    mesh IS the global mesh of a single-process run."""

    def test_maxsum_overlap_plumbing(self):
        from pydcop_tpu.parallel.multihost import run_multihost_maxsum

        t_dcop = ring_dcop()
        info = {}
        values, n_dev, _t = run_multihost_maxsum(
            t_dcop, cycles=8, overlap="exact", info=info)
        assert n_dev == 8
        assert info["shard"]["mode"] == "compact-exact"
        info_d = {}
        vd, _, _t2 = run_multihost_maxsum(
            t_dcop, cycles=8, overlap="off", info=info_d)
        assert info_d["shard"]["mode"] == "dense"
        np.testing.assert_array_equal(values, vd)

    def test_local_search_overlap_plumbing(self):
        from pydcop_tpu.parallel.multihost import (
            run_multihost_local_search,
        )

        t_dcop = ring_dcop()
        info = {}
        values, n_dev, _t = run_multihost_local_search(
            t_dcop, rule="mgm", cycles=8, seed=0, overlap="exact",
            info=info)
        assert n_dev == 8
        assert info["shard"]["mode"] == "compact-exact"
        info_d = {}
        vd, _, _t2 = run_multihost_local_search(
            t_dcop, rule="mgm", cycles=8, seed=0, overlap="off",
            info=info_d)
        np.testing.assert_array_equal(values, vd)
