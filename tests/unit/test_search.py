"""Frontier-batched anytime exact search (ISSUE 15).

The contract under test:

* **host-loop parity pin** — the frontier engine returns the
  bit-identical optimal assignment and cost as the legacy syncbb/ncbb
  host loops on exactly-representable (integer) costs, over seeded
  matrices on chain / hub / dense graphs, min and max mode;
* **anytime semantics** — the incumbent stream is monotone
  non-increasing and ``lower <= optimum <= upper`` holds at every
  emitted chunk, terminating in an optimality proof (gap exactly 0);
* **spill fallback** — a deliberately tiny slab + ring forces the
  annex path: drains are counted, every spilled row is reinjected,
  NOTHING is lost, and the search still proves the same optimum;
* **host-traffic discipline** — the chunk runner's only non-state
  output is one [2] f32 vector (incumbent + bound), the compiled
  runner traces ONCE across runs, and the registry carries the
  ``search/frontier/*`` budget cells (zero host callbacks, zero
  collectives — swept by the parametrized audit in test_analysis);
* **the dpop auto ladder** — an instance where ``engine=auto``
  previously degraded to mini-bucket bounds now PROVES optimality via
  the frontier tier (the ISSUE 15 acceptance scenario), while bulk
  instances outside the search regime still fall through;
* **checkpoint/resume** — the search state rides the existing CRC'd
  snapshot layer; a run cut short resumes onto the exact frontier
  state and finishes with the clean run's answer.
"""
from __future__ import annotations

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation


def _edges(shape: str, n: int):
    if shape == "chain":
        return [(i, i + 1) for i in range(n - 1)]
    if shape == "hub":
        return [(0, i) for i in range(1, n)]
    if shape == "dense":
        return [(i, j) for i in range(n) for j in range(i + 1, n)]
    raise ValueError(shape)


def make_dcop(shape: str, seed: int, n: int = 8, D: int = 3,
              objective: str = "min") -> DCOP:
    """Seeded integer-cost instance: every cost is an exact f32
    integer, so host-vs-device cost equality is bit-for-bit."""
    rng = np.random.default_rng(seed)
    dcop = DCOP(f"{shape}-{seed}", objective=objective)
    dom = Domain("d", "v", list(range(D)))
    vs = [Variable(f"v{i:02d}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(_edges(shape, n)):
        m = rng.integers(0, 97, (D, D)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{k}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


def frontier(dcop, **kw):
    from pydcop_tpu.search.solver import FrontierSearchSolver

    return FrontierSearchSolver(dcop, **kw)


# ---------------------------------------------------------------------------
# host-loop parity pin
# ---------------------------------------------------------------------------


class TestHostParity:
    @pytest.mark.parametrize("shape", ["chain", "hub", "dense"])
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_bit_identical_to_syncbb_and_ncbb(self, shape, seed):
        from pydcop_tpu.algorithms.ncbb import NcbbSolver
        from pydcop_tpu.algorithms.syncbb import SyncBBSolver

        n = 7 if shape == "dense" else 9
        dcop = make_dcop(shape, seed, n=n)
        host = SyncBBSolver(dcop).run()
        ncbb = NcbbSolver(dcop).run()
        res = frontier(dcop, frontier_width=32, steps=4).run()
        assert res.search["optimal"]
        assert res.cost == host.cost == ncbb.cost
        assert res.assignment == host.assignment
        assert res.assignment == ncbb.assignment

    def test_max_mode_parity(self):
        from pydcop_tpu.algorithms.ncbb import NcbbSolver

        dcop = make_dcop("dense", 11, n=6, objective="max")
        host = NcbbSolver(dcop).run()
        res = frontier(dcop, frontier_width=32, steps=4).run()
        assert res.search["optimal"]
        assert res.cost == host.cost
        assert res.assignment == host.assignment

    def test_engine_param_routes_from_build_solver(self):
        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms import syncbb as syncbb_mod
        from pydcop_tpu.search.solver import FrontierSearchSolver

        dcop = make_dcop("chain", 5, n=6)
        adef = AlgorithmDef.build_with_default_params(
            "syncbb", {"engine": "frontier"}
        )
        solver = syncbb_mod.build_solver(dcop, None, adef)
        assert isinstance(solver, FrontierSearchSolver)
        # the default stays the reference-parity host loop
        adef_host = AlgorithmDef.build_with_default_params("syncbb", {})
        assert isinstance(
            syncbb_mod.build_solver(dcop, None, adef_host),
            syncbb_mod.SyncBBSolver,
        )


# ---------------------------------------------------------------------------
# anytime semantics: monotone incumbent, bound sandwich, proof
# ---------------------------------------------------------------------------


class TestAnytime:
    def test_sandwich_and_monotone_incumbent(self):
        from pydcop_tpu.algorithms.ncbb import NcbbSolver

        dcop = make_dcop("dense", 3, n=9, D=3)
        optimum = NcbbSolver(dcop).run().cost
        # a weak bound (i_bound=1) forces a real search: many chunks,
        # a live sandwich, and a late proof
        res = frontier(dcop, frontier_width=8, steps=2,
                       i_bound=1).run(collect_cycles=True)
        assert res.search["optimal"]
        assert res.cost == optimum
        ub = [h["upper_bound"] for h in res.history]
        lb = [h["lower_bound"] for h in res.history]
        inc = [h["cost"] for h in res.history if h["cost"] is not None]
        assert len(res.history) >= 2
        assert all(b <= a + 1e-9 for a, b in zip(inc, inc[1:])), (
            "incumbent must be monotone non-increasing"
        )
        # spill chunks before the first clean one publish no bound
        pairs = [(lo, hi) for lo, hi in zip(lb, ub)
                 if lo is not None]
        assert pairs
        assert all(lo - 1e-6 <= optimum <= hi + 1e-6
                   for lo, hi in pairs)
        assert res.history[-1]["gap"] == 0.0

    def test_bound_source_tiers(self):
        dcop = make_dcop("dense", 3, n=7)
        exact = frontier(dcop, frontier_width=32)
        assert exact.plan.exact_heuristic
        assert exact.plan.info()["bound_source"] == "dpop-exact"
        weak = frontier(dcop, frontier_width=32, i_bound=1)
        assert not weak.plan.exact_heuristic
        assert weak.plan.info()["bound_source"] == "minibucket"
        # both admissible: identical proven optimum
        assert exact.run().cost == weak.run().cost

    def test_search_events_stream(self):
        from pydcop_tpu.runtime.events import event_bus

        got = []
        event_bus.enabled = True
        event_bus.subscribe("search.*", lambda t, e: got.append((t, e)))
        try:
            dcop = make_dcop("chain", 2, n=8)
            frontier(dcop, frontier_width=16).run()
        finally:
            event_bus.enabled = False
            event_bus._subs = [
                (t, cb) for t, cb in event_bus._subs
                if t != "search.*"
            ]
        bounds = [e for t, e in got if t == "search.bounds"]
        assert bounds, "search.bounds must stream per chunk"
        assert {"incumbent", "lower_bound", "upper_bound",
                "gap", "proved"} <= set(bounds[0])
        assert any(t == "search.done" for t, _e in got)


# ---------------------------------------------------------------------------
# spill fallback: ring + annex, counted, lossless
# ---------------------------------------------------------------------------


class TestSpill:
    def test_tiny_slab_spills_losslessly(self):
        from pydcop_tpu.algorithms.syncbb import SyncBBSolver

        dcop = make_dcop("dense", 7, n=8, D=3)
        host = SyncBBSolver(dcop).run()
        res = frontier(dcop, frontier_width=4, ring=8, steps=3,
                       i_bound=1).run()
        s = res.search
        assert s["optimal"] and res.cost == host.cost
        assert s["spill_drains"] > 0, "the annex path must engage"
        assert s["spill_rows"] > 0
        assert s["reinjected_rows"] == s["spill_rows"]
        assert s["lost_rows"] == 0
        assert s["stash_rows"] == 0

    def test_no_spill_on_roomy_slab(self):
        dcop = make_dcop("chain", 1, n=8)
        res = frontier(dcop, frontier_width=64).run()
        s = res.search
        assert s["spill_drains"] == 0 and s["spill_rows"] == 0
        assert s["lost_rows"] == 0


# ---------------------------------------------------------------------------
# host-traffic discipline: 2 scalars per chunk, one trace, audited
# ---------------------------------------------------------------------------


class TestDiscipline:
    def test_chunk_outputs_two_scalars_beside_state(self):
        """The jaxpr-level pin of the PR 4 discipline: the chunk
        runner's only output that is NOT the donated state pytree is
        one [2] f32 vector — incumbent + bound."""
        import jax

        dcop = make_dcop("chain", 1, n=8)
        s = frontier(dcop, frontier_width=16)
        runner = s.engine.chunk_runner()
        state = s.initial_state()
        out_state, out_stats = jax.eval_shape(runner, state)
        assert set(out_state) == set(state)
        assert out_stats.shape == (2,)
        assert out_stats.dtype == np.float32

    def test_single_trace_across_runs_and_counted_reads(self):
        dcop = make_dcop("chain", 4, n=10)
        s = frontier(dcop, frontier_width=16, steps=2)
        r1 = s.run(cycles=2)
        r2 = s.run(cycles=50, resume=True)
        assert s.trace_count() == 1, (
            "chunk runner must compile once, not per run"
        )
        assert r2.search["optimal"]
        # steady state (no spill): exactly 2 scalars per chunk
        for r in (r1, r2):
            if r.search["spill_drains"] == 0:
                assert (r.search["scalar_reads"]
                        == 2 * r.search["chunks"])

    def test_registry_carries_the_budget_cells(self):
        from pydcop_tpu.analysis import registry

        names = registry.cell_names()
        assert "search/frontier/chunk" in names
        assert "search/frontier/expand-step" in names
        # audited clean here too (the parametrized sweep in
        # test_analysis covers every cell; this pins the contract
        # from the search side so a registry regression names it)
        rep = registry.audit_cell("search/frontier/chunk")
        assert rep.ok, [f.to_dict() for f in rep.findings]
        assert rep.scorecard["host_callbacks"] == 0

    def test_config_engine_recorded(self):
        dcop = make_dcop("chain", 2, n=8)
        res = frontier(dcop, frontier_width=16).run()
        assert res.config["engine"] == "frontier"
        assert res.config["algo"] == "syncbb"
        assert res.config["i_bound"] == res.search["i_bound"]


# ---------------------------------------------------------------------------
# the dpop auto ladder (the ISSUE 15 acceptance scenario)
# ---------------------------------------------------------------------------


def _clique(K: int, D: int, seed: int) -> DCOP:
    rng = np.random.default_rng(seed)
    dcop = DCOP("clique", objective="min")
    dom = Domain("d", "v", list(range(D)))
    vs = [Variable(f"v{i:02d}", dom) for i in range(K)]
    for v in vs:
        dcop.add_variable(v)
    k = 0
    for i in range(K):
        for j in range(i + 1, K):
            m = rng.integers(0, 10, (D, D)).astype(float)
            dcop.add_constraint(
                NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{k}")
            )
            k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


class TestDpopLadder:
    def test_auto_proves_where_minibucket_was_the_ceiling(self):
        """The acceptance pin: a high-width instance whose util table
        busts the budget on every device USED to degrade to the
        mini-bucket bound sandwich (no exact answer); the frontier
        tier now closes the gap to 0 and returns the true optimum."""
        from pydcop_tpu.runtime.run import solve_result

        dcop = _clique(10, 4, 3)  # induced width 9: 4^10-entry table
        budget = {"budget_mb": 0.05, "i_bound": 2}
        # pre-ISSUE behavior, still reachable by forcing the engine:
        # bounds with a nonzero gap, not an exact answer
        mb = solve_result(
            dcop, "dpop", algo_params={**budget,
                                       "engine": "minibucket"})
        assert mb.dpop["gap"] > 0
        # the auto ladder now lands on the frontier tier and PROVES
        res = solve_result(dcop, "dpop", algo_params=budget)
        assert res.config["engine"] == "frontier"
        assert res.search["optimal"]
        exact = solve_result(dcop, "dpop")  # unbudgeted sweep
        assert res.cost == exact.cost
        # and the mini-bucket sandwich indeed bracketed this optimum
        assert (mb.dpop["lower_bound"] - 1e-6 <= res.cost
                <= mb.dpop["upper_bound"] + 1e-6)

    def test_bulk_instances_still_fall_through(self):
        """Outside the search regime (large n) the ladder must not
        burn the frontier node budget: it degrades to mini-bucket
        bounds exactly as before."""
        from pydcop_tpu.algorithms.dpop import DpopSolver
        from pydcop_tpu.portfolio.select import FRONTIER_MAX_VARS

        dcop = make_dcop("chain", 0, n=8)
        solver = DpopSolver(dcop)
        # fake a bulk instance by lowering the regime ceiling
        import pydcop_tpu.portfolio.select as sel
        old = sel.FRONTIER_MAX_VARS
        sel.FRONTIER_MAX_VARS = 4
        try:
            assert solver._run_frontier() is None
        finally:
            sel.FRONTIER_MAX_VARS = old
        assert FRONTIER_MAX_VARS == old

    def test_forced_frontier_engine_on_dpop(self):
        from pydcop_tpu.runtime.run import solve_result

        dcop = make_dcop("dense", 9, n=7)
        res = solve_result(dcop, "dpop",
                           algo_params={"engine": "frontier"})
        assert res.search["optimal"]
        exact = solve_result(dcop, "dpop")
        assert res.cost == exact.cost


# ---------------------------------------------------------------------------
# portfolio surface
# ---------------------------------------------------------------------------


class TestPortfolioArm:
    def test_grid_has_the_frontier_arm_and_masks_bulk(self):
        from pydcop_tpu.portfolio.select import (
            DEFAULT_GRID,
            FRONTIER_MAX_VARS,
            feasible_grid,
        )

        arm = [c for c in DEFAULT_GRID
               if c.algo == "syncbb" and c.engine == "frontier"]
        assert len(arm) == 1
        small = {"n_vars": 24, "max_domain": 4,
                 "sweep_bytes": 10**12, "max_node_entries": 10**11}
        feasible, _ = feasible_grid(DEFAULT_GRID, small, n_devices=1)
        assert arm[0] in feasible
        bulk = {"n_vars": FRONTIER_MAX_VARS + 1, "max_domain": 4}
        feasible, masked = feasible_grid(DEFAULT_GRID, bulk,
                                         n_devices=1)
        assert arm[0] not in feasible
        assert any(c == arm[0] for c, _r in masked)

    def test_config_encoding_covers_frontier(self):
        from pydcop_tpu.portfolio.features import (
            ALGO_CHOICES,
            ENGINE_CHOICES,
            encode_config,
        )
        from pydcop_tpu.portfolio.select import PortfolioConfig

        assert "syncbb" in ALGO_CHOICES
        assert "frontier" in ENGINE_CHOICES
        enc = encode_config(
            PortfolioConfig("syncbb", engine="frontier")
        )
        assert enc[ALGO_CHOICES.index("syncbb")] == 1.0
        assert enc[len(ALGO_CHOICES)
                   + ENGINE_CHOICES.index("frontier")] == 1.0

    def test_frontier_arm_executes_through_solve_auto_path(self):
        from pydcop_tpu.portfolio.select import PortfolioConfig
        from pydcop_tpu.runtime.run import solve_result

        cfg = PortfolioConfig("syncbb", engine="frontier")
        dcop = make_dcop("dense", 5, n=6)
        res = solve_result(dcop, cfg.algo,
                           algo_params=cfg.algo_params(),
                           **cfg.solve_kwargs())
        assert res.search is not None and res.search["optimal"]


# ---------------------------------------------------------------------------
# checkpoint / resume on the exact search state
# ---------------------------------------------------------------------------


class TestCheckpointResume:
    def test_resume_lands_on_the_search_state(self, tmp_path):
        from pydcop_tpu.runtime.run import solve_result

        dcop = _clique(9, 4, 5)
        # incumbent seeding off: the point is cutting a MULTI-chunk
        # run short and resuming, and the seeded dive proves this
        # instance within a single chunk
        params = {"engine": "frontier", "frontier_width": 64,
                  "search_chunk": 2, "seed_incumbent": False}
        clean = solve_result(dcop, "syncbb", algo_params=params)
        assert clean.search["optimal"] and clean.cycle > 2
        # cut the run short, snapshots on; then resume to completion
        part = solve_result(dcop, "syncbb", algo_params=params,
                            cycles=2, checkpoint_dir=str(tmp_path),
                            checkpoint_every=1)
        assert not part.search["optimal"]
        assert any(p.suffix == ".npz" for p in tmp_path.iterdir())
        res = solve_result(dcop, "syncbb", algo_params=params,
                           cycles=500, checkpoint_dir=str(tmp_path),
                           checkpoint_every=50, resume=True)
        assert res.search["optimal"]
        assert res.cost == clean.cost
        assert res.assignment == clean.assignment

    def test_corrupt_snapshot_skipped_on_resume(self, tmp_path):
        from pydcop_tpu.runtime.faults import corrupt_checkpoint
        from pydcop_tpu.runtime.run import solve_result

        dcop = make_dcop("dense", 6, n=7)
        params = {"engine": "frontier", "frontier_width": 16,
                  "search_chunk": 2}
        solve_result(dcop, "syncbb", algo_params=params, cycles=3,
                     checkpoint_dir=str(tmp_path), checkpoint_every=1)
        snaps = sorted(p for p in tmp_path.iterdir()
                       if p.suffix == ".npz")
        corrupt_checkpoint(str(snaps[-1]), seed=3)
        res = solve_result(dcop, "syncbb", algo_params=params,
                           cycles=500, checkpoint_dir=str(tmp_path),
                           checkpoint_every=100, resume=True)
        assert res.search["optimal"]
