"""Lane-packed sharded engines (VERDICT r4 item 3): the per-shard
pallas kernels inside shard_map must bit-match both the generic sharded
engine and the single-device engine, on the virtual 8-device CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.ops.compile import (
    compile_constraint_graph,
    compile_factor_graph,
)
from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
from pydcop_tpu.parallel.mesh import (
    ShardedLocalSearch,
    ShardedMaxSum,
    build_mesh,
)
from pydcop_tpu.parallel.packed_mesh import build_shard_packs


def _instance(n_vars=60, n_edges=120, seed=1):
    return generate_graph_coloring(
        n_variables=n_vars, n_colors=3, n_edges=n_edges, soft=True,
        n_agents=1, seed=seed,
    )


class TestBuildShardPacks:
    def test_uniform_structure(self):
        t = compile_factor_graph(_instance())
        sp = build_shard_packs(t, 4)
        assert sp is not None
        # stacked arrays carry one entry per shard with common statics;
        # the column map itself is shard-invariant (pg0.var_order)
        assert sp.cost_rows.shape == (4, sp.D * sp.D, sp.N)
        assert sp.unary_p.shape == (sp.D, sp.Vp)
        assert sp.pg0.var_order.shape[0] == t.n_vars
        assert all(c.shape[0] == 4 for c in sp.consts)

    def test_every_factor_packed_once(self):
        t = compile_factor_graph(_instance())
        sp = build_shard_packs(t, 4)
        # total non-dummy slots across shards = 2F directed edges
        total_real = int(np.asarray(sp.vmask)[:, 0, :].sum())
        assert total_real == 2 * t.n_factors

    def test_mixed_arity_packs(self):
        """ROADMAP item 7 (round 5): SECP-class mixed (1/2/3) graphs
        build per-shard packs under one shared MixedLayout."""
        from pydcop_tpu.generators.secp import generate_secp

        dcop = generate_secp(n_lights=8, n_models=3, n_rules=2,
                             max_model_size=2, seed=1)
        t = compile_factor_graph(dcop)
        sp = build_shard_packs(t, 4)
        assert sp is not None and sp.mixed
        assert sp.cost1_rows.shape[0] == 4
        # section-derived arity masks are shard-invariant singles
        assert sp.am2.shape == (1, sp.N)

    def test_quaternary_packs(self):
        """SECP with 3-light models (arity 4) packs too (round 5)."""
        from pydcop_tpu.generators.secp import generate_secp

        dcop = generate_secp(n_lights=10, n_models=3, n_rules=2,
                             max_model_size=3, seed=1)
        t = compile_factor_graph(dcop)
        assert any(b.arity == 4 for b in t.buckets)
        sp = build_shard_packs(t, 4)
        assert sp is not None and sp.cost4_rows is not None

    def test_mixed_rejects_high_arity(self):
        """Arity > 4 still falls back to the generic sharded engine."""
        from pydcop_tpu.generators.secp import generate_secp

        dcop = generate_secp(n_lights=10, n_models=6, n_rules=2,
                             max_model_size=4, seed=1)
        t = compile_factor_graph(dcop)
        assert any(b.arity > 4 for b in t.buckets)
        assert build_shard_packs(t, 4) is None

    def test_rejects_megascale_cheaply(self):
        """The A-budget pre-check fires before any per-shard layout."""
        import time

        t = compile_factor_graph(_instance())
        # fake a huge factor count through the arity-2 bucket check
        class FakeBucket:
            arity = 2
            n_factors = 10_000_000
            var_idx = np.zeros((1, 2), np.int32)

        import dataclasses

        t2 = dataclasses.replace(t, buckets=[FakeBucket()])
        t0 = time.perf_counter()
        assert build_shard_packs(t2, 8) is None
        assert time.perf_counter() - t0 < 1.0


class TestPackedShardedMaxSum:
    def test_matches_single_device_and_generic(self):
        t = compile_factor_graph(_instance())
        q, r = init_messages(t)
        for _ in range(8):
            q, r, _bel, vals = maxsum_cycle(t, q, r, damping=0.5)

        mesh = build_mesh(8)
        packed = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True)
        assert packed.packs is not None
        vp, _, _ = packed.run(cycles=8)
        np.testing.assert_array_equal(vp, np.asarray(vals))

        generic = ShardedMaxSum(t, mesh, damping=0.5, use_packed=False)
        assert generic.packs is None
        vg, _, _ = generic.run(cycles=8)
        np.testing.assert_array_equal(vg, vp)

    def test_cpu_mesh_defaults_to_generic(self):
        """On a CPU mesh the auto default picks the platform-native
        generic engine (the pallas kernels would run emulated)."""
        t = compile_factor_graph(_instance())
        solver = ShardedMaxSum(t, build_mesh(4), damping=0.5)
        assert solver.packs is None

    def test_chunked_continuation(self):
        t = compile_factor_graph(_instance())
        mesh = build_mesh(4)
        packed = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True)
        v_full, _, _ = packed.run(cycles=8)
        v1, q1, r1 = packed.run(cycles=4)
        v2, _, _ = packed.run(cycles=4, q=q1, r=r1)
        np.testing.assert_array_equal(v2, v_full)

    def test_activation_masks_run(self):
        t = compile_factor_graph(_instance())
        a = ShardedMaxSum(t, build_mesh(4), damping=0.5, activation=0.6,
                          use_packed=True)
        va, _, _ = a.run(cycles=6)
        assert va.shape == (t.n_vars,)

    def test_activation_rotated_semantics_pinned(self):
        """Regression pin for the rotated activation (amaxsum) path:
        the pending-commit key rides ONE launch behind (key_p), and the
        commit selects pick the fresh q/r on active slots.  Verified
        bit-identical to the pre-rotation two-launch engine when the
        rotation landed (code-review r5); the golden array pins that
        semantics — a future edit that folds the wrong key or swaps a
        where-arm changes these values."""
        dcop = generate_graph_coloring(
            n_variables=24, n_colors=3, n_edges=40, soft=True,
            n_agents=1, seed=7,
        )
        t = compile_factor_graph(dcop)
        mesh = build_mesh(4)
        a = ShardedMaxSum(t, mesh, damping=0.5, activation=0.6,
                          use_packed=True)
        va, _, _ = a.run(cycles=6, seed=11)
        if jax.devices()[0].platform == "cpu" and hasattr(jax,
                                                          "shard_map"):
            # the pinned values were produced by the CPU interpret-mode
            # run of the packed kernels on a jax with native
            # jax.shard_map; real TPU Mosaic lowering may legitimately
            # differ in float association on near-ties, and older jax
            # (experimental shard_map) draws a slightly different
            # activation stream — so the exact golden is only asserted
            # on the stack that minted it (ADVICE r5); the semantic
            # assertions below run everywhere
            golden = [0, 2, 2, 1, 0, 2, 0, 0, 0, 0, 0, 1, 0, 0, 1, 2, 1,
                      2, 0, 1, 2, 1, 0, 2]
            np.testing.assert_array_equal(va, golden)
        plain = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True)
        vp, _, _ = plain.run(cycles=6)
        # masking has an effect at 0.6 ...
        assert (va != vp).any()
        # ... and an (effectively) always-active mask reduces to the
        # slim no-activation engine exactly, pinning the where-arm
        # orientation (stale-carry arms would win everywhere instead)
        near_one = ShardedMaxSum(t, mesh, damping=0.5,
                                 activation=0.9999999, use_packed=True)
        vn, _, _ = near_one.run(cycles=6, seed=11)
        np.testing.assert_array_equal(vn, vp)

    def test_placement_assigns_drive_packs(self):
        """An explicit factor→shard assignment flows into the packed
        layout (the placement-driven solve path)."""
        t = compile_factor_graph(_instance())
        rng = np.random.default_rng(3)
        assigns = [rng.integers(0, 4, t.n_factors)]
        mesh = build_mesh(4)
        packed = ShardedMaxSum(t, mesh, damping=0.5, assigns=assigns,
                               use_packed=True)
        assert packed.packs is not None
        vp, _, _ = packed.run(cycles=8)
        generic = ShardedMaxSum(t, mesh, damping=0.5, assigns=assigns,
                                use_packed=False)
        vg, _, _ = generic.run(cycles=8)
        np.testing.assert_array_equal(vp, vg)


def _secp_instance(seed=3, **kw):
    from pydcop_tpu.generators.secp import generate_secp

    kw.setdefault("n_lights", 30)
    kw.setdefault("n_models", 10)
    kw.setdefault("n_rules", 6)
    kw.setdefault("max_model_size", 2)
    return generate_secp(seed=seed, **kw)


class TestMixedPackedSharded:
    """ROADMAP item 7 (round 5): the mixed-arity (1/2/3) family rides
    the lane-packed per-shard kernels, bit-matching the generic sharded
    engine."""

    def test_maxsum_matches_generic(self):
        t = compile_factor_graph(_secp_instance())
        mesh = build_mesh(4)
        packed = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True)
        assert packed.packs is not None and packed.packs.mixed
        vp, _, _ = packed.run(cycles=8)
        generic = ShardedMaxSum(t, mesh, damping=0.5, use_packed=False)
        vg, _, _ = generic.run(cycles=8)
        np.testing.assert_array_equal(vp, vg)

    def test_sparse_ternary_shards_and_chunking(self):
        """Shards with NO ternary factors keep the shard-invariant
        traced structure (zero cost3 rows, identity plan2), and the
        rotated-launch state round-trips across chunks."""
        t = compile_factor_graph(_secp_instance(
            seed=5, n_lights=40, n_models=4, n_rules=2))
        assert any(b.arity == 3 for b in t.buckets)
        mesh = build_mesh(8)
        packed = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True)
        assert packed.packs is not None
        v1, q1, r1 = packed.run(cycles=4)
        v2, _, _ = packed.run(cycles=4, q=q1, r=r1)
        generic = ShardedMaxSum(t, mesh, damping=0.5, use_packed=False)
        vg, _, _ = generic.run(cycles=8)
        np.testing.assert_array_equal(v2, vg)

    def test_mgm_matches_generic(self):
        """MGM is coin-free, so the packed mixed-arity move rule stays
        trajectory-identical to the generic sharded engine."""
        from pydcop_tpu.ops.compile import compile_constraint_graph

        t = compile_constraint_graph(_secp_instance(seed=4))
        mesh = build_mesh(4)
        packed = ShardedLocalSearch(t, mesh, rule="mgm", use_packed=True)
        assert packed.packs is not None and packed.packs.mixed
        generic = ShardedLocalSearch(t, mesh, rule="mgm",
                                     use_packed=False)
        np.testing.assert_array_equal(
            packed.run(cycles=8, seed=3), generic.run(cycles=8, seed=3)
        )

    @pytest.mark.parametrize("rule", ["dsa", "adsa"])
    def test_stochastic_rules_coin_degenerate_match(self, rule):
        """dsa/adsa draw their coins in COLUMN space (the PRNG stream
        break, docs/performance.rst) so they no longer bit-match the
        generic engine — EXCEPT where the coins cannot matter: at
        probability 1 (and adsa variant C, activation 1) every draw
        passes on both sides, making the move rule deterministic and
        the packed mixed-arity trajectory exactly the generic one."""
        from pydcop_tpu.ops.compile import compile_constraint_graph

        t = compile_constraint_graph(_secp_instance(seed=4))
        mesh = build_mesh(4)
        params = (
            {"activation": 1.0, "variant": "C"} if rule == "adsa" else {}
        )
        packed = ShardedLocalSearch(t, mesh, rule=rule, probability=1.0,
                                    algo_params=params, use_packed=True)
        assert packed.packs is not None and packed.packs.mixed
        generic = ShardedLocalSearch(t, mesh, rule=rule, probability=1.0,
                                     algo_params=params, use_packed=False)
        np.testing.assert_array_equal(
            packed.run(cycles=8, seed=3), generic.run(cycles=8, seed=3)
        )

    def test_quaternary_matches_generic(self):
        """Arity-4 SECP (3-light models) through the sharded packed
        engines — MaxSum and MGM both bit-match generic (round 5)."""
        from pydcop_tpu.ops.compile import compile_constraint_graph

        dcop = _secp_instance(seed=3, max_model_size=3)
        t = compile_factor_graph(dcop)
        assert any(b.arity == 4 for b in t.buckets)
        mesh = build_mesh(4)
        packed = ShardedMaxSum(t, mesh, damping=0.5, use_packed=True)
        assert packed.packs is not None
        assert packed.packs.cost4_rows is not None
        vp, _, _ = packed.run(cycles=8)
        generic = ShardedMaxSum(t, mesh, damping=0.5, use_packed=False)
        vg, _, _ = generic.run(cycles=8)
        np.testing.assert_array_equal(vp, vg)
        tc = compile_constraint_graph(dcop)
        pls = ShardedLocalSearch(tc, mesh, rule="mgm", use_packed=True)
        gls = ShardedLocalSearch(tc, mesh, rule="mgm", use_packed=False)
        np.testing.assert_array_equal(
            pls.run(cycles=8, seed=3), gls.run(cycles=8, seed=3)
        )


class TestPackedShardedLocalSearch:
    def test_mgm_matches_generic_sharded(self):
        """MGM has no move-rule randomness, so the lane-packed cycle
        (packed tables + psum + routed-gain pmax/pmin arbitration) is
        trajectory-identical to the generic sharded engine."""
        t = compile_constraint_graph(_instance(seed=2))
        mesh = build_mesh(8)
        packed = ShardedLocalSearch(t, mesh, rule="mgm", use_packed=True)
        assert packed.packs is not None
        generic = ShardedLocalSearch(t, mesh, rule="mgm",
                                     use_packed=False)
        np.testing.assert_array_equal(
            packed.run(cycles=8, seed=3), generic.run(cycles=8, seed=3)
        )

    @pytest.mark.parametrize("rule,params", [
        ("dsa", {}),
        ("adsa", {"activation": 1.0, "variant": "C"}),
    ])
    def test_stochastic_coin_degenerate_matches_generic(self, rule,
                                                        params):
        """At probability 1 (adsa: plus activation 1, variant C) every
        coin passes on both engines, so the column-space PRNG stream
        break cannot show and the packed trajectory must equal the
        generic one exactly — pinning that ONLY the coin stream (not
        the tables / gains / move semantics) differs."""
        t = compile_constraint_graph(_instance(seed=2))
        mesh = build_mesh(8)
        packed = ShardedLocalSearch(t, mesh, rule=rule, probability=1.0,
                                    algo_params=params, use_packed=True)
        assert packed.packs is not None
        generic = ShardedLocalSearch(t, mesh, rule=rule, probability=1.0,
                                     algo_params=params, use_packed=False)
        np.testing.assert_array_equal(
            packed.run(cycles=8, seed=3), generic.run(cycles=8, seed=3)
        )

    def test_dsa_statistical_equivalence(self):
        """The packed dsa consumes a DIFFERENT coin stream (column-space
        draws) but the same move semantics: over several seeds its final
        solution quality must match the generic engine's within a
        tolerance band — the statistical-equivalence replacement for the
        old bit-match test (the stream break is documented in
        docs/performance.rst)."""
        import jax.numpy as jnp

        from pydcop_tpu.ops.compile import total_cost

        t = compile_constraint_graph(_instance(seed=2))
        mesh = build_mesh(8)
        costs_p, costs_g = [], []
        for s in range(6):
            p = ShardedLocalSearch(t, mesh, rule="dsa", use_packed=True)
            g = ShardedLocalSearch(t, mesh, rule="dsa", use_packed=False)
            costs_p.append(float(total_cost(
                t, jnp.asarray(p.run(cycles=30, seed=s)))))
            costs_g.append(float(total_cost(
                t, jnp.asarray(g.run(cycles=30, seed=s)))))
        mp, mg = np.mean(costs_p), np.mean(costs_g)
        assert mp <= mg * 1.15 + 1.0, (costs_p, costs_g)
        # and the descent actually happened (not a frozen assignment)
        start = float(total_cost(t, jnp.asarray(
            ShardedLocalSearch(t, mesh, rule="dsa",
                               use_packed=True).run(cycles=1, seed=0))))
        assert mp < start

    def test_dsa_solves_csp_instance(self):
        """The packed dsa still SOLVES: on the satisfiable hard-coloring
        instance it reaches a zero-violation assignment from some seed
        (the same bar the generic sharded engine meets in
        test_parallel.py::test_sharded_dsa_solves_csp)."""
        import os

        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(os.path.join(
            os.path.dirname(__file__), "..", "instances",
            "coloring_csp.yaml"))
        t = compile_constraint_graph(dcop)
        results = []
        for s in range(4):
            solver = ShardedLocalSearch(t, build_mesh(2), rule="dsa",
                                        use_packed=True)
            assert solver.packs is not None
            values = solver.run(cycles=60, seed=s)
            assignment = t.assignment_from_indices(values)
            results.append(dcop.solution_cost(assignment, 10000))
        assert (0, 0) in results, results

    @pytest.mark.parametrize("rule,golden", [
        # pinned on the stack that minted them (CPU interpret-mode
        # pallas + experimental shard_map — symmetric to the activation
        # pin above, which guards on the NATIVE-shard_map stack): the
        # column-space coin stream is part of the engine's contract now,
        # so an edit that changes the key folding or the draw shape
        # must show up here as a golden break, not pass silently
        ("dsa", [2, 2, 1, 2, 0, 1, 0, 0, 0, 2, 2, 1, 0, 0, 1, 2, 1, 1,
                 0, 1, 2, 0, 2, 2]),
        ("adsa", [2, 2, 1, 2, 1, 2, 1, 0, 0, 1, 0, 2, 0, 0, 1, 2, 2, 2,
                  0, 0, 2, 0, 2, 2]),
    ])
    def test_stochastic_golden_stream(self, rule, golden):
        dcop = generate_graph_coloring(
            n_variables=24, n_colors=3, n_edges=40, soft=True,
            n_agents=1, seed=7,
        )
        t = compile_constraint_graph(dcop)
        solver = ShardedLocalSearch(t, build_mesh(4), rule=rule,
                                    use_packed=True)
        got = solver.run(cycles=8, seed=11)
        assert got.shape == (24,)
        if (jax.devices()[0].platform == "cpu"
                and not hasattr(jax, "shard_map")):
            np.testing.assert_array_equal(got, golden)

    def test_collective_budget_via_registry(self):
        """The packed move rule's collective budget — ONE psum of
        partial tables, plus the pmax/pmin arbitration pair for MGM —
        is now DECLARED next to the engine
        (ShardedLocalSearch.program_budget) and audited by the
        analysis registry sweep (ISSUE 13), which replaced the string
        pins that used to live here."""
        from pydcop_tpu.analysis import registry

        mgm = registry.build_cell("sharded/mgm/packed/off")
        assert mgm.budget.collectives == {
            "psum": 1, "pmax": 1, "pmin": 1, "ppermute": 0,
        }
        dsa = registry.build_cell("sharded/dsa/packed/off")
        assert dsa.budget.collectives == {
            "psum": 1, "pmax": 0, "pmin": 0, "ppermute": 0,
        }
        for cell in ("sharded/mgm/packed/off", "sharded/dsa/packed/off"):
            rep = registry.audit_cell(cell)
            assert rep.ok, [f.to_dict() for f in rep.findings]

    def test_collective_budget_legacy_pin(self):
        """LEGACY jaxpr string pin, MGM only (kept as a cross-check on
        the auditor's jaxpr walker — an auditor bug that stopped
        seeing collectives would not break the audit sweep, but it
        would break this)."""
        import re

        import jax.numpy as jnp

        t = compile_constraint_graph(_instance(seed=2))
        mesh = build_mesh(8)
        s = ShardedLocalSearch(t, mesh, rule="mgm", use_packed=True,
                               overlap="off")
        s._build()
        x_row = jnp.zeros((1, s.packs.Vp), jnp.float32)
        keys = jax.random.split(jax.random.PRNGKey(0), 1)
        jaxpr = str(jax.make_jaxpr(s._run_n)(
            x_row, keys, (), *s._bucket_args, *s._extra_args))
        assert len(re.findall(r"\bpsum", jaxpr)) == 1
        assert len(re.findall(r"\bpmax\b", jaxpr)) == 1
        assert len(re.findall(r"\bpmin\b", jaxpr)) == 1

    def test_mgm_matches_single_device(self):
        from pydcop_tpu.algorithms._local_search import (
            gains_and_best,
            neighborhood_winner,
            random_valid_values,
        )
        from pydcop_tpu.ops.compile import local_cost_tables

        t = compile_constraint_graph(_instance(seed=4))
        x = random_valid_values(t, jax.random.PRNGKey(17))
        state = x
        for _ in range(8):
            _cur, best, gain, _ = gains_and_best(
                t, state, tables=local_cost_tables(t, state))
            move = neighborhood_winner(t, gain)
            state = jnp.where(move, best, state).astype(jnp.int32)

        packed = ShardedLocalSearch(t, build_mesh(8), rule="mgm",
                                    use_packed=True)
        got = packed.run(cycles=8, seed=0)
        np.testing.assert_array_equal(got, np.asarray(state))

    def test_weighted_rules_stay_generic(self):
        t = compile_constraint_graph(_instance(seed=5))
        dba = ShardedLocalSearch(t, build_mesh(4), rule="dba",
                                 use_packed=True)
        assert dba.packs is None
        assert dba.run(cycles=4, seed=1).shape == (t.n_vars,)
