"""Program auditor + budget registry + source lint (ISSUE 13).

The contract under test:

* every registered engine×mode cycle program audits CLEAN against the
  budget declared next to its cycle fn — ONE parametrized sweep
  replacing the ad-hoc per-file jaxpr pins (the matrix covers ≥ 20
  programs: single-device harness, warm, batch bucket runner, sharded
  generic/packed × dense/compact/stale/exchange, DPOP per-level
  steps);
* a budget with ANY field (or collective kind) left undeclared fails
  loudly — an engine cannot opt out of a dimension by forgetting it;
* each auditor check fires on a violating program (collective count /
  payload bytes / host callback / dtype tier / embedded constants);
* each lint rule has a minimal positive fixture that fires and a
  negative that stays silent; waivers suppress only WITH a reason;
* removing ANY ``with self._lock:`` acquisition in serve/fleet.py
  makes the race rule fire (mutated-copy sweep) — the lock discipline
  is load-bearing, not decorative;
* the docs rule catalog (docs/analysis.rst) stays in sync with
  ``LINT_RULES`` and the ``ProgramBudget`` fields (PR 12
  fault-catalog style).
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.analysis import (
    COLLECTIVE_KINDS,
    BudgetUndeclared,
    LINT_RULES,
    ProgramBudget,
    audit_program,
    lint_source,
)
from pydcop_tpu.analysis import registry
from pydcop_tpu.analysis.auditor import donation_applied

REPO = os.path.join(os.path.dirname(__file__), "..", "..")


def full_budget(**over):
    base = dict(
        collectives={k: 0 for k in COLLECTIVE_KINDS},
        max_collective_bytes=0,
        max_host_callbacks=0,
        dtypes={"float32", "int32", "uint32", "bool", "key<fry>"},
        max_const_bytes=1 << 20,
        donate=False,
    )
    base.update(over)
    return ProgramBudget(**base)


# ---------------------------------------------------------------------------
# budget declaration discipline


class TestBudgetDeclarations:
    def test_undeclared_field_fails_loudly(self):
        budget = ProgramBudget(max_host_callbacks=0)
        with pytest.raises(BudgetUndeclared, match="collectives"):
            audit_program(lambda x: x * 2, (jnp.zeros(3),), budget)

    def test_undeclared_collective_kind_fails_loudly(self):
        budget = full_budget(collectives={"psum": 1})
        with pytest.raises(BudgetUndeclared, match="ppermute"):
            audit_program(lambda x: x * 2, (jnp.zeros(3),), budget)

    def test_fully_declared_budget_passes_validate(self):
        full_budget().validate()


# ---------------------------------------------------------------------------
# auditor checks, each demonstrated on a violating program


class TestAuditorChecks:
    def test_clean_program_audits_clean(self):
        rep = audit_program(
            lambda x: jnp.sum(x * 2), (jnp.zeros(3),), full_budget(),
            name="t",
        )
        assert rep.ok
        assert rep.scorecard["host_callbacks"] == 0

    def test_host_callback_detected(self):
        def f(x):
            return jax.pure_callback(
                lambda v: np.asarray(v),
                jax.ShapeDtypeStruct((), jnp.float32), x[0],
            )

        rep = audit_program(f, (jnp.zeros(3),), full_budget())
        assert [g.rule for g in rep.findings] == ["budget-host-callback"]
        assert rep.scorecard["host_callbacks"] == 1

    def test_dtype_tier_violation_detected(self):
        rep = audit_program(
            lambda x: x * 2.0, (jnp.zeros(3),),
            full_budget(dtypes={"int32"}),
        )
        assert any(g.rule == "budget-dtype" for g in rep.findings)

    def test_embedded_constant_bytes_detected(self):
        table = jnp.asarray(
            np.random.default_rng(0).uniform(size=(64, 64))
            .astype(np.float32)
        )

        def f(x):
            return jnp.sum(x[:, None] * table)

        rep = audit_program(
            f, (jnp.zeros(64),), full_budget(max_const_bytes=128)
        )
        assert any(
            g.rule == "budget-const-bytes" for g in rep.findings
        )
        assert rep.scorecard["const_bytes"] >= 64 * 64 * 4

    def test_collective_count_violation_detected(self):
        """The dense sharded maxsum program against a ZERO-psum budget
        — the regression shape the old pin tests guarded."""
        prog = registry.build_cell("sharded/maxsum/generic/off")
        tight = dataclasses.replace(
            prog.budget, collectives={k: 0 for k in COLLECTIVE_KINDS}
        )
        rep = audit_program(prog.fn, prog.args, tight)
        assert any(
            g.rule == "budget-collective-count" for g in rep.findings
        )

    def test_collective_payload_violation_detected(self):
        """Dense payload against the compact slab's byte cap — the
        'compact mode regressed to whole-space psum' failure mode."""
        prog = registry.build_cell("sharded/maxsum/generic/off")
        compact = registry.build_cell("sharded/maxsum/generic/exact")
        cap = compact.budget.max_collective_bytes
        assert cap < prog.budget.max_collective_bytes
        tight = dataclasses.replace(
            prog.budget, max_collective_bytes=cap
        )
        rep = audit_program(prog.fn, prog.args, tight)
        assert any(
            g.rule == "budget-collective-bytes" for g in rep.findings
        )

    def test_donation_marks_detected_in_lowering(self):
        """The StableHLO aliasing matcher itself (CPU lowering still
        MARKS donation; XLA:CPU merely drops it at compile — so the
        audit records 'skipped' on CPU but the matcher is testable)."""
        x = jnp.zeros((8,), jnp.float32)
        with_don = jax.jit(
            lambda v: v * 2, donate_argnums=(0,)
        ).lower(x).as_text()
        without = jax.jit(lambda v: v * 2).lower(x).as_text()
        assert donation_applied(with_don)
        assert not donation_applied(without)

    def test_donation_skipped_on_cpu_backend(self):
        from pydcop_tpu.algorithms.base import donation_supported

        rep = audit_program(
            lambda x: x * 2, (jnp.zeros(3),),
            full_budget(donate=True),
        )
        if not donation_supported():
            assert rep.scorecard["donation"].startswith("skipped")
            assert rep.ok


# ---------------------------------------------------------------------------
# the registry sweep: every engine×mode cell within its declared budget


class TestBudgetSweep:
    def test_matrix_covers_the_engine_modes(self):
        names = registry.cell_names()
        assert len(names) >= 20
        for token in (
            "single/maxsum", "single/gdba", "warm/maxsum",
            "batch/mgm", "sharded/maxsum/generic/off",
            "sharded/maxsum/generic/exact",
            "sharded/maxsum/generic/stale",
            "sharded/maxsum/packed/exact",
            "sharded/mgm/packed/off", "sharded/dpop/util-step",
        ):
            assert token in names, token

    @pytest.mark.parametrize("cell", registry.cell_names())
    def test_cell_within_declared_budget(self, cell):
        rep = registry.audit_cell(cell)
        assert rep.ok, [f.to_dict() for f in rep.findings]

    def test_warm_engines_bake_less_than_cold(self):
        """The PR 8 operand-carry contract, via the auditor: a warm
        cycle program embeds strictly fewer constant bytes than its
        cold twin (tables travel as arguments, not closures)."""
        cold = registry.audit_cell("single/mgm").scorecard
        warm = registry.audit_cell("warm/mgm").scorecard
        assert warm["const_bytes"] < cold["const_bytes"]

    def test_batch_runner_bakes_nothing(self):
        sc = registry.audit_cell("batch/mgm").scorecard
        assert sc["const_bytes"] == 0

    def test_sweep_has_zero_host_callbacks_everywhere(self):
        for cell in ("single/maxsum", "sharded/maxsum/generic/exact",
                     "batch/maxsum"):
            assert registry.audit_cell(cell).scorecard[
                "host_callbacks"] == 0


# ---------------------------------------------------------------------------
# lint rules: positive fires / negative silent, per rule


class TestLintTracerRules:
    def test_host_pull_positive(self):
        src = (
            "import numpy as np\n"
            "def cycle_fn(x, key):\n"
            "    a = np.asarray(x)\n"
            "    b = float(x)\n"
            "    c = x.item()\n"
            "    return x\n"
        )
        rules = [f.rule for f in lint_source(src)]
        assert rules.count("host-pull-in-jit") == 3

    def test_host_pull_negative(self):
        src = (
            "import numpy as np\n"
            "def cycle_fn(x, key):\n"
            "    n = int(x.shape[0])\n"          # static metadata
            "    idx = np.arange(4)\n"           # static constant
            "    return x * 2\n"
            "def host_helper(y):\n"
            "    return float(np.asarray(y))\n"  # not a traced scope
        )
        assert lint_source(src) == []

    def test_time_positive_and_negative(self):
        pos = (
            "import time\n"
            "def run_chunk(state, keys):\n"
            "    t0 = time.time()\n"
            "    return state\n"
        )
        assert [f.rule for f in lint_source(pos)] == ["time-in-jit"]
        neg = (
            "import time\n"
            "def drive(state):\n"
            "    t0 = time.perf_counter()\n"
            "    return state\n"
        )
        assert lint_source(neg) == []

    def test_global_rng_positive_and_negative(self):
        pos = (
            "import numpy as np\n"
            "def dsa_cycle(x, key):\n"
            "    u = np.random.uniform(size=4)\n"
            "    return x\n"
        )
        assert [f.rule for f in lint_source(pos)] == [
            "global-rng-in-jit"
        ]
        neg = (
            "import numpy as np\n"
            "def dsa_cycle(x, key):\n"
            "    rng = np.random.default_rng(0)\n"  # local generator
            "    return x\n"
            "def build_instance(seed):\n"
            "    return np.random.uniform(size=4)\n"  # host scope
        )
        assert lint_source(neg) == []

    def test_structural_jit_detection(self):
        """A function is traced because it is PASSED to jit/scan, not
        because of its name."""
        src = (
            "import jax, time\n"
            "def helper(state, k):\n"
            "    t = time.time()\n"
            "    return state, None\n"
            "def drive(state, keys):\n"
            "    return jax.lax.scan(helper, state, keys)\n"
        )
        assert [f.rule for f in lint_source(src)] == ["time-in-jit"]


RACE_POSITIVE = """
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}
        self.rate = None

    def start(self):
        t = threading.Thread(target=self._loop)
        t.start()

    def _loop(self):
        with self._lock:
            self.rate = 1.0
        self._jobs["x"] = 1

    def result(self, jid):
        return self._jobs[jid]
"""

RACE_NEGATIVE = """
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}
        self.rate = None

    def start(self):
        t = threading.Thread(target=self._loop)
        t.start()

    def _loop(self):
        with self._lock:
            self.rate = 1.0
            self._jobs["x"] = 1

    def result(self, jid):
        with self._lock:
            return self._jobs[jid]
"""


class TestLintRaceRule:
    def test_unlocked_cross_thread_read_fires(self):
        findings = lint_source(
            RACE_POSITIVE, "pydcop_tpu/serve/fixture.py"
        )
        assert any(
            f.rule == "unlocked-shared-attr" and "result" in f.message
            for f in findings
        )

    def test_locked_access_is_silent(self):
        assert lint_source(
            RACE_NEGATIVE, "pydcop_tpu/serve/fixture.py"
        ) == []

    def test_rule_scoped_to_serving_tier(self):
        """The same pattern outside serve/ + batch/cache.py is out of
        scope (runtime/ui.py's asyncio server is single-threaded by
        design — documented in docs/analysis.rst)."""
        assert lint_source(
            RACE_POSITIVE, "pydcop_tpu/runtime/ui.py"
        ) == []

    def test_lock_held_private_method_is_silent(self):
        src = (
            "import threading\n"
            "class Svc:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.rate = None\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.rate = 1.0\n"
            "    def snap(self):\n"
            "        with self._lock:\n"
            "            return self._read()\n"
            "    def _read(self):\n"
            "        return self.rate\n"  # every call site holds lock
        )
        assert lint_source(src, "pydcop_tpu/serve/fixture.py") == []


class TestWaivers:
    POS = (
        "import time\n"
        "def cycle_fn(x):\n"
        "    t = time.time(){COMMENT}\n"
        "    return x\n"
    )

    def test_waiver_with_reason_suppresses(self):
        src = self.POS.replace(
            "{COMMENT}",
            "  # analyze: waive[time-in-jit] trace-time label only",
        )
        assert lint_source(src) == []

    def test_waiver_without_reason_is_an_error_and_suppresses_nothing(
            self):
        src = self.POS.replace(
            "{COMMENT}", "  # analyze: waive[time-in-jit]"
        )
        rules = sorted(f.rule for f in lint_source(src))
        assert rules == ["time-in-jit", "waiver-missing-reason"]

    def test_standalone_waiver_line_covers_next_line(self):
        src = (
            "import time\n"
            "def cycle_fn(x):\n"
            "    # analyze: waive[time-in-jit] profiling scaffold\n"
            "    t = time.time()\n"
            "    return x\n"
        )
        assert lint_source(src) == []

    def test_waiver_for_other_rule_does_not_suppress(self):
        src = self.POS.replace(
            "{COMMENT}",
            "  # analyze: waive[host-pull-in-jit] wrong rule",
        )
        assert [f.rule for f in lint_source(src)] == ["time-in-jit"]


# ---------------------------------------------------------------------------
# the shipped tree lints clean, and fleet.py's locks are load-bearing


class TestShippedTree:
    def test_package_lints_clean(self):
        from pydcop_tpu.analysis.lint import lint_paths

        findings = lint_paths([os.path.join(REPO, "pydcop_tpu")])
        assert findings == [], [f.to_dict() for f in findings]

    def test_removing_any_fleet_lock_fires_the_race_rule(self):
        """Mutated-fixture sweep: every ``with self._lock:``
        acquisition in serve/fleet.py, removed one at a time (the
        block body kept, the acquisition replaced by ``if True:``),
        must produce at least one unlocked-shared-attr finding — the
        discipline the rule encodes is exactly the discipline the
        fleet relies on."""
        path = os.path.join(REPO, "pydcop_tpu", "serve", "fleet.py")
        with open(path, encoding="utf-8") as f:
            src = f.read()
        lines = src.splitlines()
        lock_lines = [
            i for i, line in enumerate(lines)
            if re.search(r"with self\._lock:", line)
        ]
        assert len(lock_lines) >= 10  # the fleet really uses its lock
        lint_path = "pydcop_tpu/serve/fleet.py"
        assert lint_source(src, lint_path) == []
        for i in lock_lines:
            mutated = lines[:]
            mutated[i] = re.sub(
                r"with self\._lock:", "if True:", mutated[i]
            )
            findings = lint_source("\n".join(mutated), lint_path)
            assert any(
                f.rule == "unlocked-shared-attr" for f in findings
            ), f"removing the lock at line {i + 1} went undetected"


# ---------------------------------------------------------------------------
# docs catalog pins (PR 12 fault-catalog style)


class TestDocsCatalog:
    def _docs(self):
        path = os.path.join(REPO, "docs", "analysis.rst")
        with open(path, encoding="utf-8") as f:
            return f.read()

    def test_every_lint_rule_documented(self):
        text = self._docs()
        start = text.index("Rule catalog")
        section = text[start:]
        for rule in LINT_RULES:
            assert f"``{rule}``" in section, rule

    def test_no_phantom_rules_documented(self):
        text = self._docs()
        start = text.index("Rule catalog")
        end = text.index("Waiver policy")
        documented = set(re.findall(r"``([a-z][\w\-]+)``",
                                    text[start:end]))
        rule_like = {d for d in documented if "-" in d}
        assert rule_like <= set(LINT_RULES), (
            rule_like - set(LINT_RULES)
        )

    def test_every_budget_field_documented(self):
        text = self._docs()
        for f in dataclasses.fields(ProgramBudget):
            assert f"``{f.name}``" in text, f.name


# ---------------------------------------------------------------------------
# CLI scorecard


@pytest.mark.slow
class TestAnalyzeCliSweep:
    def test_program_sweep_exits_zero_with_scorecard(self):
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8")
        out = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu", "analyze", "program"],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=600,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["ok"] and payload["audited"] >= 20
