"""Placement-driven execution: a Distribution object must actually drive
device sharding (VERDICT item 7 — reference parity with
pydcop/commands/solve.py:483-507 running under a given placement)."""
import numpy as np
import pytest

from pydcop_tpu.distribution.objects import (
    Distribution,
    ImpossibleDistributionException,
)
from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.ops.compile import compile_factor_graph
from pydcop_tpu.parallel.partition import assigns_from_distribution
from pydcop_tpu.runtime import solve_result


@pytest.fixture(scope="module")
def coloring():
    return generate_graph_coloring(
        n_variables=12, n_colors=3, n_edges=20, soft=True, n_agents=4,
        seed=7,
    )


def full_distribution(dcop, n_agents=4):
    """Round-robin placement of all computations (vars + constraints)."""
    comps = sorted(dcop.variables) + sorted(dcop.constraints)
    agents = sorted(dcop.agents)[:n_agents]
    mapping = {a: [] for a in agents}
    for i, c in enumerate(comps):
        mapping[agents[i % len(agents)]].append(c)
    return Distribution(mapping)


def test_assigns_follow_hosts(coloring):
    tensors = compile_factor_graph(coloring)
    dist = full_distribution(coloring)
    assigns = assigns_from_distribution(dist, tensors, 4)
    agents = sorted(dist.agents)
    for b, assign in zip(tensors.buckets, assigns):
        for f in range(b.n_factors):
            name = tensors.factor_names[int(b.factor_ids[f])]
            host = dist.agent_for(name)
            assert assign[f] == agents.index(host) % 4


def test_missing_computation_fails_loudly(coloring):
    tensors = compile_factor_graph(coloring)
    incomplete = Distribution({"a0": ["v0"]})
    with pytest.raises(ImpossibleDistributionException, match="place"):
        assigns_from_distribution(incomplete, tensors, 4)


def test_placement_driven_solve_matches_unsharded(coloring):
    dist = full_distribution(coloring)
    res = solve_result(coloring, "maxsum", distribution=dist, cycles=25)
    assert res.status == "FINISHED"
    ref = solve_result(coloring, "maxsum", cycles=25)
    # sharded-by-placement BP must land on a solution of similar quality
    assert res.cost <= ref.cost * 1.5 + 2.0
    assert sorted(res.assignment) == sorted(coloring.variables)


def test_placement_rejected_for_host_driven_algos(coloring):
    dist = full_distribution(coloring)
    with pytest.raises(ValueError, match="maxsum"):
        solve_result(coloring, "dpop", distribution=dist)


def test_cli_solve_with_distribution_file(tmp_path, coloring):
    """End-to-end: solve -d file.yaml runs under the placement."""
    import json
    import os
    import subprocess
    import sys

    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.distribution.yamlformat import yaml_dist

    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo,  # drop axon sitecustomize so cpu sticks
    }
    dcop_f = tmp_path / "prob.yaml"
    dcop_f.write_text(dcop_yaml(coloring))
    dist_f = tmp_path / "dist.yaml"
    dist_f.write_text(yaml_dist(full_distribution(coloring)))
    out = subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", "--timeout", "60", "solve",
         "--algo", "maxsum", "--cycles", "10", "-d", str(dist_f),
         str(dcop_f)],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo,
    )
    data = json.loads(out.stdout)
    assert data["status"] in ("FINISHED", "TIMEOUT"), out.stderr[-500:]
    assert set(data["assignment"]) == set(coloring.variables)

    # a placement file missing computations must fail loudly
    bad_f = tmp_path / "bad_dist.yaml"
    bad_f.write_text("distribution:\n  a0: [v0]\n")
    out = subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", "--timeout", "60", "solve",
         "--algo", "maxsum", "-d", str(bad_f), str(dcop_f)],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo,
    )
    assert out.returncode != 0
    assert "ERROR" in out.stdout
