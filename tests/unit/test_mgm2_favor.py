"""MGM-2 favor semantics (reference pydcop/algorithms/mgm2.py:812-821).

A receiver commits to a pair move iff the best offered joint gain BEATS
its own unilateral gain — or ties it, arbitrated by favor:
``coordinated`` commits on ties, ``no`` flips a coin, ``unilateral``
stays solo.

Trap instance: two binary variables, one constraint
``M = [[10, 5], [5, 5]]``, state (0, 0).  Every improving move — a
alone, b alone, or the pair — has gain exactly 5, so the joint offer
TIES the receiver's own gain: coordinated executes the pair move to
(0, 1) (argmin tie-break), unilateral arbitration moves only the lower
id to (1, 0).
"""
import jax.numpy as jnp
import jax.random
import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.mgm2 import Mgm2Solver, algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.compile import compile_constraint_graph


def trap_dcop():
    dcop = DCOP("trap", objective="min")
    d = Domain("d", "vals", [0, 1])
    a, b = Variable("a", d), Variable("b", d)
    dcop.add_variable(a)
    dcop.add_variable(b)
    m = np.array([[10.0, 5.0], [5.0, 5.0]])
    dcop.add_constraint(NAryMatrixRelation([a, b], m, name="c"))
    dcop.add_agents([AgentDef("ag")])
    return dcop


def make_solver(favor):
    dcop = trap_dcop()
    algo = AlgorithmDef.build_with_default_params(
        "mgm2", {"favor": favor}, parameters_definitions=algo_params
    )
    return Mgm2Solver(dcop, compile_constraint_graph(dcop), algo)


def run_one_cycle(solver, key):
    (x2,) = solver.cycle((jnp.array([0, 0], dtype=jnp.int32),), key)
    return tuple(int(v) for v in np.asarray(x2))


def test_favor_modes_differ_on_tie():
    coord = make_solver("coordinated")
    unil = make_solver("unilateral")
    outcomes = set()
    for k in range(40):
        key = jax.random.PRNGKey(k)
        rc = run_one_cycle(coord, key)
        ru = run_one_cycle(unil, key)
        outcomes.add((rc, ru))
        # unilateral must NEVER take the tied pair move
        assert ru != (0, 1), f"unilateral committed a tied pair (key {k})"
    # for keys where exactly one variable offered, coordinated commits
    # the pair while unilateral moves solo
    assert ((0, 1), (1, 0)) in outcomes, outcomes


def test_favor_no_is_between():
    nof = make_solver("no")
    results = {
        run_one_cycle(nof, jax.random.PRNGKey(k)) for k in range(60)
    }
    # the coin sometimes commits the tied pair, sometimes not
    assert (0, 1) in results
    assert (1, 0) in results


def test_unilateral_commits_when_joint_strictly_better():
    # pair move strictly beats both solo moves -> all favors commit
    dcop = DCOP("trap2", objective="min")
    d = Domain("d", "vals", [0, 1])
    a, b = Variable("a", d), Variable("b", d)
    dcop.add_variable(a)
    dcop.add_variable(b)
    # solo moves gain 0, joint move gains 10: the canonical MGM-2 trap
    m = np.array([[10.0, 10.0], [10.0, 0.0]])
    dcop.add_constraint(NAryMatrixRelation([a, b], m, name="c"))
    dcop.add_agents([AgentDef("ag")])
    algo = AlgorithmDef.build_with_default_params(
        "mgm2", {"favor": "unilateral"}, parameters_definitions=algo_params
    )
    solver = Mgm2Solver(dcop, compile_constraint_graph(dcop), algo)
    moved = set()
    for k in range(40):
        moved.add(run_one_cycle(solver, jax.random.PRNGKey(k)))
    assert (1, 1) in moved  # escapes the trap via the pair move
    assert (1, 0) not in moved and (0, 1) not in moved  # never solo


def test_invalid_favor_raises():
    from pydcop_tpu.algorithms import AlgoParameterException

    # central param validation catches it first...
    with pytest.raises(AlgoParameterException, match="favor"):
        make_solver("sideways")
    # ...and the solver itself refuses if validation is bypassed
    dcop = trap_dcop()
    algo = AlgorithmDef("mgm2", {"favor": "sideways", "threshold": 0.5})
    with pytest.raises(ValueError, match="favor"):
        Mgm2Solver(dcop, compile_constraint_graph(dcop), algo)


def test_full_solve_all_favors():
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.runtime import solve_result

    dcop = generate_graph_coloring(
        n_variables=12, n_colors=3, n_edges=20, soft=True, n_agents=1,
        seed=4,
    )
    costs = {}
    for favor in ("unilateral", "no", "coordinated"):
        res = solve_result(
            dcop, "mgm2", cycles=25, algo_params={"favor": favor}
        )
        assert res.status == "FINISHED"
        costs[favor] = res.cost
    # all modes must produce sane solutions on a real instance
    assert all(c < 1000 for c in costs.values()), costs
