"""Portfolio subsystem (ISSUE 10): featurizer, self-labeling dataset,
pure-JAX cost model, feasibility-masked auto-selection and the
canonical config/portfolio metrics sections.

Pins the acceptance properties: fixed-length finite seed-stable
feature vectors on every generator family (100k-var extraction under
a wall budget, no util table), dataset resumability by cell key,
ranking-quality model evaluation, typed refusals staying typed, and
``--auto`` degrading to the pre-portfolio hand heuristics when no
model is present.
"""
import os
import time

import numpy as np
import pytest

from pydcop_tpu.portfolio.features import (
    CONFIG_ENC_LEN,
    N_FEATURES,
    encode_config,
    featurize,
    featurize_detail,
    pair_vector,
)
from pydcop_tpu.portfolio.select import (
    DEFAULT_GRID,
    TINY_GRID,
    PortfolioConfig,
    feasible_grid,
    heuristic_config,
    select_config,
    solve_auto,
)


def _gc(n=10, seed=0, edges=None):
    from pydcop_tpu.generators import generate_graph_coloring

    return generate_graph_coloring(
        n_variables=n, n_colors=3, n_edges=edges or 2 * n, soft=True,
        n_agents=1, seed=seed,
    )


# ---------------------------------------------------------------------------
# features (satellite 3)
# ---------------------------------------------------------------------------


FAMILY_BUILDERS = {
    "graphcoloring": lambda seed: _gc(10, seed),
    "ising": lambda seed: __import__(
        "pydcop_tpu.generators", fromlist=["generate_ising"]
    ).generate_ising(rows=4, seed=seed)[0],
    "smallworld": lambda seed: __import__(
        "pydcop_tpu.generators", fromlist=["generate_smallworld"]
    ).generate_smallworld(n_variables=12, seed=seed),
    "iot": lambda seed: __import__(
        "pydcop_tpu.generators", fromlist=["generate_iot"]
    ).generate_iot(n_devices=10, seed=seed),
    "secp": lambda seed: __import__(
        "pydcop_tpu.generators", fromlist=["generate_secp"]
    ).generate_secp(n_lights=6, seed=seed),
    "meetingscheduling": lambda seed: __import__(
        "pydcop_tpu.generators", fromlist=["generate_meeting_scheduling"]
    ).generate_meeting_scheduling(n_agents=4, n_meetings=3, seed=seed),
}


class TestFeatures:
    @pytest.mark.parametrize("family", sorted(FAMILY_BUILDERS))
    def test_fixed_length_finite_seed_stable(self, family):
        build = FAMILY_BUILDERS[family]
        v1 = featurize(build(3))
        v2 = featurize(build(3))
        assert v1.shape == (N_FEATURES,)
        assert v1.dtype == np.float32
        assert np.isfinite(v1).all()
        # same seed -> byte-identical features (determinism rides on
        # the generator seed audit, satellite 2)
        assert np.array_equal(v1, v2)

    def test_different_seed_changes_random_families(self):
        a = featurize(_gc(10, seed=1))
        b = featurize(_gc(10, seed=2))
        assert not np.array_equal(a, b)

    def test_detail_info_keys(self):
        _vec, info = featurize_detail(_gc(8))
        for k in ("n_vars", "n_factors", "induced_width",
                  "sweep_bytes", "max_node_entries", "cut_fraction",
                  "boundary_fraction", "objective"):
            assert k in info
        assert info["n_vars"] == 8

    def test_config_encoding_shape_and_onehots(self):
        cfg = PortfolioConfig("dsa", chunk=100)
        enc = encode_config(cfg)
        assert enc.shape == (CONFIG_ENC_LEN,)
        # exactly one algo bit, one engine bit, one overlap bit
        assert enc[:6].sum() == 1.0 and enc[2] == 1.0  # dsa
        assert enc[6:10].sum() == 1.0  # harness
        assert enc[10:14].sum() == 1.0  # default overlap
        assert pair_vector(featurize(_gc(6)), cfg).shape == (
            N_FEATURES + CONFIG_ENC_LEN,
        )

    def test_100k_vars_under_wall_budget(self):
        """The featurizer is a pure shape pass: on a 100k-variable
        ring lattice it must finish well under the pinned budget —
        it never builds a cost or util table (a single joint table
        at this width would be astronomically larger than RAM)."""
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import Domain, Variable
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        V = 100_000
        dcop = DCOP("ring100k", "min")
        dom = Domain("c", "color", [0, 1, 2])
        vs = [Variable(f"v{i:06d}", dom) for i in range(V)]
        for v in vs:
            dcop.add_variable(v)
        m = (np.eye(3) * 5 + 0.25).astype(np.float32)
        for i in range(V):
            dcop.add_constraint(NAryMatrixRelation(
                [vs[i], vs[(i + 1) % V]], m, f"c{i:06d}"
            ))
        t0 = time.perf_counter()
        vec = featurize(dcop)
        wall = time.perf_counter() - t0
        assert np.isfinite(vec).all()
        # a shape pass runs ~10s on the slow reference container; any
        # accidental table materialization is minutes-to-hours.  The
        # budget needs headroom for suite-tail load on 1-core hosts
        # (observed 20.0003s under a full tier-1 run), not precision.
        assert wall < 60.0, f"featurize took {wall:.1f}s on 100k vars"


class TestStructuredRouting:
    """Table-free constraints in the portfolio (ISSUE 17): the
    featurizer carries the structure census without materializing a
    table, and the selector masks every cell that would have to."""

    def _structured(self, n=20):
        from pydcop_tpu.generators import generate_routing_structured

        return generate_routing_structured(
            n, window=n, p_soft=0.0, seed=0,
        )

    def test_structure_features_are_analytic(self):
        vec, info = featurize_detail(self._structured(20))
        assert np.isfinite(vec).all()
        assert info["n_structured"] == 1
        assert info["structured_kinds"] == {"resource": 1}
        assert 0.0 < info["structured_frac"] <= 1.0
        # 4^20 entries * 4 bytes — far past the densify cap, computed
        # as pure arithmetic (the test budget itself pins that no
        # table of this size was ever built)
        assert info["structured_dense_bytes"] == pytest.approx(
            4.0 * 4.0**20
        )
        assert info["structured_over_table_cap"] is True

    def test_dense_instance_reports_zero_structure(self):
        vec, info = featurize_detail(_gc(8))
        assert info["n_structured"] == 0
        assert info["structured_over_table_cap"] is False
        assert vec[-3] == 0.0              # structured_frac
        assert vec[-1] == pytest.approx(np.log10(4.0))

    def test_mask_leaves_only_table_free_cells(self):
        _vec, info = featurize_detail(self._structured(20))
        feasible, masked = feasible_grid(
            DEFAULT_GRID, info, n_devices=1
        )
        reasons = {c.key(): r for c, r in masked}
        # the weighted family has no tensors to weight
        assert any(c.algo == "gdba" for c, _ in masked)
        # the bounded mini-bucket tier is table-bound: masked; the
        # auto tier survives only because it routes to the frontier
        feas_dpop = {c.engine for c in feasible if c.algo == "dpop"}
        assert feas_dpop <= {"auto"}
        assert any(
            c.algo == "dpop" and "table cap" in r for c, r in masked
        )
        # the table-free paths stay on the menu
        assert any(c.algo == "maxsum" for c in feasible)
        assert any(c.algo == "syncbb" for c in feasible)
        assert all("densify" in r or "weighting" in r
                   or "table" in r for r in reasons.values())

    def test_heuristic_pick_is_table_free(self):
        _vec, info = featurize_detail(self._structured(20))
        feasible, _ = feasible_grid(DEFAULT_GRID, info, n_devices=1)
        cfg = heuristic_config(info)
        # whatever the fallback picks for this regime, it must be a
        # cell the mask kept — the selector never lands on a config
        # that would raise a densify refusal
        assert cfg.algo != "gdba"
        assert cfg in feasible or any(
            c.algo == cfg.algo for c in feasible
        )


# ---------------------------------------------------------------------------
# selection: masks, heuristic fallback, typed refusals
# ---------------------------------------------------------------------------


class TestSelection:
    def test_feasibility_masks_dpop_over_budget(self):
        info = {"sweep_bytes": 10**12, "max_node_entries": 10**11}
        feasible, masked = feasible_grid(DEFAULT_GRID, info,
                                         n_devices=1)
        keys = {c.key() for c in feasible}
        assert not any(k.startswith("dpop|auto") for k in keys)
        # the bounded mini-bucket tier stays feasible — it degrades,
        # it does not blow memory
        assert any(c.algo == "dpop" and c.engine == "minibucket"
                   for c in feasible)
        assert all(c.algo != "dpop" or c.engine == "minibucket"
                   for c in feasible)
        assert masked and all(reason for _c, reason in masked)

    def test_sharded_masked_without_mesh(self):
        grid = (PortfolioConfig("dpop", engine="sharded"),)
        info = {"sweep_bytes": 1000, "max_node_entries": 100}
        feasible, masked = feasible_grid(grid, info, n_devices=1)
        assert feasible == [] and len(masked) == 1
        feasible, masked = feasible_grid(grid, info, n_devices=8)
        assert len(feasible) == 1 and masked == []

    def test_heuristic_fallback_pinned(self):
        """No model -> the pre-portfolio hand heuristics, exactly:
        the PR 9 byte-estimate routing picks exact DPOP when the
        planner says the sweep is cheap, the MGM harness otherwise,
        and overlap stays on the PR 5 auto-policy default."""
        cheap = {"sweep_bytes": 1024, "max_node_entries": 729}
        cfg = heuristic_config(cheap)
        assert cfg.algo == "dpop" and cfg.engine == "auto"
        assert cfg.overlap == "default"
        big = {"sweep_bytes": 10**12, "max_node_entries": 10**11}
        cfg = heuristic_config(big)
        assert cfg == PortfolioConfig("mgm")

    def test_select_without_model_is_fallback(self):
        sel = select_config(_gc(8), grid=TINY_GRID)
        assert sel.fallback is True
        assert sel.predicted_label is None
        assert sel.config == heuristic_config(sel.info)

    def test_typed_refusal_stays_typed(self):
        """Masking is advisory: FORCING an over-budget sweep config
        still raises the typed UtilTableTooLarge, never a silent
        downgrade.  (engine="auto" with the same impossible budget is
        no longer a refusal: ISSUE 15 registered the frontier exact
        search between the sharded tier and the mini-bucket fallback,
        so auto PROVES the optimum instead — pinned below.)"""
        from pydcop_tpu.ops.dpop_shard import UtilTableTooLarge
        from pydcop_tpu.runtime.run import solve_result

        dcop = _gc(12, seed=0, edges=40)
        with pytest.raises(UtilTableTooLarge):
            solve_result(dcop, "dpop",
                         algo_params={"budget_mb": 1e-6,
                                      "engine": "sharded"})

    def test_auto_over_budget_routes_to_frontier(self):
        """The ISSUE 15 ladder: engine="auto" under an impossible
        byte budget lands on the frontier exact search (gap closed,
        engine recorded) instead of refusing or degrading to bounds,
        and the answer matches the unbudgeted exact sweep."""
        from pydcop_tpu.runtime.run import solve_result

        dcop = _gc(12, seed=0, edges=40)
        res = solve_result(dcop, "dpop",
                           algo_params={"budget_mb": 1e-6})
        assert res.config["engine"] == "frontier"
        assert res.search is not None and res.search["optimal"]
        exact = solve_result(dcop, "dpop")
        assert res.cost == pytest.approx(exact.cost, abs=1e-6)


# ---------------------------------------------------------------------------
# model: training, persistence, ranking eval
# ---------------------------------------------------------------------------


class TestModel:
    def _synthetic(self, n=160, d=8, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.standard_normal((n, d)).astype(np.float32)
        w = rng.standard_normal(d).astype(np.float32)
        y = X @ w + 0.01 * rng.standard_normal(n).astype(np.float32)
        return X, y

    def test_train_learns_ranking_and_roundtrips(self, tmp_path):
        from pydcop_tpu.portfolio.model import (
            CostModel,
            evaluate,
            train_model,
        )

        X, y = self._synthetic()
        model, hist = train_model(X[:120], y[:120], hidden=(16, 16),
                                  epochs=150, seed=0)
        assert hist["final_loss"] < 0.1
        groups = [(X[120 + 8 * i:120 + 8 * (i + 1)],
                   y[120 + 8 * i:120 + 8 * (i + 1)]) for i in range(5)]
        report = evaluate(model, groups)
        assert report["rank_correlation"] > 0.8
        assert report["top1_regret_ratio"] >= 1.0 or (
            report["top1_regret"] <= 0.0
        )
        path = os.path.join(tmp_path, "m.npz")
        model.save(path)
        loaded = CostModel.load(path)
        assert np.allclose(loaded.predict(X[:4]), model.predict(X[:4]),
                           atol=1e-5)

    def test_rank_loss_learns_within_group_order(self):
        """With per-group scale offsets drowning the config signal,
        the pairwise ranking hinge still recovers the within-group
        ordering the argmin selector needs."""
        from pydcop_tpu.portfolio.model import evaluate, train_model

        rng = np.random.default_rng(2)
        n_groups, n_cfg = 24, 5
        cfg_feats = np.eye(n_cfg, dtype=np.float32)
        cfg_effect = np.asarray([0.0, 0.4, 0.8, 1.2, 1.6], np.float32)
        X_rows, y_rows, gids = [], [], []
        for g in range(n_groups):
            inst = rng.standard_normal(3).astype(np.float32)
            offset = float(rng.uniform(-8, 8))  # dwarfs cfg_effect
            for c in range(n_cfg):
                X_rows.append(np.concatenate([inst, cfg_feats[c]]))
                y_rows.append(offset + cfg_effect[c])
                gids.append(f"g{g}")
        X = np.stack(X_rows)
        y = np.asarray(y_rows, np.float32)
        model, hist = train_model(
            X[:100], y[:100], hidden=(16, 16), epochs=300, seed=0,
            group_ids=gids[:100], rank_weight=2.0,
        )
        assert hist["rank_pairs"] > 0
        held = [(X[100 + 5 * i:105 + 5 * i], y[100 + 5 * i:105 + 5 * i])
                for i in range(4)]
        report = evaluate(model, held)
        # the argmin is the selector's objective: the model must pick
        # the per-group winner though the offsets bury the MSE signal
        assert report["top1_hits"] == 1.0
        assert report["rank_correlation"] > 0.5

    def test_predict_rejects_wrong_width(self, tmp_path):
        from pydcop_tpu.portfolio.model import train_model

        X, y = self._synthetic(n=32, d=6)
        model, _ = train_model(X, y, hidden=(8,), epochs=5)
        with pytest.raises(ValueError, match="width"):
            model.predict(np.zeros((2, 9), np.float32))

    def test_spearman(self):
        from pydcop_tpu.portfolio.model import spearman

        a = np.asarray([1.0, 2.0, 3.0, 4.0])
        assert spearman(a, a * 10 + 3) == pytest.approx(1.0)
        assert spearman(a, -a) == pytest.approx(-1.0)
        assert spearman(a, np.ones(4)) == 0.0


# ---------------------------------------------------------------------------
# dataset: labels, resumability
# ---------------------------------------------------------------------------


class TestDataset:
    def test_label_math(self):
        from pydcop_tpu.portfolio.dataset import training_matrix

        feats = [0.0] * N_FEATURES
        cfg_a = PortfolioConfig("mgm").as_dict()
        cfg_b = PortfolioConfig("dsa").as_dict()
        rows = [
            {  # reaches the target band at t=0.5
                "key": "i1::a", "instance": "i1", "status": "FINISHED",
                "config": cfg_a, "features": feats, "probe_rate": 2.0,
                "wall_s": 1.0, "final_cost_signed": 10.0,
                "curve": [[0.1, 20.0], [0.5, 10.0]],
            },
            {  # never reaches it -> charged penalty x slowest reacher
                "key": "i1::b", "instance": "i1", "status": "FINISHED",
                "config": cfg_b, "features": feats, "probe_rate": 2.0,
                "wall_s": 2.0, "final_cost_signed": 50.0,
                "curve": [[2.0, 50.0]],
            },
        ]
        X, y, gids, keys = training_matrix(rows)
        assert X.shape == (2, N_FEATURES + CONFIG_ENC_LEN)
        t = np.expm1(y)  # back to normalized-time units
        assert t[0] == pytest.approx(0.5 * 2.0, rel=1e-5)
        # miss penalty: 3 x the group's slowest observed time (the
        # miss's own 2.0s wall), normalized by the row's probe rate
        assert t[1] == pytest.approx(3.0 * 2.0 * 2.0, rel=1e-5)
        assert gids == ["i1", "i1"] and keys == ["i1::a", "i1::b"]

    def test_sweep_resumes_by_cell_key(self, tmp_path):
        from pydcop_tpu.portfolio.dataset import (
            PortfolioDataset,
            run_sweep,
            sweep_spec,
        )

        grid = (PortfolioConfig("mgm"), PortfolioConfig("dsa", chunk=20))
        spec = sweep_spec(["graphcoloring"], [6], [0], grid,
                          cycles=15, timeout_s=20)
        out = str(tmp_path / "ds")
        probe = lambda: 100.0  # noqa: E731 — fixed rate keeps it fast
        s1 = run_sweep(spec, out, probe=probe)
        assert s1["cells_run"] == 2 and s1["cells_error"] == 0
        s2 = run_sweep(spec, out, probe=probe)
        assert s2["cells_run"] == 0 and s2["cells_skipped"] == 2
        ds = PortfolioDataset(out)
        rows = ds.rows()
        assert len(rows) == 2
        assert all(len(r["features"]) == N_FEATURES for r in rows)
        assert all(r["probe_rate"] == 100.0 for r in rows)
        assert os.path.exists(ds.npz_path)
        with np.load(ds.npz_path) as z:
            assert z["X"].shape[0] == 2
            assert np.isfinite(z["y"]).all()

    def test_holdout_split_excludes_family(self):
        from pydcop_tpu.portfolio.dataset import split_holdout

        X = np.zeros((4, 3), np.float32)
        y = np.arange(4, dtype=np.float32)
        gids = ["ising/s4/seed0", "ising/s4/seed0",
                "iot/s5/seed0", "iot/s5/seed0"]
        (trX, trY, tr_gids), held = split_holdout(X, y, gids, ["iot"])
        assert trX.shape[0] == 2 and len(held) == 1
        assert tr_gids == ["ising/s4/seed0", "ising/s4/seed0"]
        assert held[0][1].tolist() == [2.0, 3.0]


# ---------------------------------------------------------------------------
# canonical config section (satellite 1) + the --auto audit
# ---------------------------------------------------------------------------


class TestConfigSection:
    def test_harness_config_schema(self):
        from pydcop_tpu.runtime.run import solve_result
        from pydcop_tpu.runtime.stats import CONFIG_FIELDS

        res = solve_result(_gc(8), "mgm", cycles=6, chunk=5)
        cfg = res.metrics()["config"]
        assert tuple(sorted(cfg)) == tuple(sorted(CONFIG_FIELDS))
        assert cfg["algo"] == "mgm" and cfg["engine"] == "harness"
        assert cfg["chunk"] == 5
        assert cfg["overlap"] == "default"

    def test_harness_config_records_policy_chunk(self):
        from pydcop_tpu.runtime.run import solve_result

        # fixed-cycle no-metrics run -> the policy raises chunk to 100
        res = solve_result(_gc(8), "dsa", cycles=120)
        assert res.metrics()["config"]["chunk"] == 100

    def test_dpop_config_records_executed_engine(self):
        from pydcop_tpu.runtime.run import solve_result

        res = solve_result(_gc(8), "dpop")
        cfg = res.metrics()["config"]
        assert cfg["algo"] == "dpop"
        assert cfg["engine"] in ("sweep", "sweep_perlevel", "pernode",
                                 "wholesweep")
        res = solve_result(_gc(8), "dpop",
                           algo_params={"engine": "minibucket",
                                        "i_bound": 2})
        cfg = res.metrics()["config"]
        assert cfg["engine"] == "minibucket" and cfg["i_bound"] == 2


class TestSolveAuto:
    def test_no_model_degrades_to_heuristics(self):
        """Acceptance pin: with no trained model present ``--auto``
        runs exactly the pre-portfolio heuristic choice and says so
        in metrics()['portfolio']."""
        dcop = _gc(8)
        res = solve_auto(dcop, grid=TINY_GRID, cycles=20)
        m = res.metrics()
        pf = m["portfolio"]
        assert pf["fallback"] is True and pf["model"] is None
        assert pf["predicted_time_to_target_s"] is None
        _vec, info = featurize_detail(dcop)
        assert pf["config"] == heuristic_config(info).as_dict()
        assert m["status"] == "FINISHED"
        assert "config" in m  # the executed-config section rides too

    def test_with_model_records_gap_audit(self):
        from pydcop_tpu.portfolio.model import train_model

        dcop = _gc(8)
        vec, info = featurize_detail(dcop)
        feasible, _ = feasible_grid(TINY_GRID, info, n_devices=1)
        X = np.stack([pair_vector(vec, c) for c in feasible])
        # labels favor the FIRST grid cell deterministically
        y = np.asarray([0.1 + i for i in range(len(feasible))],
                       np.float32)
        Xt = np.tile(X, (8, 1))
        yt = np.tile(y, 8)
        model, _ = train_model(Xt, yt, hidden=(16,), epochs=120,
                               meta={"probe_rate": 50.0})
        res = solve_auto(dcop, model=model, grid=TINY_GRID, cycles=20)
        pf = res.metrics()["portfolio"]
        assert pf["fallback"] is False
        assert pf["config"]["algo"] == feasible[0].algo
        assert pf["predicted_time_to_target_s"] is not None
        assert pf["actual_solve_s"] > 0
        assert "gap_s" in pf and "gap_ratio" in pf
        assert pf["n_feasible"] == len(feasible)

    def test_stale_model_path_degrades(self, tmp_path):
        bad = str(tmp_path / "nope.npz")
        res = solve_auto(_gc(8), model=bad, grid=TINY_GRID, cycles=15)
        pf = res.metrics()["portfolio"]
        assert pf["fallback"] is True

    def test_prewarm_predicted_compiles_expected_signature(self):
        """Serve integration: the predicted configs decide which
        bucket signatures the service prewarms — the batch-eligible
        pick lands in the compile pool so its later admission is a
        cache hit."""
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.serve import SolveService

        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=63)
        grid = (PortfolioConfig("mgm"),)
        chosen = svc.prewarm_predicted([_gc(8)], grid=grid,
                                       block=True)
        assert [c.algo for c in chosen] == ["mgm"]
        assert svc.counters.counts["prewarmed_runners"] >= 1
        assert svc.cache.stats()["prewarmed"] >= 1

    def test_selection_event_emitted(self):
        from pydcop_tpu.runtime.events import event_bus

        seen = []
        cb = lambda t, e: seen.append((t, e))  # noqa: E731
        event_bus.subscribe("portfolio.*", cb)
        was = event_bus.enabled
        event_bus.enabled = True
        try:
            solve_auto(_gc(8), grid=TINY_GRID, cycles=10)
        finally:
            event_bus.enabled = was
            event_bus.unsubscribe(cb)
        topics = [t for t, _ in seen]
        assert "portfolio.config.selected" in topics
        assert "portfolio.solve.done" in topics
        sel_evt = dict(seen[topics.index("portfolio.config.selected")][1])
        assert sel_evt["fallback"] is True
        assert "config" in sel_evt
