"""Drift-calibration machinery of bench.py (round-5 verdict item 1):
the probe kernel, the normalized-primary preference in the regression
guard, the tail-recovery of archived rounds and the retroactive drop
verdict — all testable without a TPU."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import bench  # noqa: E402


def _write_round(tmp_path, n, value=None, extra=None, tail=None):
    rec = {"n": n, "rc": 0}
    if value is not None:
        rec["parsed"] = {"metric": "m", "value": value,
                         "extra": extra or {}}
    if tail is not None:
        rec["tail"] = tail
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))


class TestPrimaryFromRecord:
    def test_parsed_value_wins(self):
        v, ex = bench._primary_from_record(
            {"parsed": {"value": 123.0, "extra": {"a": 1}}})
        assert v == 123.0 and ex == {"a": 1}

    def test_tail_fallback_prefers_burst2(self):
        tail = '"primary_burst1": 100.5, "primary_burst2": 101.25}'
        v, ex = bench._primary_from_record({"parsed": {}, "tail": tail})
        assert v == 101.25 and ex == {}

    def test_no_signal(self):
        assert bench._primary_from_record({"parsed": {}, "tail": "x"}) \
            == (None, {})


class TestRegressionCheck:
    def test_prefers_normalized_when_both_rounds_carry_it(self, tmp_path):
        _write_round(tmp_path, 6, value=20000.0,
                     extra={"primary_normalized": 100.0})
        # raw dropped 40% but normalized held: NOT a regression
        extra = {"primary_normalized": 99.0}
        bench.regression_check(12000.0, extra, str(tmp_path))
        assert "primary" not in extra.get("regressions", {})
        # normalized dropped too: flagged, with the basis recorded
        extra2 = {"primary_normalized": 80.0}
        bench.regression_check(12000.0, extra2, str(tmp_path))
        rec = extra2["regressions"]["primary"]
        assert rec["basis"] == "primary_normalized"
        assert rec["prev"] == 100.0 and rec["cur"] == 80.0

    def test_raw_fallback_against_pre_probe_round(self, tmp_path):
        _write_round(tmp_path, 6, value=20000.0, extra={})
        extra = {"primary_normalized": 99.0}
        bench.regression_check(12000.0, extra, str(tmp_path))
        rec = extra["regressions"]["primary"]
        assert "basis" not in rec
        assert rec["prev"] == 20000.0


class TestDriftVerdict:
    def test_recovery_reads_as_drift(self, tmp_path):
        _write_round(tmp_path, 4, value=22000.0)
        _write_round(tmp_path, 5, value=15800.0)
        extra = {}
        bench.drift_verdict(21500.0, extra, str(tmp_path))
        rec = extra["prior_round_drop"]
        assert rec["rounds"] == [4, 5]
        assert rec["verdict"].startswith("drift")

    def test_staying_low_reads_as_real_or_persistent(self, tmp_path):
        _write_round(tmp_path, 4, value=22000.0)
        _write_round(tmp_path, 5, value=15800.0)
        extra = {}
        bench.drift_verdict(15900.0, extra, str(tmp_path))
        assert extra["prior_round_drop"]["verdict"].startswith(
            "real-or-persistent")

    def test_tail_only_round_participates(self, tmp_path):
        """Round 5's archive lost the parsed primary; the verdict must
        still see it through the tail fallback (the actual repo
        state)."""
        _write_round(tmp_path, 4, value=22000.0)
        _write_round(tmp_path, 5,
                     tail='... "primary_burst2": 15826.1, ...')
        extra = {}
        bench.drift_verdict(21500.0, extra, str(tmp_path))
        assert extra["prior_round_drop"]["raw"] == [22000.0, 15826.1]

    def test_no_drop_no_verdict(self, tmp_path):
        _write_round(tmp_path, 4, value=20000.0)
        _write_round(tmp_path, 5, value=19500.0)
        extra = {}
        bench.drift_verdict(19000.0, extra, str(tmp_path))
        assert "prior_round_drop" not in extra


def test_probe_measures_a_positive_rate():
    """The calibration kernel compiles and yields a finite positive
    rate on any backend (tiny geometry — the recorded rounds use the
    fixed PROBE_DIM/PROBE_CHAIN defaults)."""
    probe = bench.make_drift_probe(repeat=2, dim=64, chain=8)
    r1 = probe()
    assert r1 > 0 and r1 == pytest.approx(r1)  # finite
