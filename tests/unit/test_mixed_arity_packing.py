"""Mixed-arity (1/2/3/4) lane packing (VERDICT r4 item 7 / ROADMAP
§2a): the packed MaxSum engine and the packed local-tables kernel must
bit-match the generic engines on graphs with unary, binary, ternary
AND quaternary factors — SECP model/rule structure, the family that
previously fell to the generic path entirely.  Kernels run in
interpret mode here."""
import numpy as np
import pytest
import jax.numpy as jnp

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.compile import compile_factor_graph, local_cost_tables
from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
from pydcop_tpu.ops.pallas_maxsum import (
    pack_mixed_for_pallas,
    packed_cycle,
    packed_init_state,
    packed_local_tables,
    try_pack_for_pallas,
)


def _mixed_dcop(V=40, n2=60, n3=25, n1=10, D=4, seed=0, ragged=False,
                n4=0):
    rng = np.random.default_rng(seed)
    dcop = DCOP("mixed", objective="min")
    doms = [Domain("d", "vals", list(range(D)))]
    if ragged:
        doms.append(Domain("d2", "vals", list(range(D - 1))))
    vs = []
    for i in range(V):
        v = Variable(f"v{i}", doms[i % len(doms)])
        vs.append(v)
        dcop.add_variable(v)

    def dims(var_list):
        return [len(v.domain) for v in var_list]

    k = 0
    for _ in range(n2):
        i, j = rng.choice(V, 2, replace=False)
        sc = [vs[i], vs[j]]
        dcop.add_constraint(NAryMatrixRelation(
            sc, rng.uniform(0, 5, dims(sc)).astype(np.float32),
            name=f"c{k}"))
        k += 1
    for _ in range(n3):
        i, j, l = rng.choice(V, 3, replace=False)
        sc = [vs[i], vs[j], vs[l]]
        dcop.add_constraint(NAryMatrixRelation(
            sc, rng.uniform(0, 5, dims(sc)).astype(np.float32),
            name=f"c{k}"))
        k += 1
    for _ in range(n1):
        i = int(rng.integers(0, V))
        sc = [vs[i]]
        dcop.add_constraint(NAryMatrixRelation(
            sc, rng.uniform(0, 5, dims(sc)).astype(np.float32),
            name=f"c{k}"))
        k += 1
    for _ in range(n4):
        i, j, l, m = rng.choice(V, 4, replace=False)
        sc = [vs[i], vs[j], vs[l], vs[m]]
        dcop.add_constraint(NAryMatrixRelation(
            sc, rng.uniform(0, 5, dims(sc)).astype(np.float32),
            name=f"c{k}"))
        k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


class TestMixedPacking:
    @pytest.mark.parametrize("ragged", [False, True])
    def test_maxsum_cycle_matches_generic(self, ragged):
        t = compile_factor_graph(_mixed_dcop(ragged=ragged))
        pg = pack_mixed_for_pallas(t)
        assert pg is not None and pg.mixed
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(4):
            q, r, bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        belp_orig = np.asarray(belp)[:, np.asarray(pg.var_order)].T
        # beliefs compared on VALID domain entries only: at invalid
        # entries the generic engine carries the PAD sentinel through
        # the unary costs while the packed engine stores 0 — neither is
        # ever read (masked argmin)
        mask = np.asarray(t.domain_mask) > 0
        assert np.allclose(np.asarray(bel)[mask], belp_orig[mask],
                           atol=1e-3)
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))

    def test_ternary_only_graph(self):
        t = compile_factor_graph(_mixed_dcop(n2=0, n1=0, n3=30, seed=3))
        pg = pack_mixed_for_pallas(t)
        assert pg is not None
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(3):
            q, r, bel, vals = maxsum_cycle(t, q, r, damping=0.3)
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.3, interpret=True
            )
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))

    def test_local_tables_match_generic(self):
        from pydcop_tpu.ops.compile import compile_constraint_graph

        dcop = _mixed_dcop(seed=5)
        t = compile_constraint_graph(dcop)
        pg = pack_mixed_for_pallas(t)
        assert pg is not None
        rng = np.random.default_rng(2)
        x = np.array([rng.integers(0, len(v.domain)) for v in
                      dcop.variables.values()], dtype=np.int32)
        ref = np.asarray(local_cost_tables(t, jnp.asarray(x)))
        got = np.asarray(
            packed_local_tables(pg, jnp.asarray(x), interpret=True))
        assert np.allclose(ref, got, atol=1e-3)

    def test_try_pack_prefers_binary_then_mixed(self):
        # all-binary → binary packer (hub/DP machinery, mixed=False)
        tb = compile_factor_graph(_mixed_dcop(n3=0, n1=0, seed=7))
        pgb = try_pack_for_pallas(tb)
        assert pgb is not None and not pgb.mixed
        # mixed graph → mixed packer via the same entry point
        tm = compile_factor_graph(_mixed_dcop(seed=7))
        pgm = try_pack_for_pallas(tm)
        assert pgm is not None and pgm.mixed

    def test_rejects_arity_5(self):
        rng = np.random.default_rng(0)
        dcop = _mixed_dcop(V=20, n2=10, n3=0, n1=0, seed=9)
        vs = list(dcop.variables.values())[:5]
        dcop.add_constraint(NAryMatrixRelation(
            vs, rng.uniform(0, 1, [len(v.domain) for v in vs]).astype(
                np.float32), name="quint"))
        t = compile_factor_graph(dcop)
        assert pack_mixed_for_pallas(t) is None

    def test_secp_instance_packs(self):
        """The real SECP generator's model factors (arity 3 at
        max_model_size=2) ride the packed engine."""
        from pydcop_tpu.generators.secp import generate_secp

        dcop = generate_secp(n_lights=12, n_models=4, n_rules=2,
                             max_model_size=2, seed=1)
        t = compile_factor_graph(dcop)
        from collections import Counter
        ar = Counter(b.arity for b in t.buckets if b.n_factors)
        if any(a > 3 for a in ar):
            pytest.skip("generator produced arity>3 at this seed")
        pg = try_pack_for_pallas(t)
        assert pg is not None and pg.mixed
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(3):
            q, r, bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))


def _hub_mixed_dcop(V=60, n2=80, n3=30, n1=10, D=4, seed=2):
    """Mixed-arity instance with a degree-150+ hub holding binary AND
    ternary factors (ROADMAP item 3 / VERDICT r5 item 4).  Integer
    costs: float sums stay exact, so the packed engines must bit-match
    (continuous costs can create EXACT mathematical ties — e.g. a pair's
    joint gain equals the receiver's unilateral gain whenever the
    offerer's optimum stays put — that flip on summation order)."""
    rng = np.random.default_rng(seed)
    from pydcop_tpu.dcop.objects import AgentDef

    dcop = DCOP("hubmix", objective="min")
    from pydcop_tpu.dcop.objects import Domain as _Dom
    from pydcop_tpu.dcop.objects import Variable as _Var

    dom = _Dom("d", "vals", list(range(D)))
    vs = [_Var(f"v{i:02d}", dom) for i in range(V)]
    for v in vs:
        dcop.add_variable(v)
    k = 0

    def add(sc):
        nonlocal k
        dcop.add_constraint(NAryMatrixRelation(
            sc, rng.integers(0, 10, [len(v.domain) for v in sc]).astype(
                np.float32), name=f"c{k:03d}"))
        k += 1

    for _ in range(n2):
        i, j = rng.choice(V, 2, replace=False)
        add([vs[i], vs[j]])
    for _ in range(n3):
        i, j, l = rng.choice(V, 3, replace=False)
        add([vs[i], vs[j], vs[l]])
    for _ in range(n1):
        add([vs[int(rng.integers(0, V))]])
    # the hub: 55 binary + 49 ternary incident factors (deg 153)
    for i in range(1, 56):
        add([vs[0], vs[i]])
    for i in range(1, 50):
        add([vs[0], vs[i], vs[i + 1]])
    dcop.add_agents([AgentDef("a0")])
    return dcop


class TestMixedHubPacking:
    """Hub splitting composed with mixed arity: the packer splits the
    hub into sub-columns whose quantized per-arity shares share one
    class block; the arity-agnostic hub combine does the rest."""

    def test_packs_with_hub(self):
        t = compile_factor_graph(_hub_mixed_dcop())
        pg = pack_mixed_for_pallas(t)
        assert pg is not None and pg.mixed
        assert pg.hub_nsteps > 0

    def test_maxsum_matches_generic(self):
        t = compile_factor_graph(_hub_mixed_dcop())
        pg = pack_mixed_for_pallas(t)
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(4):
            q, r, _bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, _belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True)
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))

    def test_local_tables_match_generic(self):
        from pydcop_tpu.ops.compile import compile_constraint_graph

        dcop = _hub_mixed_dcop(seed=4)
        t = compile_constraint_graph(dcop)
        pg = pack_mixed_for_pallas(t)
        rng = np.random.default_rng(3)
        x = np.array([rng.integers(0, len(v.domain)) for v in
                      dcop.variables.values()], dtype=np.int32)
        ref = np.asarray(local_cost_tables(t, jnp.asarray(x)))
        got = np.asarray(
            packed_local_tables(pg, jnp.asarray(x), interpret=True))
        assert np.allclose(ref, got, atol=1e-3)

    def test_move_kernels_match_generic(self):
        import jax

        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms._local_search import (
            random_valid_values,
        )
        from pydcop_tpu.algorithms.dsa import DsaSolver
        from pydcop_tpu.algorithms.mgm import MgmSolver
        from pydcop_tpu.ops.compile import compile_constraint_graph
        from pydcop_tpu.ops.pallas_local_search import (
            pack_from_pg,
            pack_x,
            packed_dsa_cycles,
            packed_mgm_cycles,
            uniforms_for_keys,
            unpack_x,
        )

        dcop = _hub_mixed_dcop()
        t = compile_constraint_graph(dcop)
        pls = pack_from_pg(pack_mixed_for_pallas(t))
        assert pls is not None and pls.pg.hub_nsteps > 0
        x = random_valid_values(t, jax.random.PRNGKey(17))

        solver = MgmSolver(dcop, t,
                           AlgorithmDef.build_with_default_params("mgm"),
                           seed=0, use_packed=False)
        state = (x,)
        for i in range(8):
            state = solver.cycle(state, jax.random.PRNGKey(i))
        got = np.asarray(unpack_x(pls, packed_mgm_cycles(
            pls, pack_x(pls, x), 8)))
        np.testing.assert_array_equal(got, np.asarray(state[0]))

        sd = DsaSolver(dcop, t, AlgorithmDef.build_with_default_params(
            "dsa", {"variant": "B", "probability": 0.7}),
            seed=0, use_packed=False)
        keys = jax.random.split(jax.random.PRNGKey(99), 6)
        state = (x,)
        for k in keys:
            state = sd.cycle(state, k)
        u = uniforms_for_keys(pls, keys)
        got = np.asarray(unpack_x(pls, packed_dsa_cycles(
            pls, pack_x(pls, x), u, probability=0.7, variant="B")))
        np.testing.assert_array_equal(got, np.asarray(state[0]))

    @pytest.mark.parametrize("favor", ["unilateral", "coordinated"])
    def test_mgm2_matches_generic(self, favor):
        import jax

        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms._local_search import (
            random_valid_values,
        )
        from pydcop_tpu.algorithms.mgm2 import Mgm2Solver
        from pydcop_tpu.ops.compile import compile_constraint_graph
        from pydcop_tpu.ops.pallas_local_search import (
            pack_from_pg,
            pack_x,
            unpack_x,
        )
        from pydcop_tpu.ops.pallas_mgm2 import (
            pack_mgm2_from_pls,
            packed_mgm2_cycles,
            uniforms_for_mgm2,
        )

        dcop = _hub_mixed_dcop()
        t = compile_constraint_graph(dcop)
        pls = pack_from_pg(pack_mixed_for_pallas(t))
        pm = pack_mgm2_from_pls(pls)
        assert pm is not None
        x = random_valid_values(t, jax.random.PRNGKey(17))
        keys = jax.random.split(jax.random.PRNGKey(99), 6)
        m2 = Mgm2Solver(dcop, t, AlgorithmDef.build_with_default_params(
            "mgm2", {"favor": favor}), seed=0, use_packed=False)
        state = (x,)
        for k in keys:
            state = m2.cycle(state, k)
        uo, up, uf = uniforms_for_mgm2(pm, keys)
        got = np.asarray(unpack_x(pls, packed_mgm2_cycles(
            pm, pack_x(pls, x), uo, up, uf, m2.threshold, favor)))
        np.testing.assert_array_equal(got, np.asarray(state[0]))


class TestQuaternaryPacking:
    """Arity-4 factors (round 5 — SECP models with 3 lights, the last
    packed-path capability gap): a THIRD Clos permutation routes the
    remaining sibling, and the D^3-block cost slabs are stored NARROW
    (quaternary section lanes only, 8-row-aligned blocks).  All engines
    must bit-match their generic twins; hardware-verified on v5e."""

    def _dcop(self, **kw):
        kw.setdefault("V", 30)
        kw.setdefault("n2", 20)
        kw.setdefault("n3", 10)
        kw.setdefault("n1", 8)
        kw.setdefault("n4", 12)
        kw.setdefault("seed", 4)
        return _mixed_dcop(**kw)

    def test_maxsum_matches_generic(self):
        t = compile_factor_graph(self._dcop())
        pg = pack_mixed_for_pallas(t)
        assert pg is not None and pg.cost4_rows is not None
        assert pg.plan3 is not None and pg.q4_sections
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(4):
            q, r, _bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, _belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        np.testing.assert_array_equal(np.asarray(vals),
                                      np.asarray(valsp))

    def test_quaternary_without_ternary_forces_structures(self):
        """An arity {1,2,4} graph still builds plan2/cost3 (zero rows)
        so the kernel structure matches the quaternary contract."""
        t = compile_factor_graph(self._dcop(n3=0))
        pg = pack_mixed_for_pallas(t)
        assert pg is not None and pg.cost4_rows is not None
        assert pg.plan2 is not None and pg.cost3_rows is not None
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(3):
            q, r, _bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, _belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        np.testing.assert_array_equal(np.asarray(vals),
                                      np.asarray(valsp))

    def test_local_tables_match_generic(self):
        from pydcop_tpu.ops.compile import compile_constraint_graph

        dcop = self._dcop()
        t = compile_constraint_graph(dcop)
        pg = pack_mixed_for_pallas(t)
        assert pg is not None and pg.cost4_rows is not None
        rng = np.random.default_rng(2)
        x = np.array([rng.integers(0, len(v.domain)) for v in
                      dcop.variables.values()], dtype=np.int32)
        ref = np.asarray(local_cost_tables(t, jnp.asarray(x)))
        got = np.asarray(
            packed_local_tables(pg, jnp.asarray(x), interpret=True))
        assert np.allclose(ref, got, atol=1e-3)

    @pytest.mark.parametrize("algo", ["mgm", "dsa", "adsa", "mgm2"])
    def test_solvers_match_generic_stream(self, algo):
        """PRNG-stream-identical packed vs generic on the quaternary
        SECP instance for the whole move family."""
        from unittest import mock

        import jax

        from pydcop_tpu.algorithms import (
            AlgorithmDef,
            load_algorithm_module,
        )
        from pydcop_tpu.generators.secp import generate_secp

        dcop = generate_secp(n_lights=12, n_models=4, n_rules=3,
                             max_model_size=3, seed=2)
        mod = load_algorithm_module(algo)
        ad = AlgorithmDef.build_with_default_params(algo)
        rg = mod.build_solver(dcop, algo_def=ad, seed=3).run(
            cycles=8, chunk=8)
        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            sp = mod.build_solver(dcop, algo_def=ad, seed=3)
        assert getattr(sp, "packed", None) is not None
        rp = sp.run(cycles=8, chunk=8)
        assert rg.assignment == rp.assignment


class TestQuaternaryHubPacking:
    def test_hub_with_quaternary_factors_matches_generic(self):
        """Hub splitting composed with the arity-4 packing: a degree-155
        hub holding binary AND ternary AND quaternary factors rides the
        packed engine, bit-matching the generic engine (integer costs:
        exact float sums, same rationale as _hub_mixed_dcop)."""
        rng = np.random.default_rng(6)
        V, D = 60, 4
        dcop = DCOP("hub4", objective="min")
        dom = Domain("d", "vals", list(range(D)))
        vs = [Variable(f"v{i:02d}", dom) for i in range(V)]
        for v in vs:
            dcop.add_variable(v)
        k = 0
        for _ in range(120):
            j = int(rng.integers(1, V))
            sc = [vs[0], vs[j]]
            dcop.add_constraint(NAryMatrixRelation(
                sc, rng.integers(0, 9, (D, D)).astype(np.float32),
                name=f"c{k}"))
            k += 1
        for _ in range(20):
            j, l = rng.choice(np.arange(1, V), 2, replace=False)
            sc = [vs[0], vs[j], vs[l]]
            dcop.add_constraint(NAryMatrixRelation(
                sc, rng.integers(0, 9, (D,) * 3).astype(np.float32),
                name=f"c{k}"))
            k += 1
        for _ in range(15):
            j, l, m = rng.choice(np.arange(1, V), 3, replace=False)
            sc = [vs[0], vs[j], vs[l], vs[m]]
            dcop.add_constraint(NAryMatrixRelation(
                sc, rng.integers(0, 9, (D,) * 4).astype(np.float32),
                name=f"c{k}"))
            k += 1
        dcop.add_agents([AgentDef("a0")])
        t = compile_factor_graph(dcop)
        pg = pack_mixed_for_pallas(t)
        assert pg is not None and pg.hub_nsteps > 0
        assert pg.cost4_rows is not None
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(5):
            q, r, _bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, _belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        np.testing.assert_array_equal(np.asarray(vals),
                                      np.asarray(valsp))
