"""GDBA full option matrix (reference pydcop/algorithms/gdba.py:177-182):
modifier {A, M} × violation {NZ, NM, MX} × increase_mode {E, R, C, T}.

Semantics pinned by driving single cycles on crafted states:

* weights bump ONLY at a quasi-local-minimum AND only for constraints
  the violation criterion marks as violated;
* the bumped entry set depends on increase_mode (E ⊆ R,C ⊆ T; for
  binary constraints R == C: "reachable by deviating one variable" and
  "keeping one variable's value" coincide at arity 2);
* modifier A adds the weight to the base cost, M multiplies it.
"""
import itertools

import jax.numpy as jnp
import jax.random
import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.gdba import GdbaSolver, algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.compile import compile_constraint_graph

MODIFIERS = ["A", "M"]
VIOLATIONS = ["NZ", "NM", "MX"]
INCREASES = ["E", "R", "C", "T"]


def trap_dcop(m=None):
    """Two binary vars, one constraint with a strict local minimum at
    (0,0): cost 1, both unilateral moves cost 2 — quasi-local-min with
    nonzero, non-minimal... wait, (0,0) IS the minimum here."""
    dcop = DCOP("trap", objective="min")
    d = Domain("d", "vals", [0, 1])
    a, b = Variable("a", d), Variable("b", d)
    dcop.add_variable(a)
    dcop.add_variable(b)
    m = np.array([[1.0, 2.0], [2.0, 3.0]]) if m is None else np.asarray(m)
    dcop.add_constraint(NAryMatrixRelation([a, b], m, name="c"))
    dcop.add_agents([AgentDef("ag")])
    return dcop


def make_solver(dcop, **params):
    algo = AlgorithmDef.build_with_default_params(
        "gdba", params, parameters_definitions=algo_params
    )
    return GdbaSolver(dcop, compile_constraint_graph(dcop), algo)


def one_cycle(solver, x):
    state = (jnp.asarray(x, dtype=jnp.int32), solver.initial_state()[1])
    (x2, ws2) = solver.cycle(state, jax.random.PRNGKey(0))
    return np.asarray(x2), [np.asarray(w) for w in ws2]


@pytest.mark.parametrize(
    "modifier,violation,increase",
    list(itertools.product(MODIFIERS, VIOLATIONS, INCREASES)),
)
def test_full_matrix_solves_coloring(modifier, violation, increase):
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.runtime import solve_result

    dcop = generate_graph_coloring(
        n_variables=10, n_colors=3, n_edges=16, soft=True, n_agents=1,
        seed=5,
    )
    res = solve_result(
        dcop, "gdba", cycles=20,
        algo_params={"modifier": modifier, "violation": violation,
                     "increase_mode": increase},
    )
    assert res.status == "FINISHED"
    assert sorted(res.assignment) == sorted(dcop.variables)
    assert res.cost < 500


def test_weights_bump_only_at_quasi_local_min():
    # (0,0) is a strict local min (gain 0 both vars) with cost 1 > 0:
    # NZ bumps; a state with positive gain must NOT bump
    dcop = trap_dcop()
    solver = make_solver(dcop, violation="NZ", increase_mode="E")
    _, ws = one_cycle(solver, [0, 0])
    assert ws[0][0, 0, 0] == 1.0  # bumped current entry
    assert ws[0].sum() == 1.0
    # state (1, 1) costs 3; moving b to 0 gains 1 -> not stuck, no bump
    _, ws = one_cycle(solver, [1, 1])
    assert ws[0].sum() == 0.0


def test_violation_modes_differ():
    # at the (0,0) local min: cost 1 = fmin -> NM says NOT violated,
    # NZ says violated (1 > 0), MX says not violated (1 < fmax=3)
    dcop = trap_dcop()
    for violation, expect_bump in (("NZ", True), ("NM", False),
                                   ("MX", False)):
        solver = make_solver(dcop, violation=violation, increase_mode="E")
        _, ws = one_cycle(solver, [0, 0])
        assert (ws[0].sum() > 0) == expect_bump, violation


def test_violation_mx_fires_on_maximal_entry():
    # constraint where the stuck state IS the maximal entry:
    # M = [[5, 6], [6, 7]] has its min at (0,0)=5... need stuck at max.
    # Use M = [[7, 8], [8, 8]]: at (1,1) cost 8 = fmax, moves cost 8 ->
    # no gain -> stuck, MX violated.
    dcop = trap_dcop(m=[[7.0, 8.0], [8.0, 8.0]])
    solver = make_solver(dcop, violation="MX", increase_mode="E")
    _, ws = one_cycle(solver, [1, 1])
    assert ws[0][0, 1, 1] == 1.0
    # NM also fires (8 > fmin=7); NZ also fires (8 > 0)
    for violation in ("NM", "NZ"):
        s2 = make_solver(dcop, violation=violation, increase_mode="E")
        _, ws2 = one_cycle(s2, [1, 1])
        assert ws2[0][0, 1, 1] == 1.0


def test_increase_mode_entry_sets():
    dcop = trap_dcop()
    masks = {}
    for mode in INCREASES:
        solver = make_solver(dcop, violation="NZ", increase_mode=mode)
        _, ws = one_cycle(solver, [0, 0])
        masks[mode] = ws[0][0] > 0  # [D, D] bump mask of the constraint
    # E: exactly the current entry
    assert masks["E"].sum() == 1 and masks["E"][0, 0]
    # R and C (binary): current row + column through (0,0) -> 3 entries
    for mode in ("R", "C"):
        assert masks[mode].sum() == 3
        assert masks[mode][0, 0] and masks[mode][0, 1] and masks[mode][1, 0]
        assert not masks[mode][1, 1]
    # T: whole tensor
    assert masks["T"].all()
    # nesting: E <= R == C <= T
    assert (masks["E"] <= masks["R"]).all()
    assert (masks["R"] <= masks["T"]).all()


def test_modifier_a_vs_m_effective_costs():
    dcop = trap_dcop()
    for modifier, expected in (("A", 1.0 + 1.0), ("M", 1.0 * 2.0)):
        solver = make_solver(dcop, modifier=modifier, violation="NZ",
                             increase_mode="E")
        x = jnp.asarray([0, 0], dtype=jnp.int32)
        state = (x, solver.initial_state()[1])
        state = solver.cycle(state, jax.random.PRNGKey(0))
        # after one bump the effective cost of entry (0,0) must be
        # base+1 (A, W0=0) or base*2 (M, W0=1 bumped to 2)
        eff = solver._effective(state[1])[0]
        assert float(eff[0, 0, 0]) == pytest.approx(expected), modifier


def test_breakout_escapes_local_minimum():
    """The defining GDBA behavior: weight bumps eventually push the
    search out of a local minimum a pure hill-climber cannot leave."""
    # (0,0) local min cost 1; global optimum (1,1) cost 0 requires
    # passing through cost-2 states -> plain MGM-style moves never take
    # it, breakout re-weights (0,0) until a move opens
    dcop = trap_dcop(m=[[1.0, 2.0], [2.0, 0.0]])
    solver = make_solver(dcop, violation="NZ", increase_mode="E")
    x = jnp.asarray([0, 0], dtype=jnp.int32)
    state = (x, solver.initial_state()[1])
    key = jax.random.PRNGKey(3)
    seen = []
    for _ in range(8):
        key, sub = jax.random.split(key)
        state = solver.cycle(state, sub)
        seen.append(tuple(int(v) for v in np.asarray(state[0])))
    assert (1, 1) in seen, seen
    assert seen[-1] == (1, 1)  # and it stays at the optimum
