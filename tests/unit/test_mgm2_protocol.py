"""MGM-2 protocol edge cases (VERDICT item 6): offer collisions at one
receiver, and a committed pair blocked by a stronger neighbor in the
gain/go rounds (partners must BOTH win their neighborhoods — reference
pydcop/algorithms/mgm2.py go handling).
"""
import jax.numpy as jnp
import jax.random
import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.mgm2 import Mgm2Solver, algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.compile import compile_constraint_graph


def build(dcop, **params):
    algo = AlgorithmDef.build_with_default_params(
        "mgm2", params, parameters_definitions=algo_params
    )
    return Mgm2Solver(dcop, compile_constraint_graph(dcop), algo)


def chain_dcop():
    """a - b - c with joint gains 6 (a,b) and 2 (b,c); every unilateral
    gain is 0.  Only coordinated moves can improve."""
    dcop = DCOP("chain", objective="min")
    d = Domain("d", "vals", [0, 1])
    a, b, c = (Variable(n, d) for n in "abc")
    for v in (a, b, c):
        dcop.add_variable(v)
    m0 = np.array([[10.0, 10.0], [10.0, 4.0]])
    m1 = np.array([[8.0, 8.0], [8.0, 6.0]])
    dcop.add_constraint(NAryMatrixRelation([a, b], m0, name="c0"))
    dcop.add_constraint(NAryMatrixRelation([b, c], m1, name="c1"))
    dcop.add_agents([AgentDef("ag")])
    return dcop


def run_cycle(solver, x, key):
    (x2,) = solver.cycle((jnp.asarray(x, dtype=jnp.int32),), key)
    return tuple(int(v) for v in np.asarray(x2))


def test_offer_collision_receiver_takes_best():
    """When both a and c offer to b, b must accept the (a,b) pair
    (joint gain 6 beats 2); the (b,c) pair only ever wins when a did
    not offer."""
    solver = build(chain_dcop())
    outcomes = set()
    for k in range(60):
        outcomes.add(run_cycle(solver, [0, 0, 0], jax.random.PRNGKey(k)))
    # possible cycle-1 outcomes: pair (a,b) moved, pair (b,c) moved, or
    # no valid offer happened this cycle
    assert outcomes <= {(1, 1, 0), (0, 1, 1), (0, 0, 0)}, outcomes
    assert (1, 1, 0) in outcomes  # the best pair does move
    # a unilateral move alone is never an improvement here
    assert (1, 0, 0) not in outcomes and (0, 1, 0) not in outcomes


def test_chain_converges_to_coordinated_optimum():
    solver = build(chain_dcop())
    x = jnp.asarray([0, 0, 0], dtype=jnp.int32)
    key = jax.random.PRNGKey(1)
    state = (x,)
    for _ in range(12):
        key, sub = jax.random.split(key)
        state = solver.cycle(state, sub)
    final = tuple(int(v) for v in np.asarray(state[0]))
    # the pair move reaches (1,1,0) cost 12, then c follows unilaterally:
    # global optimum (1,1,1), cost M0[1,1] + M1[1,1] = 4 + 6 = 10
    assert final == (1, 1, 1)
    _, cost = solver.dcop.solution_cost(
        {"a": 1, "b": 1, "c": 1}, 10000)
    assert cost == 10


def test_pair_blocked_by_stronger_neighbor():
    """A committed pair whose member loses its neighborhood to a bigger
    unilateral gain must NOT move; the big gain moves instead."""
    dcop = DCOP("blocked", objective="min")
    d = Domain("d", "vals", [0, 1])
    a, b, dd = (Variable(n, d) for n in ("a", "b", "d"))
    for v in (a, b, dd):
        dcop.add_variable(v)
    # pair (a,b): joint gain 6, unilateral 0 (same trap as chain_dcop)
    m0 = np.array([[10.0, 10.0], [10.0, 4.0]])
    # d: unilateral gain 100 by moving to 1; neighbor of b
    m2 = np.array([[100.0, 0.0], [100.0, 0.0]])  # cost(b, d)
    dcop.add_constraint(NAryMatrixRelation([a, b], m0, name="c0"))
    dcop.add_constraint(NAryMatrixRelation([b, dd], m2, name="c1"))
    dcop.add_agents([AgentDef("ag")])
    solver = build(dcop)
    for k in range(40):
        out = run_cycle(solver, [0, 0, 0], jax.random.PRNGKey(k))
        # d always wins its neighborhood (gain 100) and moves; b loses
        # (6 < 100), so the pair never goes this cycle; a alone must not
        # move either (its only gain is the blocked pair move)
        assert out[2] == 1, (k, out)
        assert out[0] == 0 and out[1] == 0, (k, out)


def test_threshold_zero_means_pure_mgm():
    """threshold=0: nobody offers, MGM-2 degenerates to MGM — in the
    all-coordination trap nothing can move."""
    solver = build(chain_dcop(), threshold=0.0)
    for k in range(10):
        assert run_cycle(solver, [0, 0, 0], jax.random.PRNGKey(k)) == \
            (0, 0, 0)


def test_threshold_one_means_everyone_offers():
    """threshold=1: every variable is an offerer, so no one receives —
    offers need a non-offerer other end — and again nothing moves."""
    solver = build(chain_dcop(), threshold=1.0)
    for k in range(10):
        assert run_cycle(solver, [0, 0, 0], jax.random.PRNGKey(k)) == \
            (0, 0, 0)
