"""YAML format robustness: extensional value tables, initial values
honored by solvers, distribution hints, and malformed-input errors
(reference format: pydcop/dcop/yamldcop.py).
"""
import textwrap

import pytest

from pydcop_tpu.dcop import load_dcop
from pydcop_tpu.dcop.yamldcop import dcop_yaml
from pydcop_tpu.runtime import solve_result


class TestExtensional:
    YAML = textwrap.dedent("""
        name: ext
        objective: min
        domains:
          d: {values: [a, b, c]}
        variables:
          x: {domain: d}
          y: {domain: d}
        constraints:
          table:
            type: extensional
            variables: [x, y]
            default: 9
            values:
              0: a a | b b
              1: a b
        agents: [a1, a2, a3]
    """)

    def test_values_and_default(self):
        dcop = load_dcop(self.YAML)
        c = dcop.constraints["table"]
        assert c(x="a", y="a") == 0
        assert c(x="b", y="b") == 0
        assert c(x="a", y="b") == 1
        assert c(x="c", y="a") == 9  # default

    def test_solvable(self):
        dcop = load_dcop(self.YAML)
        res = solve_result(dcop, "dpop")
        assert res.cost == 0
        assert res.assignment["x"] == res.assignment["y"]

    def test_roundtrip_preserves_semantics(self):
        dcop = load_dcop(self.YAML)
        dcop2 = load_dcop(dcop_yaml(dcop))
        c1, c2 = dcop.constraints["table"], dcop2.constraints["table"]
        for x in "abc":
            for y in "abc":
                assert c1(x=x, y=y) == c2(x=x, y=y), (x, y)


class TestInitialValues:
    YAML = textwrap.dedent("""
        name: init
        objective: min
        domains:
          d: {values: [0, 1, 2]}
        variables:
          x: {domain: d, initial_value: 2}
          y: {domain: d, initial_value: 1}
        constraints:
          free:
            type: intention
            function: "0 * (x + y)"
        agents: [a1, a2, a3]
    """)

    def test_parsed(self):
        dcop = load_dcop(self.YAML)
        assert dcop.variables["x"].initial_value == 2
        assert dcop.variables["y"].initial_value == 1

    def test_local_search_starts_from_initial_values(self):
        """All-zero constraint -> no gain ever -> a local-search solver
        must keep the declared initial values."""
        dcop = load_dcop(self.YAML)
        res = solve_result(dcop, "mgm", cycles=10)
        assert res.assignment == {"x": 2, "y": 1}

    def test_invalid_initial_value_rejected(self):
        from pydcop_tpu.dcop.yamldcop import DcopInvalidFormatError

        bad = self.YAML.replace("initial_value: 2", "initial_value: 7")
        with pytest.raises(DcopInvalidFormatError, match="initial value"):
            load_dcop(bad)


class TestHints:
    def test_must_host_hints_parsed_and_applied(self):
        yaml_str = textwrap.dedent("""
            name: hints
            objective: min
            domains:
              d: {values: [0, 1]}
            variables:
              x: {domain: d}
              y: {domain: d}
            constraints:
              c:
                type: intention
                function: "x + y"
            agents: [a1, a2, a3]
            distribution_hints:
              must_host:
                a1: [x]
                a2: [y]
        """)
        dcop = load_dcop(yaml_str)
        hints = dcop.dist_hints
        assert hints.must_host("a1") == ["x"]
        from pydcop_tpu.distribution import load_distribution_module
        from pydcop_tpu.graph import constraints_hypergraph

        cg = constraints_hypergraph.build_computation_graph(dcop)
        dist = load_distribution_module("adhoc").distribute(
            cg, dcop.agents.values(), hints=hints,
            computation_memory=lambda n: 1.0,
        )
        assert "x" in dist.computations_hosted("a1")
        assert "y" in dist.computations_hosted("a2")


class TestMalformed:
    def test_no_variables_section(self):
        from pydcop_tpu.dcop.yamldcop import DcopInvalidFormatError

        with pytest.raises(DcopInvalidFormatError, match="variables"):
            load_dcop("name: empty\ndomains:\n  d: {values: [0]}\n")

    def test_unknown_domain_reference(self):
        bad = textwrap.dedent("""
            name: bad
            domains:
              d: {values: [0, 1]}
            variables:
              x: {domain: nosuch}
            agents: [a1]
        """)
        with pytest.raises(Exception):
            load_dcop(bad)

    def test_constraint_over_unknown_variable(self):
        bad = textwrap.dedent("""
            name: bad
            domains:
              d: {values: [0, 1]}
            variables:
              x: {domain: d}
            constraints:
              c:
                type: intention
                function: "x + zz"
            agents: [a1]
        """)
        with pytest.raises(Exception):
            load_dcop(bad)

    def test_bad_objective_rejected(self):
        bad = textwrap.dedent("""
            name: bad
            objective: fastest
            domains:
              d: {values: [0, 1]}
            variables:
              x: {domain: d}
            agents: [a1]
        """)
        with pytest.raises(ValueError, match="objective"):
            load_dcop(bad)
