"""Hub splitting in the lane-packed pallas engines (VERDICT r3 item 2).

A variable with degree above _MAX_SLOT_CLASS (96) is split into several
sub-columns inside the normal degree-class buckets; its belief / local
table / neighborhood arbitration are recovered with a handful of
within-vreg lane gathers.  These tests check the packed engines
bit-match the generic XLA engines on scale-free (Barabási–Albert-like)
and star instances — the graphs that previously knocked the whole
problem onto the 8-25x slower generic path.

Kernels run in interpret mode (CPU test env); the traced math is the
same on TPU.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from pydcop_tpu.ops.compile import (
    compile_binary_from_arrays,
    local_cost_tables,
)
from pydcop_tpu.ops.maxsum_kernels import init_messages, maxsum_cycle
from pydcop_tpu.ops.pallas_maxsum import (
    _MAX_SLOT_CLASS,
    pack_for_pallas,
    packed_cycle,
    packed_init_state,
    packed_local_tables,
)


def barabasi_albert_edges(V: int, m: int, seed: int = 0):
    """Degree-biased preferential attachment; returns (ei, ej) with a
    heavy-tailed degree distribution (guaranteed hubs for small seeds)."""
    rng = np.random.default_rng(seed)
    targets = list(range(m))
    repeated: list = list(range(m))
    ei, ej = [], []
    for v in range(m, V):
        for t in set(targets):
            ei.append(v)
            ej.append(t)
            repeated.extend([v, t])
        targets = [repeated[rng.integers(0, len(repeated))] for _ in range(m)]
    return np.asarray(ei), np.asarray(ej)


def _scalefree_instance(V=400, m=3, D=3, seed=0, boost_hub=True):
    rng = np.random.default_rng(seed + 100)
    ei, ej = barabasi_albert_edges(V, m, seed)
    if boost_hub:
        # wire every 3rd variable to the max-degree node so its degree
        # far exceeds the slot-class ceiling
        deg = np.bincount(np.concatenate([ei, ej]), minlength=V)
        hub = int(np.argmax(deg))
        extra = np.array(
            [v for v in range(0, V, 3) if v != hub], dtype=np.int64
        )
        ei = np.concatenate([ei, extra])
        ej = np.concatenate([ej, np.full(len(extra), hub)])
    F = len(ei)
    mats = rng.uniform(0, 5, (F, D, D)).astype(np.float32)
    un = rng.uniform(0, 1, (V, D)).astype(np.float32)
    return compile_binary_from_arrays(ei, ej, mats, V, unary=un)


class TestHubLayout:
    def test_hub_is_split_not_rejected(self):
        t = _scalefree_instance()
        pg = pack_for_pallas(t)
        assert pg is not None
        assert pg.hub_nsteps > 0
        deg = np.zeros(t.n_vars, dtype=np.int64)
        vi = np.asarray(t.buckets[0].var_idx)
        for col in (vi[:, 0], vi[:, 1]):
            deg += np.bincount(col, minlength=t.n_vars)
        assert deg.max() > _MAX_SLOT_CLASS
        # every variable still has exactly one head column
        cols = np.asarray(pg.var_order)
        assert len(set(cols.tolist())) == t.n_vars
        # member columns map back to their hub in col_var
        cv = pg.col_var
        assert (np.bincount(cv[cv >= 0], minlength=t.n_vars) >= 1).all()

    def test_groups_stay_inside_bins(self):
        t = _scalefree_instance()
        pg = pack_for_pallas(t)
        cv = pg.col_var
        # group = run of equal var ids; must not straddle a 128 boundary
        counts = np.bincount(cv[cv >= 0], minlength=t.n_vars)
        for v in np.flatnonzero(counts > 1):
            cols = np.flatnonzero(cv == v)
            assert cols.max() - cols.min() == len(cols) - 1  # contiguous
            assert cols.min() // 128 == cols.max() // 128


class TestHubMaxSum:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_cycle_matches_generic_engine(self, seed):
        t = _scalefree_instance(seed=seed)
        pg = pack_for_pallas(t)
        assert pg is not None and pg.hub_nsteps > 0
        q, r = init_messages(t)
        qp, rp = packed_init_state(pg)
        for _ in range(4):
            q, r, bel, vals = maxsum_cycle(t, q, r, damping=0.5)
            qp, rp, belp, valsp = packed_cycle(
                pg, qp, rp, damping=0.5, interpret=True
            )
        belp_orig = np.asarray(belp)[:, np.asarray(pg.var_order)].T
        assert np.allclose(np.asarray(bel), belp_orig, atol=1e-3)
        assert np.array_equal(np.asarray(vals), np.asarray(valsp))

    def test_local_tables_match_generic(self):
        t = _scalefree_instance(seed=2)
        pg = pack_for_pallas(t)
        assert pg is not None and pg.hub_nsteps > 0
        rng = np.random.default_rng(3)
        x = np.asarray(rng.integers(0, 3, t.n_vars), dtype=np.int32)
        ref = np.asarray(local_cost_tables(t, jnp.asarray(x)))
        got = np.asarray(
            packed_local_tables(pg, jnp.asarray(x), interpret=True)
        )
        assert np.allclose(ref, got, atol=1e-3)


class TestHubLocalSearch:
    def _dcop(self, V=300, seed=4):
        """A scale-free coloring DCOP built through the public model API
        (so generic and packed solvers share tensors)."""
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        rng = np.random.default_rng(seed)
        ei, ej = barabasi_albert_edges(V, 3, seed)
        deg = np.bincount(np.concatenate([ei, ej]), minlength=V)
        hub = int(np.argmax(deg))
        extra = np.array(
            [v for v in range(0, V, 2) if v != hub], dtype=np.int64
        )
        ei = np.concatenate([ei, extra])
        ej = np.concatenate([ej, np.full(len(extra), hub)])
        dcop = DCOP("hubtest", objective="min")
        dom = Domain("colors", "colors", [0, 1, 2])
        vs = [Variable(f"v{i}", dom) for i in range(V)]
        for v in vs:
            dcop.add_variable(v)
        seen = set()
        for k, (i, j) in enumerate(zip(ei.tolist(), ej.tolist())):
            if i == j or (i, j) in seen or (j, i) in seen:
                continue
            seen.add((i, j))
            mat = rng.uniform(0, 5, (3, 3)).astype(np.float32)
            dcop.add_constraint(
                NAryMatrixRelation([vs[i], vs[j]], mat, name=f"c{k}")
            )
        dcop.add_agents([AgentDef("a0")])
        return dcop

    def _solver_pair(self, algo, dcop):
        """(generic solver, packed solver) with identical seeds."""
        import jax
        from pydcop_tpu.algorithms import (
            AlgorithmDef,
            load_algorithm_module,
        )

        mod = load_algorithm_module(algo)
        algo_def = AlgorithmDef.build_with_default_params(algo)
        generic = mod.build_solver(dcop, algo_def=algo_def)
        assert generic.packed is None  # CPU → generic
        import unittest.mock as mock

        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            packed = mod.build_solver(dcop, algo_def=algo_def)
        assert packed.packed is not None
        assert packed.packed.hub_nsteps > 0
        return generic, packed

    @pytest.mark.parametrize("algo", ["mgm", "dsa"])
    def test_fused_matches_generic(self, algo):
        dcop = self._dcop()
        generic, packed = self._solver_pair(algo, dcop)
        rg = generic.run(cycles=10, chunk=10)
        rp = packed.run(cycles=10, chunk=10)
        # same PRNG stream + same move rules → identical assignments
        assert rg.assignment == rp.assignment
        assert rg.cost == pytest.approx(rp.cost, rel=1e-5)
