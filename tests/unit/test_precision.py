"""Mixed-precision tiers (ISSUE 19).

The acceptance contract under test:

* ``precision="f32"`` is BIT-IDENTICAL to a build without the knob:
  :func:`ops.precision.apply_precision` returns the SAME tensors
  object and solver results match the default path exactly;
* int8 quantization is property-bounded — round-trip error of finite
  entries <= scale/2, every hard/BIG entry pinned to the saturation
  code (dequantizes to PAD_COST), and argmins preserved on
  integer-valued tables whose range fits the code space;
* bf16 final costs sit inside the DECLARED statistical gate
  (``ops.precision.BF16_COST_RTOL``/``ATOL``) for maxsum/mgm/dsa
  across seeds — one declared gate, not per-test tolerances;
* the audit registry PROVES the collective-byte cut: the bf16 cells'
  jaxpr-walked payloads are >= 2x smaller than their f32 twins';
* unsupported tiers refuse with typed errors and pinned messages
  (engine tier maps, sharded int8, batched int8, weighted-rule int8,
  structured sharding/batching);
* checkpoints record the tier and refuse a mismatched restore;
* ``solve --auto`` never routes int8 where the featurizer could not
  prove it lossless (conservative mask, pinned);
* warm quantized in-place edits keep the zero-retrace contract;
* the vectorized memo embedding scan matches the per-entry loop it
  replaced, including the stable insertion-order tie-break.
"""
from __future__ import annotations

import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.ops.compile import (
    PAD_COST,
    QUANT_SATURATION,
    QUANT_THRESHOLD,
)
from pydcop_tpu.ops.precision import (
    BF16_COST_ATOL,
    BF16_COST_RTOL,
    EXACTNESS,
    PRECISIONS,
    PrecisionError,
    apply_precision,
    cast_bf16_preserving_hard,
    dequantize_table,
    message_dtype,
    payload_itemsize,
    precision_of,
    quantize_table,
    resolve_precision,
)


def _dcop(seed=1, V=16, E=24):
    return generate_graph_coloring(
        n_variables=V, n_colors=3, n_edges=E, soft=True, n_agents=1,
        seed=seed,
    )


def _solver(algo, dcop, precision=None, seed=0):
    params = {} if precision is None else {"precision": precision}
    adef = AlgorithmDef.build_with_default_params(algo, params)
    return load_algorithm_module(algo).build_solver(
        dcop, algo_def=adef, seed=seed
    )


# ---------------------------------------------------------------------------
# tier map + knob plumbing
# ---------------------------------------------------------------------------


class TestTierMap:
    def test_exactness_map_covers_every_tier(self):
        assert set(EXACTNESS) == set(PRECISIONS)
        assert EXACTNESS["f32"] == "exact"
        assert EXACTNESS["bf16"] == "statistical"
        assert EXACTNESS["int8"] == "quantized"

    def test_resolve_defaults_and_rejects(self):
        assert resolve_precision(None) == "f32"
        assert resolve_precision("") == "f32"
        assert resolve_precision("BF16") == "bf16"
        with pytest.raises(PrecisionError, match="f32/bf16/int8"):
            resolve_precision("fp8")

    def test_payload_and_message_dtypes(self):
        import jax.numpy as jnp

        assert payload_itemsize("f32") == 4
        assert payload_itemsize("bf16") == 2
        # int8 keeps bf16 messages: quantizing accumulating state
        # would compound error cycle over cycle
        assert message_dtype("int8") == jnp.bfloat16
        assert message_dtype("f32") == jnp.float32


# ---------------------------------------------------------------------------
# int8 quantization properties
# ---------------------------------------------------------------------------


class TestQuantization:
    def test_round_trip_error_bounded_by_half_scale(self):
        rng = np.random.default_rng(0)
        t = rng.uniform(-30.0, 170.0, (6, 3, 3)).astype(np.float32)
        codes, scale, offset = quantize_table(t)
        deq = np.asarray(dequantize_table(codes, scale, offset))
        err = np.abs(deq - t).reshape(6, -1).max(axis=1)
        assert np.all(err <= scale / 2 + 1e-5), (err, scale)

    def test_big_entries_saturate_and_dequantize_to_pad(self):
        t = np.array(
            [[[0.0, 3.0], [QUANT_THRESHOLD, PAD_COST]]], np.float32
        )
        codes, scale, offset = quantize_table(t)
        assert codes[0, 1, 0] == QUANT_SATURATION
        assert codes[0, 1, 1] == QUANT_SATURATION
        deq = np.asarray(dequantize_table(codes, scale, offset))
        assert deq[0, 1, 0] == np.float32(PAD_COST)
        assert deq[0, 1, 1] == np.float32(PAD_COST)
        # finite entries unharmed by the saturated neighbors
        assert abs(deq[0, 0, 1] - 3.0) <= scale[0] / 2 + 1e-6

    def test_argmin_preserved_on_integer_tables(self):
        rng = np.random.default_rng(7)
        t = rng.integers(0, 254, (8, 4, 4)).astype(np.float32)
        codes, scale, offset = quantize_table(t)
        assert np.all(scale <= 1.0 + 1e-6)
        deq = np.asarray(dequantize_table(codes, scale, offset))
        # error < 0.5 on an integer grid -> every argmin survives
        flat_t = t.reshape(8, -1)
        flat_d = deq.reshape(8, -1)
        assert np.array_equal(
            np.argmin(flat_t, axis=1), np.argmin(flat_d, axis=1)
        )

    def test_constant_table_quantizes_without_dividing_by_zero(self):
        t = np.full((2, 3, 3), 5.0, np.float32)
        codes, scale, offset = quantize_table(t)
        deq = np.asarray(dequantize_table(codes, scale, offset))
        np.testing.assert_allclose(deq, t, atol=1e-6)


# ---------------------------------------------------------------------------
# bf16 guarded cast
# ---------------------------------------------------------------------------


class TestBf16Cast:
    def test_hard_threshold_never_rounds_below(self):
        t = np.array(
            [QUANT_THRESHOLD, 10001.0, 12345.0, PAD_COST], np.float32
        )
        bt = cast_bf16_preserving_hard(t).astype(np.float32)
        assert np.all(bt >= QUANT_THRESHOLD), bt

    def test_soft_entries_round_to_nearest(self):
        t = np.array([0.5, 9999.0, 1.0 / 3.0], np.float32)
        bt = cast_bf16_preserving_hard(t).astype(np.float32)
        assert bt[0] == 0.5
        assert bt[1] < QUANT_THRESHOLD  # stays a soft cost
        assert abs(bt[2] - 1.0 / 3.0) < 2e-3


# ---------------------------------------------------------------------------
# f32 bit-identity + staging
# ---------------------------------------------------------------------------


class TestF32BitIdentity:
    def test_apply_precision_f32_is_the_same_object(self):
        s = _solver("maxsum", _dcop())
        assert apply_precision(s.tensors, "f32") is s.tensors
        assert apply_precision(s.tensors, None) is s.tensors

    @pytest.mark.parametrize("algo", ["maxsum", "mgm", "dsa"])
    def test_explicit_f32_matches_default_run(self, algo):
        d = _dcop(seed=2)
        ref = _solver(algo, d, precision=None, seed=1).run(
            cycles=40, chunk=20
        )
        got = _solver(algo, d, precision="f32", seed=1).run(
            cycles=40, chunk=20
        )
        assert got.assignment == ref.assignment
        assert got.cost == ref.cost
        assert got.cycle == ref.cycle

    def test_double_staging_is_idempotent_and_cross_tier_refuses(self):
        s = _solver("maxsum", _dcop())
        b = apply_precision(s.tensors, "bf16")
        assert precision_of(b) == "bf16"
        assert apply_precision(b, "bf16") is b
        with pytest.raises(PrecisionError, match="already staged"):
            apply_precision(b, "int8")


# ---------------------------------------------------------------------------
# bf16 statistical equivalence (the declared gate)
# ---------------------------------------------------------------------------


class TestStatisticalEquivalence:
    @pytest.mark.parametrize("algo", ["maxsum", "mgm", "dsa"])
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_bf16_final_cost_within_declared_gate(self, algo, seed):
        d = _dcop(seed=seed)
        ref = _solver(algo, d, precision=None, seed=seed).run(
            cycles=40, chunk=20
        )
        got = _solver(algo, d, precision="bf16", seed=seed).run(
            cycles=40, chunk=20
        )
        gate = max(BF16_COST_ATOL, BF16_COST_RTOL * abs(ref.cost))
        assert abs(got.cost - ref.cost) <= gate, (
            algo, seed, ref.cost, got.cost,
        )

    def test_int8_keeps_hard_instances_feasible(self):
        # 0/BIG tables: the saturation pin must keep every violation
        # visible, so the quantized run still reaches violation 0 on
        # a colorable instance
        d = generate_graph_coloring(
            n_variables=12, n_colors=3, n_edges=16, soft=False,
            n_agents=1, seed=4,
        )
        res = _solver("mgm", d, precision="int8", seed=0).run(
            cycles=60, chunk=20
        )
        ref = _solver("mgm", d, precision=None, seed=0).run(
            cycles=60, chunk=20
        )
        assert res.violation == ref.violation


# ---------------------------------------------------------------------------
# typed refusals (messages pinned)
# ---------------------------------------------------------------------------


class TestTierRefusals:
    def test_weighted_rule_refuses_int8(self):
        with pytest.raises(PrecisionError) as e:
            _solver("dba", _dcop(), precision="int8")
        assert str(e.value) == (
            "dba does not support precision='int8' (supported: "
            "bf16/f32); run precision=f32 (exact) or bf16 (statistical)"
        )

    def test_sharded_engines_refuse_int8(self):
        from pydcop_tpu.analysis.registry import (
            _mesh,
            _ring_factor_tensors,
        )
        from pydcop_tpu.parallel.mesh import ShardedMaxSum

        with pytest.raises(PrecisionError,
                           match="single-device engine for int8"):
            ShardedMaxSum(
                _ring_factor_tensors(), _mesh(), precision="int8"
            )

    def test_batched_lanes_refuse_int8(self):
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.batch.engine import BatchEngine, BatchItem

        items = [
            BatchItem(_dcop(seed=s), "maxsum",
                      algo_params={"precision": "int8"}, seed=s)
            for s in (1, 2)
        ]
        engine = BatchEngine(cache=CompileCache(),
                             max_padding_waste=0.9)
        with pytest.raises(PrecisionError,
                           match="do not stack int8"):
            engine.solve(items, cycles=5)

    def test_structured_sharding_refusal_typed_and_pinned(self):
        from pydcop_tpu.analysis.registry import _structured_dcop
        from pydcop_tpu.ops.compile import compile_factor_graph
        from pydcop_tpu.parallel.mesh import (
            StructuredShardingUnsupported,
            shard_factor_graph,
        )

        assert issubclass(
            StructuredShardingUnsupported, NotImplementedError
        )
        t = compile_factor_graph(_structured_dcop())
        with pytest.raises(StructuredShardingUnsupported) as e:
            shard_factor_graph(t, 2)
        assert str(e.value) == (
            "sharded maxsum does not yet shard table-free (structured) "
            "buckets; run the single-device engine or densify small "
            "structured constraints first"
        )

    def test_structured_batching_refusal_typed_and_pinned(self):
        from types import SimpleNamespace

        from pydcop_tpu.batch.bucketing import (
            StructuredBatchingUnsupported,
            dims_of,
        )

        assert issubclass(
            StructuredBatchingUnsupported, NotImplementedError
        )
        fake = SimpleNamespace(sbuckets=[object()])
        with pytest.raises(StructuredBatchingUnsupported) as e:
            dims_of(fake, "factor_graph")
        assert str(e.value) == (
            "batched lanes do not yet pad table-free (structured) "
            "buckets; solve structured instances on a dedicated lane"
        )


# ---------------------------------------------------------------------------
# checkpoints record the tier; restore refuses a mismatch
# ---------------------------------------------------------------------------


class TestCheckpointTier:
    def test_tier_recorded_and_mismatch_refused(self, tmp_path):
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        d = _dcop(seed=3)
        s = _solver("mgm", d, precision="bf16", seed=0)
        s.run(cycles=10, chunk=5)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, s, cycle=10)

        other = _solver("mgm", d, precision=None, seed=0)
        with pytest.raises(PrecisionError) as e:
            load_checkpoint(path, other)
        msg = str(e.value)
        assert "precision='bf16'" in msg and "precision='f32'" in msg

        # matching tier restores fine and reports the recorded tier
        twin = _solver("mgm", d, precision="bf16", seed=0)
        meta = load_checkpoint(path, twin)
        assert meta["precision"] == "bf16"

    def test_pre_tier_checkpoints_default_to_f32(self, tmp_path):
        # a meta without the key (older writer) restores into an f32
        # solver — the default tier is the only one old files can hold
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            read_state_npz,
            save_checkpoint,
            write_state_npz,
        )

        d = _dcop(seed=3)
        s = _solver("mgm", d, seed=0)
        s.run(cycles=5, chunk=5)
        path = str(tmp_path / "old.npz")
        save_checkpoint(path, s, cycle=5)
        meta, arrays = read_state_npz(path)
        assert meta.pop("precision") == "f32"
        write_state_npz(path, arrays, meta)
        fresh = _solver("mgm", d, seed=0)
        meta2 = load_checkpoint(path, fresh)
        assert meta2.get("precision", "f32") == "f32"


# ---------------------------------------------------------------------------
# the audited collective-byte cut (jaxpr-walked, not estimated)
# ---------------------------------------------------------------------------


class TestAuditedByteCut:
    PAIRS = [
        # the compact sharded maxsum cells and the packed local-search
        # cells the acceptance names, plus the dense psum twin
        ("sharded/maxsum/generic/exact",
         "sharded/maxsum/generic/exact-bf16"),
        ("sharded/maxsum/packed/exact",
         "sharded/maxsum/packed/exact-bf16"),
        ("sharded/mgm/packed/exact", "sharded/mgm/packed/exact-bf16"),
        ("sharded/dsa/packed/off", "sharded/dsa/packed/off-bf16"),
    ]

    @pytest.mark.parametrize("f32_cell,bf16_cell", PAIRS)
    def test_bf16_halves_the_walked_payload(self, f32_cell, bf16_cell):
        from pydcop_tpu.analysis import registry

        ra = registry.audit_cell(f32_cell)
        rb = registry.audit_cell(bf16_cell)
        assert not ra.findings, ra.findings
        assert not rb.findings, rb.findings
        a = ra.scorecard["max_collective_payload_bytes"]
        b = rb.scorecard["max_collective_payload_bytes"]
        assert a > 0 and b > 0
        assert a >= 2 * b, (f32_cell, a, b)

    def test_maxsum_total_cycle_payload_at_least_halves(self):
        # maxsum has no f32 arbitration extras, so the SUM of every
        # collective payload in one cycle must cut >= 2x too
        import jax

        from pydcop_tpu.analysis import registry
        from pydcop_tpu.analysis.auditor import collect_collectives

        def total(cell):
            prog = registry.build_cell(cell)
            closed = jax.make_jaxpr(prog.fn)(*prog.args)
            return sum(n for _k, _s, n in collect_collectives(closed))

        a = total("sharded/maxsum/generic/exact")
        b = total("sharded/maxsum/generic/exact-bf16")
        assert a > 0 and a >= 2 * b, (a, b)

    def test_bf16_cells_declare_the_statistical_tier(self):
        from pydcop_tpu.analysis import registry
        from pydcop_tpu.parallel.mesh import _CommPlanMixin

        assert _CommPlanMixin.PRECISION_TIERS == {
            "f32": "exact", "bf16": "statistical",
        }
        names = registry.cell_names()
        assert "sharded/maxsum/generic/exact-bf16" in names
        assert "sharded/mgm/packed/exact-bf16" in names


# ---------------------------------------------------------------------------
# solve --auto: the cheap tiers only where safe
# ---------------------------------------------------------------------------


class TestPortfolioPrecision:
    def _integer_dcop(self):
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        rng = np.random.default_rng(0)
        d = DCOP("ints", "min")
        dom = Domain("c", "c", [0, 1, 2])
        vs = [Variable(f"v{i}", dom) for i in range(8)]
        for v in vs:
            d.add_variable(v)
        for i in range(8):
            m = rng.integers(0, 10, (3, 3)).astype(float)
            d.add_constraint(NAryMatrixRelation(
                [vs[i], vs[(i + 1) % 8]], m, name=f"c{i}"))
        d.add_agents([AgentDef("a0")])
        return d

    def test_int8_masked_on_float_and_hard_tables(self):
        from pydcop_tpu.portfolio.features import featurize_detail
        from pydcop_tpu.portfolio.select import (
            DEFAULT_GRID,
            feasible_grid,
        )

        for soft in (True, False):
            d = generate_graph_coloring(
                n_variables=10, n_colors=3, n_edges=14, soft=soft,
                n_agents=1, seed=1,
            )
            _vec, info = featurize_detail(d)
            assert info["int8_safe"] is False
            feasible, masked = feasible_grid(
                DEFAULT_GRID, info, n_devices=1
            )
            assert not any(
                getattr(c, "precision", "f32") == "int8"
                for c in feasible
            )
            reasons = [
                r for c, r in masked
                if getattr(c, "precision", "f32") == "int8"
            ]
            assert reasons and all(
                r == ("int8 is only safe on integer-valued cost "
                      "tables with range <= 253 and no hard/BIG "
                      "entries")
                for r in reasons
            )

    def test_int8_feasible_on_integer_small_range_tables(self):
        from pydcop_tpu.portfolio.features import featurize_detail
        from pydcop_tpu.portfolio.select import (
            DEFAULT_GRID,
            feasible_grid,
        )

        _vec, info = featurize_detail(self._integer_dcop())
        assert info["int8_safe"] is True
        feasible, _ = feasible_grid(DEFAULT_GRID, info, n_devices=1)
        assert any(
            getattr(c, "precision", "f32") == "int8" for c in feasible
        )

    def test_exact_engines_stay_f32_only(self):
        from pydcop_tpu.portfolio.select import (
            PortfolioConfig,
            feasible_grid,
        )

        grid = (PortfolioConfig("dpop", engine="auto",
                                precision="bf16"),)
        info = {"sweep_bytes": 1024, "max_node_entries": 729}
        feasible, masked = feasible_grid(grid, info, n_devices=1)
        assert feasible == []
        assert masked[0][1] == (
            "the exact engines compute util tables in f32 only"
        )

    def test_precision_rides_the_config_key_and_params(self):
        from pydcop_tpu.portfolio.select import PortfolioConfig

        f32 = PortfolioConfig("mgm")
        assert f32.key() == "mgm|harness|c0|default|t0.5|b0|i0"
        assert f32.algo_params() == {}
        b = PortfolioConfig("mgm", precision="bf16")
        assert b.key().endswith("|pbf16")
        assert b.algo_params() == {"precision": "bf16"}

    def test_encoder_one_hots_the_tier(self):
        from pydcop_tpu.portfolio.features import (
            CONFIG_ENC_LEN,
            CONFIG_ENC_NAMES,
            encode_config,
        )
        from pydcop_tpu.portfolio.select import PortfolioConfig

        i = CONFIG_ENC_NAMES.index("precision=int8")
        enc = encode_config(PortfolioConfig("mgm", precision="int8"))
        assert enc.shape == (CONFIG_ENC_LEN,)
        assert enc[i] == 1.0
        assert enc[CONFIG_ENC_NAMES.index("precision=f32")] == 0.0


# ---------------------------------------------------------------------------
# warm engines: quantized in-place edits keep zero-retrace
# ---------------------------------------------------------------------------


class TestWarmQuantizedEdits:
    @pytest.mark.parametrize("precision", ["bf16", "int8"])
    def test_edit_factor_zero_retrace_at_cheap_tiers(self, precision):
        from pydcop_tpu.algorithms.warm import build_warm_solver
        from pydcop_tpu.dcop.relations import constraint_from_str
        from pydcop_tpu.ops.headroom import EditFactor

        d = _dcop(seed=5, V=10, E=14)
        adef = AlgorithmDef.build_with_default_params(
            "mgm", {"precision": precision}
        )
        s = build_warm_solver(
            d, algo="mgm", algo_def=adef, seed=3, headroom=0.4
        )
        s.run(cycles=20, chunk=10)
        t0 = s.trace_count()
        name, old = next(iter(d.constraints.items()))
        edited = constraint_from_str(
            name, "1 if {} == {} else 4".format(
                *[v.name for v in old.dimensions]
            ),
            list(old.dimensions),
        )
        s.apply_mutations([EditFactor(edited)])
        d.constraints[name] = edited
        res = s.run(cycles=20, chunk=10, resume=True)
        assert s.trace_count() == t0, (
            "a warm quantized mutation must not retrace"
        )
        assert res.status == "FINISHED"


# ---------------------------------------------------------------------------
# memo: the vectorized embedding scan (ISSUE 19 satellite)
# ---------------------------------------------------------------------------


class TestMemoVectorizedScan:
    def _memo_with_bucket(self, feats_list):
        import time

        from pydcop_tpu.serve.memo import MemoCache, MemoEntry

        memo = MemoCache()
        bucket_key = ("t", "maxsum", "pk", "sig")
        now = time.time()
        for i, f in enumerate(feats_list):
            key = f"k{i}"
            e = MemoEntry(
                key=key, tenant="t", algo="maxsum", pkey="pk",
                seed=0, chash=f"h{i}", shape_sig="sig",
                digests={}, assignment={}, status="FINISHED",
                cost=0.0, violation=0, cycle=1, msg_count=0,
                msg_size=0.0, yaml="", features=f, created_at=now,
                last_used=now,
            )
            memo._entries[key] = e
            memo._buckets.setdefault(bucket_key, []).append(key)
        return memo

    def _probe(self, feats):
        from pydcop_tpu.serve.memo import MemoProbe

        return MemoProbe(
            "miss", "t", "maxsum", "pk", 0, "hX", "kX",
            shape_sig="sig", digests={}, features=feats,
        )

    def test_nearest_entry_wins_and_distance_is_euclidean(self):
        import time

        f = np.zeros(4, np.float32)
        memo = self._memo_with_bucket([
            np.full(4, 3.0, np.float32),
            np.full(4, 1.0, np.float32),
            np.full(4, 2.0, np.float32),
        ])
        probe = self._probe(f)
        with memo._lock:
            memo._match_variant_locked(probe, time.time())
        assert probe.kind == "variant"
        assert probe.entry.key == "k1"
        assert probe.distance == pytest.approx(2.0)

    def test_tie_break_keeps_bucket_insertion_order(self):
        import time

        # k0 and k2 are equidistant; the stable argsort must pick the
        # FIRST inserted — the exact tie-break of the per-entry sort
        # the matrix scan replaced
        memo = self._memo_with_bucket([
            np.array([1.0, 0, 0, 0], np.float32),
            np.array([5.0, 0, 0, 0], np.float32),
            np.array([-1.0, 0, 0, 0], np.float32),
        ])
        probe = self._probe(np.zeros(4, np.float32))
        with memo._lock:
            memo._match_variant_locked(probe, time.time())
        assert probe.entry.key == "k0"

    def test_featureless_entries_rank_last_not_crash(self):
        import time

        memo = self._memo_with_bucket([
            None,
            np.full(4, 9.0, np.float32),
        ])
        probe = self._probe(np.zeros(4, np.float32))
        with memo._lock:
            memo._match_variant_locked(probe, time.time())
        assert probe.entry.key == "k1"

    def test_matches_the_reference_loop_bit_for_bit(self):
        import time

        rng = np.random.default_rng(11)
        feats = [
            rng.standard_normal(8).astype(np.float32)
            for _ in range(17)
        ] + [None]
        memo = self._memo_with_bucket(feats)
        probe_f = rng.standard_normal(8).astype(np.float32)

        # the scan this replaced, verbatim
        ranked = []
        for i, f in enumerate(feats):
            d = (
                float(np.linalg.norm(probe_f - f.astype(np.float32)))
                if f is not None else float("inf")
            )
            ranked.append((d, f"k{i}"))
        ranked.sort(key=lambda t: t[0])

        probe = self._probe(probe_f)
        with memo._lock:
            memo._match_variant_locked(probe, time.time())
        assert probe.entry.key == ranked[0][1]
        assert probe.distance == pytest.approx(ranked[0][0], rel=1e-6)
