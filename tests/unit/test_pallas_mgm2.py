"""Fused MGM-2 kernel (VERDICT r3 item 6) vs the generic solver:
identical assignments from the identical PRNG stream — the whole
5-round pairing protocol (offer, joint-gain, response, gain, go) runs
in one pallas kernel per cycle group.  Interpret mode here; the traced
math is the same on TPU."""
import unittest.mock as mock

import numpy as np
import pytest
import jax

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module


def _coloring_dcop(V=40, E=100, seed=3, colors=3):
    from pydcop_tpu.generators import generate_graph_coloring

    return generate_graph_coloring(
        n_variables=V, n_colors=colors, n_edges=E, soft=True,
        n_agents=1, seed=seed,
    )


def _solver(dcop, packed: bool, **params):
    mod = load_algorithm_module("mgm2")
    algo_def = AlgorithmDef.build_with_default_params(
        "mgm2", params=params or None
    )
    if packed:
        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            s = mod.build_solver(dcop, algo_def=algo_def)
        assert s.packed is not None and s.packed_mgm2 is not None
    else:
        s = mod.build_solver(dcop, algo_def=algo_def)
        assert s.packed is None
    return s


class TestFusedMgm2:
    @pytest.mark.parametrize("favor", ["unilateral", "no", "coordinated"])
    def test_matches_generic_stream(self, favor):
        dcop = _coloring_dcop()
        rg = _solver(dcop, False, favor=favor).run(cycles=10, chunk=10)
        rp = _solver(dcop, True, favor=favor).run(cycles=10, chunk=10)
        assert rg.assignment == rp.assignment
        assert rg.cost == pytest.approx(rp.cost, rel=1e-5)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_matches_across_seeds(self, seed):
        dcop = _coloring_dcop(seed=seed + 10)
        mod = load_algorithm_module("mgm2")
        algo_def = AlgorithmDef.build_with_default_params("mgm2")
        rg = mod.build_solver(dcop, algo_def=algo_def, seed=seed).run(
            cycles=12, chunk=12)
        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            sp = mod.build_solver(dcop, algo_def=algo_def, seed=seed)
        rp = sp.run(cycles=12, chunk=12)
        assert rg.assignment == rp.assignment

    def test_matches_on_scalefree_hub(self):
        """Hub-split columns must pair correctly (offer picks can land
        on any sub-column; commits/arbitration combine across them).

        NEAR-parity, not bit-parity (triaged round 7): the fused kernel
        factors the joint table as ``A_i[du] + (A_j[dw] + M[du, dw])``
        while the generic solver computes ``(A_i + A_j) + M`` — a f32
        reassociation worth up to ~1.5e-5 per joint gain, 4 orders of
        magnitude above the protocol's 1e-9 tie epsilon.  On this
        instance a 170-degree hub sprays offers every cycle, so
        knife-edge ``jg vs own_gain`` comparisons (measured: margins at
        the 1e-7 level) occasionally flip a commit between the two
        engines; both runs are valid MGM-2 executions and agreement
        stays >95% of variables (measured 5/300 flips after 8 cycles).
        Exact parity would need the kernel to reproduce the generic
        association inside the lane layout — tracked as a known gap;
        the low-degree instances above remain bit-exact."""
        import numpy as np

        from tests.unit.test_hub_packing import TestHubLocalSearch

        dcop = TestHubLocalSearch()._dcop(V=300, seed=9)
        rg = _solver(dcop, False).run(cycles=8, chunk=8)
        sp = _solver(dcop, True)
        assert sp.packed.hub_nsteps > 0
        rp = sp.run(cycles=8, chunk=8)
        vals_g = np.array(list(rg.assignment.values()))
        vals_p = np.array([rp.assignment[k] for k in rg.assignment])
        agree = float((vals_g == vals_p).mean())
        assert agree >= 0.95, f"only {agree:.1%} of variables agree"
        # both engines descend to the same cost level
        assert rp.cost <= rg.cost * 1.05 + 1.0

    def test_improves_cost(self):
        dcop = _coloring_dcop(V=60, E=150, seed=7)
        s = _solver(dcop, True)
        r0 = s.run(cycles=1, chunk=1)
        r = s.run(cycles=30, chunk=30)
        assert r.cost <= r0.cost
