"""UiServer websocket protocol ≡ reference ui.py command/event shapes."""
import base64
import hashlib
import json
import os
import socket
import time

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.events import event_bus
from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator
from pydcop_tpu.runtime.ui import UiServer
from pydcop_tpu.runtime.ws import OP_TEXT, encode_frame, read_frame

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class WsClient:
    """Stdlib test client: handshake + masked text frames."""

    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            f"GET / HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
            f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n".encode()
        )
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += self.sock.recv(4096)
        assert b"101" in resp.split(b"\r\n", 1)[0]
        expect = base64.b64encode(hashlib.sha1(
            (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
        ).digest())
        assert expect in resp

    def send_json(self, obj):
        self.sock.sendall(
            encode_frame(json.dumps(obj).encode(), OP_TEXT, mask=True)
        )

    def recv_json(self, timeout=5):
        self.sock.settimeout(timeout)
        opcode, payload = read_frame(self.sock)
        assert opcode == OP_TEXT, opcode
        return json.loads(payload.decode())

    def close(self):
        self.sock.close()


@pytest.fixture
def served_orchestrator():
    dcop = load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml"))
    orch = VirtualOrchestrator(dcop, "maxsum", distribution="adhoc")
    orch.deploy_computations()
    ui = UiServer(port=free_port(), ws_port=free_port(),
                  orchestrator=orch)
    ui.start()
    time.sleep(0.1)
    yield orch, ui
    ui.stop()


def test_cmd_test_agent_computations(served_orchestrator):
    orch, ui = served_orchestrator
    orch.run(cycles=5)
    ui.update_state(**orch.end_metrics())
    c = WsClient(ui.ws_port)
    try:
        # cmd: test → broadcast {"cmd": "test", "data": "foo"}
        c.send_json({"cmd": "test"})
        assert c.recv_json() == {"cmd": "test", "data": "foo"}

        # cmd: agent → the reference's agent payload shape
        c.send_json({"cmd": "agent"})
        msg = c.recv_json()
        assert msg["cmd"] == "agent"
        agent = msg["agent"]
        assert agent["is_orchestrator"] is True
        for key in ("name", "computations", "replicas", "address"):
            assert key in agent

        # cmd: computations → one payload per graph node, reference keys
        c.send_json({"cmd": "computations"})
        msg = c.recv_json()
        comps = {m["name"]: m for m in msg["computations"]}
        assert set(comps) == {n.name for n in orch.cg.nodes}
        v1 = comps["v1"]
        for key in ("id", "type", "value", "neighbors", "algo",
                    "msg_count", "msg_size", "cycles", "footprint"):
            assert key in v1
        assert v1["type"] == "variable"
        assert v1["value"] == "G"  # tuto optimum
        assert v1["algo"]["name"] == "maxsum"
        assert comps["c_1_2"]["type"] == "factor"
    finally:
        c.close()


def _wait_clients(ui, n, deadline=5.0):
    """The client's handshake completes before the server registers it
    in its client list — wait for registration before broadcasting."""
    t0 = time.time()
    while ui._ws.n_clients < n:
        if time.time() - t0 > deadline:
            raise AssertionError("ws client not registered in time")
        time.sleep(0.01)


def test_events_are_pushed(served_orchestrator):
    orch, ui = served_orchestrator
    c = WsClient(ui.ws_port)
    try:
        _wait_clients(ui, 1)
        was_enabled = event_bus.enabled
        event_bus.enabled = True
        try:
            event_bus.send("computations.value.v1", "R")
        finally:
            event_bus.enabled = was_enabled
        msg = c.recv_json()
        assert msg == {"evt": "value", "computation": "v1", "value": "R"}
    finally:
        c.close()


@pytest.mark.parametrize("topic,evt_name,payload", [
    ("serve.job.submitted", "serve",
     {"jid": "job-000001", "tenant": "t1", "priority": 2,
      "algo": "mgm"}),
    ("serve.job.admitted", "serve",
     {"jid": "job-000001", "lane": 1, "midflight": True,
      "resumed": False}),
    ("serve.job.progress", "serve",
     {"jid": "job-000001", "cycle": 14, "cost": 3.0}),
    ("serve.job.done", "serve",
     {"jid": "job-000001", "status": "FINISHED", "cycle": 21,
      "cost": 12.0, "latency": 0.4}),
    ("serve.bucket.opened", "serve",
     {"algo": "mgm", "lanes": 4, "warm": True}),
    ("fleet.replica.up", "fleet", {"name": "replica-0"}),
    ("fleet.replica.down", "fleet",
     {"name": "replica-1", "reason": "injected kill"}),
    ("fleet.replica.stalled", "fleet", {"name": "replica-0"}),
    ("fleet.replica.healed", "fleet",
     {"name": "replica-0", "was": "stalled"}),
    ("fleet.router.placed", "fleet",
     {"jid": "job-000003", "replica": "replica-1",
      "key": ["mgm", "()", "constraints_hypergraph", "(2,)"],
      "warm": True}),
    ("fleet.job.reseated", "fleet",
     {"jid": "job-000002", "from": "replica-0", "to": "replica-1",
      "checkpoint": True}),
    ("fleet.recovery.done", "fleet",
     {"replica": "replica-0", "jobs": 3, "rto_s": 0.42}),
    ("slo.tier.breach", "slo",
     {"tier": "silver", "attainment": 0.75, "floor": 0.9}),
    ("slo.ladder.escalated", "slo",
     {"rung": 1, "rung_name": "shed_bronze", "tiers": ["silver"]}),
    ("slo.ladder.released", "slo",
     {"rung": 0, "rung_name": "normal"}),
    ("slo.shed.bronze", "slo", {"label": "coloring:bronze:7"}),
    ("slo.clamp.silver", "slo",
     {"pressure": 0.5, "exempt_priority": 2}),
    ("slo.reroute.gold", "slo", {"label": "routing:gold:4"}),
    ("slo.scorecard", "slo",
     {"tiers": {"gold": {"attainment": 1.0, "p99_ms": 412.0}},
      "shed_rate": 0.1, "rto_max_s": 0.03}),
    ("batch.bucket.formed", "batch", {"algo": "mgm", "size": 3}),
    ("harness.run.done", "harness", {"algo": "mgm", "cycle": 21}),
    ("dpop.shard.plan", "dpop",
     {"engine": "sharded", "n_shards": 8, "levels": 5,
      "bytes_per_device": 4096, "wire_bytes_pruned": 512,
      "wire_bytes_dense": 640, "pruned_fraction": 0.2}),
    ("dpop.minibucket.bounds", "dpop",
     {"i_bound": 3, "lower_bound": 10.0, "upper_bound": 14.0,
      "gap": 4.0}),
    ("repair.mutation.applied", "repair",
     {"kind": "edit_factor", "target": "c12", "mutations": 1,
      "free_var_slots": 3}),
    ("repair.repack", "repair",
     {"reason": "no free variable slot", "capacity_vars": 12}),
    ("repair.recovered", "repair",
     {"time_to_recover_s": 0.04, "cycle": 21, "cost": 3.0}),
    ("portfolio.dataset.progress", "portfolio",
     {"key": "graphcoloring/s6/seed0::mgm|harness|c0|default|t0.5|b0|i0",
      "status": "FINISHED", "done": 3, "skipped": 1,
      "wall_s": 0.4}),
    ("portfolio.model.loaded", "portfolio",
     {"path": "/tmp/model.npz", "n_in": 39,
      "meta": {"version": 1, "probe_rate": 120.0}}),
    ("portfolio.config.selected", "portfolio",
     {"config": {"algo": "mgm", "engine": "harness", "chunk": 0},
      "fallback": False, "predicted_norm_time": 12.5,
      "n_feasible": 9, "n_masked": 1}),
    ("portfolio.solve.done", "portfolio",
     {"config": {"algo": "dpop", "engine": "auto"},
      "fallback": True, "status": "FINISHED",
      "actual_solve_s": 0.8,
      "predicted_time_to_target_s": None}),
])
def test_lifecycle_topics_forwarded(served_orchestrator, topic,
                                    evt_name, payload):
    """The serve.* lifecycle topics — the streaming front door's
    events — must reach ws clients in the same envelope shape as the
    established batch.*/harness.* forwarding (pinned here alongside
    them): {"evt": <family>, "kind": <topic tail>, "data": payload}."""
    _, ui = served_orchestrator
    c = WsClient(ui.ws_port)
    try:
        _wait_clients(ui, 1)
        was_enabled = event_bus.enabled
        event_bus.enabled = True
        try:
            event_bus.send(topic, payload)
        finally:
            event_bus.enabled = was_enabled
        msg = c.recv_json()
        assert msg == {
            "evt": evt_name,
            "kind": topic.split(".", 1)[-1],
            "data": payload,
        }
    finally:
        c.close()


def test_serve_events_forwarded_from_real_service(served_orchestrator):
    """End to end: an actual SolveService run pushes its serve.*
    lifecycle over the websocket — submitted, admitted, done."""
    from pydcop_tpu.batch.cache import CompileCache
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.serve import SolveService

    _, ui = served_orchestrator
    dcop = load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml"))
    c = WsClient(ui.ws_port)
    try:
        _wait_clients(ui, 1)
        was_enabled = event_bus.enabled
        event_bus.enabled = True
        try:
            svc = SolveService(lanes=1, cache=CompileCache(),
                               max_cycles=63)
            jid = svc.submit(dcop, "mgm", seed=0)
            for _ in range(60):
                if not svc.tick():
                    break
            assert svc.result(jid, timeout=1).status == "FINISHED"
        finally:
            event_bus.enabled = was_enabled
        kinds = []
        while "job.done" not in kinds:
            msg = c.recv_json()
            # the service's compile-cache activity rides batch.* on
            # the same channel; only the serve.* envelope is under test
            if msg.get("evt") != "serve":
                continue
            kinds.append(msg["kind"])
        assert "job.submitted" in kinds
        assert "job.admitted" in kinds
        assert "bucket.opened" in kinds
    finally:
        c.close()


def test_serve_events_on_sse_stream(served_orchestrator):
    """The HTTP /events SSE endpoint carries serve.* topics through
    the wildcard subscription (no websocket client needed)."""
    import http.client

    _, ui = served_orchestrator
    conn = http.client.HTTPConnection("127.0.0.1", ui.port, timeout=5)
    conn.request("GET", "/events")
    resp = conn.getresponse()
    assert resp.status == 200
    time.sleep(0.1)  # subscriber registration
    was_enabled = event_bus.enabled
    event_bus.enabled = True
    try:
        event_bus.send("serve.job.done", {"jid": "j1",
                                          "status": "FINISHED"})
    finally:
        event_bus.enabled = was_enabled
    line = resp.fp.readline().decode()
    assert line.startswith("data: ")
    body = json.loads(line[6:])
    assert body["topic"] == "serve.job.done"
    conn.close()


def test_close_message_on_stop(served_orchestrator):
    _, ui = served_orchestrator
    c = WsClient(ui.ws_port)
    _wait_clients(ui, 1)
    ui.stop()
    msg = c.recv_json()
    assert msg == {"cmd": "close"}
    c.close()


def test_ping_pong(served_orchestrator):
    from pydcop_tpu.runtime.ws import OP_PING, OP_PONG

    _, ui = served_orchestrator
    c = WsClient(ui.ws_port)
    try:
        c.sock.sendall(encode_frame(b"hb", OP_PING, mask=True))
        opcode, payload = read_frame(c.sock)
        assert opcode == OP_PONG and payload == b"hb"
    finally:
        c.close()


def test_bad_messages_do_not_kill_connection(served_orchestrator):
    """Non-object JSON and garbage must not disconnect the client
    (one malformed GUI message would otherwise drop the session)."""
    _, ui = served_orchestrator
    c = WsClient(ui.ws_port)
    try:
        _wait_clients(ui, 1)
        for bad in ('[1]', '"hello"', "not json"):
            c.sock.sendall(encode_frame(bad.encode(), OP_TEXT, mask=True))
        c.send_json({"cmd": "test"})
        assert c.recv_json() == {"cmd": "test", "data": "foo"}
    finally:
        c.close()


def test_pipelined_first_frame_not_lost(served_orchestrator):
    """A frame sent back-to-back with the HTTP upgrade (TCP coalescing)
    must still be processed (handshake leftover buffering)."""
    import base64 as b64

    _, ui = served_orchestrator
    sock = socket.create_connection(("127.0.0.1", ui.ws_port), timeout=5)
    key = b64.b64encode(os.urandom(16)).decode()
    frame = encode_frame(json.dumps({"cmd": "test"}).encode(),
                         OP_TEXT, mask=True)
    sock.sendall(
        f"GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
        f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
        f"Sec-WebSocket-Version: 13\r\n\r\n".encode() + frame
    )
    resp = b""
    while b"\r\n\r\n" not in resp:
        resp += sock.recv(4096)
    leftover = resp.split(b"\r\n\r\n", 1)[1]

    class _Rdr:
        def __init__(self):
            self.buf = leftover

        def recv(self, n):
            if self.buf:
                out, self.buf = self.buf[:n], self.buf[n:]
                return out
            return sock.recv(n)

    sock.settimeout(5)
    opcode, payload = read_frame(_Rdr())
    assert opcode == OP_TEXT
    assert json.loads(payload) == {"cmd": "test", "data": "foo"}
    sock.close()


def test_oversized_frame_is_refused(served_orchestrator):
    """A client-claimed multi-GB payload closes the connection instead
    of allocating unbounded memory."""
    import struct

    _, ui = served_orchestrator
    c = WsClient(ui.ws_port)
    _wait_clients(ui, 1)
    # header claiming 2^40 bytes, masked
    c.sock.sendall(bytes([0x81, 0x80 | 127]) + struct.pack(">Q", 1 << 40))
    t0 = time.time()
    while ui._ws.n_clients > 0 and time.time() - t0 < 5:
        time.sleep(0.05)
    assert ui._ws.n_clients == 0
    c.close()
