"""Batched multi-instance solve engine (pydcop_tpu.batch).

Three contracts pinned here:

* the bucketing policy (pure host arithmetic, no device),
* per-algorithm BIT-IDENTITY of BatchEngine results vs sequential
  single-instance solves on mixed-shape instance sets — including
  instances that only share a bucket through padding,
* exactly one compile per (bucket, algo) pair, via the compile cache's
  hit/miss counters.
"""
import os

import numpy as np
import pytest

from pydcop_tpu.batch import (
    BatchEngine,
    BatchItem,
    InstanceDims,
    plan_buckets,
)
from pydcop_tpu.batch.bucketing import bucket_waste, padded_target
from pydcop_tpu.batch.cache import CompileCache
from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.run import solve_result

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


def _load(name):
    return load_dcop_from_file([os.path.join(INSTANCES, name)])


def _dims(graph="constraints_hypergraph", D=3, arities=(2,), V=10,
          F=(20,), M=40):
    return InstanceDims(graph_type=graph, D=D, arities=arities, V=V,
                        F=F, M=M)


class TestBucketingPolicy:
    def test_identical_shapes_one_bucket_no_padding(self):
        dims = [_dims() for _ in range(5)]
        plans = plan_buckets(dims, max_waste=0.25)
        assert len(plans) == 1
        assert plans[0].batch_size == 5
        assert plans[0].waste == 0.0
        # no padding → no dummy variable slot
        assert plans[0].target.V == 10

    def test_arity_sets_never_mix(self):
        plans = plan_buckets(
            [_dims(arities=(2,), F=(20,)),
             _dims(arities=(1, 2), F=(5, 20,))],
            max_waste=1.0,
        )
        assert len(plans) == 2

    def test_graph_families_never_mix(self):
        plans = plan_buckets(
            [_dims(), _dims(graph="factor_graph", M=0)], max_waste=1.0
        )
        assert len(plans) == 2

    def test_waste_bound_splits(self):
        big = _dims(V=100, F=(300,), M=600)
        small = _dims(V=4, F=(4,), M=8)
        # together the small instance is nearly all padding
        assert bucket_waste([big, small]) > 0.4
        plans = plan_buckets([big, small], max_waste=0.25)
        assert len(plans) == 2
        # ... but a permissive bound merges them
        plans = plan_buckets([big, small], max_waste=0.9)
        assert len(plans) == 1

    def test_padding_reserves_dummy_slot(self):
        a = _dims(V=10, F=(20,), M=40)
        b = _dims(V=10, F=(18,), M=36)
        target = padded_target([a, b])
        # factor padding needs a dummy variable to route to
        assert target.V == 11
        assert target.F == (20,)
        assert target.M == 40

    def test_plan_is_deterministic_and_size_sorted(self):
        dims = [_dims(V=v, F=(v * 2,), M=v * 4) for v in (4, 50, 4, 50)]
        p1 = plan_buckets(dims, max_waste=0.25)
        p2 = plan_buckets(list(dims), max_waste=0.25)
        assert [p.indices for p in p1] == [p.indices for p in p2]
        # big instances are packed first
        assert p1[0].indices == [1, 3]
        assert p1[1].indices == [0, 2]


FILES = ["graph_coloring_tuto.yaml", "coloring_csp.yaml",
         "coloring_intention.yaml", "ising_grid.yaml"]

ALGO_CASES = [
    ("maxsum", None),
    ("mgm", None),
    ("dsa", None),
    ("dsa", {"variant": "C", "probability": 0.8}),
    ("adsa", None),
    ("gdba", None),
    ("gdba", {"modifier": "M", "violation": "NM", "increase_mode": "R"}),
]


class TestBitMatch:
    """BatchEngine results vs sequential solver.run, bit for bit."""

    @pytest.fixture(scope="class")
    def dcops(self):
        return {f: _load(f) for f in FILES}

    @pytest.mark.parametrize(
        "algo,params", ALGO_CASES,
        ids=[f"{a}-{i}" for i, (a, _p) in enumerate(ALGO_CASES)],
    )
    def test_fixed_cycles_bit_identical(self, dcops, algo, params):
        # waste bound 0.9 forces mixed-shape instances into shared
        # padded buckets — the padding-inertness contract under test
        items = [
            BatchItem(dcops[f], algo, algo_params=params, seed=s,
                      label=f"{f}:{s}")
            for f in FILES for s in (0, 1)
        ]
        engine = BatchEngine(cache=CompileCache(), max_padding_waste=0.9)
        results = engine.solve(items, cycles=21)
        assert engine.counters.counts["buckets_formed"] >= 2
        for item, res in zip(items, results):
            seq = solve_result(item.dcop, algo, cycles=21,
                               algo_params=params, seed=item.seed)
            assert res.assignment == seq.assignment, item.label
            assert res.cost == seq.cost, item.label
            assert res.cycle == seq.cycle
            assert res.msg_count == seq.msg_count
            assert res.status == "FINISHED"

    def test_convergence_mode_bit_identical(self, dcops):
        # cycles=None: per-instance convergence masks + freeze must
        # reproduce the sequential harness's stop states AND stop cycles
        items = [
            BatchItem(dcops[f], "mgm", seed=s, label=f"{f}:{s}")
            for f in FILES[:3] for s in (0, 1)
        ]
        engine = BatchEngine(cache=CompileCache(), max_padding_waste=0.9)
        results = engine.solve(items)
        assert engine.counters.counts["instances_converged"] == len(items)
        for item, res in zip(items, results):
            seq = solve_result(item.dcop, "mgm", seed=item.seed)
            assert res.assignment == seq.assignment, item.label
            assert res.cycle == seq.cycle, item.label


class TestCompileCache:
    def test_one_compile_per_bucket_algo_pair(self):
        """Acceptance pin: a mixed set of ≥8 instances from ≥2 shape
        buckets solves with EXACTLY one compile per (bucket, algo)
        pair, and a repeat sweep is all cache hits."""
        dcops = {f: _load(f) for f in FILES}
        items = [
            BatchItem(dcops[f], "mgm", seed=s, label=f"{f}:{s}")
            for f in FILES for s in (0, 1)
        ]
        assert len(items) >= 8
        cache = CompileCache()
        engine = BatchEngine(cache=cache)
        engine.solve(items, cycles=20)  # 20 ≤ 100 → a single chunk
        n_buckets = engine.counters.counts["buckets_formed"]
        assert n_buckets >= 2
        assert cache.misses == n_buckets
        assert cache.hits == 0

        # second sweep over the same shapes: zero new compiles
        engine2 = BatchEngine(cache=cache)
        engine2.solve(items, cycles=20)
        assert cache.misses == n_buckets
        assert cache.hits == n_buckets

    def test_cache_key_covers_params(self):
        dcop = _load("coloring_csp.yaml")
        cache = CompileCache()
        engine = BatchEngine(cache=cache)
        engine.solve([BatchItem(dcop, "dsa", seed=0)], cycles=10)
        engine.solve(
            [BatchItem(dcop, "dsa", algo_params={"variant": "C"},
                       seed=0)],
            cycles=10,
        )
        # same shapes, different move rule → different compiled runner
        assert cache.misses == 2

    def test_persistent_cache_dir_enabled(self, tmp_path):
        import jax

        # enable_persistent_cache flips PROCESS-GLOBAL jax config; a
        # leaked cache dir makes every later compile in this pytest
        # process pay persistent-cache writes (measured 3-4x per
        # pallas-interpret test) — restore all three knobs
        saved = {
            k: getattr(jax.config, k)
            for k in ("jax_compilation_cache_dir",
                      "jax_persistent_cache_min_entry_size_bytes",
                      "jax_persistent_cache_min_compile_time_secs")
        }
        try:
            engine = BatchEngine(
                cache=CompileCache(),
                persistent_cache_dir=str(tmp_path / "xla"),
            )
            assert engine.persistent_cache_enabled
            engine.solve(
                [BatchItem(_load("coloring_csp.yaml"), "mgm", seed=0)],
                cycles=10,
            )
        finally:
            for k, v in saved.items():
                jax.config.update(k, v)


class TestEventsAndCounters:
    def test_batch_events_emitted(self):
        from pydcop_tpu.runtime.events import event_bus

        seen = []
        cb = lambda topic, evt: seen.append((topic, evt))  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("batch.*", cb)
        try:
            dcops = [_load(f) for f in FILES[:2]]
            engine = BatchEngine(cache=CompileCache())
            engine.solve(
                [BatchItem(d, "mgm", seed=0) for d in dcops], cycles=10
            )
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        topics = [t for t, _ in seen]
        assert any(t == "batch.bucket.formed" for t in topics)
        assert any(t == "batch.compile.miss" for t in topics)
        assert any(t == "batch.run.done" for t in topics)

    def test_converged_event_and_counter(self):
        from pydcop_tpu.runtime.events import event_bus

        seen = []
        cb = lambda topic, evt: seen.append((topic, evt))  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("batch.instance.converged", cb)
        try:
            engine = BatchEngine(cache=CompileCache())
            engine.solve(
                [BatchItem(_load("graph_coloring_tuto.yaml"), "mgm",
                           seed=0, label="tuto")],
            )
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        assert engine.counters.counts["instances_converged"] == 1
        assert seen and seen[0][1]["label"] == "tuto"
        assert engine.metrics()["cache"]["misses"] >= 1

    def test_on_lane_release_hook(self):
        """The per-lane slot-release hook (the serve scheduler's feed):
        fires once per converging instance with the lane index, the
        stop cycle the [B] mask would only reveal in aggregate, and the
        lane's final state (device-sliced, values readable)."""
        import numpy as np

        dcops = [_load(f) for f in FILES[:2]]
        released = []

        def hook(lane, stop_cycle, final_state):
            released.append(
                (lane, stop_cycle, np.asarray(final_state[0]))
            )

        engine = BatchEngine(cache=CompileCache(), max_padding_waste=0.9)
        results = engine.solve(
            [BatchItem(d, "mgm", seed=0) for d in dcops],
            on_lane_release=hook,
        )
        assert len(released) == len(dcops)
        # lanes are bucket-internal (size-sorted) indices, one each
        assert sorted(lane for lane, _c, _s in released) == [0, 1]
        # each release reports a stop cycle matching some result's, and
        # the lane's final state row (device-sliced, values readable)
        assert (sorted(c for _l, c, _s in released)
                == sorted(r.cycle for r in results))
        for _lane, _c, state in released:
            assert state.ndim == 1

    def test_fallback_sequential_counted(self):
        engine = BatchEngine(cache=CompileCache())
        res = engine.solve(
            [BatchItem(_load("graph_coloring_tuto.yaml"), "dpop")],
        )
        assert res[0].cost == 12
        assert engine.counters.counts["fallback_sequential"] == 1


class TestPaddingInertness:
    def test_padded_instance_values_match_unpadded(self):
        """Direct pin of the routing argument: solving an instance
        alone (no padding) and inside a padded mixed bucket yields the
        same bits."""
        dcops = {f: _load(f) for f in FILES[:2]}
        alone = BatchEngine(cache=CompileCache()).solve(
            [BatchItem(dcops[FILES[1]], "mgm", seed=3)], cycles=15
        )[0]
        mixed_items = [
            BatchItem(dcops[FILES[0]], "mgm", seed=3),
            BatchItem(dcops[FILES[1]], "mgm", seed=3),
        ]
        engine = BatchEngine(cache=CompileCache(), max_padding_waste=0.9)
        mixed = engine.solve(mixed_items, cycles=15)
        assert engine.counters.counts["buckets_formed"] == 1
        m = engine.metrics()
        assert m["padding_waste"] > 0.0
        assert mixed[1].assignment == alone.assignment
        assert mixed[1].cost == alone.cost

    def test_uniform_prestream_matches_generic(self):
        """The pre-drawn per-chunk uniforms reproduce the solver's
        per-cycle draws (vmap-of-uniform == stacked uniforms)."""
        import jax

        from pydcop_tpu.batch.engine import _dsa_chunk_uniforms

        key = jax.random.PRNGKey(5)
        key2, u = _dsa_chunk_uniforms(key, n=4, V=6, Vp=8)
        k_ref, sub = jax.random.split(jax.random.PRNGKey(5))
        ks = jax.random.split(sub, 4)
        for i in range(4):
            ref = jax.random.uniform(ks[i], (6,))
            assert np.array_equal(np.asarray(u[i, :6]), np.asarray(ref))
        assert np.all(np.asarray(u[:, 6:]) == 1.0)
        assert np.array_equal(np.asarray(key2), np.asarray(k_ref))
