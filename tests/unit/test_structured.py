"""Table-free structured constraints (ISSUE 17).

The IR (dcop/structured.py) compiles linear / cardinality / resource
rules into closed-form kernels (ops/structured_kernels.py) instead of
D^arity cost tables.  These tests pin:

* IR semantics — exact lowering, the densify guard, params round-trip,
  structure detection, slicing;
* kernel/solver parity with the densified twin (maxsum, MGM, frontier,
  DPOP; min AND max mode) wherever a twin fits in memory;
* the headline capability — 100-arity constraints solving end-to-end
  (maxsum and frontier) with device bytes independent of arity;
* the guards that refuse silent densification (mesh shard, batch
  bucketing, weighted local tables, PAD pin).
"""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.dcop.structured import (
    CardinalityConstraint,
    DensifyError,
    LinearConstraint,
    MAX_DENSIFY_ENTRIES,
    ResourceConstraint,
    StructuredConstraint,
    detect_structure,
    structured_from_params,
)


def _vars(n, D, prefix="v"):
    dom = Domain("d", "v", list(range(D)))
    return [Variable(f"{prefix}{i:03d}", dom) for i in range(n)]


def _dcop(vs, constraints, objective="min"):
    d = DCOP("t", objective=objective)
    for v in vs:
        d.add_variable(v)
    for c in constraints:
        d.add_constraint(c)
    d.add_agents([AgentDef("a0")])
    return d


def _resource(name, vs, seed=0, cap=None, penalty=7.0):
    """Small resource rule: random prefs + quadratic overload curve."""
    rng = np.random.default_rng(seed)
    D = len(vs[0].domain)
    k = len(vs)
    cap = cap if cap is not None else max(1, k // D)
    pref = rng.integers(0, 9, (k, D)).astype(float)
    counts = np.arange(k + 1, dtype=float)
    curve = penalty * np.maximum(0.0, counts - cap) ** 2
    return ResourceConstraint(
        name, vs, pref, list(range(D)), np.tile(curve[None, :], (D, 1))
    )


def _assignments(vs, n_samples, seed=5):
    rng = np.random.default_rng(seed)
    D = len(vs[0].domain)
    for _ in range(n_samples):
        yield {v.name: int(rng.integers(0, D)) for v in vs}


# ---------------------------------------------------------------------------
# IR semantics
# ---------------------------------------------------------------------------


class TestIRSemantics:
    def test_linear_value_and_identity_lowering(self):
        vs = _vars(3, 4)
        rows = [[1.0, 2.0, 3.0, 4.0], [0.0, 5.0, 0.0, 5.0],
                [9.0, 0.0, 1.0, 2.0]]
        c = LinearConstraint("lin", vs, rows, bias=2.5)
        a = {vs[0].name: 2, vs[1].name: 3, vs[2].name: 0}
        assert c(**a) == pytest.approx(2.5 + 3.0 + 5.0 + 9.0)
        assert c.lower() == [c]

    def test_cardinality_counts_and_missing_value(self):
        dom_a = Domain("da", "v", [0, 1, 2])
        dom_b = Domain("db", "v", [1, 2])  # lacks the counted value 0
        va = Variable("a", dom_a)
        vb = Variable("b", dom_b)
        c = CardinalityConstraint(
            "card", [va, vb], 0, [0.0, 10.0, 40.0])
        assert list(c.counted_indices()) == [0, -1]
        assert c(a=0, b=1) == pytest.approx(10.0)
        assert c(a=1, b=2) == pytest.approx(0.0)

    def test_resource_lowering_is_exact(self):
        vs = _vars(6, 3)
        c = _resource("win", vs, seed=3)
        prims = c.lower()
        assert all(
            isinstance(p, (LinearConstraint, CardinalityConstraint))
            for p in prims
        )
        for a in _assignments(vs, 25):
            whole = c(**a)
            parts = sum(p(**{v.name: a[v.name] for v in p.dimensions})
                        for p in prims)
            assert parts == pytest.approx(whole, abs=1e-9)

    def test_all_different_counts_clashing_pairs(self):
        vs = _vars(5, 4)
        c = ResourceConstraint.all_different("ad", vs, penalty=3.0)
        for a in _assignments(vs, 25, seed=1):
            vals = [a[v.name] for v in vs]
            clashes = sum(
                1
                for i in range(len(vals))
                for j in range(i + 1, len(vals))
                if vals[i] == vals[j]
            )
            assert c(**a) == pytest.approx(3.0 * clashes)

    def test_densify_guard_fires_above_budget(self):
        vs = _vars(100, 4)
        c = _resource("wide", vs, seed=0)
        assert c.dense_entries() > MAX_DENSIFY_ENTRIES
        # dense_bytes is a float on purpose: 4**100 overflows int64
        assert c.dense_bytes() > float(2**63)
        with pytest.raises(DensifyError):
            c.to_tensor()
        with pytest.raises(DensifyError):
            c.densified()

    def test_params_round_trip_every_class(self):
        vs = _vars(4, 3)
        originals = [
            LinearConstraint("l", vs, np.eye(4, 3).tolist(), 1.5),
            CardinalityConstraint("c", vs, 1, [0.0, 0.0, 5.0, 9.0, 20.0]),
            _resource("r", vs, seed=2),
        ]
        for c in originals:
            p = c.params()
            back = structured_from_params(c.name, vs, p)
            assert type(back) is type(c)
            for a in _assignments(vs, 10, seed=7):
                assert back(**a) == pytest.approx(c(**a))

    def test_detect_structure_recovers_separable_tables(self):
        vs = _vars(3, 3)
        lin = LinearConstraint(
            "sep", vs, [[1.0, 4.0, 2.0]] * 3, bias=0.5)
        dense = lin.densified()
        rec = detect_structure(dense)
        assert isinstance(rec, LinearConstraint)
        for a in _assignments(vs, 15, seed=2):
            assert rec(**a) == pytest.approx(lin(**a))
        # a genuinely coupled table must NOT be misdetected
        xor_like = NAryMatrixRelation(
            vs[:2], np.array([[0.0, 1, 1], [1, 0, 1], [1, 1, 0]]), "x")
        assert detect_structure(xor_like) is None

    def test_slice_matches_densified_slice(self):
        vs = _vars(4, 3)
        c = _resource("win", vs, seed=4)
        part = {vs[0].name: 2, vs[3].name: 1}
        sliced = c.slice(part)
        assert set(sliced.scope_names) == {vs[1].name, vs[2].name}
        for a in _assignments(vs[1:3], 9, seed=3):
            assert sliced(**a) == pytest.approx(c(**{**a, **part}))


# ---------------------------------------------------------------------------
# compiled parity with the densified twin
# ---------------------------------------------------------------------------


def _twin_dcops(objective="min", seed=0):
    """Same instance twice: structured resource rule + dense binaries
    vs the byte-identical fully-densified version."""
    vs = _vars(5, 3)
    rng = np.random.default_rng(seed)
    res = _resource("win", vs, seed=seed + 1)
    binaries = [
        NAryMatrixRelation(
            [vs[i], vs[i + 1]],
            rng.integers(0, 13, (3, 3)).astype(float),
            name=f"b{i}",
        )
        for i in range(4)
    ]
    structured = _dcop(vs, [res] + binaries, objective)
    dense = _dcop(vs, [res.densified()] + binaries, objective)
    return structured, dense, vs


class TestCompiledParity:
    @pytest.mark.parametrize("objective", ["min", "max"])
    def test_total_cost_matches_densified(self, objective):
        from pydcop_tpu.ops.compile import compile_factor_graph, total_cost

        sd, dd, vs = _twin_dcops(objective)
        ts, td = compile_factor_graph(sd), compile_factor_graph(dd)
        assert ts.sbuckets and not td.sbuckets
        rng = np.random.default_rng(8)
        for _ in range(20):
            x = jnp.asarray(rng.integers(0, 3, len(vs)), jnp.int32)
            a = float(total_cost(ts, x))
            b = float(total_cost(td, x))
            assert a == pytest.approx(b, abs=1e-4)

    @pytest.mark.parametrize("objective", ["min", "max"])
    def test_local_tables_match_densified(self, objective):
        from pydcop_tpu.ops.compile import (
            compile_constraint_graph,
            local_cost_tables,
        )

        sd, dd, vs = _twin_dcops(objective)
        ts = compile_constraint_graph(sd)
        td = compile_constraint_graph(dd)
        rng = np.random.default_rng(9)
        for _ in range(5):
            x = jnp.asarray(rng.integers(0, 3, len(vs)), jnp.int32)
            a = np.asarray(local_cost_tables(ts, x))
            b = np.asarray(local_cost_tables(td, x))
            assert np.allclose(a, b, atol=1e-4)

    @pytest.mark.parametrize("objective", ["min", "max"])
    def test_maxsum_trajectory_matches_densified(self, objective):
        from pydcop_tpu.algorithms.maxsum import MaxSumSolver, algo_params
        from pydcop_tpu.ops.compile import compile_factor_graph

        # identical topology required for message-level parity: ONE
        # factor (the resource rule) vs its own dense table
        vs = _vars(5, 3)
        res = _resource("win", vs, seed=11)
        sd = _dcop(vs, [res], objective)
        dd = _dcop(vs, [res.densified()], objective)
        algo = AlgorithmDef.build_with_default_params(
            "maxsum", {}, mode=objective,
            parameters_definitions=algo_params)
        rs = MaxSumSolver(sd, compile_factor_graph(sd), algo,
                          seed=3).run(cycles=15)
        rd = MaxSumSolver(dd, compile_factor_graph(dd), algo, seed=3,
                          use_packed=False).run(cycles=15)
        assert rs.assignment == rd.assignment
        assert rs.cost == pytest.approx(rd.cost, abs=1e-4)

    @pytest.mark.parametrize("objective", ["min", "max"])
    def test_mgm_trajectory_matches_densified(self, objective):
        from pydcop_tpu.algorithms.mgm import MgmSolver, algo_params
        from pydcop_tpu.ops.compile import compile_constraint_graph

        sd, dd, _ = _twin_dcops(objective, seed=6)
        algo = AlgorithmDef.build_with_default_params(
            "mgm", {}, mode=objective,
            parameters_definitions=algo_params)
        rs = MgmSolver(sd, compile_constraint_graph(sd), algo,
                       seed=4).run(cycles=20)
        rd = MgmSolver(dd, compile_constraint_graph(dd), algo, seed=4,
                       use_packed=False).run(cycles=20)
        assert rs.assignment == rd.assignment
        assert rs.cost == pytest.approx(rd.cost, abs=1e-4)

    def test_frontier_optimum_matches_densified(self):
        from pydcop_tpu.search.solver import FrontierSearchSolver

        sd, dd, _ = _twin_dcops("min", seed=13)
        rs = FrontierSearchSolver(sd, frontier_width=64).run()
        rd = FrontierSearchSolver(dd, frontier_width=64).run()
        assert rs.search["optimal"] and rd.search["optimal"]
        assert rs.cost == pytest.approx(rd.cost, abs=1e-4)

    @pytest.mark.parametrize("objective", ["min", "max"])
    def test_dpop_matches_densified(self, objective):
        from pydcop_tpu.algorithms.dpop import DpopSolver

        sd, dd, _ = _twin_dcops(objective, seed=17)
        rs = DpopSolver(sd).run()
        rd = DpopSolver(dd).run()
        assert rs.cost == pytest.approx(rd.cost, abs=1e-4)


# ---------------------------------------------------------------------------
# DPOP structured routing
# ---------------------------------------------------------------------------


class TestDpopStructured:
    def test_wide_separable_projects_symbolically(self):
        """A 120-ary LINEAR factor never builds a 4^120 table: it
        lowers to 120 unaries, and DPOP's answer is the analytic
        sum-of-row-minima."""
        from pydcop_tpu.algorithms.dpop import DpopSolver

        rng = np.random.default_rng(21)
        vs = _vars(120, 4)
        rows = rng.uniform(0.0, 10.0, (120, 4))
        c = LinearConstraint("sep", vs, rows, bias=1.25)
        res = DpopSolver(_dcop(vs, [c])).run()
        assert res.cost == pytest.approx(
            1.25 + float(np.sum(np.min(rows, axis=1))), abs=1e-3)

    def test_over_budget_cardinality_routes_to_frontier(self):
        from pydcop_tpu.algorithms.dpop import DpopSolver, algo_params
        from pydcop_tpu.ops.dpop_shard import UtilTableTooLarge

        # 4^14 entries > max_table_entries: can never densify
        vs = _vars(14, 4)
        counts = np.arange(15, dtype=float)
        c = CardinalityConstraint(
            "cap", vs, 0, 50.0 * np.maximum(0.0, counts - 3))
        lin = LinearConstraint(
            "pull", vs, np.tile([0.0, 1.0, 2.0, 3.0], (14, 1)))
        dcop = _dcop(vs, [c, lin])
        res = DpopSolver(dcop).run()  # engine defaults to auto
        # optimum: 3 vars at value 0 (free), the rest at value 1
        assert res.cost == pytest.approx(11.0, abs=1e-4)

        sweep = AlgorithmDef.build_with_default_params(
            "dpop", {"engine": "sweep"},
            parameters_definitions=algo_params)
        with pytest.raises(UtilTableTooLarge):
            DpopSolver(dcop, algo_def=sweep).run()


# ---------------------------------------------------------------------------
# the headline: 100-arity end-to-end, memory independent of arity
# ---------------------------------------------------------------------------


class TestHundredArity:
    def test_maxsum_runs_table_free(self):
        from pydcop_tpu.algorithms.base import tensor_const_bytes
        from pydcop_tpu.algorithms.maxsum import MaxSumSolver, algo_params
        from pydcop_tpu.generators import generate_routing_structured
        from pydcop_tpu.ops.compile import compile_factor_graph

        algo = AlgorithmDef.build_with_default_params(
            "maxsum", {}, parameters_definitions=algo_params)

        def bytes_at(n):
            d = generate_routing_structured(
                n, n_slots=4, window=n, p_soft=0.0, seed=0)
            t = compile_factor_graph(d)
            s = MaxSumSolver(d, t, algo, seed=0)
            res = s.run(cycles=3)
            assert res.assignment and len(res.assignment) == n
            return tensor_const_bytes(t)

        b50, b100 = bytes_at(50), bytes_at(100)
        # table-free: bytes grow LINEARLY with arity (4^100/4^50 would
        # be ~1e30x), and the whole graph stays well under a megabyte
        assert b100 < 4 * b50
        assert b100 < 1 << 20

    def test_frontier_solves_feasibly(self):
        from pydcop_tpu.generators import generate_routing_structured
        from pydcop_tpu.search.solver import FrontierSearchSolver

        d = generate_routing_structured(
            100, n_slots=4, window=100, p_soft=0.0, seed=0)
        s = FrontierSearchSolver(
            d, frontier_width=256, i_bound=2)
        assert s.plan.table_bytes < 4 << 20  # no 4^100 buffer anywhere
        res = s.run(cycles=3)
        # exact caps + forbidden slots: feasibility is the hard part,
        # and the beam-seeded incumbent delivers a real leaf
        assert res.violation == 0
        assert 0.0 < res.cost < 1000.0

    def test_beam_dive_survives_tight_capacity(self):
        from pydcop_tpu.generators import generate_routing_structured
        from pydcop_tpu.search.solver import FrontierSearchSolver

        d = generate_routing_structured(
            100, n_slots=4, window=100, p_soft=0.0, seed=0)
        s = FrontierSearchSolver(d, frontier_width=64, i_bound=2)
        assign, g = s.engine.beam_dive(width=400)
        counts = np.bincount(assign, minlength=4)
        assert g < 1e6  # no HARD_COST overload in the rollout
        assert counts.max() <= 25  # perfectly balanced 25/25/25/25


# ---------------------------------------------------------------------------
# refusal guards + pins
# ---------------------------------------------------------------------------


class TestGuards:
    def test_pad_cost_pinned_to_compile(self):
        from pydcop_tpu.ops import compile as compile_mod
        from pydcop_tpu.ops import structured_kernels

        assert structured_kernels.PAD_COST == compile_mod.PAD_COST

    def test_mesh_shard_refuses_structured(self):
        from pydcop_tpu.ops.compile import compile_factor_graph
        from pydcop_tpu.parallel.mesh import shard_factor_graph

        sd, _, _ = _twin_dcops()
        with pytest.raises(NotImplementedError):
            shard_factor_graph(compile_factor_graph(sd), 2)

    def test_bucketing_refuses_structured(self):
        from pydcop_tpu.batch.bucketing import dims_of
        from pydcop_tpu.ops.compile import compile_factor_graph

        sd, _, _ = _twin_dcops()
        with pytest.raises(NotImplementedError):
            dims_of(compile_factor_graph(sd), "factor")

    def test_weighted_local_tables_refuse_structured(self):
        from pydcop_tpu.ops.compile import (
            compile_constraint_graph,
            local_cost_tables,
        )

        sd, _, vs = _twin_dcops()
        t = compile_constraint_graph(sd)
        x = jnp.zeros(len(vs), jnp.int32)
        w = jnp.ones(t.n_factors, jnp.float32)
        with pytest.raises(NotImplementedError):
            local_cost_tables(t, x, factor_weights=w)


# ---------------------------------------------------------------------------
# warm mutations: scalar param patches, no slab rewrite
# ---------------------------------------------------------------------------


class TestWarmStructured:
    def _warm(self, algo="maxsum", seed=0):
        from pydcop_tpu.algorithms.warm import build_warm_solver

        sd, _, vs = _twin_dcops(seed=seed)
        return sd, vs, build_warm_solver(sd, algo=algo, seed=1)

    def test_edit_patches_params_and_matches_cold(self):
        from pydcop_tpu.algorithms.warm import build_warm_solver

        sd, vs, s = self._warm()
        s.run(cycles=10)
        old = sd.constraints["win"]
        new = ResourceConstraint(
            "win", old.dimensions,
            [2.0 * p for p in old.pref], old.values,
            2.0 * old.count_cost)
        s.change_factor_function(new)
        warm = s.run(cycles=10)
        cold = build_warm_solver(sd, algo="maxsum", seed=1).run(
            cycles=10)
        assert warm.cost == pytest.approx(cold.cost, abs=1e-4)

    def test_add_structured_needs_repack(self):
        from pydcop_tpu.ops.headroom import AddFactor, HeadroomExhausted

        sd, vs, s = self._warm(seed=2)
        extra = _resource("win2", vs[:3], seed=9)
        with pytest.raises(HeadroomExhausted):
            s.apply_mutations([AddFactor(extra)])

    def test_remove_structured_refused(self):
        from pydcop_tpu.ops.headroom import RemoveFactor

        sd, vs, s = self._warm(seed=3)
        with pytest.raises(ValueError):
            s.apply_mutations([RemoveFactor("win")])
