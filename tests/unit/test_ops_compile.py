"""Unit tests for the tensorization compiler and kernel ops."""
import numpy as np
import pytest

from pydcop_tpu.dcop import (
    DCOP,
    Domain,
    NAryMatrixRelation,
    Variable,
    VariableWithCostDict,
    constraint_from_str,
)
from pydcop_tpu.ops import (
    PAD_COST,
    compile_constraint_graph,
    compile_factor_graph,
)
from pydcop_tpu.ops.compile import local_cost_tables, total_cost
from pydcop_tpu.ops.maxsum_kernels import (
    factor_to_var_messages,
    init_messages,
    maxsum_cycle,
)


@pytest.fixture
def mixed_dcop():
    """Heterogeneous domains + mixed arity, to exercise padding/bucketing."""
    d2 = Domain("d2", "d", [0, 1])
    d3 = Domain("d3", "d", [0, 1, 2])
    x, y, z = Variable("x", d2), Variable("y", d3), Variable("z", d3)
    dcop = DCOP("mixed")
    dcop.add_variable(VariableWithCostDict("w", d2, {0: 1.0, 1: 2.0}))
    w = dcop.variables["w"]
    dcop.add_constraint(constraint_from_str("c_xy", "x * y", [x, y]))
    dcop.add_constraint(
        constraint_from_str("c_xyz", "x + y + z", [x, y, z])
    )
    dcop.add_constraint(constraint_from_str("c_w", "w * 5", [w]))
    return dcop


class TestCompile:
    def test_shapes(self, mixed_dcop):
        t = compile_factor_graph(mixed_dcop)
        assert t.n_vars == 4
        assert t.max_domain_size == 3
        assert t.n_factors == 3
        # arities 1, 2, 3 → three buckets, edges = 1 + 2 + 3
        assert [b.arity for b in t.buckets] == [1, 2, 3]
        assert t.n_edges == 6

    def test_padding(self, mixed_dcop):
        t = compile_factor_graph(mixed_dcop)
        # w has domain size 2 → mask [1,1,0]
        wi = t.var_index("w")
        np.testing.assert_array_equal(np.asarray(t.domain_mask)[wi], [1, 1, 0])
        assert np.asarray(t.unary_costs)[wi, 2] == PAD_COST
        np.testing.assert_allclose(np.asarray(t.unary_costs)[wi, :2], [1, 2])

    def test_factor_tensor_content(self, mixed_dcop):
        t = compile_factor_graph(mixed_dcop)
        b2 = next(b for b in t.buckets if b.arity == 2)
        tens = np.asarray(b2.tensors)[0]
        # c_xy = x * y with x in d2, y in d3
        for xv in range(2):
            for yv in range(3):
                assert tens[xv, yv] == xv * yv
        assert tens[2, 0] == PAD_COST  # padded x value

    def test_total_cost(self, mixed_dcop):
        t = compile_factor_graph(mixed_dcop)
        x = t.indices_from_assignment({"x": 1, "y": 2, "z": 1, "w": 1})
        got = float(total_cost(t, np.asarray(x)))
        # c_xy=2, c_xyz=4, c_w=5, unary w=2
        assert got == pytest.approx(13.0)

    def test_assignment_roundtrip(self, mixed_dcop):
        t = compile_factor_graph(mixed_dcop)
        asst = {"x": 1, "y": 2, "z": 0, "w": 0}
        x = t.indices_from_assignment(asst)
        assert t.assignment_from_indices(x) == asst

    def test_max_objective_sign(self):
        d = Domain("d", "d", [0, 1])
        v = Variable("v", d)
        dcop = DCOP("m", objective="max")
        dcop.add_constraint(constraint_from_str("c", "v * 3", [v]))
        t = compile_factor_graph(dcop)
        b = t.buckets[0]
        np.testing.assert_allclose(np.asarray(b.tensors)[0], [0, -3])


class TestLocalCostTables:
    def test_binary_chain(self):
        d = Domain("d", "d", [0, 1, 2])
        vs = [Variable(f"v{i}", d) for i in range(3)]
        dcop = DCOP("chain")
        dcop.add_constraint(
            constraint_from_str("c01", "10 if v0 == v1 else 0", vs))
        dcop.add_constraint(
            constraint_from_str("c12", "10 if v1 == v2 else 0", vs))
        t = compile_constraint_graph(dcop)
        x = t.indices_from_assignment({"v0": 0, "v1": 1, "v2": 0})
        tables = np.asarray(local_cost_tables(t, np.asarray(x)))
        i1 = t.var_index("v1")
        # v1: conflicts with v0=0 and v2=0 → value 0 costs 20, 1 and 2 cost 0
        np.testing.assert_allclose(tables[i1], [20, 0, 0])
        i0 = t.var_index("v0")
        # v0 vs v1=1: value 1 costs 10
        np.testing.assert_allclose(tables[i0], [0, 10, 0])

    def test_nary(self):
        d = Domain("d", "d", [0, 1])
        vs = [Variable(f"v{i}", d) for i in range(3)]
        dcop = DCOP("t")
        dcop.add_constraint(
            constraint_from_str("c", "v0 * v1 * v2", vs))
        t = compile_constraint_graph(dcop)
        x = np.array([1, 1, 0], dtype=np.int32)
        tables = np.asarray(local_cost_tables(t, x))
        # for v2 (idx of 'v2'), cost at value 1 = 1*1*1 = 1
        i2 = t.var_index("v2")
        np.testing.assert_allclose(tables[i2], [0, 1])

    def test_neighbors(self):
        d = Domain("d", "d", [0, 1])
        vs = [Variable(f"v{i}", d) for i in range(3)]
        dcop = DCOP("t")
        dcop.add_constraint(constraint_from_str("c", "v0 + v1 + v2", vs))
        t = compile_constraint_graph(dcop)
        assert t.neighbor_src.shape == (6,)  # 3 vars, all pairs directed


class TestMaxSumKernels:
    def test_factor_messages_binary(self):
        """Hand-checked factor→var messages on a single binary factor."""
        d = Domain("d", "d", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("t")
        dcop.add_constraint(
            NAryMatrixRelation([x, y], [[0.0, 3.0], [5.0, 1.0]], "c"))
        t = compile_factor_graph(dcop)
        b = t.buckets[0]
        q = np.zeros((1, 2, 2), dtype=np.float32)
        r = np.asarray(factor_to_var_messages(b, q))
        # message to x (pos 0): min over y → [min(0,3), min(5,1)] = [0, 1]
        np.testing.assert_allclose(r[0, 0], [0, 1])
        # message to y (pos 1): min over x → [0, 1]
        np.testing.assert_allclose(r[0, 1], [0, 1])
        # with a nonzero message from y: q_y = [10, 0]
        q[0, 1] = [10.0, 0.0]
        r = np.asarray(factor_to_var_messages(b, q))
        # to x: min_y(c(x,y)+q_y(y)) = [min(10,3), min(15,1)] = [3, 1]
        np.testing.assert_allclose(r[0, 0], [3, 1])
        # to y unchanged by its own message
        np.testing.assert_allclose(r[0, 1], [0, 1])

    def test_cycle_converges_on_tree(self):
        """On an acyclic factor graph max-sum is exact: check the argmin."""
        d = Domain("d", "d", [0, 1, 2])
        vs = [Variable(f"v{i}", d) for i in range(3)]
        dcop = DCOP("chain")
        dcop.add_constraint(
            constraint_from_str("c01", "(v0 - v1)**2 + v0", vs))
        dcop.add_constraint(
            constraint_from_str("c12", "(v1 - v2)**2 + 2*v2", vs))
        t = compile_factor_graph(dcop)
        q, r = init_messages(t)
        for _ in range(6):
            q, r, beliefs, values = maxsum_cycle(t, q, r)
        got = t.assignment_from_indices(np.asarray(values))
        # brute force optimum
        best, best_cost = None, float("inf")
        for a0 in range(3):
            for a1 in range(3):
                for a2 in range(3):
                    c = (a0 - a1) ** 2 + a0 + (a1 - a2) ** 2 + 2 * a2
                    if c < best_cost:
                        best, best_cost = {"v0": a0, "v1": a1, "v2": a2}, c
        assert got == best

    def test_cycle_heterogeneous_domains(self, mixed_dcop):
        t = compile_factor_graph(mixed_dcop)
        q, r = init_messages(t)
        for _ in range(5):
            q, r, beliefs, values = maxsum_cycle(t, q, r, damping=0.3)
        vals = np.asarray(values)
        # never select a padded value
        for i in range(t.n_vars):
            assert vals[i] < len(t.domain_values[i])
