"""Unit tests for runtime: orchestrator, replication, repair, checkpoint,
events."""
import os
import numpy as np

import pytest

from pydcop_tpu.dcop import (
    AgentDef,
    DcopEvent,
    EventAction,
    Scenario,
    load_dcop_from_file,
)
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.replication import place_replicas, route_distances
from pydcop_tpu.reparation import build_repair_dcop, solve_repair_dcop
from pydcop_tpu.runtime.events import EventDispatcher
from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def tuto():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


class TestEvents:
    def test_pubsub_wildcards(self):
        bus = EventDispatcher(enabled=True)
        got = []
        bus.subscribe("computations.value.*", lambda t, e: got.append(e))
        bus.send("computations.value.v1", 42)
        bus.send("computations.cycle.v1", 1)
        assert got == [42]

    def test_disabled_by_default(self):
        bus = EventDispatcher()
        got = []
        bus.subscribe("*", lambda t, e: got.append(e))
        bus.send("x", 1)
        assert got == []


class TestReplication:
    def test_route_distances_triangle_inequality(self):
        agents = [
            AgentDef("a1", routes={"a2": 1, "a3": 10}),
            AgentDef("a2", routes={"a1": 1, "a3": 1}),
            AgentDef("a3", routes={"a1": 10, "a2": 1}),
        ]
        d = route_distances(agents)
        # a1→a3 direct costs 10, via a2 costs 2
        assert d["a1"]["a3"] == 2

    def test_place_replicas(self):
        agents = [AgentDef(f"a{i}", capacity=10) for i in range(4)]
        dist = Distribution({"a0": ["c1"], "a1": ["c2"], "a2": [], "a3": []})
        reps = place_replicas(
            ["c1", "c2"], dist, agents, k=2,
            computation_memory=lambda c: 1.0,
        )
        for c in ("c1", "c2"):
            r = reps.replicas(c)
            assert len(r) == 2
            assert dist.agent_for(c) not in r
            assert len(set(r)) == 2

    def test_replicas_respect_capacity(self):
        agents = [AgentDef("a0", capacity=10), AgentDef("a1", capacity=1)]
        dist = Distribution({"a0": ["c1", "c2", "c3"], "a1": []})
        reps = place_replicas(
            ["c1", "c2", "c3"], dist, agents, k=1,
            computation_memory=lambda c: 1.0,
        )
        # a1 can hold only one replica
        held = sum(1 for c in ("c1", "c2", "c3")
                   if "a1" in reps.replicas(c))
        assert held == 1


class TestRepair:
    def test_repair_dcop_and_solve(self):
        agents = {
            "a1": AgentDef("a1", capacity=10),
            "a2": AgentDef("a2", capacity=10),
        }
        dist = Distribution({"a1": ["k1"], "a2": ["k2"]})
        repair, vars_by_comp = build_repair_dcop(
            orphaned=["o1", "o2"],
            candidates={"o1": ["a1", "a2"], "o2": ["a1", "a2"]},
            agents=agents,
            distribution=dist,
            computation_memory=lambda c: 1.0,
        )
        # 4 binary variables, 2 hosted constraints + 2 capacity constraints
        assert len(repair.variables) == 4
        placement = solve_repair_dcop(repair, vars_by_comp, seed=1)
        assert set(placement) == {"o1", "o2"}
        assert all(a in ("a1", "a2") for a in placement.values())

    def test_repair_respects_capacity(self):
        agents = {
            "a1": AgentDef("a1", capacity=1),
            "a2": AgentDef("a2", capacity=10),
        }
        dist = Distribution({"a1": ["k1"], "a2": []})  # a1 already full
        repair, vars_by_comp = build_repair_dcop(
            orphaned=["o1"],
            candidates={"o1": ["a1", "a2"]},
            agents=agents,
            distribution=dist,
            computation_memory=lambda c: 1.0,
        )
        placement = solve_repair_dcop(repair, vars_by_comp, seed=0)
        assert placement["o1"] == "a2"


class TestOrchestrator:
    def test_static_run(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        res = orch.run(timeout=20)
        assert res.status == "FINISHED"
        assert res.cost == 12
        m = orch.end_metrics()
        assert m["distribution"]

    def test_scenario_remove_agent(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.start_replication(2)
        scenario = Scenario(
            [
                DcopEvent("d1", delay=0.5),
                DcopEvent(
                    "e1",
                    actions=[EventAction("remove_agent", agent="a1")],
                ),
            ]
        )
        res = orch.run(scenario, timeout=30)
        assert "a1" not in orch.distribution.agents
        # every computation is still hosted somewhere
        hosted = sorted(orch.distribution.computations)
        assert hosted == sorted(n.name for n in orch.cg.nodes)
        assert res.cost == 12  # solution quality survives the repair

    def test_invalid_distribution_rejected(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.distribution.remove_computation("v1")
        with pytest.raises(ValueError):
            orch.deploy_computations()


class TestCheckpoint:
    def test_save_load_roundtrip(self, tuto, tmp_path):
        import numpy as np

        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.algorithms.maxsum import build_solver
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        solver = build_solver(tuto)
        res1 = solver.run(cycles=6)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, solver, extra={"note": "test"})

        solver2 = build_solver(tuto)
        meta = load_checkpoint(path, solver2)
        assert meta["algo"] == "maxsum"
        assert meta["extra"]["note"] == "test"
        # resuming from the checkpoint reproduces the same next state
        res_a = solver.run(cycles=4, resume=True)
        res_b = solver2.run(cycles=4, resume=True)
        assert res_a.assignment == res_b.assignment

    def test_shape_mismatch_rejected(self, tuto, tmp_path):
        from pydcop_tpu.algorithms.maxsum import build_solver
        from pydcop_tpu.generators import generate_graph_coloring
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        solver = build_solver(tuto)
        solver.run(cycles=2)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, solver)
        other = generate_graph_coloring(6, 3, n_edges=5, seed=0)
        solver_other = build_solver(other)
        with pytest.raises(ValueError):
            load_checkpoint(path, solver_other)


class TestPauseResume:
    """Reference mgmt verbs pause/resume/stop (orchestrator.py:1127-1159)
    mapped onto the phase-based runtime: pause blocks further phases,
    resume allows a warm restart from retained device state."""

    def test_pause_blocks_run(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.pause_computations()
        assert orch.status == "PAUSED"
        with pytest.raises(RuntimeError, match="paused"):
            orch.run(cycles=5)

    def test_pause_before_deploy_rejected(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        with pytest.raises(RuntimeError, match="deploy"):
            orch.pause_computations()

    def test_run_after_stop_rejected(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.run(cycles=3)
        orch.stop_agents(2)
        with pytest.raises(RuntimeError, match="stopped"):
            orch.run(cycles=3)

    def test_resume_continues_prng_stream(self, tuto):
        """A warm restart must continue the PRNG stream, not replay it:
        dsa's activation coins in cycles 4-6 must differ from 1-3."""
        from pydcop_tpu.runtime import solve_result

        orch = VirtualOrchestrator(tuto, "dsa", distribution="adhoc")
        orch.deploy_computations()
        orch.run(cycles=3)
        solver = orch.solver
        key_after_first = np.asarray(solver._last_key)
        orch.pause_computations()
        orch.resume_computations()
        orch.run(cycles=3)
        key_after_second = np.asarray(solver._last_key)
        assert not np.array_equal(key_after_first, key_after_second)

    def test_resume_continues_from_state(self, tuto):
        # mgm is monotone and deterministically seeded: a COLD restart
        # replays the same 3-cycle trajectory, a WARM restart continues
        orch = VirtualOrchestrator(tuto, "mgm", distribution="adhoc")
        orch.deploy_computations()
        res1 = orch.run(cycles=3)
        orch.pause_computations()
        orch.resume_computations()
        assert orch.status != "PAUSED"  # restored to its pre-pause state
        res2 = orch.run(cycles=3)
        # warm restart: the combined 6 cycles match one straight 6-cycle
        # run, not a replay of the first 3
        straight = VirtualOrchestrator(tuto, "mgm", distribution="adhoc")
        straight.deploy_computations()
        res6 = straight.run(cycles=6)
        assert res2.cost == pytest.approx(res6.cost)
        assert res2.cost <= res1.cost

    def test_stop_agents_marks_stopped(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.run(cycles=5)
        orch.stop_agents(2)
        assert orch.status == "STOPPED"


class TestLifecycleEdgeCases:
    def test_double_pause_is_idempotent(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.pause_computations()
        orch.pause_computations()  # must not trap the pre-pause status
        orch.resume_computations()
        assert orch.status != "PAUSED"
        res = orch.run(cycles=3)
        assert res.status == "FINISHED"

    def test_pause_after_stop_rejected(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.run(cycles=3)
        orch.stop_agents(2)
        with pytest.raises(RuntimeError, match="stopped"):
            orch.pause_computations()

    def test_checkpoint_persists_prng_key(self, tuto, tmp_path):
        """A restored stochastic solver must CONTINUE the PRNG stream."""
        from pydcop_tpu.algorithms.dsa import build_solver
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        s1 = build_solver(tuto)
        s1.run(cycles=5)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, s1)
        s2 = build_solver(tuto)
        load_checkpoint(path, s2)
        assert np.array_equal(
            np.asarray(s2._last_key), np.asarray(s1._last_key)
        )
        # and the continued run differs from a replayed-seed run
        s2.run(cycles=5, resume=True)
        assert not np.array_equal(
            np.asarray(s2._last_key), np.asarray(s1._last_key)
        )


class TestRateDerivedPhases:
    """Scenario delays are seconds of solver activity: the device rate is
    calibrated on the first phase and each delay converts to a
    proportional cycle budget (VERDICT r2 item 7)."""

    def _run(self, tuto, delays):
        orch = VirtualOrchestrator(tuto, "dsa", distribution="adhoc")
        orch.deploy_computations()
        events = [
            DcopEvent(f"d{i}", delay=d) for i, d in enumerate(delays)
        ]
        orch.run(Scenario(events), timeout=60)
        return orch

    def test_delay_converts_to_proportional_cycles(self, tuto):
        short = self._run(tuto, [0.2])
        long = self._run(tuto, [2.0])
        assert short._cycle_rate is not None
        # final convergence phases are both ~1s worth; the delay phases
        # differ 10x, so total cycles must clearly increase with delay
        # (loose threshold: machine load skews wall-derived rates)
        ratio = long._cycles_done / max(1, short._cycles_done)
        assert ratio > 1.2, (
            short._cycles_done, long._cycles_done, short._cycle_rate,
        )

    def test_explicit_cycles_still_win(self, tuto):
        orch = VirtualOrchestrator(tuto, "dsa", distribution="adhoc")
        orch.deploy_computations()
        scenario = Scenario([DcopEvent("d1", delay=5.0)])
        res = orch.run(scenario, cycles=7, timeout=60)
        # 7 for the delay phase + 7 for the final phase, not 5s worth
        assert res.cycle == 14

    def test_rate_is_refreshed_across_phases(self, tuto):
        orch = self._run(tuto, [0.3, 0.3])
        assert orch._cycle_rate is not None and orch._cycle_rate > 0
