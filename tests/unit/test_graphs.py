"""Unit tests for the four computation-graph models."""
import pytest

from pydcop_tpu.dcop import DCOP, Domain, Variable, constraint_from_str
from pydcop_tpu.graph import load_graph_module
from pydcop_tpu.graph import factor_graph, constraints_hypergraph
from pydcop_tpu.graph import pseudotree, ordered_graph


@pytest.fixture
def coloring_dcop():
    """Triangle + one pendant variable."""
    d = Domain("colors", "color", ["R", "G", "B"])
    dcop = DCOP("coloring")
    vs = {n: Variable(n, d) for n in ("v1", "v2", "v3", "v4")}
    for a, b in [("v1", "v2"), ("v2", "v3"), ("v1", "v3"), ("v3", "v4")]:
        dcop.add_constraint(
            constraint_from_str(
                f"c_{a}_{b}", f"1 if {a} == {b} else 0", vs.values()
            )
        )
    return dcop


def test_load_graph_module():
    m = load_graph_module("factor_graph")
    assert m.GRAPH_TYPE == "factor_graph"
    with pytest.raises(ValueError):
        load_graph_module("nope")


class TestFactorGraph:
    def test_build(self, coloring_dcop):
        fg = factor_graph.build_computation_graph(coloring_dcop)
        assert len(fg.var_nodes) == 4
        assert len(fg.factor_nodes) == 4
        assert fg.node_count() == 8
        v3 = fg.computation("v3")
        assert set(v3.neighbors) == {"c_v2_v3", "c_v1_v3", "c_v3_v4"}
        f = fg.computation("c_v1_v2")
        assert set(f.neighbors) == {"v1", "v2"}

    def test_density(self, coloring_dcop):
        fg = factor_graph.build_computation_graph(coloring_dcop)
        assert 0 < fg.density() < 1


class TestConstraintsHypergraph:
    def test_build(self, coloring_dcop):
        g = constraints_hypergraph.build_computation_graph(coloring_dcop)
        assert g.node_count() == 4
        v3 = g.computation("v3")
        assert set(v3.neighbors) == {"v1", "v2", "v4"}
        assert len(v3.constraints) == 3
        v4 = g.computation("v4")
        assert set(v4.neighbors) == {"v3"}


class TestPseudoTree:
    def test_build(self, coloring_dcop):
        pt = pseudotree.build_computation_graph(coloring_dcop)
        assert len(pt.roots) == 1
        root = pt.computation(pt.root)
        assert root.parent is None
        # every non-root has exactly one parent, depths are consistent
        for n in pt.nodes:
            if n.name != pt.root:
                assert n.parent is not None
                assert pt.depth(n.name) == pt.depth(n.parent) + 1

    def test_back_edges(self, coloring_dcop):
        pt = pseudotree.build_computation_graph(coloring_dcop)
        # triangle v1-v2-v3 forces exactly one pseudo edge
        pseudo = [
            (n.name, pp) for n in pt.nodes for pp in n.pseudo_parents
        ]
        assert len(pseudo) == 1
        node, pp = pseudo[0]
        # the pseudo parent must be an ancestor of the node
        anc = pt.computation(node).parent
        ancestors = set()
        while anc is not None:
            ancestors.add(anc)
            anc = pt.computation(anc).parent
        assert pp in ancestors
        # symmetric pseudo_children
        assert node in pt.computation(pp).pseudo_children

    def test_constraints_on_lowest_node(self, coloring_dcop):
        pt = pseudotree.build_computation_graph(coloring_dcop)
        all_attached = [c.name for n in pt.nodes for c in n.constraints]
        assert sorted(all_attached) == sorted(coloring_dcop.constraints)
        for n in pt.nodes:
            for c in n.constraints:
                # node must be the deepest variable of the constraint
                depths = [pt.depth(v.name) for v in c.dimensions]
                assert pt.depth(n.name) == max(depths)

    def test_forest_on_disconnected(self):
        d = Domain("d", "d", [0, 1])
        dcop = DCOP("two_comps")
        vs = {n: Variable(n, d) for n in ("a1", "a2", "b1", "b2")}
        dcop.add_constraint(
            constraint_from_str("ca", "1 if a1 == a2 else 0", vs.values()))
        dcop.add_constraint(
            constraint_from_str("cb", "1 if b1 == b2 else 0", vs.values()))
        pt = pseudotree.build_computation_graph(dcop)
        assert len(pt.roots) == 2

    def test_levels(self, coloring_dcop):
        pt = pseudotree.build_computation_graph(coloring_dcop)
        levels = pt.nodes_by_depth()
        assert sum(len(l) for l in levels) == 4
        assert [n.name for n in levels[0]] == [pt.root]


class TestOrderedGraph:
    def test_build(self, coloring_dcop):
        og = ordered_graph.build_computation_graph(coloring_dcop)
        assert og.order == ["v1", "v2", "v3", "v4"]
        n1 = og.computation("v1")
        assert n1.previous_node is None and n1.next_node == "v2"
        n4 = og.computation("v4")
        assert n4.next_node is None and n4.previous_node == "v3"
        assert len(og.computation("v3").constraints) == 3
