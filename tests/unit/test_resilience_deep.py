"""Deeper resilience coverage: sequential failures, replica survival
under k-replication, repair placement quality, and metrics continuity
(reference flow: pydcop/infrastructure/orchestrator.py:943-1125 +
agents.py:1044-1355).
"""
import os

import pytest

from pydcop_tpu.dcop import (
    AgentDef,
    DcopEvent,
    EventAction,
    Scenario,
    load_dcop_from_file,
)
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.replication import place_replicas, route_distances
from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def tuto():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


def removal_scenario(*agents, delay=0.3):
    events = [DcopEvent("d0", delay=delay)]
    for i, a in enumerate(agents):
        events.append(DcopEvent(
            f"e{i}", actions=[EventAction("remove_agent", agent=a)]
        ))
        events.append(DcopEvent(f"d{i + 1}", delay=delay))
    return Scenario(events)


class TestSequentialFailures:
    def test_two_sequential_removals_still_hosted(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.start_replication(2)
        res = orch.run(removal_scenario("a1", "a2"), timeout=60)
        assert res.status == "FINISHED"
        assert "a1" not in orch.distribution.agents
        assert "a2" not in orch.distribution.agents
        hosted = sorted(orch.distribution.computations)
        assert hosted == sorted(n.name for n in orch.cg.nodes)
        assert res.cost == 12  # quality survives two repairs

    def test_events_logged_per_removal(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.start_replication(2)
        orch.run(removal_scenario("a1", "a2"), timeout=60)
        action_logs = [
            e["actions"] for e in orch.events_log if "actions" in e
        ]
        assert action_logs.count(["remove_agent"]) == 2
        # each removal triggers a repair placement entry
        repairs = [e for e in orch.events_log if "repaired" in e]
        assert len(repairs) == 2

    def test_add_agent_then_remove_other(self, tuto):
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.start_replication(2)
        scenario = Scenario([
            DcopEvent("d0", delay=0.3),
            DcopEvent("e0", actions=[
                EventAction("add_agent", agent="a_new")
            ]),
            DcopEvent("e1", actions=[
                EventAction("remove_agent", agent="a1")
            ]),
            DcopEvent("d1", delay=0.3),
        ])
        res = orch.run(scenario, timeout=60)
        assert res.status == "FINISHED"
        assert "a_new" in orch.distribution.agents
        hosted = sorted(orch.distribution.computations)
        assert hosted == sorted(n.name for n in orch.cg.nodes)


class TestReplicaSurvival:
    def agents(self, n, capacity=10):
        return [
            AgentDef(f"a{i}", capacity=capacity,
                     routes={f"a{j}": 1 for j in range(n) if j != i})
            for i in range(n)
        ]

    def test_k2_replicas_survive_single_failure(self):
        agents = self.agents(5)
        comps = ["c0", "c1", "c2"]
        dist = Distribution({
            "a0": ["c0"], "a1": ["c1"], "a2": ["c2"], "a3": [], "a4": [],
        })
        placement = place_replicas(
            comps, dist, agents, k=2, computation_memory=lambda c: 1.0
        )
        for comp in comps:
            hosts = placement.replicas(comp)
            assert len(hosts) == 2
            owner = dist.agent_for(comp)
            assert owner not in hosts  # replicas live off the owner
            # single agent failure leaves at least one replica
            for failed in agents:
                survivors = [h for h in hosts if h != failed.name]
                assert survivors or failed.name not in hosts

    def test_replicas_prefer_cheap_routes(self):
        # a1 is 1 hop from a0; a2 is 100 — k=1 replica of a0's comp
        # must land on a1
        agents = [
            AgentDef("a0", capacity=10, routes={"a1": 1, "a2": 100}),
            AgentDef("a1", capacity=10, routes={"a0": 1, "a2": 100}),
            AgentDef("a2", capacity=10, routes={"a0": 100, "a1": 100}),
        ]
        dist = Distribution({"a0": ["c0"], "a1": [], "a2": []})
        placement = place_replicas(
            ["c0"], dist, agents, k=1, computation_memory=lambda c: 1.0
        )
        assert placement.replicas("c0") == ["a1"]

    def test_replica_count_capped_by_agents(self):
        agents = self.agents(3)
        dist = Distribution({"a0": ["c0"], "a1": [], "a2": []})
        placement = place_replicas(
            ["c0"], dist, agents, k=5, computation_memory=lambda c: 1.0
        )
        # only 2 other agents exist: k is effectively min(k, |A|-1)
        assert len(placement.replicas("c0")) == 2


class TestRouteDistances:
    def test_disconnected_agents_unreachable(self):
        # routes are direction-of-sender: every agent must declare the
        # partition (default inf) for a3 to be truly unreachable
        inf = float("inf")
        agents = [
            AgentDef("a1", routes={"a2": 1}, default_route=inf),
            AgentDef("a2", routes={"a1": 1}, default_route=inf),
            AgentDef("a3", routes={}, default_route=inf),
        ]
        d = route_distances(agents)
        assert d["a1"]["a2"] == 1
        assert d["a1"].get("a3", inf) == inf

    def test_default_route_used_when_no_explicit(self):
        agents = [AgentDef("a1", default_route=3),
                  AgentDef("a2", default_route=3)]
        d = route_distances(agents)
        assert d["a1"]["a2"] == 3


class TestRepairQuality:
    def test_repair_prefers_low_comm_hosts(self, tuto):
        """After removing an agent, its computation should land on a
        surviving replica host (not vanish, not duplicate)."""
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc")
        orch.deploy_computations()
        orch.start_replication(2)
        lost = orch.distribution.computations_hosted("a1")
        res = orch.run(removal_scenario("a1"), timeout=60)
        assert res.status == "FINISHED"
        for comp in lost:
            new_host = orch.distribution.agent_for(comp)
            assert new_host != "a1"
        # no computation is hosted twice
        all_comps = []
        for a in orch.distribution.agents:
            all_comps.extend(orch.distribution.computations_hosted(a))
        assert len(all_comps) == len(set(all_comps))


class TestReplicaDistYaml:
    """Round-trip of the replica-distribution YAML format (reference
    replication/yamlformat.py:44-58)."""

    def test_roundtrip(self):
        from pydcop_tpu.replication import ReplicaDistribution
        from pydcop_tpu.replication.yamlformat import (
            load_replica_dist,
            yaml_replica_dist,
        )

        replicas = ReplicaDistribution(
            {"v1": ["a2", "a3"], "c_1_2": ["a1"]}
        )
        text = yaml_replica_dist(replicas, inputs={"k": 2})
        loaded = load_replica_dist(text)
        assert loaded.mapping() == replicas.mapping()

    def test_invalid_file_rejected(self):
        from pydcop_tpu.replication.yamlformat import load_replica_dist

        with pytest.raises(ValueError):
            load_replica_dist("distribution:\n  a1: [v1]\n")
        with pytest.raises(ValueError):
            load_replica_dist("replica_dist: [not, a, mapping]\n")

    def test_file_roundtrip(self, tmp_path):
        from pydcop_tpu.replication import ReplicaDistribution
        from pydcop_tpu.replication.yamlformat import (
            load_replica_dist_from_file,
            yaml_replica_dist,
        )

        path = tmp_path / "rep.yaml"
        replicas = ReplicaDistribution({"v1": ["a2"]})
        path.write_text(yaml_replica_dist(replicas))
        assert load_replica_dist_from_file(
            str(path)).mapping() == {"v1": ["a2"]}

    def test_scalar_replicas_rejected(self):
        from pydcop_tpu.replication.yamlformat import load_replica_dist

        with pytest.raises(ValueError):
            load_replica_dist("replica_dist:\n  v1: a2\n")
        with pytest.raises(ValueError):
            load_replica_dist("replica_dist:\n  v1:\n")
