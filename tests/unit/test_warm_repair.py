"""Warm repair (ISSUE 8): survive agent churn and live mutations
without a cold restart.

Pins the tentpole guarantees:

* a seeded 50-mutation churn stream on a headroom-packed maxsum AND a
  local-search engine completes with ZERO chunk-runner retraces
  (the acceptance criterion's trace-count pin);
* warm-repair vs cold-repack equivalence — after any single mutation
  the warm-started solve reaches the same fixed point as a cold
  repack that carries the same state (bit-identical for coin-free
  MGM and deterministic maxsum, statistical for dsa/adsa);
* graceful degradation: headroom exhaustion triggers exactly ONE
  counted repack (one retrace, one ``repair.repack`` event, never an
  exception mid-run);
* checkpoint schema v3 restores a MUTATED problem at its exact padded
  shape; corrupt/newer files keep the existing ValueError path.
"""
import textwrap

import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.warm import (
    WarmLocalSearchSolver,
    WarmMaxSumSolver,
    build_warm_solver,
    repack_solver,
)
from pydcop_tpu.dcop import load_dcop
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.ops.headroom import (
    AddFactor,
    AddVariable,
    EditFactor,
    HeadroomExhausted,
    HeadroomLayout,
    RemoveFactor,
    RemoveVariable,
    reserve_headroom,
)
from pydcop_tpu.runtime.events import event_bus
from pydcop_tpu.runtime.repair import (
    WarmRepairController,
    perturbed_constraint,
)
from pydcop_tpu.runtime.stats import RepairCounters

YAML = textwrap.dedent("""
    name: t
    objective: min
    domains:
      d: {values: [0, 1, 2]}
    variables:
      v1: {domain: d}
      v2: {domain: d}
      v3: {domain: d}
      v4: {domain: d}
    constraints:
      c12: {type: intention, function: "0 if v1 == v2 else 5"}
      c23: {type: intention, function: "0 if v2 != v3 else 3"}
      c34: {type: intention, function: "abs(v3 - v4)"}
    agents: [a1, a2, a3, a4, a5, a6, a7, a8]
""")


def fresh_dcop():
    return load_dcop(YAML)


def swap_c12(dcop):
    return constraint_from_str(
        "c12", "0 if v1 != v2 else 5",
        list(dcop.constraints["c12"].dimensions),
    )


# ---------------------------------------------------------------------------
# headroom layout
# ---------------------------------------------------------------------------


class TestHeadroomLayout:
    def test_reserve_shapes_and_inert_slots(self):
        dcop = fresh_dcop()
        cap, layout = reserve_headroom(dcop, graph="factor",
                                       headroom=0.5, min_free=3)
        V = 4
        assert layout.n_vars_cap == cap.n_vars
        assert cap.n_vars > V  # headroom + parking
        assert layout.parking == cap.n_vars - 1
        # inert slots: single valid value, zero cost
        mask = np.asarray(cap.domain_mask)
        assert (mask[V:, 0] == 1).all() and (mask[V:, 1:] == 0).all()
        # free factor slots wired to parking
        b = cap.buckets[0]
        free = layout.free_factor_slots(2)
        assert free, "headroom must reserve free factor slots"
        for k in free:
            assert (np.asarray(b.var_idx[k]) == layout.parking).all()

    def test_claim_release_cycle(self):
        dcop = fresh_dcop()
        _cap, layout = reserve_headroom(dcop, headroom=0.5, min_free=2)
        s = layout.claim_var("z1")
        assert layout.var_slot("z1") == s
        assert layout.release_var("z1") == s
        with pytest.raises(KeyError):
            layout.var_slot("z1")
        b, k = layout.claim_factor("fz", 2)
        assert layout.factor_slot("fz") == (b, k)
        layout.release_factor("fz")
        assert not layout.has_factor("fz")

    def test_meta_roundtrip(self):
        dcop = fresh_dcop()
        _cap, layout = reserve_headroom(dcop, headroom=0.25)
        layout.claim_var("zz")
        layout.claim_factor("fzz", 2)
        back = HeadroomLayout.from_meta(layout.to_meta())
        assert back.var_names == layout.var_names
        assert back.fac_names == layout.fac_names
        assert back.parking == layout.parking

    def test_exhaustion_is_typed(self):
        dcop = fresh_dcop()
        _cap, layout = reserve_headroom(dcop, headroom=0.0, min_free=1)
        layout.claim_var("z1")
        with pytest.raises(HeadroomExhausted):
            layout.claim_var("z2")
        with pytest.raises(HeadroomExhausted):
            layout.claim_factor("f9", 9)  # no arity-9 bucket

    def test_assignment_hides_free_and_parking_slots(self):
        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="mgm", seed=1, headroom=0.5)
        res = s.run(cycles=10, chunk=8)
        assert sorted(res.assignment) == ["v1", "v2", "v3", "v4"]


# ---------------------------------------------------------------------------
# warm solvers: solve quality + zero-retrace mutation
# ---------------------------------------------------------------------------


class TestWarmSolvers:
    @pytest.mark.parametrize("algo", ["maxsum", "mgm", "dsa", "adsa"])
    def test_warm_solver_solves_correctly(self, algo):
        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo=algo, seed=3, headroom=0.4)
        res = s.run(chunk=8)
        assert res.status == "FINISHED"
        # easy instance: the optimum (v1==v2, v2!=v3, v3==v4) is 0
        assert res.violation == 0
        assert res.cost is not None

    @pytest.mark.parametrize("algo", ["maxsum", "mgm", "dsa", "adsa"])
    def test_edit_factor_zero_retrace_and_solution_follows(self, algo):
        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo=algo, seed=3, headroom=0.4)
        s.run(chunk=8)
        t0 = s.trace_count()
        s.apply_mutations([EditFactor(swap_c12(dcop))])
        dcop.constraints["c12"] = swap_c12(dcop)
        res = s.run(resume=True, chunk=8)
        assert s.trace_count() == t0, "a warm mutation must not retrace"
        assert res.assignment["v1"] != res.assignment["v2"]

    def test_add_variable_and_factor_then_remove(self):
        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="mgm", seed=3, headroom=0.5)
        s.run(chunk=8)
        t0 = s.trace_count()
        d = dcop.domains["d"]
        z = Variable("z9", d)  # sorts after v*: index order preserved
        dcop.add_variable(z)
        s.apply_mutations([AddVariable(z)])
        c = constraint_from_str(
            "cz", "0 if z9 == v4 else 7", [z, dcop.variables["v4"]]
        )
        dcop.add_constraint(c)
        s.apply_mutations([AddFactor(c)])
        res = s.run(resume=True, chunk=8)
        assert res.assignment["z9"] == res.assignment["v4"]
        assert s.trace_count() == t0
        # and back out again
        del dcop.constraints["cz"]
        s.apply_mutations([RemoveFactor("cz")])
        del dcop.variables["z9"]
        s.apply_mutations([RemoveVariable("z9")])
        res2 = s.run(resume=True, chunk=8)
        assert "z9" not in res2.assignment
        assert s.trace_count() == t0

    def test_remove_variable_with_live_factor_rejected(self):
        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="mgm", seed=0, headroom=0.4)
        with pytest.raises(ValueError, match="factor"):
            s.apply_mutations([RemoveVariable("v1")])

    def test_scope_mismatch_rejected_and_state_untouched(self):
        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="maxsum", seed=0, headroom=0.4)
        s.run(cycles=5, chunk=8)
        bad = constraint_from_str(
            "c12", "v1 + v3", [dcop.variables["v1"], dcop.variables["v3"]]
        )
        with pytest.raises(ValueError, match="scope"):
            s.apply_mutations([EditFactor(bad)])
        # the factor table is unchanged: re-running converges as before
        res = s.run(resume=True, chunk=8)
        assert res.assignment["v1"] == res.assignment["v2"]

    def test_oversized_domain_rejected(self):
        from pydcop_tpu.dcop.objects import Domain

        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="mgm", seed=0, headroom=0.4)
        big = Variable("zb", Domain("big", "v", list(range(9))))
        with pytest.raises(ValueError, match="domain size"):
            s.apply_mutations([AddVariable(big)])

    def test_external_change_routes_as_edit(self):
        yaml_str = textwrap.dedent("""
            name: ext
            objective: min
            domains:
              d: {values: [0, 1]}
            variables:
              v1: {domain: d}
            external_variables:
              sensor: {domain: d, initial_value: 0}
            constraints:
              follow: {type: intention,
                       function: "0 if v1 == sensor else 5"}
            agents: [a1, a2]
        """)
        dcop = load_dcop(yaml_str)
        s = build_warm_solver(dcop, algo="maxsum", seed=0, headroom=0.3)
        s.run(chunk=8)
        t0 = s.trace_count()
        s.on_external_change("sensor", 1)
        res = s.run(resume=True, chunk=8)
        assert res.assignment["v1"] == 1
        assert s.trace_count() == t0


# ---------------------------------------------------------------------------
# the acceptance pin: seeded 50-mutation churn stream, zero retraces
# ---------------------------------------------------------------------------


class TestChurnStream:
    @pytest.mark.parametrize("algo", ["maxsum", "mgm"])
    def test_50_mutation_stream_zero_retraces(self, algo):
        dcop = fresh_dcop()
        ctl = WarmRepairController(
            dcop, algo, seed=7, headroom=1.0, min_free=8, chunk=8,
        )
        res = ctl.solver.run(chunk=ctl.chunk)
        ctl.phase_done(res)
        rng = np.random.default_rng(42)
        names = sorted(dcop.constraints)
        added = []
        for m in range(50):
            roll = rng.integers(4)
            if roll == 0 and len(added) < 4:
                z = Variable(f"z{m:02d}", dcop.domains["d"])
                ctl.add_variable(z)
                c = constraint_from_str(
                    f"cz{m:02d}", f"0 if z{m:02d} == v1 else 2",
                    [z, dcop.variables["v1"]],
                )
                ctl.add_constraint(c)
                added.append((z.name, c.name))
            elif roll == 1 and added:
                vn, cn = added.pop()
                ctl.remove_constraint(cn)
                ctl.remove_variable(vn)
            else:
                name = names[int(rng.integers(len(names)))]
                ctl.edit_factor(
                    perturbed_constraint(dcop.constraints[name], seed=m)
                )
            res = ctl.solver.run(resume=True, chunk=ctl.chunk)
            ctl.phase_done(res)
        c = ctl.counters.as_dict()
        assert c["repair_retraces"] == 0, c
        assert c["headroom_exhausted_repacks"] == 0, c
        assert c["mutations_applied"] >= 50
        assert c["time_to_recover_s"] > 0

    def test_headroom_exhaustion_exactly_one_repack_one_retrace(self):
        dcop = fresh_dcop()
        ctl = WarmRepairController(
            dcop, "mgm", seed=7, headroom=0.0, min_free=1, chunk=8,
        )
        events = []
        was = event_bus.enabled
        event_bus.enabled = True
        event_bus.subscribe("repair.*", lambda t, e: events.append(t))
        try:
            res = ctl.solver.run(chunk=ctl.chunk)
            ctl.phase_done(res)
            # 1 free slot: the second add must repack, not raise
            for i in range(2):
                ctl.add_variable(Variable(f"z{i}", dcop.domains["d"]))
                res = ctl.solver.run(resume=True, chunk=ctl.chunk)
                ctl.phase_done(res)
        finally:
            event_bus.enabled = was
        c = ctl.counters.as_dict()
        assert c["headroom_exhausted_repacks"] == 1, c
        assert c["repair_retraces"] == 1, c  # exactly the repack's one
        assert events.count("repair.repack") == 1
        assert "z0" in res.assignment and "z1" in res.assignment

    def test_counters_schema_is_closed(self):
        rc = RepairCounters()
        with pytest.raises(KeyError):
            rc.inc("nope")


# ---------------------------------------------------------------------------
# parity guard: warm repair vs cold repack (satellite 2)
# ---------------------------------------------------------------------------


def _mutated_pair(algo, seed):
    """Two identical warm solvers, converged, then the same mutation:
    A continues warm; B is cold-repacked (fresh capacity, state
    carried by name)."""
    da, db = fresh_dcop(), fresh_dcop()
    A = build_warm_solver(da, algo=algo, seed=seed, headroom=0.5)
    B = build_warm_solver(db, algo=algo, seed=seed, headroom=0.5)
    A.run(chunk=8)
    B.run(chunk=8)
    A.apply_mutations([EditFactor(swap_c12(da))])
    da.constraints["c12"] = swap_c12(da)
    B.apply_mutations([EditFactor(swap_c12(db))])
    db.constraints["c12"] = swap_c12(db)
    B2 = repack_solver(B, headroom=0.0, min_free=2)
    return A, B2


class TestWarmColdParity:
    def test_mgm_bit_identical_fixed_point(self):
        A, B = _mutated_pair("mgm", seed=5)
        ra = A.run(resume=True, chunk=8)
        rb = B.run(resume=True, chunk=8)
        assert ra.assignment == rb.assignment
        assert ra.cycle == rb.cycle  # same stop cycle, bit-identical

    def test_maxsum_bit_identical_fixed_point(self):
        A, B = _mutated_pair("maxsum", seed=5)
        ra = A.run(resume=True, chunk=8)
        rb = B.run(resume=True, chunk=8)
        assert ra.assignment == rb.assignment
        assert ra.cost == rb.cost

    @pytest.mark.parametrize("algo", ["dsa", "adsa"])
    def test_stochastic_rules_statistically_equivalent(self, algo):
        # coins are drawn at the capacity shape, so warm (headroom) and
        # cold-repacked (minimal) streams differ; equivalence is
        # distributional: same mean cost over seeds at the fixed point
        costs_a, costs_b = [], []
        for seed in range(6):
            A, B = _mutated_pair(algo, seed=seed)
            costs_a.append(A.run(resume=True, cycles=40, chunk=8).cost)
            costs_b.append(B.run(resume=True, cycles=40, chunk=8).cost)
        assert np.mean(costs_a) == pytest.approx(
            np.mean(costs_b), abs=2.0
        )

    def test_repack_preserves_claims_and_key(self):
        dcop = fresh_dcop()
        A = build_warm_solver(dcop, algo="mgm", seed=5, headroom=0.5)
        A.run(chunk=8)
        z = Variable("z9", dcop.domains["d"])
        dcop.add_variable(z)
        A.apply_mutations([AddVariable(z)])
        B = repack_solver(A)
        assert sorted(B.layout.claimed_vars) == sorted(
            A.layout.claimed_vars)
        assert np.array_equal(np.asarray(B._last_key),
                              np.asarray(A._last_key))


# ---------------------------------------------------------------------------
# checkpoint schema v3 (satellite 1)
# ---------------------------------------------------------------------------


class TestCheckpointV3:
    def test_mutated_solver_roundtrip(self, tmp_path):
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="mgm", seed=3, headroom=0.5)
        s.run(chunk=8)
        z = Variable("z9", dcop.domains["d"])
        dcop.add_variable(z)
        s.apply_mutations([AddVariable(z)])
        c = constraint_from_str(
            "cz", "0 if z9 == v4 else 7", [z, dcop.variables["v4"]]
        )
        dcop.add_constraint(c)
        s.apply_mutations([AddFactor(c)])
        s.run(resume=True, chunk=8)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, s, cycle=30)

        # a FRESH solver built from the pre-mutation problem restores
        # the mutated padded shape + slot maps from the snapshot
        s2 = build_warm_solver(
            fresh_dcop(), algo="mgm", seed=3, headroom=0.5)
        meta = load_checkpoint(path, s2)
        assert meta["version"] == 3
        assert s2.layout.has_factor("cz")
        assert "z9" in s2.layout.claimed_vars
        vals = s2.tensors.assignment_from_indices(
            np.asarray(s2.values_of(s2._last_state)))
        assert vals["z9"] == vals["v4"]

    def test_corrupt_and_future_versions_still_rejected(self, tmp_path):
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
            read_state_npz,
            write_state_npz,
        )
        from pydcop_tpu.runtime.faults import corrupt_checkpoint

        dcop = fresh_dcop()
        s = build_warm_solver(dcop, algo="mgm", seed=3, headroom=0.3)
        s.run(cycles=5, chunk=8)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, s, cycle=5)
        corrupt_checkpoint(path, seed=1)
        with pytest.raises(ValueError):
            load_checkpoint(path, s)
        # future schema: refused to guess
        import json as _json

        p4 = str(tmp_path / "v99.npz")
        np.savez(p4,
                 __meta__=_json.dumps({"version": 99, "kind": "solver"}),
                 leaf_0=np.zeros(3))
        with pytest.raises(ValueError, match="schema version"):
            read_state_npz(p4)
        _ = write_state_npz  # imported for symmetry with the writer

    def test_v2_solver_checkpoints_unaffected(self, tmp_path):
        """Cold solvers (no layout attr) still roundtrip — v3 is
        additive."""
        from pydcop_tpu.runtime.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )
        from pydcop_tpu.runtime.run import solve_result  # noqa: F401
        from pydcop_tpu.algorithms.mgm import build_solver

        dcop = fresh_dcop()
        s = build_solver(dcop, None, None, seed=1)
        s.run(cycles=5)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, s, cycle=5)
        s2 = build_solver(fresh_dcop(), None, None, seed=1)
        meta = load_checkpoint(path, s2)
        assert "headroom" not in meta


# ---------------------------------------------------------------------------
# orchestrator integration
# ---------------------------------------------------------------------------


def orch_for(dcop, algo="maxsum_dynamic", warm=True, fault_plan=None):
    from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

    algo_def = AlgorithmDef.build_with_default_params(
        algo, {}, mode=dcop.objective)
    orch = VirtualOrchestrator(
        dcop, algo_def, warm_repair=warm, fault_plan=fault_plan)
    orch.deploy_computations()
    return orch


class TestOrchestratorWarm:
    def test_structural_scenario_end_to_end(self):
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )

        dcop = fresh_dcop()
        orch = orch_for(dcop)
        orch.start_replication(2)
        scenario = Scenario([
            DcopEvent("d1", delay=0.2),
            DcopEvent("e1", actions=[EventAction(
                "change_factor", constraint="c12",
                expression="0 if v1 != v2 else 5")]),
            DcopEvent("e2", actions=[
                EventAction("add_variable", variable="z9", domain="d"),
                EventAction("add_constraint", constraint="cz",
                            expression="0 if z9 == v4 else 7",
                            scope=["z9", "v4"]),
            ]),
            DcopEvent("e3", actions=[EventAction(
                "remove_agent", agent="a2")]),
            DcopEvent("d2", delay=0.2),
        ])
        res = orch.run(scenario, cycles=20)
        m = orch.end_metrics()
        assert res.assignment["v1"] != res.assignment["v2"]
        assert res.assignment["z9"] == res.assignment["v4"]
        assert m["repair"]["repair_retraces"] == 0
        assert m["repair"]["mutations_applied"] >= 3
        assert m["resilience"]["repairs"] == 1
        # the solver result itself carries the scorecard too
        assert res.metrics()["repair"] == m["repair"]

    def test_structural_actions_need_warm_repair(self):
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )

        dcop = fresh_dcop()
        orch = orch_for(dcop, algo="maxsum", warm=False)
        scenario = Scenario([
            DcopEvent("e1", actions=[EventAction(
                "add_variable", variable="z9", domain="d")]),
        ])
        with pytest.raises(ValueError, match="warm-repair"):
            orch.run(scenario, cycles=5)

    def test_warm_repair_rejects_unsupported_algo(self):
        with pytest.raises(ValueError, match="warm"):
            orch_for(fresh_dcop(), algo="gdba")

    def test_churn_fault_kinds_fire_and_stay_warm(self):
        from pydcop_tpu.runtime.faults import Fault, FaultPlan

        plan = FaultPlan(seed=11, faults=[
            Fault(kind="edit_factor", cycle=4),
            Fault(kind="edit_factor", cycle=8, constraint="c23"),
            Fault(kind="remove_agent_burst", cycle=12, count=2),
            Fault(kind="add_agent_burst", cycle=16, count=2),
        ])
        dcop = fresh_dcop()
        orch = orch_for(dcop, algo="mgm", fault_plan=plan)
        orch.run(cycles=40)
        m = orch.end_metrics()
        assert m["resilience"]["faults_injected"] == 4
        assert m["repair"]["repair_retraces"] == 0
        assert m["repair"]["mutations_applied"] >= 2
        assert len(dcop.agents) == 8  # -2 burst, +2 burst
        kinds = [e.get("fault") for e in m["events"] if "fault" in e]
        assert kinds.count("edit_factor") == 2
        assert "remove_agent_burst" in kinds
        assert "add_agent_burst" in kinds

    def test_churn_bursts_are_seed_deterministic(self):
        from pydcop_tpu.runtime.faults import Fault, FaultPlan

        removed = []
        for _ in range(2):
            plan = FaultPlan(seed=3, faults=[
                Fault(kind="remove_agent_burst", cycle=4, count=2),
            ])
            dcop = fresh_dcop()
            orch = orch_for(dcop, algo="mgm", fault_plan=plan)
            orch.run(cycles=10)
            removed.append(tuple(sorted(
                set("a1 a2 a3 a4 a5 a6 a7 a8".split())
                - set(dcop.agents))))
        assert removed[0] == removed[1]

    def test_edit_factor_fault_cold_dynamic_works_cold_mgm_raises(self):
        from pydcop_tpu.runtime.faults import Fault, FaultPlan

        plan = FaultPlan(seed=5, faults=[
            Fault(kind="edit_factor", cycle=2)])
        orch = orch_for(fresh_dcop(), algo="maxsum_dynamic", warm=False,
                        fault_plan=plan)
        orch.run(cycles=10)
        assert orch.fault_counters.counts["faults_injected"] == 1

        orch2 = orch_for(fresh_dcop(), algo="mgm", warm=False,
                         fault_plan=plan)
        with pytest.raises(ValueError, match="warm-repair"):
            orch2.run(cycles=10)

    def test_dynamic_scenario_still_works_warm(self):
        """The historical dynamic-DCOP scenario runs unchanged through
        the warm layer — one mechanism (ISSUE 8 tentpole wiring)."""
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )

        dcop = fresh_dcop()
        orch = orch_for(dcop, algo="maxsum")
        scenario = Scenario([
            DcopEvent("d1", delay=0.2),
            DcopEvent("e1", actions=[EventAction(
                "change_factor", constraint="c12", seed=4)]),
            DcopEvent("d2", delay=0.2),
        ])
        res = orch.run(scenario, cycles=15)
        assert res.status == "FINISHED"
        assert orch.end_metrics()["repair"]["mutations_applied"] == 1


class TestChurnScenario:
    def test_seeded_stream_is_deterministic_and_runs(self):
        from pydcop_tpu.dcop.scenario import churn_scenario

        d1, d2 = fresh_dcop(), fresh_dcop()
        s1 = churn_scenario(d1, n_events=6, seed=9, delay=0.05)
        s2 = churn_scenario(d2, n_events=6, seed=9, delay=0.05)
        acts1 = [(a.type, sorted(a.parameters.items()))
                 for e in s1 for a in e.actions]
        acts2 = [(a.type, sorted(a.parameters.items()))
                 for e in s2 for a in e.actions]
        assert acts1 == acts2 and len(acts1) == 6
        orch = orch_for(d1, algo="mgm")
        res = orch.run(s1, cycles=20)
        assert res.status == "FINISHED"
        assert orch.end_metrics()["repair"]["repair_retraces"] == 0


# ---------------------------------------------------------------------------
# packed-layout hot swap (ops/pallas_maxsum + maxsum_dynamic wiring)
# ---------------------------------------------------------------------------


class TestPackedSwap:
    def test_packed_swap_matches_fresh_pack(self):
        from pydcop_tpu.ops.compile import compile_binary_from_arrays
        from pydcop_tpu.ops.pallas_maxsum import (
            pack_for_pallas,
            packed_swap_factor,
        )

        rng = np.random.default_rng(0)
        V, F, D = 24, 40, 3
        ei = rng.integers(0, V, F)
        ej = (ei + 1 + rng.integers(0, V - 1, F)) % V
        mats = rng.uniform(0, 5, (F, D, D)).astype(np.float32)
        pg = pack_for_pallas(compile_binary_from_arrays(ei, ej, mats, V))
        new_tab = rng.uniform(0, 5, (D, D)).astype(np.float32)
        pg2 = packed_swap_factor(pg, 7, new_tab)
        mats2 = mats.copy()
        mats2[7] = new_tab
        fresh = pack_for_pallas(
            compile_binary_from_arrays(ei, ej, mats2, V))
        assert np.allclose(np.asarray(pg2.cost_rows),
                           np.asarray(fresh.cost_rows))
        # static structure shared, wrong shapes rejected
        assert pg2.plan is pg.plan
        with pytest.raises(ValueError, match="scope"):
            packed_swap_factor(pg, 7, np.zeros((D, D + 1)))
        with pytest.raises(ValueError, match="range"):
            packed_swap_factor(pg, F, new_tab)

    def test_stacked_swap_matches_fresh_stacked_pack(self):
        from pydcop_tpu.ops.compile import compile_binary_from_arrays
        from pydcop_tpu.parallel.packed_mesh import build_shard_packs

        rng = np.random.default_rng(1)
        V, F, D = 24, 40, 3
        ei = rng.integers(0, V, F)
        ej = (ei + 1 + rng.integers(0, V - 1, F)) % V
        mats = rng.uniform(0, 5, (F, D, D)).astype(np.float32)
        sp = build_shard_packs(
            compile_binary_from_arrays(ei, ej, mats, V), 2)
        assert sp is not None
        new_tab = rng.uniform(0, 5, (D, D)).astype(np.float32)
        sp.swap_factor(11, new_tab)
        mats2 = mats.copy()
        mats2[11] = new_tab
        fresh = build_shard_packs(
            compile_binary_from_arrays(ei, ej, mats2, V), 2)
        assert np.allclose(np.asarray(sp.cost_rows),
                           np.asarray(fresh.cost_rows))
